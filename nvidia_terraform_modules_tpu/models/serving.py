# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""Continuous-batching serve engine on a block/paged KV cache.

``greedy_decode`` serves ONE batch whose requests start and stop
together. Real serving traffic doesn't: requests ARRIVE over time with
different prompt lengths and LEAVE after different generation lengths.
This module is the scheduler between those two worlds — the vLLM-style
continuous-batching engine, re-thought for TPU static shapes:

- **admission queue**: requests join in-flight decode at step (wave)
  boundaries the moment a slot AND enough KV blocks are free; an
  optional per-request arrival time (from ``utils/traffic.py``'s seeded
  Poisson/diurnal traces) gates admission, so the engine serves a load
  model, not just a ready-made batch;
- **paged KV cache** (``models/paging.py`` + ``decode.forward_paged``):
  the physical cache is fixed-size blocks shared by every request; each
  admission allocates exactly the blocks its prompt + generation budget
  needs, and retirement returns them to the free list — ragged sequence
  lengths stop reserving ``max_len`` HBM per slot, and a bounded pool
  (``kv_blocks``) turns into admission control instead of an OOM;
- **per-request EOS retirement**: a finished request's blocks free and
  its slot re-admits immediately — the freed capacity is what lets a
  fixed pool beat run-to-completion batching on ragged-EOS traffic
  (``bench.py section_serve_engine`` pins the comparison);
- **chunked-prefill/decode interleaving** (``prefill_chunk``): a long
  prompt admits one ``[1, C]`` chunk per wave while every active slot
  keeps decoding between chunks — long prompts stop stalling the decode
  batch for their whole prefill;
- **per-request ``n_new``**: a sequence of generation budgets makes
  ragged OUTPUT lengths first-class (the bench's deterministic ragged
  workload), with the same per-request retirement.

Three SCHEDULER LEVERS (each independently toggleable; all defaults
off, reproducing the baseline engine exactly):

- **cross-request prefix sharing** (``share_prefix=True``): the block
  allocator grows per-block refcounts and a host-side
  :class:`..paging.PrefixIndex` of block-aligned token-hash chains, so
  an admission whose prompt shares full leading blocks with any live or
  recently retired request maps those PHYSICAL blocks into its table
  (refcount++) and prefills only from the first unshared token — the
  popular template's KV lives once in HBM and its prefill compute is
  paid once, not per request;
- **policy admission** (``policy="fifo"|"sjf"|"priority"``):
  shortest-job-first on the known prompt length + ``n_new`` budget, or
  a priority lane fed per-request (``run(..., priorities=)``), both
  under a configurable ``aging`` bound (waves waited, after which a
  request jumps the policy order) so starvation is impossible;
- **lazy block growth** (``lazy_growth=True``): admission grants only
  the prompt's blocks plus one decode block; the wave loop grows each
  slot's table as its position crosses block boundaries, so eos-heavy
  traffic stops reserving its worst-case budget and the same
  ``kv_blocks`` pool admits measurably more concurrent requests. A
  growth that finds the pool empty STALLS the slot (its writes stay
  fenced, its position frozen) until a retirement frees a block; if
  every live request is stalled the youngest is preempted back to the
  queue (its deterministic tokens regenerate identically on
  re-admission — scheduling, never different output).

A fourth lever TIERS the prefix index itself (``host_spill=True``,
``models/hostkv.py``): LRU evictions spill chains into a pinned
host-RAM block pool instead of dropping them and a later hit swaps the
rows back in (async double-buffered against the wave loop,
crc-verified), so the retained template working set is bounded by host
RAM, not ``prefix_keep_blocks`` — the host-as-backing-store pattern
the TPU-serving comparison papers make the decisive lever on hosts
carrying 48-384 GB of RAM next to 16 GB of HBM per chip.

Every decode wave advances ALL busy slots in ONE compiled program — a
batched ``[slots, 1]`` cached forward over the paged pool with per-slot
positions and block tables; admission is host-side bookkeeping between
compiled steps (the host owns WHICH request sits in a slot and WHICH
physical blocks it holds, the device owns the math — no data-dependent
shapes anywhere). On TPU the wave step reads the cache through the
BLOCK-TABLE-NATIVE pallas decode kernel
(``ops/decode_attention.paged_decode_attention``, the
``paged_kernel`` lever): live blocks are DMA'd straight from the
physical pool inside the kernel grid, so per-wave cache traffic
scales with live tokens — the jnp ``k_phys[tables]`` logical-view
gather (which scales with POOL size) stays as the bit-match-gated
reference path. Dead slots keep computing (the static-shape bubble)
but their cache writes are fenced to the reserved garbage block, so a
retired slot can never scribble over blocks already recycled to a new
request.

Exactness contract (unchanged from the dense-pool engine, pinned by
``tests/test_serving.py``): each request's tokens EQUAL ``greedy_decode``
run alone on that request — batching, paging, slot recycling, arrival
schedules and chunk interleaving are scheduling, never a different
model. Speculative (``spec_k``) and int8-KV paths keep their contracts
on paged storage: the verification forward reads the same gathered
rows a plain paged step would, so spec-vs-plain equality survives
occupancy > 1.

Telemetry (PR 7 plane): ``serve_queue_depth`` / ``serve_slot_occupancy``
/ ``kv_blocks_in_use`` gauges per wave, a ``serve_prefill`` span per
admission and a ``serve_request`` span per retirement carrying
``queue_wait_ms`` / ``prefill_ms`` / ``decode_steps`` — the
p50/p99 request-latency record lands in ``serve_request_ms``.

The FLEET seam (PR 12): the admission/queue head is an injectable
interface — ``run(..., admission=)`` takes any
:class:`AdmissionSource` and serves exactly what it yields (results
keyed by request index), which is how ``models/fleet.py`` drives N
replica engines, steals work between their queues mid-run, and feeds
decode workers prefilled KV through ``kv_import`` payloads built by
:func:`make_serve_engine`'s ``prefill_session`` (the disaggregated
prefill→decode handoff; ``models/paging.py``'s block transfer pair
moves the bytes). The interface is also the fleet's PROCESS seam
(PR 17): ``models/transport.py``'s multi-proc replicas run this very
engine in a child process against an :class:`AdmissionSource` proxy
whose every call is a crc-framed RPC to the router — the engine never
learns whether its queue lives in-thread or across a pipe, which is
what keeps in-proc and multi-proc fleets bit-identical.

Reference analogue: none — the reference provisions serving
infrastructure (node pools, runtime DaemonSets) and never touches model
bytes (SURVEY §2.6); this engine is the workload the ``serve``-named
slice pools exist to run.
"""

from __future__ import annotations

import functools
import time
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..parallel.sharding import ShardingRules
from .burnin import BurnInConfig
from .decode import forward_paged
from .paging import (
    BlockAllocator,
    PrefixIndex,
    blocks_for_rows,
    chain_chunks,
    chunk_tokens_covered,
    paged_pool_spec,
)

_POLICIES = ("fifo", "sjf", "priority")
_DEFAULT_AGING = 512                   # waves; bounds starvation by default


class AdmissionSource:
    """The engine's admission/queue head as an INJECTABLE interface.

    ``run(..., admission=source)`` hands WHICH request to admit next —
    and WHEN — to the caller: the engine polls ``candidate()`` at every
    wave boundary, ``pop``s what it admits, ``requeue``s what a lazy-
    growth preemption returns, and keeps its wave loop alive until
    ``exhausted()`` says no candidate will ever come again. This is the
    seam the fleet router (``models/fleet.py``) drives its replicas
    through — dynamic cross-replica work stealing is just the router
    mutating a replica's source between waves — with no reaching into
    engine-private state and no test-hook monkeypatching. The built-in
    :class:`_Sched` (policy admission over a fixed prompt list with
    optional arrival gating) implements the same interface, so the
    injected and default paths run the identical engine loop.

    Contract for implementers (thread-safety is the implementer's
    problem — the engine calls from its own run thread, a router may
    mutate from another):

    - ``candidate()`` → the request index to try admitting next, or
      ``None`` (empty, or nothing has arrived yet). The engine may call
      it several times per wave; a candidate whose block grant does not
      fit is HELD (the engine stops admitting for the wave without
      popping it).
    - ``pop(req)`` — the engine admitted ``req``.
    - ``requeue(req)`` — a preempted request goes back (its output must
      regenerate on re-admission; the engine guarantees tokens are
      schedule-invariant).
    - ``tick()`` — one wave passed (aging hooks).
    - ``waiting()`` → how many requests are admissible now (queue-depth
      stat + the spec loop's wave sizing).
    - ``exhausted()`` → True only when the source will NEVER yield
      another candidate (empty AND closed); the engine's run loop exits
      when exhausted with nothing in flight.
    - ``idle_wait()`` — nothing admissible and nothing computing: block
      briefly (until the next arrival, a router poll interval, …)
      instead of spinning.
    - ``wait_s(req)`` → the queue wait to bill for ``req`` at admission
      (seconds).
    - ``kv_import(req)`` → ``None`` for a normal admission, or a
      prefill→decode handoff payload (see ``prefill_session``): the
      engine then allocates blocks, IMPORTS the payload's prefilled KV
      rows via ``paging.import_block_rows`` and starts decoding at the
      payload's position — no prefill compute on this engine. The
      payload stays the source's to keep until retirement (a preempted
      import re-imports on re-admission).
    - ``retired(req, tokens)`` — completion notification at the wave
      the engine retired ``req`` (SLO attainment clocks stop here).
    - ``draining()`` → True when the source's owner wants this engine
      to STOP ADMITTING while finishing everything already in flight
      (the planned-removal drain hook): the engine skips admission for
      the wave even if a candidate is mid-claim, keeps stepping its
      active slots to retirement, and exits once the source closes.
      Default False — the built-in scheduler never drains.

    FAULT SEAM (the serving chaos plane, ``models/fleet.py``): the
    engine deliberately does NOT catch exceptions from these hooks — an
    implementation that raises from ``candidate()``/``tick()`` kills
    the run mid-wave exactly like the process dying would, with the
    partially-decoded outputs lost (they are assembled only at the end
    of ``run``). That raise-at-a-poll-boundary is how the fleet's
    seeded fault injection simulates a replica death deterministically
    (the same step-boundary discipline as ``smoketest/chaos.py``'s
    self-delivered kills); recovery — redriving the dead replica's
    requests to survivors — is the ROUTER's job, correct because
    tokens are schedule-invariant. A planned drain is the graceful
    twin: ``draining()`` flips True (admission stops), the owner
    removes the still-pending requests, the queue closes, and the
    engine retires its in-flight work normally — nothing recomputed.
    """

    def candidate(self):
        raise NotImplementedError

    def pop(self, req):
        raise NotImplementedError

    def requeue(self, req):
        raise NotImplementedError

    def tick(self):
        pass

    def waiting(self) -> int:
        return 0

    def exhausted(self) -> bool:
        raise NotImplementedError

    def idle_wait(self) -> None:
        pass

    def wait_s(self, req) -> float:
        return 0.0

    def kv_import(self, req):
        return None

    def retired(self, req, tokens: int) -> None:
        """The engine retired ``req`` after emitting ``tokens`` tokens
        — the router's completion signal (SLO attainment clocks stop
        here, steal heuristics see the slot free up). Default: no-op."""

    def draining(self) -> bool:
        """True = stop admitting, finish in-flight work (the planned
        drain hook — see the class docstring). Default: never."""
        return False

    def warm_chains(self):
        """WARM BRING-UP (the elastic fleet's host-tier prefix
        migration): ``None``/empty for a cold start, or a list of
        ``(chunks, payload)`` prefix chains (``paging.chain_chunks``
        chunk tuples + ``export_block_rows``-format rows) the engine
        seeds HOST-side into its prefix index before the first
        admission (``HostBlockPool.adopt`` + ``PrefixIndex.seed_host``)
        — a scale-up replica then inherits the popular-template working
        set and the first matching admission swaps each chain in
        through the ordinary crc-verified tiered path. Consulted once
        per run, only on engines built with ``share_prefix`` +
        ``host_spill`` (no host tier ⇒ chains are dropped, billed in
        ``last_stats["prefix"]["warm"]``). Default: cold."""
        return None

    def chain_sink(self):
        """The drain-time PUBLISH sink (``None`` = discard, the
        default): an object with ``publish(chains) → stored`` (e.g.
        ``hostkv.WarmChainStore``) that receives the prefix index's
        retained chains at the END of the run, before the pool is
        released — how a scaled-down replica's warm state outlives it
        for successors to inherit. Publishing is read-only against the
        index and best-effort: correctness never depends on it."""
        return None


class _Sched(AdmissionSource):
    """Host-side admission ORDER: which pending request the engine
    should try to admit next. ``fifo`` is strict arrival order with
    head-of-line blocking (the baseline engine's exact semantics);
    ``sjf`` picks the shortest known job (prompt length + ``n_new``
    budget) among ARRIVED requests; ``priority`` picks the highest
    caller-supplied priority. Both non-fifo policies run under an
    aging bound: a request that has waited ``aging`` waves past its
    arrival jumps to the front (FIFO among the aged), so no job starves
    behind an endless stream of policy-preferred ones. Whatever the
    policy, a candidate whose block grant does not fit HOLDS admission
    for the wave (no skip-ahead — deterministic, and a big job cannot
    be starved for memory by smaller ones slipping past it)."""

    def __init__(self, prompts, n_new_of, policy, aging, priorities,
                 arrivals, t0):
        self.pending = list(range(len(prompts)))   # arrival order
        self.prompts = prompts
        self.cost = [int(p.shape[-1]) + n_new_of[i]
                     for i, p in enumerate(prompts)]
        self.policy = policy
        self.aging = aging
        self.prio = priorities
        self.arrivals = arrivals
        self.t0 = t0
        self.age = [0] * len(prompts)              # waves arrived-unadmitted

    def __len__(self):
        return len(self.pending)

    def _now(self):
        """ONE clock read per scan — a per-request time.monotonic() in
        the hot wave loop would pay O(pending) syscalls per wave."""
        return None if self.arrivals is None else \
            time.monotonic() - self.t0

    def _arrived(self, req, now):
        return self.arrivals is None or self.arrivals[req] <= now

    def candidate(self):
        """Next request to try admitting, or None (empty / not arrived)."""
        if not self.pending:
            return None
        now = self._now()
        if self.policy == "fifo":
            head = self.pending[0]
            return head if self._arrived(head, now) else None
        arrived = [r for r in self.pending if self._arrived(r, now)]
        if not arrived:
            return None
        aged = [r for r in arrived if self.age[r] >= self.aging]
        if aged:
            return aged[0]                         # FIFO among the aged
        if self.policy == "sjf":
            return min(arrived, key=lambda r: (self.cost[r], r))
        return min(arrived, key=lambda r: (-self.prio[r], r))

    def pop(self, req):
        self.pending.remove(req)

    def requeue(self, req):
        """Re-insert a preempted request at its arrival-order position
        (age preserved — a preemption must not reset its aging)."""
        import bisect

        bisect.insort(self.pending, req)

    def tick(self):
        """One wave passed: age every arrived-but-unadmitted request."""
        now = self._now()
        for r in self.pending:
            if self._arrived(r, now):
                self.age[r] += 1

    def waiting(self):
        """Arrived-but-unadmitted count (one clock read)."""
        if self.arrivals is None:
            return len(self.pending)
        now = self._now()
        return sum(1 for r in self.pending if self.arrivals[r] <= now)

    def next_arrival(self):
        """The request whose arrival unblocks admission (the sleep
        target when nothing is computable): fifo blocks on its HEAD —
        a later-but-earlier-arriving request cannot jump it — while
        the other policies unblock on the earliest arrival."""
        if self.arrivals is None or self.policy == "fifo":
            return self.pending[0]
        return min(self.pending, key=lambda r: self.arrivals[r])

    def exhausted(self) -> bool:
        """A fixed prompt list never grows: empty IS exhausted."""
        return not self.pending

    def idle_wait(self) -> None:
        """Nothing to compute and no pending request has arrived:
        sleep the gap to the blocking arrival instead of spinning.
        (Without an arrival trace every pending request is admissible,
        so this is never reached — blocks exhausted with nothing
        active cannot happen; single-request capacity is validated up
        front.)"""
        if self.arrivals is None or not self.pending:
            return
        wait = self.arrivals[self.next_arrival()] \
            - (time.monotonic() - self.t0)
        if wait > 0:
            time.sleep(wait)

    def wait_s(self, req) -> float:
        """Queue wait vs the request's arrival (t0 when no trace): a
        request held for slots or KV blocks reports its real wait,
        never a hardwired zero. One definition for both loops so the
        spec and plain engines cannot diverge on wait accounting."""
        return max(0.0, time.monotonic() - self.t0
                   - (self.arrivals[req]
                      if self.arrivals is not None else 0.0))


def _request_key(rng, req, pos):
    """THE sampled-token key contract, in one place: key =
    ``fold_in(fold_in(rng, request), position)``. Used by the
    admission path (host-side, first token) and inside the compiled
    sampled step (vmapped, every wave) — one definition so the two
    sites can never diverge on what keys tokens, which is what makes
    sampled output schedule-invariant."""
    return jax.random.fold_in(jax.random.fold_in(rng, req), pos)


def _sampler_fingerprint(sampler) -> str:
    """Deterministic sampler description for the AOT cache scope
    (``models/aotcache.py``): None / a spec dict / a callable's
    qualname — never a callable's ``repr``, whose memory address would
    split the cache key across processes. Two DIFFERENT callables with
    one qualname alias under this; the admission avals still separate
    greedy from sampled, and priming recompiles anything stale."""
    if sampler is None:
        return "none"
    if isinstance(sampler, dict):
        return ("spec("
                + ",".join(f"{k}={sampler[k]!r}" for k in sorted(sampler))
                + ")")
    return getattr(sampler, "__qualname__", type(sampler).__name__)


def _make_pick(sampler):
    """The greedy-vs-sampled token pick shared by every admission path:
    ``pick(logits [1, T, V], idx, key) → token`` — argmax at ``idx``
    when greedy, the sampler over that position otherwise. One
    definition so the admission paths and the decode step can never
    diverge on the pick contract."""
    if sampler is None:
        def pick(logits, idx, key):                    # noqa: ARG001
            return jnp.argmax(logits[0, idx], axis=-1)
    else:
        def pick(logits, idx, key):
            return sampler(logits[:, idx], key)[0]
    return pick


def make_serve_step(params, cfg: BurnInConfig, sampler=None, *,
                    int8_kernel: bool = True, paged_kernel: str = "auto",
                    rules: ShardingRules | None = None):
    """Compiled all-slots decode step over the PAGED pool: one batched
    ``[slots, 1]`` cached forward (``decode.forward_paged``) with
    per-slot positions and block tables. The pool is DONATED — the step
    updates the physical blocks in place rather than paying a full-pool
    copy per token (the bandwidth a slot engine exists to save).
    ``active`` fences dead slots' writes to the garbage block and
    freezes their positions.

    ``paged_kernel`` picks the T=1 read path (``forward_paged``):
    ``"auto"`` takes the block-table-native pallas kernel on TPU — the
    wave step is THE gather-tax hot path, one kernel per layer per
    wave — while ``"off"`` keeps the jnp gather reference the kernel
    is bit-match gated against. ``int8_kernel=False`` keeps an int8
    pool's attention on the jnp path: the engine passes it whenever
    the pool is mesh-sharded (``rules``), where a pallas_call on
    sharded operands inside jit is not a supported lowering (see
    ``forward_paged``) — the engine demotes ``paged_kernel`` to
    ``"off"`` under ``rules`` for exactly the same reason.

    Greedy (``sampler=None``): ``(tokens [slots], active, pool) →
    (next, pool)``. Sampled: ``(tokens, active, req_ids, positions,
    rng, pool) → ...`` — one PRNG key per slot per step, derived INSIDE
    the compiled step from (request, position) so token randomness is
    keyed to the request stream, never to the schedule.
    """
    # params enter every compiled function as a runtime ARGUMENT, never a
    # closure: a closed-over array tree lowers as module constants, and at
    # flagship size that embeds the full weight set (hundreds of MB) into
    # each program — observed as multi-minute serve compiles on TPU before
    # the serve section ever ran a step (BENCH_tpu_capture_r04 serve
    # timeout). Passing the tree costs nothing: the buffers are already
    # device-resident.
    if sampler is None:
        @functools.partial(jax.jit, donate_argnums=(3,))
        def step(p, tokens, active, pool):
            logits, pool = forward_paged(p, tokens[:, None], pool, cfg,
                                         rules, prefill_impl="cached",
                                         active=active,
                                         int8_kernel=int8_kernel,
                                         paged_kernel=paged_kernel)
            return jnp.argmax(logits[:, -1], axis=-1), pool

        def wave(tokens, active, pool):
            return step(params, tokens, active, pool)

        wave._aot = step               # the inner jit, for AOT warming
        return wave

    @functools.partial(jax.jit, donate_argnums=(6,))
    def sampled_step(p, tokens, active, req_ids, positions, rng, pool):
        logits, pool = forward_paged(p, tokens[:, None], pool, cfg,
                                     rules, prefill_impl="cached",
                                     active=active,
                                     int8_kernel=int8_kernel,
                                     paged_kernel=paged_kernel)
        # keys derived INSIDE the compiled step (one dispatch per step
        # regardless of slot count; typed or legacy rng keys both work)
        # from the shared (request, position) contract
        keys = jax.vmap(lambda r, pos: _request_key(rng, r, pos))(
            req_ids, positions)
        toks = jax.vmap(lambda row, kk: sampler(row[None], kk)[0])(
            logits[:, -1], keys)
        return toks, pool

    def wave(tokens, active, req_ids, positions, rng, pool):
        return sampled_step(params, tokens, active, req_ids, positions,
                            rng, pool)

    wave._aot = sampled_step           # the inner jit, for AOT warming
    return wave


def make_spec_step(params, cfg: BurnInConfig, k: int, *,
                   int8_kernel: bool = True, paged_kernel: str = "auto",
                   rules: ShardingRules | None = None):
    """Compiled all-slots SPECULATIVE step on the paged pool:
    prompt-lookup drafts + ONE batched ``[slots, k+1]`` verification
    forward per iteration.

    Extends ``speculative_greedy_decode``'s single-request loop
    (``models/speculative.py`` — the acceptance core ``accept_drafts``
    is literally shared) to continuous batching: each slot drafts ``k``
    tokens by bigram lookup in its OWN context row, verifies them at
    its OWN position through the paged read path, and accepts the
    longest prefix matching the model's argmax chain. Rollback is
    per-slot ``pos`` arithmetic, never buffer surgery: rejected draft
    rows stay position-masked in the slot's blocks until real writes
    reclaim them.

    Step signature (``ctx``/``cur``/``n_out``/``pool`` donated):
    ``(ctx [slots, Lc], cur [slots], n_out [slots], n_new [slots],
    eos_id, active [slots] bool, stop_count, granted_rows [slots],
    pool) → (ctx, cur, n_out, fin [slots] bool, steps [slots],
    need_grow [slots] bool, pool)`` where ``ctx`` rows hold
    prefix+prompt+generated tokens, ``cur`` the valid length, ``n_out``
    tokens generated, ``n_new`` the PER-SLOT generation budget;
    ``eos_id < 0`` disables eos. The step is a device-resident
    MULTI-step: it loops until ``stop_count`` of the ``active`` slots
    have finished (``fin``), freezing each finished slot's state at the
    step it completed, and returns ``steps``, the PER-SLOT count of
    unfrozen-active verification steps it ran (summed: the stats
    denominator; per slot: each request's decode_steps). Emission
    per slot is capped at ``n_new - n_out`` FIRST, then truncated at
    the first eos inside the capped window — so a slot can never finish
    on an eos the cap already excluded.

    ``granted_rows`` is the PER-K-TOKEN GROWTH BOUNDARY that lets
    ``spec_k`` compose with ``lazy_growth``: a verification at ``pos``
    writes rows ``pos..pos+k``, so a slot whose granted rows (table
    entries × block_size) don't cover ``pos + k + 1`` is GROWTH-
    BLOCKED — frozen for the iteration (writes fenced, state held)
    exactly like a finished slot, and reported in ``need_grow`` so the
    host can grant blocks at the next wave boundary. When every
    unfinished active slot is growth-blocked the loop EXITS early
    (whatever ``stop_count`` says — nothing on device can make
    progress), returning control to the host-side allocator. Eagerly
    granted engines pass the full logical row count and the machinery
    compiles to the PR 8 behaviour bit for bit (``blocked`` is
    constant-false). Frozen slots still compute a forward per
    iteration, but their writes are fenced to the garbage block and
    their ``pos`` frozen — a few ms of MXU time traded against a
    ~90 ms host round trip per avoided sync (the measured dispatch RTT
    through the tunnelled backend).
    """
    from .speculative import _ngram_draft, accept_drafts

    def row_accept(ctx_row, cur, n_done, draft, preds, n_new_row, eos_id):
        new_toks, n_acc = accept_drafts(draft, preds)         # [k+1]
        idx = jnp.arange(k + 1)
        emit = jnp.clip(n_acc + 1, 0, jnp.maximum(n_new_row - n_done, 0))
        is_eos = (new_toks == eos_id) & (eos_id >= 0) & (idx < emit)
        hit = jnp.any(is_eos)
        emit = jnp.where(hit, jnp.argmax(is_eos) + 1, emit)
        keep = idx < emit
        upd = jax.lax.dynamic_slice_in_dim(ctx_row, cur, k + 1)
        upd = jnp.where(keep, new_toks, upd)
        ctx_row = jax.lax.dynamic_update_slice_in_dim(ctx_row, upd, cur, 0)
        n_done = n_done + emit
        done = (n_done >= n_new_row) | hit
        return ctx_row, cur + emit, n_done, done

    vaccept = jax.vmap(row_accept, in_axes=(0, 0, 0, 0, 0, 0, None))
    vdraft = jax.vmap(lambda c, cu: _ngram_draft(c, cu, k, cfg.vocab))

    # params as argument, not closure — see make_serve_step
    @functools.partial(jax.jit, donate_argnums=(1, 2, 3, 9))
    def step(p, ctx, cur, n_out, n_new, eos_id, active, stop_count,
             granted_rows, pool):
        def blocked_of(pool, fin):
            # the next verification writes pos..pos+k — a slot whose
            # grant doesn't cover them must wait for the host
            return (pool["pos"] + (k + 1) > granted_rows) & active & ~fin

        def cond(s):
            _, _, _, fin, _, pool = s
            runnable = active & ~fin & ~blocked_of(pool, fin)
            return (jnp.sum(fin & active) < stop_count) & jnp.any(runnable)

        def body(s):
            ctx, cur, n_out, fin, steps, pool = s
            # frozen = finished, never-active, OR growth-blocked: a
            # frozen slot's writes are fenced to the garbage block
            # (forward_paged's active mask) and its ctx/cur/pos held,
            # so its stale state can never drift or corrupt a recycled
            # (or ungranted) block
            blocked = blocked_of(pool, fin)
            frozen = fin | ~active | blocked
            last = jnp.take_along_axis(
                ctx, jnp.maximum(cur - 1, 0)[:, None], axis=1)  # [S, 1]
            draft = vdraft(ctx, cur)                            # [S, k]
            block = jnp.concatenate([last, draft], axis=1)      # [S, k+1]
            # "cached": a mid-stream t>1 forward attending over each
            # slot's blocks at its own position (T=k+1, so the read
            # stays on the reference gather path — see forward_paged)
            logits, npool = forward_paged(p, block, pool, cfg, rules,
                                          prefill_impl="cached",
                                          active=~frozen,
                                          int8_kernel=int8_kernel,
                                          paged_kernel=paged_kernel)
            preds = jnp.argmax(logits, axis=-1)                 # [S, k+1]
            nctx, ncur, nn_out, done = vaccept(ctx, cur, n_out, draft,
                                               preds, n_new, eos_id)
            ctx = jnp.where(frozen[:, None], ctx, nctx)
            cur = jnp.where(frozen, cur, ncur)
            n_out = jnp.where(frozen, n_out, nn_out)
            # rollback by pos arithmetic: valid forwarded rows are
            # exactly the context minus the one new un-forwarded last
            # token; frozen slots keep the pos forward_paged froze
            npool = dict(npool)
            npool["pos"] = jnp.where(frozen, pool["pos"], ncur - 1)
            # count BEFORE updating fin: a slot's finishing step is a
            # real verification step; frozen iterations are not.
            # Per-SLOT so the host can attribute steps to requests
            steps = steps + (active & ~fin & ~blocked).astype(jnp.int32)
            fin = fin | (done & active & ~blocked)
            return ctx, cur, n_out, fin, steps, npool

        fin0 = jnp.zeros(active.shape, bool)
        s = (ctx, cur, n_out, fin0,
             jnp.zeros(active.shape, jnp.int32), pool)
        ctx, cur, n_out, fin, steps, pool = jax.lax.while_loop(
            cond, body, s)
        return ctx, cur, n_out, fin, steps, blocked_of(pool, fin), pool

    def wave(ctx, cur, n_out, n_new, eos_id, active, stop_count,
             granted_rows, pool):
        return step(params, ctx, cur, n_out, n_new, eos_id, active,
                    stop_count, granted_rows, pool)

    wave._aot = step                   # the inner jit, for AOT warming
    return wave


def make_serve_engine(params, cfg: BurnInConfig, *, max_len: int,
                      cache_dtype: str = "bf16", prefix=None,
                      sampler=None, prefill_chunk: int | None = None,
                      spec_k: int | None = None, telemetry=None,
                      kv_block: int = 16, policy: str = "fifo",
                      aging: int | None = None,
                      share_prefix: bool = False,
                      lazy_growth: bool = False,
                      prefix_keep_blocks: int = 64,
                      paged_kernel: str = "auto",
                      host_spill: bool = False,
                      host_blocks: int | None = None,
                      host_swap: str = "async",
                      shared_store=None,
                      aot_cache=None):
    """Reusable engine: compile once, run many schedules.

    The compiled pieces (per-bucket admissions, the all-slots paged
    step) live in the returned closure — repeated calls (and warm-up
    passes) share them. The KV cache underneath is PAGED
    (``kv_block``-row blocks; ``models/paging.py``): every run builds a
    physical pool of ``kv_blocks`` blocks (default: full provisioning —
    one table's worth per slot, the dense-equivalent capacity at which
    admission never blocks on memory), admissions allocate exactly the
    blocks their prompt + generation budget needs, and retirements
    recycle them. Pass a smaller ``kv_blocks`` to ``run`` to cap KV HBM
    — the queue then holds requests until blocks free (admission
    control), and ``run.last_stats["kv"]`` reports the realised
    high-water mark against the dense reservation.

    ``prefix`` (a ``[L_p]`` token array) enables PREFIX CACHING, now
    with physical BLOCK SHARING: the shared prefix prefills once per
    run into its own blocks; every admission's table points at the full
    prefix blocks directly (zero copies) and copies only the one
    partial tail block (``prefix_len % kv_block`` rows). Results equal
    decoding ``concat(prefix, prompt)`` from scratch.

    ``sampler`` (from :func:`..decode.make_sampler`, or the equivalent
    SPEC dict of its kwargs — ``dict(temperature=0.7, top_k=40)`` —
    normalised through ``make_sampler`` here, the picklable form a
    process-isolated fleet transport ships to its children) switches
    the engine from greedy to sampled generation; ``run`` then requires
    ``rng``. Every token's key is derived from (request index, token
    position) — NEVER from the schedule — so the same ``rng`` yields
    the same tokens whatever the slot count, arrival pattern or
    admission order (``sampler`` built with ``top_k=1`` reproduces the
    greedy engine exactly).

    ``prefill_chunk`` switches admission to CHUNKED PREFILL, now
    INTERLEAVED with decode: the prompt admits one ``[1, C]`` chunk per
    engine wave while every active slot keeps decoding between chunks —
    a long prompt no longer stalls the whole decode batch for its full
    prefill (the stall was the cost of the old one-dispatch sweep).
    Peak prefill score memory drops from ``[T, S_max]`` to
    ``[C, S_max]`` as before. Exact for bf16 caches; under an ``int8``
    cache every token attends fully-quantised history, so results are
    chunk-size-INVARIANT but can differ from unchunked int8 admission
    within quantisation noise.

    Int8-weight params (``quantize_params`` trees with QTensor leaves)
    serve through the PREFILL/DECODE PHASE SPLIT: admissions run from a
    dequantised compute-dtype copy built once here (prompt-width
    matmuls are compute-bound), decode/verification steps from the int8
    tree (weight-bandwidth-bound). Tokens equal the all-int8 engine
    exactly at f32 compute dtype and within one bf16 weight-rounding
    otherwise.

    ``spec_k`` turns on SPECULATIVE continuous batching (greedy only)
    on the paged pool: every step drafts ``k`` tokens per slot by
    prompt lookup in that slot's own context and verifies them in one
    batched ``[slots, k+1]`` forward through the same gather path the
    plain step reads (see :func:`make_spec_step`) — so the acceptance
    win survives occupancy > 1 on exactly the storage the plain engine
    uses. ``max_len`` must leave ``spec_k`` rows of verification
    headroom past each request's last token. After each call
    ``engine.last_stats`` reports realised acceptance
    (``accepted_per_step`` ≥ 1 is the speedup lever). Use ``spec_k``
    for eos/structured traffic; on fixed-length no-eos traffic the
    plain loop's count-based retirement is fully async and usually
    wins (see the bench ``serve_spec`` sweep).

    ``policy`` picks the ADMISSION ORDER (``"fifo"`` — strict arrival
    order, the baseline engine bit for bit; ``"sjf"`` — shortest job
    first on prompt length + budget; ``"priority"`` — per-request
    priorities via ``run(..., priorities=)``), with ``aging`` (waves; a
    non-fifo default of 512 bounds starvation) promoting any
    request that has waited past the bound. ``share_prefix`` turns on
    CROSS-REQUEST prefix-block sharing through a refcounted
    :class:`..paging.PrefixIndex`: an admission whose prompt shares
    full leading ``kv_block``-aligned blocks with a live or recently
    retired request maps those physical blocks (refcount++) and
    prefills only the unshared tail — ``prefix_keep_blocks`` caps the
    retained-but-unreferenced blocks the index holds past their
    writer's retirement (LRU). Shared-tail prefill runs the exact
    cached path, so on dense-attn configs outputs stay bitwise equal
    to the unshared engine; flash-attn configs resolve like chunked
    prefill (exact-dense suffix math). ``lazy_growth`` grants only the
    prompt's blocks plus one decode block at admission and grows each
    slot's table per wave as its position crosses block boundaries —
    the same ``kv_blocks`` cap then admits more concurrent requests on
    eos-heavy/short-output traffic, at the cost of a possible
    mid-flight STALL (and, if every live request stalls, a preemption
    — outputs are schedule-invariant either way). Both levers compose
    with chunked prefill AND with ``spec_k``: a speculative admission
    shares its full leading prompt blocks and prefills only the
    unshared suffix like any other, and the device-resident multi-step
    has a PER-K-TOKEN growth boundary — a lazily-granted slot whose
    next ``[k+1]``-row verification window would cross into an
    ungranted table entry freezes on device and hands control back to
    the host, which grants ``spec_k + 1`` more rows of blocks (or
    stalls / preempts, exactly as the plain loop does) before
    re-entering (see :func:`make_spec_step`). ``lazy_growth`` requires
    ``eos_check_every == 1`` on the plain loop.

    ``host_spill`` (requires ``share_prefix``) turns on the TIERED KV
    cache (``models/hostkv.py``): the prefix index's LRU evictions
    COPY a chain's blocks into a pinned host-RAM pool of
    ``host_blocks`` blocks (default ``max(4·prefix_keep_blocks, 64)``
    — the host tier exists because the template working set dwarfs
    the device cap, so it defaults strictly larger) instead of
    dropping them, and a later prefix hit against a spilled chain
    swaps the rows back in through fresh device blocks
    (crc-verified; a corrupt row is a CLASSIFIED drop — the request
    re-prefills from tokens, never decodes garbage). ``host_swap``
    picks the swap-in schedule: ``"async"`` (default) stages the next
    queued admission's host rows on a worker thread so the
    host→device copy overlaps the current wave's decode dispatch;
    ``"sync"`` loads at admission — identical bytes either way (the
    bit-match gate pins both), so the knob is purely a latency lever.
    Spilling composes with every scheduler lever (sharing refcounts,
    ``lazy_growth``, chunked prefill, ``spec_k``, the fleet) because
    the swap restores the exact exported bytes; ``last_stats
    ["prefix"]["spill"]`` carries the spill/hit/swap-latency split.

    ``paged_kernel`` (``"auto"|"on"|"off"``) picks the wave step's T=1
    read path: ``"auto"`` routes decode attention through the
    block-table-native pallas kernel on TPU — no per-wave
    ``[slots, NT·bs, kv, D]`` logical-view gather, cache reads scale
    with live tokens — falling back to the jnp gather on CPU, sharded
    pools, or non-lane-aligned geometry; ``"off"`` keeps the gather
    reference everywhere (the bit-match baseline); ``"on"`` forces the
    kernel (interpret mode off-TPU — the CI/bench gate). Admission and
    verification forwards always use the gather path (their q width
    amortises it).

    ``telemetry`` injects a telemetry registry (default: the process
    registry — the no-op unless ``TPU_TELEMETRY_DIR`` is set). When
    enabled, every admission emits a ``serve_prefill`` span, every
    retirement a ``serve_request`` span (admission → retirement — the
    p50/p99 request-latency record in ``serve_request_ms``) carrying
    ``queue_wait_ms`` / ``prefill_ms`` / ``decode_steps``, and every
    wave sets the ``serve_queue_depth`` / ``serve_slot_occupancy`` /
    ``kv_blocks_in_use`` gauges. Spans clock the host's view of the
    schedule: on an async backend the admission span covers dispatch,
    and the request span closes at the wave the host RETIRED the slot,
    not device completion. Under ``eos_check_every > 1`` a span's
    ``tokens`` counts the SCHEDULED tokens at retirement, which can
    exceed the emitted output by the lag window when a count-cap
    retirement precedes the scan that would have seen an earlier eos
    (``run.last_stats["generated"]`` reports emitted tokens exactly).

    ``aot_cache`` (a directory path or a
    :class:`..aotcache.AotCompileCache`) plugs the engine into the
    PERSISTENT AOT compile cache (``models/aotcache.py``): build
    activates jax's on-disk XLA cache under it (sticky — every compile
    this process makes lands on / loads from disk), and the returned
    engine grows a warm surface — ``run.warm(slots=, kv_blocks=,
    prompt_lens=)`` probes-or-compiles the WHOLE step family into
    crc-framed cache entries and primes the jit call path with a tiny
    seeded synthetic schedule, so a fleet joiner's bring-up pays disk
    reads and trace time instead of XLA compile walls
    (``engine_warmup_ms`` / ``join_first_token_ms`` gauges,
    ``aot_cache_hit_total`` / ``aot_cache_miss_total`` counters).
    Warming never changes output: a primed engine's runs are
    byte-identical to an unprimed engine's (the priming run leaves no
    cross-run state), and ``aot_cache=None`` engines are exactly the
    pre-cache engine.
    """
    # the AOT fingerprint reads the sampler BEFORE normalisation: a
    # spec dict describes itself deterministically on every side of a
    # process boundary, where the callable it builds would repr with a
    # memory address and split the cache key per process
    sampler_fp = _sampler_fingerprint(sampler)
    if isinstance(sampler, dict):
        # a sampler SPEC (dict(temperature=, top_k=, top_p=)) instead
        # of a callable: normalise through make_sampler here so the
        # spec form builds the identical pick function on every side
        # of a process boundary (a callable does not pickle — the
        # multi-proc transport ships specs and each child lands here)
        from .decode import make_sampler

        sampler = make_sampler(**sampler)
    if prefill_chunk is not None and prefill_chunk < 1:
        raise ValueError(
            f"prefill_chunk must be >= 1, got {prefill_chunk}")
    if kv_block < 1:
        raise ValueError(f"kv_block must be >= 1, got {kv_block}")
    if spec_k is not None:
        if spec_k < 1:
            raise ValueError(f"spec_k must be >= 1, got {spec_k}")
        if sampler is not None:
            raise ValueError(
                "speculative serving is greedy-only: acceptance tests "
                "the model's argmax chain — drop sampler or spec_k")
    if paged_kernel not in ("auto", "on", "off"):
        raise ValueError(f"unknown paged_kernel {paged_kernel!r}: "
                         f"use auto|on|off")
    if policy not in _POLICIES:
        raise ValueError(
            f"unknown policy {policy!r}: use {' | '.join(_POLICIES)}")
    if aging is not None and aging < 1:
        raise ValueError(f"aging must be >= 1 waves, got {aging}")
    aging = _DEFAULT_AGING if aging is None else aging
    if prefix_keep_blocks < 0:
        raise ValueError(
            f"prefix_keep_blocks must be >= 0, got {prefix_keep_blocks}")
    if host_swap not in ("async", "sync"):
        raise ValueError(
            f"unknown host_swap {host_swap!r}: use async|sync")
    if host_blocks is not None and host_blocks < 1:
        raise ValueError(f"host_blocks must be >= 1, got {host_blocks}")
    if host_spill and not share_prefix:
        raise ValueError(
            "host_spill is the prefix index's second tier — enable "
            "share_prefix=True alongside it (there is nothing to spill "
            "without an index)")
    if shared_store is not None:
        if host_spill:
            raise ValueError(
                "shared_store replaces the private host tier — a "
                "replica cannot spill both to its own HostBlockPool "
                "and to the fleet CDN; drop host_spill")
        if not share_prefix:
            raise ValueError(
                "shared_store is the prefix index's CDN tier — enable "
                "share_prefix=True alongside it (there is nothing to "
                "publish without an index)")
    if host_blocks is None:
        # default: room for several keep-caps' worth of templates — the
        # host tier exists precisely because the working set dwarfs the
        # device cap, so it must default strictly larger
        host_blocks = max(4 * prefix_keep_blocks, 64)
    from ..telemetry import get_registry

    reg = telemetry if telemetry is not None else get_registry()
    # persistent AOT compile cache (models/aotcache.py): a string is a
    # directory path — the form the multi-process transport ships,
    # since the object pickles down to its path anyway. Activation is
    # STICKY by design: a fleet child points jax's persistent XLA
    # cache at the shared directory once at build, so every compile —
    # warm-stage or call-path — lands on / loads from disk.
    if isinstance(aot_cache, str):
        from .aotcache import AotCompileCache

        aot_cache = AotCompileCache(aot_cache, telemetry=reg)
    if aot_cache is not None:
        aot_cache.activate()
    pick = _make_pick(sampler)
    from .quantize import QTensor

    def _is_q(x):
        return isinstance(x, QTensor)

    prefill_params = params
    if any(_is_q(x) for x in jax.tree.leaves(params, is_leaf=_is_q)):
        # PREFILL/DECODE PHASE SPLIT for int8-weight params: admission
        # is compute-bound (prompt-width matmuls route past the M<=64
        # kernel gate to XLA's dequant-dot, which is SLOWER than a bf16
        # matmul — measured 0.72-0.90x end-to-end, BENCH_r04), while
        # decode steps are weight-bandwidth-bound (int8 bytes win). So
        # the engine dequantises ONCE at build into a resident compute-
        # dtype tree and serves every admission path from it; decode and
        # verification steps keep the int8 tree. Residency cost: int8 +
        # bf16 copies = 3 bytes/weight vs pure bf16's 2.
        prefill_params = jax.tree.map(
            lambda x: x.dequantize() if _is_q(x) else x, params,
            is_leaf=_is_q)

    geom = paged_pool_spec(cfg, max_len, kv_block, cache_dtype)
    bs = kv_block
    nt = geom["tables"]
    quant = cache_dtype == "int8"
    pool_keys = ("k", "v") + (("k_scale", "v_scale") if quant else ())

    # the host tier's pool is built ONCE here — the big numpy
    # allocation happens at engine build (an oversized host_blocks
    # surfaces at construction, not mid-serving) and each run resets
    # the allocator/crc state over the same buffers
    host_pool = None
    if host_spill:
        from .hostkv import HostBlockPool

        host_pool = HostBlockPool(cfg, host_blocks, block_size=bs,
                                  cache_dtype=cache_dtype)

    prefix_len = 0
    prefix_full_blocks = 0                 # whole blocks shared read-only
    prefix_tail_rows = 0                   # rows copied per admission
    if prefix is not None:
        prefix = jnp.asarray(prefix)
        prefix_len = int(prefix.shape[-1])
        if prefix_len >= max_len:
            raise ValueError(
                f"prefix ({prefix_len}) must leave room under max_len "
                f"({max_len})")
        prefix_full_blocks = prefix_len // bs
        prefix_tail_rows = prefix_len % bs

    # the AOT cache SCOPE: jax/backend/devices + cfg + every lever
    # that changes generated code (models/aotcache.py). Computed even
    # without an aot_cache so ``warm_engine(engine, cache)`` can warm
    # an engine built before the cache existed.
    from .aotcache import engine_fingerprint

    aot_scope = engine_fingerprint(cfg, max_len, dict(
        cache_dtype=cache_dtype, sampler=sampler_fp,
        prefill_chunk=prefill_chunk, spec_k=spec_k, kv_block=kv_block,
        policy=policy, aging=aging, share_prefix=share_prefix,
        lazy_growth=lazy_growth, prefix_keep_blocks=prefix_keep_blocks,
        paged_kernel=paged_kernel, host_spill=host_spill,
        host_blocks=host_blocks, host_swap=host_swap,
        # the CDN lever as a BOOLEAN: the store object's repr carries a
        # memory address, which would split the cache key per process
        shared_store=shared_store is not None,
        prefix_len=prefix_len,
        quant_weights=prefill_params is not params))

    # ---------------------------------------------------------- jits
    # shared helpers for the one-row (per-slot) view of the pool

    def _sub1(pool, tables, slot, start):
        return dict(pool, block_tables=tables[slot][None],
                    pos=jnp.full((1,), start, jnp.int32))

    def _merge(pool, sub, tables, slot):
        out = dict(pool)
        for key_ in pool_keys:
            out[key_] = sub[key_]
        out["block_tables"] = tables
        out["pos"] = pool["pos"].at[slot].set(sub["pos"][0])
        return out

    def _tail_copy(pool, src, dst):
        """Copy the prefix's partial tail block into the admission's
        first own block — the only per-admission prefix bytes; full
        prefix blocks are shared read-only across every request."""
        out = dict(pool)
        for key_ in pool_keys:
            out[key_] = [buf.at[dst].set(buf[src]) for buf in pool[key_]]
        return out

    @functools.partial(jax.jit, static_argnums=(2,), donate_argnums=(8,))
    def _admit_full(p, prompt, impl, slot, row, key, tail, start, pool):
        """One dispatch per admission: set the slot's table row and
        start position, copy the prefix tail block (when configured),
        prefill the prompt through the slot's blocks, pick the first
        token. ``tail`` is ``(src, dst)`` physical block ids; ``start``
        is the first position the prompt (or, under cross-request
        sharing, its unshared suffix) prefills at."""
        tables = pool["block_tables"].at[slot].set(row)
        if prefix_tail_rows:
            pool = _tail_copy(pool, tail[0], tail[1])
        sub = _sub1(pool, tables, slot, start)
        # int8_kernel and paged_kernel OFF on every admission path:
        # these jits compile once per engine but run against pools a
        # later run() may have mesh-sharded (the pallas-on-sharded-
        # operands hazard fires at t==1 — single-token prompts, C=1
        # chunks), and admission is a one-shot dispatch, not the
        # bandwidth-bound wave loop the kernels exist for
        logits, sub = forward_paged(p, prompt, sub, cfg,
                                    prefill_impl=impl,
                                    int8_kernel=False,
                                    paged_kernel="off")
        return pick(logits, -1, key), _merge(pool, sub, tables, slot)

    @functools.partial(jax.jit, donate_argnums=(4,))
    def _admit_table(slot, row, tail, start, pool):
        """Chunked admission's setup dispatch: table row + start pos +
        prefix tail copy; the chunks then stream via ``_chunk_step``."""
        tables = pool["block_tables"].at[slot].set(row)
        if prefix_tail_rows:
            pool = _tail_copy(pool, tail[0], tail[1])
        out = dict(pool)
        out["block_tables"] = tables
        out["pos"] = pool["pos"].at[slot].set(start)
        return out

    @functools.partial(jax.jit, donate_argnums=(3,))
    def _grow_table(slot, idx, block, pool):
        """Lazy growth's one-entry table write: map physical ``block``
        at the slot's next logical index. One tiny dispatch per growth
        event — once per ``kv_block`` generated tokens per slot."""
        out = dict(pool)
        out["block_tables"] = pool["block_tables"].at[slot, idx].set(block)
        return out

    @functools.partial(jax.jit, donate_argnums=(4,))
    def _chunk_sweep(p, chunks, n, last_idx, pool, slot, key, true_pos):
        """ONE-dispatch chunked admission (the speculative loop's
        variant — its device multi-step has no per-wave host boundary
        to interleave chunks into, and per-chunk dispatches measured
        3-4x slower through the tunnelled backend's dispatch latency):
        a fori_loop with a TRACED trip count walks the ``[1, MC, C]``
        padded prompt; dead trailing chunks never run. Same math in
        the same order as the interleaved path — both are
        ``forward_paged`` at the slot's running position."""
        tables = pool["block_tables"]

        def body(i, carry):
            row, pool = carry
            sub = _sub1(pool, tables, slot, pool["pos"][slot])
            logits, sub = forward_paged(p, chunks[:, i], sub, cfg,
                                        prefill_impl="cached",
                                        int8_kernel=False,
                                        paged_kernel="off")
            pool = _merge(pool, sub, tables, slot)
            # keep only the FINAL live chunk's last-token logits
            row = jnp.where(i == n - 1, logits[0, last_idx], row)
            return row, pool

        row0 = jnp.zeros((cfg.vocab,), cfg.dtype)
        row, pool = jax.lax.fori_loop(0, n, body, (row0, pool))
        out = dict(pool)
        out["pos"] = pool["pos"].at[slot].set(true_pos)
        return pick(row[None, None], 0, key), out

    @functools.partial(jax.jit, donate_argnums=(3,))
    def _chunk_step(p, chunk, slot, pool):
        """One ``[1, C]`` prefill chunk at the slot's current position —
        the unit the engine interleaves with decode waves. Pad rows in
        the final chunk land in the cache but are unreachable: cached
        attention masks ``k_pos > q_pos`` and ``pos`` rewinds to the
        true length at finish, so decode writes overwrite them in
        order."""
        tables = pool["block_tables"]
        sub = _sub1(pool, tables, slot, pool["pos"][slot])
        logits, sub = forward_paged(p, chunk, sub, cfg,
                                    prefill_impl="cached",
                                    int8_kernel=False,   # see _admit_full
                                    paged_kernel="off")
        return logits[0], _merge(pool, sub, tables, slot)

    @functools.partial(jax.jit, donate_argnums=(4,))
    def _chunk_finish(logits_c, last_idx, key, slot, pool, true_pos):
        """Final-chunk epilogue: rewind ``pos`` past the pad rows and
        pick the first token from the last TRUE position's logits."""
        out = dict(pool)
        out["pos"] = pool["pos"].at[slot].set(true_pos)
        return pick(logits_c[None], last_idx, key), out

    @functools.partial(jax.jit, donate_argnums=(3,))
    def _prefix_fill(p, prefix_toks, row, pool):
        """Prefill the shared prefix once per run into its own blocks
        (no slot involved — the table row is passed directly)."""
        sub = dict(pool, block_tables=row[None],
                   pos=jnp.zeros((1,), jnp.int32))
        impl = _prefix_impl
        _logits, sub = forward_paged(p, prefix_toks, sub, cfg,
                                     prefill_impl=impl,
                                     int8_kernel=False,  # see _admit_full
                                     paged_kernel="off")
        out = dict(pool)
        for key_ in pool_keys:
            out[key_] = sub[key_]
        return out

    if prefix is not None:
        from .decode import _select_prefill_impl

        _prefix_impl = _select_prefill_impl(cfg, prefix_len, "auto")

    # one dispatch per speculative admission: building the context row
    # with eager .at[] ops cost ~7 device round trips per request
    # through the tunnelled backend. ``prefix`` is a closure constant
    # here deliberately — it is a short token vector, not a weight tree.
    @functools.partial(jax.jit, donate_argnums=(3, 4, 5))
    def _spec_admit_row(prompt, first, slot, ctxbuf, cur, n_out):
        length = prompt.shape[-1]
        row = jnp.zeros((ctxbuf.shape[1],), jnp.int32)
        if prefix is not None:
            row = row.at[:prefix_len].set(prefix)
        row = jax.lax.dynamic_update_slice(row, prompt, (prefix_len,))
        row = row.at[prefix_len + length].set(first)
        return (ctxbuf.at[slot].set(row),
                cur.at[slot].set(prefix_len + length + 1),
                n_out.at[slot].set(1))

    # the all-slots steps are built per int8-kernel flag on first use: a
    # mesh-sharded int8 pool must keep the jnp attention path (pallas on
    # sharded operands — see make_serve_step), and only run() sees rules
    _steps: dict[tuple, Any] = {}

    def step_for(kind: str, int8_kernel: bool, rules):
        # ONE cached step per (kind, kernel-flags): a different rules
        # object rebuilds that slot (recompile) rather than growing a
        # keyed-by-id cache without bound — callers alternating rules
        # objects pay compiles, never leak them. The entry keeps the
        # rules reference so its id stays valid while cached. The
        # paged kernel demotes to the gather path under rules exactly
        # like the int8 kernel (pallas on sharded operands).
        pk = paged_kernel if rules is None else "off"
        key_ = (kind, int8_kernel, pk)
        rid = None if rules is None else id(rules)
        ent = _steps.get(key_)
        if ent is None or ent[0] != rid:
            if kind == "spec":
                step = make_spec_step(params, cfg, spec_k,
                                      int8_kernel=int8_kernel,
                                      paged_kernel=pk, rules=rules)
            else:
                step = make_serve_step(params, cfg, sampler,
                                       int8_kernel=int8_kernel,
                                       paged_kernel=pk, rules=rules)
            _steps[key_] = (rid, step, rules)
        return _steps[key_][1]

    # ------------------------------------------------------ admission

    def _check_chunk_bound(length: int, start: int | None = None) -> int:
        start = prefix_len if start is None else start
        n = -(-length // prefill_chunk)
        if start + n * prefill_chunk > max_len:
            # the padded tail would index past the table, where the
            # clipped block lookup would silently overwrite the last
            # cache rows — refuse loudly instead
            raise ValueError(
                f"chunked prefill pads the prompt ({length}) to "
                f"{n * prefill_chunk} rows, which after the start "
                f"position ({start}) exceeds max_len ({max_len}) — "
                f"raise max_len to >= {start + n * prefill_chunk} or "
                f"shrink prefill_chunk")
        return n

    def _rows_needed(length: int, n_new_i: int, headroom: int) -> int:
        rows = prefix_len + length + n_new_i + headroom
        if prefill_chunk is not None:
            padded = prefix_len + _check_chunk_bound(length) * prefill_chunk
            rows = max(rows, padded)
        return min(rows, geom["rows"])

    class _Run:
        """Per-run scheduler state: the paged pool + allocator + the
        host-side request bookkeeping (one instance per ``run`` call —
        the compiled pieces above are engine-lifetime)."""

        def __init__(self, slots, rules, kv_blocks, headroom,
                     n_new_of, prompts):
            from .paging import init_paged_cache

            self.slots = slots
            self.headroom = headroom
            self.n_new_of = n_new_of
            need_prefix = (prefix_full_blocks
                           + (1 if prefix_tail_rows else 0))
            if kv_blocks is None:
                kv_blocks = 1 + need_prefix + slots * nt
            # feasibility is always the FULL budget, lazy growth or
            # not: a request that ends up alone in the pool (the
            # preemption fallback's terminal state) must be able to
            # grow to its worst case
            worst = max(
                blocks_for_rows(
                    _rows_needed(int(p.shape[-1]), n_new_of[i], headroom)
                    - prefix_full_blocks * bs, bs)
                for i, p in enumerate(prompts))
            if kv_blocks < 1 + need_prefix + worst:
                raise ValueError(
                    f"kv_blocks ({kv_blocks}) cannot hold the largest "
                    f"request ({worst} blocks of {bs} rows"
                    + (f" + {need_prefix} prefix blocks" if need_prefix
                       else "")
                    + " + the reserved garbage block) — the queue would "
                    "deadlock; raise kv_blocks")
            self.kv_blocks = kv_blocks
            self.alloc = BlockAllocator(kv_blocks)
            # tiered prefix index (models/hostkv.py): evictions spill
            # to the pinned host pool instead of dropping; the adapter
            # reads the LIVE pool through a closure because the wave
            # loop rebinds self.pool every dispatch
            self.host = host_pool
            self.store = shared_store
            spill = None
            if self.host is not None:
                from .hostkv import IndexSpill

                self.host.reset()
                spill = IndexSpill(self.host, lambda: self.pool)
            elif self.store is not None:
                # fleet-shared CDN tier: evictions hand over whole
                # root→leaf CHAINS (tokens + rows) to the shared store
                # — no per-index host ids, no "host" entries; re-entry
                # happens at admission via _cdn_swap_in
                from .hostkv import ChainSpill

                spill = ChainSpill(self.store, lambda: self.pool)
            self.index = (PrefixIndex(self.alloc, prefix_keep_blocks,
                                      spill=spill)
                          if share_prefix else None)
            # async swap-in staging (host_swap="async"): at most one
            # prefetched chain, keyed by its exact (key, host_id) tail
            # so a chain that moved under the prefetch falls back to
            # the synchronous load — identical bytes either way
            self._staged_sig: tuple | None = None
            self._staged_fut = None
            self.pool = init_paged_cache(
                cfg, slots, max_len, block_size=bs, num_blocks=kv_blocks,
                rules=rules, cache_dtype=cache_dtype)
            self.owned: dict[int, list[int]] = {}     # req → blocks
            self.prefix_blocks: list[int] = []
            self.tail_src = 0
            self.in_use_sum = 0                       # per-wave samples
            self.in_use_n = 0
            self.logical: dict[int, int] = {}         # req → table blocks
            self.logical_now = 0
            self.logical_peak = 0
            self.logical_sum = 0
            self.live_sum = 0
            self.grown_lazy = 0
            self.preempted = 0
            self.admit_wave: dict[int, int] = {}
            self.retire_wave: dict[int, int] = {}
            self.prefix_stats = {"hit_blocks": 0, "lookups": 0,
                                 "prompt_blocks": 0, "tokens_saved": 0,
                                 # tiered-KV split: blocks served from
                                 # the host tier (swapped in on a hit
                                 # against a spilled chain), the swap
                                 # traffic/latency, classified corrupt
                                 # drops, and why reclaim() came back
                                 # empty-handed (live vs empty — the
                                 # satellite distinction)
                                 "host_hit_blocks": 0, "swapins": 0,
                                 "swapped_blocks": 0, "swap_ms": 0.0,
                                 "swap_tokens_saved": 0,
                                 "corrupt_dropped": 0,
                                 "reclaim_blocked_live": 0,
                                 "reclaim_blocked_empty": 0,
                                 # elastic-fleet state migration: warm
                                 # chains seeded at bring-up (adopted
                                 # host-side + indexed), seeds the host
                                 # pool refused, and retained chains
                                 # published to a drain sink at close
                                 "warm_chains": 0, "warm_blocks": 0,
                                 "warm_dropped": 0,
                                 "published_chains": 0,
                                 # durable prefix CDN (shared_store):
                                 # blocks swapped in from the shared
                                 # store, the subset that came off the
                                 # crash-safe DISK tail, and the
                                 # disk-path latency share
                                 "cdn_hit_blocks": 0,
                                 "disk_hit_blocks": 0,
                                 "disk_swap_ms": 0.0}
            self._toks: dict[int, list] = {}          # host prompt cache
            self._row_np: dict[int, Any] = {}
            if prefix is not None:
                blocks = self.alloc.alloc(need_prefix)
                assert blocks is not None            # sized above
                self.prefix_blocks = blocks
                row = np.zeros((nt,), np.int32)
                row[:need_prefix] = blocks
                if prefix_tail_rows:
                    self.tail_src = blocks[-1]
                self.pool = _prefix_fill(prefill_params, prefix[None, :],
                                         jnp.asarray(row), self.pool)

        def admit_blocks(self, req: int, prompt, length: int, *,
                         share: bool = True):
            """Allocate the request's blocks, sharing any indexed full
            leading prefix blocks first (refcount++ — read-only for
            this request); None = hold in queue. Returns ``(row, tail,
            start, shared_tokens, entries)`` where ``start`` is the
            prefill start position and ``entries`` the table entries
            granted so far (the lazy-growth watermark). ``share=False``
            skips the prefix index entirely (imported admissions: their
            rows arrive as bytes from another pool, so matching would
            skip an import that must happen and registering would index
            blocks this engine never hashed)."""
            shared: list[int] = []
            cov = 0
            n_chunks = 0
            dev_k = 0
            if share and self.index is not None:
                chunks = self._chunks_for(req, prompt, length)
                n_chunks = len(chunks)
                if self.host is not None:
                    # tiered match: the device-resident prefix is
                    # shared like any match; a spilled continuation is
                    # swapped back in (fresh device blocks + row
                    # import + promote), extending the hit — or left
                    # host-side when the pool cannot spare the blocks
                    shared, tail = self.index.match_tiered(chunks)
                    dev_k = len(shared)
                    if tail:
                        shared = shared + self._swap_in(tail)
                else:
                    shared = self.index.match(chunks)
                    dev_k = len(shared)
                    if self.store is not None and dev_k < n_chunks:
                        # CDN continuation: the fleet-shared store (RAM
                        # tier, crash-safe disk tail behind it) may
                        # hold the rest of the chain — swap it in and
                        # REGISTER it so the next admission hits
                        # device-resident
                        shared = shared + self._cdn_swap_in(
                            chunks, dev_k, shared)
                cov = chunk_tokens_covered(len(shared), bs,
                                           prefix_tail_rows)
                if prefill_chunk is not None:
                    # the PADDED unshared suffix must stay within the
                    # table — un-share blocks until it fits
                    while shared and (prefix_len + cov + -(-(
                            length - cov) // prefill_chunk)
                            * prefill_chunk) > max_len:
                        self.alloc.free([shared.pop()])
                        cov = chunk_tokens_covered(len(shared), bs,
                                                   prefix_tail_rows)
            k = len(shared)
            budget = prefix_len + length + self.n_new_of[req] \
                + self.headroom
            # lazy grant covers the first write window: one decode row
            # for the plain loop, the k+1-row verification window for
            # the speculative loop (headroom == spec_k) — the per-
            # k-token growth boundary that lets spec compose with lazy
            grant = (prefix_len + length + 1 + self.headroom) \
                if lazy_growth else budget
            if prefill_chunk is not None:
                padded_end = prefix_len + cov + -(-(
                    length - cov) // prefill_chunk) * prefill_chunk
                grant = max(grant, padded_end)
            grant = min(grant, geom["rows"])
            own_rows = grant - prefix_full_blocks * bs - k * bs
            blocks = self._alloc_reclaiming(blocks_for_rows(own_rows, bs))
            if blocks is None:
                if shared:
                    self.alloc.free(shared)          # undo the shares
                return None
            # stats count ADMISSIONS, not probes: a request held for
            # blocks re-matches every wave, and billing each failed
            # attempt would skew hit_frac low by the wait length
            if share and self.index is not None:
                self.prefix_stats["lookups"] += 1
                self.prefix_stats["prompt_blocks"] += n_chunks
                self.prefix_stats["hit_blocks"] += k
                self.prefix_stats["tokens_saved"] += cov
                host_k = max(0, k - dev_k)
                if host_k:
                    # the tier split: hits the HBM cap alone would have
                    # missed, and the prefill tokens the host tier
                    # saved beyond the device-resident prefix
                    self.prefix_stats["host_hit_blocks"] += host_k
                    self.prefix_stats["swap_tokens_saved"] += (
                        cov - chunk_tokens_covered(dev_k, bs,
                                                   prefix_tail_rows))
            self.owned[req] = shared + blocks
            row = np.zeros((nt,), np.int32)
            row[:prefix_full_blocks] = \
                self.prefix_blocks[:prefix_full_blocks]
            row[prefix_full_blocks:prefix_full_blocks + k] = shared
            row[prefix_full_blocks + k:
                prefix_full_blocks + k + len(blocks)] = blocks
            # the template tail copy applies only when no shared block
            # already carries those rows (k == 0)
            tail = jnp.asarray(
                [self.tail_src if k == 0 else 0,
                 blocks[0] if k == 0 else 0], jnp.int32)
            entries = prefix_full_blocks + k + len(blocks)
            self.logical[req] = entries         # every table-mapped block
            self.logical_now += self.logical[req]
            self.logical_peak = max(self.logical_peak, self.logical_now)
            self._row_np[req] = row
            return (jnp.asarray(row), tail, prefix_len + cov, cov,
                    entries)

        def _chunks_for(self, req: int, prompt, length: int) -> list:
            """The prompt's candidate chain chunks for the prefix
            index — at least one prompt token must remain to forward
            (its logits pick the first generated token). ONE
            definition, so the admission match and the async swap-in
            PREFETCH can never disagree on the chain they name."""
            toks = self._toks.get(req)
            if toks is None:
                toks = [int(t) for t in np.asarray(prompt)]
                self._toks[req] = toks
            chunks = chain_chunks(toks, bs, prefix_tail_rows)
            while chunks and chunk_tokens_covered(
                    len(chunks), bs, prefix_tail_rows) > length - 1:
                chunks.pop()
            return chunks

        def _swap_in(self, tail: list) -> list[int]:
            """Swap a spilled chain continuation back to the device
            tier: grant fresh device blocks, import the host rows
            (``paging.import_block_rows`` — the staged async payload
            when the prefetch matched, the synchronous crc-verified
            load otherwise; identical bytes either way, which is what
            the bit-match gate pins) and ``promote`` the entries.
            Returns the now-device-resident blocks carrying this
            request's reference, exactly like matched shared blocks —
            or ``[]`` when the device pool cannot spare the grant (the
            chain stays host-resident, nothing to undo) or the rows
            failed their crc (classified: the chain is DROPPED and the
            request prefills from tokens — slow, never wrong)."""
            from .hostkv import HostSpillCorruptError
            from .paging import import_block_rows

            keys = [key for key, _hid in tail]
            blocks = self._alloc_reclaiming(len(tail))
            if blocks is None:
                return []
            sig = tuple(tail)
            staged = None
            if self._staged_sig == sig and self._staged_fut is not None:
                # consume the prefetch; on a mismatch LEAVE it staged —
                # it belongs to a different queued request whose
                # admission may still claim it this wave (the sig keys
                # content, so a stale entry can never serve wrong
                # bytes, only be replaced by the next prefetch)
                staged = self._staged_fut
                self._staged_sig, self._staged_fut = None, None
            t0 = time.monotonic()
            try:
                payload = (staged.result() if staged is not None
                           else self.host.load([h for _k, h in tail]))
            except HostSpillCorruptError:
                self.alloc.free(blocks)
                self.index.discard(keys[0])      # quarantine the chain
                self.prefix_stats["corrupt_dropped"] += 1
                return []
            self.pool = import_block_rows(self.pool, blocks, payload)
            self.index.promote(keys, blocks)
            self.prefix_stats["swapins"] += 1
            self.prefix_stats["swapped_blocks"] += len(blocks)
            self.prefix_stats["swap_ms"] += (time.monotonic() - t0) * 1e3
            return blocks

        def _cdn_swap_in(self, chunks: list, dev_k: int,
                         shared: list[int]) -> list[int]:
            """Swap a chain continuation in from the fleet-shared CDN
            store: fetch the crc-verified rows (RAM pin-copy, or the
            disk tail's PCD1-framed restore — the store promotes disk
            hits to RAM itself), grant fresh device blocks, import the
            rows and ``register`` the chain so the index holds one
            reference past this request's retirement — the same
            terminal refcounts the private host tier's swap-in +
            ``promote`` leaves. Returns the now-device-resident blocks
            carrying this request's reference — or ``[]`` on a store
            miss, a corrupt drop (the store quarantined/dropped it
            already) or an exhausted device pool (nothing to undo; the
            request prefills from tokens — slow, never wrong)."""
            from .paging import import_block_rows

            t0 = time.monotonic()
            clk0 = _clk()
            got = self.store.fetch(chunks, start=dev_k)
            if got is None:
                return []
            n, payload, from_disk = got
            blocks = self._alloc_reclaiming(n)
            if blocks is None:
                return []
            self.pool = import_block_rows(self.pool, blocks, payload)
            # already-indexed dev nodes (the matched prefix) are
            # skipped by register; the new nodes take one index
            # reference each — rc 2 = this request + the index
            self.index.register(chunks[:dev_k + n], shared + blocks)
            ms = (time.monotonic() - t0) * 1e3
            ps = self.prefix_stats
            ps["swapins"] += 1
            ps["swapped_blocks"] += n
            ps["swap_ms"] += ms
            ps["cdn_hit_blocks"] += n
            if from_disk:
                ps["disk_hit_blocks"] += n
                ps["disk_swap_ms"] += ms
                if reg.enabled:
                    reg.emit_span("prefix_disk_swap", clk0, reg.clock(),
                                  blocks=n)
            return blocks

        def prefetch_swap(self, req: int, prompt) -> None:
            """The double-buffering half (``host_swap="async"``): probe
            the NEXT admission's spilled continuation read-only
            (``peek_host_tail`` — no references, no LRU touch) and
            stage its host rows on the pool's worker thread, so the
            host→device copy overlaps this wave's decode dispatch. A
            chain that moves between prefetch and admission misses the
            signature and falls back to the synchronous path."""
            if self.host is None or self.index is None:
                return
            from .hostkv import HostSpillCorruptError

            tail = self.index.peek_host_tail(
                self._chunks_for(req, prompt, int(prompt.shape[-1])))
            if not tail:
                return
            sig = tuple(tail)
            if self._staged_sig == sig:
                return                           # already in flight
            try:
                fut = self.host.stage([h for _k, h in tail])
            except HostSpillCorruptError:
                # the admission's synchronous load re-detects this and
                # runs the classified drop — never stage garbage
                return
            self._staged_sig, self._staged_fut = sig, fut

        def register_prefix(self, req: int) -> None:
            """Index the request's prefilled FULL prompt blocks so
            later admissions can share them (no-op when sharing is
            off). Skips chain nodes the donor itself matched."""
            if self.index is None:
                return
            chunks = chain_chunks(self._toks[req], bs, prefix_tail_rows)
            row = self._row_np[req]
            self.index.register(
                chunks, [int(row[prefix_full_blocks + j])
                         for j in range(len(chunks))])

        def _alloc_reclaiming(self, n: int):
            """``alloc`` that EVICTS retained-but-unreferenced prefix
            blocks under allocation pressure before giving up — a
            retained prefix must never starve a new admission into
            permanent queueing at a tight ``kv_blocks`` cap. A
            fruitless reclaim is billed by WHY (live-referenced vs
            nothing retained), the distinction the spill tier's
            admission control reads."""
            blocks = self.alloc.alloc(n)
            while blocks is None and self.index is not None:
                if not self.index.reclaim(n - self.alloc.free_blocks):
                    why = self.index.reclaim_blocked
                    if why is not None:
                        self.prefix_stats[f"reclaim_blocked_{why}"] += 1
                    return None
                blocks = self.alloc.alloc(n)
            return blocks

        def grow_block(self, req: int) -> int | None:
            """One more block for a lazily-granted request (None: pool
            empty — the caller stalls the slot)."""
            b = self._alloc_reclaiming(1)
            if b is None:
                return None
            self.owned[req].append(b[0])
            self.logical[req] += 1
            self.logical_now += 1
            self.logical_peak = max(self.logical_peak, self.logical_now)
            self.grown_lazy += 1
            return b[0]

        def retire_blocks(self, req: int) -> None:
            self.alloc.free(self.owned.pop(req))
            self.logical_now -= self.logical.pop(req)
            self._toks.pop(req, None)
            self._row_np.pop(req, None)
            if self.index is not None:
                # drop retained-but-unreferenced prefix blocks past the
                # LRU cap now that this request's references are gone
                self.index.trim()

        def seed_warm(self, chains) -> None:
            """WARM BRING-UP: adopt ``(chunks, payload)`` chains into
            the HOST tier and index them (``PrefixIndex.seed_host``)
            before the first admission — the joining replica's
            inheritance of the fleet's popular-prefix working set. A
            chain the host pool cannot hold (or an engine with no host
            tier at all) is dropped and billed — a cold chain costs a
            re-prefill, never correctness. The seeded rows swap in
            through the ordinary crc-verified tiered admission path, so
            a corrupt migrated chain quarantines exactly like a corrupt
            spill."""
            ps = self.prefix_stats
            for chunks, payload in chains:
                if self.host is None or self.index is None:
                    ps["warm_dropped"] += 1
                    continue
                hids = self.host.adopt(payload)
                if hids is None:
                    ps["warm_dropped"] += 1
                    continue
                seeded = self.index.seed_host(chunks, hids)
                ps["warm_chains"] += 1
                ps["warm_blocks"] += seeded

        def publish_chains(self, sink) -> None:
            """Drain-time PUBLISH: copy every retained indexed chain
            (device tier exported from the live pool, host tier loaded
            crc-verified) into ``sink`` — how a drained/finishing
            replica's warm state reaches the fleet-shared store for
            successors to inherit. Read-only against the index: no
            references move, no eviction runs, and in particular
            ``spill_dropped`` is NEVER billed here — a publish the sink
            refuses is the SINK's accounting (``store_full_drops``),
            not a spill drop, so a drain racing a pressure reclaim can
            never double-count the eviction (regression-pinned in
            tests/test_paging.py)."""
            from .hostkv import HostSpillCorruptError
            from .paging import export_block_rows

            if self.index is None:
                return
            chains = []
            for chunks, ids in self.index.export_chains():
                dev = [b for t, b in ids if t == "dev"]
                hst = [b for t, b in ids if t == "host"]
                parts = []
                if dev:
                    pay = export_block_rows(self.pool, dev)
                    parts.append({k: [np.asarray(b) for b in bufs]
                                  for k, bufs in pay.items()})
                if hst:
                    try:
                        parts.append(self.host.load(hst))
                    except HostSpillCorruptError:
                        # quarantine discipline: suspect bytes never
                        # migrate — drop the chain from the publish
                        self.prefix_stats["corrupt_dropped"] += 1
                        continue
                if not parts:
                    continue
                if len(parts) == 1:
                    payload = parts[0]
                else:
                    payload = {
                        k: [np.concatenate([np.asarray(a),
                                            np.asarray(b)])
                            for a, b in zip(parts[0][k], parts[1][k])]
                        for k in parts[0]}
                chains.append((chunks, payload))
            if chains:
                self.prefix_stats["published_chains"] += \
                    sink.publish(chains)

        def close(self, sink=None) -> None:
            """End of run: publish retained chains to the drain sink
            (when one is wired — BEFORE release tears the tiers down),
            release the prefix index's retained blocks so the pool
            drains to empty (the leak check's invariant — BOTH tiers:
            release frees host copies too), and shut the swap worker
            down."""
            if sink is not None and self.index is not None:
                self.publish_chains(sink)
            if self.index is not None:
                self.index.release()
            self._staged_sig, self._staged_fut = None, None
            if self.host is not None:
                self.host.close()

        def sample(self, live: int = 0) -> None:
            """One per-wave occupancy sample (host ints — runs whether
            or not telemetry is on; feeds the mean-utilisation and
            admitted-concurrency stats)."""
            self.in_use_sum += self.alloc.in_use
            self.in_use_n += 1
            self.logical_sum += self.logical_now
            self.live_sum += live

        def kv_stats(self) -> dict:
            s = self.alloc.stats()
            dense = self.slots * geom["rows"]
            mean_blocks = (self.in_use_sum / self.in_use_n
                           if self.in_use_n else 0.0)
            return {
                **s,
                "block_size": bs,
                "peak_rows": s["high_water"] * bs,
                # what the dense [slots, max_len] pool would have
                # RESERVED for the same schedule — the paging win
                "dense_rows": dense,
                # peak/mean bill PHYSICAL blocks — a refcounted shared
                # block counts once, however many tables map it; the
                # logical twin (what the same tables would cost
                # unshared) rides alongside so the sharing win is
                # visible in the same record
                "utilisation": round(s["high_water"] * bs
                                     / max(dense, 1), 4),
                "mean_utilisation": round(mean_blocks * bs
                                          / max(dense, 1), 4),
                "kv_blocks_physical": s["high_water"],
                "kv_blocks_logical": self.logical_peak,
                "mean_logical_blocks": round(
                    self.logical_sum / max(self.in_use_n, 1), 3),
                "blocks_grown_lazy": self.grown_lazy,
            }

        def sched_stats(self) -> dict:
            rw = sorted(self.retire_wave.values())
            aw = sorted(self.admit_wave.values())

            def mean(xs):
                return round(sum(xs) / len(xs), 3) if xs else None

            return {
                "policy": policy,
                "preempted": self.preempted,
                # wave-clock scheduling metrics (deterministic for
                # saturated schedules): admit wave = the wait the
                # admission policy imposed, turnaround = retire wave
                "mean_admit_wave": mean(aw),
                "mean_turnaround_waves": mean(rw),
                "p50_turnaround_waves": (rw[len(rw) // 2] if rw
                                         else None),
                "mean_live_requests": round(
                    self.live_sum / max(self.in_use_n, 1), 3),
                # per-request admit waves: aggregate means are
                # permutation-invariant at slots=1, so starvation (and
                # the aging bound repairing it) is only visible on the
                # individual request's wait
                "admit_wave_of": dict(self.admit_wave),
            }

    # -------------------------------------------------------- telemetry

    if reg.enabled:
        # handles resolved once (a per-wave gauge() call would pay a
        # lock + dict lookup three times per wave for nothing)
        _g_queue = reg.gauge("serve_queue_depth")
        _g_occ = reg.gauge("serve_slot_occupancy")
        _g_kv = reg.gauge("kv_blocks_in_use")
        _g_hit = reg.gauge("prefix_hit_blocks")
        _g_hitf = reg.gauge("prefix_hit_frac")
        _g_lazy = reg.gauge("blocks_grown_lazy")
        # tiered-KV gauges (host_spill): cumulative blocks spilled to
        # the host tier, swap-in latency spent, and the fraction of
        # prompt blocks the HOST tier served (hits the HBM cap alone
        # would have missed) — the dashboard triple the gke-tpu
        # runbook's sizing guidance reads
        _g_spill = reg.gauge("prefix_spilled_blocks")
        _g_swapms = reg.gauge("prefix_swapin_ms")
        _g_hosthitf = reg.gauge("prefix_host_hit_frac")
        # durable prefix CDN (shared_store): the disk tail's share of
        # prompt blocks and its swap-in latency — the restart-warmth
        # pair the gke-tpu prefix-CDN runbook reads alongside the
        # prefix_disk_quarantine_total/degraded_total counters the
        # store itself bills
        _g_diskhitf = reg.gauge("prefix_disk_hit_frac")
        _g_diskms = reg.gauge("prefix_disk_swapin_ms")
        # per-wave decode time: the paged-kernel lever's live signal
        # (the gather path scales with pool size, the kernel with live
        # tokens — watch this drop when paged_kernel engages). Honest
        # wall time whenever the wave ends in a readback (eos checks,
        # the spec multi-step); dispatch time on fully-async schedules.
        _g_paged = reg.gauge("paged_decode_ms")

    def _gauges(rstate: _Run, waiting: int, busy: int):
        if reg.enabled:
            _g_queue.set(waiting)
            _g_occ.set(busy / rstate.slots)
            _g_kv.set(rstate.alloc.in_use)
            if share_prefix:
                ps = rstate.prefix_stats
                _g_hit.set(ps["hit_blocks"])
                _g_hitf.set(round(ps["hit_blocks"]
                                  / max(ps["prompt_blocks"], 1), 4))
                if host_spill:
                    _g_spill.set(rstate.index.spilled_blocks)
                    _g_swapms.set(round(ps["swap_ms"], 3))
                    _g_hosthitf.set(round(ps["host_hit_blocks"]
                                          / max(ps["prompt_blocks"], 1),
                                          4))
                if shared_store is not None:
                    _g_spill.set(rstate.index.spilled_blocks)
                    _g_swapms.set(round(ps["swap_ms"], 3))
                    _g_diskhitf.set(round(ps["disk_hit_blocks"]
                                          / max(ps["prompt_blocks"], 1),
                                          4))
                    _g_diskms.set(round(ps["disk_swap_ms"], 3))
            if lazy_growth:
                _g_lazy.set(rstate.grown_lazy)

    def _prefetch_next(rstate, sched, prompts):
        """Between admission and dispatch: stage the NEXT queued
        request's spilled rows so the host→device swap overlaps this
        wave's decode (the double buffer of the tiered KV cache). A
        no-op unless the engine spills, the swap mode is async, and
        the next candidate's chain has a host tail; read-only against
        the scheduler (``candidate()`` is a peek) and the index."""
        if not host_spill or host_swap != "async":
            return
        if sched.exhausted():
            return
        req = sched.candidate()
        if req is not None:
            rstate.prefetch_swap(req, prompts[req])

    def _note_admit(meta, req, wait_s):
        # every telemetry timestamp below comes from the REGISTRY clock
        # (never mixed with time.monotonic durations): an injected
        # simulated clock must yield spans in its own domain, or the
        # merged Chrome-trace timeline garbles. The host-stats latency
        # list stays monotonic-based, separately.
        m = {"admit": time.monotonic(),
             "queue_wait_ms": round(wait_s * 1e3, 3), "prefill_ms": 0.0}
        if reg.enabled:
            m["admit_clk"] = reg.clock()
            reg.counter("serve_admissions").inc()
        meta[req] = m

    def _note_prefill(meta, req, start_clk, prompt_len, chunks=None):
        """``start_clk`` is ``reg.clock()`` captured before the
        admission dispatch (None when telemetry is disabled)."""
        if reg.enabled:
            t1 = reg.clock()
            if not getattr(run, "_join_noted", True):
                # join→first-token: run() entry to the END of the
                # run's FIRST admission dispatch — the cold-start
                # gauge the warm-vs-cold bench legs and the fleet's
                # ``warm_compile=`` span arg are read against (a
                # joiner's first run() starts right after bring-up)
                run._join_noted = True
                reg.gauge("join_first_token_ms").set(
                    round((t1 - run._join_clk0) * 1e3, 3))
            meta[req]["prefill_ms"] += round((t1 - start_clk) * 1e3, 3)
            args = {"prompt_len": prompt_len}
            if chunks is not None:
                args["chunks"] = chunks
            reg.emit_span("serve_prefill", start_clk, t1, **args)

    def _clk():
        return reg.clock() if reg.enabled else None

    def _note_retire(meta, latencies, req, ntok, decode_steps):
        """One ``serve_request`` span per retired request (admission →
        retirement: the request-latency record) + the token counter."""
        m = meta.pop(req, None)
        if m is None:
            return
        latencies.append((time.monotonic() - m["admit"]) * 1e3)
        if reg.enabled:
            t1 = reg.clock()
            t0 = m.get("admit_clk", t1)
            reg.emit_span("serve_request", t0, t1, request=req,
                          tokens=int(ntok),
                          queue_wait_ms=m["queue_wait_ms"],
                          prefill_ms=round(m["prefill_ms"], 3),
                          decode_steps=int(decode_steps))
            reg.histogram("serve_request_ms").record((t1 - t0) * 1e3)
            reg.counter("serve_generated_tokens").inc(int(ntok))

    # ------------------------------------------------------------- run

    def _admit_one(rstate: _Run, slot: int, req: int, prompt, key,
                   meta, wait_s):
        """Full (non-chunked) admission: one compiled dispatch. Under
        cross-request sharing only the UNSHARED suffix is forwarded —
        the shared span's prefill compute is skipped entirely. Returns
        ``(first_token, granted_entries)`` or None (blocks exhausted)."""
        from .decode import _select_prefill_impl

        length = int(prompt.shape[-1])
        got = rstate.admit_blocks(req, prompt, length)
        if got is None:
            return None
        row, tail, start, cov, entries = got
        suffix = prompt[cov:] if cov else prompt
        impl = ("cached" if (prefix is not None or cov) else
                _select_prefill_impl(cfg, length, "auto"))
        _note_admit(meta, req, wait_s)
        if key is None:
            key = jnp.zeros((2,), jnp.uint32)
        t0c = _clk()
        first, rstate.pool = _admit_full(
            prefill_params, suffix[None, :], impl, jnp.int32(slot), row,
            key, tail, jnp.int32(start), rstate.pool)
        rstate.register_prefix(req)
        _note_prefill(meta, req, t0c, length)
        return first, entries

    def _admit_imported(rstate: _Run, slot: int, req: int, prompt,
                        payload, meta, wait_s):
        """Admission from a prefill→decode HANDOFF payload (built by
        another engine's ``prefill_session``): allocate the full block
        grant like any admission, but instead of prefilling, IMPORT the
        payload's prefilled KV blocks into this pool
        (``paging.import_block_rows`` — the explicit cross-pool copy)
        and start decoding from the payload's first token at its
        position. No prefix sharing on either side of an import: the
        rows arrive as bytes, not as tokens this engine hashed.
        Returns ``(first_token, granted_entries)`` or None (blocks
        exhausted — the source keeps the payload for the retry)."""
        from .paging import import_block_rows

        if prefix is not None:
            raise ValueError(
                "imported admissions need an engine without a template "
                "prefix= — the payload's rows start at position 0")
        if sampler is not None:
            raise ValueError(
                "imported admissions are greedy-only: the handoff "
                "payload's first token was picked by the (greedy) "
                "prefill worker")
        length = int(prompt.shape[-1])
        if int(payload["n_tokens"]) != length:
            raise ValueError(
                f"handoff payload covers {payload['n_tokens']} tokens "
                f"for a {length}-token prompt — foreign payload?")
        got = rstate.admit_blocks(req, prompt, length, share=False)
        if got is None:
            return None
        row, tail, start, _cov, entries = got
        _note_admit(meta, req, wait_s)
        # table + pos first (pos = the payload's prefilled length), then
        # the block copy: ceil(length/bs) whole blocks, garbage tail
        # rows unreachable behind pos exactly as after a local prefill
        rstate.pool = _admit_table(jnp.int32(slot), row, tail,
                                   jnp.int32(length), rstate.pool)
        nb = blocks_for_rows(length, bs)
        rstate.pool = import_block_rows(
            rstate.pool, rstate.owned[req][:nb], payload["blocks"])
        return payload["first"], entries

    def _chunk_split(prompt, length: int, start: int | None = None):
        """Pad-to-C chunking shared by the sync (spec) and interleaved
        (plain) admission paths: the chunk list, the true last token's
        offset within the final chunk, and the post-rewind position —
        ONE definition of the finish arithmetic, so the two paths can
        never disagree on which logit picks the first token. ``prompt``
        is the tokens actually prefilled (the unshared suffix under
        cross-request sharing) and ``start`` their first position."""
        start = prefix_len if start is None else start
        c = prefill_chunk
        nc = _check_chunk_bound(length, start)
        padded = jnp.zeros((nc * c,), jnp.int32).at[:length].set(prompt)
        chunks = [padded[i * c:(i + 1) * c][None] for i in range(nc)]
        return (chunks, jnp.int32(length - 1 - (nc - 1) * c),
                jnp.int32(start + length))

    def _admit_chunked_sync(rstate: _Run, slot: int, req: int, prompt,
                            key, meta, wait_s):
        """Chunked admission WITHOUT interleaving, as ONE compiled
        dispatch (``_chunk_sweep``): keeps chunked admission's memory
        ceiling (``[C, S_max]`` scores) and one-compile-per-engine
        property without paying a host dispatch per chunk. Spec-loop
        only. Under cross-request sharing only the UNSHARED suffix is
        chunked and swept — the shared span's blocks are mapped
        read-only and its prefill compute skipped, exactly like the
        interleaved path."""
        length = int(prompt.shape[-1])
        got = rstate.admit_blocks(req, prompt, length)
        if got is None:
            return None
        row, tail, start, cov, entries = got
        _note_admit(meta, req, wait_s)
        t0c = _clk()
        rstate.pool = _admit_table(jnp.int32(slot), row, tail,
                                   jnp.int32(start), rstate.pool)
        suffix = prompt[cov:] if cov else prompt
        chunks, last_idx, true_pos = _chunk_split(suffix, length - cov,
                                                  start)
        c = prefill_chunk
        # ONE [1, MC, C] buffer per admission (static shape → one
        # compile per engine); trailing dead chunks never execute
        mc = max(1, (max_len - prefix_len) // c)
        buf = jnp.zeros((1, mc, c), jnp.int32)
        buf = buf.at[0, :len(chunks)].set(
            jnp.concatenate(chunks, axis=0))
        if key is None:
            key = jnp.zeros((2,), jnp.uint32)
        first, rstate.pool = _chunk_sweep(
            prefill_params, buf, jnp.int32(len(chunks)), last_idx,
            rstate.pool, jnp.int32(slot), key, true_pos)
        rstate.register_prefix(req)
        _note_prefill(meta, req, t0c, length, chunks=len(chunks))
        return first, entries

    def run_spec(prompts, n_new_of, slots, rules, eos_id, arrivals,
                 kv_blocks, priorities):
        """Speculative schedule: same admission/retire bookkeeping as
        the plain loop, but outputs live in a device-side context
        buffer (the draft source) and each step can emit up to
        ``spec_k + 1`` tokens per slot. The host syncs once per
        RETIREMENT WAVE, not per step: the compiled multi-step loops
        on device until enough slots finish (one, when requests are
        queued and a slot should recycle promptly; all active, when
        the queue is empty and nothing is waiting to admit) — or, under
        ``lazy_growth``, until every unfinished slot hits its granted-
        rows boundary, at which point the host grants ``spec_k + 1``
        more rows of blocks per blocked slot and re-enters. A grant
        the pool cannot cover STALLS the slot (state frozen on device
        exactly like a finished slot's); all-stalled preempts the
        YOUNGEST back to the queue, mirroring the plain loop."""
        rstate = _Run(slots, rules, kv_blocks, spec_k, n_new_of, prompts)
        spec_step = step_for("spec", cache_dtype != "int8"
                             or rules is None, rules)
        # + k + 1 slack: the verification window is sliced at cur even
        # when a request is one token from done
        ctxbuf = jnp.zeros((slots, max_len + spec_k + 1), jnp.int32)
        cur = jnp.zeros((slots,), jnp.int32)
        n_out = jnp.zeros((slots,), jnp.int32)
        sched = _Sched(prompts, n_new_of, policy, aging, priorities,
                       arrivals, time.monotonic())
        active: dict[int, int] = {}
        start_of: dict[int, int] = {}            # req → first output idx
        out: dict[int, Any] = {}
        meta: dict[int, dict] = {}
        latencies: list[float] = []
        req_steps: dict[int, int] = {}           # req → its slot-steps
        # lazy-growth state (all no-ops when the lever is off): granted
        # table entries per slot, host mirror of each slot's device pos
        # (the growth target is pos + k + 1), stalled slots, admission
        # order (preemption takes the youngest)
        granted: dict[int, int] = {}
        pos_h_of: dict[int, int] = {}
        stalled: dict[int, int] = {}             # slot → req
        admit_seq: dict[int, int] = {}
        admit_counter = [0]
        full_rows = jnp.full((slots,), nt * bs, jnp.int32)
        slot_steps = 0
        host_waves = 0                 # retirement waves (host syncs)
        generated = 0
        admitted = 0                   # prefill-emitted (non-step) tokens
        eos_dev = jnp.int32(-1 if eos_id is None else eos_id)

        def grow_to(slot: int, req: int, target_rows: int) -> bool:
            """Grant blocks until the slot's table covers
            ``target_rows`` (False: pool dry — the caller stalls)."""
            while granted[slot] * bs < target_rows:
                b_ = rstate.grow_block(req)
                if b_ is None:
                    return False
                rstate.pool = _grow_table(
                    jnp.int32(slot), jnp.int32(granted[slot]),
                    jnp.int32(b_), rstate.pool)
                granted[slot] += 1
            return True

        while len(sched) or active or stalled:
            if lazy_growth and stalled:
                # resume stalled slots BEFORE admission — freed blocks
                # must reach the oldest stalled request first (the
                # plain loop's livelock-breaking order)
                for slot in list(stalled):
                    req = stalled[slot]
                    if grow_to(slot, req, pos_h_of[slot] + spec_k + 1):
                        active[slot] = req
                        del stalled[slot]
            for slot in range(slots):
                if slot in active or slot in stalled or not len(sched):
                    continue
                req = sched.candidate()
                if req is None:
                    break                        # nothing arrived yet
                prompt = jnp.asarray(prompts[req])
                wait_s = sched.wait_s(req)
                admit = (_admit_chunked_sync if prefill_chunk is not None
                         else _admit_one)
                got = admit(rstate, slot, req, prompt, None,
                            meta, wait_s)
                if got is None:
                    break                        # blocks exhausted: hold
                first, entries = got
                sched.pop(req)
                rstate.admit_wave[req] = host_waves
                admit_seq[req] = admit_counter[0]
                admit_counter[0] += 1
                length = int(prompt.shape[-1])
                start_of[req] = prefix_len + length
                granted[slot] = entries
                pos_h_of[slot] = prefix_len + length
                ctxbuf, cur, n_out = _spec_admit_row(
                    prompt, first, jnp.int32(slot), ctxbuf, cur, n_out)
                generated += 1
                admitted += 1
                # the prefill token may already satisfy the request
                if n_new_of[req] == 1 or (eos_id is not None
                                          and int(first) == eos_id):
                    out[req] = first[None]
                    rstate.retire_wave[req] = host_waves
                    rstate.retire_blocks(req)
                    _note_retire(meta, latencies, req, 1, 0)
                    continue
                active[slot] = req
            waiting = sched.waiting()
            sched.tick()
            rstate.sample(live=len(active) + len(stalled))
            _gauges(rstate, waiting, len(active) + len(stalled))
            _prefetch_next(rstate, sched, prompts)
            if not active:
                if lazy_growth and stalled:
                    # every live request is stalled on block growth:
                    # preempt the YOUNGEST back to the queue (its
                    # blocks free; greedy tokens regenerate
                    # identically on re-admission)
                    slot = max(stalled, key=lambda s: admit_seq[stalled[s]])
                    req = stalled.pop(slot)
                    rstate.preempted += 1
                    rstate.retire_blocks(req)    # frees; index retains
                    sched.requeue(req)
                    meta.pop(req, None)
                    start_of.pop(req, None)
                    granted.pop(slot, None)
                    # step accounting restarts with the re-admission —
                    # the retirement span's decode_steps must describe
                    # the run that produced the output, matching the
                    # plain loop's count/span reset on preemption
                    req_steps.pop(req, None)
                    continue
                if len(sched) and sched.candidate() is None:
                    # nothing admissible until the blocking request
                    # arrives (fifo: the head; else: the earliest) —
                    # blocks exhausted with nothing active cannot
                    # happen; capacity for the largest single request
                    # is validated up front
                    sched.idle_wait()
                continue
            active_mask = jnp.asarray(
                [s in active for s in range(slots)])
            n_new_dev = jnp.asarray(
                [n_new_of[active[s]] if s in active else 0
                 for s in range(slots)], jnp.int32)
            granted_rows = (jnp.asarray(
                [granted.get(s, 0) * bs for s in range(slots)],
                jnp.int32) if lazy_growth else full_rows)
            # wave size follows the admission backlog: with a deep queue
            # the next admissions arrive as a batch anyway, so drain as
            # many slots as there are requests waiting (one sync per
            # admission WAVE); a single queued request still gets the
            # first free slot (stop=1), and an empty queue runs every
            # active slot to completion — nothing is waiting to admit
            stop = (min(len(active), max(1, waiting))
                    if len(sched) else len(active))
            tw0 = time.monotonic() if reg.enabled else 0.0
            (ctxbuf, cur, n_out, fin, steps_inc, need_grow,
             rstate.pool) = spec_step(
                ctxbuf, cur, n_out, n_new_dev, eos_dev,
                active_mask, jnp.int32(stop), granted_rows, rstate.pool)
            # one batched transfer: separate device_gets would pay the
            # host round trip repeatedly in the per-wave hot loop
            # graftlint: ignore[graft-host-sync-in-loop] — wave boundary
            fin_h, n_out_h, steps_h, need_h, pos_h = jax.device_get(
                (fin, n_out, steps_inc, need_grow, rstate.pool["pos"]))
            if reg.enabled:
                # the spec "wave" is the whole device-resident multi-
                # step; the readback above syncs it, so this is honest
                # wall time, not dispatch time
                _g_paged.set(round((time.monotonic() - tw0) * 1e3, 3))
            slot_steps += int(steps_h.sum())
            host_waves += 1
            # per-slot step counts attribute to the request holding the
            # slot — each retirement's decode_steps is ITS verification
            # steps, not the engine-wide counter
            for slot, req in active.items():
                req_steps[req] = req_steps.get(req, 0) + int(steps_h[slot])
                pos_h_of[slot] = int(pos_h[slot])
            for slot, req in list(active.items()):
                if bool(fin_h[slot]):
                    n = int(n_out_h[slot])
                    start = start_of[req]
                    out[req] = ctxbuf[slot, start:start + n]
                    generated += n - 1           # first counted at admit
                    rstate.retire_wave[req] = host_waves
                    rstate.retire_blocks(req)
                    _note_retire(meta, latencies, req, n,
                                 req_steps.get(req, 0))
                    del active[slot]
            if lazy_growth:
                # growth AFTER retirements: a slot at its boundary must
                # see the blocks this very wave's finishers freed
                for slot, req in list(active.items()):
                    if bool(need_h[slot]) and not grow_to(
                            slot, req, pos_h_of[slot] + spec_k + 1):
                        # pool dry: stall until a retirement frees
                        # blocks (state frozen on device meanwhile)
                        stalled[slot] = req
                        del active[slot]
        rstate.close()
        _gauges(rstate, 0, 0)
        if reg.enabled:
            # each verification slot-step emits exactly one model token
            # plus its accepted drafts, so the drafts the speculation
            # actually bought are the step-emitted tokens beyond one per
            # step — the counter the spec_k knob is tuned against
            reg.counter("serve_accepted_draft_tokens").inc(
                max(0, (generated - admitted) - slot_steps))
            reg.counter("serve_verify_slot_steps").inc(slot_steps)
        # accepted_per_step excludes admission tokens: it is tokens per
        # VERIFICATION slot-step, so zero draft acceptance reads exactly
        # 1.0 (the plain engine's rate), never above it
        # waves = host retirement waves (the sync count), matching the
        # plain loop's semantics; verification work is slot_steps
        run.last_stats = _stats(len(prompts), generated, host_waves,
                                latencies, rstate)
        run.last_stats.update({
            "slot_steps": slot_steps,
            "accepted_per_step": (round((generated - admitted)
                                        / slot_steps, 3)
                                  if slot_steps else None),
        })
        return [out[i] for i in range(len(prompts))]

    def _stats(n_req, generated, waves, latencies, rstate):
        lat = sorted(latencies)

        def q(p):
            return (round(lat[min(len(lat) - 1,
                                  int(p * len(lat)))], 3)
                    if lat else None)

        ps = rstate.prefix_stats
        idx, host = rstate.index, rstate.host
        return {
            "requests": n_req,
            "generated": generated,
            "waves": waves,
            "latency_ms": {"p50": q(0.5), "p99": q(0.99),
                           "max": round(lat[-1], 3) if lat else None},
            "kv": rstate.kv_stats(),
            "sched": rstate.sched_stats(),
            "prefix": {
                "enabled": share_prefix,
                "hit_blocks": ps["hit_blocks"],
                "prompt_blocks": ps["prompt_blocks"],
                "hit_frac": round(ps["hit_blocks"]
                                  / max(ps["prompt_blocks"], 1), 4),
                "tokens_saved": ps["tokens_saved"],
                "lookups": ps["lookups"],
                # why fruitless reclaims came back empty-handed (the
                # 0-return disambiguation the spill tier needs):
                # "live" = retained chains exist but every one is
                # table-referenced, "empty" = nothing device-resident
                # retained at all
                "reclaim_blocked": {
                    "live": ps["reclaim_blocked_live"],
                    "empty": ps["reclaim_blocked_empty"],
                },
                # the tiered-KV split: spill traffic, host-tier hits
                # (blocks the HBM cap alone would have re-prefilled),
                # swap-in latency/volume, and the classified drops
                "spill": {
                    "enabled": host is not None,
                    "host_blocks": (host.host_blocks
                                    if host is not None else 0),
                    "spilled_blocks": (idx.spilled_blocks
                                       if idx is not None else 0),
                    "spill_dropped": (idx.spill_dropped
                                      if idx is not None else 0),
                    "host_hit_blocks": ps["host_hit_blocks"],
                    "host_hit_frac": round(
                        ps["host_hit_blocks"]
                        / max(ps["prompt_blocks"], 1), 4),
                    "swapins": ps["swapins"],
                    "swapped_blocks": ps["swapped_blocks"],
                    "swap_ms": round(ps["swap_ms"], 3),
                    "swap_tokens_saved": ps["swap_tokens_saved"],
                    "corrupt_dropped": ps["corrupt_dropped"],
                    "host_in_use": (host.in_use
                                    if host is not None else 0),
                    "host_high_water": (host.high_water
                                        if host is not None else 0),
                },
                # durable prefix CDN (shared_store): blocks served
                # from the fleet-shared store, the disk tail's share,
                # and the shared store's own ledger (nested "disk"
                # record carries quarantine reasons + degraded count)
                "cdn": {
                    "enabled": rstate.store is not None,
                    "hit_blocks": ps["cdn_hit_blocks"],
                    "disk_hit_blocks": ps["disk_hit_blocks"],
                    "disk_hit_frac": round(
                        ps["disk_hit_blocks"]
                        / max(ps["prompt_blocks"], 1), 4),
                    "disk_swap_ms": round(ps["disk_swap_ms"], 3),
                    "store": (rstate.store.stats()
                              if rstate.store is not None else None),
                },
                # elastic-fleet state migration (zeros outside a
                # scale event): bring-up chains seeded from the warm
                # store vs dropped, and retained chains published to
                # the drain sink at close
                "warm": {
                    "seeded_chains": ps["warm_chains"],
                    "seeded_blocks": ps["warm_blocks"],
                    "seed_dropped": ps["warm_dropped"],
                    "published_chains": ps["published_chains"],
                },
            },
        }

    def run(prompts: Sequence[Any], n_new, *, slots: int = 4,
            rules: ShardingRules | None = None,
            eos_id: int | None = None, rng=None,
            eos_check_every: int = 1, arrivals=None,
            kv_blocks: int | None = None,
            static_batching: bool = False,
            priorities=None, admission=None):
        # reset on entry: a failed run must not leave a prior run's
        # stats for an error-catching caller to misattribute
        run.last_stats = None
        # join→first-token clock: armed here, fired by the run's first
        # _note_prefill (telemetry only — None keeps the hook dead)
        run._join_clk0 = reg.clock() if reg.enabled else None
        run._join_noted = not reg.enabled
        if admission is not None:
            # an injected AdmissionSource OWNS order, timing and the
            # kv-import decision — the knobs that overlap it must be
            # absent, not silently ignored
            if arrivals is not None:
                raise ValueError(
                    "admission= owns arrival gating — drop arrivals")
            if static_batching:
                raise ValueError(
                    "admission= replaces the engine's scheduler; "
                    "static_batching configures the built-in one")
            if priorities is not None:
                raise ValueError(
                    "admission= replaces the engine's policy order; "
                    "priorities configure the built-in one")
            if spec_k is not None:
                raise ValueError(
                    "external admission drives the plain wave loop "
                    "only — drop spec_k")
        if not prompts:
            # same stats schema as every other path — a caller reading
            # last_stats["kv"]["utilisation"] after any run must never
            # KeyError on the degenerate schedule
            run.last_stats = {
                "requests": 0, "generated": 0, "waves": 0,
                "latency_ms": {"p50": None, "p99": None, "max": None},
                "kv": {"num_blocks": 0, "reserved": 0, "in_use": 0,
                       "free": 0, "high_water": 0, "refs_total": 0,
                       "block_size": bs,
                       "peak_rows": 0, "dense_rows": 0,
                       "utilisation": 0.0, "mean_utilisation": 0.0,
                       "kv_blocks_physical": 0, "kv_blocks_logical": 0,
                       "mean_logical_blocks": 0.0,
                       "blocks_grown_lazy": 0},
                "sched": {"policy": policy, "preempted": 0,
                          "mean_admit_wave": None,
                          "mean_turnaround_waves": None,
                          "p50_turnaround_waves": None,
                          "mean_live_requests": 0.0,
                          "admit_wave_of": {}},
                "prefix": {"enabled": share_prefix, "hit_blocks": 0,
                           "prompt_blocks": 0, "hit_frac": 0.0,
                           "tokens_saved": 0, "lookups": 0,
                           "reclaim_blocked": {"live": 0, "empty": 0},
                           "spill": {"enabled": host_spill,
                                     "host_blocks": (host_blocks
                                                     if host_spill
                                                     else 0),
                                     "spilled_blocks": 0,
                                     "spill_dropped": 0,
                                     "host_hit_blocks": 0,
                                     "host_hit_frac": 0.0,
                                     "swapins": 0, "swapped_blocks": 0,
                                     "swap_ms": 0.0,
                                     "swap_tokens_saved": 0,
                                     "corrupt_dropped": 0,
                                     "host_in_use": 0,
                                     "host_high_water": 0},
                           "cdn": {"enabled": shared_store is not None,
                                   "hit_blocks": 0,
                                   "disk_hit_blocks": 0,
                                   "disk_hit_frac": 0.0,
                                   "disk_swap_ms": 0.0,
                                   "store": (shared_store.stats()
                                             if shared_store is not None
                                             else None)},
                           "warm": {"seeded_chains": 0,
                                    "seeded_blocks": 0,
                                    "seed_dropped": 0,
                                    "published_chains": 0}},
            }
            return {} if admission is not None else []
        if eos_check_every < 1:
            raise ValueError(
                f"eos_check_every must be >= 1, got {eos_check_every}")
        if spec_k is not None and eos_check_every != 1:
            # the speculative loop already batches retirement readbacks
            # per wave on device; silently dropping the knob would let a
            # caller believe batching was applied where it is built in
            raise ValueError(
                "eos_check_every applies to the plain engine only — the "
                "speculative loop checks eos on device and reads back "
                "once per retirement wave already")
        if sampler is not None and rng is None:
            raise ValueError("a sampled engine needs rng (a PRNG key)")
        if isinstance(n_new, int):
            n_new_of = [n_new] * len(prompts)
        else:
            n_new_of = [int(n) for n in n_new]
            if len(n_new_of) != len(prompts):
                raise ValueError(
                    f"per-request n_new has {len(n_new_of)} entries for "
                    f"{len(prompts)} prompts")
        for n in n_new_of:
            if n < 1:
                raise ValueError(f"n_new must be >= 1, got {n}")
        if arrivals is not None:
            arrivals = [float(a) for a in arrivals]
            if len(arrivals) != len(prompts):
                raise ValueError(
                    f"arrivals has {len(arrivals)} entries for "
                    f"{len(prompts)} prompts")
        if priorities is not None:
            if policy != "priority":
                raise ValueError(
                    f"priorities only apply to policy='priority' "
                    f"(engine built with {policy!r})")
            priorities = [float(p_) for p_ in priorities]
            if len(priorities) != len(prompts):
                raise ValueError(
                    f"priorities has {len(priorities)} entries for "
                    f"{len(prompts)} prompts")
        elif policy == "priority":
            # no lane supplied: every request equal — arrival order
            # under the aging bound
            priorities = [0.0] * len(prompts)
        if lazy_growth and eos_check_every != 1:
            raise ValueError(
                "lazy_growth needs per-wave retirement accounting "
                "(eos_check_every=1): the lagged scan's wave→token "
                "mapping assumes uninterrupted slot tenancy, which a "
                "growth stall breaks")

        def key_for(req: int, idx: int):
            # keyed to (request, position) via the one shared contract:
            # the schedule — slot count, admission order, neighbours —
            # can never change a token
            return _request_key(rng, req, idx)
        headroom = 0 if spec_k is None else spec_k
        for i, p in enumerate(prompts):
            if int(p.shape[-1]) < 1:
                # a zero-length prompt has no last token to continue
                # from — refuse loudly
                raise ValueError("prompts must have at least one token")
            if prefix_len + int(p.shape[-1]) + n_new_of[i] + headroom \
                    > max_len:
                raise ValueError(
                    f"prefix ({prefix_len}) + prompt "
                    f"({int(p.shape[-1])}) + n_new ({n_new_of[i]})"
                    + (f" + spec_k ({spec_k}) verification headroom"
                       if headroom else "")
                    + f" exceeds max_len ({max_len})")
            if prefill_chunk is not None:
                # every prompt must fit PADDED, checked before any work:
                # an admission-time refusal mid-schedule would discard
                # already-finished requests' outputs
                _check_chunk_bound(int(p.shape[-1]))
        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        if rules is not None:
            data_shards = 1
            for a in rules.data:
                data_shards *= rules.mesh.shape.get(a, 1)
            if slots % data_shards:
                # the wave batch IS the data-parallel dim at serve time
                raise ValueError(
                    f"slots ({slots}) must divide over the data axes "
                    f"({data_shards} shards) — pad the pool")
        if static_batching and spec_k is not None:
            raise ValueError(
                "static_batching is the plain loop's run-to-completion "
                "A/B baseline — drop spec_k to use it")
        if spec_k is not None:
            return run_spec(prompts, n_new_of, slots, rules, eos_id,
                            arrivals, kv_blocks, priorities)

        # the pallas int8-pool attention only when the pool is
        # UNSHARDED; a mesh pool keeps the jnp path (see make_serve_step)
        step = step_for("plain", cache_dtype != "int8" or rules is None,
                        rules)
        rstate = _Run(slots, rules, kv_blocks, 0, n_new_of, prompts)
        tokens = jnp.zeros((slots,), jnp.int32)
        sched = (admission if admission is not None
                 else _Sched(prompts, n_new_of, policy, aging,
                             priorities, arrivals, time.monotonic()))
        if admission is not None:
            # elastic-fleet seams (both optional, getattr so a minimal
            # AdmissionSource implementation stays valid): warm
            # bring-up chains seed the host tier BEFORE any admission,
            # and the drain sink receives retained chains at close
            warm = getattr(sched, "warm_chains", lambda: None)()
            if warm:
                rstate.seed_warm(warm)
        lens_of = [int(jnp.asarray(p).shape[-1]) for p in prompts]
        active: dict[int, int] = {}              # slot → request index
        firsts: dict[int, Any] = {}              # req → prefill token
        span: dict[int, tuple] = {}              # req → (slot, start wave)
        count: dict[int, int] = {}               # req → tokens so far
        done_at: dict[int, int] = {}             # req → final token count
        meta: dict[int, dict] = {}
        latencies: list[float] = []
        # chunked-prefill interleaving state: slot → in-flight admission
        filling: dict[int, dict] = {}
        # lazy-growth state: granted table entries per slot; stalled
        # slots (growth found the pool empty) with their saved token;
        # fragmented requests' per-wave indices (a stall breaks the
        # contiguous hist span the fast assembly path slices)
        granted: dict[int, int] = {}
        stalled: dict[int, tuple] = {}           # slot → (req, token)
        frag: dict[int, list] = {}               # req → active wave idxs
        admit_seq: dict[int, int] = {}           # req → admission order
        admit_counter = [0]                      # monotone: re-admission
        #                                          must read as YOUNGER
        mask_key: list = [None, None]    # active-set key → device mask
        hist: list = []          # one [slots] token vector per step wave

        def retire(req, ntok, steps):
            done_at[req] = ntok
            rstate.retire_wave[req] = len(hist)
            rstate.retire_blocks(req)
            _note_retire(meta, latencies, req, ntok, steps)
            sched.retired(req, ntok)

        def activate(slot, req, first, entries):
            """First-token bookkeeping shared by both admission paths."""
            nonlocal tokens
            tokens = tokens.at[slot].set(first)
            firsts[req] = first
            span[req] = (slot, len(hist))
            count[req] = 1
            granted[slot] = entries
            rstate.admit_wave[req] = len(hist)
            admit_seq[req] = admit_counter[0]
            admit_counter[0] += 1
            # a request the prefill token already satisfied must retire
            # BEFORE any step, or it collects an extra token
            if n_new_of[req] == 1 or (eos_id is not None
                                      and eos_check_every == 1
                                      and int(first) == eos_id):
                retire(req, 1, 0)
                return
            active[slot] = req

        def mark_frag(req):
            """Convert a request to fragmented assembly: its step waves
            so far are the contiguous span from admission."""
            if req not in frag:
                sw = span[req][1]
                frag[req] = list(range(sw, sw + count[req] - 1))

        def try_grow(slot, req) -> bool:
            """Ensure the slot's next write position has a granted
            block; grow by one when it crosses. False = pool empty."""
            nxt = prefix_len + lens_of[req] + count[req] - 1
            if nxt // bs < granted[slot]:
                return True
            b = rstate.grow_block(req)
            if b is None:
                return False
            rstate.pool = _grow_table(
                jnp.int32(slot), jnp.int32(granted[slot]), jnp.int32(b),
                rstate.pool)
            granted[slot] += 1
            return True

        # Host bookkeeping is integer-only: the loop keeps whole [slots]
        # token vectors per wave and assembles outputs AFTER the
        # schedule in O(requests) device ops. Without eos_id the
        # schedule is fully async end to end; eos makes lengths variable
        # and costs a readback — by default ONE [slots] vector per wave,
        # but a readback that must wait on freshly dispatched work pays
        # the backend's full pipeline-flush RTT (~65 ms through the
        # tunnelled chip vs ~0.02 ms for a resident value), so
        # ``eos_check_every=W`` batches the check: one [W, slots]
        # readback per W waves. Retirement then LAGS an eos by up to W-1
        # waves (the slot computes ignored tokens before recycling —
        # bubble, never wrongness: outputs are truncated at the first
        # eos either way), trading a bounded bubble for 1/W of the
        # flushes. The first-token eos check rides the same schedule:
        # eager (one host int per admission) at W=1, caught by the
        # periodic scan/assembly truncation at W>1.
        eos_pending = 0                  # waves since the last eos scan
        while not sched.exhausted() or active or filling or stalled:
            if lazy_growth and stalled:
                # resume stalled slots BEFORE admission: freed blocks
                # must reach the oldest stalled request first, or a
                # preempted request's re-admission could re-grab them
                # every cycle and starve the stalled one forever (the
                # livelock the preemption exists to break). Restores
                # each slot's last real token — the step overwrites
                # every row, active or not.
                for slot in list(stalled):
                    req, tok = stalled[slot]
                    if try_grow(slot, req):
                        tokens = tokens.at[slot].set(tok)
                        active[slot] = req
                        del stalled[slot]
            # admission: every free slot takes the POLICY's next ARRIVED
            # request whose block grant fits; the candidate blocks
            # (fairness over utilisation; document, don't starve — and
            # the aging bound keeps non-fifo policies starvation-free).
            # ``static_batching`` is the RUN-TO-COMPLETION A/B baseline
            # (bench.py section_serve_engine): admission only when the
            # engine is fully idle, so early finishers idle until the
            # whole resident batch drains — identical compiled steps
            # and dispatch pattern, different SCHEDULER, which is
            # exactly the variable the comparison isolates
            admit_ok = not static_batching or (not active and not filling
                                               and not stalled)
            # the drain hook: an injected source whose owner is removing
            # this replica stops NEW admissions here while the active
            # slots below keep stepping to retirement (nothing is
            # cancelled mid-decode — drain never recomputes)
            admit_ok = admit_ok and not sched.draining()
            for slot in range(slots):
                if not admit_ok or slot in active or slot in filling \
                        or slot in stalled:
                    continue
                req = sched.candidate()
                if req is None:
                    break               # empty, or nothing arrived yet
                prompt = jnp.asarray(prompts[req])
                key = key_for(req, 0) if sampler is not None else None
                wait_s = sched.wait_s(req)
                payload = sched.kv_import(req)
                if payload is not None:
                    # prefill→decode handoff: another engine prefilled
                    # this request's KV; allocate blocks, import the
                    # rows, start decoding at the payload's position —
                    # zero prefill compute here (models/fleet.py's
                    # disaggregated mode)
                    got = _admit_imported(rstate, slot, req, prompt,
                                          payload, meta, wait_s)
                    if got is None:
                        break                    # blocks exhausted: hold
                    first, entries = got
                    sched.pop(req)
                    activate(slot, req, first, entries)
                elif prefill_chunk is None:
                    got = _admit_one(rstate, slot, req, prompt, key,
                                     meta, wait_s)
                    if got is None:
                        break                    # blocks exhausted: hold
                    first, entries = got
                    sched.pop(req)
                    activate(slot, req, first, entries)
                else:
                    length = int(prompt.shape[-1])
                    got = rstate.admit_blocks(req, prompt, length)
                    if got is None:
                        break
                    row, tail, start, cov, entries = got
                    sched.pop(req)
                    _note_admit(meta, req, wait_s)
                    rstate.pool = _admit_table(jnp.int32(slot), row,
                                               tail, jnp.int32(start),
                                               rstate.pool)
                    suffix = prompt[cov:] if cov else prompt
                    chunks, last_idx, true_pos = _chunk_split(
                        suffix, length - cov, start)
                    filling[slot] = {
                        "req": req, "key": key, "len": length,
                        "chunks": chunks, "last_idx": last_idx,
                        "true_pos": true_pos, "entries": entries,
                        # span start: the prefill span of an INTERLEAVED
                        # admission covers the decode waves riding
                        # between its chunks (the host's honest view)
                        "next": 0, "clk0": _clk(),
                    }
            # chunked-prefill/decode interleaving: ONE chunk per filling
            # slot per wave — active slots keep decoding in between, so
            # a long prompt's admission no longer stalls the batch
            for slot in list(filling):
                f = filling[slot]
                logits_c, rstate.pool = _chunk_step(
                    prefill_params, f["chunks"][f["next"]],
                    jnp.int32(slot), rstate.pool)
                f["next"] += 1
                if f["next"] == len(f["chunks"]):
                    key = f["key"]
                    if key is None:
                        key = jnp.zeros((2,), jnp.uint32)
                    first, rstate.pool = _chunk_finish(
                        logits_c, f["last_idx"], key, jnp.int32(slot),
                        rstate.pool, f["true_pos"])
                    req = f["req"]
                    del filling[slot]
                    _note_prefill(meta, req, f["clk0"], f["len"],
                                  chunks=f["next"])
                    rstate.register_prefix(req)
                    activate(slot, req, first, f["entries"])
            if lazy_growth:
                # grow any active slot whose next write crosses into an
                # ungranted table entry, stalling it when the pool is
                # dry (writes fenced, position frozen, token saved: a
                # bounded bubble, never different output)
                for slot, req in list(active.items()):
                    if not try_grow(slot, req):
                        mark_frag(req)
                        stalled[slot] = (req, tokens[slot])
                        del active[slot]
            waiting = sched.waiting()
            sched.tick()
            busy = len(active) + len(filling) + len(stalled)
            rstate.sample(live=busy)
            _gauges(rstate, waiting, busy)
            _prefetch_next(rstate, sched, prompts)
            if not active:
                if stalled and not filling:
                    # every live request is stalled on block growth and
                    # nothing else can free capacity: preempt the
                    # YOUNGEST back to the queue (its blocks free, its
                    # tokens regenerate identically on re-admission —
                    # greedy and (request, position)-keyed sampling are
                    # both schedule-invariant)
                    slot = max(stalled,
                               key=lambda s: admit_seq[stalled[s][0]])
                    req, _tok = stalled.pop(slot)
                    rstate.preempted += 1
                    rstate.retire_blocks(req)    # frees; index retains
                    sched.requeue(req)
                    del count[req], span[req]
                    firsts.pop(req, None)
                    frag.pop(req, None)
                    meta.pop(req, None)
                    granted.pop(slot, None)
                    continue
                if not filling and not sched.exhausted() \
                        and sched.candidate() is None:
                    # nothing admissible until the blocking request
                    # arrives (fifo: the head; else: the earliest) —
                    # or, under an injected source, until the router
                    # adds/steals work or closes the stream
                    sched.idle_wait()
                continue
            # one compiled step advances every slot (idle slots compute
            # too — the static-shape bubble; their writes are fenced to
            # the garbage block and their tokens are never read). The
            # mask array is rebuilt only when membership changes —
            # re-shipping an identical h2d constant every wave of a
            # long fixed-budget stretch buys nothing
            key_ = tuple(sorted(active))
            if key_ != mask_key[0]:
                mask_key[0] = key_
                mask_key[1] = jnp.asarray(
                    [s in active for s in range(slots)])
            active_mask = mask_key[1]
            tw0 = time.monotonic() if reg.enabled else 0.0
            if sampler is None:
                tokens, rstate.pool = step(tokens, active_mask,
                                           rstate.pool)
            else:
                # idle slots get a dead (request-id == len(prompts)) key
                # — valid to derive, never read
                reqs = jnp.asarray(
                    [active.get(s, len(prompts)) for s in range(slots)],
                    jnp.int32)
                poss = jnp.asarray(
                    [count[active[s]] if s in active else 0
                     for s in range(slots)], jnp.int32)
                tokens, rstate.pool = step(tokens, active_mask, reqs,
                                           poss, rng, rstate.pool)
            hist.append(tokens)
            for slot, req in active.items():
                if req in frag:                  # stalled-ever requests
                    frag[req].append(len(hist) - 1)
            for slot, req in list(active.items()):
                count[req] += 1
                if count[req] >= n_new_of[req]:
                    retire(req, count[req], count[req] - 1)
                    del active[slot]             # slot recycles next wave
            if eos_id is not None:
                eos_pending += 1
                if eos_check_every == 1:
                    # exact per-wave eos retirement is this mode's contract
                    # graftlint: ignore[graft-host-sync-in-loop] — exact eos
                    tok_h = jax.device_get(hist[-1])
                    eos_pending = 0
                    for slot, req in list(active.items()):
                        if int(tok_h[slot]) == eos_id:
                            retire(req, count[req], count[req] - 1)
                            del active[slot]
                elif eos_pending >= eos_check_every:
                    # one flush per W waves: scan the batched window for
                    # each active request's FIRST eos (only rows since
                    # its admission belong to it) — done_at stays exact,
                    # only the retirement is late
                    # one flush per W waves is the amortised sync this
                    # batching exists to provide
                    # graftlint: ignore[graft-host-sync-in-loop] — amortised
                    block = jax.device_get(
                        jnp.stack(hist[-eos_pending:]))   # [W, slots]
                    base = len(hist) - eos_pending
                    eos_pending = 0
                    for slot, req in list(active.items()):
                        sw = span[req][1]
                        for j in range(block.shape[0]):
                            h = base + j
                            if h >= sw and int(block[j, slot]) == eos_id:
                                retire(req, h - sw + 2, h - sw + 1)
                                del active[slot]
                                break
            if reg.enabled:
                # see the handle comment: wall time when the wave ended
                # in an eos readback, dispatch time otherwise
                _g_paged.set(round((time.monotonic() - tw0) * 1e3, 3))
        sink = (getattr(sched, "chain_sink", lambda: None)()
                if admission is not None else None)
        rstate.close(sink=sink)
        _gauges(rstate, 0, 0)

        waves = jnp.stack(hist) if hist else None      # [W, slots]
        # with an injected admission source only the requests IT
        # yielded were served — assemble those, return a dict keyed by
        # request index (the router merges replicas' dicts)
        served = sorted(done_at)
        outs: dict[int, Any] = {}
        for req in served:
            n, (slot, sw) = done_at[req], span[req]
            if n == 1:
                outs[req] = firsts[req][None]
            elif req in frag:
                # a growth stall fragmented this request's tenancy: its
                # emissions are the recorded active waves, not a
                # contiguous slice
                idx = jnp.asarray(frag[req][:n - 1], jnp.int32)
                outs[req] = jnp.concatenate(
                    [firsts[req][None], waves[idx, slot]])
            else:
                # the n-1 step waves while req held its slot are exactly
                # hist[sw : sw+n-1] — one emission per active wave
                outs[req] = jnp.concatenate(
                    [firsts[req][None], waves[sw:sw + n - 1, slot]])
        if eos_id is not None and eos_check_every > 1:
            # lagged scheduling can retire by count cap before a scan
            # saw an eos (and never sees first-token eos at all) —
            # truncation at the first eos restores the exact W=1
            # semantics; it runs on host ints, zero extra flushes
            for req, o in outs.items():
                toks = [int(t) for t in jax.device_get(o)]
                n = next((i + 1 for i, t in enumerate(toks)
                          if t == eos_id), len(toks))
                outs[req] = o[:n]
        # generated counts EMITTED tokens (post-truncation output
        # lengths): under lagged eos checks a count-cap retirement can
        # precede the scan that would have seen an earlier eos, and
        # done_at would overcount the discarded tail. The per-request
        # telemetry spans, emitted live at retirement, record the
        # SCHEDULED token count in that case — the same bounded bubble
        # the eos_check_every docs describe.
        run.last_stats = _stats(
            len(served), sum(int(o.shape[0]) for o in outs.values()),
            len(hist), latencies, rstate)
        if admission is not None:
            return outs
        return [outs[i] for i in range(len(prompts))]

    class _PrefillSession:
        """PREFILL-WORKER state for the disaggregated fleet
        (``models/fleet.py``): a slots=1 paged pool that prefills one
        prompt per call and exports the finished blocks as a handoff
        payload (``paging.export_block_rows``) for a decode engine's
        ``kv_import`` admission — the Podracer role split with the
        paged block as the transfer unit. Prefix sharing (an engine
        built with ``share_prefix=True``) works ACROSS calls: the
        session's index retains popular template blocks up to
        ``prefix_keep_blocks``, so a repeated template prefills once
        per worker and later requests only pay the export copy."""

        def __init__(self, kv_blocks: int | None):
            from .paging import init_paged_cache

            if kv_blocks is None:
                kv_blocks = 1 + nt + (prefix_keep_blocks
                                      if share_prefix else 0)
            if kv_blocks < 1 + nt:
                raise ValueError(
                    f"prefill session needs >= {1 + nt} blocks (one "
                    f"full table + the garbage block), got {kv_blocks}")
            self.alloc = BlockAllocator(kv_blocks)
            self.index = (PrefixIndex(self.alloc, prefix_keep_blocks)
                          if share_prefix else None)
            self.pool = init_paged_cache(
                cfg, 1, max_len, block_size=bs, num_blocks=kv_blocks,
                cache_dtype=cache_dtype)
            self.stats = {"requests": 0, "hit_blocks": 0,
                          "prompt_blocks": 0, "tokens_saved": 0}

        def _alloc_reclaiming(self, n: int) -> list[int]:
            blocks = self.alloc.alloc(n)
            while blocks is None and self.index is not None:
                if not self.index.reclaim(n - self.alloc.free_blocks):
                    break
                blocks = self.alloc.alloc(n)
            if blocks is None:
                # sized for a full table at construction, so only a
                # caller-shrunk pool can get here — loud, not a hold
                # (there is no queue to hold in; the router owns one)
                raise ValueError(
                    f"prefill session pool exhausted allocating {n} "
                    f"blocks — raise its kv_blocks")
            return blocks

        def prefill(self, prompt):
            """Prefill ``prompt`` (``[L]`` tokens) and return the
            handoff payload ``{"first": token, "n_tokens": L,
            "blocks": export_block_rows(...)}``: whole ``kv_block``
            blocks covering rows ``0..L-1`` (tail rows inside the last
            block ride along unreachable behind the importer's pos),
            plus the greedily-picked first token. Exactly the math a
            colocated admission runs — same prefill impl selection,
            same unshared-suffix start — so a decode engine importing
            the payload continues bit-identically."""
            from .decode import _select_prefill_impl
            from .paging import export_block_rows

            prompt = jnp.asarray(prompt)
            length = int(prompt.shape[-1])
            if length < 1:
                raise ValueError("prompts must have at least one token")
            if length >= max_len:
                raise ValueError(
                    f"prompt ({length}) must leave room for at least "
                    f"one generated token under max_len ({max_len})")
            shared: list[int] = []
            cov = 0
            chunks: list = []
            if self.index is not None:
                toks = [int(t) for t in np.asarray(prompt)]
                chunks = chain_chunks(toks, bs)
                # one prompt token must remain to forward — its logits
                # pick the first generated token
                while chunks and chunk_tokens_covered(
                        len(chunks), bs) > length - 1:
                    chunks.pop()
                shared = self.index.match(chunks)
                cov = chunk_tokens_covered(len(shared), bs)
            k = len(shared)
            own = self._alloc_reclaiming(
                blocks_for_rows(length - k * bs, bs))
            row = np.zeros((nt,), np.int32)
            row[:k] = shared
            row[k:k + len(own)] = own
            impl = ("cached" if cov
                    else _select_prefill_impl(cfg, length, "auto"))
            suffix = prompt[cov:] if cov else prompt
            t0c = _clk()
            first, self.pool = _admit_full(
                prefill_params, suffix[None, :], impl, jnp.int32(0),
                jnp.asarray(row), jnp.zeros((2,), jnp.uint32),
                jnp.zeros((2,), jnp.int32), jnp.int32(cov), self.pool)
            if self.index is not None:
                self.index.register(
                    chunks, [int(row[j]) for j in range(len(chunks))])
                self.stats["hit_blocks"] += k
                self.stats["prompt_blocks"] += len(chunks)
                self.stats["tokens_saved"] += cov
            self.stats["requests"] += 1
            if reg.enabled:
                reg.emit_span("serve_prefill", t0c, reg.clock(),
                              prompt_len=length, handoff=True)
            nb = blocks_for_rows(length, bs)
            payload = {
                "first": first, "n_tokens": length,
                "blocks": export_block_rows(
                    self.pool, [int(row[j]) for j in range(nb)]),
            }
            # this request's references drop; registered template
            # blocks stay resident through the index's own refs (LRU
            # capped) for the next same-template prefill
            self.alloc.free(shared + own)
            if self.index is not None:
                self.index.trim()
            return payload

        def close(self) -> None:
            if self.index is not None:
                self.index.release()

    def prefill_session(*, kv_blocks: int | None = None):
        """Open a prefill-worker session (see :class:`_PrefillSession`).
        Greedy engines without a template ``prefix``/``prefill_chunk``/
        ``spec_k`` only: the handoff payload carries one greedily
        picked first token and rows starting at position 0."""
        if sampler is not None:
            raise ValueError("prefill sessions are greedy-only — the "
                             "payload's first token has no rng lane")
        if spec_k is not None:
            raise ValueError("prefill sessions prefill and hand off — "
                             "spec_k belongs to the decode engine")
        if prefix is not None:
            raise ValueError("prefill sessions need prefix=None: the "
                             "payload's rows must start at position 0")
        if prefill_chunk is not None:
            raise ValueError(
                "prefill sessions use the one-dispatch prefill — "
                "prefill_chunk's interleaving needs the wave loop; "
                "build the prefill-worker engine without it")
        if host_spill:
            # a handoff payload is export_block_rows over DEVICE rows;
            # a spilled chain's bytes live host-side, so its donation
            # would export whatever garbage now sits in the recycled
            # device blocks — refuse the combination outright rather
            # than silently corrupt a decode pool downstream
            raise ValueError(
                "prefill sessions hand off device-resident blocks — a "
                "host-spilled chain has no device rows to export, so "
                "host_spill does not compose with kv_import donation; "
                "build the prefill-worker engine without host_spill "
                "(decode-side engines may still spill)")
        return _PrefillSession(kv_blocks)

    # ------------------------------------------------ AOT warm surface
    # (models/aotcache.py): the engine's step family, enumerable as
    # (name, jit, abstract args) so warm_engine can compile the WHOLE
    # family ahead of the first request and a fleet joiner pays disk
    # reads instead of XLA walls. Everything below is inert unless
    # something calls it — an unwarmed engine is the pre-cache engine.

    def _tree_aval(tree):
        return jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), tree)

    def _pool_aval(slots_: int, kv_blocks_):
        """Abstract pool matching ``_Run``'s geometry for ``slots_`` —
        the default block count mirrors ``_Run.__init__``'s full
        provisioning exactly, so a warm against the serving geometry
        compiles the serving programs."""
        need_prefix = (prefix_full_blocks
                       + (1 if prefix_tail_rows else 0))
        nb = (1 + need_prefix + slots_ * nt
              if kv_blocks_ is None else int(kv_blocks_))
        aval = jax.ShapeDtypeStruct
        kv_shape = (nb, bs, cfg.kv_heads, cfg.head_dim)
        buf_dtype = jnp.int8 if quant else cfg.dtype
        pool = {
            "k": [aval(kv_shape, buf_dtype)] * cfg.n_layers,
            "v": [aval(kv_shape, buf_dtype)] * cfg.n_layers,
            "block_tables": aval((slots_, nt), jnp.int32),
            "pos": aval((slots_,), jnp.int32),
        }
        if quant:
            pool["k_scale"] = [aval(kv_shape[:3],
                                    jnp.float32)] * cfg.n_layers
            pool["v_scale"] = [aval(kv_shape[:3],
                                    jnp.float32)] * cfg.n_layers
        return pool

    def aot_registrations(*, slots: int = 4, kv_blocks=None,
                          prompt_lens=(), n_new: int = 2):
        """The engine's enumerable step family for the given serving
        geometry. Each prompt length is its OWN admission compile
        (there is no length bucketing), so ``prompt_lens`` should be
        the lengths the schedule will actually admit. Registrations
        cover the default (rules=None) steps — mesh-sharded runs
        compile per rules object and warm through priming instead.
        Admissions are registered at full length (a cross-request
        prefix hit admits a shorter suffix — that variant warms on
        first use; degradation here is one extra compile, never a
        wrong executable)."""
        del n_new                   # a runtime value, not a compile axis
        from .decode import _select_prefill_impl

        aval = jax.ShapeDtypeStruct
        pool = _pool_aval(slots, kv_blocks)
        p_pre = _tree_aval(prefill_params)
        p_dec = _tree_aval(params)
        i32 = aval((), jnp.int32)
        key_av = aval((2,), jnp.uint32)
        row_av = aval((nt,), jnp.int32)
        tail_av = aval((2,), jnp.int32)
        bool_s = aval((slots,), jnp.bool_)
        i32_s = aval((slots,), jnp.int32)
        lens = sorted({int(x) for x in prompt_lens})
        regs = []
        if prefill_chunk is None:
            for length in lens:
                impl = ("cached" if prefix is not None else
                        _select_prefill_impl(cfg, length, "auto"))
                regs.append((
                    f"admit_full_L{length}", _admit_full,
                    (p_pre, aval((1, length), jnp.int32), impl, i32,
                     row_av, key_av, tail_av, i32, pool)))
        else:
            c = prefill_chunk
            regs.append(("admit_table", _admit_table,
                         (i32, row_av, tail_av, i32, pool)))
            if spec_k is None:
                regs.append(("chunk_step", _chunk_step,
                             (p_pre, aval((1, c), jnp.int32), i32,
                              pool)))
                regs.append(("chunk_finish", _chunk_finish,
                             (aval((c, cfg.vocab), cfg.dtype), i32,
                              key_av, i32, pool, i32)))
            else:
                mc = max(1, (max_len - prefix_len) // c)
                regs.append(("chunk_sweep", _chunk_sweep,
                             (p_pre, aval((1, mc, c), jnp.int32), i32,
                              i32, pool, i32, key_av, i32)))
        if lazy_growth:
            regs.append(("grow_table", _grow_table,
                         (i32, i32, i32, pool)))
        if prefix is not None:
            regs.append(("prefix_fill", _prefix_fill,
                         (p_pre, aval((1, prefix_len), jnp.int32),
                          row_av, pool)))
        if spec_k is not None:
            ctx_av = aval((slots, max_len + spec_k + 1), jnp.int32)
            for length in lens:
                regs.append((
                    f"spec_admit_row_L{length}", _spec_admit_row,
                    (aval((length,), jnp.int32), i32, i32, ctx_av,
                     i32_s, i32_s)))
            spec_step = step_for("spec", cache_dtype != "int8", None)
            regs.append(("spec_step", spec_step._aot,
                         (p_dec, ctx_av, i32_s, i32_s, i32_s, i32,
                          bool_s, i32, i32_s, pool)))
        else:
            step = step_for("plain", cache_dtype != "int8", None)
            if sampler is None:
                regs.append(("wave_step", step._aot,
                             (p_dec, i32_s, bool_s, pool)))
            else:
                regs.append(("wave_step_sampled", step._aot,
                             (p_dec, i32_s, bool_s, i32_s, i32_s,
                              key_av, pool)))
        # the fleet handoff pair (paging._xfer_jits): the crc-stamped
        # prefill→decode block transfer, per distinct block count the
        # given prompt lengths export
        if lens:
            from .paging import _xfer_jits

            xfer_keys = ("k", "v") + (("k_scale", "v_scale")
                                      if quant else ())
            for nxf in sorted({blocks_for_rows(x, bs) for x in lens}):
                bufs = [pool[k_][layer] for k_ in xfer_keys
                        for layer in range(cfg.n_layers)]
                payload = [aval((nxf,) + tuple(b.shape[1:]), b.dtype)
                           for b in bufs]
                ids = aval((nxf,), jnp.int32)
                regs.append((f"xfer_export_N{nxf}",
                             _xfer_jits()["export"], (bufs, ids)))
                regs.append((f"xfer_import_N{nxf}",
                             _xfer_jits()["import"],
                             (bufs, ids, payload)))
        return regs

    def aot_prime(*, slots: int = 4, kv_blocks=None, prompt_lens=(),
                  n_new: int = 2):
        """Call-path warm: ``jit(...).lower().compile()`` does NOT
        populate the jit call-path cache (a later direct call
        re-traces), so drive ONE tiny seeded synthetic schedule
        through the real ``run()``. With the persistent XLA cache
        active its compiles are disk hits — trace time, not XLA time.
        Leaves no cross-run state (every ``run()`` builds a fresh
        ``_Run``), so a primed engine's later runs stay byte-identical
        to an unprimed engine's."""
        # clamp to the engine's real budget envelope: callers hand the
        # SERVING schedule's lens/budgets (fleet warm_kw), and the
        # longest prompt + the largest budget may not fit together —
        # priming only needs the call path, not the full decode
        lens = sorted({int(x) for x in prompt_lens
                       if prefix_len + int(x) < max_len})
        if not lens:
            return 0
        n_new = max(1, min(int(n_new), max_len - prefix_len - lens[-1]))
        prompts = [np.arange(1, x + 1, dtype=np.int32) % cfg.vocab
                   for x in lens]
        run(prompts, n_new, slots=slots, kv_blocks=kv_blocks,
            rng=jax.random.PRNGKey(0) if sampler is not None else None)
        return len(prompts)

    def warm(cache=None, *, slots: int = 4, kv_blocks=None,
             prompt_lens=(), n_new: int = 2, prime: bool = True):
        """One-call cold-start warm — see
        :func:`..aotcache.warm_engine`. A no-op stats dict when the
        engine has no cache and none is passed."""
        from .aotcache import warm_engine

        return warm_engine(
            run, cache if cache is not None else aot_cache,
            slots=slots, kv_blocks=kv_blocks, prompt_lens=prompt_lens,
            n_new=n_new, prime=prime, telemetry=reg)

    run.last_stats = None
    run.prefill_session = prefill_session
    run.aot_scope = aot_scope
    run.aot_cache = aot_cache
    run.aot_registrations = aot_registrations
    run.aot_prime = aot_prime
    run.warm = warm
    return run


def serve(params, prompts: Sequence[Any], n_new, cfg: BurnInConfig,
          *, slots: int = 4, max_len: int | None = None,
          rules: ShardingRules | None = None,
          cache_dtype: str = "bf16",
          eos_id: int | None = None,
          eos_check_every: int = 1,
          prefill_chunk: int | None = None,
          spec_k: int | None = None,
          kv_block: int = 16,
          kv_blocks: int | None = None,
          arrivals=None,
          static_batching: bool = False) -> list[Any]:
    """Serve ``prompts`` (each ``[L_i]``) with continuous batching.

    Returns one token array per prompt, in request order (``[n_new]``
    each, shorter when ``eos_id`` fires; ``n_new`` may be a per-request
    sequence). ``slots`` bounds device-resident concurrency; requests
    beyond it queue and take over slots as earlier requests finish. The
    KV cache is PAGED: ``kv_block``-row blocks allocated per admission
    and recycled at retirement (``models/paging.py``); ``kv_blocks``
    caps the physical pool (default: full provisioning), turning KV HBM
    pressure into queueing instead of an OOM. ``arrivals`` (seconds,
    per request — e.g. a ``utils/traffic.py`` trace) gates admission so
    the engine serves a load model. With ``rules`` the pool shards KV
    heads over ``tp`` and the engine runs on the training mesh;
    ``slots`` must divide the data-axis shard count.

    ``eos_check_every=W`` batches eos retirement readbacks: one
    ``[W, slots]`` transfer per ``W`` waves instead of one ``[slots]``
    per wave — slots recycle up to ``W-1`` waves late, outputs are
    EXACT either way. ``prefill_chunk`` admits through chunk-per-wave
    interleaved prefill; ``spec_k`` serves through speculative
    continuous batching (see :func:`make_serve_engine`).

    One-shot convenience over :func:`make_serve_engine` — callers timing
    or re-running schedules should build the engine once instead.
    """
    if not prompts:
        return []
    if max_len is None:
        n_max = n_new if isinstance(n_new, int) else max(n_new)
        longest = max(int(p.shape[-1]) for p in prompts)
        if prefill_chunk:
            # leave room for the padded tail of the longest prompt
            longest = -(-longest // prefill_chunk) * prefill_chunk
        max_len = longest + n_max + (spec_k or 0)
    engine = make_serve_engine(params, cfg, max_len=max_len,
                               cache_dtype=cache_dtype,
                               prefill_chunk=prefill_chunk,
                               spec_k=spec_k, kv_block=kv_block)
    return engine(prompts, n_new, slots=slots, rules=rules, eos_id=eos_id,
                  eos_check_every=eos_check_every, kv_blocks=kv_blocks,
                  arrivals=arrivals, static_batching=static_batching)
