# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""The health probe is real (VERDICT item 4): device observation, node
condition export via the Kubernetes API, and Prometheus gauges — exercised
directly from the chart's files/probe.py, plus render-level assertions that
the DaemonSet actually wires the script, identity, and scrape surface.

Reference capability replaced: the GPU Operator's DCGM / node-status role
(/root/reference/gke/main.tf:195-213).
"""

import http.server
import importlib.util
import json
import os
import threading
import urllib.request

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CHART = os.path.join(ROOT, "charts", "tpu-runtime")


def _load_probe():
    spec = importlib.util.spec_from_file_location(
        "tpu_probe", os.path.join(CHART, "files", "probe.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


probe = _load_probe()


# ------------------------------------------------------------ observation

def test_probe_devices_counts_accel_and_vfio(tmp_path):
    dev = tmp_path / "dev"
    (dev / "vfio").mkdir(parents=True)
    (dev / "accel0").touch()
    (dev / "accel1").touch()
    (dev / "vfio" / "0").touch()
    (dev / "vfio" / "vfio").touch()   # control node: not a chip
    (tmp_path / "tmp").mkdir()
    r = probe.probe_devices(str(dev), str(tmp_path / "tmp"), min_chips=3)
    assert r["device_files"] == 3
    assert r["ok"] is True
    assert r["in_use"] is False


def test_probe_devices_unhealthy_and_in_use(tmp_path):
    (tmp_path / "dev").mkdir()
    (tmp_path / "tmp").mkdir()
    (tmp_path / "tmp" / "libtpu_lockfile").touch()
    r = probe.probe_devices(str(tmp_path / "dev"), str(tmp_path / "tmp"))
    assert r["ok"] is False
    assert r["in_use"] is True


# -------------------------------------------------------- node condition

def test_condition_body_merges_by_type():
    body = probe.condition_body(
        {"ok": True, "device_files": 4, "in_use": False},
        "TPUHealthy", now="2026-07-29T00:00:00Z")
    (cond,) = body["status"]["conditions"]
    assert cond["type"] == "TPUHealthy"
    assert cond["status"] == "True"
    assert cond["reason"] == "TPUDevicesPresent"
    assert "4 TPU device file(s)" in cond["message"]
    assert cond["lastHeartbeatTime"] == "2026-07-29T00:00:00Z"


def test_condition_body_preserves_transition_time_on_heartbeat():
    """lastTransitionTime only advances on a status flip (kubelet/NPD
    semantics) — heartbeats carry the remembered flip time."""
    body = probe.condition_body(
        {"ok": True, "device_files": 4, "in_use": False},
        "TPUHealthy", now="2026-07-29T00:05:00Z",
        transition_time="2026-07-29T00:00:00Z")
    (cond,) = body["status"]["conditions"]
    assert cond["lastHeartbeatTime"] == "2026-07-29T00:05:00Z"
    assert cond["lastTransitionTime"] == "2026-07-29T00:00:00Z"


def test_patch_node_condition_hits_status_subresource(tmp_path):
    seen = {}

    class Handler(http.server.BaseHTTPRequestHandler):
        def do_PATCH(self):
            seen["path"] = self.path
            seen["content_type"] = self.headers["Content-Type"]
            seen["auth"] = self.headers.get("Authorization")
            length = int(self.headers["Content-Length"])
            seen["body"] = json.loads(self.rfile.read(length))
            self.send_response(200)
            self.send_header("Content-Length", "2")
            self.end_headers()
            self.wfile.write(b"{}")

        def log_message(self, *a):
            pass

    server = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    token = tmp_path / "token"
    token.write_text("sekret\n")
    try:
        code = probe.patch_node_condition(
            {"ok": False, "device_files": 0, "in_use": False},
            node="gke-tpu-node-7",
            condition_type="TPUHealthy",
            api_base=f"http://127.0.0.1:{server.server_address[1]}",
            token_path=str(token))
    finally:
        server.shutdown()
    assert code == 200
    assert seen["path"] == "/api/v1/nodes/gke-tpu-node-7/status"
    assert seen["content_type"] == "application/strategic-merge-patch+json"
    assert seen["auth"] == "Bearer sekret"
    (cond,) = seen["body"]["status"]["conditions"]
    assert (cond["type"], cond["status"]) == ("TPUHealthy", "False")
    assert cond["reason"] == "TPUDevicesMissing"


def test_patch_failure_never_raises():
    code = probe.patch_node_condition(
        {"ok": True, "device_files": 1, "in_use": False},
        node="n", api_base="http://127.0.0.1:1",   # nothing listens
        token_path="/nonexistent")
    assert code == 0


# -------------------------------------------------------------- metrics

def test_metrics_endpoint_serves_gauges():
    server = probe.serve_metrics(0)
    probe._MetricsHandler.latest = {
        "ok": True, "device_files": 4, "in_use": True}
    try:
        port = server.server_address[1]
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=5) as resp:
            text = resp.read().decode()
            assert resp.headers["Content-Type"].startswith("text/plain")
    finally:
        server.shutdown()
    assert "tpu_healthprobe_ok 1" in text
    assert "tpu_healthprobe_device_files 4" in text
    assert "tpu_healthprobe_in_use 1" in text
    assert "# TYPE tpu_healthprobe_ok gauge" in text


# ------------------------------------------------------- chart wiring

def _tmpl(name: str) -> str:
    with open(os.path.join(CHART, "templates", name)) as fh:
        return fh.read()


def test_daemonset_runs_shipped_script_with_identity():
    ds = _tmpl("healthprobe-daemonset.yaml")
    assert 'command: ["python", "/opt/probe/probe.py"]' in ds
    assert "serviceAccountName: {{ .Release.Name }}-healthprobe" in ds
    assert "-healthprobe-script" in ds           # configmap volume
    assert "PROBE_PATCH_NODE_CONDITION" in ds
    assert "containerPort: {{ .Values.probe.metrics.port }}" in ds


def test_rbac_grants_only_node_status_patch():
    rbac = _tmpl("healthprobe-rbac.yaml")
    assert '"nodes/status"' in rbac
    assert '"patch"' in rbac
    # nothing broader: no wildcard verbs/resources, no reads, no writes
    for forbidden in ('"*"', "secrets", '"get"', '"list"', '"update"',
                      '"create"', '"delete"'):
        assert forbidden not in rbac, forbidden


def test_daemonset_pod_labels_do_not_collide_with_selector():
    """The shared-labels helper must not re-emit app.kubernetes.io/name in
    the pod template — last-key-wins would break the selector match."""
    ds = _tmpl("healthprobe-daemonset.yaml")
    pod_tmpl = ds[ds.index("template:"):]
    assert "tpu-runtime.sharedLabels" in pod_tmpl
    assert 'app.kubernetes.io/name: tpu-runtime-healthprobe' in pod_tmpl
    assert "tpu-runtime.labels" not in pod_tmpl
    helpers = _tmpl("_helpers.tpl")
    shared = helpers.split('define "tpu-runtime.sharedLabels"')[1].split(
        "{{- end }}")[0]
    assert "app.kubernetes.io/name" not in shared


def test_daemonset_rolls_on_script_change():
    ds = _tmpl("healthprobe-daemonset.yaml")
    assert 'checksum/probe-script: {{ .Files.Get "files/probe.py" | sha256sum }}' in ds


def test_script_configmap_ships_the_probe_file():
    cm = _tmpl("healthprobe-script.yaml")
    assert '.Files.Get "files/probe.py"' in cm


def test_podmonitoring_gated_and_scrapes_metrics_port():
    pm = _tmpl("healthprobe-podmonitoring.yaml")
    assert "PodMonitoring" in pm
    assert ".Values.probe.metrics.podMonitoring" in pm
    assert "port: metrics" in pm


def test_module_passes_podmonitoring_value_through():
    from nvidia_terraform_modules_tpu.tfsim import simulate_plan
    plan = simulate_plan(os.path.join(ROOT, "gke-tpu"), {
        "project_id": "p", "cluster_name": "c",
        "tpu_runtime": {"pod_monitoring": True},
        "smoketest": {"enabled": False},
    })
    rel = plan.instance("helm_release.tpu_runtime[0]")
    vals = json.loads(rel.attrs["values"][0])
    assert vals["probe"]["metrics"]["podMonitoring"] is True
