# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""Timing helpers for device-side work.

``jax.block_until_ready`` is not a reliable barrier on every backend (the
tunnelled ``axon`` TPU platform acks dispatch without waiting for execution),
so everything here synchronises by reading one element of the output back to
the host — a d2h copy can only complete after the producing kernel has.

That readback costs a fixed per-call latency (tens of ms over a tunnel),
which would swamp short kernels. ``delta_time`` therefore measures the same
computation at two different iteration counts and reports the per-iteration
cost from the difference, cancelling the fixed sync overhead.
"""

from __future__ import annotations

import time
from typing import Any, Callable


def sync(out: Any) -> None:
    """Barrier that provably waits for device execution of ``out``.

    Reads a single element of the first non-empty array leaf back to the
    host. For sharded (possibly non-fully-addressable, multi-host) arrays the
    read goes through the local addressable shard, so every process syncs on
    its own data without a cross-process fetch. Falls back to
    ``block_until_ready`` for non-array outputs.
    """
    import jax
    import numpy as np

    for leaf in jax.tree.leaves(out):
        shards = getattr(leaf, "addressable_shards", None)
        if shards and shards[0].data.size:
            np.asarray(jax.device_get(shards[0].data.ravel()[0:1]))
            return
    jax.block_until_ready(out)


def timed(fn: Callable[..., Any], *args: Any) -> tuple[Any, float]:
    """Run ``fn(*args)``, wait for device execution, return (out, seconds)."""
    t0 = time.perf_counter()
    out = fn(*args)
    sync(out)
    return out, time.perf_counter() - t0


def median_time(fn: Callable[..., Any], *args: Any, iters: int = 5, warmup: int = 2) -> float:
    """Median wall-clock seconds of ``fn(*args)`` over ``iters`` timed runs.

    ``warmup`` untimed runs first absorb compilation (first XLA compile of a
    probe is 20-40s on TPU; steady-state is what we report). Includes the
    fixed sync latency — use ``delta_time`` when that must cancel out.
    """
    for _ in range(warmup):
        timed(fn, *args)
    samples = sorted(timed(fn, *args)[1] for _ in range(iters))
    return samples[len(samples) // 2]


def delta_time(
    make_fn: Callable[[int], Callable[..., Any]],
    *args: Any,
    iters_lo: int,
    iters_hi: int,
    samples: int = 3,
) -> float:
    """Per-iteration seconds via two-point measurement.

    ``make_fn(n)`` must return a callable running ``n`` iterations of the
    kernel under test. Timing both ``iters_lo`` and ``iters_hi`` and dividing
    the difference removes fixed overhead (dispatch + host readback), which
    otherwise dominates short kernels on tunnelled backends.
    """
    assert iters_hi > iters_lo
    fn_lo, fn_hi = make_fn(iters_lo), make_fn(iters_hi)
    t_lo = median_time(fn_lo, *args, iters=samples)
    t_hi = median_time(fn_hi, *args, iters=samples)
    if t_hi <= t_lo:
        # Jitter swamped the delta; fall back to the bounded single-point
        # estimate (includes fixed overhead → conservative underestimate of
        # throughput) rather than reporting a nonsense near-zero time.
        return t_hi / iters_hi
    return (t_hi - t_lo) / (iters_hi - iters_lo)
