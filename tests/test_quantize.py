# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""Weight-only int8 quantization: fidelity, footprint, quantized decode."""

import jax
import jax.numpy as jnp
import numpy as np

from nvidia_terraform_modules_tpu.models import (
    BurnInConfig,
    forward,
    greedy_decode,
    init_params,
)
from nvidia_terraform_modules_tpu.models.quantize import (
    dequantize,
    dequantize_tree,
    make_quantized_decoder,
    quantize,
    quantize_params,
    quantize_tree,
    quantized_nbytes,
)

CFG = BurnInConfig(vocab=64, d_model=32, n_heads=2, d_ff=64, n_layers=2,
                   seq_len=16, batch=2, dtype=jnp.float32)


def test_roundtrip_error_is_small():
    w = jax.random.normal(jax.random.PRNGKey(0), (64, 128), jnp.float32)
    q, scale = quantize(w)
    assert q.dtype == jnp.int8
    assert scale.shape == (1, 128)          # one scale per output channel
    back = dequantize(q, scale, jnp.float32)
    # symmetric int8 per-channel: max error bounded by scale/2 per entry
    err = np.abs(np.asarray(back - w))
    assert err.max() <= float(np.asarray(scale).max()) * 0.51


def test_tree_roundtrip_keeps_norms_exact():
    params = init_params(jax.random.PRNGKey(0), CFG)
    qp = quantize_tree(params)
    back = dequantize_tree(qp, jnp.float32)
    # norm scales pass through bit-exact
    assert jnp.array_equal(back["out_norm"], params["out_norm"])
    assert jnp.array_equal(back["layers"][0]["attn_norm"],
                           params["layers"][0]["attn_norm"])
    # matmul weights are int8-stored
    assert qp["q"]["embed"].dtype == jnp.int8
    # footprint: int8 + f32 scales + norms is well under half the f32 tree
    full = sum(x.nbytes for x in jax.tree.leaves(params))
    assert quantized_nbytes(qp) < 0.5 * full


def test_quantized_logits_close():
    params = init_params(jax.random.PRNGKey(0), CFG)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, CFG.vocab)
    ref = forward(params, tokens, CFG)
    qlogits = forward(dequantize_tree(quantize_tree(params), jnp.float32),
                      tokens, CFG)
    # relative error at the logit level stays small for int8 per-channel
    denom = np.maximum(np.abs(np.asarray(ref)), 1.0)
    rel = np.abs(np.asarray(qlogits - ref)) / denom
    assert rel.max() < 0.15
    assert np.mean(rel) < 0.02


def test_quantized_decoder_runs_and_mostly_agrees():
    params = init_params(jax.random.PRNGKey(0), CFG)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 6), 0, CFG.vocab)
    full = greedy_decode(params, prompt, 8, CFG)
    decoder = make_quantized_decoder(CFG, n_new=8, dtype=jnp.float32)
    q_toks = decoder(quantize_params(params, dtype=jnp.float32), prompt)
    assert q_toks.shape == (2, 8)
    # greedy argmax under small logit perturbation: most tokens agree
    agree = float(np.mean(np.asarray(full) == np.asarray(q_toks)))
    assert agree >= 0.5, (full, q_toks)


def test_unfused_decoder_matches_fused():
    """The bench's fused-vs-unfused comparison is apples-to-apples: both
    paths consume the same quantize_params tree and emit the same
    tokens (the schedule of dequantization changes HBM traffic, never
    the math)."""
    params = init_params(jax.random.PRNGKey(0), CFG)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 6), 0, CFG.vocab)
    qp = quantize_params(params, dtype=jnp.float32)
    fused = make_quantized_decoder(CFG, n_new=8, dtype=jnp.float32)
    unfused = make_quantized_decoder(CFG, n_new=8, dtype=jnp.float32,
                                     fused=False)
    assert np.array_equal(np.asarray(fused(qp, prompt)),
                          np.asarray(unfused(qp, prompt)))
