# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""``tfsim chaos``: sweep fault seeds × parallelism, assert convergence.

For each (seed, parallelism) pair the harness runs the full operator
playbook in a throwaway sandbox, end-to-end through the real CLI (the
same code paths a human drives), and asserts the invariants the
recovery story promises:

1. **apply** with the fault profile (seeded, at ``-parallelism N``).
   A clean run must already match the planned state.
2. If the run was interrupted: break a leftover crash lock by ID
   (``force-unlock``), push a leftover ``errored.tfstate`` back
   (``state push``), then **re-apply fault-free** — which must exit 0
   and land exactly the planned state: no orphans, no duplicate
   creates, no lingering taint — and a follow-up
   ``plan -detailed-exitcode`` must report an **empty plan**.
3. From the *interrupted* state, a fault-free ``apply -destroy`` must
   leave empty state — interruption never wedges teardown.
4. **Scheduling invariants**, asserted against a deterministic replay
   of the same (profile, seed, parallelism) through the engine (the
   replay IS the CLI run — that determinism is itself invariant 4a,
   checked by replaying twice): no operation ever starts before every
   operation it depends on completed; never more than ``parallelism``
   operations in flight; and the skipped set equals the exact
   transitive dependent-closure of the terminal failures.

Because every (seed, parallelism) run must converge to the SAME
expected state, the sweep also proves serial/parallel final-state
equivalence. Any violated invariant fails the sweep (exit 1) with the
run's transcript, making ``tfsim chaos -seeds 8 -parallelism 1,4,10
MODULE`` a standing CI gate for the module's crash-consistency under
realistic concurrency.
"""

from __future__ import annotations

import contextlib
import dataclasses
import io
import json
import os
import sys
import tempfile

from ..plan import simulate_plan
from ..state import State, apply_plan, diff
from .profile import DEFAULT_CHAOS_PROFILE, load_profile

DEFAULT_PARALLELISM_LEVELS = (1, 4, 10)


@dataclasses.dataclass
class SeedResult:
    seed: int
    parallelism: int = 1
    interrupted: bool = False
    crashed: bool = False
    errored_state: bool = False
    failure_op: str | None = None    # "<address>:<op>" of the first failure
    failure_kind: str | None = None
    skipped: int = 0                 # dependent operations skipped
    recovery: list = dataclasses.field(default_factory=list)  # steps taken
    violations: list = dataclasses.field(default_factory=list)
    transcript: str = ""

    @property
    def ok(self) -> bool:
        return not self.violations

    def record(self) -> dict:
        """The machine-readable per-run record (``chaos -json``)."""
        return {
            "seed": self.seed,
            "parallelism": self.parallelism,
            "interrupted": self.interrupted,
            "crashed": self.crashed,
            "errored_state": self.errored_state,
            "failure_op": self.failure_op,
            "failure_kind": self.failure_kind,
            "skipped": self.skipped,
            "converged": self.ok,
            "recovery": self.recovery,
            "violations": self.violations,
        }

    def summary(self) -> str:
        if not self.interrupted:
            how = "clean apply"
        else:
            bits = ["interrupted"]
            if self.failure_kind:
                bits.append(self.failure_kind)
            if self.crashed:
                bits.append("crash")
            if self.errored_state:
                bits.append("errored.tfstate")
            if self.skipped:
                bits.append(f"{self.skipped} skipped")
            how = "+".join(bits)
        verdict = "converged" if self.ok else \
            "; ".join(self.violations)
        tail = f" ({', '.join(self.recovery)})" if self.recovery else ""
        return (f"seed {self.seed} ×{self.parallelism}: {how} — "
                f"{verdict}{tail}")


def _run_cli(cli, argv: list[str], stdin_text: str | None = None
             ) -> tuple[int, str]:
    """Run one CLI invocation, capturing stdout+stderr (and feeding
    stdin for ``state push``)."""
    buf = io.StringIO()
    old_stdin = sys.stdin
    try:
        if stdin_text is not None:
            sys.stdin = io.StringIO(stdin_text)
        with contextlib.redirect_stdout(buf), \
                contextlib.redirect_stderr(buf):
            rc = cli(argv)
    finally:
        sys.stdin = old_stdin
    return rc, buf.getvalue()


def _load(path: str) -> State | None:
    if not os.path.exists(path):
        return None
    with open(path) as fh:
        return State.from_json(fh.read())


def _check_converged(res: SeedResult, state: State | None,
                     expected: State) -> None:
    if state is None:
        res.violations.append("no state after recovery")
        return
    if state.resources != expected.resources:
        missing = sorted(set(expected.resources) - set(state.resources))
        extra = sorted(set(state.resources) - set(expected.resources))
        drift = sorted(a for a in set(state.resources) &
                       set(expected.resources)
                       if state.resources[a] != expected.resources[a])
        res.violations.append(
            f"state does not match plan after re-apply "
            f"(missing={missing} extra={extra} drifted={drift})")
    if state.tainted:
        res.violations.append(
            f"taint survived convergence: {sorted(state.tainted)}")
    if state.outputs != expected.outputs:
        res.violations.append("outputs drifted from the planned outputs")


def _replay(plan, profile, seed: int, parallelism: int):
    """Re-run the seeded apply through the engine, no sandbox. The
    scheduler is a pure function of (profile, seed, parallelism), so
    this reproduces the CLI run exactly — and hands back the trace the
    CLI cannot surface."""
    from .apply import SimulatedCrash, run_apply
    from .control_plane import ControlPlane

    cp = ControlPlane(profile, seed=seed)
    try:
        return run_apply(plan, None, cp, parallelism=parallelism)
    except SimulatedCrash as ex:
        return ex.outcome


def _check_schedule(res: SeedResult, plan, outcome,
                    parallelism: int) -> None:
    """The scheduling invariants, from the replayed engine trace."""
    from .apply import operation_schedule

    ops, deps = operation_schedule(plan, diff(plan, None))
    info = {(t.address, t.op): t for t in outcome.trace}
    ran = {"ok", "failed", "crashed", "abandoned"}

    # 1. dependency-order safety: nothing starts before its deps finish
    for i, key in enumerate(ops):
        t = info.get(key)
        if t is None or t.status not in ran:
            continue
        for j in deps[i]:
            dt = info.get(ops[j])
            if dt is None or dt.status != "ok":
                res.violations.append(
                    f"{key[0]} {key[1]} ran although dependency "
                    f"{ops[j][0]} {ops[j][1]} never completed")
            elif dt.finish_s - t.start_s > 1e-9:
                res.violations.append(
                    f"{key[0]} {key[1]} started at {t.start_s:g}s, before "
                    f"dependency {ops[j][0]} finished at {dt.finish_s:g}s")

    # 2. the -parallelism cap held at every instant
    marks: list[tuple[float, int]] = []
    for t in info.values():
        if t.status in ran:
            marks.append((t.start_s, 1))
            marks.append((t.finish_s, -1))
    marks.sort()             # at equal times the -1 frees a slot first
    live = peak = 0
    for _, delta in marks:
        live += delta
        peak = max(peak, live)
    if peak > parallelism:
        res.violations.append(
            f"{peak} operations ran concurrently (parallelism "
            f"{parallelism})")

    # 3. skipped set == the exact transitive closure of the failures
    #    (meaningless after a crash: pending work is abandoned, not
    #    skipped)
    if not outcome.crashed:
        failed = {i for i, key in enumerate(ops)
                  if (t := info.get(key)) is not None
                  and t.status == "failed"}
        expected: set[int] = set()
        for i in range(len(ops)):
            if i not in failed and any(j in failed or j in expected
                                       for j in deps[i]):
                expected.add(i)
        want = {ops[i] for i in expected}
        got = {(s.address, s.op) for s in outcome.skipped}
        if want != got:
            res.violations.append(
                f"skipped set is not the failure closure (missing="
                f"{sorted(want - got)} extra={sorted(got - want)})")


def run_one_seed(cli, module_dir: str, var_argv: list[str],
                 profile_path: str, seed: int, expected: State,
                 plan=None, profile=None,
                 parallelism: int = 1) -> SeedResult:
    """The full interrupt-recover-converge-destroy cycle for one
    (seed, parallelism) pair."""
    from ..locking import lock_path, read_holder

    res = SeedResult(seed=seed, parallelism=parallelism)
    lines: list[str] = []

    # ---- engine replay: scheduling invariants + per-run record ------
    if plan is not None and profile is not None:
        outcome = _replay(plan, profile, seed, parallelism)
        again = _replay(plan, profile, seed, parallelism)
        if outcome.trace != again.trace:
            res.violations.append(
                "nondeterministic schedule: two replays of the same "
                "(seed, parallelism) diverged")
        # the replayed trace IS the CLI run (determinism is invariant
        # 4a): emit it as simulated-clock spans, one lane per
        # parallelism slot, labelled per run so sweeps don't interleave
        from .apply import emit_apply_telemetry

        emit_apply_telemetry(outcome, run=f"seed{seed}x{parallelism}")
        _check_schedule(res, plan, outcome, parallelism)
        if outcome.failures:
            first = outcome.failures[0]
            res.failure_op = f"{first.address}:{first.op}"
            res.failure_kind = first.kind
        res.skipped = len(outcome.skipped)
    else:
        outcome = None

    with tempfile.TemporaryDirectory(prefix=f"tfsim-chaos-{seed}-") as tmp:
        spath = os.path.join(tmp, "terraform.tfstate.json")
        errored = os.path.join(tmp, "errored.tfstate")

        rc, out = _run_cli(cli, ["apply", module_dir, *var_argv,
                                 "-state", spath,
                                 "-parallelism", str(parallelism),
                                 "-fault-profile", profile_path,
                                 "-fault-seed", str(seed)])
        lines.append(out)
        res.interrupted = rc != 0
        if rc not in (0, 1):
            res.violations.append(f"faulted apply exited {rc} (usage error)")
        if outcome is not None and not outcome.ok and rc == 0:
            res.violations.append(
                "engine replay reports failures but the CLI apply "
                "exited 0")

        # ---- recovery playbook (only after an interruption) ----------
        if os.path.exists(lock_path(spath)):
            res.crashed = True
            holder = read_holder(spath)
            rc, out = _run_cli(cli, ["force-unlock", holder.id,
                                     "-state", spath])
            lines.append(out)
            if rc != 0:
                res.violations.append(
                    "force-unlock by ID failed on a crash-left lock")
            res.recovery.append("lock broken by ID")

        if os.path.exists(errored):
            res.errored_state = True
            with open(errored) as fh:
                text = fh.read()
            rc, out = _run_cli(cli, ["state", "push", "-state", spath],
                               stdin_text=text)
            lines.append(out)
            if rc != 0:
                res.violations.append("state push of errored.tfstate failed")
            res.recovery.append("errored.tfstate pushed")

        # snapshot the interrupted state for the destroy invariant —
        # AFTER the lock break (teardown needs the lock too) and AFTER
        # the errored.tfstate push: for a state-write fault the pushed
        # file IS the interrupted state, and snapshotting earlier would
        # silently skip the invariant for exactly that failure class
        interrupted_json = None
        if res.interrupted and os.path.exists(spath):
            with open(spath) as fh:
                interrupted_json = fh.read()

        if res.interrupted:
            rc, out = _run_cli(cli, ["apply", module_dir, *var_argv,
                                     "-state", spath,
                                     "-parallelism", str(parallelism)])
            lines.append(out)
            if rc != 0:
                res.violations.append(f"fault-free re-apply exited {rc}")
            res.recovery.append("re-applied")

        _check_converged(res, _load(spath), expected)

        # a converged state must also read back as an EMPTY plan — the
        # operator-visible form of "nothing left to do"
        rc, out = _run_cli(cli, ["plan", module_dir, *var_argv,
                                 "-state", spath, "-detailed-exitcode"])
        lines.append(out)
        if rc != 0:
            res.violations.append(
                f"plan after convergence is not empty (exit {rc})")
        elif res.interrupted:
            res.recovery.append("re-plan empty")

        # ---- destroy-after-interruption invariant --------------------
        if interrupted_json is not None:
            snap = State.from_json(interrupted_json)
            if snap.resources:
                dpath = os.path.join(tmp, "interrupted.tfstate.json")
                with open(dpath, "w") as fh:
                    fh.write(interrupted_json)
                rc, out = _run_cli(cli, ["apply", module_dir, *var_argv,
                                         "-state", dpath, "-destroy",
                                         "-parallelism", str(parallelism)])
                lines.append(out)
                final = _load(dpath)
                if rc != 0:
                    res.violations.append(
                        f"destroy from interrupted state exited {rc}")
                elif final is None or final.resources:
                    left = sorted(final.resources) if final else "<none>"
                    res.violations.append(
                        f"destroy from interrupted state left "
                        f"resources: {left}")
                else:
                    res.recovery.append("destroy from interruption clean")
    res.transcript = "".join(lines)
    return res


def run_chaos(cli, module_dir: str, tfvars: dict, var_argv: list[str],
              seeds: int, profile_path: str | None = None,
              parallelism_levels=DEFAULT_PARALLELISM_LEVELS,
              log=None) -> list[SeedResult]:
    """Sweep ``seeds`` fault seeds × ``parallelism_levels`` over
    ``module_dir``; returns one :class:`SeedResult` per (seed,
    parallelism) run. ``cli`` is the tfsim ``main`` callable (injected
    to avoid an import cycle); ``var_argv`` is the raw
    ``-var``/``-var-file`` argv to forward to each CLI run, ``tfvars``
    the same variables resolved, for computing the expected state."""
    plan = simulate_plan(module_dir, tfvars)
    expected = apply_plan(plan, None)

    if profile_path is not None:
        # fail fast on a bad profile — otherwise every seeded apply dies
        # on it and the sweep misreads the failures as interruptions
        load_profile(profile_path)
    own_profile = None
    if profile_path is None:
        own_profile = tempfile.NamedTemporaryFile(
            "w", suffix=".fault.json", delete=False)
        json.dump(DEFAULT_CHAOS_PROFILE, own_profile)
        own_profile.close()
        profile_path = own_profile.name
    try:
        profile = load_profile(profile_path)   # the replay's own copy
        results = []
        for parallelism in parallelism_levels:
            for seed in range(seeds):
                res = run_one_seed(cli, module_dir, var_argv, profile_path,
                                   seed, expected, plan=plan,
                                   profile=profile,
                                   parallelism=parallelism)
                if log:
                    log(res.summary())
                results.append(res)
        _emit_chaos_telemetry(results)
        return results
    finally:
        if own_profile is not None:
            os.unlink(own_profile.name)


def _emit_chaos_telemetry(results: list[SeedResult]) -> None:
    """SLO-style attainment summary of a chaos sweep: every (seed,
    parallelism) run is one "request" whose SLO is *convergence*, so
    ``tfsim_chaos_attainment`` reads exactly like a serving
    availability number — plus one structured event per run (the
    ``chaos -json`` record, now on the shared schema, merge-compatible
    with the training harness's resume journal)."""
    from ...telemetry import get_registry

    reg = get_registry()
    if not reg.enabled or not results:
        return
    converged = sum(1 for r in results if r.ok)
    reg.counter("tfsim_chaos_runs").inc(len(results))
    reg.counter("tfsim_chaos_converged").inc(converged)
    reg.counter("tfsim_chaos_interrupted").inc(
        sum(1 for r in results if r.interrupted))
    reg.gauge("tfsim_chaos_attainment").set(converged / len(results))
    for r in results:
        reg.event("tfsim.chaos.run", **r.record())
