# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
# Output surface (composition API for examples/ and sibling modules).
#
# Capability parity with the reference's 10 outputs
# (/root/reference/gke/outputs.tf:8-63): cluster identity/endpoint/CA,
# network facts, and latest-version probes per channel.

output "cluster_name" {
  description = "Name of the created GKE cluster."
  value       = google_container_cluster.this.name
}

output "cluster_location" {
  description = "Location (zone or region) of the cluster."
  value       = google_container_cluster.this.location
}

output "cluster_endpoint" {
  description = "Cluster API endpoint."
  value       = google_container_cluster.this.endpoint
  sensitive   = true
}

output "cluster_ca_certificate" {
  description = "Base64-encoded public CA certificate of the cluster."
  value       = google_container_cluster.this.master_auth[0].cluster_ca_certificate
  sensitive   = true
}

output "project_id" {
  description = "Project the cluster runs in."
  value       = var.project_id
}

output "region" {
  description = "Region of the cluster network."
  value       = var.region
}

output "network_name" {
  description = "VPC network the cluster is attached to."
  value       = local.network_name
}

output "subnetwork_name" {
  description = "Subnetwork the cluster is attached to."
  value       = local.subnetwork_name
}

output "gpu_pool_name" {
  description = "Name of the GPU node pool (null when disabled)."
  value       = var.gpu_pool.enabled ? google_container_node_pool.gpu[0].name : null
}

output "latest_version_per_channel" {
  description = "Latest available GKE master versions, per release channel."
  value       = data.google_container_engine_versions.channel.release_channel_latest_version
}
