# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""The serving chaos gate: a replica kill loses ZERO unshed requests,
bit-exactly.

PR 5–6 proved the training stack's resilience with a kill-and-resume
harness whose invariants are exact (resumed params bit-match the
uninterrupted run); this is the serving twin (``models/fleet.py``'s
fault plane). The invariants these tests pin:

- **Bit-exact recovery.** Under a seeded mid-run replica kill, every
  unshed request completes with tokens equal to its UNDISTURBED solo
  greedy decode — redrive is re-admission from the original prompt,
  and tokens are schedule-invariant (PR 10's contract), so recovery is
  correctness-preserving, not best-effort. The fleet itself enforces
  no-loss/no-duplication loudly (a missing or double-served request
  raises), so a green run IS the no-loss certificate.
- **Defaults-off.** An EMPTY fault profile reproduces the fault-free
  fleet byte for byte — the fault plane is a seam, never a behaviour
  change.
- **Planned drain never recomputes.** A drained replica finishes its
  in-flight work; only its still-queued requests move.
- **Slow ≠ dead.** A stalling replica trips the circuit breaker
  (``resilience.LivenessBreaker``) and is quarantined as a
  steal/redrive target, while its outputs stay exact; nothing is
  redriven for mere slowness.
- **Corrupt handoffs are classified.** A disaggregated prefill→decode
  payload that fails its crc retries from prefill (``utils/retry``)
  and the decode still bit-matches — never silent garbage.
- **Degraded-mode shedding replays.** With deadlines armed, the shed
  set under a capacity schedule is a pure function of (trace, capacity
  schedule) — two fleets with the same (seed, profile) shed the same
  requests.

One seeded kill case is tier-1; the kill matrix (seeds × kill times ×
colocated/disaggregated) is slow-marked, the chaos-suite convention
since PR 5.
"""

import functools

import jax
import jax.numpy as jnp
import pytest

from nvidia_terraform_modules_tpu.models import (
    BurnInConfig,
    greedy_decode,
    init_params,
    make_fleet,
)
from nvidia_terraform_modules_tpu.models.fleet import (
    FleetFault,
    FleetFaultProfile,
    HashRing,
    affinity_key,
)
from nvidia_terraform_modules_tpu.utils.traffic import (
    fault_times,
    poisson_trace,
    slo_deadlines,
)

CFG = dict(vocab=64, d_model=32, n_heads=4, d_ff=64, n_layers=2,
           seq_len=16, batch=2, dtype=jnp.float32)


@functools.lru_cache(maxsize=None)
def _setup(n=8):
    """One shared template → affinity concentrates every request on ONE
    replica (the ring target of the template's first-block key), so a
    kill of that replica is guaranteed to have work to redrive."""
    cfg = BurnInConfig(**CFG)
    params = init_params(jax.random.PRNGKey(0), cfg)
    tmpl = jax.random.randint(jax.random.PRNGKey(3), (4,), 0, cfg.vocab)
    prompts = tuple(jnp.concatenate(
        [tmpl, jax.random.randint(jax.random.PRNGKey(40 + i),
                                  (1 + i % 3,), 0, cfg.vocab)])
        for i in range(n))
    return cfg, params, prompts


def _solo(params, prompts, n_new, cfg):
    return [greedy_decode(params, p[None, :], n_new, cfg,
                          max_len=16)[0] for p in prompts]


def _assert_all_equal(outs, want, label=""):
    for i, (g, w) in enumerate(zip(outs, want)):
        assert g is not None, f"{label} request {i} unserved"
        assert jnp.array_equal(g, w), f"{label} request {i} diverged"


def _victim(prompts, n_targets, kv_block=4):
    """The replica the shared template routes to — the deterministic
    kill target that is guaranteed to own the whole queue."""
    return HashRing(n_targets).target(affinity_key(prompts[0], kv_block))


def test_fleet_chaos_one_replica_kill_redrives_bit_exact_tier1():
    """THE chaos gate (ISSUE 13 acceptance): a 3-replica fleet with a
    seeded mid-run kill of the loaded replica serves EVERY request with
    solo-greedy-bit-exact tokens — the dead replica's queued and
    in-flight requests redrive to survivors, completed-elsewhere work
    is never re-run (the fleet raises on duplicates), and a replay of
    the same (seed, profile) reproduces the outputs exactly."""
    cfg, params, prompts = _setup()
    want = _solo(params, prompts, 6, cfg)
    victim = _victim(prompts, 3)
    profile = FleetFaultProfile(
        [FleetFault("kill_replica", target=victim, at_s=0.05)], seed=0)
    fleet = make_fleet(params, cfg, max_len=16, replicas=3, kv_block=4,
                       faults=profile, steal=False)
    got = fleet(prompts, 6, slots=2)
    _assert_all_equal(got, want, "after kill:")
    st = fleet.last_stats["fleet"]
    assert st["served"] == len(prompts) and st["shed"] == 0
    fr = st["faults"]
    assert fr["replica_down"] == 1
    assert fr["killed"] == [f"replica-{victim}"]
    assert fr["redriven"] >= 1
    assert fr["degraded"] is True and fr["drained"] == []
    assert fr["profile_seed"] == "0"
    # the dead replica is reported, never a KeyError on its missing
    # engine stats
    dead = [r for r in st["per_replica"] if r["dead"]]
    assert [r["replica"] for r in dead] == [f"replica-{victim}"]
    assert fleet.last_stats["replica_stats"][victim] is None
    # survivors drained their pools (redriven blocks freed at retire)
    for i, rs in enumerate(fleet.last_stats["replica_stats"]):
        if rs is not None:
            assert rs["kv"]["in_use"] == 0
    # replay: identical (seed, profile) ⇒ identical outputs, again
    # through a kill — the fault plane is deterministic end to end
    again = fleet(prompts, 6, slots=2)
    _assert_all_equal(again, want, "replay:")
    assert fleet.last_stats["fleet"]["faults"]["replica_down"] == 1


def test_fleet_chaos_empty_profile_reproduces_fault_free_fleet():
    """Defaults-off, pinned: an armed-but-empty profile byte-matches
    the ``faults=None`` fleet — same tokens, same placements, same
    (absent) shed set — and bills an all-zero fault record. The fault
    plane is a seam, not a behaviour change."""
    cfg, params, prompts = _setup()
    base = make_fleet(params, cfg, max_len=16, replicas=2, kv_block=4,
                      steal=False)
    want = base(prompts, 5, slots=2)
    bst = base.last_stats["fleet"]
    assert bst["faults"] is None
    armed = make_fleet(params, cfg, max_len=16, replicas=2, kv_block=4,
                       faults=FleetFaultProfile([], seed=7), steal=False)
    got = armed(prompts, 5, slots=2)
    _assert_all_equal(got, want, "empty profile:")
    ast = armed.last_stats["fleet"]
    assert ast["routed_to"] == bst["routed_to"]
    assert ast["shed_requests"] == bst["shed_requests"] == []
    fr = ast["faults"]
    assert fr["replica_down"] == 0 and fr["redriven"] == 0
    assert fr["drained"] == [] and fr["killed"] == []
    assert fr["handoff_retries"] == 0 and fr["degraded"] is False


def test_fleet_chaos_planned_drain_finishes_in_flight_work():
    """A planned ``drain_replica`` is removal WITHOUT recomputation:
    the drained replica stops admitting, finishes what it already
    started (it is never marked dead), only its still-queued requests
    move to survivors, and every output stays solo-exact."""
    cfg, params, prompts = _setup()
    want = _solo(params, prompts, 6, cfg)
    victim = _victim(prompts, 2)
    profile = FleetFaultProfile(
        [FleetFault("drain_replica", target=victim, at_s=0.05)], seed=1)
    fleet = make_fleet(params, cfg, max_len=16, replicas=2, kv_block=4,
                       faults=profile, steal=False)
    got = fleet(prompts, 6, slots=2)
    _assert_all_equal(got, want, "after drain:")
    st = fleet.last_stats["fleet"]
    fr = st["faults"]
    assert st["served"] == len(prompts) and st["shed"] == 0
    assert fr["drained"] == [f"replica-{victim}"]
    assert fr["replica_down"] == 0 and fr["killed"] == []
    assert fr["redriven"] >= 1 and fr["degraded"] is True
    # the drained replica FINISHED its in-flight work — it reports
    # stats (alive), served at least one request, and moved the rest
    by_label = {r["replica"]: r for r in st["per_replica"]}
    v = by_label[f"replica-{victim}"]
    assert v["dead"] is False and v["requests"] >= 1
    assert by_label[f"replica-{1 - victim}"]["requests"] >= 1
    moved = [w for r, w in st["routed_to"].items()
             if w.startswith("drained->")]
    assert len(moved) == fr["redriven"] >= 1


def test_fleet_chaos_slow_replica_trips_breaker_stays_exact():
    """Slow ≠ dead: a replica stalling past ``health_timeout_s`` opens
    the circuit breaker (billed in the fault record) and is quarantined
    as a steal/redrive target — but nothing is redriven for slowness,
    no capacity is lost, and the outputs still bit-match solo."""
    cfg, params, prompts = _setup()
    want = _solo(params, prompts, 6, cfg)
    victim = _victim(prompts, 2)
    profile = FleetFaultProfile(
        [FleetFault("slow_replica", target=victim, at_s=0.0,
                    stall_s=0.12, waves=3)], seed=2)
    fleet = make_fleet(params, cfg, max_len=16, replicas=2, kv_block=4,
                       faults=profile, steal=True, steal_poll_s=0.001,
                       health_timeout_s=0.04, quarantine_polls=4)
    got = fleet(prompts, 6, slots=2)
    _assert_all_equal(got, want, "slow replica:")
    st = fleet.last_stats["fleet"]
    fr = st["faults"]
    assert st["served"] == len(prompts)
    assert fr["circuit_open"] >= 1
    assert fr["replica_down"] == 0 and fr["killed"] == []
    assert fr["degraded"] is False          # sick, not gone


def test_fleet_chaos_corrupt_handoff_retries_from_prefill():
    """The disaggregated transfer's integrity leg: a corrupted
    prefill→decode payload fails its crc (``paging.transfer_crc``), is
    classified RETRYABLE, re-runs the prefill, and the decode output
    still bit-matches — never silent garbage in a decode pool."""
    cfg, params, prompts = _setup()
    want = _solo(params, prompts, 5, cfg)
    profile = FleetFaultProfile(
        [FleetFault("corrupt_handoff", target=0, nth=2)], seed=3)
    fleet = make_fleet(params, cfg, max_len=16, replicas=2, kv_block=4,
                       share_prefix=True, disaggregate=True,
                       prefill_workers=1, faults=profile, steal=False)
    got = fleet(prompts, 5, slots=2)
    _assert_all_equal(got, want, "corrupt handoff:")
    st = fleet.last_stats["fleet"]
    fr = st["faults"]
    assert st["served"] == len(prompts)
    assert fr["handoff_retries"] == 1
    assert fr["replica_down"] == 0 and fr["redriven"] == 0
    # pools drained on both sides of the wire
    for rs in fleet.last_stats["replica_stats"]:
        assert rs["kv"]["in_use"] == 0


def test_fleet_chaos_shed_set_deterministic_under_capacity_schedule():
    """Degraded-mode admission: with deadlines armed and a kill in the
    schedule, the shed set is a pure function of (trace, capacity
    schedule) — two independently built fleets with the same (seed,
    profile) shed the SAME requests, unshed requests complete
    solo-exact, and shed positions return None."""
    cfg, params, prompts = _setup()
    n = len(prompts)
    arrivals = poisson_trace(500.0, n, seed=4)
    budgets = [6] * n
    deadlines = slo_deadlines(budgets, seed=5, base_s=0.2,
                              per_token_s=0.02, jitter=0.2)
    kill_at = fault_times(arrivals, 1, seed=6, lo=0.4, hi=0.6)[0]
    want = _solo(params, prompts, 6, cfg)

    def run():
        profile = FleetFaultProfile(
            [FleetFault("kill_replica", target=None, at_s=kill_at)],
            seed=8)
        fleet = make_fleet(params, cfg, max_len=16, replicas=2,
                           kv_block=4, est_token_s=0.02,
                           faults=profile, steal=False)
        got = fleet(prompts, budgets, slots=2, arrivals=arrivals,
                    deadlines=deadlines)
        return got, fleet.last_stats["fleet"]

    got_a, st_a = run()
    got_b, st_b = run()
    assert st_a["shed_requests"] == st_b["shed_requests"]
    # the degraded virtual clock actually bit: the N-replica capacity
    # minus the scheduled victim sheds a strict, non-total subset
    assert 0 < st_a["shed"] < n, st_a
    for req in range(n):
        if req in st_a["shed_requests"]:
            assert got_a[req] is None and got_b[req] is None
        else:
            assert jnp.array_equal(got_a[req], want[req]), req
            assert jnp.array_equal(got_b[req], want[req]), req
    assert st_a["served"] + st_a["shed"] == n


def test_fleet_fault_profile_validation_is_loud():
    """Schedule mistakes are build-time errors, never mid-run
    surprises: bad kinds/params, role mismatches, out-of-range and
    doubly-scheduled targets, and schedules that would remove a whole
    role (the fleet must always keep a redrive target)."""
    cfg, params, _ = _setup()
    with pytest.raises(ValueError, match="unknown fault kind"):
        FleetFault("explode")
    with pytest.raises(ValueError, match="stall_s"):
        FleetFault("slow_replica")
    with pytest.raises(ValueError, match="waves"):
        FleetFault("slow_replica", stall_s=0.1, waves=0)
    with pytest.raises(ValueError, match="nth"):
        FleetFault("corrupt_handoff", nth=0)
    with pytest.raises(ValueError, match="at_s"):
        FleetFault("kill_replica", at_s=-1.0)
    with pytest.raises(ValueError, match="target"):
        FleetFault("kill_replica", target=-1)
    with pytest.raises(ValueError, match="FleetFault"):
        FleetFaultProfile(["kill_replica"])
    with pytest.raises(ValueError, match="FleetFaultProfile"):
        make_fleet(params, cfg, max_len=16, replicas=2, faults=object())
    with pytest.raises(ValueError, match="health_timeout_s"):
        make_fleet(params, cfg, max_len=16, replicas=2,
                   health_timeout_s=0.0)
    with pytest.raises(ValueError, match="quarantine_polls"):
        make_fleet(params, cfg, max_len=16, replicas=2,
                   quarantine_polls=0)
    # role/shape validation happens at build time, against THIS fleet
    with pytest.raises(ValueError, match="disaggregate=True"):
        make_fleet(params, cfg, max_len=16, replicas=2,
                   faults=FleetFaultProfile(
                       [FleetFault("kill_prefill", target=0)]))
    with pytest.raises(ValueError, match="only 2"):
        make_fleet(params, cfg, max_len=16, replicas=2,
                   faults=FleetFaultProfile(
                       [FleetFault("kill_replica", target=5)]))
    with pytest.raises(ValueError, match="already scheduled"):
        make_fleet(params, cfg, max_len=16, replicas=2,
                   faults=FleetFaultProfile(
                       [FleetFault("kill_replica", target=0),
                        FleetFault("drain_replica", target=0)]))
    with pytest.raises(ValueError, match="survivor"):
        make_fleet(params, cfg, max_len=16, replicas=2,
                   faults=FleetFaultProfile(
                       [FleetFault("kill_replica", target=0),
                        FleetFault("kill_replica", target=1)]))
    with pytest.raises(ValueError, match="surviving prefill"):
        make_fleet(params, cfg, max_len=16, replicas=2,
                   disaggregate=True,
                   faults=FleetFaultProfile(
                       [FleetFault("kill_prefill", target=0)]))
    with pytest.raises(ValueError, match="duplicate slow_replica"):
        FleetFaultProfile(
            [FleetFault("slow_replica", target=0, stall_s=0.1),
             FleetFault("slow_replica", target=0, stall_s=0.2)]
        ).resolve(2, 0)


def test_fleet_fault_profile_seeded_resolution_replays():
    """``target=None`` draws from ONE string-seeded stream in spec
    order: identical (seed, faults) resolve to the identical schedule
    (subprocess-deterministic like every generator in utils/traffic),
    different seeds may differ, and every draw happens whether or not
    the spec pinned its target (stream position is spec-order only)."""
    faults = [FleetFault("kill_replica", at_s=0.1),
              FleetFault("slow_replica", at_s=0.2, stall_s=0.05)]
    a = FleetFaultProfile(faults, seed="chaos").resolve(4, 0)
    b = FleetFaultProfile(faults, seed="chaos").resolve(4, 0)
    assert a == b
    assert list(a["kills_dec"]) and list(a["slow_dec"])
    # pinning an EARLIER spec's target must not shift a LATER spec's
    # seeded draw (one draw per spec, whatever the targeting)
    kill_t = list(a["kills_dec"])[0]
    pinned = FleetFaultProfile(
        [FleetFault("kill_replica", target=kill_t, at_s=0.1),
         FleetFault("slow_replica", at_s=0.2, stall_s=0.05)],
        seed="chaos").resolve(4, 0)
    assert pinned["slow_dec"] == a["slow_dec"]


@pytest.mark.slow
def test_fleet_chaos_kill_matrix():
    """The seeded kill matrix (slow; one case stays tier-1): seeds ×
    kill times × colocated/disaggregated topologies, every cell
    asserting the full gate — all requests served, solo-bit-exact,
    exactly one replica down, nothing lost or duplicated (the fleet
    raises on either)."""
    cfg, params, prompts = _setup()
    want = _solo(params, prompts, 5, cfg)
    cases = []
    for seed in (0, 1, 2):
        for frac in (0.1, 0.5):
            cases.append(("colocated", seed, frac, "kill_replica"))
    cases += [("disaggregated", 0, 0.3, "kill_replica"),
              ("disaggregated", 1, 0.3, "kill_prefill")]
    for mode, seed, frac, kind in cases:
        label = f"{mode}/seed={seed}/frac={frac}/{kind}"
        at_s = 0.02 + frac * 0.3
        profile = FleetFaultProfile(
            [FleetFault(kind, target=None, at_s=at_s)], seed=seed)
        if mode == "colocated":
            fleet = make_fleet(params, cfg, max_len=16, replicas=3,
                               kv_block=4, faults=profile, steal=True,
                               steal_poll_s=0.001)
        else:
            fleet = make_fleet(params, cfg, max_len=16, replicas=4,
                               kv_block=4, share_prefix=True,
                               disaggregate=True, prefill_workers=2,
                               faults=profile, steal=False)
        got = fleet(prompts, 5, slots=2)
        _assert_all_equal(got, want, label)
        st = fleet.last_stats["fleet"]
        fr = st["faults"]
        assert st["served"] == len(prompts), label
        assert fr["replica_down"] == 1, (label, fr)
        assert fr["degraded"] is True, label
        role = "prefill" if kind == "kill_prefill" else \
            ("decode" if mode == "disaggregated" else "replica")
        assert fr["killed"][0].startswith(role), (label, fr)


def test_fleet_monitor_failure_propagates_and_joins_workers(
        tmp_path, monkeypatch):
    """The steal/monitor-loop bugfix (ISSUE 13 satellite): an exception
    anywhere in the router's monitor loop — here, the queue-depth gauge
    backend exploding — must CLOSE every replica queue, JOIN every
    worker thread, and propagate to the caller. The PR 12 loop let it
    escape before closure, stranding replicas polling open queues
    forever."""
    import threading

    from nvidia_terraform_modules_tpu.telemetry import Registry
    from nvidia_terraform_modules_tpu.telemetry.core import Gauge

    cfg, params, prompts = _setup()
    reg = Registry(str(tmp_path))
    fleet = make_fleet(params, cfg, max_len=16, replicas=2, kv_block=4,
                       telemetry=reg, steal=True, steal_poll_s=0.001)
    orig = Gauge.set

    def boom(self, v):
        # only the ROUTER's monitor loop runs outside fleet-* threads;
        # the engines' own gauge writes must keep working so the
        # failure is unambiguously the monitor's
        if not threading.current_thread().name.startswith("fleet-"):
            raise RuntimeError("telemetry backend exploded")
        return orig(self, v)

    monkeypatch.setattr(Gauge, "set", boom)
    with pytest.raises(RuntimeError, match="telemetry backend exploded"):
        fleet(prompts, 5, slots=2)
    monkeypatch.setattr(Gauge, "set", orig)
    # every replica thread was joined on the failure path — nothing
    # is left polling a queue that will never close
    stranded = [t.name for t in threading.enumerate()
                if t.name.startswith("fleet-")]
    assert stranded == []


def test_fleet_chaos_kill_redrive_under_lockwatch_tier1():
    """ISSUE 16 satellite: the seeded kill+redrive chaos case runs with
    the runtime lock-order watchdog armed — every lock the fleet stack
    creates is instrumented, and the run must produce ZERO ordering
    cycles and ZERO lock-held blocking polls (time.sleep while holding
    any runtime lock), on top of staying bit-exact through the kill."""
    from nvidia_terraform_modules_tpu.analysis import lockwatch

    cfg, params, prompts = _setup()
    want = _solo(params, prompts, 6, cfg)
    victim = _victim(prompts, 3)
    profile = FleetFaultProfile(
        [FleetFault("kill_replica", target=victim, at_s=0.05)], seed=0)
    with lockwatch.armed() as watch:
        fleet = make_fleet(params, cfg, max_len=16, replicas=3,
                           kv_block=4, faults=profile, steal=False)
        got = fleet(prompts, 6, slots=2)
    _assert_all_equal(got, want, "under lockwatch:")
    assert fleet.last_stats["fleet"]["faults"]["replica_down"] == 1

    pkg = "nvidia_terraform_modules_tpu/"
    # the watchdog really observed the runtime's locks, not a no-op arm
    runtime_locks = [n for n in watch.lock_names if n.startswith(pkg)]
    assert runtime_locks, "no runtime locks observed under the watchdog"
    assert watch.acquisitions > 0
    # zero ordering cycles among runtime locks (jax/stdlib internals
    # created inside the window are outside the contract)
    cycles = [c for c in watch.cycles()
              if any(n.startswith(pkg) for n in c)]
    assert cycles == [], f"lock-order cycles under chaos: {cycles}"
    # zero blocking polls while holding any runtime lock
    held = [h for h in watch.held_sleeps if h[0].startswith(pkg)]
    assert held == [], f"time.sleep while holding a lock: {held}"
