# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""Regression tests for code-review findings on the tfsim front-end."""

import textwrap

from nvidia_terraform_modules_tpu.tfsim import simulate_plan
from nvidia_terraform_modules_tpu.tfsim.eval import Scope, evaluate
from nvidia_terraform_modules_tpu.tfsim.functions import FUNCTIONS
from nvidia_terraform_modules_tpu.tfsim.parser import parse_expression
from nvidia_terraform_modules_tpu.parallel.mesh import plan_mesh


def test_ceil_negative():
    assert FUNCTIONS["ceil"](-2.5) == -2
    assert FUNCTIONS["ceil"](2.5) == 3
    assert FUNCTIONS["floor"](-2.5) == -3


def test_trimsuffix_empty_suffix():
    assert FUNCTIONS["trimsuffix"]("abc", "") == "abc"
    assert FUNCTIONS["trimsuffix"]("abc", "c") == "ab"


def test_nested_string_brace_in_interpolation():
    e = parse_expression('"${replace(var.a, "}", "y")}"')
    v = evaluate(e, Scope(variables={"a": "x}z"}))
    assert v == "xyz"


def test_nested_string_with_interp_inside_interp():
    e = parse_expression('"${join("-", ["a", "${var.b}"])}"')
    assert evaluate(e, Scope(variables={"b": "c"})) == "a-c"


def test_plan_mesh_sp_aware_default_tp():
    plan = plan_mesh(4, sp=2)
    assert plan.shape == (1, 2, 2)


def test_module_call_count_zero_plans_nothing(tmp_path):
    child = tmp_path / "child"
    child.mkdir()
    (child / "main.tf").write_text(textwrap.dedent('''
        variable "name" {
          description = "n"
          type        = string
          default     = "x"
        }
        resource "null_resource" "r" {
          triggers = { n = var.name }
        }
        output "marker" {
          description = "m"
          value       = null_resource.r.id
        }
    '''))
    root = tmp_path / "root"
    root.mkdir()
    (root / "main.tf").write_text(textwrap.dedent('''
        variable "enabled" {
          description = "flag"
          type        = bool
          default     = false
        }
        module "maybe" {
          source = "../child"
          count  = var.enabled ? 1 : 0
          name   = "demo"
        }
    '''))
    off = simulate_plan(str(root), {"enabled": False})
    assert off.instances == {}
    on = simulate_plan(str(root), {"enabled": True})
    assert "module.maybe[0].null_resource.r" in on.instances


def test_module_call_foreach(tmp_path):
    child = tmp_path / "c"
    child.mkdir()
    (child / "main.tf").write_text(textwrap.dedent('''
        variable "size" {
          description = "s"
          type        = number
        }
        resource "null_resource" "n" {
          triggers = { s = var.size }
        }
    '''))
    root = tmp_path / "r"
    root.mkdir()
    (root / "main.tf").write_text(textwrap.dedent('''
        module "slices" {
          source   = "../c"
          for_each = { small = 1, big = 8 }
          size     = each.value
        }
    '''))
    plan = simulate_plan(str(root))
    assert 'module.slices["small"].null_resource.n' in plan.instances
    assert 'module.slices["big"].null_resource.n' in plan.instances
    assert plan.instances['module.slices["big"].null_resource.n'].attrs[
        "triggers"]["s"] == 8


def test_optional_default_applies_to_explicit_null(tmp_path):
    (tmp_path / "main.tf").write_text('''
variable "x" {
  description = "obj"
  type = object({ a = optional(bool, true) })
  default = {}
}
resource "null_resource" "r" {
  count = var.x.a ? 1 : 0
}
''')
    on = simulate_plan(str(tmp_path), {"x": {"a": None}})
    assert "null_resource.r[0]" in on.instances  # null takes the default
    off = simulate_plan(str(tmp_path), {"x": {"a": False}})
    assert off.instances == {}


def test_lazy_local_reads_resource_attr(tmp_path):
    """A local referencing a resource must see its planned value (lazy eval),
    and consumers of the local must be ordered after that resource."""
    (tmp_path / "main.tf").write_text('''
locals {
  ns = null_resource.first.triggers.name
}
resource "null_resource" "first" {
  triggers = { name = "alpha" }
}
resource "null_resource" "second" {
  triggers = { ns = local.ns }
}
''')
    plan = simulate_plan(str(tmp_path))
    assert plan.instances["null_resource.second"].attrs["triggers"]["ns"] == "alpha"
    assert plan.order.index("null_resource.first") < plan.order.index(
        "null_resource.second")
