# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
# Cluster-auth wiring: kubernetes + helm providers against the cluster
# created in this same apply (token auth, no local-exec, no kubeconfig
# mutation — the reference's cleanest of three bootstrap variants, adopted
# per SURVEY.md §7 / §3.3).

data "google_client_config" "current" {}

locals {
  cluster_endpoint = "https://${google_container_cluster.this.endpoint}"
  cluster_ca       = base64decode(google_container_cluster.this.master_auth[0].cluster_ca_certificate)
}

provider "kubernetes" {
  host                   = local.cluster_endpoint
  token                  = data.google_client_config.current.access_token
  cluster_ca_certificate = local.cluster_ca
}

provider "helm" {
  kubernetes {
    host                   = local.cluster_endpoint
    token                  = data.google_client_config.current.access_token
    cluster_ca_certificate = local.cluster_ca
  }
}
