# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""Static lock-acquisition-order graph + cycle detection.

Builds the may-acquire graph over every scanned module: nodes are lock
creation sites (``file::Class.attr`` for ``self.x = threading.Lock()``,
``file::NAME`` for module-level locks), and an edge A → B means some
code path acquires B while holding A — either a directly nested
``with``, or a call made under A into a function whose transitive
may-acquire set contains B (an interprocedural fixpoint over the local
call graph). A cycle in that graph is a potential deadlock: two threads
entering the cycle from different nodes block each other forever.

Resolution is best-effort and deliberately conservative about
ambiguity: ``self.m()`` resolves within the class, bare names resolve
within the module, and ``obj.m()`` resolves across classes only when
exactly one scanned class defines ``m`` — an ambiguous method name
contributes no edge rather than a spurious cycle.

The runtime twin is :mod:`.lockwatch`, which observes the ACTUAL
acquisition order under chaos tests; this module predicts it from
source.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Iterator, Optional

from .graftlint import rule
from .pysrc import PyContext, self_attr, walk_scope

_LOCK_FACTORIES = {"threading.Lock", "threading.RLock"}


@dataclasses.dataclass
class LockGraph:
    nodes: set
    # (holder, acquired) -> "file:line" of the first site creating it
    edges: dict

    def cycles(self) -> list[list[str]]:
        """Dependency cycles as node paths closed on the start node
        (``[A, B, A]``), deterministically ordered. Computed per
        strongly-connected component; within an SCC every node pair is
        mutually reachable, so one canonical cycle through the
        component (plus self-loops) is complete for the fail/pass
        question the gate asks."""
        adj: dict = {}
        for a, b in self.edges:
            adj.setdefault(a, set()).add(b)
        out = []
        for comp in _sccs(adj):
            if len(comp) == 1:
                n = comp[0]
                if n in adj.get(n, ()):
                    out.append([n, n])
                continue
            comp = sorted(comp)
            # canonical walk: from the smallest node, greedily step to
            # the smallest in-component unvisited successor (falling
            # back to the start) until closure
            path, cur = [comp[0]], comp[0]
            while True:
                succ = [b for b in adj.get(cur, ()) if b in comp
                        and b not in path[1:] and b != cur]
                nxt = min(succ) if succ else path[0]
                path.append(nxt)
                if nxt == path[0]:
                    break
                cur = nxt
            out.append(path)
        return sorted(out)


def _sccs(adj: dict) -> list[list]:
    """Tarjan's strongly-connected components, iterative."""
    index: dict = {}
    low: dict = {}
    on: set = set()
    stack: list = []
    comps: list[list] = []
    counter = [0]

    def strongconnect(root):
        work = [(root, iter(sorted(adj.get(root, ()))))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on.add(root)
        while work:
            v, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on.add(w)
                    work.append((w, iter(sorted(adj.get(w, ())))))
                    advanced = True
                    break
                elif w in on:
                    low[v] = min(low[v], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                u = work[-1][0]
                low[u] = min(low[u], low[v])
            if low[v] == index[v]:
                comp = []
                while True:
                    w = stack.pop()
                    on.discard(w)
                    comp.append(w)
                    if w == v:
                        break
                comps.append(comp)

    nodes = set(adj)
    for tos in adj.values():
        nodes.update(tos)
    for n in sorted(nodes):
        if n not in index:
            strongconnect(n)
    return comps


# ------------------------------------------------------------ collection

@dataclasses.dataclass
class _FnInfo:
    key: tuple                    # (fname, class-or-None, name)
    acquires: set                 # lock nodes taken directly
    # events: (holder-or-None, callee-candidate-keys, direct-lock-node,
    #          "file:line") — a `with` acquisition has direct set and no
    # candidates; a call has candidates and direct None
    events: list


def _collect(ctx: PyContext) -> dict[tuple, _FnInfo]:
    fns: dict[tuple, _FnInfo] = {}
    # registration pass over ALL files first, so obj.m() calls in file A
    # can resolve to the unique class defining m in file B
    method_owners: dict[str, set] = {}
    per_file_module_locks: dict[str, dict] = {}
    per_file_class_locks: dict[str, dict] = {}

    for fname, tree in ctx.trees():
        module_locks: dict = {}
        for n in tree.body:
            if isinstance(n, ast.Assign) and isinstance(n.value, ast.Call) \
                    and ctx.resolve(fname, n.value.func) in _LOCK_FACTORIES:
                for t in n.targets:
                    if isinstance(t, ast.Name):
                        module_locks[t.id] = f"{fname}::{t.id}"
        per_file_module_locks[fname] = module_locks

        class_locks: dict[str, dict] = {}
        for n in tree.body:
            if not isinstance(n, ast.ClassDef):
                continue
            lock_map: dict = {}
            cond_alias: dict = {}
            for m in n.body:
                if not isinstance(m, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    continue
                method_owners.setdefault(m.name, set()).add((fname, n.name))
                for s in walk_scope(m):
                    if isinstance(s, ast.Assign) and \
                            isinstance(s.value, ast.Call):
                        r = ctx.resolve(fname, s.value.func)
                        for t in s.targets:
                            a = self_attr(t)
                            if a is None:
                                continue
                            if r in _LOCK_FACTORIES:
                                lock_map[a] = f"{fname}::{n.name}.{a}"
                            elif r == "threading.Condition":
                                # Condition(self._lock) IS that lock; a
                                # bare Condition() is its own node
                                arg = s.value.args[0] if s.value.args \
                                    else None
                                inner = self_attr(arg) \
                                    if arg is not None else None
                                cond_alias[a] = inner or a
            for a, target in cond_alias.items():
                lock_map[a] = lock_map.get(
                    target, f"{fname}::{n.name}.{a}")
            class_locks[n.name] = lock_map
        per_file_class_locks[fname] = class_locks

    # summary pass: one _FnInfo per function/method
    for fname, tree in ctx.trees():
        module_locks = per_file_module_locks[fname]

        def scan_function(fn, cls_name, lock_map):
            key = (fname, cls_name, fn.name)
            info = fns.setdefault(key, _FnInfo(key, set(), []))

            def lock_node(expr) -> Optional[str]:
                a = self_attr(expr)
                if a is not None:
                    return lock_map.get(a)
                if isinstance(expr, ast.Name):
                    return module_locks.get(expr.id)
                return None

            def visit(node, held):
                for child in ast.iter_child_nodes(node):
                    if isinstance(child, (ast.FunctionDef,
                                          ast.AsyncFunctionDef,
                                          ast.ClassDef, ast.Lambda)):
                        continue
                    h = held
                    if isinstance(child, ast.With):
                        for item in child.items:
                            ln = lock_node(item.context_expr)
                            if ln is not None:
                                info.acquires.add(ln)
                                info.events.append(
                                    (h[-1] if h else None, (), ln,
                                     f"{fname}:{child.lineno}"))
                                h = h + [ln]
                    if isinstance(child, ast.Call):
                        cands = ()
                        f = child.func
                        a = self_attr(f)
                        if a is not None and cls_name is not None:
                            cands = ((fname, cls_name, a),)
                        elif isinstance(f, ast.Name):
                            cands = ((fname, None, f.id),)
                        elif isinstance(f, ast.Attribute):
                            owners = method_owners.get(f.attr, ())
                            if len(owners) == 1:
                                (ofile, ocls), = owners
                                cands = ((ofile, ocls, f.attr),)
                        if cands:
                            info.events.append(
                                (h[-1] if h else None, cands, None,
                                 f"{fname}:{child.lineno}"))
                    visit(child, h)

            visit(fn, [])

        for n in tree.body:
            if isinstance(n, ast.ClassDef):
                for m in n.body:
                    if isinstance(m, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                        scan_function(
                            m, n.name,
                            per_file_class_locks[fname][n.name])
            elif isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scan_function(n, None, {})

    return fns


def build_lock_graph(ctx: PyContext) -> LockGraph:
    fns = _collect(ctx)

    # interprocedural fixpoint: may_acquire(fn) = direct ∪ callees'
    may: dict[tuple, set] = {k: set(i.acquires) for k, i in fns.items()}
    changed = True
    while changed:
        changed = False
        for key, info in fns.items():
            for _, cands, _, _ in info.events:
                for c in cands:
                    if c in may and not may[c] <= may[key]:
                        may[key] |= may[c]
                        changed = True

    nodes: set = set()
    edges: dict = {}
    for info in fns.values():
        nodes |= info.acquires
        for holder, cands, direct, where in info.events:
            if direct is not None:
                if holder is not None and holder != direct:
                    edges.setdefault((holder, direct), where)
                continue
            if holder is None:
                continue
            for c in cands:
                for acquired in may.get(c, ()):
                    if acquired != holder:
                        edges.setdefault((holder, acquired), where)
    return LockGraph(nodes=nodes, edges=edges)


@rule("graft-lock-cycle", severity="error", family="locking",
      summary="the static lock-acquisition-order graph must be acyclic")
def check_lock_cycles(ctx: PyContext) -> Iterator[tuple[str, str]]:
    g = build_lock_graph(ctx)
    for cyc in g.cycles():
        # anchor the finding at the first edge of the cycle
        where = g.edges.get((cyc[0], cyc[1])) or "lockgraph:0"
        path = " -> ".join(c.split("::", 1)[-1] for c in cyc)
        files = sorted({c.split("::", 1)[0] for c in cyc})
        yield (where,
               f"lock-order cycle {path} (locks created in "
               f"{', '.join(files)}) — two threads entering from "
               f"different ends deadlock; impose one global "
               f"acquisition order")
