# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""``tfsim chaos``: sweep fault seeds over a module, assert convergence.

For each seed the harness runs the full operator playbook in a throwaway
sandbox, end-to-end through the real CLI (the same code paths a human
drives), and asserts the convergence invariants the recovery story
promises:

1. **apply** with the fault profile (seeded). A clean run must already
   match the planned state.
2. If the run was interrupted: break a leftover crash lock by ID
   (``force-unlock``), push a leftover ``errored.tfstate`` back
   (``state push``), then **re-apply fault-free** — which must exit 0
   and land exactly the planned state: no orphans, no duplicate
   creates, no lingering taint.
3. From the *interrupted* state, a fault-free ``apply -destroy`` must
   leave empty state — interruption never wedges teardown.

Any violated invariant fails the sweep (exit 1) with the seed's
transcript, making ``tfsim chaos -seeds 8 MODULE`` a standing CI gate
for the module's crash-consistency.
"""

from __future__ import annotations

import contextlib
import dataclasses
import io
import json
import os
import sys
import tempfile

from ..plan import simulate_plan
from ..state import State, apply_plan
from .profile import DEFAULT_CHAOS_PROFILE, load_profile


@dataclasses.dataclass
class SeedResult:
    seed: int
    interrupted: bool = False
    crashed: bool = False
    errored_state: bool = False
    recovery: list = dataclasses.field(default_factory=list)  # steps taken
    violations: list = dataclasses.field(default_factory=list)
    transcript: str = ""

    @property
    def ok(self) -> bool:
        return not self.violations

    def summary(self) -> str:
        if not self.interrupted:
            how = "clean apply"
        else:
            bits = ["interrupted"]
            if self.crashed:
                bits.append("crash")
            if self.errored_state:
                bits.append("errored.tfstate")
            how = "+".join(bits)
        verdict = "converged" if self.ok else \
            "; ".join(self.violations)
        tail = f" ({', '.join(self.recovery)})" if self.recovery else ""
        return f"seed {self.seed}: {how} — {verdict}{tail}"


def _run_cli(cli, argv: list[str], stdin_text: str | None = None
             ) -> tuple[int, str]:
    """Run one CLI invocation, capturing stdout+stderr (and feeding
    stdin for ``state push``)."""
    buf = io.StringIO()
    old_stdin = sys.stdin
    try:
        if stdin_text is not None:
            sys.stdin = io.StringIO(stdin_text)
        with contextlib.redirect_stdout(buf), \
                contextlib.redirect_stderr(buf):
            rc = cli(argv)
    finally:
        sys.stdin = old_stdin
    return rc, buf.getvalue()


def _load(path: str) -> State | None:
    if not os.path.exists(path):
        return None
    with open(path) as fh:
        return State.from_json(fh.read())


def _check_converged(res: SeedResult, state: State | None,
                     expected: State) -> None:
    if state is None:
        res.violations.append("no state after recovery")
        return
    if state.resources != expected.resources:
        missing = sorted(set(expected.resources) - set(state.resources))
        extra = sorted(set(state.resources) - set(expected.resources))
        drift = sorted(a for a in set(state.resources) &
                       set(expected.resources)
                       if state.resources[a] != expected.resources[a])
        res.violations.append(
            f"state does not match plan after re-apply "
            f"(missing={missing} extra={extra} drifted={drift})")
    if state.tainted:
        res.violations.append(
            f"taint survived convergence: {sorted(state.tainted)}")
    if state.outputs != expected.outputs:
        res.violations.append("outputs drifted from the planned outputs")


def run_one_seed(cli, module_dir: str, var_argv: list[str],
                 profile_path: str, seed: int,
                 expected: State) -> SeedResult:
    """The full interrupt-recover-converge-destroy cycle for one seed."""
    from ..locking import lock_path, read_holder

    res = SeedResult(seed=seed)
    lines: list[str] = []
    with tempfile.TemporaryDirectory(prefix=f"tfsim-chaos-{seed}-") as tmp:
        spath = os.path.join(tmp, "terraform.tfstate.json")
        errored = os.path.join(tmp, "errored.tfstate")

        rc, out = _run_cli(cli, ["apply", module_dir, *var_argv,
                                 "-state", spath,
                                 "-fault-profile", profile_path,
                                 "-fault-seed", str(seed)])
        lines.append(out)
        res.interrupted = rc != 0
        if rc not in (0, 1):
            res.violations.append(f"faulted apply exited {rc} (usage error)")

        # ---- recovery playbook (only after an interruption) ----------
        if os.path.exists(lock_path(spath)):
            res.crashed = True
            holder = read_holder(spath)
            rc, out = _run_cli(cli, ["force-unlock", holder.id,
                                     "-state", spath])
            lines.append(out)
            if rc != 0:
                res.violations.append(
                    "force-unlock by ID failed on a crash-left lock")
            res.recovery.append("lock broken by ID")

        if os.path.exists(errored):
            res.errored_state = True
            with open(errored) as fh:
                text = fh.read()
            rc, out = _run_cli(cli, ["state", "push", "-state", spath],
                               stdin_text=text)
            lines.append(out)
            if rc != 0:
                res.violations.append("state push of errored.tfstate failed")
            res.recovery.append("errored.tfstate pushed")

        # snapshot the interrupted state for the destroy invariant —
        # AFTER the lock break (teardown needs the lock too) and AFTER
        # the errored.tfstate push: for a state-write fault the pushed
        # file IS the interrupted state, and snapshotting earlier would
        # silently skip the invariant for exactly that failure class
        interrupted_json = None
        if res.interrupted and os.path.exists(spath):
            with open(spath) as fh:
                interrupted_json = fh.read()

        if res.interrupted:
            rc, out = _run_cli(cli, ["apply", module_dir, *var_argv,
                                     "-state", spath])
            lines.append(out)
            if rc != 0:
                res.violations.append(f"fault-free re-apply exited {rc}")
            res.recovery.append("re-applied")

        _check_converged(res, _load(spath), expected)

        # ---- destroy-after-interruption invariant --------------------
        if interrupted_json is not None:
            snap = State.from_json(interrupted_json)
            if snap.resources:
                dpath = os.path.join(tmp, "interrupted.tfstate.json")
                with open(dpath, "w") as fh:
                    fh.write(interrupted_json)
                rc, out = _run_cli(cli, ["apply", module_dir, *var_argv,
                                         "-state", dpath, "-destroy"])
                lines.append(out)
                final = _load(dpath)
                if rc != 0:
                    res.violations.append(
                        f"destroy from interrupted state exited {rc}")
                elif final is None or final.resources:
                    left = sorted(final.resources) if final else "<none>"
                    res.violations.append(
                        f"destroy from interrupted state left "
                        f"resources: {left}")
                else:
                    res.recovery.append("destroy from interruption clean")
    res.transcript = "".join(lines)
    return res


def run_chaos(cli, module_dir: str, tfvars: dict, var_argv: list[str],
              seeds: int, profile_path: str | None = None,
              log=None) -> list[SeedResult]:
    """Sweep ``seeds`` fault seeds over ``module_dir``; returns one
    :class:`SeedResult` per seed. ``cli`` is the tfsim ``main`` callable
    (injected to avoid an import cycle); ``var_argv`` is the raw
    ``-var``/``-var-file`` argv to forward to each CLI run, ``tfvars``
    the same variables resolved, for computing the expected state."""
    plan = simulate_plan(module_dir, tfvars)
    expected = apply_plan(plan, None)

    if profile_path is not None:
        # fail fast on a bad profile — otherwise every seeded apply dies
        # on it and the sweep misreads the failures as interruptions
        load_profile(profile_path)
    own_profile = None
    if profile_path is None:
        own_profile = tempfile.NamedTemporaryFile(
            "w", suffix=".fault.json", delete=False)
        json.dump(DEFAULT_CHAOS_PROFILE, own_profile)
        own_profile.close()
        profile_path = own_profile.name
    try:
        results = []
        for seed in range(seeds):
            res = run_one_seed(cli, module_dir, var_argv, profile_path,
                               seed, expected)
            if log:
                log(res.summary())
            results.append(res)
        return results
    finally:
        if own_profile is not None:
            os.unlink(own_profile.name)
