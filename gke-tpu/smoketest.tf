# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
# The JAX psum smoke-test Job: `terraform apply` is the integration test.
#
# North star (BASELINE.json): after apply, a Job runs jax.devices() and a
# psum all-reduce over the whole slice, and the apply only succeeds if it
# passes (wait_for_completion). This replaces the reference's manual
# runbook validation ("wait ~5 min, kubectl get pods" —
# /root/reference/gke/README.md:50) with an automated gate, and replaces its
# plan-time node gate (/root/reference/eks/main.tf:186, a two-phase-apply
# wart) with real apply-time readiness.
#
# Multi-host choreography (no reference precedent): an Indexed Job with
# completions = hosts-per-slice, one pod per TPU host; a headless Service
# gives pod 0 a stable DNS name that every pod uses as the
# jax.distributed.initialize coordinator; the TPU node selectors pin pods to
# the target slice and `google.com/tpu` requests claim every chip on each
# host. The pod payload is the single-file bundle of this repo's
# nvidia_terraform_modules_tpu.smoketest (scripts/tpu_smoketest.py), shipped
# via ConfigMap so any JAX-capable image works unmodified.
#
# Multi-slice (smoketest.multislice = true): one indexed Job PER slice, all
# joined into a single jax.distributed world — process ids are offset per
# slice (TPU_SMOKETEST_PROCESS_BASE), every pod dials slice 0's pod 0, and
# MEGASCALE_* env wires libtpu's DCN transport. The payload then also runs a
# cross-slice psum, proving the DCN path the way the single-slice test
# proves ICI.

locals {
  smoketest_enabled = local.tpu_enabled && var.smoketest.enabled
  # target resolution: the named key if declared; otherwise, when exactly
  # one slice exists, that slice (so renaming the sole slice never breaks
  # the default target). A genuine mismatch against a multi-slice fleet
  # must fail the PLAN with a message naming the bad key — the synthetic
  # index below carries it into the error.
  smoke_target = (
    contains(keys(local.tpu_slice), var.smoketest.target_slice)
    ? var.smoketest.target_slice
    : (
      length(local.tpu_slice) == 1
      ? one(keys(local.tpu_slice))
      : "smoketest.target_slice '${var.smoketest.target_slice}' is not a declared tpu_slices key"
    )
  )
  smoke_slices = (
    local.smoketest_enabled
    ? (
      var.smoketest.multislice
      ? local.tpu_slice
      : { (local.smoke_target) = local.tpu_slice[local.smoke_target] }
    )
    : {}
  )
  # deterministic slice order → process-id layout; lexicographic `<` below
  # matches sort()'s ordering
  smoke_slice_order = sort(keys(local.smoke_slices))
  smoke_total_hosts = sum(concat([0], [for s in values(local.smoke_slices) : s.hosts]))
  smoke_total_chips = sum(concat([0], [for s in values(local.smoke_slices) : s.chips]))
  smoke_process_base = {
    for name in local.smoke_slice_order :
    name => sum(concat([0], [
      for other in local.smoke_slice_order :
      local.smoke_slices[other].hosts if other < name
    ]))
  }
  smoke_slice_id = {
    for name in local.smoke_slice_order :
    name => length([for other in local.smoke_slice_order : other if other < name])
  }
  smoke_ns   = local.smoketest_enabled ? kubernetes_namespace_v1.tpu_runtime[0].metadata[0].name : var.tpu_runtime.namespace
  smoke_name = "${var.cluster_name}-tpu-smoketest"
  # Two distinct rendezvous planes, two ports: jax.distributed's coordinator
  # (gRPC, default 8476 — the payload appends it when the env value has no
  # port) and libtpu's MEGASCALE DCN transport bootstrap (8080, libtpu's
  # default). Both are declared on the headless Service and the container
  # for documentation/policy tooling; headless DNS resolves the pod A-record
  # either way, so the declarations are about intent, not reachability.
  smoke_coordinator_port = 8476
  smoke_megascale_port   = 8080
  # one budget for both gates: terraform's wait_for_completion timeout AND
  # the Job's own in-cluster deadline. Scales with WORLD size, not slice
  # size: every pod in every slice must schedule + pull the JAX image
  # before jax.distributed.initialize can return anywhere.
  smoke_deadline_s = (
    var.smoketest.timeout_seconds +
    var.smoketest.timeout_per_host_seconds * local.smoke_total_hosts
  )
  # jax.distributed coordinator: slice 0, pod 0 (indexed-Job hostname
  # "<job-name>-<index>" under the headless service's subdomain)
  smoke_coordinator = (
    length(local.smoke_slice_order) > 0
    ? "${local.smoke_name}-${local.smoke_slice_order[0]}-0.${local.smoke_name}.${local.smoke_ns}.svc"
    : ""
  )
}

# advisory, not provable at plan time (the claim is bring-your-own): a
# multi-host world mounting one PVC from several nodes needs ReadWriteMany
check "checkpoint_pvc_needs_rwx" {
  assert {
    condition = (
      var.smoketest.checkpoint_pvc == null || local.smoke_total_hosts <= 1
    )
    error_message = "smoketest.checkpoint_pvc is mounted by every smoke-test pod across ${local.smoke_total_hosts} hosts: the claim must be ReadWriteMany (e.g. Filestore CSI) — a ReadWriteOnce GCE-PD claim deadlocks all but the first pod."
  }
}

resource "kubernetes_config_map_v1" "smoketest_script" {
  count = local.smoketest_enabled ? 1 : 0

  metadata {
    name      = "${local.smoke_name}-script"
    namespace = local.smoke_ns
  }

  data = {
    "tpu_smoketest.py" = file("${path.module}/scripts/tpu_smoketest.py")
  }

  depends_on = [kubernetes_namespace_v1.tpu_runtime]
}

resource "kubernetes_service_v1" "smoketest_coordinator" {
  count = local.smoketest_enabled ? 1 : 0

  metadata {
    name      = local.smoke_name
    namespace = local.smoke_ns
  }

  spec {
    cluster_ip = "None" # headless: stable per-pod DNS for the coordinator
    selector = {
      # one service spans every slice's Job pods (multi-slice worlds share
      # the coordinator), so match the group label, not job-name
      "smoketest-group" = local.smoke_name
    }
    port {
      name = "coordinator"
      port = local.smoke_coordinator_port
    }
    port {
      name = "megascale"
      port = local.smoke_megascale_port
    }
  }

  depends_on = [kubernetes_namespace_v1.tpu_runtime]
}

resource "kubernetes_job_v1" "tpu_smoketest" {
  for_each = local.smoke_slices

  metadata {
    name      = "${local.smoke_name}-${each.key}"
    namespace = local.smoke_ns
    labels = {
      "app.kubernetes.io/part-of" = "tpu-terraform-modules"
    }
  }

  spec {
    completions     = each.value.hosts
    parallelism     = each.value.hosts
    completion_mode = "Indexed"
    # the in-cluster retry window must not outlive the apply gate: with the
    # disruption-exempt failure policy below, an unbounded Job on contested
    # spot capacity would keep recreating pods and claiming TPU quota long
    # after wait_for_completion has timed the apply out
    active_deadline_seconds = local.smoke_deadline_s
    # with resume enabled the Job must survive repeated spot preemptions —
    # one preemption fails ALL of a slice's pods at once (coordinator and
    # collective peers die together), so a small fixed budget would burn
    # out on the first event and the checkpoint would never be read
    backoff_limit = coalesce(
      var.smoketest.backoff_limit,
      var.smoketest.checkpoint_dir != null ? 10 : 2
    )

    # don't bill spot/maintenance evictions against the retry budget at
    # all: a DisruptionTarget pod failure is capacity churn, not a test
    # failure (kubernetes 1.26+ API surface, same as the certified GKE
    # channel in README.md's support matrix)
    dynamic "pod_failure_policy" {
      for_each = var.smoketest.checkpoint_dir != null ? [1] : []
      content {
        rule {
          action = "Ignore"
          on_pod_condition {
            status = "True"
            type   = "DisruptionTarget"
          }
        }
      }
    }

    template {
      metadata {
        labels = {
          "smoketest-group" = local.smoke_name
          "smoketest-slice" = each.key
        }
      }

      spec {
        subdomain      = local.smoke_name
        restart_policy = "Never"
        # preemption drain window: SIGTERM → this many seconds → SIGKILL.
        # The supervised loop uses TPU_SMOKETEST_GRACE_SECONDS (half of
        # it, wired below) to finish the in-flight step and commit an
        # emergency checkpoint; see "Preemption & resume runbook" in
        # README.md and the tpu-spot-no-grace lint rule.
        termination_grace_period_seconds = var.smoketest.grace_period_seconds

        node_selector = {
          "cloud.google.com/gke-tpu-accelerator" = each.value.node_selector
          "cloud.google.com/gke-tpu-topology"    = each.value.topology
        }

        toleration {
          key      = "google.com/tpu"
          operator = "Exists"
          effect   = "NoSchedule"
        }

        container {
          name    = "smoketest"
          image   = var.tpu_runtime.jax_image
          command = var.smoketest.command

          env {
            name  = "TPU_SMOKETEST_EXPECTED_DEVICES"
            value = tostring(local.smoke_total_chips)
          }
          env {
            name  = "TPU_SMOKETEST_LEVEL"
            value = var.smoketest.level
          }
          env {
            name  = "TPU_SMOKETEST_HOSTS"
            value = tostring(local.smoke_total_hosts)
          }
          env {
            name  = "TPU_SMOKETEST_PROCESS_BASE"
            value = tostring(local.smoke_process_base[each.key])
          }
          env {
            name  = "TPU_SMOKETEST_SLICES"
            value = tostring(length(local.smoke_slice_order))
          }
          env {
            name  = "TPU_SMOKETEST_COORDINATOR"
            value = local.smoke_coordinator
          }

          # spot-slice resume: preempted burn-in pods restart from their
          # last checkpoint instead of step 0, with an emergency-save
          # budget of half the pod's termination grace (the other half
          # is drain + teardown headroom)
          dynamic "env" {
            for_each = var.smoketest.checkpoint_dir != null ? {
              TPU_SMOKETEST_CHECKPOINT_DIR = var.smoketest.checkpoint_dir
              TPU_SMOKETEST_GRACE_SECONDS  = tostring(floor(var.smoketest.grace_period_seconds / 2))
            } : {}
            content {
              name  = env.key
              value = env.value
            }
          }

          # telemetry plane: the package runner exports trace.json /
          # metrics.prom / summary.txt here (README "Observability")
          dynamic "env" {
            for_each = var.smoketest.telemetry_dir != null ? {
              TPU_TELEMETRY_DIR = var.smoketest.telemetry_dir
            } : {}
            content {
              name  = env.key
              value = env.value
            }
          }

          # durable disk tail for the serving prefix CDN: the burn-in's
          # prefix_cdn_ok leg files prefix chains here and proves a
          # restarted fleet comes back warm (README "Prefix CDN runbook")
          dynamic "env" {
            for_each = var.smoketest.disk_spill_dir != null ? {
              TPU_PREFIX_DISK_SPILL = var.smoketest.disk_spill_dir
            } : {}
            content {
              name  = env.key
              value = env.value
            }
          }

          # libtpu's DCN transport for cross-slice collectives
          dynamic "env" {
            for_each = length(local.smoke_slice_order) > 1 ? {
              MEGASCALE_NUM_SLICES          = tostring(length(local.smoke_slice_order))
              MEGASCALE_SLICE_ID            = tostring(local.smoke_slice_id[each.key])
              MEGASCALE_COORDINATOR_ADDRESS = "${local.smoke_coordinator}:${local.smoke_megascale_port}"
            } : {}
            content {
              name  = env.key
              value = env.value
            }
          }

          port {
            name           = "coordinator"
            container_port = local.smoke_coordinator_port
          }
          port {
            name           = "megascale"
            container_port = local.smoke_megascale_port
          }

          resources {
            requests = {
              "google.com/tpu" = each.value.chips_per_host
            }
            limits = {
              "google.com/tpu" = each.value.chips_per_host
            }
          }

          volume_mount {
            name       = "script"
            mount_path = "/opt/smoketest"
          }

          # durable resume state for local checkpoint paths (gs:// needs none)
          dynamic "volume_mount" {
            for_each = var.smoketest.checkpoint_pvc != null ? [1] : []
            content {
              name       = "checkpoint"
              mount_path = var.smoketest.checkpoint_dir
            }
          }
        }

        volume {
          name = "script"
          config_map {
            name = kubernetes_config_map_v1.smoketest_script[0].metadata[0].name
          }
        }

        dynamic "volume" {
          for_each = var.smoketest.checkpoint_pvc != null ? [1] : []
          content {
            name = "checkpoint"
            persistent_volume_claim {
              claim_name = var.smoketest.checkpoint_pvc
            }
          }
        }
      }
    }
  }

  wait_for_completion = true

  timeouts {
    create = "${local.smoke_deadline_s}s"
  }

  depends_on = [
    google_container_node_pool.tpu_slice,
    kubernetes_service_v1.smoketest_coordinator,
  ]
}
