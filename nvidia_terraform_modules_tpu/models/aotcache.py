# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""Persistent ahead-of-time compile cache for the serve engine — the
cold-start half of second-scale elastic joins.

PR 15/18 made elastic joins KV-warm (``WarmChainStore`` seeds the
joiner's prefix working set), but a joiner still paid full jit
tracing + XLA compilation for the engine's ENTIRE step family —
admission buckets, chunk stream, lazy growth, the all-slots wave step,
the speculative multi-step, and the paged-block handoff jits — and at
spike time that compile wall is exactly the scale-up latency that
decides ``serve_fleet_autoscale_p99_under_spike`` (ROADMAP item 4;
*Automatic Full Compilation … to Cloud TPUs* is the whole-program-AOT
direction this follows).

This module closes it in three composed stages, all driven by
:func:`warm_engine` at fleet start / replica bring-up:

1. **AOT store** — every step jit the engine owns is enumerable via
   ``engine.aot_registrations()`` as ``(name, fn, abstract args)``;
   :func:`warm_engine` drives ``fn.lower(*args).compile()`` for the
   whole family and records one crc-framed entry per registration in
   an :class:`AotCompileCache`. Where the backend supports executable
   serialization (``jax.experimental.serialize_executable``) the
   compiled binary rides in the entry (``mode="serialized"``) and is
   deserialize-VALIDATED on every later hit; where it does not, the
   entry degrades to ``mode="traceonly"`` — the compile still happened
   against the activated persistent XLA cache below, so later
   bring-ups skip the XLA work even though the entry itself carries no
   binary.
2. **Persistent XLA cache** — :meth:`AotCompileCache.activate` points
   ``jax_compilation_cache_dir`` at ``<cache_dir>/xla`` (thresholds
   zeroed) so every compile — AOT-stage or call-path — lands on disk
   and every later identical compile is a disk hit. This is what makes
   the warm join fast ACROSS PROCESSES: a fleet child activates the
   shared directory and its call-path compiles disk-hit the donor's.
3. **Priming** — ``jax.jit(...).lower().compile()`` does NOT populate
   the jit call-path cache (measured: a direct call after AOT compile
   re-traces), so :func:`warm_engine` finishes by driving a tiny
   seeded synthetic schedule through the engine's real ``run()``
   (``engine.aot_prime``). Priming is the authoritative call-path
   warm; with stage 2 active its compiles are disk hits, so it costs
   trace time, not XLA time.

Integrity is the checkpoint/hostkv crc discipline applied to compiled
executables: every entry is ``GAC1``-framed with a crc32 over the
pickled body AND stores its full un-hashed key — a corrupt, truncated,
or stale (hash-collision / schema-drift) entry is QUARANTINED into
``<cache_dir>/quarantine/`` and recompiled, never silently served
(:class:`AotCacheCorruptError` classifies the failure for callers that
probe directly). Keys hash an :func:`engine_fingerprint` covering the
jax version, backend + device kind/count, mesh axes, model config,
and every engine lever, plus the per-registration abstract signature
(treedef + per-leaf ``dtype[shape]``) — differing levers, meshes, or
dtypes can never share an executable. Writes are atomic
(tmp + ``os.replace``), so concurrent warmers race only to duplicate
identical bytes, harmlessly.

Telemetry: ``aot_cache_hit_total`` / ``aot_cache_miss_total`` counters
per registration probe, the ``engine_warmup_ms`` gauge on every
:func:`warm_engine`, and ``join_first_token_ms`` set by the engine on
the first prefill of a run (``models/serving.py``) — the gauge the
cold-start bench legs and the fleet's ``warm_compile=`` span arg are
read against.

``tests/test_aotcache.py`` pins key separation per lever/mesh/dtype,
the corrupt/truncated → quarantine + recompile path, the warmed ==
unwarmed bit-match, and concurrent-warmer safety;
``bench.py --section serve_coldstart`` carries the wall-clock gate
(``serve_join_first_token_warm_vs_cold`` strictly > 1).
"""

from __future__ import annotations

import hashlib
import os
import pickle
import struct
from typing import Any

import jax

_MAGIC = b"GAC1"
_HEADER = struct.Struct(">II")          # (len(body), crc32(body))
_SUFFIX = ".gac"


class AotCacheCorruptError(RuntimeError):
    """A cache entry failed its magic / crc / key check — a CLASSIFIED
    integrity failure (like ``HostSpillCorruptError``): the entry is
    quarantined and the caller recompiles from source, never loads the
    corrupt executable."""


def _crc32(data: bytes) -> int:
    import zlib

    return zlib.crc32(data) & 0xFFFFFFFF


def describe_avals(args: Any) -> str:
    """Deterministic abstract signature of a registration's arguments:
    the pytree structure plus per-leaf ``dtype[shape]`` (non-array
    leaves — static strings, ints — by ``repr``). Two registrations
    whose signatures differ can never share an entry, which is what
    keeps a dtype or geometry change from serving a stale executable."""
    leaves, treedef = jax.tree_util.tree_flatten(args)
    parts = []
    for leaf in leaves:
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is not None and dtype is not None:
            dims = "x".join(str(int(d)) for d in shape)
            parts.append(f"{dtype}[{dims}]")
        else:
            parts.append(repr(leaf))
    return f"{treedef}|{';'.join(parts)}"


def engine_fingerprint(cfg, max_len: int, levers: dict, *,
                       mesh=None) -> str:
    """The cache SCOPE: everything outside a single registration that
    may change generated code — jax version, backend platform + device
    kind and count, mesh axes, the model config, and every engine
    lever (sorted; values must be primitives — ``models/serving.py``
    sanitises callables to qualnames before calling this, because a
    ``repr`` carrying a memory address would split the key across
    processes)."""
    devs = jax.devices()
    dev_desc = (f"{devs[0].platform}:"
                f"{getattr(devs[0], 'device_kind', '?')}x{len(devs)}")
    if mesh is None:
        mesh_desc = "none"
    else:
        shape = getattr(mesh, "shape", None)
        mesh_desc = (",".join(f"{k}={v}" for k, v in shape.items())
                     if isinstance(shape, dict) else repr(shape))
    lever_desc = ",".join(f"{k}={levers[k]!r}" for k in sorted(levers))
    return (f"gac1|jax={jax.__version__}|dev={dev_desc}"
            f"|mesh={mesh_desc}|cfg={cfg!r}|max_len={max_len}"
            f"|{lever_desc}")


def _serializer():
    """The executable (de)serialization backend, or None where jax
    does not ship it — callers degrade to trace-only entries."""
    try:
        from jax.experimental import serialize_executable
    except ImportError:
        return None
    return serialize_executable


class AotCompileCache:
    """One directory of crc-framed compile entries + the activated
    persistent XLA cache underneath it (``<path>/xla``).

    Entry file format: ``GAC1`` magic, big-endian ``(len, crc32)``
    header, pickled body ``{"key", "mode", "payload"}`` where ``key``
    is the FULL un-hashed key (stale/collision detection), ``mode`` is
    ``"serialized" | "traceonly"``, and ``payload`` is the
    ``serialize_executable.serialize`` triple or None. File names are
    the first 24 hex chars of sha256(key).

    The cache object is picklable (it carries only its path), so a
    multi-process fleet ships it to children through ``engine_kw`` and
    every replica shares one on-disk store.
    """

    def __init__(self, path: str, *, telemetry=None):
        self.path = str(path)
        self._telemetry = telemetry
        self._active: dict | None = None
        self._seq = 0
        os.makedirs(self.path, exist_ok=True)
        os.makedirs(self.quarantine_dir, exist_ok=True)
        os.makedirs(self.xla_dir, exist_ok=True)

    # picklability: drop the registry handle and runtime activation
    # state — a child re-activates against its own jax config
    def __getstate__(self):
        return {"path": self.path}

    def __setstate__(self, state):
        self.__init__(state["path"])

    @property
    def xla_dir(self) -> str:
        return os.path.join(self.path, "xla")

    @property
    def quarantine_dir(self) -> str:
        return os.path.join(self.path, "quarantine")

    # ---- keys -------------------------------------------------------
    def entry_key(self, scope: str, name: str, args: Any) -> str:
        """Full key for one registration: the engine scope + the jit's
        name + the abstract signature of its arguments."""
        return f"{scope}::{name}::{describe_avals(args)}"

    def _entry_path(self, key: str) -> str:
        digest = hashlib.sha256(key.encode()).hexdigest()[:24]
        return os.path.join(self.path, digest + _SUFFIX)

    # ---- entries ----------------------------------------------------
    def probe(self, key: str):
        """Return the entry body dict for ``key`` or None. Any
        integrity failure — bad magic, short read, crc mismatch,
        unpicklable body, or a stored key that is not ``key`` (hash
        collision / fingerprint drift) — QUARANTINES the file and
        returns None so the caller recompiles."""
        path = self._entry_path(key)
        try:
            with open(path, "rb") as fh:
                raw = fh.read()
        except FileNotFoundError:
            return None
        try:
            body = self._decode(raw, key)
        except AotCacheCorruptError as exc:
            self.quarantine(path, str(exc))
            return None
        return body

    def _decode(self, raw: bytes, key: str) -> dict:
        if raw[:4] != _MAGIC:
            raise AotCacheCorruptError(
                f"bad magic {raw[:4]!r} (want {_MAGIC!r})")
        if len(raw) < 4 + _HEADER.size:
            raise AotCacheCorruptError(
                f"truncated header ({len(raw)} bytes)")
        length, crc = _HEADER.unpack_from(raw, 4)
        body_raw = raw[4 + _HEADER.size:]
        if len(body_raw) != length:
            raise AotCacheCorruptError(
                f"truncated body ({len(body_raw)} of {length} bytes)")
        if _crc32(body_raw) != crc:
            raise AotCacheCorruptError(
                f"crc mismatch ({_crc32(body_raw):#010x} != {crc:#010x})")
        try:
            body = pickle.loads(body_raw)
        except Exception as exc:  # noqa: BLE001 — classified below
            raise AotCacheCorruptError(
                f"body unpicklable: {exc!r}") from exc
        if not isinstance(body, dict) or body.get("key") != key:
            raise AotCacheCorruptError(
                f"stale entry: stored key {str(body.get('key'))[:80]!r}… "
                "does not match probe key")
        return body

    def store(self, key: str, mode: str, payload) -> str:
        """Atomically write one entry; returns the mode actually
        stored (a payload that refuses to pickle degrades the entry to
        trace-only rather than failing the warm)."""
        body = {"key": key, "mode": mode, "payload": payload}
        try:
            body_raw = pickle.dumps(body,
                                    protocol=pickle.HIGHEST_PROTOCOL)
        except Exception as exc:  # noqa: BLE001 — degrade, don't fail
            body = {"key": key, "mode": "traceonly", "payload": None,
                    "degraded": repr(exc)}
            body_raw = pickle.dumps(body,
                                    protocol=pickle.HIGHEST_PROTOCOL)
        path = self._entry_path(key)
        tmp = f"{path}.tmp.{os.getpid()}.{self._seq}"
        self._seq += 1
        with open(tmp, "wb") as fh:
            fh.write(_MAGIC)
            fh.write(_HEADER.pack(len(body_raw), _crc32(body_raw)))
            fh.write(body_raw)
        os.replace(tmp, path)          # atomic: racers duplicate bytes
        return body["mode"]

    def quarantine(self, path: str, reason: str) -> None:
        """Move a corrupt/stale entry aside (never delete — the bytes
        are the postmortem) and remember why."""
        dest = os.path.join(self.quarantine_dir, os.path.basename(path))
        try:
            os.replace(path, dest)
        except FileNotFoundError:
            pass                       # a racer already moved it
        self.quarantine_reasons.append(reason)

    def quarantine_key(self, key: str, reason: str) -> None:
        self.quarantine(self._entry_path(key), reason)

    @property
    def quarantine_reasons(self) -> list:
        reasons = getattr(self, "_quarantine_reasons", None)
        if reasons is None:
            reasons = self._quarantine_reasons = []
        return reasons

    # ---- persistent XLA cache --------------------------------------
    def activate(self) -> None:
        """Point jax's persistent compilation cache at ``<path>/xla``
        (thresholds zeroed so every compile lands) and reset the
        in-memory handle so the switch takes effect immediately.
        Idempotent; :meth:`deactivate` restores the previous config."""
        if self._active is not None:
            return
        keys = ("jax_compilation_cache_dir",
                "jax_persistent_cache_min_compile_time_secs",
                "jax_persistent_cache_min_entry_size_bytes")
        prev = {k: getattr(jax.config, k) for k in keys}
        jax.config.update("jax_compilation_cache_dir", self.xla_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        _reset_xla_cache()
        self._active = prev

    def deactivate(self) -> None:
        if self._active is None:
            return
        for k, v in self._active.items():
            jax.config.update(k, v)
        self._active = None
        _reset_xla_cache()

    # ---- inspection -------------------------------------------------
    def entries(self) -> list:
        return sorted(f for f in os.listdir(self.path)
                      if f.endswith(_SUFFIX))

    def stats(self) -> dict:
        return {
            "path": self.path,
            "entries": len(self.entries()),
            "quarantined": len([f for f in
                                os.listdir(self.quarantine_dir)
                                if f.endswith(_SUFFIX)]),
            "active": self._active is not None,
        }


def _reset_xla_cache() -> None:
    # private jax surface, version-guarded: a missing reset just means
    # the new directory applies to compiles after the next process
    # start instead of immediately
    try:
        from jax._src import compilation_cache as _cc
    except ImportError:
        return
    reset = getattr(_cc, "reset_cache", None)
    if reset is not None:
        reset()


def warm_engine(engine, cache: AotCompileCache | None = None, *,
                slots: int = 2, kv_blocks: int | None = None,
                prompt_lens=(), n_new: int = 2, prime: bool = True,
                telemetry=None) -> dict:
    """Warm a serve engine's whole step family against ``cache``.

    Stages (see the module docstring): probe-or-compile every
    registration into the AOT store (hits validated by deserialize
    where serialized), with the persistent XLA cache ACTIVATED so all
    compiles land on disk; then prime the jit call path by driving a
    seeded synthetic schedule through the engine's real ``run()``
    (``prime=False`` skips it — the bring-up paths that run a real
    schedule immediately afterwards warm themselves).

    ``slots`` / ``kv_blocks`` / ``prompt_lens`` must match the
    geometry the engine will serve (each prompt length is its own
    admission compile — there is no length bucketing). Returns a stats
    dict (``registered/hits/misses/serialized/traceonly/demoted/
    quarantined/primed/errors/warm_ms``); a compile that fails to
    lower (aval
    drift) is recorded in ``errors`` and degrades gracefully — priming
    still covers the call path. A total no-op returning
    ``{"enabled": False}`` when the engine has no cache, so unwarmed
    runs stay byte-identical."""
    from ..telemetry import get_registry

    reg = telemetry if telemetry is not None else get_registry()
    clk0 = reg.clock()
    if cache is None:
        cache = getattr(engine, "aot_cache", None)
    stats: dict[str, Any] = {
        "enabled": cache is not None, "registered": 0, "hits": 0,
        "misses": 0, "serialized": 0, "traceonly": 0,
        "demoted": 0, "quarantined": 0, "primed": 0, "errors": [],
    }
    if cache is None:
        return stats
    cache.activate()
    q0 = len(cache.quarantine_reasons)
    se = _serializer()
    scope = engine.aot_scope
    c_hit = reg.counter("aot_cache_hit_total")
    c_miss = reg.counter("aot_cache_miss_total")
    regs = engine.aot_registrations(slots=slots, kv_blocks=kv_blocks,
                                    prompt_lens=tuple(prompt_lens),
                                    n_new=n_new)
    for name, fn, args in regs:
        stats["registered"] += 1
        key = cache.entry_key(scope, name, args)
        entry = cache.probe(key)
        demote = False
        if entry is not None and entry["mode"] == "serialized":
            if se is None:
                entry = None           # can't validate — recompile
                demote = True
                cache.quarantine_key(
                    key, "serialized entry on a backend without "
                    "serialize_executable")
            else:
                try:
                    se.deserialize_and_load(*entry["payload"])
                except Exception as exc:  # noqa: BLE001 — classified
                    # a deserialize that fails once fails every
                    # bring-up (e.g. XLA:CPU executables referencing
                    # jit-compiled fusion symbols that do not survive
                    # reload) — DEMOTE the recompile to trace-only so
                    # the entry converges instead of quarantining
                    # forever; the activated XLA disk cache still
                    # banks the compile itself
                    cache.quarantine_key(
                        key, f"deserialize failed: {exc!r}")
                    entry = None
                    demote = True
        if entry is not None:
            stats["hits"] += 1
            c_hit.inc()
            continue
        stats["misses"] += 1
        c_miss.inc()
        try:
            compiled = fn.lower(*args).compile()
        except Exception as exc:  # noqa: BLE001 — aval drift degrades
            stats["errors"].append(f"{name}: {exc!r}")
            continue
        mode, payload = "traceonly", None
        if se is not None and not demote:
            try:
                payload = se.serialize(compiled)
                mode = "serialized"
            except Exception as exc:  # noqa: BLE001 — backend limit
                stats["errors"].append(
                    f"{name}: serialize unsupported: {exc!r}")
                payload = None
        stored = cache.store(key, mode, payload)
        stats[stored] += 1
        if demote:
            stats["demoted"] += 1
    if prime:
        stats["primed"] = int(engine.aot_prime(
            slots=slots, kv_blocks=kv_blocks,
            prompt_lens=tuple(prompt_lens), n_new=n_new))
    stats["quarantined"] = len(cache.quarantine_reasons) - q0
    warm_ms = round((reg.clock() - clk0) * 1e3, 3)
    stats["warm_ms"] = warm_ms
    reg.gauge("engine_warmup_ms").set(warm_ms)
    return stats
