# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""The fleet's router↔replica transport seam: one interface, two wires.

``models/fleet.py`` owns every routing decision — admission queues,
steal, redrive, drains, health — and until this module existed it also
owned the assumption that a replica is a *thread*: a kill was an
exception raised at a poll boundary, a simulation of failure rather
than failure. This module extracts the communication layer behind a
:class:`Transport` interface so the router no longer knows what a
replica IS:

- :class:`InProcTransport` — today's wire: the serve engine runs on a
  daemon thread polling the router's ``_FleetQueue`` directly.
  Bit-for-bit identical to the pre-seam fleet (the 13 fleet bit-match
  gates in ``tests/test_fleet*.py`` pin it).
- :class:`MultiProcTransport` — replicas as REAL processes: each
  replica is a spawned subprocess running its own serve engine; every
  ``AdmissionSource`` poll crosses the process boundary as a
  length-prefixed, crc32-verified, sequence-numbered frame over an OS
  pipe (:func:`pack_frame`/:func:`unpack_frame`), with bounded
  send/recv timeouts everywhere (``graft-unbounded-recv`` is the lint
  rule this module's poll-guard idiom satisfies). A scheduled
  ``kill_replica`` fault becomes an actual ``SIGKILL`` of the replica
  process, delivered at the identical admission-poll boundary the
  in-proc fault seam uses — so the chaos gates rerun against real
  process death and stay bit-exact (tokens are schedule-invariant;
  redrive is exactly-once).

Design invariants the bit-match rests on:

- **All router state stays router-side.** The ``_FleetQueue`` lives in
  the parent in BOTH transports; the multi-proc replica drives it
  through an RPC proxy (:class:`_RPCAdmission`), one strict
  request/reply frame pair per poll, served by a parent-side handler
  thread (:class:`_ProcHandle`) that calls the real queue methods.
  Routing, steal, redrive and shed therefore execute identically.
- **Classified transport errors.** :class:`TransportTimeout` is the
  TRANSIENT class (the reply may still come — the receiver re-waits
  under a ``utils/retry`` capped-backoff policy; requests are never
  re-SENT, polls are not idempotent); :class:`TransportDead` (peer
  EOF / process gone) and :class:`TransportProtocolError` /
  :class:`TransportCorruptFrame` (truncation, out-of-order delivery,
  crc mismatch) are TERMINAL — the replica is classified dead, the
  router's ordinary ``take_lost``→redrive machinery recovers, and a
  replica that exhausts its reply budget exits with
  ``resilience.EXIT_PEER_DEAD`` so the supervisor-side classification
  (``resilience.classify_exit``) reads the truth.
- **Real liveness.** ``_FleetQueue.last_poll`` stamps land when the
  poll frame ARRIVES, so ``resilience.LivenessBreaker`` inside the
  fleet's health monitor observes real heartbeat lag over the wire,
  not same-address-space stamps.

Paged-block handoff payloads reuse the paging layer's own wire
integrity primitive: :func:`encode_block_payload` /
:func:`decode_block_payload` stamp and re-verify
``paging.transfer_crc`` over the exported block rows
(``paging.export_block_rows`` → wire → ``paging.import_block_rows``),
so a corrupt frame is loud on the decode side of the wire exactly like
the in-proc disaggregated handoff.

Full compose scope (CPU; ROADMAP item 2's v5e ICI/DCN impl is a third
``Transport`` on this seam): the multi-proc fleet accepts everything
the in-proc fleet accepts —

- ``autoscale``: a scale-up spawns a REAL child under
  ``_SPAWN_PROC_RETRY`` (all-attempts spawn failure ⇒ the target is
  classified dead and its planned requests redrive — never a hang),
  and a warm join ships the joiner's keyspace share of the
  fleet-shared ``WarmChainStore`` as crc-stamped chain frames over
  the duplex pipe (:func:`encode_block_payload` per chain; a chain
  that fails its ``transfer_crc`` on the child side is dropped and
  billed in the engine's ``warm.seed_dropped`` — suspect bytes are
  never imported), seeding the child's ``PrefixIndex.seed_host`` so
  the ``warm`` stats bit-match the thread fleet. A scale-down drains
  through the ordinary ``draining()`` RPC and the child publishes its
  retained chains home (``publish_chains`` frames, crc-stamped the
  same way) before its DONE frame.
- ``disaggregate``: prefill workers stay PARENT-side (the handoff
  payload is the cross-boundary object, not the worker — see
  :meth:`Transport.prefill_engine`); the prefill→decode handoff rides
  the existing ``kv_import`` RPC, crc-stamped at the parent and
  re-verified in the child, with the ``HandoffCorruptError`` retry
  discipline unchanged router-side.
- ``sampler``/``rng``: a sampler crosses the boundary as a SPEC dict
  (``dict(temperature=, top_k=, top_p=)`` — ``make_serve_engine``
  normalises it through ``decode.make_sampler`` on both sides, so
  in-proc and multi-proc build the identical pick function; a raw
  callable is still refused, it does not pickle); the per-call PRNG
  key ships as its host key data in the RUN frame and the child
  rebuilds it — (request, position)-keyed sampling is
  schedule-invariant by construction, so sampled tokens bit-match
  the thread fleet and solo decode.

A crashed parent never strands a child: every child runs a
parent-pid watchdog (:func:`start_parent_watchdog`) that exits
``EXIT_PEER_DEAD`` the moment it is reparented, and the transport
registers an ``atexit`` close as the parent-side backstop — the
orphan-reaper discipline for a parent that dies between spawn and
registry insert. Telemetry:
``transport_bytes_total``/``transport_frames_total`` count every frame
through the parent side of each pipe, ``transport_rtt_ms`` records
the replica-measured poll round-trip, ``transport_retries_total``
the classified reply retries, ``transport_child_respawn_total`` each
replacement of a dead child, and ``warm_chains_bytes_total`` the
warm-chain bytes shipped over pipes in either direction (see
:class:`TransportMetrics`).
"""

from __future__ import annotations

import atexit
import os
import pickle
import signal
import struct
import threading
import time
import weakref
import zlib
from typing import Any, Callable

import numpy as np

from ..utils.retry import RetryPolicy, RetriesExhausted, retry_call
from .resilience import EXIT_PEER_DEAD
from .serving import AdmissionSource, make_serve_engine

# ------------------------------------------------------ classified errors


class TransportError(RuntimeError):
    """Base of the transport fault taxonomy. ``transient`` is the
    retry classification: True means the condition can resolve by
    waiting (route through ``utils/retry``), False means the peer or
    the stream is unrecoverable (classify the replica dead and
    redrive)."""

    transient = False


class TransportTimeout(TransportError):
    """Bounded recv expired with no frame — TRANSIENT: the peer may
    merely be busy (a replica mid-compile, a router mid-steal). The
    receiver re-waits under capped backoff; it never re-sends
    (admission polls are not idempotent)."""

    transient = True


class TransportDead(TransportError):
    """The peer is gone — EOF, closed pipe, or a dead process behind
    the frame stream. TERMINAL: the router classifies the replica dead
    and its work redrives; a replica seeing this exits
    ``EXIT_PEER_DEAD``."""


class TransportProtocolError(TransportError):
    """The frame stream itself is broken — bad magic, a truncated
    frame, or out-of-order delivery (sequence mismatch). TERMINAL and
    LOUD: a desynchronised stream must never be resynchronised by
    guesswork."""


class TransportCorruptFrame(TransportProtocolError):
    """A frame's payload failed its crc32 — wire corruption. TERMINAL
    at the stream level (the in-proc disaggregated handoff retries
    from prefill instead, through its own ``HandoffCorruptError``
    seam)."""


# the replica-side reply wait: one bounded recv per attempt, capped
# backoff between attempts, then the replica classifies the ROUTER
# dead and exits EXIT_PEER_DEAD (never a silent hang — the satellite
# bugfix's contract)
_REPLY_RETRY = RetryPolicy(initial_s=0.05, multiplier=2.0, cap_s=1.0,
                           max_attempts=4, jitter=False)

# replica process bring-up (spawn + READY handshake): a transient
# spawn failure costs a retry, a spawn that fails every attempt is a
# real failure — the target classifies dead and its planned requests
# redrive (the _SPAWN_RETRY discipline, process-sized backoff)
_SPAWN_PROC_RETRY = RetryPolicy(initial_s=0.1, multiplier=2.0, cap_s=1.0,
                                max_attempts=3, jitter=False)


# ------------------------------------------------------------ frame codec

# length-prefixed + crc-verified + sequence-numbered: magic, payload
# length, crc32(payload), then the 64-bit per-direction sequence number
_MAGIC = b"GFT1"
_HEADER = struct.Struct(">4sIIQ")


def pack_frame(seq: int, payload: bytes) -> bytes:
    """One wire frame: ``magic | len | crc32 | seq | payload``. The
    length makes truncation detectable, the crc makes corruption loud,
    and the sequence number makes reordered delivery refusable."""
    return _HEADER.pack(_MAGIC, len(payload),
                        zlib.crc32(payload), seq) + payload


def unpack_frame(frame: bytes, *, expect_seq: int | None = None) -> bytes:
    """Verify and strip one frame's header; returns the payload.

    Every failure is classified and loud: a short or length-mismatched
    frame raises :class:`TransportProtocolError` (truncated), a crc
    mismatch raises :class:`TransportCorruptFrame`, and a sequence
    number other than ``expect_seq`` raises
    :class:`TransportProtocolError` (out-of-order delivery refused —
    the stream is desynchronised, not repairable)."""
    if len(frame) < _HEADER.size:
        raise TransportProtocolError(
            f"truncated frame: {len(frame)} byte(s) is shorter than "
            f"the {_HEADER.size}-byte header")
    magic, length, crc, seq = _HEADER.unpack_from(frame)
    if magic != _MAGIC:
        raise TransportProtocolError(
            f"bad frame magic {magic!r} (want {_MAGIC!r}) — the "
            f"stream is desynchronised or not a transport frame")
    payload = frame[_HEADER.size:]
    if len(payload) != length:
        raise TransportProtocolError(
            f"truncated frame: header promises {length} payload "
            f"byte(s), got {len(payload)}")
    if zlib.crc32(payload) != crc:
        raise TransportCorruptFrame(
            f"frame {seq} failed its crc32 — payload corrupted on "
            f"the wire")
    if expect_seq is not None and seq != expect_seq:
        raise TransportProtocolError(
            f"out-of-order frame: got seq {seq}, expected "
            f"{expect_seq} — refusing to resynchronise a broken "
            f"stream")
    return payload


class TransportMetrics:
    """The transport's instruments on the fleet's shared registry:
    ``transport_bytes_total``/``transport_frames_total`` (every frame
    through the parent side of a channel, both directions),
    ``transport_rtt_ms`` (replica-measured poll round-trips, sampled),
    ``transport_retries_total`` (classified reply retries),
    ``transport_child_respawn_total`` (a dead child replaced by a
    fresh spawn — the post-SIGKILL/crash recovery rate) and
    ``warm_chains_bytes_total`` (warm-chain payload bytes shipped over
    pipes, both the join seeding and the drain publish direction). A
    disabled registry costs nothing (no-op instruments)."""

    def __init__(self, registry=None):
        self.enabled = registry is not None and registry.enabled
        if self.enabled:
            self._bytes = registry.counter("transport_bytes_total")
            self._frames = registry.counter("transport_frames_total")
            self._retries = registry.counter("transport_retries_total")
            self._rtt = registry.histogram("transport_rtt_ms")
            self._respawn = registry.counter(
                "transport_child_respawn_total")
            self._warm_bytes = registry.counter(
                "warm_chains_bytes_total")

    def frame(self, nbytes: int) -> None:
        if self.enabled:
            self._bytes.inc(nbytes)
            self._frames.inc()

    def retries(self, n: int) -> None:
        if self.enabled and n:
            self._retries.inc(n)

    def rtt_ms(self, samples) -> None:
        if self.enabled:
            for s in samples:
                self._rtt.record(float(s))

    def respawn(self) -> None:
        if self.enabled:
            self._respawn.inc()

    def warm_bytes(self, nbytes: int) -> None:
        if self.enabled and nbytes:
            self._warm_bytes.inc(nbytes)


class FrameChannel:
    """One side of a framed duplex stream over a
    ``multiprocessing.connection.Connection``: every message is
    pickled, wrapped by :func:`pack_frame` with this side's
    monotonically increasing send sequence, and every receive is
    BOUNDED — ``recv`` polls the connection up to ``timeout`` seconds
    (:class:`TransportTimeout` on expiry; ``None`` means one
    ``poll_s``-bounded slice, still never an unbounded block) before
    reading, then verifies length/crc/sequence via
    :func:`unpack_frame`. Single-owner by design: exactly one thread
    sends and one thread receives on each side (the fleet serialises
    calls per replica), so the sequence counters need no lock."""

    poll_s = 0.25

    def __init__(self, conn, *, metrics: TransportMetrics | None = None,
                 label: str = ""):
        self._conn = conn
        self._metrics = metrics
        self.label = label
        self._send_seq = 0
        self._recv_seq = 0

    def send(self, obj) -> None:
        frame = pack_frame(self._send_seq,
                           pickle.dumps(obj, pickle.HIGHEST_PROTOCOL))
        try:
            self._conn.send_bytes(frame)
        except (BrokenPipeError, EOFError, OSError) as exc:
            raise TransportDead(
                f"{self.label}: peer closed while sending frame "
                f"{self._send_seq}: {exc}") from exc
        self._send_seq += 1
        if self._metrics is not None:
            self._metrics.frame(len(frame))

    def send_raw(self, payload: bytes) -> None:
        """Send PRE-PICKLED payload bytes: framing (seq/crc) is still
        per-channel, but the pickle happened once upstream — how the
        parent streams ONE shared donor-weight snapshot to N joiners
        (``MultiProcTransport._param_wire``) instead of re-pickling
        the weight tree per child. Billed like any frame, so the
        stream shows up in ``transport_bytes_total``."""
        frame = pack_frame(self._send_seq, payload)
        try:
            self._conn.send_bytes(frame)
        except (BrokenPipeError, EOFError, OSError) as exc:
            raise TransportDead(
                f"{self.label}: peer closed while sending frame "
                f"{self._send_seq}: {exc}") from exc
        self._send_seq += 1
        if self._metrics is not None:
            self._metrics.frame(len(frame))

    def recv(self, timeout: float | None):
        """Bounded receive: ``timeout`` seconds (``None`` → one
        ``poll_s`` slice). :class:`TransportTimeout` when nothing
        arrived, :class:`TransportDead` on EOF, the
        :func:`unpack_frame` classification on a bad frame."""
        budget = self.poll_s if timeout is None else timeout
        try:
            if not self._conn.poll(budget):
                raise TransportTimeout(
                    f"{self.label}: no frame within {budget:.3f}s "
                    f"(waiting for seq {self._recv_seq})")
            frame = self._conn.recv_bytes()
        except (BrokenPipeError, EOFError, OSError) as exc:
            raise TransportDead(
                f"{self.label}: peer closed the stream at seq "
                f"{self._recv_seq}: {exc}") from exc
        payload = unpack_frame(frame, expect_seq=self._recv_seq)
        self._recv_seq += 1
        if self._metrics is not None:
            self._metrics.frame(len(frame))
        return pickle.loads(payload)

    def close(self) -> None:
        try:
            self._conn.close()
        except OSError:
            pass  # already closed by the peer — closing is idempotent


# -------------------------------------------- paged-block payload codec


def encode_block_payload(payload: dict) -> dict:
    """Flatten a ``paging.export_block_rows`` payload for the wire and
    stamp it with ``paging.transfer_crc`` — the paged transfer layer's
    own integrity primitive, chained crc32 over the key-sorted,
    layer-ordered buffers. The decode side re-derives the crc from the
    rebuilt arrays, so corruption anywhere between export and import
    is loud (:class:`TransportCorruptFrame`), never silently imported
    garbage rows."""
    from .paging import transfer_crc

    keys = sorted(payload)
    bufs = [np.asarray(b) for k in keys for b in payload[k]]
    return {
        "keys": keys,
        "layers": [len(payload[k]) for k in keys],
        "shapes": [b.shape for b in bufs],
        "dtypes": [b.dtype.str for b in bufs],
        "data": [b.tobytes() for b in bufs],
        "crc": transfer_crc(payload),
    }


def decode_block_payload(wire: dict) -> dict:
    """Rebuild the block payload and verify its ``transfer_crc``
    stamp; raises :class:`TransportCorruptFrame` on mismatch."""
    from .paging import transfer_crc

    bufs = [np.frombuffer(d, dtype=np.dtype(dt)).reshape(sh)
            for d, dt, sh in zip(wire["data"], wire["dtypes"],
                                 wire["shapes"])]
    payload: dict = {}
    at = 0
    for k, n in zip(wire["keys"], wire["layers"]):
        payload[k] = bufs[at:at + n]
        at += n
    got = transfer_crc(payload)
    if got != wire["crc"]:
        raise TransportCorruptFrame(
            f"paged-block payload failed transfer_crc on the decode "
            f"side of the wire: got {got:#010x}, stamped "
            f"{wire['crc']:#010x}")
    return payload


# ------------------------------------------- rng + warm-chain wire codecs


def encode_rng(rng):
    """A per-call PRNG key for the RUN frame: ships as its HOST key
    data (a typed ``jax.random.key`` unwraps through ``key_data``, a
    raw ``PRNGKey`` uint32 vector ships as-is) so the child rebuilds
    an identical key — (request, position)-keyed sampling is
    schedule-invariant, so the rebuilt key reproduces the thread
    fleet's tokens bit for bit."""
    if rng is None:
        return None
    import jax
    import jax.numpy as jnp

    arr = jnp.asarray(rng)
    if jnp.issubdtype(arr.dtype, jax.dtypes.prng_key):
        return {"kind": "typed",
                "data": np.asarray(jax.random.key_data(arr))}
    return {"kind": "raw", "data": np.asarray(arr)}


def decode_rng(wire):
    """Rebuild the per-call PRNG key from its RUN-frame encoding."""
    if wire is None:
        return None
    import jax
    import jax.numpy as jnp

    if wire["kind"] == "typed":
        return jax.random.wrap_key_data(jnp.asarray(wire["data"]))
    return jnp.asarray(wire["data"])


def encode_warm_chains(chains) -> list:
    """Warm ``(chunks, payload)`` chains for the wire: each payload is
    individually crc-stamped by :func:`encode_block_payload`, so the
    receiving side verifies (and can drop) chains ONE AT A TIME — one
    corrupt chain costs that chain, never the whole warm join."""
    return [
        (tuple(tuple(int(t) for t in c) for c in chunks),
         encode_block_payload(payload))
        for chunks, payload in chains]


def decode_warm_chains(wire_chains) -> tuple[list, int]:
    """Rebuild warm chains, verifying each payload's ``transfer_crc``;
    a chain that fails is DROPPED and counted (suspect bytes are never
    imported into a prefix index — the taker bills the drop in its
    warm stats). Returns ``(chains, dropped)``."""
    chains: list = []
    dropped = 0
    for chunks, enc in wire_chains:
        try:
            chains.append((chunks, decode_block_payload(enc)))
        except TransportCorruptFrame:
            dropped += 1
    return chains, dropped


def warm_chains_nbytes(wire_chains) -> int:
    """Payload bytes in an encoded warm-chain batch (the
    ``warm_chains_bytes_total`` unit — KV rows, not pickle framing)."""
    return sum(len(d) for _, enc in wire_chains for d in enc["data"])


# ------------------------------------------------- child-side orphan reaper


def start_parent_watchdog(parent_pid: int, *, poll_s: float = 1.0,
                          getppid=os.getppid,
                          on_orphan: Callable[[], None] | None = None):
    """The child-side half of the orphan-reaper contract: a daemon
    thread that polls ``getppid()`` and fires ``on_orphan`` (default:
    ``os._exit(EXIT_PEER_DEAD)``) the moment the child is reparented —
    i.e. the parent died, even BETWEEN spawn and the transport's
    registry insert, where no parent-side ``close()``/atexit hook can
    know the child exists. Returns ``(thread, stop_event)``;
    ``getppid``/``on_orphan`` are injectable so the regression test
    can simulate a parent crash without killing the test runner."""
    if on_orphan is None:
        def on_orphan() -> None:
            os._exit(EXIT_PEER_DEAD)
    stop = threading.Event()

    def watch() -> None:
        while not stop.wait(poll_s):
            if getppid() != parent_pid:
                on_orphan()
                return

    thread = threading.Thread(target=watch, daemon=True,
                              name="transport-parent-watchdog")
    thread.start()
    return thread, stop


# --------------------------------------------------------- the interface


class Transport:
    """How the router reaches its decode replicas. ``configure`` binds
    a fleet shape (idempotent — an unchanged configuration keeps warm
    replicas across ``make_fleet`` calls, which is how a shared
    :class:`MultiProcTransport` amortises child spawns and compiles);
    ``launch_decode`` starts one replica run and returns a
    :class:`ReplicaHandle` the monitor polls instead of a raw thread.
    ``process_isolated`` tells the fleet whether replica death is a
    real possibility outside the fault plane (a crashed process) — the
    fleet then always runs its managed recovery loop."""

    name = "base"
    process_isolated = False

    def configure(self, *, params, cfg, max_len: int, engine_kw: dict,
                  registry, n_dec: int, n_pre: int) -> None:
        raise NotImplementedError

    def ensure_engine(self, i: int):
        """Build (or reuse) replica ``i``'s engine ahead of a
        scale-up launch — the retryable unit ``_SPAWN_RETRY`` wraps."""
        raise NotImplementedError

    def prefill_engine(self, i: int):
        """The disaggregated prefill side stays in-process in every
        current transport (the handoff payload is the cross-boundary
        object, not the worker)."""
        raise NotImplementedError

    def launch_decode(self, i: int, queue, run_kw: dict, *,
                      on_error: Callable[[str, BaseException], None]
                      ) -> "ReplicaHandle":
        raise NotImplementedError

    def warm_replica(self, i: int, warm_kw: dict) -> dict:
        """AOT-warm replica ``i``'s engine ahead of its first run
        (``models/aotcache.py`` — probe-or-compile the step family,
        prime the call path). Returns the warm stats dict; ``{}`` on
        transports that cannot warm, and engines without an
        ``aot_cache`` return ``{"enabled": False}`` without running
        anything, so the fleet's bit-match gates are untouched."""
        return {}

    def close(self) -> None:
        """Release replica resources (no-op in-proc; terminates child
        processes multi-proc)."""


class ReplicaHandle:
    """One replica run in flight. ``is_alive`` / bounded ``join`` are
    the monitor's liveness view; ``result``/``stats`` are read after
    join; ``kill`` is the hard stop (SIGKILL for a process replica —
    a thread replica cannot be killed, only abandoned)."""

    label = "?"
    error: BaseException | None = None

    def is_alive(self) -> bool:
        raise NotImplementedError

    def join(self, timeout: float) -> bool:
        """Bounded wait; True when the run finished inside
        ``timeout`` (never an unbounded block — the satellite
        bugfix's contract for fleet joins)."""
        raise NotImplementedError

    def result(self):
        return None

    def stats(self):
        return None

    def kill(self) -> None:
        """Hard-stop the replica if the transport can (SIGKILL)."""


# ------------------------------------------------------------ in-process


class _ThreadHandle(ReplicaHandle):
    """The in-proc replica: the engine runs on a daemon thread against
    the router's queue directly — byte-for-byte the pre-seam fleet's
    ``dec_worker``."""

    def __init__(self, label: str, engine, queue, run_kw: dict,
                 on_error) -> None:
        self.label = label
        self.error = None
        self._result = None
        self._engine = engine

        def work():
            try:
                self._result = engine(
                    run_kw["prompts"], run_kw["budgets"],
                    slots=run_kw["slots"], eos_id=run_kw["eos_id"],
                    rng=run_kw["rng"], kv_blocks=run_kw["kv_blocks"],
                    admission=queue)
            except Exception as exc:     # noqa: BLE001 — classified below
                from .fleet import ReplicaKilled

                if isinstance(exc, ReplicaKilled):
                    # the queue's dead flag (set at the raise, before
                    # the stack unwound) is the monitor's signal —
                    # nothing else to do; the replica is simply gone
                    return
                self.error = exc
                on_error(self.label, exc)

        self._thread = threading.Thread(target=work, daemon=True,
                                        name=f"fleet-{label}")
        self._thread.start()

    def is_alive(self) -> bool:
        return self._thread.is_alive()

    def join(self, timeout: float) -> bool:
        self._thread.join(timeout)
        return not self._thread.is_alive()

    def result(self):
        return self._result

    def stats(self):
        return self._engine.last_stats

    def kill(self) -> None:
        # a thread cannot be killed — the caller abandons it (daemon)
        # after classifying it hung; only a process transport can do
        # better, which is much of the point of having one
        pass


class InProcTransport(Transport):
    """Today's fleet wire: engines in this process, replicas as
    threads, the queue polled directly. The bit-match reference for
    every other transport."""

    name = "inproc"
    process_isolated = False

    def __init__(self):
        self._key = None
        self._registry = None
        self.dec_engines: list = []
        self.pre_engines: list = []

    def configure(self, *, params, cfg, max_len, engine_kw, registry,
                  n_dec, n_pre) -> None:
        key = (id(params), cfg, max_len, tuple(sorted(
            (k, repr(v)) for k, v in engine_kw.items())))
        self._registry = registry
        if key == self._key:
            # unchanged config: keep warm engines (their step caches
            # and prefix indexes), just grow to the new shape
            while len(self.dec_engines) < n_dec:
                self.dec_engines.append(self._build())
            while len(self.pre_engines) < n_pre:
                self.pre_engines.append(self._build())
            return
        self._key = key
        self._params, self._cfg, self._max_len = params, cfg, max_len
        self._engine_kw = dict(engine_kw)
        # every engine shares the fleet's registry so router + engine
        # spans stitch on one timeline; engines are separate objects on
        # purpose — separate pools, separate step caches, no
        # cross-thread state
        self.dec_engines = [self._build() for _ in range(n_dec)]
        self.pre_engines = [self._build() for _ in range(n_pre)]

    def _build(self):
        return make_serve_engine(self._params, self._cfg,
                                 max_len=self._max_len,
                                 telemetry=self._registry,
                                 **self._engine_kw)

    def ensure_engine(self, i: int):
        while len(self.dec_engines) <= i:
            self.dec_engines.append(None)
        if self.dec_engines[i] is None:
            self.dec_engines[i] = self._build()
        return self.dec_engines[i]

    def prefill_engine(self, i: int):
        return self.pre_engines[i]

    def launch_decode(self, i, queue, run_kw, *, on_error):
        return _ThreadHandle(f"decode-{i}", self.dec_engines[i],
                             queue, run_kw, on_error)

    def warm_replica(self, i, warm_kw):
        engine = self.ensure_engine(i)
        warm = getattr(engine, "warm", None)
        return warm(**warm_kw) if warm is not None else {}

    def close(self) -> None:
        pass                             # nothing lives outside us


# ---------------------------------------------------------- multi-process


class _RPCAdmission(AdmissionSource):
    """The replica-side proxy: every engine-facing admission poll
    becomes one ``("REQ", method, args)`` frame to the router and one
    bounded wait for its ``("REP", ...)``. A reply that times out is
    re-WAITED under ``_REPLY_RETRY`` (never re-sent — polls are not
    idempotent); an exhausted budget classifies the router dead and
    the replica exits ``EXIT_PEER_DEAD``. Round-trips are measured
    here (the replica's clock, both directions of real wire) and
    shipped home in the DONE frame."""

    _SAMPLE_CAP = 256

    def __init__(self, chan: FrameChannel, reply_timeout_s: float):
        self._chan = chan
        self._reply_timeout_s = reply_timeout_s
        self.rtt_ms: list[float] = []
        self.retries = 0
        self.warm_dropped = 0            # wire-corrupt warm chains

    def _call(self, method: str, *args):
        t0 = time.monotonic()
        self._chan.send(("REQ", method, args))

        def _recv():
            return self._chan.recv(self._reply_timeout_s)

        def _note(_msg: str) -> None:
            self.retries += 1

        reply = retry_call(_recv, policy=_REPLY_RETRY,
                           what=f"{self._chan.label} {method} reply",
                           retryable=(TransportTimeout,), log=_note)
        if len(self.rtt_ms) < self._SAMPLE_CAP:
            self.rtt_ms.append((time.monotonic() - t0) * 1e3)
        tag, payload = reply
        if tag != "REP":
            raise TransportProtocolError(
                f"{self._chan.label}: expected a REP frame for "
                f"{method}, got {tag!r}")
        status, value = payload
        if status == "EXC":
            # the router-side queue method raised: surface it in the
            # replica's engine exactly like the in-proc fault seam
            # (the engine deliberately does not catch hook errors)
            raise RuntimeError(
                f"router-side {method}() failed: {value}")
        return value

    def candidate(self):
        return self._call("candidate")

    def pop(self, req) -> None:
        self._call("pop", int(req))

    def requeue(self, req) -> None:
        self._call("requeue", int(req))

    def tick(self) -> None:
        self._call("tick")

    def draining(self) -> bool:
        return self._call("draining")

    def waiting(self) -> int:
        return self._call("waiting")

    def exhausted(self) -> bool:
        return self._call("exhausted")

    def idle_wait(self) -> None:
        self._call("idle_wait")

    def wait_s(self, req) -> float:
        return self._call("wait_s", int(req))

    def kv_import(self, req):
        wire = self._call("kv_import", int(req))
        if wire is None:
            return None
        return dict(wire, blocks=decode_block_payload(wire["blocks"]))

    def retired(self, req, tokens: int) -> None:
        self._call("retired", int(req), int(tokens))

    def warm_chains(self):
        """The elastic warm-join plane over the wire: the joiner's
        keyspace share of the fleet's ``WarmChainStore`` arrives as
        per-chain crc-stamped payloads; a chain that fails its
        ``transfer_crc`` here is dropped and counted (``warm_dropped``
        folds into the engine's ``warm.seed_dropped`` in the DONE
        frame) — suspect bytes never reach ``seed_host``."""
        wire = self._call("warm_chains")
        if not wire:
            return None
        chains, dropped = decode_warm_chains(wire)
        self.warm_dropped += dropped
        return chains or None

    def chain_sink(self):
        """A drain/close-time publish target when the fleet runs a
        ``WarmChainStore``: the store itself stays ROUTER-side (it
        holds locks and a host pool — it does not pickle); the replica
        gets a proxy whose ``publish`` ships crc-stamped chains home
        through the ``publish_chains`` RPC."""
        return _ChainSinkProxy(self) if self._call("chain_sink") else None


class _ChainSinkProxy:
    """The replica-side face of the router's ``WarmChainStore``: quacks
    like the sink ``publish_chains`` expects (``publish(chains) →
    stored``), encoding each retained chain with its own
    ``transfer_crc`` stamp so the router side verifies before storing
    — a drain never launders corrupt rows into the fleet-shared warm
    tier."""

    def __init__(self, adm: "_RPCAdmission"):
        self._adm = adm

    def publish(self, chains) -> int:
        return self._adm._call("publish_chains",
                               encode_warm_chains(chains))


def _recv_params(chan: FrameChannel, timeout_s: float):
    """The donor weight stream: the FIRST frame into a fresh child is
    ``("PARAMS", snapshot_wire)`` — one shared, crc-stamped host
    snapshot the parent pickled once for every joiner
    (``hostkv.HostParamSnapshot``). Every leaf crc is verified HERE,
    before any engine exists; a corrupt stream is reclassified as
    :class:`TransportCorruptFrame` so the child's classified-exit path
    (``EXIT_PEER_DEAD``) fires and the parent's spawn retry respawns —
    a joiner never builds on silently corrupt weights."""
    from .hostkv import HostParamSnapshot, SnapshotCorruptError

    msg = chan.recv(timeout_s)
    if not (isinstance(msg, tuple) and msg and msg[0] == "PARAMS"):
        raise TransportProtocolError(
            f"{chan.label}: expected PARAMS as the first frame, "
            f"got {msg!r:.80}")
    try:
        return HostParamSnapshot.decode(msg[1])
    except SnapshotCorruptError as exc:
        raise TransportCorruptFrame(
            f"{chan.label}: donor weight stream corrupt: {exc}"
        ) from exc


def _replica_child_main(conn, index: int, params, cfg, max_len: int,
                        engine_kw: dict, reply_timeout_s: float,
                        parent_pid: int | None = None) -> None:
    """The replica process: receive the donor weight stream, build the
    engine once, then serve WARM/RUN frames until EXIT (children
    persist across fleet calls — compiles amortise exactly like
    in-proc engines). Every recv is bounded; a dead or desynchronised
    router stream exits ``EXIT_PEER_DEAD`` so
    ``resilience.classify_exit`` reads a classified death, never a
    hang. The parent-pid watchdog starts BEFORE the params receive — a
    parent that crashes mid-spawn (before its registry insert) still
    reaps this child. ``params`` rides the spawn args only for direct
    (non-fleet) callers; the fleet passes None and streams."""
    if parent_pid is not None:
        start_parent_watchdog(parent_pid)
    chan = FrameChannel(conn, label=f"replica-{index}/child")
    try:
        if params is None:
            # generous budget: the wire bytes are already in flight
            # when we get here — this bounds a dead parent, not a slow
            # stream
            params = _recv_params(chan, max(reply_timeout_s, 60.0))
        engine = make_serve_engine(params, cfg, max_len=max_len,
                                   **engine_kw)
        chan.send(("READY", index, os.getpid()))
        while True:
            try:
                # idle between fleet calls: wait patiently in bounded
                # slices (poll_s) — EOF means the router is gone
                msg = chan.recv(None)
            except TransportTimeout:
                continue
            if msg[0] == "EXIT":
                return
            if msg[0] == "WARM":
                # AOT warm (models/aotcache.py): probe-or-compile the
                # step family + prime the call path BEFORE the first
                # RUN — the whole point of the process fleet's warm
                # joins. Failures ship home as stats, never kill the
                # child: an unwarmed replica is slow, not wrong.
                try:
                    stats = engine.warm(**msg[1])
                except Exception as exc:  # noqa: BLE001 — shipped home
                    stats = {"enabled": False, "registered": 0,
                             "hits": 0, "misses": 0,
                             "error": f"{type(exc).__name__}: {exc}"}
                chan.send(("WARMED", stats))
                continue
            if msg[0] != "RUN":
                raise TransportProtocolError(
                    f"replica-{index}: unexpected frame {msg[0]!r} "
                    f"while waiting for RUN")
            run_kw = msg[1]
            adm = _RPCAdmission(chan, reply_timeout_s)
            try:
                res = engine(run_kw["prompts"], run_kw["budgets"],
                             slots=run_kw["slots"],
                             eos_id=run_kw["eos_id"],
                             rng=decode_rng(run_kw.get("rng")),
                             kv_blocks=run_kw["kv_blocks"],
                             admission=adm)
            except (TransportError, RetriesExhausted):
                # the ROUTER side of the wire failed mid-run: that is
                # a peer death, not an engine error — escalate to the
                # classified exit below, never an ERR frame into a
                # broken stream
                raise
            except Exception as exc:     # noqa: BLE001 — shipped home
                chan.send(("ERR", type(exc).__name__, str(exc),
                           adm.rtt_ms, adm.retries))
                continue
            out = {int(r): np.asarray(v) for r, v in res.items()}
            stats = engine.last_stats
            if adm.warm_dropped:
                # wire-corrupt warm chains never reached seed_warm, so
                # the engine could not bill them — fold the drops into
                # the warm stats here (0 in clean runs: bit-match with
                # the thread fleet holds)
                prefix = dict(stats.get("prefix") or {})
                warm = dict(prefix.get("warm") or {})
                warm["seed_dropped"] = (warm.get("seed_dropped", 0)
                                        + adm.warm_dropped)
                prefix["warm"] = warm
                stats = dict(stats, prefix=prefix)
            chan.send(("DONE", out, stats,
                       adm.rtt_ms, adm.retries))
    except (TransportError, RetriesExhausted):
        # classified peer/stream death: the router is gone or the
        # stream desynchronised — exit with the classified code
        # (resilience.classify_exit → "peer_dead")
        os._exit(EXIT_PEER_DEAD)
    finally:
        chan.close()


class _ProcHandle(ReplicaHandle):
    """One multi-proc replica run: the parent-side RPC handler. A
    daemon thread sends the RUN frame, then serves the replica's
    admission polls against the real router queue until DONE/ERR —
    or until a poll raises ``ReplicaKilled``, at which point the
    fault plane's kill becomes a REAL ``SIGKILL`` of the replica
    process at the identical poll boundary. Unexpected child death
    (EOF, crash, OOM-kill) classifies the replica dead through the
    same ``queue.dead`` flag the in-proc fault seam sets, so the
    router's redrive machinery recovers identically."""

    poll_s = 0.05

    def __init__(self, transport: "MultiProcTransport", i: int,
                 proc, chan: FrameChannel, queue, run_kw: dict,
                 on_error) -> None:
        self.label = f"decode-{i}"
        self.error = None
        self._transport = transport
        self._i = i
        self._proc = proc
        self._chan = chan
        self._queue = queue
        self._run_kw = run_kw
        self._on_error = on_error
        self._result = None
        self._stats = None
        self._killed = False
        self._done = threading.Event()
        self._thread = threading.Thread(
            target=self._serve, daemon=True,
            name=f"fleet-rpc-{self.label}")
        self._thread.start()

    def _serve(self) -> None:
        from .fleet import ReplicaKilled

        try:
            try:
                self._chan.send(("RUN", self._run_kw))
                while True:
                    try:
                        msg = self._chan.recv(self.poll_s)
                    except TransportTimeout:
                        if not self._proc.is_alive():
                            raise TransportDead(
                                f"{self.label}: replica process "
                                f"pid={self._proc.pid} died "
                                f"(exitcode={self._proc.exitcode}) "
                                f"mid-run") from None
                        continue
                    if msg[0] == "REQ":
                        _, method, args = msg
                        try:
                            if method == "chain_sink":
                                # the store itself stays router-side
                                # (locks + host pool do not pickle):
                                # the replica only learns whether a
                                # sink exists and publishes over RPC
                                value = (self._queue.chain_sink()
                                         is not None)
                            elif method == "publish_chains":
                                value = self._publish(args[0])
                            else:
                                value = getattr(self._queue,
                                                method)(*args)
                        except ReplicaKilled:
                            # the fault plane fired at this poll
                            # boundary: make it REAL — SIGKILL the
                            # replica process (queue.dead is already
                            # set by _pulse; the router redrives)
                            self._sigkill()
                            return
                        except Exception as exc:  # noqa: BLE001 — shipped to replica
                            self._chan.send(
                                ("REP", ("EXC",
                                         f"{type(exc).__name__}: "
                                         f"{exc}")))
                            continue
                        if method == "kv_import" and value is not None:
                            value = dict(
                                value,
                                first=np.asarray(value["first"]),
                                blocks=encode_block_payload(
                                    value["blocks"]))
                        elif method == "warm_chains" and value:
                            value = encode_warm_chains(value)
                            self._transport.metrics.warm_bytes(
                                warm_chains_nbytes(value))
                        self._chan.send(("REP", ("OK", value)))
                    elif msg[0] == "DONE":
                        _, out, stats, rtt_ms, retries = msg
                        self._result = out
                        self._stats = stats
                        self._transport.metrics.rtt_ms(rtt_ms)
                        self._transport.metrics.retries(retries)
                        return
                    elif msg[0] == "ERR":
                        _, tname, text, rtt_ms, retries = msg
                        self._transport.metrics.rtt_ms(rtt_ms)
                        self._transport.metrics.retries(retries)
                        exc = RuntimeError(
                            f"[replica process {tname}] {text}")
                        self.error = exc
                        self._on_error(self.label, exc)
                        return
                    else:
                        raise TransportProtocolError(
                            f"{self.label}: unexpected frame "
                            f"{msg[0]!r} mid-run")
            except (TransportDead, TransportProtocolError) as exc:
                # terminal transport failure: classify the replica
                # dead through the same flag the in-proc kill seam
                # sets — the router's take_lost→redrive machinery
                # recovers; never a hang, never a silent strand
                self.error = exc
                self._queue.dead = True
                self._transport._discard_child(self._i)
        finally:
            self._done.set()

    def _publish(self, wire_chains) -> int:
        """The drain-side landing of ``publish_chains``: verify each
        chain's ``transfer_crc`` before it touches the fleet-shared
        store (a corrupt chain is dropped here, never stored), then
        hand the survivors to the real sink."""
        sink = self._queue.chain_sink()
        if sink is None:
            return 0
        self._transport.metrics.warm_bytes(
            warm_chains_nbytes(wire_chains))
        chains, _dropped = decode_warm_chains(wire_chains)
        return sink.publish(chains)

    def _sigkill(self) -> None:
        self._killed = True
        try:
            os.kill(self._proc.pid, signal.SIGKILL)
        except (ProcessLookupError, OSError):
            pass                         # already gone — same outcome
        self._proc.join(5.0)
        self._transport._discard_child(self._i)

    def is_alive(self) -> bool:
        return not self._done.is_set()

    def join(self, timeout: float) -> bool:
        return self._done.wait(timeout)

    def result(self):
        return self._result

    def stats(self):
        return self._stats

    def kill(self) -> None:
        """Hard stop: SIGKILL the replica process (the hung-worker
        escape hatch — a real process can always be reaped, which is
        exactly what a thread replica cannot offer)."""
        if self._proc.is_alive():
            self._sigkill()
        self._done.set()


def _close_at_exit(ref) -> None:
    """The atexit backstop behind a weakref: reap whatever children a
    still-live transport knows about when the interpreter exits
    without an explicit ``close()`` — without the weakref, the atexit
    registry would pin every transport (and its children's pipes)
    alive for the interpreter's whole lifetime."""
    transport = ref()
    if transport is not None:
        transport.close()


class MultiProcTransport(Transport):
    """Replicas as real, persistent subprocesses (spawn context — a
    forked JAX runtime deadlocks) connected by framed OS pipes. Every
    ``launch_decode`` reuses the replica's warm child when it is
    alive and respawns it when it is not (the call after a SIGKILL —
    bring-up under ``utils/retry`` capped backoff, the respawn billed
    on ``transport_child_respawn_total``). Orphan-reaper discipline is
    two-sided: ``close()`` runs at interpreter exit through a weakref
    atexit hook, and every child watches its parent pid and exits
    ``EXIT_PEER_DEAD`` when reparented — a crashed parent strands no
    child even if it died between spawn and registry insert."""

    name = "multiproc"
    process_isolated = True

    def __init__(self, *, reply_timeout_s: float = 15.0,
                 spawn_timeout_s: float = 180.0):
        if reply_timeout_s <= 0:
            raise ValueError(
                f"reply_timeout_s must be > 0, got {reply_timeout_s}")
        if spawn_timeout_s <= 0:
            raise ValueError(
                f"spawn_timeout_s must be > 0, got {spawn_timeout_s}")
        self.reply_timeout_s = reply_timeout_s
        self.spawn_timeout_s = spawn_timeout_s
        self.metrics = TransportMetrics(None)
        self._key = None
        self._lock = threading.Lock()
        self._children: dict[int, tuple] = {}     # i -> (proc, chan)
        self._params_wire: bytes | None = None    # pickled ONCE/config
        self._params_nbytes = 0
        self._registry = None
        self._atexit_registered = False
        self.pre_engines: list = []

    def configure(self, *, params, cfg, max_len, engine_kw, registry,
                  n_dec, n_pre) -> None:
        sampler = engine_kw.get("sampler")
        if sampler is not None and not isinstance(sampler, dict):
            raise ValueError(
                "MultiProcTransport needs the sampler as a SPEC dict "
                "(dict(temperature=..., top_k=..., top_p=...)) — a "
                "raw sampler callable does not pickle across the "
                "process boundary; make_serve_engine normalises the "
                "spec through decode.make_sampler identically on both "
                "sides")
        key = (id(params), cfg, max_len, tuple(sorted(
            (k, repr(v)) for k, v in engine_kw.items())))
        self.metrics = TransportMetrics(registry)
        self._registry = registry
        if key == self._key:
            # unchanged config: keep warm children (their compiles);
            # just grow the parent-side prefill pool to the new shape
            while len(self.pre_engines) < n_pre:
                self.pre_engines.append(self._build_prefill())
            return
        self.close()
        self._key = key
        self._params, self._cfg, self._max_len = params, cfg, max_len
        self._engine_kw = dict(engine_kw)
        self._params_wire = None         # re-snapshot lazily
        # disaggregated prefill workers stay PARENT-side in every
        # current transport: the handoff payload (crc-stamped paged
        # blocks riding the kv_import RPC) is the cross-boundary
        # object, not the worker itself
        self.pre_engines = [self._build_prefill() for _ in range(n_pre)]

    def _build_prefill(self):
        return make_serve_engine(self._params, self._cfg,
                                 max_len=self._max_len,
                                 telemetry=self._registry,
                                 **self._engine_kw)

    def ensure_engine(self, i: int):
        """Bring up (or reuse) replica ``i``'s child ahead of a
        scale-up launch: spawn + READY handshake under
        ``_SPAWN_PROC_RETRY``. Exhaustion propagates — the fleet's
        spawn discipline classifies the target dead and its planned
        requests redrive; a scale-up NEVER hangs on a spawn that
        cannot succeed."""
        with self._lock:
            child = self._children.get(i)
        if child is not None:
            if child[0].is_alive():
                return child
            # died since its last run (SIGKILL, crash): reap, respawn
            self._discard_child(i)
            self.metrics.respawn()
        child = self._spawn(i)
        with self._lock:
            self._children[i] = child
        return child

    def prefill_engine(self, i: int):
        return self.pre_engines[i]

    def _param_wire(self) -> bytes:
        """The donor weight stream, pickled ONCE per configure: one
        immutable crc-stamped host snapshot
        (``hostkv.HostParamSnapshot``) shared by every joiner — N
        scale-ups used to re-``device_get`` and re-pickle the full
        weight tree per child; now they frame the identical shared
        bytes (``FrameChannel.send_raw``), billed per child in
        ``transport_bytes_total``."""
        if self._params_wire is None:
            from .hostkv import HostParamSnapshot

            snap = HostParamSnapshot(self._params)
            self._params_wire = pickle.dumps(
                ("PARAMS", snap.encode()), pickle.HIGHEST_PROTOCOL)
            self._params_nbytes = snap.nbytes
        return self._params_wire

    def _spawn(self, i: int):
        """Bring up replica ``i``: spawn + READY handshake, the whole
        unit retried under capped backoff (a transient spawn failure
        costs a retry; exhaustion propagates and the fleet classifies
        the replica dead — its planned requests redrive)."""
        import multiprocessing as mp

        ctx = mp.get_context("spawn")
        wire = self._param_wire()        # shared: built outside retry
        # the parent-side half of the orphan-reaper contract: close()
        # at interpreter exit reaps every REGISTERED child; the
        # child-side parent-pid watchdog (started before the engine
        # build) covers the window between spawn and registry insert,
        # where a parent crash would otherwise strand the child
        if not self._atexit_registered:
            self._atexit_registered = True
            atexit.register(_close_at_exit, weakref.ref(self))

        def bring_up():
            parent_conn, child_conn = ctx.Pipe(duplex=True)
            proc = ctx.Process(
                target=_replica_child_main,
                args=(child_conn, i, None, self._cfg,
                      self._max_len, self._engine_kw,
                      self.reply_timeout_s, os.getpid()),
                daemon=True, name=f"fleet-replica-{i}")
            proc.start()
            child_conn.close()
            chan = FrameChannel(parent_conn, metrics=self.metrics,
                                label=f"replica-{i}/router")
            try:
                # weights ride the pipe, not the spawn args: the same
                # pre-pickled snapshot bytes for every joiner, crc-
                # verified child-side before its engine build
                chan.send_raw(wire)
                msg = chan.recv(self.spawn_timeout_s)
                if msg[0] != "READY" or msg[1] != i:
                    raise TransportProtocolError(
                        f"replica-{i}: bad READY handshake: {msg!r}")
            except TransportError:
                chan.close()
                if proc.is_alive():
                    proc.terminate()
                proc.join(5.0)
                raise
            return proc, chan

        return retry_call(bring_up, policy=_SPAWN_PROC_RETRY,
                          what=f"replica-{i} process spawn",
                          retryable=(TransportError,))

    def _discard_child(self, i: int) -> None:
        with self._lock:
            child = self._children.pop(i, None)
        if child is not None:
            proc, chan = child
            chan.close()
            if proc.is_alive():
                proc.terminate()
            proc.join(5.0)

    def launch_decode(self, i, queue, run_kw, *, on_error):
        with self._lock:
            child = self._children.get(i)
        if child is not None and not child[0].is_alive():
            # killed (or crashed) on a previous call: reap and respawn
            self._discard_child(i)
            self.metrics.respawn()
            child = None
        if child is None:
            child = self._spawn(i)
            with self._lock:
                self._children[i] = child
        proc, chan = child
        wire_kw = {
            "prompts": [np.asarray(p) for p in run_kw["prompts"]],
            "budgets": [int(b) for b in run_kw["budgets"]],
            "slots": run_kw["slots"],
            "eos_id": run_kw["eos_id"],
            "rng": encode_rng(run_kw.get("rng")),
            "kv_blocks": run_kw["kv_blocks"],
        }
        return _ProcHandle(self, i, proc, chan, queue, wire_kw,
                           on_error)

    def warm_replica(self, i, warm_kw):
        """AOT-warm replica ``i``'s child over the wire: ensure the
        child is up (spawn + weight stream + READY), send WARM, wait
        for WARMED under the spawn budget (compiles ARE the spawn
        cost). The stats dict ships home; a child-side warm failure
        arrives as ``{"error": ...}`` stats, never a dead child."""
        proc, chan = self.ensure_engine(i)
        del proc
        chan.send(("WARM", dict(warm_kw)))
        msg = chan.recv(self.spawn_timeout_s)
        if msg[0] != "WARMED":
            raise TransportProtocolError(
                f"replica-{i}: unexpected frame {msg[0]!r} while "
                f"waiting for WARMED")
        return msg[1]

    def close(self) -> None:
        with self._lock:
            children, self._children = dict(self._children), {}
        for proc, chan in children.values():
            try:
                chan.send(("EXIT",))
            except TransportError:
                pass                     # already dead — reap below
            proc.join(2.0)
            chan.close()
            if proc.is_alive():
                proc.terminate()
                proc.join(2.0)
            if proc.is_alive():
                os.kill(proc.pid, signal.SIGKILL)
                proc.join(2.0)
