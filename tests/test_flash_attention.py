# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""Pallas flash attention: exactness vs dense, grads, burn-in integration.

Runs in pallas interpret mode on the virtual CPU mesh (the kernel's TPU
lowering shares the same trace), mirroring how tfsim stands in for terraform:
full logic coverage offline, hardware numbers from bench.py on the chip.
"""

import jax
import jax.numpy as jnp
import pytest

from nvidia_terraform_modules_tpu.models import (
    BurnInConfig,
    forward,
    init_params,
    make_train_step,
    synthetic_batch,
)
from nvidia_terraform_modules_tpu.ops import flash_attention
from nvidia_terraform_modules_tpu.ops.ring_attention import (
    dense_reference_attention,
)
from nvidia_terraform_modules_tpu.parallel import build_mesh, make_rules, plan_mesh


def _qkv(b=2, s=64, h=2, d=16, dtype=jnp.float32):
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    return tuple(jax.random.normal(k, (b, s, h, d), dtype) for k in ks)


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("block", [16, 32, 64])
def test_flash_matches_dense(causal, block):
    q, k, v = _qkv()
    out = flash_attention(q, k, v, causal=causal, block_q=block, block_k=block)
    ref = dense_reference_attention(q, k, v, causal=causal)
    assert jnp.max(jnp.abs(out - ref)) < 1e-5


def test_flash_rectangular_blocks():
    q, k, v = _qkv(s=64)
    out = flash_attention(q, k, v, block_q=16, block_k=32)
    ref = dense_reference_attention(q, k, v)
    assert jnp.max(jnp.abs(out - ref)) < 1e-5


def test_flash_gradients_match_dense():
    q, k, v = _qkv(s=32)

    def f1(q, k, v):
        return jnp.sum(jnp.square(flash_attention(q, k, v, block_q=16,
                                                  block_k=16)))

    def f2(q, k, v):
        return jnp.sum(jnp.square(dense_reference_attention(q, k, v)))

    g1 = jax.grad(f1, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(f2, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        assert jnp.max(jnp.abs(a - b)) < 1e-4


def test_flash_bf16_close_to_f32_dense():
    q, k, v = _qkv(s=32, dtype=jnp.bfloat16)
    out = flash_attention(q, k, v).astype(jnp.float32)
    ref = dense_reference_attention(
        *(t.astype(jnp.float32) for t in (q, k, v)))
    assert jnp.max(jnp.abs(out - ref)) < 0.05  # bf16 inputs, f32 accumulate


def test_flash_blocks_autoshrink_to_divisor():
    # S=48 with requested 32 → blocks shrink to 24; numbers unchanged
    q, k, v = _qkv(s=48)
    out = flash_attention(q, k, v, block_q=32, block_k=32)
    ref = dense_reference_attention(q, k, v)
    assert jnp.max(jnp.abs(out - ref)) < 1e-5


def test_flash_rejects_untileable_seq():
    # prime S with a smaller requested block leaves no divisor ≥ 8
    q, k, v = _qkv(s=97)
    with pytest.raises(ValueError, match="no block divisor"):
        flash_attention(q, k, v, block_q=32, block_k=32)


def test_fit_block_only_returns_sublane_multiples():
    """ADVICE round-1: block sizes must be 8-multiples — odd divisors like
    125 (S=250) pass CPU interpret but real-TPU pallas rejects them."""
    from nvidia_terraform_modules_tpu.ops.flash_attention import _fit_block
    assert _fit_block(192, None) == 96          # not 64? 96 divides and is 8k
    assert _fit_block(250, None) == 0           # 125 must NOT be picked
    # None default is min(1024, max(128, S/4)) — the measured v5e q-block
    # rule (1024x1024 runs S=4096 2x faster than the old 512 default)
    assert _fit_block(4096, None) == 1024
    assert _fit_block(48, 32) == 24             # 24 = 3×8, divides 48
    assert _fit_block(8, None) == 8
    assert _fit_block(4, None) == 4             # tiny interpret-only shapes
    for s in (128, 192, 256, 384, 512, 1024, 4096):
        b = _fit_block(s, None)
        assert b % 8 == 0 and s % b == 0
    # S=250 now takes the explicit pad-the-sequence error path
    q, k, v = _qkv(s=250)
    with pytest.raises(ValueError, match="pad the sequence"):
        flash_attention(q, k, v)


# ------------------------------------------------- fused backward (PR 4)

def _grads(fn, q, k, v):
    """(dq, dk, dv) of the scalar loss sum(fn(q,k,v)²)."""
    return jax.grad(
        lambda q_, k_, v_: jnp.sum(
            jnp.square(fn(q_, k_, v_).astype(jnp.float32))),
        argnums=(0, 1, 2))(q, k, v)


# square blocks, rectangular blocks, and an autoshrink shape (S=48 with
# requested 32 → blocks shrink to the non-power-of-two divisor 24)
_BWD_BLOCK_CASES = [
    ("square", 64, 16, 16),
    ("rect", 64, 16, 32),
    ("autoshrink", 48, 32, 32),
]


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16],
                         ids=["f32", "bf16"])
@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("case", _BWD_BLOCK_CASES, ids=lambda c: c[0])
def test_fused_backward_parity_matrix(case, causal, dtype):
    """The differential-correctness oracle for the single-pass backward:
    fused vs dense ``jax.grad`` reference AND fused vs split, across
    causal × non-causal, square × rectangular blocks, f32 × bf16, and an
    autoshrink (non-divisible S) shape — interpret mode on CPU. The full
    matrix is slow-marked; test_fused_backward_tier1_seed keeps one seed
    in the fast profile."""
    _, s, bq, bk = case
    q, k, v = _qkv(s=s, dtype=dtype)

    def flash(mode):
        return lambda q_, k_, v_: flash_attention(
            q_, k_, v_, causal=causal, block_q=bq, block_k=bk,
            backward=mode)

    g_fused = _grads(flash("fused"), q, k, v)
    g_split = _grads(flash("split"), q, k, v)
    g_dense = _grads(
        lambda q_, k_, v_: dense_reference_attention(q_, k_, v_,
                                                     causal=causal),
        q, k, v)
    # fused and split share _bwd_tile and accumulate in the same order, so
    # interpret mode should agree to f32 rounding; dense is the analytic
    # reference with a dtype-dependent tolerance
    tol_split = 1e-6 if dtype == jnp.float32 else 1e-2
    tol_dense = 1e-4 if dtype == jnp.float32 else 0.15
    for gf, gs, gd in zip(g_fused, g_split, g_dense):
        assert jnp.max(jnp.abs(gf - gs)) < tol_split
        assert jnp.max(jnp.abs(gf - gd)) < tol_dense


def test_fused_backward_tier1_seed():
    """One fused interpret-mode seed of the parity matrix stays tier-1
    (causal, square blocks, f32) so the default backward path is gated on
    every fast run without paying for the full sweep."""
    q, k, v = _qkv(s=32)

    def flash(mode):
        return lambda q_, k_, v_: flash_attention(
            q_, k_, v_, block_q=16, block_k=16, backward=mode)

    g_fused = _grads(flash("fused"), q, k, v)
    g_split = _grads(flash("split"), q, k, v)
    g_dense = _grads(dense_reference_attention, q, k, v)
    for gf, gs, gd in zip(g_fused, g_split, g_dense):
        assert jnp.max(jnp.abs(gf - gs)) < 1e-6
        assert jnp.max(jnp.abs(gf - gd)) < 1e-4


def test_backward_knob_validated():
    q, k, v = _qkv(s=16)
    with pytest.raises(ValueError, match="fused|split"):
        flash_attention(q, k, v, backward="bogus")
    with pytest.raises(ValueError, match="flash_backward"):
        BurnInConfig(flash_backward="bogus")


def _count_pallas_calls(jaxpr) -> int:
    """Recursively count pallas_call eqns in a (Closed)Jaxpr."""
    inner = getattr(jaxpr, "jaxpr", jaxpr)
    total = 0
    for eqn in inner.eqns:
        if eqn.primitive.name == "pallas_call":
            total += 1
        for val in eqn.params.values():
            for sub in (val if isinstance(val, (list, tuple)) else (val,)):
                if hasattr(sub, "eqns") or hasattr(sub, "jaxpr"):
                    total += _count_pallas_calls(sub)
    return total


@pytest.mark.parametrize("backward,expected", [("fused", 1), ("split", 2)])
def test_backward_lowering_pallas_call_count(backward, expected):
    """Lowering regression: the fused path must stage exactly ONE backward
    pallas_call (and split exactly two) — a silent fallback to the split
    kernels can never masquerade as a perf win. Counted on the vjp
    function's jaxpr, which contains only the backward (the forward ran
    eagerly; its residuals are constants)."""
    q, k, v = _qkv(s=32)
    _, vjp_fn = jax.vjp(
        lambda q_, k_, v_: flash_attention(q_, k_, v_, block_q=16,
                                           block_k=16, backward=backward),
        q, k, v)
    jaxpr = jax.make_jaxpr(vjp_fn)(jnp.ones_like(q))
    assert _count_pallas_calls(jaxpr) == expected


def test_burnin_flash_matches_dense_forward_unsharded():
    base = dict(vocab=64, d_model=32, n_heads=2, d_ff=64, n_layers=2,
                seq_len=16, batch=4, dtype=jnp.float32)
    cfg_d = BurnInConfig(**base, attn="dense")
    cfg_f = BurnInConfig(**base, attn="flash")
    params = init_params(jax.random.PRNGKey(0), cfg_d)
    tokens, _ = synthetic_batch(jax.random.PRNGKey(1), cfg_d)
    dense = forward(params, tokens, cfg_d)
    flash = forward(params, tokens, cfg_f)
    assert jnp.max(jnp.abs(dense - flash)) < 1e-5


def test_burnin_flash_matches_dense_forward_sharded(jax8):
    mesh = build_mesh(plan_mesh(8, tp=2, sp=2))
    rules = make_rules(mesh)
    base = dict(vocab=64, d_model=32, n_heads=2, d_ff=64, n_layers=1,
                seq_len=16, batch=8, dtype=jnp.float32)
    cfg_d = BurnInConfig(**base, attn="dense")
    cfg_f = BurnInConfig(**base, attn="flash")
    params = init_params(jax.random.PRNGKey(0), cfg_d, rules)
    tokens, _ = synthetic_batch(jax.random.PRNGKey(1), cfg_d, rules)
    dense = forward(params, tokens, cfg_d, rules)
    flash = forward(params, tokens, cfg_f, rules)
    assert jnp.max(jnp.abs(dense - flash)) < 1e-5


def test_burnin_flash_train_step_decreases_loss(jax8):
    mesh = build_mesh(plan_mesh(8, tp=2, sp=2))
    rules = make_rules(mesh)
    cfg = BurnInConfig(vocab=64, d_model=32, n_heads=2, d_ff=64, n_layers=1,
                       seq_len=16, batch=8, attn="flash")
    params = init_params(jax.random.PRNGKey(0), cfg, rules)
    step = make_train_step(cfg, rules, lr=5e-2)
    batch = synthetic_batch(jax.random.PRNGKey(1), cfg, rules)
    losses = []
    for _ in range(4):
        params, loss = step(params, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses


# ------------------------------------------- pipelined + splash (PR 9)

from nvidia_terraform_modules_tpu.ops.flash_attention import (  # noqa: E402
    MASK_DEAD,
    MASK_FULL,
    MASK_PARTIAL,
    FLASH_VMEM_BUDGET,
    MaskSpec,
    as_mask_spec,
    auto_blocks,
    block_liveness,
    flash_vmem_bytes,
    mask_live_frac,
    splash_stats,
)


def test_pipelined_bitmatches_unpipelined_tier1():
    """The pipeline's core contract, gated on every fast run: at equal
    block sizes the paired-sub-tile kernels fold the SAME sub-tiles in the
    SAME order with the same ops, so forward AND fused gradients BIT-match
    the serial kernels — the property flash_pipeline_ok re-checks on the
    chip's real lowering."""
    q, k, v = _qkv(s=64)

    def flash(pipeline):
        return lambda q_, k_, v_: flash_attention(
            q_, k_, v_, block_q=16, block_k=16, pipeline=pipeline)

    o_on = flash("on")(q, k, v)
    o_off = flash("off")(q, k, v)
    assert jnp.array_equal(o_on, o_off)
    for g_on, g_off in zip(_grads(flash("on"), q, k, v),
                           _grads(flash("off"), q, k, v)):
        assert jnp.array_equal(g_on, g_off)


@pytest.mark.slow
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16],
                         ids=["f32", "bf16"])
@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("case", _BWD_BLOCK_CASES, ids=lambda c: c[0])
def test_pipelined_parity_matrix(case, causal, dtype):
    """Differential oracle for the software-pipelined kernels: pipelined
    vs dense ``jax.grad`` reference AND pipelined vs the PR-4 fused
    (serial) kernels, across causal × non-causal, square × rectangular
    blocks, f32 × bf16, and an autoshrink shape. The serial comparison is
    BITWISE — the pipeline is a scheduling change, never an arithmetic
    one; tier-1 keeps one seed via
    test_pipelined_bitmatches_unpipelined_tier1."""
    _, s, bq, bk = case
    q, k, v = _qkv(s=s, dtype=dtype)

    def flash(pipeline):
        return lambda q_, k_, v_: flash_attention(
            q_, k_, v_, causal=causal, block_q=bq, block_k=bk,
            pipeline=pipeline)

    assert jnp.array_equal(flash("on")(q, k, v), flash("off")(q, k, v))
    g_pipe = _grads(flash("on"), q, k, v)
    g_base = _grads(flash("off"), q, k, v)
    g_dense = _grads(
        lambda q_, k_, v_: dense_reference_attention(q_, k_, v_,
                                                     causal=causal),
        q, k, v)
    tol_dense = 1e-4 if dtype == jnp.float32 else 0.15
    for gp, gb, gd in zip(g_pipe, g_base, g_dense):
        assert jnp.array_equal(gp, gb)
        assert jnp.max(jnp.abs(gp - gd)) < tol_dense


def test_pipeline_knob_validated():
    q, k, v = _qkv(s=64)
    with pytest.raises(ValueError, match="auto|on|off"):
        flash_attention(q, k, v, pipeline="bogus")
    # block_k = whole sequence -> one K block: "on" must refuse loudly
    with pytest.raises(ValueError, match="even number of K blocks"):
        flash_attention(q, k, v, block_q=16, block_k=64, pipeline="on")
    with pytest.raises(ValueError, match="flash_pipeline"):
        BurnInConfig(flash_pipeline="bogus")


def test_pipeline_auto_degrades_on_odd_tiling():
    """pipeline='auto' with an odd K tiling must fall back to the serial
    kernels silently (same numbers), never raise."""
    q, k, v = _qkv(s=48)
    out = flash_attention(q, k, v, block_q=16, block_k=48)  # nk = 1
    ref = dense_reference_attention(q, k, v)
    assert jnp.max(jnp.abs(out - ref)) < 1e-5


def _pallas_eqns(jaxpr):
    """Recursively collect pallas_call eqns from a (Closed)Jaxpr."""
    inner = getattr(jaxpr, "jaxpr", jaxpr)
    out = []
    for eqn in inner.eqns:
        if eqn.primitive.name == "pallas_call":
            out.append(eqn)
        for val in eqn.params.values():
            for sub in (val if isinstance(val, (list, tuple)) else (val,)):
                if hasattr(sub, "eqns") or hasattr(sub, "jaxpr"):
                    out.extend(_pallas_eqns(sub))
    return out


@pytest.mark.parametrize("pipeline,k_steps", [("on", 2), ("off", 4)])
def test_pipeline_lowering_grid_pin(pipeline, k_steps):
    """Lowering regression: the pipelined fused backward must stage ONE
    pallas_call whose k grid dimension iterates sub-tile PAIRS (nk/2), the
    serial one the full nk — a silent fallback to the unpipelined path
    (or an extra kernel) fails tier-1 here, exactly like the fused/split
    pin above."""
    q, k, v = _qkv(s=64)
    _, vjp_fn = jax.vjp(
        lambda q_, k_, v_: flash_attention(q_, k_, v_, block_q=16,
                                           block_k=16, pipeline=pipeline),
        q, k, v)
    eqns = _pallas_eqns(jax.make_jaxpr(vjp_fn)(jnp.ones_like(q)))
    assert len(eqns) == 1
    assert eqns[0].params["grid_mapping"].grid[-1] == k_steps
    # forward: same pairing on the same grid axis
    fwd_eqns = _pallas_eqns(jax.make_jaxpr(
        lambda q_, k_, v_: flash_attention(q_, k_, v_, block_q=16,
                                           block_k=16,
                                           pipeline=pipeline))(q, k, v))
    assert len(fwd_eqns) == 1
    assert fwd_eqns[0].params["grid_mapping"].grid[-1] == k_steps


def test_splash_lowering_stays_pallas():
    """A splash (window) mask must lower to the SAME single pallas kernels
    as causal — same grid, liveness riding as data — never fall back to a
    dense XLA attention or a per-mask kernel zoo."""
    q, k, v = _qkv(s=64)

    def run(mask):
        fn = lambda q_, k_, v_: flash_attention(  # noqa: E731
            q_, k_, v_, block_q=16, block_k=16, mask=mask)
        fwd = _pallas_eqns(jax.make_jaxpr(fn)(q, k, v))
        _, vjp_fn = jax.vjp(fn, q, k, v)
        bwd = _pallas_eqns(jax.make_jaxpr(vjp_fn)(jnp.ones_like(q)))
        return fwd, bwd

    fwd_w, bwd_w = run(("window", 24))
    fwd_c, bwd_c = run(None)
    assert len(fwd_w) == 1 and len(bwd_w) == 1
    assert (fwd_w[0].params["grid_mapping"].grid
            == fwd_c[0].params["grid_mapping"].grid)
    assert (bwd_w[0].params["grid_mapping"].grid
            == bwd_c[0].params["grid_mapping"].grid)


# ------------------------------------------------------- splash masks

def test_window_mask_matches_dense_masked():
    """Block-sparse window attention vs the dense-masked XLA reference:
    forward and gradients, window straddling block boundaries."""
    q, k, v = _qkv(s=64)
    w = 24

    def flash(q_, k_, v_):
        return flash_attention(q_, k_, v_, block_q=16, block_k=16,
                               mask=("window", w))

    def dense(q_, k_, v_):
        return dense_reference_attention(q_, k_, v_, window=w)

    assert jnp.max(jnp.abs(flash(q, k, v) - dense(q, k, v))) < 1e-5
    for gf, gd in zip(_grads(flash, q, k, v), _grads(dense, q, k, v)):
        assert jnp.max(jnp.abs(gf - gd)) < 1e-4


def test_window_covering_seq_bitmatches_causal():
    """window >= S keeps every causal element live: the splash map and the
    kernels must produce BIT-identical outputs to plain causal."""
    q, k, v = _qkv(s=64)
    o_w = flash_attention(q, k, v, block_q=16, block_k=16,
                          mask=("window", 64))
    o_c = flash_attention(q, k, v, block_q=16, block_k=16)
    assert jnp.array_equal(o_w, o_c)


def test_window_composes_with_pipeline_and_split():
    """Splash masking threads through every backward path: pipelined
    fused, serial fused, and the historical split kernels agree."""
    q, k, v = _qkv(s=64)

    def flash(backward, pipeline):
        return lambda q_, k_, v_: flash_attention(
            q_, k_, v_, block_q=16, block_k=16, mask=("window", 20),
            backward=backward, pipeline=pipeline)

    g_pipe = _grads(flash("fused", "on"), q, k, v)
    g_base = _grads(flash("fused", "off"), q, k, v)
    g_split = _grads(flash("split", "off"), q, k, v)
    for gp, gb, gs in zip(g_pipe, g_base, g_split):
        assert jnp.array_equal(gp, gb)
        assert jnp.max(jnp.abs(gp - gs)) < 1e-6


def test_block_liveness_matches_elementwise_brute_force():
    """The splash map generalises _causal_live: every (q-block, k-block)
    class must equal the brute-force elementwise reduction of the mask
    predicate over the tile."""
    import numpy as np

    for spec in (MaskSpec("causal"), MaskSpec("full"),
                 MaskSpec("window", 5), MaskSpec("window", 16),
                 MaskSpec("window", 37)):
        for bq, bk in ((8, 8), (8, 16), (16, 8)):
            s = 64
            nq, nk = s // bq, s // bk
            live = block_liveness(spec, nq, nk, bq, bk)
            qp = np.arange(s)[:, None]
            kp = np.arange(s)[None, :]
            if spec.kind == "full":
                keep = np.ones((s, s), bool)
            else:
                keep = qp >= kp
                if spec.kind == "window":
                    keep &= qp - kp < spec.window
            for i in range(nq):
                for j in range(nk):
                    tile = keep[i * bq:(i + 1) * bq, j * bk:(j + 1) * bk]
                    want = (MASK_FULL if tile.all() else
                            MASK_DEAD if not tile.any() else MASK_PARTIAL)
                    assert live[i, j] == want, (spec, i, j)


def test_splash_stats_and_live_frac():
    st = splash_stats(MaskSpec("causal"), 64, 64, 16, 16)
    assert st["total"] == 16 and st["dead"] == 6
    assert st["skip_frac"] == 0.375
    # a tight window kills strictly more tiles than causal
    st_w = splash_stats(MaskSpec("window", 8), 64, 64, 16, 16)
    assert st_w["dead"] > st["dead"]
    assert mask_live_frac(MaskSpec("causal"), 64) == 0.5
    assert mask_live_frac(MaskSpec("full"), 64) == 1.0
    assert 0 < mask_live_frac(MaskSpec("window", 8), 64) < 0.25


def test_mask_spec_validated():
    with pytest.raises(ValueError, match="causal|full|window"):
        MaskSpec("diagonal")
    with pytest.raises(ValueError, match="window >= 1"):
        MaskSpec("window")
    with pytest.raises(ValueError, match="takes no window"):
        MaskSpec("causal", 8)
    with pytest.raises(ValueError, match="unknown mask"):
        as_mask_spec(42)
    assert as_mask_spec(None, causal=False) == MaskSpec("full")
    assert as_mask_spec(("window", 8)) == MaskSpec("window", 8)
    q, k, v = _qkv(s=16)
    with pytest.raises(ValueError, match="flash_window"):
        BurnInConfig(flash_window=0)
    with pytest.raises(ValueError, match="window masking implies causal"):
        dense_reference_attention(q, k, v, causal=False, window=4)


# ------------------------------------------------- VMEM-budget autoshrink

def test_auto_blocks_reproduces_measured_v5e_defaults():
    """The budget computation must land exactly on the round-5 measured
    defaults at the flagship shapes (bf16, itemsize 2): the table became a
    consequence, not an input."""
    assert auto_blocks(4096, 128, 2, pipe=False) == (1024, 1024, False)
    assert auto_blocks(2048, 128, 2, pipe=False) == (512, 1024, False)
    # the pipelined kernels hold two K sub-tiles in flight: same budget,
    # half the K width at the flagship
    assert auto_blocks(4096, 128, 2, pipe=True) == (1024, 512, True)
    # narrow heads leave VMEM headroom the old cap-1024 table wasted
    assert auto_blocks(4096, 64, 2, pipe=False) == (1024, 2048, False)


def test_auto_blocks_rejects_what_failed_on_chip():
    """PROFILE_r05: 2048-wide tiles at d=128 failed to compile (VMEM).
    The plan must price them over budget so they can never be selected."""
    assert flash_vmem_bytes(1024, 2048, 4096, 128, 2,
                            pipe=False) > FLASH_VMEM_BUDGET
    assert flash_vmem_bytes(2048, 1024, 4096, 128, 2,
                            pipe=False) > FLASH_VMEM_BUDGET
    # and the selected defaults must fit, forward and backward
    for pipe in (False, True):
        bq, bk, _ = auto_blocks(4096, 128, 2, pipe=pipe)
        assert flash_vmem_bytes(bq, bk, 4096, 128, 2,
                                pipe=pipe) <= FLASH_VMEM_BUDGET


def test_explicit_blocks_auto_pipeline_respects_vmem_budget():
    """pipeline='auto' with EXPLICIT blocks must degrade to serial when
    the doubled pipelined K/V window would overflow the VMEM plan — the
    round-5 shipping blocks (1024×1024 at S=4096, d=128, bf16) fit serial
    but not pipelined, and auto silently pipelining them would hand the
    chip exactly the tile class PROFILE_r05 saw fail to compile. An
    explicit pipeline='on' remains an operator override (block sweeps
    probe past the planning model deliberately)."""
    from nvidia_terraform_modules_tpu.ops.flash_attention import (
        _resolve_pipeline,
    )

    kw = dict(block_q=1024, d=128, itemsize=2)
    assert not _resolve_pipeline("auto", 4096, 1024, **kw)
    assert _resolve_pipeline("auto", 4096, 512, **kw)
    assert _resolve_pipeline("on", 4096, 1024, **kw)


def test_auto_blocks_only_returns_sublane_multiples():
    """Same ADVICE round-1 property _fit_block carries: every candidate the
    budget chooser can select must be an 8-multiple divisor — S=24 would
    otherwise offer 12 (= S/2), which CPU interpret accepts and real-TPU
    pallas rejects."""
    for s in (24, 40, 48, 56, 64, 120, 192, 256, 1024, 4096):
        for pipe in (False, True):
            bq, bk, _ = auto_blocks(s, 16, 4, pipe=pipe)
            assert bq % 8 == 0 and s % bq == 0, (s, pipe, bq)
            assert bk % 8 == 0 and s % bk == 0, (s, pipe, bk)


def test_auto_blocks_tiny_and_untileable_shapes():
    assert auto_blocks(8, 16, 4, pipe=True) == (8, 8, False)
    bq, bk, pipe = auto_blocks(250, 16, 4, pipe=True)
    assert bk == 0 and not pipe          # no 8-multiple divisor: caller raises
    q, k, v = _qkv(s=250)
    with pytest.raises(ValueError, match="pad the sequence"):
        flash_attention(q, k, v)


def test_default_blocks_auto_path_end_to_end():
    """No explicit blocks anywhere: the budget path must pick a legal
    tiling and match dense (auto pipeline on the even tiling it picks)."""
    q, k, v = _qkv(s=256)
    out = flash_attention(q, k, v)
    ref = dense_reference_attention(q, k, v)
    assert jnp.max(jnp.abs(out - ref)) < 1e-5


def test_burnin_window_flash_matches_dense():
    """Model-level splash: a windowed flash config is a pure mask change —
    same logits as the dense path applying the same window through XLA."""
    base = dict(vocab=64, d_model=32, n_heads=2, d_ff=64, n_layers=2,
                seq_len=16, batch=4, dtype=jnp.float32, flash_window=6)
    cfg_d = BurnInConfig(**base, attn="dense")
    cfg_f = BurnInConfig(**base, attn="flash")
    params = init_params(jax.random.PRNGKey(0), cfg_d)
    tokens, _ = synthetic_batch(jax.random.PRNGKey(1), cfg_d)
    dense = forward(params, tokens, cfg_d)
    flash = forward(params, tokens, cfg_f)
    assert jnp.max(jnp.abs(dense - flash)) < 1e-5
