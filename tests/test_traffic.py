# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""Arrival-trace generator: determinism, process shapes, shared use.

The one-seed-one-trace property is the module's reason to exist (the
tfsim fleet simulator and bench.py's serve section must see the SAME
users for the same seed, across processes), so it is property-tested
here — including in a SUBPROCESS with a different PYTHONHASHSEED, the
failure mode a hash-based seed would have.
"""

import math
import subprocess
import sys

import pytest

from nvidia_terraform_modules_tpu.utils.traffic import (
    diurnal_rate,
    diurnal_trace,
    make_trace,
    poisson_trace,
    ragged_lengths,
    shared_prefix_prompts,
    slo_deadlines,
    spike_trace,
    trace_summary,
)


def test_one_seed_one_trace_across_kinds():
    for kind, kw in (("poisson", {}),
                     ("diurnal", {"amplitude": 0.7, "period": 20.0}),
                     ("spike", {"spike_every": 5.0,
                                "spike_duration": 1.0})):
        a = make_trace(kind, 8.0, 40, seed=3, **kw)
        b = make_trace(kind, 8.0, 40, seed=3, **kw)
        c = make_trace(kind, 8.0, 40, seed=4, **kw)
        assert a == b, kind
        assert a != c, kind                     # the seed matters
        assert len(a) == 40
        assert all(x < y for x, y in zip(a, a[1:])), kind  # ascending


def test_traces_survive_hash_randomisation():
    """Same seed in a subprocess with a different PYTHONHASHSEED must
    yield the same trace — the cross-process contract bench children
    and tfsim runs rely on."""
    code = ("from nvidia_terraform_modules_tpu.utils.traffic import "
            "poisson_trace, ragged_lengths\n"
            "print(repr(poisson_trace(5.0, 5, seed=7)))\n"
            "print(repr(ragged_lengths(5, seed=7)))\n")
    outs = []
    for hashseed in ("0", "12345"):
        p = subprocess.run(
            [sys.executable, "-c", code], capture_output=True,
            text=True, env={"PYTHONHASHSEED": hashseed, "PATH": "/usr/bin:/bin"},
            check=True)
        outs.append(p.stdout)
    assert outs[0] == outs[1]
    assert repr(poisson_trace(5.0, 5, seed=7)) in outs[0]


def test_poisson_mean_rate_converges():
    t = poisson_trace(10.0, 4000, seed=1)
    s = trace_summary(t)
    assert s["count"] == 4000
    assert 9.0 < s["mean_rate"] < 11.0          # LLN at 4k samples


def test_diurnal_rate_curve_and_trace_modulation():
    assert diurnal_rate(0.0, 10.0, 0.5, 100.0) == pytest.approx(10.0)
    assert diurnal_rate(25.0, 10.0, 0.5, 100.0) == pytest.approx(15.0)
    assert diurnal_rate(75.0, 10.0, 0.5, 100.0) == pytest.approx(5.0)
    # arrivals concentrate in the high-rate half of each period
    t = diurnal_trace(10.0, 3000, seed=2, amplitude=0.9, period=10.0)
    phase = [x % 10.0 for x in t]
    first_half = sum(1 for p in phase if p < 5.0)
    assert first_half > 0.6 * len(phase)        # peak is sin>0 half
    with pytest.raises(ValueError, match="amplitude"):
        diurnal_trace(10.0, 5, amplitude=1.0)


def test_spike_trace_bursts_cluster_in_windows():
    t = spike_trace(2.0, 2000, seed=3, spike_rate=40.0,
                    spike_every=10.0, spike_duration=1.0)
    in_spike = sum(1 for x in t if (x % 10.0) < 1.0)
    # spike windows are 10% of the time but ~20/22 of the rate mass
    assert in_spike > 0.6 * len(t)
    assert trace_summary(t)["max_burst_1s"] >= 10


def test_ragged_lengths_bounds_and_determinism():
    ls = ragged_lengths(500, seed=9, lo=2, hi=32, mean=8.0)
    assert ls == ragged_lengths(500, seed=9, lo=2, hi=32, mean=8.0)
    assert all(2 <= x <= 32 for x in ls)
    assert len(set(ls)) > 5                     # actually ragged
    m = sum(ls) / len(ls)
    assert 4.0 < m < 14.0                       # clamped-exp around 8+2
    with pytest.raises(ValueError, match="lo"):
        ragged_lengths(3, lo=0)


def test_shared_prefix_prompts_zipf_pool_shape_and_determinism():
    """The prefix-reuse workload generator: (template_id, prompt)
    pairs whose prompts literally share the template's leading span,
    Zipf-popular (rank 0 drawn most), ragged unique suffixes, and the
    one-seed-one-workload property the other generators keep."""
    pairs = shared_prefix_prompts(200, seed=5, n_templates=4,
                                  template_len=8, suffix_lo=1,
                                  suffix_hi=6, vocab=32)
    assert pairs == shared_prefix_prompts(200, seed=5, n_templates=4,
                                          template_len=8, suffix_lo=1,
                                          suffix_hi=6, vocab=32)
    assert pairs != shared_prefix_prompts(200, seed=6, n_templates=4,
                                          template_len=8, suffix_lo=1,
                                          suffix_hi=6, vocab=32)
    assert len(pairs) == 200
    by_tid: dict = {}
    for tid, prompt in pairs:
        assert 0 <= tid < 4
        assert 9 <= len(prompt) <= 14          # template + suffix
        by_tid.setdefault(tid, []).append(prompt)
    # prompts of one template agree on the full template span
    for tid, prompts in by_tid.items():
        head = prompts[0][:8]
        assert all(p[:8] == head for p in prompts)
    # Zipf popularity: rank 0 strictly most popular at 200 draws
    counts = {tid: len(ps) for tid, ps in by_tid.items()}
    assert counts[0] == max(counts.values())
    assert counts[0] > 200 / 4                 # above uniform
    with pytest.raises(ValueError, match="n_templates"):
        shared_prefix_prompts(3, n_templates=0)
    with pytest.raises(ValueError, match="suffix_lo"):
        shared_prefix_prompts(3, suffix_lo=0)
    with pytest.raises(ValueError, match="zipf_s"):
        shared_prefix_prompts(3, zipf_s=0.0)


def test_shared_prefix_prompts_working_set_blocks_knob():
    """The tiered-KV sizing knob: working_set_blocks derives the
    smallest template pool whose FULL-BLOCK footprint reaches the
    target, so a bench can provably overflow prefix_keep_blocks; the
    derived pool is deterministic and the derivation is exact."""
    # 8-token templates at block_size=4 → 2 full blocks each; a
    # 7-block working set needs ceil(7/2) = 4 templates
    pairs = shared_prefix_prompts(300, seed=5, template_len=8,
                                  suffix_lo=1, suffix_hi=3, vocab=32,
                                  working_set_blocks=7, block_size=4)
    tids = {tid for tid, _p in pairs}
    assert tids == {0, 1, 2, 3}
    footprint = len(tids) * (8 // 4)
    assert footprint >= 7
    # explicit n_templates is overridden by the derivation — the knob
    # names the working set, not the pool
    assert pairs == shared_prefix_prompts(
        300, seed=5, n_templates=99, template_len=8, suffix_lo=1,
        suffix_hi=3, vocab=32, working_set_blocks=7, block_size=4)
    with pytest.raises(ValueError, match="working_set_blocks"):
        shared_prefix_prompts(3, working_set_blocks=0)
    with pytest.raises(ValueError, match="block_size"):
        shared_prefix_prompts(3, working_set_blocks=4, block_size=0)
    with pytest.raises(ValueError, match="FULL"):
        shared_prefix_prompts(3, working_set_blocks=4, template_len=3,
                              block_size=4)


def test_shared_prefix_prompts_survive_hash_randomisation():
    """Cross-process determinism under a different PYTHONHASHSEED —
    the same property the arrival traces pin, so a bench child and a
    tfsim run see the SAME template pool for the same seed."""
    code = ("from nvidia_terraform_modules_tpu.utils.traffic import "
            "shared_prefix_prompts\n"
            "print(repr(shared_prefix_prompts(6, seed=3, n_templates=2,"
            " template_len=4, suffix_lo=1, suffix_hi=3, vocab=16)))\n"
            "print(repr(shared_prefix_prompts(6, seed=3,"
            " template_len=8, suffix_lo=1, suffix_hi=3, vocab=16,"
            " working_set_blocks=5, block_size=4)))\n")
    outs = []
    for hashseed in ("0", "4242"):
        p = subprocess.run(
            [sys.executable, "-c", code], capture_output=True,
            text=True,
            env={"PYTHONHASHSEED": hashseed, "PATH": "/usr/bin:/bin"},
            check=True)
        outs.append(p.stdout)
    assert outs[0] == outs[1]
    assert repr(shared_prefix_prompts(
        6, seed=3, n_templates=2, template_len=4, suffix_lo=1,
        suffix_hi=3, vocab=16)) in outs[0]
    assert repr(shared_prefix_prompts(
        6, seed=3, template_len=8, suffix_lo=1, suffix_hi=3, vocab=16,
        working_set_blocks=5, block_size=4)) in outs[0]


def test_slo_deadlines_work_proportional_and_deterministic():
    """The PR 12 deadline generator: seeded, work-proportional (bigger
    budget → later deadline at zero jitter), jitter bounded, and the
    one-seed-one-vector property every generator here keeps."""
    budgets = [4, 4, 32, 8, 64]
    a = slo_deadlines(budgets, seed=7, base_s=0.1, per_token_s=0.01,
                      jitter=0.2)
    assert a == slo_deadlines(budgets, seed=7, base_s=0.1,
                              per_token_s=0.01, jitter=0.2)
    assert a != slo_deadlines(budgets, seed=8, base_s=0.1,
                              per_token_s=0.01, jitter=0.2)
    # every deadline inside its jitter band around base + per_token*b
    for d, b in zip(a, budgets):
        centre = 0.1 + 0.01 * b
        assert 0.8 * centre - 1e-12 <= d <= 1.2 * centre + 1e-12
    # zero jitter: exactly work-proportional, identical budgets equal
    z = slo_deadlines(budgets, seed=7, base_s=0.1, per_token_s=0.01,
                      jitter=0.0)
    assert z[0] == z[1] and z[4] > z[2] > z[3] > z[0]
    with pytest.raises(ValueError, match="base_s"):
        slo_deadlines([1], base_s=0.0)
    with pytest.raises(ValueError, match="jitter"):
        slo_deadlines([1], jitter=1.0)
    with pytest.raises(ValueError, match="budgets"):
        slo_deadlines([0])


def test_slo_deadlines_survive_hash_randomisation():
    """Cross-process determinism under a different PYTHONHASHSEED —
    the property every traffic generator pins, extended to the PR 12
    deadline vector (the fleet's shed decisions replay from it)."""
    code = ("from nvidia_terraform_modules_tpu.utils.traffic import "
            "slo_deadlines\n"
            "print(repr(slo_deadlines([2, 9, 5], seed=11,"
            " base_s=0.05, per_token_s=0.02, jitter=0.3)))\n")
    outs = []
    for hashseed in ("0", "777"):
        p = subprocess.run(
            [sys.executable, "-c", code], capture_output=True,
            text=True,
            env={"PYTHONHASHSEED": hashseed, "PATH": "/usr/bin:/bin"},
            check=True)
        outs.append(p.stdout)
    assert outs[0] == outs[1]
    assert repr(slo_deadlines([2, 9, 5], seed=11, base_s=0.05,
                              per_token_s=0.02, jitter=0.3)) in outs[0]


def test_make_trace_rejects_unknown_kind_and_bad_rate():
    with pytest.raises(ValueError, match="unknown trace kind"):
        make_trace("weibull", 1.0, 3)
    with pytest.raises(ValueError, match="rate"):
        poisson_trace(0.0, 3)


def test_trace_summary_empty_and_burst():
    assert trace_summary([])["count"] == 0
    s = trace_summary([0.0, 0.1, 0.2, 5.0])
    assert s["max_burst_1s"] == 3
    assert s["horizon_s"] == 5.0
    assert math.isclose(s["mean_rate"], 4 / 5.0, rel_tol=1e-6)


def test_fault_times_mid_trace_seeded_and_sorted():
    """The PR 13 kill-schedule generator: instants land strictly inside
    the [lo, hi] fraction of the trace horizon (mid-trace — never
    before the first arrival's routing, never after the run), sorted,
    and one (trace, n, seed) tuple yields one vector."""
    from nvidia_terraform_modules_tpu.utils.traffic import fault_times

    trace = poisson_trace(5.0, 20, seed=3)
    horizon = max(trace)
    a = fault_times(trace, 4, seed=9)
    assert a == fault_times(trace, 4, seed=9)
    assert a != fault_times(trace, 4, seed=10)
    assert a == sorted(a) and len(a) == 4
    for t in a:
        assert 0.25 * horizon <= t <= 0.75 * horizon
    tight = fault_times(trace, 2, seed=9, lo=0.5, hi=0.5)
    assert tight == [0.5 * horizon] * 2
    assert fault_times(trace, 0, seed=1) == []
    with pytest.raises(ValueError, match="non-empty"):
        fault_times([], 1)
    with pytest.raises(ValueError, match="lo"):
        fault_times(trace, 1, lo=0.8, hi=0.2)
    with pytest.raises(ValueError, match="n must"):
        fault_times(trace, -1)


def test_fault_times_survive_hash_randomisation():
    """Cross-process determinism under a different PYTHONHASHSEED —
    the chaos gate's kill schedule must replay in a bench child
    process exactly like every other generator here."""
    from nvidia_terraform_modules_tpu.utils.traffic import fault_times

    code = ("from nvidia_terraform_modules_tpu.utils.traffic import "
            "fault_times, poisson_trace\n"
            "print(repr(fault_times(poisson_trace(4.0, 12, seed=2),"
            " 3, seed=5)))\n")
    outs = []
    for hashseed in ("0", "424242"):
        p = subprocess.run(
            [sys.executable, "-c", code], capture_output=True,
            text=True,
            env={"PYTHONHASHSEED": hashseed, "PATH": "/usr/bin:/bin"},
            check=True)
        outs.append(p.stdout)
    assert outs[0] == outs[1]
    assert repr(fault_times(poisson_trace(4.0, 12, seed=2), 3,
                            seed=5)) in outs[0]
