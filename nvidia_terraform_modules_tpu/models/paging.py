# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""Block/paged KV-cache allocation for the continuous-batching engine.

The dense serving pool reserved ``max_len`` cache rows per slot for the
whole life of the engine — a request generating 12 tokens from an
8-token prompt held the same HBM as one filling the window. With ragged
real traffic (variable prompt AND output lengths) most of that
reservation is dead rows. The paged design (vLLM's PagedAttention,
re-thought for XLA static shapes) splits the physical cache into
fixed-size BLOCKS:

- the physical store is one ``[num_blocks, block_size, kv_heads, D]``
  buffer per layer, shared by every request;
- each request owns a **block table** — the logical→physical mapping —
  and exactly ``ceil(rows_needed / block_size)`` blocks, so internal
  fragmentation is bounded by ``block_size - 1`` rows per request;
- blocks return to a host-side free list the moment the request
  retires, and the next admission reuses them — the recycling that lets
  a fixed pool serve an unbounded request stream.

Division of labour (the same host/device split the serving engine
already lives by): the **host** owns WHICH blocks belong to which
request (:class:`BlockAllocator` — plain integers, no device traffic),
the **device** owns the math — block tables and per-slot positions are
small int32 arrays threaded through ``decode.forward_paged``, whose
gather/scatter path reads and writes physical rows through them with no
data-dependent shapes anywhere.

Block 0 is RESERVED as the garbage block: idle and retired slots'
writes are routed there (their table rows may point at blocks already
recycled to another request — without the reroute a retired slot's
still-computing forward would corrupt the new owner's cache).

Blocks are REFCOUNTED: :meth:`BlockAllocator.alloc` hands a block out
at refcount 1, :meth:`BlockAllocator.share` maps an already-allocated
block into another request's table (refcount++), and
:meth:`BlockAllocator.free` only returns a block to the free list when
the LAST reference drops — the mechanism that lets a popular prompt
prefix live ONCE in HBM while any number of concurrent requests read
it. :class:`PrefixIndex` is the host-side lookup that finds those
shareable blocks: block-aligned token-hash chains → physical block
ids, holding one reference per indexed block so a retained prefix
survives its writer's retirement, with an LRU cap on
retained-but-unreferenced blocks.

The paged block is also the fleet's TRANSFER UNIT:
:func:`export_block_rows` / :func:`import_block_rows` copy whole
blocks' physical content between two pools (the prefill→decode handoff
of ``models/fleet.py``'s disaggregated mode — an explicit device copy
on CPU, the seam an ICI/DCN transfer slots into on chip). The fleet's
``Transport`` layer (``models/transport.py``) ships the SAME exported
rows across a process boundary: ``encode_block_payload`` stamps the
export with ``transfer_crc`` before pickling and the importer
re-verifies after, so a block handoff is end-to-end checked whether it
crosses a function call, a pipe, or (eventually) DCN.

``tests/test_paging.py`` pins the allocator invariants (no double
alloc, free-list recycling, exhaustion, the fragmentation bound,
refcount free-at-zero, LRU eviction safety, cross-pool transfer
roundtrips) and ``tests/test_serving.py`` the end-to-end exactness of
paged serving against solo decode.
"""

from __future__ import annotations

import hashlib
import zlib
from collections import OrderedDict
from typing import Any, Sequence

from .burnin import BurnInConfig
from .decode import cache_rows


def blocks_for_rows(rows: int, block_size: int) -> int:
    """Blocks needed to hold ``rows`` cache rows (0 rows → 0 blocks)."""
    if rows < 0:
        raise ValueError(f"rows must be >= 0, got {rows}")
    return -(-rows // block_size)


class BlockAllocator:
    """Host-side free-list allocator over ``num_blocks`` physical blocks.

    Block 0 (more generally ``reserved`` leading blocks) is never handed
    out — it is the garbage block dead slots write into. ``alloc`` is
    all-or-nothing (a request needs its whole table before admission);
    ``free`` returns blocks for reuse in LIFO order, so a retire→admit
    pair tends to reuse hot blocks. Exhaustion returns ``None`` — the
    scheduler's signal to hold the request in the admission queue until
    a retirement frees capacity (admission control, not an error).
    """

    def __init__(self, num_blocks: int, *, reserved: int = 1):
        if num_blocks <= reserved:
            raise ValueError(
                f"num_blocks ({num_blocks}) must exceed the reserved "
                f"garbage block count ({reserved})")
        self.num_blocks = num_blocks
        self.reserved = reserved
        self._free = list(range(num_blocks - 1, reserved - 1, -1))
        self._ref: dict[int, int] = {}           # block → reference count
        self.high_water = 0

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def in_use(self) -> int:
        """PHYSICAL blocks allocated — each counted once, however many
        tables reference it (the HBM bill)."""
        return len(self._ref)

    @property
    def refs_total(self) -> int:
        """LOGICAL block references — what the same tables would cost
        WITHOUT sharing (``refs_total - in_use`` is the sharing win)."""
        return sum(self._ref.values())

    def refcount(self, block: int) -> int:
        return self._ref.get(block, 0)

    def alloc(self, n: int) -> list[int] | None:
        """``n`` blocks or ``None`` (never a partial grant); each block
        starts at refcount 1 (the caller's reference)."""
        if n < 0:
            raise ValueError(f"cannot allocate {n} blocks")
        if n > len(self._free):
            return None
        blocks = [self._free.pop() for _ in range(n)]
        for b in blocks:
            self._ref[b] = 1
        self.high_water = max(self.high_water, len(self._ref))
        return blocks

    def share(self, blocks: Sequence[int]) -> None:
        """Add one reference to each (already-allocated) block — the
        physical bytes stay where they are, another table maps them."""
        for b in blocks:
            if b not in self._ref:
                raise ValueError(
                    f"block {b} is not allocated — only a live block "
                    f"can be shared into another table")
        for b in blocks:
            self._ref[b] += 1

    def free(self, blocks) -> None:
        """Drop one reference per block; a block returns to the free
        list only when its LAST reference drops. Freeing an unallocated
        block is loud (double free / reserved / foreign id)."""
        for b in blocks:
            if b not in self._ref:
                raise ValueError(
                    f"block {b} is not allocated (double free, a "
                    f"reserved block, or a foreign id)")
            self._ref[b] -= 1
            if self._ref[b] == 0:
                del self._ref[b]
                self._free.append(b)

    def stats(self) -> dict[str, int]:
        return {
            "num_blocks": self.num_blocks,
            "reserved": self.reserved,
            "in_use": self.in_use,
            "free": self.free_blocks,
            "high_water": self.high_water,
            "refs_total": self.refs_total,
        }


def chain_chunks(tokens: Sequence[int], block_size: int,
                 offset: int = 0) -> list[tuple[int, ...]]:
    """Split ``tokens`` into the FULL block-grid chunks of a request's
    own blocks.

    ``offset`` is the number of leading rows of the first own block
    already occupied by non-prompt content identical across requests
    (the template prefix's copied tail rows), so the first chunk covers
    ``block_size - offset`` tokens and every later chunk ``block_size``.
    Only chunks whose block is COMPLETELY covered by ``tokens`` are
    returned — a partial tail block is never shareable (its remaining
    rows differ per request).
    """
    if not 0 <= offset < block_size:
        raise ValueError(
            f"offset must be in [0, block_size), got {offset}")
    out: list[tuple[int, ...]] = []
    start, width = 0, block_size - offset
    while start + width <= len(tokens):
        out.append(tuple(int(t) for t in tokens[start:start + width]))
        start += width
        width = block_size
    return out


def chunk_tokens_covered(k: int, block_size: int, offset: int = 0) -> int:
    """Prompt tokens covered by the first ``k`` full own-block chunks —
    the prefill-start offset after sharing ``k`` blocks (0 for k=0)."""
    return 0 if k == 0 else k * block_size - offset


def chain_key(chunks: Sequence[tuple], upto: int | None = None) -> bytes:
    """The :class:`PrefixIndex` chain key of ``chunks[:upto]`` — the
    key naming the ENTIRE token history through that chunk. One
    definition shared by the index, the fleet's routing
    (``fleet.affinity_key`` keys on ``chain_key(chunks, 1)``) and the
    warm-bring-up store (``hostkv.WarmChainStore`` files spilled chains
    under their root/leaf keys), so placement, matching and migration
    can never disagree on a chain's name."""
    if upto is None:
        upto = len(chunks)
    if upto < 1:
        raise ValueError("chain_key needs >= 1 chunk")
    parent: bytes | None = None
    for chunk in chunks[:upto]:
        parent = PrefixIndex._key(parent, chunk)
    return parent


class PrefixIndex:
    """Host-side prefix lookup: block-aligned token-hash chains →
    physical blocks, holding ONE allocator reference per indexed block.

    The chain key of a request's ``i``-th full own block is
    ``H(key_{i-1}, tokens_i)`` (blake2b over the raw token bytes), so a
    key names the ENTIRE token history up to and including that block —
    two requests produce the same key iff their prompts agree on every
    row the block holds and on everything before it, which (positions
    being engine-constant) is exactly when the cached K/V content is
    identical. Hash collisions are nevertheless never trusted with
    correctness: each entry stores its token chunk and a match compares
    tokens outright.

    Because the index holds its own reference, an indexed block can
    never be recycled under a reader: a writer's retirement decrements
    its reference but the content stays resident ("recently retired")
    until the LRU cap on retained-but-UNREFERENCED blocks (refcount 1 —
    the index's own) evicts it. Entries are touched leaf-first on a
    match so eviction takes chain suffixes before the prefixes that
    reach them; evicting an interior entry cascades to its descendants
    (unreachable entries must not keep holding references).

    TIERED (the host-RAM spill, ``models/hostkv.py``): with a ``spill``
    adapter (``store(dev_blocks) → host_ids | None`` + ``free``), an
    eviction COPIES the chain's blocks host-side instead of dropping
    them — the entry stays in the index at ``tier="host"`` with its
    device reference released, so the retained working set is bounded
    by host RAM, not ``capacity``. Eviction spills the candidate AND
    its whole descendant subtree (an interior entry's readers always
    reference every ancestor, so an unreferenced interior implies an
    unreferenced subtree — pinned by the same refcount argument the
    LRU-safety test makes), which keeps the invariant the match walk
    relies on: a host entry never has a device-tier descendant, so
    every matched chain is a device prefix followed by a host tail.
    Host-pool exhaustion falls back to the plain drop (correctness
    never depends on the spill). A later match returns the host tail
    via :meth:`match_tiered`; the engine grants fresh device blocks,
    imports the rows and :meth:`promote`\\ s the entries back to
    device tier.
    """

    def __init__(self, alloc: BlockAllocator, capacity: int, *,
                 spill=None):
        if capacity < 0:
            raise ValueError(f"capacity must be >= 0, got {capacity}")
        self.alloc = alloc
        self.capacity = capacity
        self.spill = spill
        # key → (block, token-chunk, parent key, tier) in LRU order;
        # tier "dev": block is a device block id carrying one allocator
        # reference; tier "host": block is a host-pool id (no device
        # reference — the bytes live in the spill adapter's pool)
        self._entries: "OrderedDict[bytes, tuple[int, tuple, bytes | None, str]]" = OrderedDict()
        self._children: dict[bytes, set[bytes]] = {}
        self.hit_blocks = 0
        self.lookups = 0
        self.spilled_blocks = 0        # cumulative entries spilled
        self.spill_dropped = 0         # evictions the full host pool
        #                                demoted to plain drops
        self.host_hit_blocks = 0       # host-tier entries matched
        # why the last reclaim() returned 0 (None after a fruitful
        # one): "live" = device-tier entries exist but every one is
        # still table-referenced; "empty" = nothing device-resident to
        # reclaim at all — the distinction the spill tier's admission
        # control needs (live: wait for retirements; empty: the pool
        # pressure is real allocations, queue)
        self.reclaim_blocked: str | None = None

    def __len__(self) -> int:
        return len(self._entries)

    @property
    def retained_unreferenced(self) -> list[bytes]:
        """DEVICE-tier indexed blocks no table references (refcount 1 =
        ours only), in LRU order — the eviction candidates the cap
        bounds. Host-tier entries hold no device blocks, so they are
        never candidates."""
        return [k for k, (b, _t, _p, tier) in self._entries.items()
                if tier == "dev" and self.alloc.refcount(b) == 1]

    @property
    def host_tier(self) -> list[bytes]:
        """Spilled entries (host-resident chains), in LRU order."""
        return [k for k, (_b, _t, _p, tier) in self._entries.items()
                if tier == "host"]

    @staticmethod
    def _key(parent: bytes | None, chunk: tuple) -> bytes:
        h = hashlib.blake2b(parent or b"root", digest_size=16)
        h.update(",".join(str(t) for t in chunk).encode())
        return h.digest()

    def match(self, chunks: Sequence[tuple]) -> list[int]:
        """Longest DEVICE-RESIDENT indexed chain prefix of ``chunks`` →
        its physical blocks (with one reference ADDED to each via
        ``share`` — the caller maps them into a table and frees them at
        retirement like any owned block). Matched entries are touched
        most-recent, leaf-first. A spilled (host-tier) entry ends the
        walk — callers that can swap in use :meth:`match_tiered`."""
        dev, _host = self.match_tiered(chunks, host=False)
        return dev

    def _walk(self, chunks: Sequence[tuple]
              ) -> tuple[list[tuple[bytes, int]],
                         list[tuple[bytes, int]]]:
        """The tier-aware chain walk — ONE definition, so the
        admission match and the prefetch probe can never disagree on
        the chain they name. Pure lookup: NO references, NO LRU touch,
        NO stats. Returns ``(dev, tail)`` as ``(key, id)`` pairs: the
        device-resident prefix, then the spilled continuation."""
        dev: list[tuple[bytes, int]] = []
        tail: list[tuple[bytes, int]] = []
        parent: bytes | None = None
        for chunk in chunks:
            key = self._key(parent, chunk)
            ent = self._entries.get(key)
            if ent is None or ent[1] != chunk:
                break
            if ent[3] == "host":
                tail.append((key, ent[0]))
            elif tail:
                # defensive: the spill invariant (no device entry below
                # a host one) makes this unreachable — never extend a
                # mixed sandwich
                break
            else:
                dev.append((key, ent[0]))
            parent = key
        return dev, tail

    def match_tiered(self, chunks: Sequence[tuple], *,
                     host: bool = True) -> tuple[list[int],
                                                 list[tuple[bytes, int]]]:
        """The tier-aware match: ``(dev_blocks, host_tail)`` where
        ``dev_blocks`` is the device-resident chain prefix (shared —
        one reference added each, exactly like :meth:`match`) and
        ``host_tail`` the spilled continuation as ``(key, host_id)``
        pairs, deepest last. Host entries take NO references here — the
        caller that decides to swap them in allocates device blocks,
        imports the rows and calls :meth:`promote`; a caller that
        cannot (blocks exhausted) just walks away, nothing to undo."""
        self.lookups += 1
        dev, tail = self._walk(chunks)
        if not host:
            tail = []
        blocks = [b for _k, b in dev]
        keys = [k for k, _b in dev] + [k for k, _h in tail]
        for key in reversed(keys):               # leaf ends most recent
            self._entries.move_to_end(key)
        if blocks:
            self.alloc.share(blocks)
            self.hit_blocks += len(blocks)
        self.host_hit_blocks += len(tail)
        return blocks, tail

    def peek_host_tail(self, chunks: Sequence[tuple]
                       ) -> list[tuple[bytes, int]]:
        """Read-only probe of the spilled continuation a
        :meth:`match_tiered` of ``chunks`` would return — NO references
        taken, NO LRU touch, NO stats: the wave loop's swap-in
        PREFETCH uses it to stage the next admission's host rows while
        the current wave decodes, and a probe must never perturb the
        schedule-invariant eviction order."""
        return self._walk(chunks)[1]

    def promote(self, keys: Sequence[bytes],
                blocks: Sequence[int]) -> None:
        """Re-register swapped-in entries as DEVICE-resident:
        ``blocks[i]`` (a freshly granted device block whose rows the
        caller just imported) replaces ``keys[i]``'s host id — the
        index takes one reference (``share``) like any registration and
        frees the host copy. Keys must be host-tier, in chain order."""
        if len(keys) != len(blocks):
            raise ValueError(f"{len(keys)} keys for {len(blocks)} blocks")
        for key, block in zip(keys, blocks):
            ent = self._entries.get(key)
            if ent is None or ent[3] != "host":
                raise ValueError(
                    "promote() takes host-tier entries — the chain "
                    "moved under the caller (evicted or already "
                    "promoted); re-match before swapping in")
            self.alloc.share([block])
            if self.spill is not None:
                self.spill.free([ent[0]])
            self._entries[key] = (block, ent[1], ent[2], "dev")

    def register(self, chunks: Sequence[tuple],
                 blocks: Sequence[int]) -> None:
        """Index ``blocks[i]`` as holding ``chunks[i]`` (a prefilled
        request's full own blocks, in chain order). Already-indexed
        device-tier chain nodes are skipped (the donor matched them);
        new entries take one reference each. A HOST-tier node the donor
        re-prefilled (it was capped out of the match, or diverged past
        the cap) PROMOTES in place: the donor's device block replaces
        the host copy — fresher bytes, identical content."""
        if len(chunks) != len(blocks):
            raise ValueError(
                f"{len(chunks)} chunks for {len(blocks)} blocks")
        parent: bytes | None = None
        for chunk, block in zip(chunks, blocks):
            key = self._key(parent, chunk)
            ent = self._entries.get(key)
            if ent is None:
                self.alloc.share([block])
                self._entries[key] = (block, chunk, parent, "dev")
                if parent is not None:
                    self._children.setdefault(parent, set()).add(key)
            elif ent[3] == "host":
                self.alloc.share([block])
                if self.spill is not None:
                    self.spill.free([ent[0]])
                self._entries[key] = (block, chunk, parent, "dev")
            self._entries.move_to_end(key)
            parent = key

    def seed_host(self, chunks: Sequence[tuple],
                  host_ids: Sequence[int]) -> int:
        """WARM BRING-UP seeding (the elastic fleet's host-tier prefix
        migration): register ``chunks[i]`` as a HOST-tier entry holding
        ``host_ids[i]`` — rows the caller already adopted into this
        index's spill pool (``HostBlockPool.adopt``). A fresh replica
        seeded this way starts with the popular-prefix working set
        host-resident, and the FIRST admission that matches a seeded
        chain swaps it in through the ordinary tiered path
        (:meth:`match_tiered` → crc-verified load → :meth:`promote`) —
        no new read machinery, so the warm join inherits the bit-match
        and quarantine discipline of the spill tier. Chain nodes
        already indexed (either tier) keep their existing entry and the
        duplicate adopted row is released back to the spill pool.
        Returns the number of NEW host-tier entries seeded."""
        if self.spill is None:
            raise ValueError(
                "seed_host needs a spill adapter — the seeded entries "
                "live in the host tier")
        if len(chunks) != len(host_ids):
            raise ValueError(
                f"{len(chunks)} chunks for {len(host_ids)} host ids")
        seeded = 0
        parent: bytes | None = None
        for chunk, hid in zip(chunks, host_ids):
            key = self._key(parent, chunk)
            ent = self._entries.get(key)
            if ent is None:
                self._entries[key] = (int(hid), chunk, parent, "host")
                if parent is not None:
                    self._children.setdefault(parent, set()).add(key)
                seeded += 1
            else:
                # already indexed (a prior seed, or this replica's own
                # traffic got there first): the duplicate row goes back
                self.spill.free([int(hid)])
            self._entries.move_to_end(key)
            parent = key
        return seeded

    def export_chains(self) -> list[tuple[list[tuple],
                                          list[tuple[str, int]]]]:
        """Every maximal indexed chain, root-first per chain and
        most-recently-used LEAF first across chains: ``(chunks,
        [(tier, id), …])`` where ``id`` is a device block
        (``tier="dev"``) or a host-pool row (``tier="host"``).
        Read-only — no references, no LRU touch: the drain/close-time
        PUBLISH walk (the elastic fleet copies these chains into its
        shared :class:`~.hostkv.WarmChainStore` so successors inherit
        the working set). MRU-first ordering means a capacity-limited
        sink keeps the popular head and drops the cold tail."""
        out: list[tuple[list[tuple], list[tuple[str, int]]]] = []
        for leaf in reversed(self._entries):
            if self._children.get(leaf):
                continue
            chunks: list[tuple] = []
            ids: list[tuple[str, int]] = []
            k: bytes | None = leaf
            while k is not None:
                block, chunk, parent, tier = self._entries[k]
                chunks.append(chunk)
                ids.append((tier, block))
                k = parent
            chunks.reverse()
            ids.reverse()
            out.append((chunks, ids))
        return out

    def _drop(self, key: bytes) -> int:
        """Plain drop of ``key`` and every descendant entry
        (unreachable once the parent is gone), releasing the index's
        device reference or host copy on each. Returns the number of
        entries dropped."""
        n = 0
        stack = [key]
        while stack:
            k = stack.pop()
            ent = self._entries.pop(k, None)
            if ent is None:
                continue
            block, _chunk, parent, tier = ent
            if tier == "dev":
                self.alloc.free([block])
            elif self.spill is not None:
                self.spill.free([block])
            if parent is not None and parent in self._children:
                self._children[parent].discard(k)
            stack.extend(self._children.pop(k, ()))
            n += 1
        return n

    def discard(self, key: bytes) -> int:
        """Drop ``key`` and its whole subtree unconditionally — device
        references freed, host copies released, NO spill. The
        quarantine path: a spilled chain whose rows failed their crc
        re-check must leave the index entirely (the engine prefills
        from tokens), never re-spill the suspect bytes."""
        return self._drop(key)

    def _evict(self, key: bytes) -> int:
        """Evict ``key``: SPILL its device-tier subtree host-side when
        a spill adapter is wired (entries stay indexed at
        ``tier="host"``, device references released), falling back to
        :meth:`_drop` when the host pool cannot hold the whole subtree
        (all-or-nothing — a half-spilled chain would strand the tail).
        Returns device-tier entries released either way.

        A CHAIN-LEVEL spill adapter (``chain_level=True`` — the
        fleet-shared prefix CDN, ``hostkv.ChainSpill``) takes whole
        chains instead of raw blocks: see
        :meth:`_evict_chain_level`."""
        if self.spill is None:
            return self._drop(key)
        if getattr(self.spill, "chain_level", False):
            return self._evict_chain_level(key)
        # collect the device-tier subtree in chain (parent-first) order
        sub: list[bytes] = []
        stack = [key]
        while stack:
            k = stack.pop()
            ent = self._entries.get(k)
            if ent is None:
                continue
            if ent[3] == "dev":
                sub.append(k)
            stack.extend(self._children.get(k, ()))
        if not sub:
            return 0
        dev_blocks = [self._entries[k][0] for k in sub]
        hids = self.spill.store(dev_blocks)
        if hids is None:
            # host pool exhausted: the eviction still must free device
            # blocks — plain drop, loudly billed. Return the DEVICE
            # count, not _drop's entry count: the subtree may carry
            # previously spilled host-tier descendants whose removal
            # frees no device block, and reclaim()'s callers budget
            # against device blocks released
            self.spill_dropped += len(sub)
            self._drop(key)
            return len(sub)
        for k, hid in zip(sub, hids):
            block, chunk, parent, _tier = self._entries[k]
            self.alloc.free([block])
            self._entries[k] = (hid, chunk, parent, "host")
        self.spilled_blocks += len(sub)
        return len(sub)

    def _evict_chain_level(self, key: bytes) -> int:
        """CHAIN-LEVEL eviction (the fleet-shared prefix CDN): publish
        every root→leaf chain whose path runs through the evicted
        subtree into the shared store — ancestors ride along so the
        store files the WHOLE content-addressed chain (shared prefix
        rows dedup by node key on its side) — then plain-DROP the
        subtree. No ``tier="host"`` entry is ever created in this
        mode; a later hit re-enters through ``WarmChainStore.fetch``
        on the admission path. Publishing is best-effort (the store
        bills its own capacity/disk drops), the eviction always
        completes and always frees the device blocks."""
        if key not in self._entries:
            return 0
        # ancestors root→parent-of-key: still indexed, still device
        # tier (chain-level mode never files host entries)
        prefix: list[tuple] = []        # (chunk, block) pairs
        k = self._entries[key][2]
        while k is not None:
            ent = self._entries[k]
            prefix.append((ent[1], ent[0]))
            k = ent[2]
        prefix.reverse()
        chains: list[tuple[list, list]] = []
        released = 0
        stack: list[tuple[bytes, list]] = [(key, prefix)]
        while stack:
            k, path = stack.pop()
            ent = self._entries.get(k)
            if ent is None:
                continue
            path = path + [(ent[1], ent[0])]
            released += 1
            kids = [c for c in self._children.get(k, ())
                    if c in self._entries]
            if kids:
                stack.extend((c, path) for c in kids)
            else:
                chains.append(([c for c, _b in path],
                               [b for _c, b in path]))
        self.spill.store_chains(chains)
        self.spilled_blocks += released
        self._drop(key)
        return released

    def trim(self) -> int:
        """Enforce the LRU cap: evict least-recently-used
        retained-but-unreferenced entries (NEVER a block a live table
        still references) until at most ``capacity`` remain — spilling
        them host-side when the tier is wired. Returns evicted entry
        count."""
        n = 0
        while True:
            cands = self.retained_unreferenced
            if len(cands) <= self.capacity:
                return n
            n += self._evict(cands[0])

    def reclaim(self, n: int) -> int:
        """Evict up to ``n`` retained-but-unreferenced entries NOW
        (allocation pressure: a block a new admission needs beats a
        retained prefix, whatever the cap says). Returns the number of
        device blocks released — 0 means nothing was reclaimable and
        the caller should queue, with :attr:`reclaim_blocked` saying
        WHY ("live": retained chains exist but live tables still
        reference every one; "empty": nothing device-resident is
        retained at all)."""
        freed = 0
        while freed < n:
            cands = self.retained_unreferenced
            if not cands:
                break
            freed += self._evict(cands[0])
        if freed == 0:
            self.reclaim_blocked = (
                "live" if any(tier == "dev" for _b, _t, _p, tier
                              in self._entries.values()) else "empty")
        else:
            self.reclaim_blocked = None
        return freed

    def release(self) -> int:
        """Drop every entry — device references freed, host copies
        released (end of a run: both tiers tear down with the pool).
        Returns evicted entry count."""
        n = 0
        while self._entries:
            n += self._drop(next(iter(self._entries)))
        self._children.clear()
        return n


_POOL_KEYS = ("k", "v", "k_scale", "v_scale")

_XFER_JITS: dict[str, Any] = {}


def _xfer_jits() -> dict[str, Any]:
    """Module-level jit singletons for the cross-pool transfer pair —
    built lazily (this module stays importable without paying jax) and
    cached so repeated transfers of the same block count reuse one
    compiled program."""
    if not _XFER_JITS:
        import functools

        import jax

        @jax.jit
        def export_fn(bufs, ids):
            return [b[ids] for b in bufs]

        @functools.partial(jax.jit, donate_argnums=(0,))
        def import_fn(bufs, ids, payload):
            return [b.at[ids].set(p) for b, p in zip(bufs, payload)]

        _XFER_JITS["export"] = export_fn
        _XFER_JITS["import"] = import_fn
    return _XFER_JITS


def pool_transfer_keys(pool: dict) -> list[str]:
    """The pool entries a block transfer moves: the per-layer physical
    buffers (k/v, plus int8 scale sidecars when present) — never the
    per-slot ``block_tables``/``pos``, which are the RECEIVER's own
    bookkeeping."""
    return [k for k in _POOL_KEYS if k in pool]


def export_block_rows(pool: dict, block_ids: Sequence[int]) -> dict:
    """Copy the physical content of ``block_ids`` out of ``pool``:
    ``{key: [per-layer [n, block_size, ...] arrays]}`` in block-id
    order, every transferable key in one dispatch.

    This is the prefill→decode handoff's transfer unit (ROADMAP
    direction 2 / Podracer's role split): a prefill worker exports the
    blocks its finished prompt occupies and a DIFFERENT pool imports
    them via :func:`import_block_rows` — an explicit device copy on
    CPU, and exactly the seam where an ICI/DCN block transfer slots in
    on chip (the payload is already the wire format: whole blocks, no
    row surgery). Rows past the request's position inside the last
    block ride along as unreachable garbage on both sides.
    """
    import jax.numpy as jnp

    ids = jnp.asarray(list(block_ids), jnp.int32)
    if ids.ndim != 1 or ids.shape[0] < 1:
        raise ValueError("export_block_rows needs >= 1 block id")
    keys = pool_transfer_keys(pool)
    bufs = [b for k in keys for b in pool[k]]
    outs = _xfer_jits()["export"](bufs, ids)
    n_layers = len(pool["k"])
    payload: dict[str, Any] = {}
    i = 0
    for k in keys:
        payload[k] = list(outs[i:i + n_layers])
        i += n_layers
    return payload


def transfer_crc(payload: dict) -> int:
    """crc32 over an :func:`export_block_rows` payload's wire content —
    buffers in key-sorted, layer order, so the checksum is a pure
    function of the transferred bytes on both sides of the wire.

    This is the paged transfer's integrity primitive: a cross-pool copy
    is exactly the seam where an ICI/DCN hop slots in on chip, and a
    hop can corrupt. The fleet's disaggregated prefill→decode handoff
    stamps every payload with this crc at export and re-checks it at
    the import side (``models/fleet.py``); a mismatch is a CLASSIFIED,
    retryable transfer failure (re-run the prefill), never a silent
    import of garbage rows into a decode pool."""
    import numpy as np

    crc = 0
    for k in sorted(payload):
        for buf in payload[k]:
            crc = zlib.crc32(np.asarray(buf).tobytes(), crc)
    return crc


def import_block_rows(pool: dict, block_ids: Sequence[int],
                      payload: dict) -> dict:
    """Write :func:`export_block_rows` ``payload`` into ``pool`` at
    ``block_ids`` (the receiver's own allocated blocks — transfer never
    implies the same physical ids on both sides). Returns a NEW pool
    dict; the physical buffers are DONATED (updated in place when XLA
    can), so callers must rebind their pool reference, exactly like the
    engine's wave step. Importing into a reserved block is refused
    loudly — scribbling the garbage block would corrupt every fenced
    write in flight."""
    import jax.numpy as jnp

    ids_h = [int(b) for b in block_ids]
    if any(b < 1 for b in ids_h):
        raise ValueError(
            f"cannot import into reserved block(s) {sorted(set(b for b in ids_h if b < 1))} "
            f"— block 0 is the garbage block every fenced write targets")
    keys = pool_transfer_keys(pool)
    if sorted(payload) != sorted(keys):
        raise ValueError(
            f"payload keys {sorted(payload)} do not match the pool's "
            f"transferable keys {sorted(keys)} (cache_dtype mismatch "
            f"between the exporting and importing pools?)")
    n = len(ids_h)
    for k in keys:
        for buf in payload[k]:
            if int(buf.shape[0]) != n:
                raise ValueError(
                    f"payload[{k!r}] carries {int(buf.shape[0])} blocks "
                    f"for {n} block ids")
    ids = jnp.asarray(ids_h, jnp.int32)
    bufs = [b for k in keys for b in pool[k]]
    pl = [b for k in keys for b in payload[k]]
    outs = _xfer_jits()["import"](bufs, ids, pl)
    n_layers = len(pool["k"])
    out = dict(pool)
    i = 0
    for k in keys:
        out[k] = list(outs[i:i + n_layers])
        i += n_layers
    return out


def paged_pool_spec(cfg: BurnInConfig, max_len: int, block_size: int,
                    cache_dtype: str = "bf16") -> dict[str, int]:
    """Static pool geometry shared by every constructor and the engine.

    ``rows`` is :func:`..decode.cache_rows`'s buffer length for
    ``max_len`` (int8 keeps its 256-row kernel grain), ``tables`` the
    per-slot block-table width, sized so the gathered logical cache
    spans at least ``rows`` — every position a request can legally
    occupy has a table entry, and the logical width stays static.
    """
    if block_size < 1:
        raise ValueError(f"block_size must be >= 1, got {block_size}")
    rows = cache_rows(max_len, cache_dtype)
    tables = blocks_for_rows(rows, block_size)
    return {"rows": rows, "tables": tables, "block_size": block_size,
            "logical_rows": tables * block_size}


def init_paged_cache(cfg: BurnInConfig, slots: int, max_len: int, *,
                     block_size: int, num_blocks: int,
                     rules=None, cache_dtype: str = "bf16") -> dict[str, Any]:
    """Zeroed paged pool + per-slot tables and positions.

    Layout (per layer): ``k``/``v`` ``[num_blocks, block_size, kv, D]``;
    int8 caches add ``k_scale``/``v_scale`` ``[num_blocks, block_size,
    kv]`` sidecars. ``block_tables`` is ``[slots, tables]`` int32 —
    all-zero at init, i.e. every slot points at the garbage block until
    its first admission — and ``pos`` ``[slots]`` int32.

    With ``rules`` the KV-head axis shards over ``tp`` when it divides;
    the block axis replicates (blocks are assigned dynamically, so a
    block-sharded pool would turn every gather into a cross-shard
    shuffle). The paged pool's HBM story is the block COUNT — sized to
    live rows, not ``slots × max_len`` — so replication across the data
    groups still undercuts the dense pool whenever occupancy is ragged.
    """
    import jax
    import jax.numpy as jnp

    if cache_dtype not in ("bf16", "int8"):
        raise ValueError(
            f"unknown cache_dtype {cache_dtype!r}: use bf16|int8")
    spec = paged_pool_spec(cfg, max_len, block_size, cache_dtype)
    quant = cache_dtype == "int8"
    s4 = s3 = None
    if rules is not None:
        from jax.sharding import PartitionSpec as P

        tp = rules.mesh.shape.get("tp", 1)
        head_axis = "tp" if cfg.kv_heads % tp == 0 else None
        # the BLOCK axis replicates (blocks are assigned dynamically);
        # only the KV-head axis shards, matching init_cache's layout
        s4 = rules.shard(P(None, None, head_axis, None))
        s3 = rules.shard(P(None, None, head_axis))

    def zeros(shape, dtype, sharding):
        if sharding is None:
            return jnp.zeros(shape, dtype)
        # materialise DIRECTLY into the sharded layout (one transient
        # replicated pool on one device is the OOM the sharding avoids)
        return jax.jit(lambda: jnp.zeros(shape, dtype),
                       out_shardings=sharding)()

    kv_shape = (num_blocks, block_size, cfg.kv_heads, cfg.head_dim)
    buf_dtype = jnp.int8 if quant else cfg.dtype
    pool: dict[str, Any] = {
        "k": [zeros(kv_shape, buf_dtype, s4) for _ in range(cfg.n_layers)],
        "v": [zeros(kv_shape, buf_dtype, s4) for _ in range(cfg.n_layers)],
        "block_tables": jnp.zeros((slots, spec["tables"]), jnp.int32),
        "pos": jnp.zeros((slots,), jnp.int32),
    }
    if quant:
        pool["k_scale"] = [zeros(kv_shape[:3], jnp.float32, s3)
                           for _ in range(cfg.n_layers)]
        pool["v_scale"] = [zeros(kv_shape[:3], jnp.float32, s3)
                           for _ in range(cfg.n_layers)]
    return pool
