# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""Dependency lockfile support — tfsim's `.terraform.lock.hcl` surface.

The reference commits a lockfile per root module — 6 files pinning 13
provider selections (``/root/reference/gke/.terraform.lock.hcl:1``, SURVEY
§4 "Determinism") — so that every `terraform init` resolves the exact same
plugin builds. This repo's CI has no registry access, so the terraform
binary can never produce those files here; instead tfsim owns the same
artifact:

* ``generate_lockfile`` renders a `.terraform.lock.hcl` that pins the exact
  version *selection* for every provider required anywhere in a root
  module's tree (walking local ``source = "../../"`` module calls the way
  `terraform init` does). Version selections are what make `init`
  deterministic; the ``hashes`` entries are per-platform checksums that
  only a networked ``terraform providers lock`` can compute, and terraform
  fills them in on first networked init without changing the selection.
* ``check_lockfile`` is the CI gate: the committed lockfile must exist,
  cover every required provider, pin a version that satisfies every
  constraint in the module tree, and carry no stale extra providers.

Selections default to ``CERTIFIED_PROVIDERS`` — the certified-versions row
of the support matrix in the repo README (reference analogue:
``/root/reference/README.md:25-28``).
"""

from __future__ import annotations

import os
import re
from dataclasses import dataclass, field

from . import ast as A
from .module import Module, load_module
from .parser import parse_hcl

# The certified provider selections (support matrix, README.md). Exact
# released versions chosen from each module's `~>` line; bump these with a
# CHANGELOG entry when re-certifying.
CERTIFIED_PROVIDERS: dict[str, str] = {
    "hashicorp/google": "6.8.0",
    "hashicorp/google-beta": "6.8.0",
    "hashicorp/kubernetes": "2.32.0",
    "hashicorp/helm": "2.15.0",
    "hashicorp/random": "3.6.0",
}

REGISTRY = "registry.terraform.io"
LOCKFILE = ".terraform.lock.hcl"

HEADER = """\
# This file is maintained automatically by "terraform init".
# Manual edits may be lost in future updates.
#
# Version selections generated offline by `tfsim lock` from the certified
# provider table (see README support matrix); `hashes` are per-platform
# registry checksums that the first networked `terraform init` (or
# `terraform providers lock -platform=...`) records without altering the
# selections below. CI checks selections against every versions.tf
# constraint in the module tree (tests/test_lockfile.py).
"""


class LockfileError(ValueError):
    pass


@dataclass
class LockEntry:
    address: str                 # registry.terraform.io/hashicorp/google
    version: str
    constraints: str | None
    hashes: list[str] = field(default_factory=list)


# --------------------------------------------------------------- versions

def _ver(v: str) -> tuple[int, ...]:
    parts = v.strip().split("-")[0].split(".")
    if not all(p.isdigit() for p in parts):
        raise LockfileError(f"unparsable version {v!r}")
    return tuple(int(p) for p in parts)


def _pad(v: tuple[int, ...], n: int = 3) -> tuple[int, ...]:
    return v + (0,) * (n - len(v))


_CLAUSE_RE = re.compile(r"^(~>|>=|<=|!=|[=><])?\s*([\d.]+)$")


def parse_constraint_clause(clause: str):
    """``(op, version-string)`` for one constraint clause, ``None`` when
    it does not parse; a bare version means ``=``. The ONE copy of the
    clause grammar — the lint pinning rule consumes it too, so the two
    surfaces can never drift."""
    m = _CLAUSE_RE.match(clause.strip())
    if m is None:
        return None
    return (m.group(1) or "="), m.group(2)


def constraint_satisfied(version: str, constraint: str) -> bool:
    """Terraform (go-version) constraint semantics: ``=``, ``!=``, ``>``,
    ``>=``, ``<``, ``<=``, ``~>`` with comma-separated conjunction.
    Partial versions zero-pad ("= 3.6" means exactly 3.6.0); the pessimistic
    operator bounds above at the incremented second-to-last segment
    ("~> 6.8" → >= 6.8.0, < 7.0.0; "~> 2.32.0" → >= 2.32.0, < 2.33.0;
    "~> 6" → >= 6.0.0, < 7.0.0)."""
    v = _ver(version)
    for clause in constraint.split(","):
        clause = clause.strip()
        if not clause:
            continue
        parsed = parse_constraint_clause(clause)
        if parsed is None:
            raise LockfileError(f"unparsable constraint clause {clause!r}")
        op, rhs = parsed[0], _ver(parsed[1])
        n = max(len(v), len(rhs), 3)
        vp, rp = _pad(v, n), _pad(rhs, n)
        if op == "~>":
            prefix = rhs[:-1] if len(rhs) > 1 else rhs
            upper = prefix[:-1] + (prefix[-1] + 1,)
            if not (vp >= rp and v < upper):
                return False
        elif op == "=":
            if vp != rp:
                return False
        elif op == "!=":
            if vp == rp:
                return False
        elif op == ">":
            if not vp > rp:
                return False
        elif op == ">=":
            if not vp >= rp:
                return False
        elif op == "<":
            if not vp < rp:
                return False
        elif op == "<=":
            if not vp <= rp:
                return False
    return True


# ------------------------------------------------- requirements gathering

def local_module_calls(mod: Module) -> list[tuple[str, str]]:
    """``(call name, resolved dir)`` for every local-path module call —
    the one definition of "local source" shared by lockfile requirement
    gathering and the ``providers`` requirement tree."""
    out = []
    for name, call in sorted(mod.module_calls.items()):
        src = call.body.attr("source")
        if src and isinstance(src.expr, A.Literal) and \
                str(src.expr.value).startswith((".", "/")):
            out.append((name, os.path.normpath(
                os.path.join(mod.path, str(src.expr.value)))))
    return out


def _local_module_dirs(mod: Module) -> list[str]:
    return [d for _, d in local_module_calls(mod)]


def walk_module_tree(root_dir: str):
    """Yield ``(label, dir, module)`` over the local module-call tree.

    BFS from ``root_dir`` (label ""), every CALL yielded separately
    (siblings sharing a source dir are distinct entries, as terraform
    lists them); loading dedups by dir. A dir reappearing in its own
    ancestry chain raises ``ValueError`` — exact module-source cycle
    detection at any depth. One walker for every consumer (``init``,
    ``providers``) so traversal semantics cannot drift.
    """
    loaded: dict = {}
    queue = [(root_dir, "", (os.path.normpath(root_dir),))]
    while queue:
        d, label, chain = queue.pop(0)
        d = os.path.normpath(d)
        if d in chain[:-1]:
            raise ValueError(
                "module source cycle: " + " -> ".join(
                    os.path.relpath(c, root_dir) or "." for c in chain))
        if d not in loaded:
            loaded[d] = load_module(d)
        yield label, d, loaded[d]
        queue.extend(
            (dd, (f"{label}.{n}" if label else n),
             chain + (os.path.normpath(dd),))
            for n, dd in local_module_calls(loaded[d]))


def gather_requirements(module_dir: str) -> dict[str, list[str]]:
    """source address ("hashicorp/google") → constraint strings collected
    from the root module and every local child module, recursively."""
    reqs: dict[str, list[str]] = {}
    seen: set[str] = set()
    queue = [os.path.normpath(module_dir)]
    while queue:
        path = queue.pop()
        if path in seen:
            continue
        seen.add(path)
        mod = load_module(path)
        for name, spec in mod.required_providers.items():
            source = str(spec.get("source", f"hashicorp/{name}"))
            constraint = spec.get("version")
            lst = reqs.setdefault(source, [])
            if constraint and constraint not in lst:
                lst.append(str(constraint))
        queue.extend(_local_module_dirs(mod))
    return reqs


# ------------------------------------------------------------ parse/render

def parse_lockfile(text: str, filename: str = LOCKFILE) -> dict[str, LockEntry]:
    body = parse_hcl(text, filename=filename)
    entries: dict[str, LockEntry] = {}
    for blk in body.blocks:
        if blk.type != "provider" or len(blk.labels) != 1:
            raise LockfileError(
                f"{filename}:{blk.line}: unexpected block {blk.type!r}")
        addr = blk.labels[0]
        ver = blk.body.attr("version")
        cons = blk.body.attr("constraints")
        hashes_attr = blk.body.attr("hashes")
        hashes: list[str] = []
        if hashes_attr and isinstance(hashes_attr.expr, A.TupleExpr):
            hashes = [str(e.value) for e in hashes_attr.expr.items
                      if isinstance(e, A.Literal)]
        if not (ver and isinstance(ver.expr, A.Literal)):
            raise LockfileError(f"{filename}:{blk.line}: {addr} missing version")
        entries[addr] = LockEntry(
            address=addr,
            version=str(ver.expr.value),
            constraints=(str(cons.expr.value)
                         if cons and isinstance(cons.expr, A.Literal) else None),
            hashes=hashes,
        )
    return entries


def generate_lockfile(module_dir: str,
                      selections: dict[str, str] | None = None) -> str:
    selections = selections or CERTIFIED_PROVIDERS
    reqs = gather_requirements(module_dir)
    out = [HEADER]
    for source in sorted(reqs):
        if source not in selections:
            raise LockfileError(
                f"no certified selection for provider {source!r} "
                f"(required by {module_dir})")
        version = selections[source]
        for c in reqs[source]:
            if not constraint_satisfied(version, c):
                raise LockfileError(
                    f"{source} selection {version} violates constraint {c!r}")
        out.append(f'provider "{REGISTRY}/{source}" {{')
        out.append(f'  version     = "{version}"')
        if reqs[source]:
            out.append(f'  constraints = "{", ".join(sorted(reqs[source]))}"')
        out.append("}")
        out.append("")
    return "\n".join(out)


# ------------------------------------------------------------------ check

def check_lockfile(module_dir: str) -> list[str]:
    """CI findings; empty list == lockfile present and consistent."""
    findings: list[str] = []
    path = os.path.join(module_dir, LOCKFILE)
    if not os.path.exists(path):
        return [f"{module_dir}: missing {LOCKFILE}"]
    with open(path) as fh:
        entries = parse_lockfile(fh.read(), filename=path)
    reqs = gather_requirements(module_dir)
    for source, constraints in sorted(reqs.items()):
        addr = f"{REGISTRY}/{source}"
        entry = entries.pop(addr, None)
        if entry is None:
            findings.append(f"{path}: required provider {source} not locked")
            continue
        for c in constraints:
            if not constraint_satisfied(entry.version, c):
                findings.append(
                    f"{path}: {source} locked at {entry.version}, which "
                    f"violates constraint {c!r}")
    for addr in sorted(entries):
        findings.append(f"{path}: stale lock entry {addr} (no longer required)")
    return findings


def write_lockfile(module_dir: str,
                   selections: dict[str, str] | None = None) -> str:
    path = os.path.join(module_dir, LOCKFILE)
    text = generate_lockfile(module_dir, selections)
    with open(path, "w") as fh:
        fh.write(text)
    return path
