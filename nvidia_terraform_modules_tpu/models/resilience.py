# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""The preemption-tolerant training runtime: supervision around the loop.

PRs 2–3 taught the *infrastructure* simulator to survive failure
(fault-injecting control plane, failure-isolating parallel apply); this
module is the same posture for the *workload* the clusters exist to run.
Podracer (Hessel et al., 2021) and the Maple line in PAPERS.md treat
preemption-tolerant, supervised workers as the precondition for cheap
large-scale TPU training — on spot slices (``gke-tpu/tpu_slices.tf``)
the preemption notice is routine, not exceptional. Three mechanisms:

- :class:`PreemptionGuard` — a SIGTERM/preemption-notice handler that
  *drains* instead of dying: the in-flight train step completes, an
  emergency checkpoint commits inside a configurable grace budget
  (``ResilienceConfig.grace_seconds``, sized against the pod's
  ``termination_grace_period_seconds`` — the ``tpu-spot-no-grace`` lint
  rule cross-checks the two), and the process exits with a *restartable*
  code instead of losing the step;
- :class:`Heartbeat` + :class:`HeartbeatMonitor` — per-process liveness
  files next to the checkpoints. A peer that dies inside a collective
  leaves everyone else blocked in gloo/ICI forever; the monitor converts
  that indefinite hang into a bounded, **classified** failure
  (:class:`PeerFailure` written to disk, exit ``EXIT_PEER_DEAD``) that a
  supervisor restarts;
- capped exponential backoff with jitter (``utils/retry.py`` — the
  ``tfsim`` control-plane policy shape) around distributed init
  (``parallel/multihost.py``) and restore-time reads
  (``models/checkpoint.py``), so transient infrastructure noise costs
  milliseconds, not attempts.

:class:`SupervisedLoop` composes the three around any ``step_fn`` — the
burn-in smoke test and the chaos harness's training worker both run
through it, so the kill-and-resume invariants the harness asserts are
properties of the same code path production uses.

**The serving twin.** PR 13 gives the serving fleet the same posture:
:class:`LivenessBreaker` factors the classified-liveness state machine
(stale ⇒ circuit opens, fresh ⇒ a bounded quarantine before re-entry —
slow and dead never conflated) out into a reusable, thread-free form;
``models/fleet.py`` runs it over replica queue poll-stamps to quarantine
flapping replicas while dead ones are redriven.

**Elastic worlds.** PR 5's supervision was shape-preserving: a
classified ``EXIT_PEER_DEAD`` restarted the *same* N-host world, so a
spot fleet that shrank from N to N-1 hosts simply died N-1 restarts
later. This revision makes the world a variable (Podracer's decoupled,
slice-granular scaling): :class:`ElasticConfig` carries the floor
(``TPU_ELASTIC_MIN_WORLD``) and grow-back posture
(``TPU_ELASTIC_GROW_BACK``), :func:`plan_world_size` is the one
re-forming decision — on a dead peer the supervisor relaunches the
*survivors* as a smaller world (bounded distributed init with the new
process set, a fresh mesh over the remaining devices, and an elastic
**re-sharding** restore of the N-host checkpoint —
``models/checkpoint.py`` streams each parameter against the new
``NamedSharding``), and when capacity returns the next restart grows
the world back the same way. :func:`classify_exit` maps the process
exit codes to those decisions without parsing logs. The restore phase
itself is retried under ``ResilienceConfig.restore_policy``: a peer
that is merely *slow to restart* surfaces as a classified checkpoint
rendezvous timeout, which must cost a backoff-spaced retry — not an
immediate escalation that burns a restart attempt (the
``EXIT_PEER_DEAD``-during-restore fix).
"""

from __future__ import annotations

import dataclasses
import json
import os
import signal
import threading
import time
from typing import Any, Callable, Optional

from ..utils.retry import RetryPolicy, retry_call

# process exit codes a supervisor can classify without parsing logs:
# preempted-and-drained (restart me, my checkpoint is committed) vs
# peer-dead (restart the world; one of us stopped heartbeating) vs
# elastic-paused (a reduced world yielded because capacity returned —
# restart me at the grown world size)
EXIT_PREEMPTED = 75    # EX_TEMPFAIL: transient, retry the job
EXIT_PEER_DEAD = 76    # EX_PROTOCOL: the collective world is broken
EXIT_ELASTIC_PAUSE = 77  # EX_NOPERM+: yielded for a world-size change

_HEARTBEAT_DIR = "heartbeats"


def classify_exit(returncode: int) -> str:
    """Map a worker's exit code to the supervisor's restart decision.

    ``completed`` — done, don't restart. ``preempted`` — drained with a
    committed checkpoint; restart at the same world size. ``peer_dead``
    — the collective world broke; re-form it from the *survivors*
    (:func:`plan_world_size`). ``elastic_pause`` — a reduced world
    yielded at a step boundary so the supervisor can grow the world
    back. ``error`` — everything else (raw SIGKILL death shows up here
    as a negative returncode); restartable, same world.
    """
    if returncode == 0:
        return "completed"
    if returncode == EXIT_PREEMPTED:
        return "preempted"
    if returncode == EXIT_PEER_DEAD:
        return "peer_dead"
    if returncode == EXIT_ELASTIC_PAUSE:
        return "elastic_pause"
    return "error"


@dataclasses.dataclass(frozen=True)
class ResilienceConfig:
    """Knobs for the supervised loop (env-overridable, see
    :func:`resilience_from_env`; operational guidance in
    ``gke-tpu/README.md`` "Preemption & resume runbook")."""

    # emergency-checkpoint budget after the preemption notice: the drain
    # (finish the in-flight step) plus the final save must fit here. Size
    # the pod's termination_grace_period_seconds ABOVE this value.
    grace_seconds: float = 30.0
    # liveness: how often each process stamps its heartbeat file, and how
    # stale a previously-seen peer heartbeat may grow before the hang is
    # classified as a dead peer. The timeout must exceed the longest
    # legitimate silent stretch (one train step + one checkpoint save).
    heartbeat_interval_s: float = 2.0
    heartbeat_timeout_s: float = 60.0
    # distributed init / restore-read retry shapes (control-plane mirror)
    init_policy: RetryPolicy = RetryPolicy(
        initial_s=1.0, multiplier=2.0, cap_s=30.0, max_attempts=3)
    # restore-phase retries: a classified checkpoint failure during
    # RESTORE (rendezvous timeout — peer-dead territory, but the peer is
    # usually just slow to restart) retries with backoff before it
    # escalates; a corrupt step is terminal here (quarantine handles it)
    restore_policy: RetryPolicy = RetryPolicy(
        initial_s=0.5, multiplier=2.0, cap_s=10.0, max_attempts=4)

    def __post_init__(self):
        if self.grace_seconds <= 0:
            raise ValueError(
                f"grace_seconds must be > 0, got {self.grace_seconds}")
        if self.heartbeat_timeout_s <= self.heartbeat_interval_s:
            raise ValueError(
                f"heartbeat_timeout_s ({self.heartbeat_timeout_s}) must "
                f"exceed heartbeat_interval_s "
                f"({self.heartbeat_interval_s}) — a timeout inside the "
                f"stamping interval declares every live peer dead")


def resilience_from_env(env: Optional[dict] = None) -> ResilienceConfig:
    """Build the config from the Job env (all optional):

    - ``TPU_SMOKETEST_GRACE_SECONDS`` — emergency-checkpoint budget;
    - ``TPU_HEARTBEAT_INTERVAL_S`` / ``TPU_HEARTBEAT_TIMEOUT_S`` —
      liveness stamping/staleness.
    """
    e = os.environ if env is None else env
    kw: dict[str, Any] = {}
    if "TPU_SMOKETEST_GRACE_SECONDS" in e:
        kw["grace_seconds"] = float(e["TPU_SMOKETEST_GRACE_SECONDS"])
    if "TPU_HEARTBEAT_INTERVAL_S" in e:
        kw["heartbeat_interval_s"] = float(e["TPU_HEARTBEAT_INTERVAL_S"])
    if "TPU_HEARTBEAT_TIMEOUT_S" in e:
        kw["heartbeat_timeout_s"] = float(e["TPU_HEARTBEAT_TIMEOUT_S"])
    return ResilienceConfig(**kw)


# --------------------------------------------------------- elastic worlds


class ElasticWorldError(RuntimeError):
    """The surviving process set is below the elastic floor — no world
    size satisfies ``TPU_ELASTIC_MIN_WORLD``, so the job must escalate
    instead of limping on a world too small to make progress."""


@dataclasses.dataclass(frozen=True)
class ElasticConfig:
    """Elastic-resume posture: how far the world may shrink, and whether
    it grows back when capacity returns.

    ``desired_world`` is the fleet's full size (the Job's completions /
    slice count × hosts); ``min_world`` is the floor below which
    continuing is worse than waiting (throughput, or a batch that no
    longer shards). Env knobs: ``TPU_ELASTIC_MIN_WORLD``,
    ``TPU_ELASTIC_GROW_BACK`` (see :func:`elastic_from_env` and the
    "Preemption & resume runbook" in ``gke-tpu/README.md``).
    """

    desired_world: int = 1
    min_world: int = 1
    grow_back: bool = True

    def __post_init__(self):
        if self.desired_world < 1:
            raise ValueError(
                f"desired_world must be >= 1, got {self.desired_world}")
        if not 1 <= self.min_world <= self.desired_world:
            raise ValueError(
                f"min_world must be in [1, desired_world="
                f"{self.desired_world}], got {self.min_world}")


def elastic_from_env(desired_world: int,
                     env: Optional[dict] = None) -> ElasticConfig:
    """Build the elastic posture from the Job env (all optional):

    - ``TPU_ELASTIC_MIN_WORLD`` — smallest world worth running
      (default 1: train on the last survivor rather than die);
    - ``TPU_ELASTIC_GROW_BACK`` — ``0`` pins a shrunken world until the
      run ends (default ``1``: re-expand as capacity returns).
    """
    e = os.environ if env is None else env
    kw: dict[str, Any] = {"desired_world": desired_world}
    if "TPU_ELASTIC_MIN_WORLD" in e:
        kw["min_world"] = int(e["TPU_ELASTIC_MIN_WORLD"])
    if "TPU_ELASTIC_GROW_BACK" in e:
        kw["grow_back"] = e["TPU_ELASTIC_GROW_BACK"] not in (
            "0", "false", "False", "")
    return ElasticConfig(**kw)


def plan_world_size(alive: int, cfg: ElasticConfig,
                    current: Optional[int] = None) -> int:
    """The one elastic decision: the world size to (re-)form next.

    ``alive`` is how many processes can join the next attempt (survivors
    after a dead peer, or the full fleet once capacity returned);
    ``current`` is the world size of the attempt that just ended (None
    for the first). Shrink follows the survivors immediately; growth
    only happens when ``grow_back`` allows it — a fleet pinned small by
    policy re-forms at ``current`` even when more capacity shows up.
    Raises :class:`ElasticWorldError` below the floor.
    """
    if alive < cfg.min_world:
        raise ElasticWorldError(
            f"only {alive} process(es) can join the next world — below "
            f"the elastic floor TPU_ELASTIC_MIN_WORLD={cfg.min_world} "
            f"(desired {cfg.desired_world}); escalating instead of "
            f"limping")
    target = min(alive, cfg.desired_world)
    if current is not None and target > current and not cfg.grow_back:
        return current
    return target


# ------------------------------------------------------------- preemption


class PreemptionGuard:
    """Convert SIGTERM into a drain request with a grace deadline.

    Use as a context manager around the train loop. The handler only
    sets state — the *loop* decides when to stop (after the in-flight
    step), which is the whole point: a mid-step kill loses the step, a
    drained stop commits it. Installing from a non-main thread (pytest
    workers, library use) degrades to an inert guard (``installed`` is
    False) rather than crashing — signals are a main-thread facility.

    A second SIGTERM while draining is left to the default disposition
    of the *restored* handler on exit; inside the guard it is absorbed
    (Kubernetes repeats the signal; repeats must not kill the drain).
    """

    def __init__(self, grace_seconds: float = 30.0,
                 signals: tuple = (signal.SIGTERM,)):
        self.grace_seconds = grace_seconds
        self._signals = signals
        self._previous: dict = {}
        self.installed = False
        self._preempted_at: Optional[float] = None

    def __enter__(self) -> "PreemptionGuard":
        try:
            for sig in self._signals:
                self._previous[sig] = signal.signal(sig, self._on_signal)
            self.installed = True
        except ValueError:   # not the main thread
            self._previous.clear()
            self.installed = False
        return self

    def __exit__(self, *exc) -> None:
        for sig, prev in self._previous.items():
            signal.signal(sig, prev)
        self._previous.clear()
        self.installed = False

    def _on_signal(self, signum, frame) -> None:  # noqa: ARG002
        if self._preempted_at is None:
            self._preempted_at = time.monotonic()

    @property
    def preempted(self) -> bool:
        return self._preempted_at is not None

    @property
    def remaining_s(self) -> float:
        """Grace budget left for the emergency checkpoint (0 when not
        preempted — callers gate on :attr:`preempted` first)."""
        if self._preempted_at is None:
            return 0.0
        used = time.monotonic() - self._preempted_at
        return max(0.0, self.grace_seconds - used)


# -------------------------------------------------------------- liveness


class PeerFailure(Exception):
    """A peer stopped heartbeating: the collective world is broken.

    Carries enough to classify the failure without logs: which process,
    how stale, and at which step it was last seen alive.
    """

    def __init__(self, process: int, age_s: float, last_step: int):
        super().__init__(
            f"peer process {process} last heartbeat {age_s:.1f}s ago "
            f"(at step {last_step}) — classifying the collective hang "
            f"as a dead peer")
        self.process = process
        self.age_s = age_s
        self.last_step = last_step


class Heartbeat:
    """Per-process liveness file, stamped on every step and on a timer.

    The timer thread covers long silent stretches (compile, big
    collective) so a *slow* step is distinguishable from a *dead*
    process; :meth:`beat` stamps synchronously with the current step so
    a supervisor can also read training progress from the same file.
    """

    def __init__(self, directory: str, process_id: int,
                 interval_s: float = 2.0, clock=time.time):
        self.path = os.path.join(directory, _HEARTBEAT_DIR,
                                 f"p{process_id:05d}.json")
        self.process_id = process_id
        self.interval_s = interval_s
        # epoch clock, injected: stamps are compared across PROCESSES,
        # so the default must be wallclock — tests inject a virtual one
        self._clock = clock
        self._step = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "Heartbeat":
        os.makedirs(os.path.dirname(self.path), exist_ok=True)
        self.beat(0)
        self._thread = threading.Thread(
            target=self._run, name="heartbeat", daemon=True)
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            self._stamp()

    def beat(self, step: int) -> None:
        self._step = step
        self._stamp()

    def _stamp(self) -> None:
        tmp = f"{self.path}.tmp"
        try:
            with open(tmp, "w") as fh:
                json.dump({"process": self.process_id, "step": self._step,
                           "time": self._clock(), "pid": os.getpid()}, fh)
            os.replace(tmp, self.path)
        except OSError:
            pass   # liveness is best-effort; the monitor handles absence

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def __enter__(self) -> "Heartbeat":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


class HeartbeatMonitor:
    """Watch every peer's heartbeat file; classify the dead ones.

    A peer only *arms* once a heartbeat stamped AFTER this monitor was
    born has been seen: a pod that never scheduled is the init timeout's
    failure, not a liveness one, and a stale file surviving a pod
    replacement on the shared checkpoint PVC must not let a resumed
    world re-classify a merely *slow-to-restart* peer as dead (the peer
    keeps stamping once alive, so it arms on the next check). After
    arming, a heartbeat older than ``timeout_s`` is a
    :class:`PeerFailure`. :meth:`watch` runs the check on a background
    thread and invokes ``on_dead`` — the supervised loop's callback
    writes the classification next to the checkpoints and exits
    ``EXIT_PEER_DEAD``, bounding what would otherwise be an indefinite
    gloo/ICI collective hang.
    """

    def __init__(self, directory: str, num_processes: int,
                 timeout_s: float = 60.0, self_id: Optional[int] = None,
                 telemetry=None, clock=time.time):
        self.directory = os.path.join(directory, _HEARTBEAT_DIR)
        self.num_processes = num_processes
        self.timeout_s = timeout_s
        self.self_id = self_id
        if telemetry is None:
            from ..telemetry import get_registry

            telemetry = get_registry()
        self._telemetry = telemetry
        # epoch clock, injected: ages are computed against peer stamps
        # written by Heartbeat with the same default
        self._clock = clock
        self._born = self._clock()
        self._armed: dict[int, dict] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def read(self) -> dict[int, dict]:
        """Current heartbeat payloads by process id (missing = absent)."""
        out: dict[int, dict] = {}
        for k in range(self.num_processes):
            path = os.path.join(self.directory, f"p{k:05d}.json")
            try:
                with open(path) as fh:
                    out[k] = json.load(fh)
            except (OSError, json.JSONDecodeError):
                continue
        return out

    def check(self, now: Optional[float] = None) -> list[PeerFailure]:
        """Dead peers as classified failures (empty = everyone lives)."""
        now = self._clock() if now is None else now
        for k, payload in self.read().items():
            # arm only on a heartbeat from THIS attempt's lifetime;
            # once armed, always track the latest payload
            if k in self._armed or payload.get("time", 0.0) >= self._born:
                self._armed[k] = payload
        failures = []
        worst = 0.0
        for k, last in self._armed.items():
            if k == self.self_id:
                continue
            age = now - last.get("time", 0.0)
            worst = max(worst, age)
            if age > self.timeout_s:
                failures.append(
                    PeerFailure(k, age, int(last.get("step", 0))))
        if self._telemetry.enabled:
            # the liveness headroom dashboarded: how stale the WORST
            # armed peer heartbeat is right now (0 = nothing armed yet)
            self._telemetry.gauge("heartbeat_lag_s").set(worst)
        return failures

    def watch(self, on_dead: Callable[[PeerFailure], None],
              interval_s: float = 1.0) -> "HeartbeatMonitor":
        def run():
            while not self._stop.wait(interval_s):
                failures = self.check()
                if failures:
                    on_dead(failures[0])
                    return
        self._thread = threading.Thread(
            target=run, name="heartbeat-monitor", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


class LivenessBreaker:
    """Classified-liveness circuit breaker: the staleness→quarantine
    state machine shared by everything that watches poll stamps.

    :class:`HeartbeatMonitor` classifies a peer as DEAD when its stamp
    goes stale past a timeout; this is the milder classification next
    to it — a target that is *alive but sick* (stale, then stamping
    again). Slow and dead must never be conflated: dead means redrive
    the work somewhere else, sick means stop SENDING new work until the
    target proves itself. Each key runs ``ok → suspect`` on a stale
    observation (the circuit OPENS — billed via ``on_open``),
    ``suspect → quarantine`` on the first fresh one, and only
    ``quarantine_polls`` consecutive fresh observations later does it
    re-enter ``ok``; flapping (stale again mid-quarantine) re-opens and
    restarts the sentence. The serving fleet's health monitor
    (``models/fleet.py``) runs one of these over its replica queues'
    poll stamps — a quarantined replica keeps serving what it already
    has but receives no steals or redrives.

    Pure state machine on purpose: no threads, no clocks, no files —
    the caller decides what "stale" means (heartbeat age, poll-stamp
    age, missed acks) and when to observe, so it is testable and
    reusable as-is.
    """

    def __init__(self, quarantine_polls: int = 16,
                 on_open: Optional[Callable[[object], None]] = None):
        if quarantine_polls < 1:
            raise ValueError(
                f"quarantine_polls must be >= 1, got {quarantine_polls}")
        self.quarantine_polls = quarantine_polls
        self._on_open = on_open
        self._state: dict = {}
        self.opens = 0

    def _open(self, key) -> None:
        self.opens += 1
        if self._on_open is not None:
            self._on_open(key)

    def observe(self, key, stale: bool) -> str:
        """Feed one liveness observation for ``key``; returns the new
        state (``"ok"`` | ``"suspect"`` | ``"quarantine"``)."""
        st = self._state.setdefault(key, ["ok", 0])
        if st[0] == "ok":
            if stale:
                st[0] = "suspect"
                self._open(key)
        elif st[0] == "suspect":
            if not stale:
                st[0] = "quarantine"
                st[1] = self.quarantine_polls
        else:                            # quarantine
            if stale:                    # flapped again: re-open
                st[0] = "suspect"
                self._open(key)
            else:
                st[1] -= 1
                if st[1] <= 0:
                    st[0] = "ok"
        return st[0]

    def state(self, key) -> str:
        return self._state.get(key, ["ok"])[0]

    def healthy(self, key) -> bool:
        """True when the circuit for ``key`` is closed (``"ok"``)."""
        return self.state(key) == "ok"


# ------------------------------------------------------- supervised loop


@dataclasses.dataclass
class LoopOutcome:
    """What the supervised loop did: ``completed`` (reached
    ``total_steps``) or ``preempted`` (drained + emergency checkpoint).
    ``resumed_from`` is the restored step (None for a fresh start)."""

    status: str
    step: int
    resumed_from: Optional[int]
    emergency_saved: bool = False


class SupervisedLoop:
    """Drive ``step_fn`` to ``total_steps`` under full supervision.

    One object owns the composition: restore-or-init, per-step
    checkpoints every ``save_every`` steps, heartbeats, the SIGTERM
    drain with an emergency checkpoint inside the grace budget, and the
    dead-peer monitor. The burn-in smoke test and the chaos harness's
    worker both run through here — the harness's kill-and-resume
    invariants hold for the production path because they ARE the
    production path.
    """

    def __init__(self, ckpt, cfg: ResilienceConfig, *,
                 total_steps: int, save_every: int = 1,
                 process_id: int = 0, num_processes: int = 1,
                 heartbeat_dir: Optional[str] = None,
                 on_peer_dead: Optional[Callable] = None,
                 telemetry=None):
        if save_every < 1:
            raise ValueError(f"save_every must be >= 1, got {save_every}")
        self.ckpt = ckpt
        self.cfg = cfg
        self.total_steps = total_steps
        self.save_every = save_every
        self.process_id = process_id
        self.num_processes = num_processes
        self.heartbeat_dir = heartbeat_dir
        self.on_peer_dead = on_peer_dead
        if telemetry is None:
            from ..telemetry import get_registry

            telemetry = get_registry()
        self.telemetry = telemetry

    def restore(self, abstract: Any, step: Optional[int] = None):
        """Restore ``abstract`` through the restart policy fix: a
        *classified, transient* checkpoint failure during restore — the
        rendezvous timeout a peer killed mid-restart leaves behind, the
        same hang the heartbeat monitor classifies ``EXIT_PEER_DEAD``
        during training — is retried with backoff
        (``cfg.restore_policy``) instead of escalated immediately, so a
        slow-to-reschedule peer costs seconds, not a whole restart
        attempt. Corrupt steps stay terminal here: quarantine-and-
        fallback inside ``restore_tree`` already owns that path, and a
        :class:`CorruptCheckpointError` that still escapes (an explicit
        ``step=``) must not be hammered, and neither must the
        deterministic missing-explicit-step error."""
        from .checkpoint import (
            CheckpointError,
            CorruptCheckpointError,
            MissingStepError,
        )

        return retry_call(
            lambda: self.ckpt.restore_tree(abstract, step),
            policy=self.cfg.restore_policy,
            what="checkpoint restore",
            retryable=(CheckpointError, OSError),
            giveup=lambda exc: isinstance(
                exc, (CorruptCheckpointError, MissingStepError)))

    # the default dead-peer action: leave a classification on disk where
    # the supervisor (and the next attempt) can read it, then exit with
    # the protocol code — never hang in the collective
    def _default_peer_dead(self, failure: PeerFailure) -> None:
        if self.telemetry.enabled:
            # the event layer flushes per record, so the classification
            # is on the timeline before os._exit skips every atexit hook
            self.telemetry.counter("supervisor_exit_peer_dead").inc()
            self.telemetry.event(
                "supervisor.exit", status="peer_dead",
                dead_process=failure.process,
                age_s=round(failure.age_s, 1),
                observed_by=self.process_id)
        if self.heartbeat_dir:
            # atomic (tmp + os.replace): the supervisor reads this
            # breadcrumb after our os._exit, so it must see the whole
            # classification or none of it — never a torn JSON
            dst = os.path.join(
                self.heartbeat_dir,
                f"peer_failure_p{self.process_id:05d}.json")
            try:
                tmp = f"{dst}.tmp.{os.getpid()}"
                with open(tmp, "w") as fh:
                    json.dump({"process": failure.process,
                               "age_s": round(failure.age_s, 1),
                               "last_step": failure.last_step,
                               "observed_by": self.process_id}, fh)
                os.replace(tmp, dst)
            except OSError:
                pass
        os._exit(EXIT_PEER_DEAD)

    def run(self, state: Any, step_fn: Callable[[Any, int], Any],
            start_step: int = 0,
            resumed_from: Optional[int] = None,
            meta: Optional[Callable[[int, Any], dict]] = None,
            ) -> tuple[Any, LoopOutcome]:
        """Run from ``start_step`` (exclusive) to ``total_steps``.

        ``step_fn(state, step) -> state`` is one train step (1-indexed
        ``step``). Returns the final state and a :class:`LoopOutcome`;
        on preemption the caller decides the exit path (the module-level
        workers exit ``EXIT_PREEMPTED``).
        """
        hb = None
        monitor = None
        step = start_step
        emergency_saved = False
        reg = self.telemetry
        if reg.enabled:
            reg.counter("supervisor_runs").inc()
            if resumed_from is not None:
                # this attempt is a RESTART: it resumed a prior attempt's
                # checkpoint — the counter a fleet dashboard alarms on
                reg.counter("supervisor_restart_attempts").inc()
                reg.event("supervisor.restart",
                          resumed_from=resumed_from,
                          process=self.process_id,
                          world=self.num_processes)
        try:
            if self.heartbeat_dir and self.num_processes >= 1:
                hb = Heartbeat(self.heartbeat_dir, self.process_id,
                               self.cfg.heartbeat_interval_s).start()
                hb.beat(step)
            if self.heartbeat_dir and self.num_processes > 1:
                monitor = HeartbeatMonitor(
                    self.heartbeat_dir, self.num_processes,
                    timeout_s=self.cfg.heartbeat_timeout_s,
                    self_id=self.process_id, telemetry=reg,
                ).watch(self.on_peer_dead or self._default_peer_dead)
            with PreemptionGuard(self.cfg.grace_seconds) as guard:
                while step < self.total_steps:
                    state = step_fn(state, step + 1)
                    step += 1
                    if hb is not None:
                        hb.beat(step)
                    saved_this_step = False
                    if self.ckpt is not None and (
                            step % self.save_every == 0 or
                            step == self.total_steps):
                        self.ckpt.save(
                            step, state,
                            meta=meta(step, state) if meta else
                            {"step": step})
                        saved_this_step = True
                    if guard.preempted and step < self.total_steps:
                        # drained: the in-flight step finished. Commit an
                        # emergency checkpoint inside the grace budget so
                        # the restart loses nothing — flush first so a
                        # pending async save cannot race the final one.
                        if self.ckpt is not None and not saved_this_step:
                            self.ckpt.save(
                                step, state,
                                meta=meta(step, state) if meta else
                                {"step": step, "emergency": True})
                            emergency_saved = True
                        if self.ckpt is not None:
                            self.ckpt.flush()
                        if reg.enabled:
                            reg.counter("supervisor_exit_preempted").inc()
                            reg.event("supervisor.exit",
                                      status="preempted", step=step,
                                      emergency_saved=emergency_saved,
                                      process=self.process_id)
                        return state, LoopOutcome(
                            "preempted", step, resumed_from,
                            emergency_saved)
                if self.ckpt is not None:
                    self.ckpt.flush()
                if reg.enabled:
                    reg.event("supervisor.exit", status="completed",
                              step=step, process=self.process_id)
                return state, LoopOutcome(
                    "completed", step, resumed_from, emergency_saved)
        finally:
            if monitor is not None:
                monitor.stop()
            if hb is not None:
                hb.stop()
