# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""Pallas flash attention: exactness vs dense, grads, burn-in integration.

Runs in pallas interpret mode on the virtual CPU mesh (the kernel's TPU
lowering shares the same trace), mirroring how tfsim stands in for terraform:
full logic coverage offline, hardware numbers from bench.py on the chip.
"""

import jax
import jax.numpy as jnp
import pytest

from nvidia_terraform_modules_tpu.models import (
    BurnInConfig,
    forward,
    init_params,
    make_train_step,
    synthetic_batch,
)
from nvidia_terraform_modules_tpu.ops import flash_attention
from nvidia_terraform_modules_tpu.ops.ring_attention import (
    dense_reference_attention,
)
from nvidia_terraform_modules_tpu.parallel import build_mesh, make_rules, plan_mesh


def _qkv(b=2, s=64, h=2, d=16, dtype=jnp.float32):
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    return tuple(jax.random.normal(k, (b, s, h, d), dtype) for k in ks)


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("block", [16, 32, 64])
def test_flash_matches_dense(causal, block):
    q, k, v = _qkv()
    out = flash_attention(q, k, v, causal=causal, block_q=block, block_k=block)
    ref = dense_reference_attention(q, k, v, causal=causal)
    assert jnp.max(jnp.abs(out - ref)) < 1e-5


def test_flash_rectangular_blocks():
    q, k, v = _qkv(s=64)
    out = flash_attention(q, k, v, block_q=16, block_k=32)
    ref = dense_reference_attention(q, k, v)
    assert jnp.max(jnp.abs(out - ref)) < 1e-5


def test_flash_gradients_match_dense():
    q, k, v = _qkv(s=32)

    def f1(q, k, v):
        return jnp.sum(jnp.square(flash_attention(q, k, v, block_q=16,
                                                  block_k=16)))

    def f2(q, k, v):
        return jnp.sum(jnp.square(dense_reference_attention(q, k, v)))

    g1 = jax.grad(f1, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(f2, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        assert jnp.max(jnp.abs(a - b)) < 1e-4


def test_flash_bf16_close_to_f32_dense():
    q, k, v = _qkv(s=32, dtype=jnp.bfloat16)
    out = flash_attention(q, k, v).astype(jnp.float32)
    ref = dense_reference_attention(
        *(t.astype(jnp.float32) for t in (q, k, v)))
    assert jnp.max(jnp.abs(out - ref)) < 0.05  # bf16 inputs, f32 accumulate


def test_flash_blocks_autoshrink_to_divisor():
    # S=48 with requested 32 → blocks shrink to 24; numbers unchanged
    q, k, v = _qkv(s=48)
    out = flash_attention(q, k, v, block_q=32, block_k=32)
    ref = dense_reference_attention(q, k, v)
    assert jnp.max(jnp.abs(out - ref)) < 1e-5


def test_flash_rejects_untileable_seq():
    # prime S with a smaller requested block leaves no divisor ≥ 8
    q, k, v = _qkv(s=97)
    with pytest.raises(ValueError, match="no block divisor"):
        flash_attention(q, k, v, block_q=32, block_k=32)


def test_fit_block_only_returns_sublane_multiples():
    """ADVICE round-1: block sizes must be 8-multiples — odd divisors like
    125 (S=250) pass CPU interpret but real-TPU pallas rejects them."""
    from nvidia_terraform_modules_tpu.ops.flash_attention import _fit_block
    assert _fit_block(192, None) == 96          # not 64? 96 divides and is 8k
    assert _fit_block(250, None) == 0           # 125 must NOT be picked
    # None default is min(1024, max(128, S/4)) — the measured v5e q-block
    # rule (1024x1024 runs S=4096 2x faster than the old 512 default)
    assert _fit_block(4096, None) == 1024
    assert _fit_block(48, 32) == 24             # 24 = 3×8, divides 48
    assert _fit_block(8, None) == 8
    assert _fit_block(4, None) == 4             # tiny interpret-only shapes
    for s in (128, 192, 256, 384, 512, 1024, 4096):
        b = _fit_block(s, None)
        assert b % 8 == 0 and s % b == 0
    # S=250 now takes the explicit pad-the-sequence error path
    q, k, v = _qkv(s=250)
    with pytest.raises(ValueError, match="pad the sequence"):
        flash_attention(q, k, v)


# ------------------------------------------------- fused backward (PR 4)

def _grads(fn, q, k, v):
    """(dq, dk, dv) of the scalar loss sum(fn(q,k,v)²)."""
    return jax.grad(
        lambda q_, k_, v_: jnp.sum(
            jnp.square(fn(q_, k_, v_).astype(jnp.float32))),
        argnums=(0, 1, 2))(q, k, v)


# square blocks, rectangular blocks, and an autoshrink shape (S=48 with
# requested 32 → blocks shrink to the non-power-of-two divisor 24)
_BWD_BLOCK_CASES = [
    ("square", 64, 16, 16),
    ("rect", 64, 16, 32),
    ("autoshrink", 48, 32, 32),
]


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16],
                         ids=["f32", "bf16"])
@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("case", _BWD_BLOCK_CASES, ids=lambda c: c[0])
def test_fused_backward_parity_matrix(case, causal, dtype):
    """The differential-correctness oracle for the single-pass backward:
    fused vs dense ``jax.grad`` reference AND fused vs split, across
    causal × non-causal, square × rectangular blocks, f32 × bf16, and an
    autoshrink (non-divisible S) shape — interpret mode on CPU. The full
    matrix is slow-marked; test_fused_backward_tier1_seed keeps one seed
    in the fast profile."""
    _, s, bq, bk = case
    q, k, v = _qkv(s=s, dtype=dtype)

    def flash(mode):
        return lambda q_, k_, v_: flash_attention(
            q_, k_, v_, causal=causal, block_q=bq, block_k=bk,
            backward=mode)

    g_fused = _grads(flash("fused"), q, k, v)
    g_split = _grads(flash("split"), q, k, v)
    g_dense = _grads(
        lambda q_, k_, v_: dense_reference_attention(q_, k_, v_,
                                                     causal=causal),
        q, k, v)
    # fused and split share _bwd_tile and accumulate in the same order, so
    # interpret mode should agree to f32 rounding; dense is the analytic
    # reference with a dtype-dependent tolerance
    tol_split = 1e-6 if dtype == jnp.float32 else 1e-2
    tol_dense = 1e-4 if dtype == jnp.float32 else 0.15
    for gf, gs, gd in zip(g_fused, g_split, g_dense):
        assert jnp.max(jnp.abs(gf - gs)) < tol_split
        assert jnp.max(jnp.abs(gf - gd)) < tol_dense


def test_fused_backward_tier1_seed():
    """One fused interpret-mode seed of the parity matrix stays tier-1
    (causal, square blocks, f32) so the default backward path is gated on
    every fast run without paying for the full sweep."""
    q, k, v = _qkv(s=32)

    def flash(mode):
        return lambda q_, k_, v_: flash_attention(
            q_, k_, v_, block_q=16, block_k=16, backward=mode)

    g_fused = _grads(flash("fused"), q, k, v)
    g_split = _grads(flash("split"), q, k, v)
    g_dense = _grads(dense_reference_attention, q, k, v)
    for gf, gs, gd in zip(g_fused, g_split, g_dense):
        assert jnp.max(jnp.abs(gf - gs)) < 1e-6
        assert jnp.max(jnp.abs(gf - gd)) < 1e-4


def test_backward_knob_validated():
    q, k, v = _qkv(s=16)
    with pytest.raises(ValueError, match="fused|split"):
        flash_attention(q, k, v, backward="bogus")
    with pytest.raises(ValueError, match="flash_backward"):
        BurnInConfig(flash_backward="bogus")


def _count_pallas_calls(jaxpr) -> int:
    """Recursively count pallas_call eqns in a (Closed)Jaxpr."""
    inner = getattr(jaxpr, "jaxpr", jaxpr)
    total = 0
    for eqn in inner.eqns:
        if eqn.primitive.name == "pallas_call":
            total += 1
        for val in eqn.params.values():
            for sub in (val if isinstance(val, (list, tuple)) else (val,)):
                if hasattr(sub, "eqns") or hasattr(sub, "jaxpr"):
                    total += _count_pallas_calls(sub)
    return total


@pytest.mark.parametrize("backward,expected", [("fused", 1), ("split", 2)])
def test_backward_lowering_pallas_call_count(backward, expected):
    """Lowering regression: the fused path must stage exactly ONE backward
    pallas_call (and split exactly two) — a silent fallback to the split
    kernels can never masquerade as a perf win. Counted on the vjp
    function's jaxpr, which contains only the backward (the forward ran
    eagerly; its residuals are constants)."""
    q, k, v = _qkv(s=32)
    _, vjp_fn = jax.vjp(
        lambda q_, k_, v_: flash_attention(q_, k_, v_, block_q=16,
                                           block_k=16, backward=backward),
        q, k, v)
    jaxpr = jax.make_jaxpr(vjp_fn)(jnp.ones_like(q))
    assert _count_pallas_calls(jaxpr) == expected


def test_burnin_flash_matches_dense_forward_unsharded():
    base = dict(vocab=64, d_model=32, n_heads=2, d_ff=64, n_layers=2,
                seq_len=16, batch=4, dtype=jnp.float32)
    cfg_d = BurnInConfig(**base, attn="dense")
    cfg_f = BurnInConfig(**base, attn="flash")
    params = init_params(jax.random.PRNGKey(0), cfg_d)
    tokens, _ = synthetic_batch(jax.random.PRNGKey(1), cfg_d)
    dense = forward(params, tokens, cfg_d)
    flash = forward(params, tokens, cfg_f)
    assert jnp.max(jnp.abs(dense - flash)) < 1e-5


def test_burnin_flash_matches_dense_forward_sharded(jax8):
    mesh = build_mesh(plan_mesh(8, tp=2, sp=2))
    rules = make_rules(mesh)
    base = dict(vocab=64, d_model=32, n_heads=2, d_ff=64, n_layers=1,
                seq_len=16, batch=8, dtype=jnp.float32)
    cfg_d = BurnInConfig(**base, attn="dense")
    cfg_f = BurnInConfig(**base, attn="flash")
    params = init_params(jax.random.PRNGKey(0), cfg_d, rules)
    tokens, _ = synthetic_batch(jax.random.PRNGKey(1), cfg_d, rules)
    dense = forward(params, tokens, cfg_d, rules)
    flash = forward(params, tokens, cfg_f, rules)
    assert jnp.max(jnp.abs(dense - flash)) < 1e-5


def test_burnin_flash_train_step_decreases_loss(jax8):
    mesh = build_mesh(plan_mesh(8, tp=2, sp=2))
    rules = make_rules(mesh)
    cfg = BurnInConfig(vocab=64, d_model=32, n_heads=2, d_ff=64, n_layers=1,
                       seq_len=16, batch=8, attn="flash")
    params = init_params(jax.random.PRNGKey(0), cfg, rules)
    step = make_train_step(cfg, rules, lr=5e-2)
    batch = synthetic_batch(jax.random.PRNGKey(1), cfg, rules)
    losses = []
    for _ in range(4):
        params, loss = step(params, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses
