# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""Block/paged KV-cache allocation for the continuous-batching engine.

The dense serving pool reserved ``max_len`` cache rows per slot for the
whole life of the engine — a request generating 12 tokens from an
8-token prompt held the same HBM as one filling the window. With ragged
real traffic (variable prompt AND output lengths) most of that
reservation is dead rows. The paged design (vLLM's PagedAttention,
re-thought for XLA static shapes) splits the physical cache into
fixed-size BLOCKS:

- the physical store is one ``[num_blocks, block_size, kv_heads, D]``
  buffer per layer, shared by every request;
- each request owns a **block table** — the logical→physical mapping —
  and exactly ``ceil(rows_needed / block_size)`` blocks, so internal
  fragmentation is bounded by ``block_size - 1`` rows per request;
- blocks return to a host-side free list the moment the request
  retires, and the next admission reuses them — the recycling that lets
  a fixed pool serve an unbounded request stream.

Division of labour (the same host/device split the serving engine
already lives by): the **host** owns WHICH blocks belong to which
request (:class:`BlockAllocator` — plain integers, no device traffic),
the **device** owns the math — block tables and per-slot positions are
small int32 arrays threaded through ``decode.forward_paged``, whose
gather/scatter path reads and writes physical rows through them with no
data-dependent shapes anywhere.

Block 0 is RESERVED as the garbage block: idle and retired slots'
writes are routed there (their table rows may point at blocks already
recycled to another request — without the reroute a retired slot's
still-computing forward would corrupt the new owner's cache).

Blocks are REFCOUNTED: :meth:`BlockAllocator.alloc` hands a block out
at refcount 1, :meth:`BlockAllocator.share` maps an already-allocated
block into another request's table (refcount++), and
:meth:`BlockAllocator.free` only returns a block to the free list when
the LAST reference drops — the mechanism that lets a popular prompt
prefix live ONCE in HBM while any number of concurrent requests read
it. :class:`PrefixIndex` is the host-side lookup that finds those
shareable blocks: block-aligned token-hash chains → physical block
ids, holding one reference per indexed block so a retained prefix
survives its writer's retirement, with an LRU cap on
retained-but-unreferenced blocks.

The paged block is also the fleet's TRANSFER UNIT:
:func:`export_block_rows` / :func:`import_block_rows` copy whole
blocks' physical content between two pools (the prefill→decode handoff
of ``models/fleet.py``'s disaggregated mode — an explicit device copy
on CPU, the seam an ICI/DCN transfer slots into on chip).

``tests/test_paging.py`` pins the allocator invariants (no double
alloc, free-list recycling, exhaustion, the fragmentation bound,
refcount free-at-zero, LRU eviction safety, cross-pool transfer
roundtrips) and ``tests/test_serving.py`` the end-to-end exactness of
paged serving against solo decode.
"""

from __future__ import annotations

import hashlib
import zlib
from collections import OrderedDict
from typing import Any, Sequence

from .burnin import BurnInConfig
from .decode import cache_rows


def blocks_for_rows(rows: int, block_size: int) -> int:
    """Blocks needed to hold ``rows`` cache rows (0 rows → 0 blocks)."""
    if rows < 0:
        raise ValueError(f"rows must be >= 0, got {rows}")
    return -(-rows // block_size)


class BlockAllocator:
    """Host-side free-list allocator over ``num_blocks`` physical blocks.

    Block 0 (more generally ``reserved`` leading blocks) is never handed
    out — it is the garbage block dead slots write into. ``alloc`` is
    all-or-nothing (a request needs its whole table before admission);
    ``free`` returns blocks for reuse in LIFO order, so a retire→admit
    pair tends to reuse hot blocks. Exhaustion returns ``None`` — the
    scheduler's signal to hold the request in the admission queue until
    a retirement frees capacity (admission control, not an error).
    """

    def __init__(self, num_blocks: int, *, reserved: int = 1):
        if num_blocks <= reserved:
            raise ValueError(
                f"num_blocks ({num_blocks}) must exceed the reserved "
                f"garbage block count ({reserved})")
        self.num_blocks = num_blocks
        self.reserved = reserved
        self._free = list(range(num_blocks - 1, reserved - 1, -1))
        self._ref: dict[int, int] = {}           # block → reference count
        self.high_water = 0

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def in_use(self) -> int:
        """PHYSICAL blocks allocated — each counted once, however many
        tables reference it (the HBM bill)."""
        return len(self._ref)

    @property
    def refs_total(self) -> int:
        """LOGICAL block references — what the same tables would cost
        WITHOUT sharing (``refs_total - in_use`` is the sharing win)."""
        return sum(self._ref.values())

    def refcount(self, block: int) -> int:
        return self._ref.get(block, 0)

    def alloc(self, n: int) -> list[int] | None:
        """``n`` blocks or ``None`` (never a partial grant); each block
        starts at refcount 1 (the caller's reference)."""
        if n < 0:
            raise ValueError(f"cannot allocate {n} blocks")
        if n > len(self._free):
            return None
        blocks = [self._free.pop() for _ in range(n)]
        for b in blocks:
            self._ref[b] = 1
        self.high_water = max(self.high_water, len(self._ref))
        return blocks

    def share(self, blocks: Sequence[int]) -> None:
        """Add one reference to each (already-allocated) block — the
        physical bytes stay where they are, another table maps them."""
        for b in blocks:
            if b not in self._ref:
                raise ValueError(
                    f"block {b} is not allocated — only a live block "
                    f"can be shared into another table")
        for b in blocks:
            self._ref[b] += 1

    def free(self, blocks) -> None:
        """Drop one reference per block; a block returns to the free
        list only when its LAST reference drops. Freeing an unallocated
        block is loud (double free / reserved / foreign id)."""
        for b in blocks:
            if b not in self._ref:
                raise ValueError(
                    f"block {b} is not allocated (double free, a "
                    f"reserved block, or a foreign id)")
            self._ref[b] -= 1
            if self._ref[b] == 0:
                del self._ref[b]
                self._free.append(b)

    def stats(self) -> dict[str, int]:
        return {
            "num_blocks": self.num_blocks,
            "reserved": self.reserved,
            "in_use": self.in_use,
            "free": self.free_blocks,
            "high_water": self.high_water,
            "refs_total": self.refs_total,
        }


def chain_chunks(tokens: Sequence[int], block_size: int,
                 offset: int = 0) -> list[tuple[int, ...]]:
    """Split ``tokens`` into the FULL block-grid chunks of a request's
    own blocks.

    ``offset`` is the number of leading rows of the first own block
    already occupied by non-prompt content identical across requests
    (the template prefix's copied tail rows), so the first chunk covers
    ``block_size - offset`` tokens and every later chunk ``block_size``.
    Only chunks whose block is COMPLETELY covered by ``tokens`` are
    returned — a partial tail block is never shareable (its remaining
    rows differ per request).
    """
    if not 0 <= offset < block_size:
        raise ValueError(
            f"offset must be in [0, block_size), got {offset}")
    out: list[tuple[int, ...]] = []
    start, width = 0, block_size - offset
    while start + width <= len(tokens):
        out.append(tuple(int(t) for t in tokens[start:start + width]))
        start += width
        width = block_size
    return out


def chunk_tokens_covered(k: int, block_size: int, offset: int = 0) -> int:
    """Prompt tokens covered by the first ``k`` full own-block chunks —
    the prefill-start offset after sharing ``k`` blocks (0 for k=0)."""
    return 0 if k == 0 else k * block_size - offset


class PrefixIndex:
    """Host-side prefix lookup: block-aligned token-hash chains →
    physical blocks, holding ONE allocator reference per indexed block.

    The chain key of a request's ``i``-th full own block is
    ``H(key_{i-1}, tokens_i)`` (blake2b over the raw token bytes), so a
    key names the ENTIRE token history up to and including that block —
    two requests produce the same key iff their prompts agree on every
    row the block holds and on everything before it, which (positions
    being engine-constant) is exactly when the cached K/V content is
    identical. Hash collisions are nevertheless never trusted with
    correctness: each entry stores its token chunk and a match compares
    tokens outright.

    Because the index holds its own reference, an indexed block can
    never be recycled under a reader: a writer's retirement decrements
    its reference but the content stays resident ("recently retired")
    until the LRU cap on retained-but-UNREFERENCED blocks (refcount 1 —
    the index's own) evicts it. Entries are touched leaf-first on a
    match so eviction takes chain suffixes before the prefixes that
    reach them; evicting an interior entry cascades to its descendants
    (unreachable entries must not keep holding references).
    """

    def __init__(self, alloc: BlockAllocator, capacity: int):
        if capacity < 0:
            raise ValueError(f"capacity must be >= 0, got {capacity}")
        self.alloc = alloc
        self.capacity = capacity
        # key → (block, token-chunk, parent key) in LRU order
        self._entries: "OrderedDict[bytes, tuple[int, tuple, bytes | None]]" = OrderedDict()
        self._children: dict[bytes, set[bytes]] = {}
        self.hit_blocks = 0
        self.lookups = 0

    def __len__(self) -> int:
        return len(self._entries)

    @property
    def retained_unreferenced(self) -> list[bytes]:
        """Indexed blocks no table references (refcount 1 = ours only),
        in LRU order — the eviction candidates the cap bounds."""
        return [k for k, (b, _t, _p) in self._entries.items()
                if self.alloc.refcount(b) == 1]

    @staticmethod
    def _key(parent: bytes | None, chunk: tuple) -> bytes:
        h = hashlib.blake2b(parent or b"root", digest_size=16)
        h.update(",".join(str(t) for t in chunk).encode())
        return h.digest()

    def match(self, chunks: Sequence[tuple]) -> list[int]:
        """Longest indexed chain prefix of ``chunks`` → its physical
        blocks (with one reference ADDED to each via ``share`` — the
        caller maps them into a table and frees them at retirement like
        any owned block). Matched entries are touched most-recent,
        leaf-first."""
        self.lookups += 1
        blocks: list[int] = []
        keys: list[bytes] = []
        parent: bytes | None = None
        for chunk in chunks:
            key = self._key(parent, chunk)
            ent = self._entries.get(key)
            if ent is None or ent[1] != chunk:
                break
            blocks.append(ent[0])
            keys.append(key)
            parent = key
        for key in reversed(keys):               # leaf ends most recent
            self._entries.move_to_end(key)
        if blocks:
            self.alloc.share(blocks)
            self.hit_blocks += len(blocks)
        return blocks

    def register(self, chunks: Sequence[tuple],
                 blocks: Sequence[int]) -> None:
        """Index ``blocks[i]`` as holding ``chunks[i]`` (a prefilled
        request's full own blocks, in chain order). Already-indexed
        chain nodes are skipped (the donor matched them); new entries
        take one reference each."""
        if len(chunks) != len(blocks):
            raise ValueError(
                f"{len(chunks)} chunks for {len(blocks)} blocks")
        parent: bytes | None = None
        for chunk, block in zip(chunks, blocks):
            key = self._key(parent, chunk)
            ent = self._entries.get(key)
            if ent is None:
                self.alloc.share([block])
                self._entries[key] = (block, chunk, parent)
                if parent is not None:
                    self._children.setdefault(parent, set()).add(key)
            self._entries.move_to_end(key)
            parent = key

    def _evict(self, key: bytes) -> int:
        """Drop ``key`` and every descendant entry (unreachable once
        the parent is gone), freeing the index's reference on each.
        Returns the number of entries evicted."""
        n = 0
        stack = [key]
        while stack:
            k = stack.pop()
            ent = self._entries.pop(k, None)
            if ent is None:
                continue
            block, _chunk, parent = ent
            self.alloc.free([block])
            if parent is not None and parent in self._children:
                self._children[parent].discard(k)
            stack.extend(self._children.pop(k, ()))
            n += 1
        return n

    def trim(self) -> int:
        """Enforce the LRU cap: evict least-recently-used
        retained-but-unreferenced entries (NEVER a block a live table
        still references) until at most ``capacity`` remain. Returns
        evicted entry count."""
        n = 0
        while True:
            cands = self.retained_unreferenced
            if len(cands) <= self.capacity:
                return n
            n += self._evict(cands[0])

    def reclaim(self, n: int) -> int:
        """Evict up to ``n`` retained-but-unreferenced entries NOW
        (allocation pressure: a block a new admission needs beats a
        retained prefix, whatever the cap says). Returns the number of
        entries evicted — 0 means nothing was reclaimable and the
        caller should queue."""
        freed = 0
        while freed < n:
            cands = self.retained_unreferenced
            if not cands:
                break
            freed += self._evict(cands[0])
        return freed

    def release(self) -> int:
        """Drop every entry (end of a run: the pool is being torn
        down). Returns evicted entry count."""
        n = 0
        while self._entries:
            n += self._evict(next(iter(self._entries)))
        self._children.clear()
        return n


_POOL_KEYS = ("k", "v", "k_scale", "v_scale")

_XFER_JITS: dict[str, Any] = {}


def _xfer_jits() -> dict[str, Any]:
    """Module-level jit singletons for the cross-pool transfer pair —
    built lazily (this module stays importable without paying jax) and
    cached so repeated transfers of the same block count reuse one
    compiled program."""
    if not _XFER_JITS:
        import functools

        import jax

        @jax.jit
        def export_fn(bufs, ids):
            return [b[ids] for b in bufs]

        @functools.partial(jax.jit, donate_argnums=(0,))
        def import_fn(bufs, ids, payload):
            return [b.at[ids].set(p) for b, p in zip(bufs, payload)]

        _XFER_JITS["export"] = export_fn
        _XFER_JITS["import"] = import_fn
    return _XFER_JITS


def pool_transfer_keys(pool: dict) -> list[str]:
    """The pool entries a block transfer moves: the per-layer physical
    buffers (k/v, plus int8 scale sidecars when present) — never the
    per-slot ``block_tables``/``pos``, which are the RECEIVER's own
    bookkeeping."""
    return [k for k in _POOL_KEYS if k in pool]


def export_block_rows(pool: dict, block_ids: Sequence[int]) -> dict:
    """Copy the physical content of ``block_ids`` out of ``pool``:
    ``{key: [per-layer [n, block_size, ...] arrays]}`` in block-id
    order, every transferable key in one dispatch.

    This is the prefill→decode handoff's transfer unit (ROADMAP
    direction 2 / Podracer's role split): a prefill worker exports the
    blocks its finished prompt occupies and a DIFFERENT pool imports
    them via :func:`import_block_rows` — an explicit device copy on
    CPU, and exactly the seam where an ICI/DCN block transfer slots in
    on chip (the payload is already the wire format: whole blocks, no
    row surgery). Rows past the request's position inside the last
    block ride along as unreachable garbage on both sides.
    """
    import jax.numpy as jnp

    ids = jnp.asarray(list(block_ids), jnp.int32)
    if ids.ndim != 1 or ids.shape[0] < 1:
        raise ValueError("export_block_rows needs >= 1 block id")
    keys = pool_transfer_keys(pool)
    bufs = [b for k in keys for b in pool[k]]
    outs = _xfer_jits()["export"](bufs, ids)
    n_layers = len(pool["k"])
    payload: dict[str, Any] = {}
    i = 0
    for k in keys:
        payload[k] = list(outs[i:i + n_layers])
        i += n_layers
    return payload


def transfer_crc(payload: dict) -> int:
    """crc32 over an :func:`export_block_rows` payload's wire content —
    buffers in key-sorted, layer order, so the checksum is a pure
    function of the transferred bytes on both sides of the wire.

    This is the paged transfer's integrity primitive: a cross-pool copy
    is exactly the seam where an ICI/DCN hop slots in on chip, and a
    hop can corrupt. The fleet's disaggregated prefill→decode handoff
    stamps every payload with this crc at export and re-checks it at
    the import side (``models/fleet.py``); a mismatch is a CLASSIFIED,
    retryable transfer failure (re-run the prefill), never a silent
    import of garbage rows into a decode pool."""
    import numpy as np

    crc = 0
    for k in sorted(payload):
        for buf in payload[k]:
            crc = zlib.crc32(np.asarray(buf).tobytes(), crc)
    return crc


def import_block_rows(pool: dict, block_ids: Sequence[int],
                      payload: dict) -> dict:
    """Write :func:`export_block_rows` ``payload`` into ``pool`` at
    ``block_ids`` (the receiver's own allocated blocks — transfer never
    implies the same physical ids on both sides). Returns a NEW pool
    dict; the physical buffers are DONATED (updated in place when XLA
    can), so callers must rebind their pool reference, exactly like the
    engine's wave step. Importing into a reserved block is refused
    loudly — scribbling the garbage block would corrupt every fenced
    write in flight."""
    import jax.numpy as jnp

    ids_h = [int(b) for b in block_ids]
    if any(b < 1 for b in ids_h):
        raise ValueError(
            f"cannot import into reserved block(s) {sorted(set(b for b in ids_h if b < 1))} "
            f"— block 0 is the garbage block every fenced write targets")
    keys = pool_transfer_keys(pool)
    if sorted(payload) != sorted(keys):
        raise ValueError(
            f"payload keys {sorted(payload)} do not match the pool's "
            f"transferable keys {sorted(keys)} (cache_dtype mismatch "
            f"between the exporting and importing pools?)")
    n = len(ids_h)
    for k in keys:
        for buf in payload[k]:
            if int(buf.shape[0]) != n:
                raise ValueError(
                    f"payload[{k!r}] carries {int(buf.shape[0])} blocks "
                    f"for {n} block ids")
    ids = jnp.asarray(ids_h, jnp.int32)
    bufs = [b for k in keys for b in pool[k]]
    pl = [b for k in keys for b in payload[k]]
    outs = _xfer_jits()["import"](bufs, ids, pl)
    n_layers = len(pool["k"])
    out = dict(pool)
    i = 0
    for k in keys:
        out[k] = list(outs[i:i + n_layers])
        i += n_layers
    return out


def paged_pool_spec(cfg: BurnInConfig, max_len: int, block_size: int,
                    cache_dtype: str = "bf16") -> dict[str, int]:
    """Static pool geometry shared by every constructor and the engine.

    ``rows`` is :func:`..decode.cache_rows`'s buffer length for
    ``max_len`` (int8 keeps its 256-row kernel grain), ``tables`` the
    per-slot block-table width, sized so the gathered logical cache
    spans at least ``rows`` — every position a request can legally
    occupy has a table entry, and the logical width stays static.
    """
    if block_size < 1:
        raise ValueError(f"block_size must be >= 1, got {block_size}")
    rows = cache_rows(max_len, cache_dtype)
    tables = blocks_for_rows(rows, block_size)
    return {"rows": rows, "tables": tables, "block_size": block_size,
            "logical_rows": tables * block_size}


def init_paged_cache(cfg: BurnInConfig, slots: int, max_len: int, *,
                     block_size: int, num_blocks: int,
                     rules=None, cache_dtype: str = "bf16") -> dict[str, Any]:
    """Zeroed paged pool + per-slot tables and positions.

    Layout (per layer): ``k``/``v`` ``[num_blocks, block_size, kv, D]``;
    int8 caches add ``k_scale``/``v_scale`` ``[num_blocks, block_size,
    kv]`` sidecars. ``block_tables`` is ``[slots, tables]`` int32 —
    all-zero at init, i.e. every slot points at the garbage block until
    its first admission — and ``pos`` ``[slots]`` int32.

    With ``rules`` the KV-head axis shards over ``tp`` when it divides;
    the block axis replicates (blocks are assigned dynamically, so a
    block-sharded pool would turn every gather into a cross-shard
    shuffle). The paged pool's HBM story is the block COUNT — sized to
    live rows, not ``slots × max_len`` — so replication across the data
    groups still undercuts the dense pool whenever occupancy is ragged.
    """
    import jax
    import jax.numpy as jnp

    if cache_dtype not in ("bf16", "int8"):
        raise ValueError(
            f"unknown cache_dtype {cache_dtype!r}: use bf16|int8")
    spec = paged_pool_spec(cfg, max_len, block_size, cache_dtype)
    quant = cache_dtype == "int8"
    s4 = s3 = None
    if rules is not None:
        from jax.sharding import PartitionSpec as P

        tp = rules.mesh.shape.get("tp", 1)
        head_axis = "tp" if cfg.kv_heads % tp == 0 else None
        # the BLOCK axis replicates (blocks are assigned dynamically);
        # only the KV-head axis shards, matching init_cache's layout
        s4 = rules.shard(P(None, None, head_axis, None))
        s3 = rules.shard(P(None, None, head_axis))

    def zeros(shape, dtype, sharding):
        if sharding is None:
            return jnp.zeros(shape, dtype)
        # materialise DIRECTLY into the sharded layout (one transient
        # replicated pool on one device is the OOM the sharding avoids)
        return jax.jit(lambda: jnp.zeros(shape, dtype),
                       out_shardings=sharding)()

    kv_shape = (num_blocks, block_size, cfg.kv_heads, cfg.head_dim)
    buf_dtype = jnp.int8 if quant else cfg.dtype
    pool: dict[str, Any] = {
        "k": [zeros(kv_shape, buf_dtype, s4) for _ in range(cfg.n_layers)],
        "v": [zeros(kv_shape, buf_dtype, s4) for _ in range(cfg.n_layers)],
        "block_tables": jnp.zeros((slots, spec["tables"]), jnp.int32),
        "pos": jnp.zeros((slots,), jnp.int32),
    }
    if quant:
        pool["k_scale"] = [zeros(kv_shape[:3], jnp.float32, s3)
                           for _ in range(cfg.n_layers)]
        pool["v_scale"] = [zeros(kv_shape[:3], jnp.float32, s3)
                           for _ in range(cfg.n_layers)]
    return pool
