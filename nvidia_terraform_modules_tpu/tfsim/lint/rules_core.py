# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""Core rules: the ``tfsim validate`` checks, bridged into the engine.

``validate_module`` predates the lint layer and keeps its own API (it is
the offline ``terraform validate``, run by the validate verb and a dozen
tests). Registering each of its finding families as a first-class rule
makes ``tfsim lint`` a strict superset of ``tfsim validate`` — same
diagnostics, now severity-overridable and suppressible like any other
rule. ``validate.py`` stamps every finding with one of these ids.
"""

from __future__ import annotations

from .engine import LintContext, rule

_CORE = [
    ("core-ref", "error",
     "reference to an undeclared variable/local/resource/data/module"),
    ("core-schema", "error",
     "argument or block the provider schema does not define (or a "
     "missing required one)"),
    ("core-provider", "error",
     "resource's provider has no required_providers entry"),
    ("core-exclusive", "error",
     "count and for_each set on the same resource"),
    ("core-source", "error",
     "module call without source / output without value"),
    ("core-style", "warning",
     "variable or output missing description/type (terraform-docs gate)"),
    ("core-pins", "warning",
     "module declares no required_providers / required_version"),
]


def _make(rule_id: str):
    def check(ctx: LintContext):
        for f in ctx.validate_findings():
            if f.rule == rule_id:
                yield f
    return check


for _id, _sev, _summary in _CORE:
    rule(_id, severity=_sev, family="core", summary=_summary)(_make(_id))


@rule("core-load", severity="error", family="core",
      summary="source file that does not parse (the lint CLI also stamps "
              "whole-module load failures with this id)")
def check_load_errors(ctx: LintContext):
    ctx.tfvars_bodies()  # populate tfvars_errors
    yield from ctx.tfvars_errors


@rule("core-unbridged", severity="error", family="core",
      summary="validate finding with no dedicated core rule (safety net)")
def check_unbridged(ctx: LintContext):
    """The superset guarantee, enforced: if validate ever stamps a rule
    id the table above doesn't list (or none at all), the finding must
    still surface through lint — silently dropping it would let a CI
    gate on ``tfsim lint`` pass a config ``tfsim validate`` rejects.
    Findings keep their original severity and id; only unstamped ones
    get this rule's."""
    known = {i for i, _, _ in _CORE}
    for f in ctx.validate_findings():
        if f.rule not in known:
            yield f
