# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""State lifecycle: apply → checkpoint → re-plan → diff (SURVEY §5).

The reference's checkpoint/resume story is "terraform state is the
checkpoint; apply is idempotent" — untestable there without a cloud. Here the
whole lifecycle runs offline: idempotent re-plan, surgical diffs on variable
changes, and JSON round-trip of the state artifact.
"""

import os

from nvidia_terraform_modules_tpu.tfsim import (
    State,
    apply_plan,
    diff,
    simulate_plan,
)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BASE = {"project_id": "proj-x", "cluster_name": "demo"}


def _plan(extra=None):
    return simulate_plan(os.path.join(ROOT, "gke-tpu"), {**BASE, **(extra or {})})


def test_apply_then_replan_is_noop():
    """The resume guarantee: unchanged config plans to zero actions."""
    plan = _plan()
    state = apply_plan(plan)
    assert state.serial == 1
    d = diff(_plan(), state)
    assert d.is_noop, d.actions
    assert d.summary() == "Plan: 0 to add, 0 to change, 0 to destroy."
    # applying a no-op must not bump the checkpoint serial
    assert apply_plan(_plan(), state).serial == 1


def test_added_slice_plans_exactly_one_create():
    state = apply_plan(_plan())
    d = diff(_plan({"tpu_slices": {"default": {}, "big": {"topology": "4x4"}}}),
             state)
    creates = d.by_action("create")
    assert 'google_container_node_pool.tpu_slice["big"]' in creates
    assert d.by_action("delete") == []
    # pre-existing resources untouched
    assert d.actions['google_container_cluster.this'] == "no-op"


def test_removed_slice_plans_delete():
    state = apply_plan(_plan({"tpu_slices": {"default": {}, "big": {"topology": "4x4"}}}))
    d = diff(_plan(), state)
    deletes = d.by_action("delete")
    assert 'google_container_node_pool.tpu_slice["big"]' in deletes
    assert 'google_container_node_pool.tpu_slice["default"]' not in deletes


def test_changed_machine_type_plans_update_with_key():
    state = apply_plan(_plan())
    d = diff(_plan({"cpu_pool": {"machine_type": "n2-standard-16"}}), state)
    addr = "google_container_node_pool.cpu"
    assert d.actions[addr] == "update"
    assert "node_config" in d.changed_keys[addr]
    # the cluster itself must not churn on a pool-only change
    assert d.actions["google_container_cluster.this"] == "no-op"


def test_computed_attrs_never_drive_updates():
    plan = _plan()
    state = apply_plan(plan)
    # every instance has id = <computed>; a second diff must not call that a
    # change (provider-owned attributes are not config drift)
    d = diff(plan, state)
    assert d.is_noop


def test_state_json_roundtrip(tmp_path):
    state = apply_plan(_plan())
    path = tmp_path / "terraform.tfstate"
    path.write_text(state.to_json())
    restored = State.from_json(path.read_text())
    assert restored.serial == state.serial
    assert restored.resources == state.resources
    assert diff(_plan(), restored).is_noop


def test_removed_config_attribute_surfaces_as_update(tmp_path):
    """Dropping a block from config must plan an update, not a no-op."""
    import textwrap

    def write(body):
        (tmp_path / "main.tf").write_text(textwrap.dedent(body))
        return simulate_plan(str(tmp_path), {})

    plan = write("""
        resource "google_container_node_pool" "p" {
          name = "x"
          placement_policy {
            type = "COMPACT"
          }
        }
    """)
    state = apply_plan(plan)
    plan2 = write("""
        resource "google_container_node_pool" "p" {
          name = "x"
        }
    """)
    d = diff(plan2, state)
    assert d.actions["google_container_node_pool.p"] == "update"
    assert d.changed_keys["google_container_node_pool.p"] == ["placement_policy"]


def test_data_sources_are_not_plan_actions(tmp_path):
    import textwrap
    (tmp_path / "main.tf").write_text(textwrap.dedent("""
        data "google_project" "p" {}

        resource "google_compute_network" "n" {
          name = "x"
        }
    """))
    plan = simulate_plan(str(tmp_path), {})
    d = diff(plan, None)
    assert "data.google_project.p" not in d.actions
    assert d.summary() == "Plan: 1 to add, 0 to change, 0 to destroy."
    state = apply_plan(plan)
    assert "data.google_project.p" not in state.resources


def test_child_module_data_sources_not_tracked(tmp_path):
    import textwrap
    child = tmp_path / "child"
    child.mkdir()
    (child / "main.tf").write_text(textwrap.dedent("""
        data "google_project" "p" {}

        resource "google_compute_network" "n" {
          name = "x"
        }
    """))
    (tmp_path / "main.tf").write_text(
        'module "c" {\n  source = "./child"\n}\n')
    plan = simulate_plan(str(tmp_path), {})
    d = diff(plan, None)
    assert "module.c.data.google_project.p" not in d.actions
    assert d.summary() == "Plan: 1 to add, 0 to change, 0 to destroy."


def test_nested_computed_key_removal_is_noop(tmp_path):
    """The provider-owned rule must hold at any nesting depth."""
    import textwrap

    def write(labels_line):
        (tmp_path / "main.tf").write_text(textwrap.dedent(f"""
            resource "google_container_cluster" "c" {{
              name = "x"
            }}

            resource "google_compute_network" "n" {{
              name = "y"
              labels = {{
                {labels_line}
              }}
            }}
        """))
        return simulate_plan(str(tmp_path), {})

    state = apply_plan(write('owner = google_container_cluster.c.id'))
    d = diff(write(""), state)
    # the removed nested key's stored value was <computed> → not config drift
    assert d.actions["google_compute_network.n"] == "no-op", d.changed_keys


def test_moved_block_renames_state_without_churn(tmp_path):
    """terraform 1.1 refactoring: a rename plans no-op, not destroy+create."""
    import textwrap

    from nvidia_terraform_modules_tpu.tfsim import load_module, migrate_state

    def write(body):
        (tmp_path / "main.tf").write_text(textwrap.dedent(body))
        return str(tmp_path)

    path = write("""
        resource "google_compute_network" "old" {
          count = 2
          name  = "net-${count.index}"
        }
    """)
    state = apply_plan(simulate_plan(path, {}))
    assert "google_compute_network.old[1]" in state.resources

    path = write("""
        resource "google_compute_network" "new" {
          count = 2
          name  = "net-${count.index}"
        }

        moved {
          from = google_compute_network.old
          to   = google_compute_network.new
        }
    """)
    migrated, renames = migrate_state(state, load_module(path))
    assert ("google_compute_network.old[0]",
            "google_compute_network.new[0]") in renames
    d = diff(simulate_plan(path, {}), migrated)
    assert d.is_noop, d.actions
    # and with no moved blocks the same refactor would churn
    d_raw = diff(simulate_plan(path, {}), state)
    assert d_raw.by_action("create") and d_raw.by_action("delete")


def test_moved_single_instance_and_module(tmp_path):
    import textwrap

    from nvidia_terraform_modules_tpu.tfsim import load_module, migrate_state

    state = State(resources={
        "google_compute_network.a[0]": {"name": "n0"},
        "google_compute_network.a[1]": {"name": "n1"},
        "module.a.google_compute_network.n": {"name": "child"},
        "module.ab.google_compute_network.n": {"name": "other"},
    }, serial=1)
    (tmp_path / "main.tf").write_text(textwrap.dedent("""
        moved {
          from = google_compute_network.a[1]
          to   = google_compute_network.b
        }

        moved {
          from = module.a
          to   = module.z
        }
    """))
    migrated, renames = migrate_state(state, load_module(str(tmp_path)))
    assert ("google_compute_network.a[1]", "google_compute_network.b") in renames
    assert ("module.a.google_compute_network.n",
            "module.z.google_compute_network.n") in renames
    # name-prefix sibling untouched; unmoved instance untouched
    assert "module.ab.google_compute_network.n" in migrated.resources
    assert "google_compute_network.a[0]" in migrated.resources


def test_moved_collision_raises(tmp_path):
    import textwrap

    import pytest

    from nvidia_terraform_modules_tpu.tfsim import load_module, migrate_state

    state = State(resources={
        "google_compute_network.a": {"name": "x"},
        "google_compute_network.b": {"name": "y"},
    }, serial=1)
    (tmp_path / "main.tf").write_text(textwrap.dedent("""
        moved {
          from = google_compute_network.a
          to   = google_compute_network.b
        }
    """))
    with pytest.raises(ValueError, match="already exists"):
        migrate_state(state, load_module(str(tmp_path)))


def test_check_block_failures_surface_as_warnings(tmp_path):
    import textwrap
    (tmp_path / "main.tf").write_text(textwrap.dedent("""
        variable "n" {
          type    = number
          default = 3
        }

        resource "google_compute_network" "net" {
          name = "x"
        }

        check "capacity" {
          assert {
            condition     = var.n <= 2
            error_message = "n must stay within quota"
          }
        }
    """))
    plan = simulate_plan(str(tmp_path), {})
    assert plan.check_failures == ["check 'capacity': n must stay within quota"]
    ok_plan = simulate_plan(str(tmp_path), {"n": 1})
    assert ok_plan.check_failures == []


def test_incremental_apply_converges():
    state = apply_plan(_plan())
    plan2 = _plan({"tpu_slices": {"default": {}, "b": {"topology": "2x2x4",
                                                       "version": "v4"}}})
    state2 = apply_plan(plan2, state)
    assert state2.serial == 2
    assert diff(plan2, state2).is_noop
    # and rolling back reconverges too
    state3 = apply_plan(_plan(), state2)
    assert 'google_container_node_pool.tpu_slice["b"]' not in state3.resources
    assert diff(_plan(), state3).is_noop


# ---------------------------------------------------- state surgery (rm/mv)

def test_state_rm_whole_resource_and_replan_recreates():
    """``state rm`` forgets but doesn't destroy: the orphaned resource
    re-plans as a create (terraform's documented semantics)."""
    from nvidia_terraform_modules_tpu.tfsim import state_rm

    state = apply_plan(_plan())
    new, removed = state_rm(state, ["google_container_node_pool.tpu_slice"])
    assert removed == ['google_container_node_pool.tpu_slice["default"]']
    assert new.serial == state.serial + 1
    d = diff(_plan(), new)
    assert d.actions['google_container_node_pool.tpu_slice["default"]'] == \
        "create"


def test_state_rm_unknown_address_raises():
    import pytest

    from nvidia_terraform_modules_tpu.tfsim import state_rm

    with pytest.raises(ValueError, match="no resource in state"):
        state_rm(apply_plan(_plan()), ["google_compute_network.nope"])


def test_state_rm_runbook_parity():
    """The reference's GKE teardown runbook (gke/README.md:59): state rm the
    operator namespace, then destroy proceeds without touching it."""
    from nvidia_terraform_modules_tpu.tfsim import state_rm

    plan = simulate_plan(os.path.join(ROOT, "gke"),
                         {"project_id": "p", "cluster_name": "c"})
    state = apply_plan(plan)
    ns = "kubernetes_namespace_v1.gpu_operator[0]"
    assert ns in state.resources
    new, removed = state_rm(state, ["kubernetes_namespace_v1.gpu_operator"])
    assert removed == [ns]
    assert ns not in new.resources
    # remaining teardown surface no longer includes the namespace
    assert all(not a.startswith("kubernetes_namespace_v1.")
               for a in new.resources)


def test_state_mv_is_imperative_moved_block():
    from nvidia_terraform_modules_tpu.tfsim import state_mv

    state = apply_plan(_plan())
    new, renames = state_mv(
        state, 'google_container_node_pool.tpu_slice["default"]',
        'google_container_node_pool.tpu_slice["primary"]')
    assert renames == [('google_container_node_pool.tpu_slice["default"]',
                        'google_container_node_pool.tpu_slice["primary"]')]
    assert 'google_container_node_pool.tpu_slice["primary"]' in new.resources


def test_state_mv_target_exists_raises():
    import pytest

    from nvidia_terraform_modules_tpu.tfsim import state_mv

    state = apply_plan(_plan())
    with pytest.raises(ValueError, match="already exists"):
        state_mv(state, "google_container_cluster.this",
                 "google_container_cluster.this")


def test_outputs_recorded_in_state_with_sensitivity():
    state = apply_plan(_plan())
    assert state.outputs["cluster_name"] == {
        "value": "demo", "sensitive": False}
    assert state.outputs["cluster_ca_certificate"]["sensitive"] is True
    # round-trips through the statefile JSON
    again = State.from_json(state.to_json())
    assert again.outputs == state.outputs
    # pre-outputs statefiles (older serial format) still load
    legacy = State.from_json(
        '{"serial": 3, "resources": {}}')
    assert legacy.outputs == {}


# ------------------------------------------------------------ -target/import

def test_target_scopes_plan_to_dependency_closure():
    """-target on the smoketest Job pulls in its dependency closure (pool,
    cluster, namespace, configmap, service...) but nothing else."""
    from nvidia_terraform_modules_tpu.tfsim import select_targets

    plan = _plan()
    kept = select_targets(plan, ["kubernetes_job_v1.tpu_smoketest"])
    assert 'kubernetes_job_v1.tpu_smoketest["default"]' in kept
    assert 'google_container_node_pool.tpu_slice["default"]' in kept
    assert "google_container_cluster.this" in kept
    # the runtime helm release is NOT a dependency of the Job
    assert not any(a.startswith("helm_release.") for a in kept)


def test_target_unknown_raises():
    import pytest

    from nvidia_terraform_modules_tpu.tfsim import PlanError, select_targets

    with pytest.raises(PlanError, match="matches no resource"):
        select_targets(_plan(), ["google_compute_network.nope"])


def test_targeted_diff_and_apply_leave_rest_untouched():
    plan = _plan()
    d = diff(plan, None, targets=["google_compute_network.vpc"])
    assert set(d.actions) == {"google_compute_network.vpc[0]"}
    state = apply_plan(plan, None, targets=["google_compute_network.vpc"])
    assert set(state.resources) == {"google_compute_network.vpc[0]"}
    # untargeted deletes are skipped: full apply then targeted apply of a
    # config without the slice must NOT delete the slice pool
    full = apply_plan(_plan())
    d2 = diff(_plan(), full, targets=["google_compute_network.vpc"])
    assert d2.is_noop
    partial = apply_plan(_plan(), full, targets=["google_compute_network.vpc"])
    assert 'google_container_node_pool.tpu_slice["default"]' in \
        partial.resources


def test_targeted_instance_keeps_only_that_instance():
    from nvidia_terraform_modules_tpu.tfsim import select_targets

    plan = _plan({"tpu_slices": {"default": {}, "b": {"topology": "2x2",
                                                      "version": "v5e"}}})
    kept = select_targets(
        plan, ['google_container_node_pool.tpu_slice["b"]'])
    assert 'google_container_node_pool.tpu_slice["b"]' in kept
    assert 'google_container_node_pool.tpu_slice["default"]' not in kept
    assert "google_container_cluster.this" in kept  # dependency, whole node


def test_import_adopts_and_replans_noop():
    from nvidia_terraform_modules_tpu.tfsim import import_resource

    plan = _plan()
    state = import_resource(None, plan, "google_compute_network.vpc[0]",
                            "projects/p/global/networks/demo-net")
    assert state.resources["google_compute_network.vpc[0]"]["id"] == \
        "projects/p/global/networks/demo-net"
    d = diff(plan, state)
    assert d.actions["google_compute_network.vpc[0]"] == "no-op"


def test_import_errors():
    import pytest

    from nvidia_terraform_modules_tpu.tfsim import import_resource

    plan = _plan()
    state = apply_plan(plan)
    with pytest.raises(ValueError, match="already managed"):
        import_resource(state, plan, "google_compute_network.vpc[0]", "x")
    with pytest.raises(ValueError, match="no configuration block"):
        import_resource(None, plan, "google_compute_network.other", "x")


def test_targeted_delete_of_removed_instance():
    """A targeted resource whose instance left the config still diffs as
    a delete — but ONLY when targeted."""
    full = apply_plan(_plan({"tpu_slices": {"default": {}, "b": {
        "topology": "2x2", "version": "v5e"}}}))
    shrunk = _plan()   # "b" removed from config
    d = diff(shrunk, full,
             targets=["google_container_node_pool.tpu_slice"])
    assert d.actions['google_container_node_pool.tpu_slice["b"]'] == "delete"
    # untargeted plan of an unrelated resource must not touch "b"
    d2 = diff(shrunk, full, targets=["google_compute_network.vpc"])
    assert 'google_container_node_pool.tpu_slice["b"]' not in d2.actions


def test_target_module_inner_resource_selects_only_that_subtree(tmp_path):
    """-target module.m.res.name must NOT expand to the whole module."""
    import textwrap

    from nvidia_terraform_modules_tpu.tfsim import select_targets

    (tmp_path / "child").mkdir()
    (tmp_path / "child" / "main.tf").write_text(textwrap.dedent("""
        resource "google_compute_network" "vpc" {
          name = "n"
        }

        resource "google_compute_firewall" "fw" {
          name = "f"
        }
    """))
    (tmp_path / "main.tf").write_text(
        'module "net" {\n  source = "./child"\n}\n')
    plan = simulate_plan(str(tmp_path), {})
    kept = select_targets(plan, ["module.net.google_compute_network.vpc"])
    assert "module.net.google_compute_network.vpc" in kept
    assert "module.net.google_compute_firewall.fw" not in kept
    # whole-module target still takes everything
    kept = select_targets(plan, ["module.net"])
    assert "module.net.google_compute_firewall.fw" in kept


def test_targeted_destroy_of_fully_removed_resource(tmp_path):
    """Removing a whole resource block then -targeting it plans its
    destroy (terraform's targeted-destroy workflow), not an error."""
    import textwrap

    (tmp_path / "main.tf").write_text(textwrap.dedent("""
        resource "google_compute_network" "a" {
          name = "a"
        }

        resource "google_compute_firewall" "b" {
          name = "b"
        }
    """))
    prior = apply_plan(simulate_plan(str(tmp_path), {}))
    (tmp_path / "main.tf").write_text(
        'resource "google_compute_network" "a" {\n  name = "a"\n}\n')
    shrunk = simulate_plan(str(tmp_path), {})
    d = diff(shrunk, prior, targets=["google_compute_firewall.b"])
    assert d.actions == {"google_compute_firewall.b": "delete"}
    # a target matching neither config nor state still errors
    import pytest

    from nvidia_terraform_modules_tpu.tfsim import PlanError
    with pytest.raises(PlanError, match="configuration or state"):
        diff(shrunk, prior, targets=["google_compute_firewall.nope"])


def test_import_rejects_data_source_and_names_instances():
    import pytest

    from nvidia_terraform_modules_tpu.tfsim import import_resource

    plan = _plan()
    with pytest.raises(ValueError, match="data source"):
        import_resource(None, plan, "data.google_client_config.current", "x")
    with pytest.raises(ValueError, match=r"vpc\[0\]"):
        import_resource(None, plan, "google_compute_network.vpc", "x")


def test_target_typod_instance_key_errors():
    import pytest

    from nvidia_terraform_modules_tpu.tfsim import PlanError, select_targets

    with pytest.raises(PlanError, match="matches no resource instance"):
        select_targets(_plan(),
                       ['google_container_node_pool.tpu_slice["typo"]'])


def test_target_module_inner_includes_in_module_deps(tmp_path):
    """module.m.res target pulls res's dependencies INSIDE the module."""
    import textwrap

    from nvidia_terraform_modules_tpu.tfsim import select_targets

    (tmp_path / "child").mkdir()
    (tmp_path / "child" / "main.tf").write_text(textwrap.dedent("""
        resource "google_compute_network" "net" {
          name = "n"
        }

        resource "google_compute_subnetwork" "sub" {
          network = google_compute_network.net.id
        }

        resource "google_compute_firewall" "unrelated" {
          name = "f"
        }
    """))
    (tmp_path / "main.tf").write_text(
        'module "m" {\n  source = "./child"\n}\n')
    plan = simulate_plan(str(tmp_path), {})
    kept = select_targets(plan, ["module.m.google_compute_subnetwork.sub"])
    assert "module.m.google_compute_subnetwork.sub" in kept
    assert "module.m.google_compute_network.net" in kept   # in-module dep
    assert "module.m.google_compute_firewall.unrelated" not in kept


def test_target_counted_module_instance_includes_in_module_deps(tmp_path):
    import textwrap

    from nvidia_terraform_modules_tpu.tfsim import select_targets

    (tmp_path / "child").mkdir()
    (tmp_path / "child" / "main.tf").write_text(textwrap.dedent("""
        resource "google_compute_network" "net" {
          name = "n"
        }

        resource "google_compute_subnetwork" "sub" {
          network = google_compute_network.net.id
        }
    """))
    (tmp_path / "main.tf").write_text(
        'module "m" {\n  source = "./child"\n  count = 1\n}\n')
    plan = simulate_plan(str(tmp_path), {})
    kept = select_targets(
        plan, ["module.m[0].google_compute_subnetwork.sub"])
    assert "module.m[0].google_compute_subnetwork.sub" in kept
    assert "module.m[0].google_compute_network.net" in kept


def test_target_count_zero_resource_is_legal():
    """Targeting a conditional resource with the flag off selects nothing
    (terraform accepts it); the vpc is count-gated via network.create."""
    from nvidia_terraform_modules_tpu.tfsim import select_targets

    plan = _plan({"network": {"create": False,
                              "existing_network": "shared",
                              "existing_subnetwork": "shared-sub"}})
    kept = select_targets(plan, ["google_compute_network.vpc"])
    assert kept == set()


def test_targeted_apply_keeps_prior_outputs():
    """Outputs evaluated from the full plan may reflect unapplied,
    untargeted changes — a targeted apply must not record them."""
    full = apply_plan(_plan())
    assert full.outputs["cluster_name"]["value"] == "demo"
    renamed = simulate_plan(
        os.path.join(ROOT, "gke-tpu"),
        {"project_id": "proj-x", "cluster_name": "other"})
    partial = apply_plan(renamed, full,
                         targets=["google_compute_network.vpc"])
    assert partial.outputs["cluster_name"]["value"] == "demo"


def test_target_indexless_resource_in_counted_module(tmp_path):
    """module.m.res on a counted module targets res in EVERY instance
    (terraform's all-instances form) — never silently nothing."""
    import textwrap

    from nvidia_terraform_modules_tpu.tfsim import select_targets

    (tmp_path / "child").mkdir()
    (tmp_path / "child" / "main.tf").write_text(textwrap.dedent("""
        resource "google_compute_network" "net" {
          name = "n"
        }

        resource "google_compute_firewall" "other" {
          name = "f"
        }
    """))
    (tmp_path / "main.tf").write_text(
        'module "m" {\n  source = "./child"\n  count = 2\n}\n')
    plan = simulate_plan(str(tmp_path), {})
    kept = select_targets(plan, ["module.m.google_compute_network.net"])
    assert "module.m[0].google_compute_network.net" in kept
    assert "module.m[1].google_compute_network.net" in kept
    assert not any("firewall" in a for a in kept)
