# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""The lint rule engine: registry, severity overrides, suppressions.

``tfsim validate`` reproduces the floor the reference enforces
(``terraform validate`` + conventions); the lint layer is everything
*above* that floor — the pre-flight analyses that catch a misconfigured
TPU slice before a multi-hour apply burns quota.

The MACHINERY — :class:`Finding`, the rule registry, severity
overrides, suppression comments, exit codes, the JSON/SARIF surfaces —
is language-agnostic and lives in :mod:`nvidia_terraform_modules_tpu.
analysis.core`, shared with the Python-side ``graftlint`` pack; this
module binds it to HCL (same public API as before the factor-out, byte
for byte) and owns what IS HCL-specific:

* :class:`LintContext` — the per-run module view rules consume: the
  parsed module, raw file texts, tfvars bodies, loaded local child
  modules, and the cached ``validate_module`` findings;
* the ``# tfsim:ignore rule-id[,rule-id]`` suppression marker;
* :func:`run_lint` — load, run every enabled rule, filter, sort.

The analyses live in the ``rules_*`` modules. Severities order
``error > warning > info``; the CLI exit code is 2 with any error, 1
with only warnings, 0 otherwise (info never fails a build).
"""

from __future__ import annotations

import os
import re
from typing import Optional

from ...analysis.core import (  # noqa: F401  (re-exported shared API)
    SEVERITIES,
    Finding,
    Registry,
    Rule,
    exit_code,
    ignore_ids,
    scan_suppressions,
)
from ..module import Module, load_module
from ..parser import parse_hcl

_REGISTRY = Registry(
    "tfsim-lint",
    catalog_hint="(see `tfsim lint -rules` for the catalog)")

# the module-level dict rules_* and tests address directly — THE registry
# storage, not a copy (the shared Registry mutates this very mapping)
RULES: dict[str, Rule] = _REGISTRY.rules


def rule(id: str, *, severity: str, family: str, summary: str):
    """Register a rule. The check yields ``(where, message)`` pairs —
    stamped with the rule's severity — or full :class:`Finding`s when a
    single rule emits mixed severities (the validate bridge)."""
    return _REGISTRY.rule(id, severity=severity, family=family,
                          summary=summary)


@_REGISTRY.loader
def _ensure_rules_loaded() -> None:
    """Import the rule modules exactly once (lazy: ``validate`` imports
    this module for :class:`Finding`, and the core rules import validate
    back — eager loading would be a cycle)."""
    from . import rules_core, rules_deadcode, rules_deprecation, rules_tpu  # noqa: F401


# --------------------------------------------------------------- context

class LintContext:
    """Everything a rule may need, computed once per run.

    Rules are read-only consumers: the module object, raw file texts
    (suppression comments, tfvars), parsed tfvars bodies, loaded local
    child modules, and the cached ``validate_module`` findings.
    """

    def __init__(self, path: str, mod: Optional[Module] = None):
        self.path = path
        self.mod = mod if mod is not None else load_module(path)
        self._texts: dict[str, str] = {}
        self._tfvars: Optional[list] = None
        self.tfvars_errors: list[Finding] = []
        self._children: Optional[dict] = None
        self._validate: Optional[list] = None
        self._requirements: Optional[dict] = None

    # ---- raw sources ------------------------------------------------
    def lintable_files(self) -> list[str]:
        """Bare filenames lint looks at: every parsed ``.tf`` file plus
        tfvars variants and the dependency lockfile."""
        names = list(self.mod.files)
        for f in sorted(os.listdir(self.path)):
            if f.endswith((".tfvars", ".tfvars.example", ".auto.tfvars")) \
                    or f == ".terraform.lock.hcl":
                if os.path.isfile(os.path.join(self.path, f)):
                    names.append(f)
        return names

    def text(self, fname: str) -> str:
        if fname not in self._texts:
            with open(os.path.join(self.path, fname)) as fh:
                self._texts[fname] = fh.read()
        return self._texts[fname]

    def tfvars_bodies(self):
        """``(fname, Body)`` for each variable-definitions file. The
        ``.example`` file ships in-repo as documentation — drifted keys
        there mislead every operator who copies it, so it is linted.

        A file that does not parse is contained, not fatal: it lands in
        :attr:`tfvars_errors` (surfaced by the ``core-load`` rule) and the
        other rules keep their findings — a broken docs-only ``.example``
        must never suppress a real TPU misconfiguration."""
        if self._tfvars is None:
            self._tfvars = []
            for f in self.lintable_files():
                if f.endswith((".tfvars", ".tfvars.example")):
                    try:
                        self._tfvars.append(
                            (f, parse_hcl(self.text(f), filename=f)))
                    except SyntaxError as ex:
                        # HclParseError/HclLexError subclass SyntaxError;
                        # their message already leads with "file:line: "
                        m = re.match(r"^(.+?:\d+):\s*(.*)$", str(ex),
                                     re.DOTALL)
                        where, msg = (m.group(1), m.group(2)) if m \
                            else (f"{f}:0", str(ex))
                        self.tfvars_errors.append(
                            Finding("error", where, msg, rule="core-load"))
        return self._tfvars

    # ---- cross-module -----------------------------------------------
    def child_modules(self) -> dict[str, Optional[Module]]:
        """call name → loaded child Module for local-path module calls
        (None when the child fails to load — validate owns that error)."""
        if self._children is None:
            from ..lockfile import local_module_calls

            self._children = {}
            for name, d in local_module_calls(self.mod):
                try:
                    self._children[name] = load_module(d)
                except (SyntaxError, ValueError, OSError):
                    # SyntaxError covers HclParseError/HclLexError: a child
                    # that does not even parse degrades to None like any
                    # other unloadable child
                    self._children[name] = None
        return self._children

    def requirements(self) -> dict:
        """provider source → constraints over the whole local module tree
        (``gather_requirements`` BFS-loads every child from disk — shared
        here so rules don't each re-walk the tree)."""
        if self._requirements is None:
            from ..lockfile import gather_requirements

            self._requirements = gather_requirements(self.path)
        return self._requirements

    # ---- validate bridge --------------------------------------------
    def validate_findings(self) -> list[Finding]:
        if self._validate is None:
            from ..validate import validate_module

            self._validate = validate_module(self.mod)
        return self._validate

    # ---- literal resolution -----------------------------------------
    def resolve_literal(self, expr):
        """Best-effort static value of an expression: literals, and
        ``var.x`` traversals whose variable has a literal default (the
        cross-file hop that lets TPU rules see through
        ``topology = var.slice_topology``). Returns None when unknown."""
        from .. import ast as A

        if isinstance(expr, A.Literal):
            return expr.value
        if isinstance(expr, A.Template) and len(expr.parts) == 1 and \
                isinstance(expr.parts[0], str):
            return expr.parts[0]
        if isinstance(expr, A.Traversal) and expr.root == "var" and \
                len(expr.ops) == 1 and expr.ops[0][0] == "attr":
            v = self.mod.variables.get(expr.ops[0][1])
            if v is not None and isinstance(v.default, A.Literal):
                return v.default.value
        return None


# ----------------------------------------------------------- suppression

_IGNORE_RE = re.compile(r"#\s*tfsim:ignore[:]?\s+([A-Za-z0-9_*,\- ]+)")


def _ignore_ids(tail: str) -> set:
    """The suppressed rule ids in an ignore comment's tail (shared
    semantics: the id list ends at the first non-rule-id token, so free
    prose after the list never suppresses extra rules)."""
    return ignore_ids(tail, RULES)


def collect_suppressions(ctx: LintContext) -> dict[tuple[str, int], set]:
    """(fname, line) → rule-ids suppressed there (shared semantics: a
    trailing comment covers its own line, a standalone comment line the
    next line, ``*`` everything at that location)."""

    def files():
        for fname in ctx.lintable_files():
            try:
                yield fname, ctx.text(fname)
            except OSError:
                continue

    return scan_suppressions(files(), _IGNORE_RE, RULES)


# ------------------------------------------------------------------ run

def list_rules() -> list[Rule]:
    return _REGISTRY.list()


def run_lint(path: str, mod: Optional[Module] = None,
             overrides: Optional[dict[str, str]] = None) -> list[Finding]:
    """Run every enabled rule over the module at ``path``.

    ``overrides`` maps rule id → severity (or ``"off"`` to disable).
    Returns findings sorted by (file, line, rule), suppressions applied.
    """
    overrides = overrides or {}
    # overrides are validated before the module loads: a bad -severity
    # flag is the same diagnostic with or without a loadable module
    _REGISTRY.check_overrides(overrides)
    ctx = LintContext(path, mod)
    return _REGISTRY.run(ctx, overrides, collect_suppressions(ctx))
