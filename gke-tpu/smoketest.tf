# The JAX psum smoke-test Job: `terraform apply` is the integration test.
#
# North star (BASELINE.json): after apply, a Job runs jax.devices() and a
# psum all-reduce over the whole slice, and the apply only succeeds if it
# passes (wait_for_completion). This replaces the reference's manual
# runbook validation ("wait ~5 min, kubectl get pods" —
# /root/reference/gke/README.md:50) with an automated gate, and replaces its
# plan-time node gate (/root/reference/eks/main.tf:186, a two-phase-apply
# wart) with real apply-time readiness.
#
# Multi-host choreography (no reference precedent): an Indexed Job with
# completions = hosts-per-slice, one pod per TPU host; a headless Service
# gives pod 0 a stable DNS name that every pod uses as the
# jax.distributed.initialize coordinator; the TPU node selectors pin pods to
# the target slice and `google.com/tpu` requests claim every chip on each
# host. The pod payload is the single-file bundle of this repo's
# nvidia_terraform_modules_tpu.smoketest (scripts/tpu_smoketest.py), shipped
# via ConfigMap so any JAX-capable image works unmodified.

locals {
  smoketest_enabled = local.tpu_enabled && var.smoketest.enabled
  smoke_slice       = local.smoketest_enabled ? local.tpu_slice[var.smoketest.target_slice] : null
  smoke_ns          = local.smoketest_enabled ? kubernetes_namespace_v1.tpu_runtime[0].metadata[0].name : var.tpu_runtime.namespace
  smoke_name        = "${var.cluster_name}-tpu-smoketest"
}

resource "kubernetes_config_map_v1" "smoketest_script" {
  count = local.smoketest_enabled ? 1 : 0

  metadata {
    name      = "${local.smoke_name}-script"
    namespace = local.smoke_ns
  }

  data = {
    "tpu_smoketest.py" = file("${path.module}/scripts/tpu_smoketest.py")
  }

  depends_on = [kubernetes_namespace_v1.tpu_runtime]
}

resource "kubernetes_service_v1" "smoketest_coordinator" {
  count = local.smoketest_enabled ? 1 : 0

  metadata {
    name      = local.smoke_name
    namespace = local.smoke_ns
  }

  spec {
    cluster_ip = "None" # headless: stable per-pod DNS for the coordinator
    selector = {
      "job-name" = local.smoke_name
    }
    port {
      name = "coordinator"
      port = 8476
    }
  }

  depends_on = [kubernetes_namespace_v1.tpu_runtime]
}

resource "kubernetes_job_v1" "tpu_smoketest" {
  count = local.smoketest_enabled ? 1 : 0

  metadata {
    name      = local.smoke_name
    namespace = local.smoke_ns
    labels = {
      "app.kubernetes.io/part-of" = "tpu-terraform-modules"
    }
  }

  spec {
    completions     = local.smoke_slice.hosts
    parallelism     = local.smoke_slice.hosts
    completion_mode = "Indexed"
    backoff_limit   = 2

    template {
      metadata {
        labels = {
          "job-name" = local.smoke_name
        }
      }

      spec {
        subdomain      = local.smoke_name
        restart_policy = "Never"

        node_selector = {
          "cloud.google.com/gke-tpu-accelerator" = local.smoke_slice.node_selector
          "cloud.google.com/gke-tpu-topology"    = local.smoke_slice.topology
        }

        toleration {
          key      = "google.com/tpu"
          operator = "Exists"
          effect   = "NoSchedule"
        }

        container {
          name    = "smoketest"
          image   = var.tpu_runtime.jax_image
          command = ["python", "/opt/smoketest/tpu_smoketest.py"]

          env {
            name  = "TPU_SMOKETEST_EXPECTED_DEVICES"
            value = tostring(local.smoke_slice.chips)
          }
          env {
            name  = "TPU_SMOKETEST_LEVEL"
            value = var.smoketest.level
          }
          env {
            name  = "TPU_SMOKETEST_HOSTS"
            value = tostring(local.smoke_slice.hosts)
          }
          env {
            name  = "TPU_SMOKETEST_COORDINATOR"
            value = "${local.smoke_name}-0.${local.smoke_name}.${local.smoke_ns}.svc"
          }

          resources {
            requests = {
              "google.com/tpu" = local.smoke_slice.chips_per_host
            }
            limits = {
              "google.com/tpu" = local.smoke_slice.chips_per_host
            }
          }

          volume_mount {
            name       = "script"
            mount_path = "/opt/smoketest"
          }
        }

        volume {
          name = "script"
          config_map {
            name = kubernetes_config_map_v1.smoketest_script[0].metadata[0].name
          }
        }
      }
    }
  }

  wait_for_completion = true

  timeouts {
    create = "${var.smoketest.timeout_seconds}s"
  }

  depends_on = [
    google_container_node_pool.tpu_slice,
    kubernetes_service_v1.smoketest_coordinator,
  ]
}
