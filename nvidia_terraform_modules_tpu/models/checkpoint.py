# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""Durable checkpoint/resume for the burn-in workload (preemption story).

Why this exists: the ``gke-tpu`` module makes *preemptible* TPU slices a
first-class provisioning option (``gke-tpu/tpu_slices.tf`` ``spot`` flag).
A spot slice can vanish mid-burn-in — and mid-**save**. The previous
revision delegated local storage to orbax, whose installed version lists
a crash-mid-write partial step directory as ``latest_step()`` and then
*raises* from ``restore`` — a preempted pod could wedge every future
attempt on a checkpoint that never finished writing. This revision owns
the local storage engine end to end so durability is a property of the
commit protocol, not of library behaviour:

- **atomic commit**: every save writes into a hidden temp directory
  (``.tmp.step_N``), fsyncs data and directory, and publishes with one
  ``os.rename`` — a step directory either exists completely or not at
  all, and ``latest_step()`` cannot see an in-flight write;
- **verified restore**: each committed step carries ``manifest.json``
  with a per-shard crc32 over the raw bytes. ``restore`` verifies the
  manifest; a truncated/corrupt/stale step is logged, **quarantined**
  (renamed under ``quarantine/`` with the failure reason), and restore
  falls back to the newest *valid* step instead of crashing or silently
  loading garbage. A quarantined step is never restored;
- **sharded**: saves/restores ``jax.Array``\\ s with their
  ``NamedSharding`` preserved — each host writes only its addressable
  shards (no gather through one host), restore places shards directly
  on the mesh via ``jax.make_array_from_callback``;
- **multi-host without collectives**: processes rendezvous through the
  (shared) checkpoint filesystem itself — nonce-stamped part files that
  process 0 merges and commits. No barrier runs through the collective
  fabric, so an emergency save still commits when a peer is already
  dead (the exact moment the old in-band barrier would hang). Every
  wait is bounded (``TPU_CHECKPOINT_SYNC_TIMEOUT_S``) and times out as
  a classified :class:`CheckpointError`, never an indefinite hang;
- **async save**: ``async_save=True`` snapshots device arrays to host
  synchronously, then writes/commits on a background thread so the
  train step doesn't stall on I/O; :meth:`flush`/:meth:`close` are the
  commit barriers and re-raise any background failure;
- **step-numbered + run-scoped**: exactly as before — the global step
  survives restarts, and a successful run calls :meth:`clear`;
- **elastic (re-sharding) restore**: the manifest records every shard's
  global bounds, so a checkpoint written by an N-host world restores
  into an M-host mesh with a *different* sharding (M < N after a spot
  reclaim shrinks the fleet, M > N when capacity returns). Restore
  streams leaf by leaf and shard by shard: for each target shard of the
  run's ``NamedSharding`` it reads only the intersecting byte ranges
  (``seek`` + ranged read, crc32-verified per record), so peak host
  memory is bounded by one leaf's working set — never the whole
  checkpoint, never a gather through one host. Corrupt records on the
  read path still classify, quarantine, and fall back exactly like the
  shape-preserving path; in a **multi-process** world restore first
  verifies every record (still streamed one at a time) so all peers
  reach the same valid/quarantine verdict — the partial-read fast path
  is single-process-only, because a verdict that depends on *which*
  ranges a host needs would let peers resume from different steps.
  :meth:`Checkpointer.stored_world` reports the writing world's
  process count for the resume journal.

Restore-time reads retry transient I/O with capped exponential backoff
and jitter (``utils/retry.py`` — the workload-side mirror of the
``tfsim`` control-plane policy) before classifying a step as corrupt: a
PVC remount blip should cost milliseconds, not a quarantined step.

``directory`` may also be a remote URI (``gs://…``); remote prefixes
keep the orbax/tensorstore backend (atomicity is then orbax's commit
contract, and the manifest/quarantine layer does not apply — document
accordingly in the Job wiring).

On-disk layout of a committed local step::

    <root>/step_00000042/
        manifest.json     # step, world size, per-leaf shard records + crc32
        meta.json         # the caller's JSON metadata
        shards_p00000.bin # process 0's raw shard bytes (one file per host)
    <root>/quarantine/
        step_00000041.bad-crc/   # quarantined, never restored
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import queue
import shutil
import threading
import time
import uuid
import zlib
from typing import Any, Callable, Optional

import jax
import numpy as np

from ..utils.retry import RetryPolicy, retry_call
from .burnin import BurnInConfig, init_params, param_shardings

log = logging.getLogger(__name__)

_STEP_PREFIX = "step_"
_TMP_PREFIX = ".tmp."
_QUARANTINE = "quarantine"
_MANIFEST = "manifest.json"
_META = "meta.json"
_TOKEN = "token.json"
_FORMAT = 1

# bounded rendezvous: how long a process waits for its peers' part files
# (or the committed step) before failing with a classified error instead
# of hanging — a dead peer must cost one timeout, not the whole job
DEFAULT_SYNC_TIMEOUT_S = 120.0

# restore-time read retries: transient I/O (PVC remount, NFS blip) is
# retried briefly before the step is classified corrupt
_READ_RETRY = RetryPolicy(initial_s=0.1, multiplier=2.0, cap_s=1.0,
                          max_attempts=3, jitter=True)


class CheckpointError(Exception):
    """Classified checkpoint-layer failure (rendezvous timeout, missing
    explicit step, unwritable storage)."""


class CorruptCheckpointError(CheckpointError):
    """A specific step failed verification; ``reason`` says how."""

    def __init__(self, step: int, reason: str):
        super().__init__(f"checkpoint step {step} is not restorable: "
                         f"{reason}")
        self.step = step
        self.reason = reason


class MissingStepError(CheckpointError):
    """An explicitly requested step is not in the committed namespace —
    deterministic (retention pruned it or it never existed), so retry
    layers must NOT hammer it like a transient rendezvous failure."""


def _is_remote(directory: str) -> bool:
    return "://" in directory


def _root(directory: str) -> str:
    # os.path.abspath would mangle gs://bucket/x into <cwd>/gs:/bucket/x
    return directory if _is_remote(directory) else os.path.abspath(directory)


def _no_checkpoint_possible(directory: str) -> bool:
    """Cheap local fast-path; never touches (or creates) remote storage
    when the directory plainly doesn't exist yet."""
    return not _is_remote(directory) and not os.path.isdir(directory)


def _step_dirname(step: int) -> str:
    return f"{_STEP_PREFIX}{step:08d}"


def _parse_step(name: str) -> Optional[int]:
    if not name.startswith(_STEP_PREFIX):
        return None
    tail = name[len(_STEP_PREFIX):]
    return int(tail) if tail.isdigit() else None


def _world() -> tuple[int, int]:
    try:
        return jax.process_index(), jax.process_count()
    # graftlint: ignore[graft-silent-except] — backend probe by design
    except Exception:  # pre-init / no backend: single-process semantics
        return 0, 1


def _fsync_file(path: str) -> None:
    with open(path, "rb") as fh:
        os.fsync(fh.fileno())


def _fsync_dir(path: str) -> None:
    # directory fsync makes the rename itself durable; some filesystems
    # (and the test tmpfs) don't support it — durability degrades, the
    # atomicity of the rename does not
    with contextlib.suppress(OSError):
        fd = os.open(path, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)


def _wait_for(predicate: Callable[[], Any], timeout_s: float, what: str,
              interval_s: float = 0.05):
    """Poll ``predicate`` until truthy; bounded by ``timeout_s``.

    The timeout converts "a peer died mid-save" from an indefinite hang
    into a classified failure the supervisor can act on."""
    t0 = time.monotonic()
    while True:
        value = predicate()
        if value:
            return value
        if time.monotonic() - t0 > timeout_s:
            raise CheckpointError(
                f"checkpoint rendezvous timed out after {timeout_s:.0f}s "
                f"waiting for {what} — a peer process is dead or shared "
                f"storage has stalled")
        time.sleep(interval_s)


def _np_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # jax's extended dtypes (bfloat16, fp8, …)

        return np.dtype(getattr(ml_dtypes, name))


def _normalize_index(index, shape) -> list[list[int]]:
    """A shard's global index as explicit [start, stop] bounds per dim."""
    out = []
    for sl, dim in zip(index, shape):
        start, stop, stride = sl.indices(dim)
        if stride != 1:
            raise CheckpointError(
                f"non-contiguous shard stride {stride} is not supported")
        out.append([start, stop])
    return out


def _index_slices(bounds) -> tuple:
    return tuple(slice(a, b) for a, b in bounds)


def _leaf_paths(tree) -> tuple[list[tuple[str, Any]], Any]:
    """Flatten a pytree to ``(path-string, leaf)`` pairs + treedef."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    return [(jax.tree_util.keystr(path), leaf) for path, leaf in flat], \
        treedef


def _snapshot_leaf(leaf) -> tuple[tuple[int, ...], str, list]:
    """Host-side copy of one leaf's addressable data.

    Returns ``(global_shape, dtype_name, [(bounds, np_array), …])``.
    For a ``jax.Array`` only the addressable shards are copied (each
    host persists its own data); replicated shards are deduplicated
    within the process. Plain numpy/python leaves are one full shard.
    """
    shards = getattr(leaf, "addressable_shards", None)
    if shards:
        shape = tuple(leaf.shape)
        dtype = np.dtype(leaf.dtype).name
        seen: set = set()
        out = []
        for s in shards:
            bounds = _normalize_index(s.index, shape)
            key = tuple(map(tuple, bounds))
            if key in seen:
                continue
            seen.add(key)
            out.append((bounds, np.array(s.data)))
        return shape, dtype, out
    arr = np.asarray(leaf)
    bounds = [[0, d] for d in arr.shape]
    return tuple(arr.shape), arr.dtype.name, [(bounds, arr)]


# --------------------------------------------------------------- local store


class _LocalStore:
    """The durable local engine: commit protocol, verification,
    quarantine, retention. One instance per :class:`Checkpointer`."""

    def __init__(self, root: str, max_to_keep: int,
                 sync_timeout_s: Optional[float] = None):
        self.root = root
        self.max_to_keep = max_to_keep
        self.sync_timeout_s = sync_timeout_s if sync_timeout_s is not None \
            else float(os.environ.get("TPU_CHECKPOINT_SYNC_TIMEOUT_S",
                                      DEFAULT_SYNC_TIMEOUT_S))

    # ---- listing ----------------------------------------------------
    def committed_steps(self) -> list[int]:
        """Steps with a published directory AND a readable manifest —
        the commit marker. (A partial directory cannot appear here: the
        rename publishes manifest and data together.)"""
        if not os.path.isdir(self.root):
            return []
        steps = []
        for name in os.listdir(self.root):
            step = _parse_step(name)
            if step is None:
                continue
            if os.path.isfile(os.path.join(self.root, name, _MANIFEST)):
                steps.append(step)
        return sorted(steps)

    def quarantined(self) -> list[str]:
        qdir = os.path.join(self.root, _QUARANTINE)
        if not os.path.isdir(qdir):
            return []
        return sorted(os.listdir(qdir))

    # ---- save -------------------------------------------------------
    def save(self, step: int, snapshot, meta: dict) -> None:
        """Commit one step from a host-side ``snapshot`` (the list built
        by :func:`_snapshot_leaf` per leaf path).

        Single-writer protocol per process; process 0 is the committer.
        All cross-process coordination is file-based and bounded.
        """
        pid, nprocs = _world()
        os.makedirs(self.root, exist_ok=True)
        tmp = os.path.join(self.root, f"{_TMP_PREFIX}{_step_dirname(step)}")
        token_path = os.path.join(tmp, _TOKEN)

        if pid == 0:
            # fresh attempt: sweep any leftover from a crashed writer so
            # stale parts can never be merged into this commit
            shutil.rmtree(tmp, ignore_errors=True)
            os.makedirs(tmp)
            nonce = uuid.uuid4().hex
            _atomic_write_json(token_path, {"nonce": nonce, "step": step,
                                            "nprocs": nprocs})
        else:
            nonce = _wait_for(
                lambda: _read_json_or_none(token_path, key="nonce"),
                self.sync_timeout_s,
                f"the save token of step {step} from process 0")

        self._write_part(tmp, step, pid, nonce, snapshot)

        final = os.path.join(self.root, _step_dirname(step))
        if pid == 0:
            parts = _wait_for(
                lambda: self._all_parts(tmp, nonce, nprocs),
                self.sync_timeout_s,
                f"{nprocs} shard part file(s) of step {step}")
            manifest = {
                "format": _FORMAT,
                "step": step,
                "nprocs": nprocs,
                "leaves": _merge_parts(parts),
            }
            _atomic_write_json(os.path.join(tmp, _META), dict(meta or {}))
            _atomic_write_json(os.path.join(tmp, _MANIFEST), manifest)
            os.remove(token_path)
            _fsync_dir(tmp)
            if os.path.isdir(final):
                # re-saving an existing step replaces it atomically-ish:
                # demote the old directory out of the committed namespace
                # first so no reader ever sees a half-replaced step
                doomed = os.path.join(
                    self.root, f"{_TMP_PREFIX}rm.{uuid.uuid4().hex}")
                os.rename(final, doomed)
                shutil.rmtree(doomed, ignore_errors=True)
            os.rename(tmp, final)
            _fsync_dir(self.root)
            self._enforce_retention()
        else:
            def committed_or_token_changed():
                if os.path.isfile(os.path.join(final, _MANIFEST)):
                    return "committed"
                current = _read_json_or_none(token_path, key="nonce")
                if current is not None and current != nonce:
                    return "restarted"
                return None

            outcome = _wait_for(
                committed_or_token_changed, self.sync_timeout_s,
                f"process 0 to commit step {step}")
            if outcome == "restarted":
                # process 0 started a fresh attempt (it swept our part):
                # rejoin it once — self-heals the crashed-writer leftover
                # race where this process wrote against a stale token
                self.save(step, snapshot, meta)

    def _write_part(self, tmp: str, step: int, pid: int, nonce: str,
                    snapshot) -> None:
        shard_file = f"shards_p{pid:05d}.bin"
        records = []
        offset = 0
        with open(os.path.join(tmp, shard_file), "wb") as fh:
            for path, (shape, dtype, shards) in snapshot:
                for bounds, arr in shards:
                    raw = np.ascontiguousarray(arr).tobytes()
                    fh.write(raw)
                    records.append({
                        "path": path,
                        "shape": list(shape),
                        "dtype": dtype,
                        "bounds": bounds,
                        "file": shard_file,
                        "offset": offset,
                        "nbytes": len(raw),
                        "crc32": zlib.crc32(raw) & 0xFFFFFFFF,
                    })
                    offset += len(raw)
            fh.flush()
            os.fsync(fh.fileno())
        _atomic_write_json(
            os.path.join(tmp, f"part_p{pid:05d}.json"),
            {"nonce": nonce, "step": step, "process": pid,
             "records": records})

    @staticmethod
    def _all_parts(tmp: str, nonce: str, nprocs: int):
        parts = []
        for k in range(nprocs):
            data = _read_json_or_none(
                os.path.join(tmp, f"part_p{k:05d}.json"))
            if data is None or data.get("nonce") != nonce:
                return None
            parts.append(data)
        return parts

    def _enforce_retention(self) -> None:
        steps = self.committed_steps()
        for old in steps[:-self.max_to_keep] if self.max_to_keep else []:
            self._remove_step(old)

    def _remove_step(self, step: int) -> None:
        path = os.path.join(self.root, _step_dirname(step))
        if not os.path.isdir(path):
            return
        # demote out of the committed namespace before deleting so a
        # crash mid-rmtree can never leave a half-deleted "committed" dir
        doomed = os.path.join(self.root,
                              f"{_TMP_PREFIX}rm.{uuid.uuid4().hex}")
        with contextlib.suppress(FileNotFoundError):
            os.rename(path, doomed)
            shutil.rmtree(doomed, ignore_errors=True)

    # ---- verify / quarantine ---------------------------------------
    def read_manifest(self, step: int) -> tuple[dict, dict]:
        """Read + header-verify one committed step's ``(meta, manifest)``.

        Shard *data* is deliberately not read here — the streaming
        restore pulls only the byte ranges the target sharding needs
        (see :class:`_RecordReader`). Raises
        :class:`CorruptCheckpointError` on an unreadable or mismatched
        manifest.
        """
        stepdir = os.path.join(self.root, _step_dirname(step))

        def read(path):
            return retry_call(
                lambda: open(path, "rb").read(), policy=_READ_RETRY,
                what=f"read {os.path.basename(path)}",
                retryable=(OSError,))

        try:
            manifest = json.loads(read(os.path.join(stepdir, _MANIFEST)))
            meta = json.loads(read(os.path.join(stepdir, _META)))
        except Exception as exc:  # noqa: BLE001 — classified below
            raise CorruptCheckpointError(
                step, f"unreadable manifest/meta ({exc})") from exc
        if manifest.get("format") != _FORMAT or \
                manifest.get("step") != step:
            raise CorruptCheckpointError(
                step, f"manifest format/step mismatch "
                      f"(format={manifest.get('format')}, "
                      f"step={manifest.get('step')})")
        return meta, manifest

    def record_reader(self, step: int) -> "_RecordReader":
        return _RecordReader(
            os.path.join(self.root, _step_dirname(step)), step)

    def quarantine(self, step: int, reason: str) -> None:
        """Move a failed step out of the committed namespace for good.

        The renamed directory keeps the bytes (post-mortem evidence) but
        can never be listed or restored again. Multi-process safe: the
        first process to rename wins, the rest observe ENOENT and move
        on — every process still falls back to the same next step.
        """
        src = os.path.join(self.root, _step_dirname(step))
        qdir = os.path.join(self.root, _QUARANTINE)
        os.makedirs(qdir, exist_ok=True)
        slug = "".join(c if c.isalnum() or c in "-_" else "-"
                       for c in reason.split("(")[0].strip())[:48].rstrip("-")
        dst = os.path.join(qdir, f"{_step_dirname(step)}.{slug or 'bad'}")
        if os.path.exists(dst):
            dst = f"{dst}.{uuid.uuid4().hex[:8]}"
        with contextlib.suppress(FileNotFoundError):
            os.rename(src, dst)
            log.warning(
                "quarantined checkpoint step %d -> %s (%s)", step,
                os.path.relpath(dst, self.root), reason)

    def sweep_stale_tmp(self, min_age_s: float = 3600.0) -> None:
        """Remove crashed writers' leftovers (old ``.tmp.*`` dirs) —
        age-gated so an in-flight save on a peer is never swept."""
        if not os.path.isdir(self.root):
            return
        # ages are computed against filesystem mtimes; epoch time is
        # the only clock comparable to them
        # graftlint: ignore[graft-wallclock-nondeterminism] — mtime ages
        now = time.time()
        for name in os.listdir(self.root):
            if not name.startswith(_TMP_PREFIX):
                continue
            path = os.path.join(self.root, name)
            with contextlib.suppress(OSError):
                if now - os.path.getmtime(path) >= min_age_s:
                    shutil.rmtree(path, ignore_errors=True)


def _atomic_write_json(path: str, payload: dict) -> None:
    tmp = f"{path}.tmp.{uuid.uuid4().hex[:8]}"
    with open(tmp, "w") as fh:
        json.dump(payload, fh)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)


def _read_json_or_none(path: str, key: Optional[str] = None):
    try:
        with open(path) as fh:
            data = json.load(fh)
    except (OSError, json.JSONDecodeError):
        return None
    return data.get(key) if key is not None else data


def _merge_parts(parts: list[dict]) -> list[dict]:
    records = []
    for part in parts:
        records.extend(part["records"])
    return records


# ------------------------------------------- streaming (elastic) assembly


class _RecordReader:
    """Ranged, verified reads of individual shard records.

    The elastic restore path's I/O layer: one persistent handle per shard
    file, ``seek`` + ranged read per record (retried via ``_READ_RETRY``),
    length- and crc32-checked so corruption classifies per record — a
    process restoring into an M-host mesh reads only the byte ranges its
    own target shards intersect, never whole files.
    """

    def __init__(self, stepdir: str, step: int):
        self.stepdir = stepdir
        self.step = step
        self._handles: dict[str, Any] = {}

    def close(self) -> None:
        for fh in self._handles.values():
            with contextlib.suppress(OSError):
                fh.close()
        self._handles.clear()

    def __enter__(self) -> "_RecordReader":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def read(self, rec: dict) -> np.ndarray:
        fname = rec["file"]
        fh = self._handles.get(fname)
        if fh is None:
            try:
                fh = retry_call(
                    lambda: open(os.path.join(self.stepdir, fname), "rb"),
                    policy=_READ_RETRY, what=f"open {fname}",
                    retryable=(OSError,))
            except Exception as exc:  # noqa: BLE001 — classified
                raise CorruptCheckpointError(
                    self.step, f"missing/unreadable shard file {fname} "
                               f"({exc})") from exc
            self._handles[fname] = fh

        def ranged():
            fh.seek(rec["offset"])
            return fh.read(rec["nbytes"])

        try:
            raw = retry_call(
                ranged, policy=_READ_RETRY,
                what=f"read {fname}[{rec['offset']}:+{rec['nbytes']}]",
                retryable=(OSError,))
        except Exception as exc:  # noqa: BLE001 — classified: a ranged
            # read that stays broken past the retry budget (bad block,
            # vanished mount) must quarantine-and-fall-back like any
            # other unreadable shard, not crash the restore attempt
            raise CorruptCheckpointError(
                self.step, f"unreadable shard range "
                           f"{fname}[{rec['offset']}:+{rec['nbytes']}] "
                           f"for {rec['path']} ({exc})") from exc
        if len(raw) != rec["nbytes"]:
            raise CorruptCheckpointError(
                self.step, f"shard file {fname} truncated at offset "
                           f"{rec['offset']} (wanted {rec['nbytes']} bytes "
                           f"for {rec['path']})")
        if (zlib.crc32(raw) & 0xFFFFFFFF) != rec["crc32"]:
            raise CorruptCheckpointError(
                self.step, f"crc32 mismatch in {fname} for {rec['path']} "
                           f"{rec['bounds']}")
        arr = np.frombuffer(raw, dtype=_np_dtype(rec["dtype"]))
        return arr.reshape([b - a for a, b in rec["bounds"]])


def _intersect_bounds(a, b) -> Optional[list[tuple[int, int]]]:
    """Per-dim overlap of two explicit bounds lists, or None if disjoint."""
    out = []
    for (a0, a1), (b0, b1) in zip(a, b):
        lo, hi = max(a0, b0), min(a1, b1)
        if lo >= hi:
            return None
        out.append((lo, hi))
    return out


def _volume(bounds) -> int:
    n = 1
    for a, b in bounds:
        n *= b - a
    return n


def _unique_records(path: str, abstract, records, step: int) -> list[dict]:
    """Validate a leaf's manifest records against the run's expectation
    and reduce them to one record per distinct shard bounds.

    Replicated leaves are stored once per *writing* process — identical
    bounds in different part files — so dedup keeps the first copy (the
    rest are never read). Coverage is judged by arithmetic before any
    data I/O: unique bounds come from a sharding's device index map, a
    disjoint partition of the leaf, so their volumes must sum to the
    leaf exactly — short means a writer died before its part was
    recorded, long means overlapping records.
    """
    shape = tuple(abstract.shape)
    dtype = np.dtype(abstract.dtype)
    stored_shapes = {tuple(rec["shape"]) for rec in records}
    if stored_shapes != {shape}:
        raise CorruptCheckpointError(
            step, f"stale checkpoint: leaf {path} has shape "
                  f"{sorted(stored_shapes)} on disk but the run expects "
                  f"{shape}")
    stored_dtypes = {rec["dtype"] for rec in records}
    if any(_np_dtype(d) != dtype for d in stored_dtypes):
        raise CorruptCheckpointError(
            step, f"stale checkpoint: leaf {path} stored as "
                  f"{sorted(stored_dtypes)}, run expects {dtype.name}")
    unique: dict[tuple, dict] = {}
    for rec in records:
        unique.setdefault(tuple(map(tuple, rec["bounds"])), rec)
    volume = sum(_volume(rec["bounds"]) for rec in unique.values())
    size = _volume([(0, d) for d in shape])
    if volume != size:
        raise CorruptCheckpointError(
            step, f"partial checkpoint: leaf {path} shard records cover "
                  f"{volume} of {size} elements (a writer died before "
                  f"its part was recorded, or records overlap)")
    return list(unique.values())


def _assemble_leaf(path: str, abstract, records, step: int,
                   reader: _RecordReader):
    """One leaf, streamed from its shard records onto the target placement.

    The re-sharding core: the stored bounds partition the leaf along the
    *writing* world's sharding, the target ``NamedSharding`` partitions
    it along the *restoring* world's — generally neither a refinement of
    the other (N→M with misaligned boundaries). Each addressable target
    shard is assembled from the intersecting stored records only, read
    as verified byte ranges; a per-leaf cache bounds re-reads when one
    record feeds several target shards and is dropped with the leaf, so
    peak host memory stays at one leaf's working set.
    """
    shape = tuple(abstract.shape)
    unique = _unique_records(path, abstract, records, step)
    sharding = getattr(abstract, "sharding", None)
    if sharding is None:
        full = np.empty(shape, dtype=np.dtype(abstract.dtype))
        for rec in unique:
            full[_index_slices(rec["bounds"])] = reader.read(rec)
        import jax.numpy as jnp

        return jnp.asarray(full)

    record_cache: dict[tuple, np.ndarray] = {}
    shard_cache: dict[tuple, np.ndarray] = {}

    def target_shard(idx):
        bounds = _normalize_index(idx, shape)
        key = tuple(map(tuple, bounds))
        if key in shard_cache:   # replicated target shards read once
            return shard_cache[key]
        out = np.empty([b - a for a, b in bounds],
                       dtype=np.dtype(abstract.dtype))
        filled = 0
        for rec in unique:
            inter = _intersect_bounds(rec["bounds"], bounds)
            if inter is None:
                continue
            rkey = tuple(map(tuple, rec["bounds"]))
            arr = record_cache.get(rkey)
            if arr is None:
                arr = record_cache[rkey] = reader.read(rec)
            dst = tuple(slice(lo - t0, hi - t0)
                        for (lo, hi), (t0, _t1) in zip(inter, bounds))
            src = tuple(slice(lo - r0, hi - r0)
                        for (lo, hi), (r0, _r1) in zip(inter,
                                                       rec["bounds"]))
            out[dst] = arr[src]
            filled += _volume(inter)
        if filled != out.size:
            raise CorruptCheckpointError(
                step, f"partial checkpoint: leaf {path} target shard "
                      f"{key} assembled {filled} of {out.size} elements")
        shard_cache[key] = out
        return out

    return jax.make_array_from_callback(shape, sharding, target_shard)


# ------------------------------------------------------------ async writer


class _AsyncWriter:
    """One background thread draining a queue of commit jobs.

    ``save`` snapshots device arrays on the caller's thread (training
    may mutate params immediately after) and enqueues only host-side
    I/O. The first failure is stored and re-raised at the next
    ``save``/``flush``/``close`` — an async save must never fail
    silently."""

    def __init__(self):
        self._q: queue.Queue = queue.Queue()
        self._error: Optional[BaseException] = None
        self._thread = threading.Thread(
            target=self._run, name="checkpoint-writer", daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while True:
            job = self._q.get()
            if job is None:
                self._q.task_done()
                return
            try:
                if self._error is None:
                    job()
            except BaseException as exc:  # noqa: BLE001 — re-raised at flush
                self._error = exc
            finally:
                self._q.task_done()

    def submit(self, job: Callable[[], None]) -> None:
        self.raise_pending()
        self._q.put(job)

    def flush(self) -> None:
        self._q.join()
        self.raise_pending()

    def raise_pending(self) -> None:
        if self._error is not None:
            exc, self._error = self._error, None
            raise CheckpointError(
                f"a background checkpoint save failed: "
                f"{type(exc).__name__}: {exc}") from exc

    def close(self) -> None:
        self._q.put(None)
        self._q.join()
        self._thread.join(timeout=30)
        self.raise_pending()


# -------------------------------------------------------------- the fronts


class Checkpointer:
    """One durable checkpoint store for a whole run.

    Local paths run the manifest/quarantine engine above; remote URIs
    (``gs://…``) delegate to orbax/tensorstore. Use as a context manager
    or call :meth:`close`; the run loop holds ONE instance (per-save
    construction would re-scan the directory every step).
    """

    def __init__(self, directory: str, max_to_keep: int = 2,
                 async_save: bool = False,
                 sync_timeout_s: Optional[float] = None,
                 telemetry=None):
        """``async_save=True`` makes :meth:`save` return after the
        device arrays are snapshotted to host, with serialization and
        the atomic commit running behind the next training steps — the
        standard TPU lever for hiding checkpoint I/O. The commit point
        moves to :meth:`flush` / :meth:`close` / the next read. The
        smoke-test Job keeps the blocking default: it may be preempted
        right after a step, and an uncommitted async write racing pod
        teardown would lose the step."""
        self.directory = directory
        self._max_to_keep = max_to_keep
        self._async = async_save
        # explicit injection wins; otherwise the process registry (the
        # NULL no-op unless TPU_TELEMETRY_DIR enabled it) — spans cover
        # save/restore/verify/re-shard, counters cover saves/quarantines
        self._telemetry = telemetry
        self._writer: Optional[_AsyncWriter] = None
        self._remote = _RemoteOrbax(directory, max_to_keep) \
            if _is_remote(directory) else None
        self._store = None if self._remote is not None else _LocalStore(
            _root(directory), max_to_keep, sync_timeout_s)

    @property
    def _reg(self):
        if self._telemetry is not None:
            return self._telemetry
        from ..telemetry import get_registry

        return get_registry()

    # ---- lifecycle --------------------------------------------------
    def __enter__(self) -> "Checkpointer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def close(self) -> None:
        """Commit any in-flight async save, then tear down — a close
        that dropped a scheduled write would silently lose the run's
        last step."""
        if self._writer is not None:
            self._writer.close()
            self._writer = None
        if self._remote is not None:
            self._remote.close()

    def flush(self) -> None:
        """Block until every scheduled (async) save has committed."""
        if self._writer is not None:
            self._writer.flush()
        if self._remote is not None:
            self._remote.flush()

    # ---- listing ----------------------------------------------------
    def latest_step(self) -> Optional[int]:
        self.flush()   # reads must not miss a scheduled-but-uncommitted save
        if _no_checkpoint_possible(self.directory):
            return None
        if self._remote is not None:
            return self._remote.latest_step()
        steps = self._store.committed_steps()
        return steps[-1] if steps else None

    def all_steps(self) -> list[int]:
        self.flush()
        if _no_checkpoint_possible(self.directory):
            return []
        if self._remote is not None:
            return self._remote.all_steps()
        return self._store.committed_steps()

    def quarantined(self) -> list[str]:
        """Quarantined step directory names (never restorable)."""
        if self._remote is not None or \
                _no_checkpoint_possible(self.directory):
            return []
        return self._store.quarantined()

    # ---- save -------------------------------------------------------
    def save(self, step: int, params: Any,
             meta: Optional[dict[str, Any]] = None) -> None:
        """Atomic, checksummed save of ``params`` (+ JSON ``meta``).

        Blocking by default; with ``async_save=True`` the write+commit
        overlaps subsequent compute and lands at the next
        save/:meth:`flush`/:meth:`close`.
        """
        # one code path whatever the telemetry state: checkpoints are
        # per-save, not per-step, so the NULL registry's no-op span is
        # the right tool here (the once-per-call-site enabled guard is
        # for the hot loops). The caller-visible save span covers host
        # snapshot (+ the commit when blocking); an async commit gets
        # its own span from the writer thread, so the timeline shows
        # what the train step PAID vs what the background writer hid.
        reg = self._reg
        with reg.span("checkpoint_save", step=step,
                      asynchronous=self._async,
                      backend="orbax" if self._remote else "local"):
            reg.counter("checkpoint_saves").inc()
            if self._remote is not None:
                self._remote.save(step, params, meta,
                                  wait=not self._async)
                return
            pairs, _ = _leaf_paths(params)
            snapshot = [(path, _snapshot_leaf(leaf))
                        for path, leaf in pairs]
            if not self._async:
                self._store.save(step, snapshot, meta or {})
                return
            if self._writer is None:
                self._writer = _AsyncWriter()
            store, m = self._store, dict(meta or {})

            def job():
                with reg.span("checkpoint_commit", step=step):
                    store.save(step, snapshot, m)

            self._writer.submit(job)

    # ---- restore ----------------------------------------------------
    def restore(self, cfg: BurnInConfig, rules=None,
                step: Optional[int] = None,
                ) -> Optional[tuple[Any, int, dict[str, Any]]]:
        """Restore ``(params, step, meta)`` from the newest valid (or a
        given) step.

        Params come back placed: an abstract pytree built from ``cfg``
        (and the mesh's sharding rules, when given) describes the target
        shape/dtype/sharding of every leaf, so restore writes device
        shards directly. Returns None when no valid checkpoint exists.
        """
        abstract = jax.eval_shape(
            lambda: init_params(jax.random.PRNGKey(0), cfg))
        if rules is not None:
            shardings = param_shardings(abstract, rules)
            abstract = jax.tree.map(
                lambda a, s: jax.ShapeDtypeStruct(
                    a.shape, a.dtype, sharding=s),
                abstract, shardings)
        return self.restore_tree(abstract, step)

    def restore_tree(self, abstract: Any, step: Optional[int] = None,
                     ) -> Optional[tuple[Any, int, dict[str, Any]]]:
        """Restore an arbitrary pytree saved with :meth:`save`.

        ``abstract`` is a ``jax.ShapeDtypeStruct`` pytree (shardings
        included) describing the target placement — e.g. the AdamW train
        state ``{"params": …, "opt": …}`` whose moments carry ZeRO-1
        shardings. With ``step=None`` the newest step that passes
        manifest verification wins; corrupt/truncated/stale steps are
        quarantined and skipped. An explicit ``step`` is strict: a
        missing or corrupt step raises instead of falling back (the
        caller asked for *that* step). Returns ``(tree, step, meta)`` or
        None when no valid checkpoint exists.
        """
        self.flush()   # never restore a step whose commit hasn't landed
        if _no_checkpoint_possible(self.directory):
            return None
        if self._remote is not None:
            return self._remote.restore_tree(abstract, step)
        with self._reg.span("checkpoint_restore") as sp:
            out = self._restore_local(abstract, step)
            sp.args["step"] = out[1] if out is not None else None
            return out

    def _restore_local(self, abstract: Any, step: Optional[int],
                       ) -> Optional[tuple[Any, int, dict[str, Any]]]:
        if step is not None:
            if step not in self._store.committed_steps():
                raise MissingStepError(
                    f"checkpoint step {step} does not exist in "
                    f"{self.directory} (committed: "
                    f"{self._store.committed_steps() or 'none'})")
            return self._load(abstract, step)
        for candidate in reversed(self._store.committed_steps()):
            try:
                return self._load(abstract, candidate)
            except CorruptCheckpointError as exc:
                log.warning(
                    "checkpoint step %d failed verification (%s); "
                    "quarantining and falling back to the previous step",
                    candidate, exc.reason)
                self._reg.counter("checkpoint_quarantined").inc()
                self._reg.event("checkpoint.quarantine", step=candidate,
                                reason=exc.reason)
                self._store.quarantine(candidate, exc.reason)
        return None

    def _load(self, abstract: Any, step: int,
              ) -> tuple[Any, int, dict[str, Any]]:
        """Streamed, re-sharding load: leaf by leaf, target shard by
        target shard — the stored world size and sharding never have to
        match the restoring run's (elastic resume)."""
        meta, manifest = self._store.read_manifest(step)
        stored: dict[str, list] = {}
        for rec in manifest.get("leaves", []):
            stored.setdefault(rec["path"], []).append(rec)
        pairs, treedef = _leaf_paths(abstract)
        want = {path for path, _ in pairs}
        have = set(stored)
        if want != have:
            missing = sorted(want - have)[:3]
            extra = sorted(have - want)[:3]
            raise CorruptCheckpointError(
                step, f"stale checkpoint: leaf set mismatch "
                      f"(missing {missing}, unexpected {extra})")
        reg = self._reg
        with self._store.record_reader(step) as reader:
            if _world()[1] > 1:
                # multi-host: every process must reach the SAME
                # valid/quarantine verdict, or peers could resume from
                # different steps (split-brain) when corruption touches
                # only some hosts' target ranges. Verify every record
                # (streamed, one at a time — memory stays bounded)
                # before any assembly; single-process worlds keep the
                # partial-read fast path, having no peer to disagree
                # with.
                with reg.span("checkpoint_verify", step=step) as sp:
                    for rec in manifest.get("leaves", []):
                        reader.read(rec)
                    sp.args["records"] = len(manifest.get("leaves", []))
            # the assembly phase IS the re-shard when the writing world
            # differs from ours — name it so the timeline says whether a
            # restore crossed world sizes
            stored_world = manifest.get("nprocs")
            name = ("checkpoint_reshard"
                    if stored_world not in (None, _world()[1])
                    else "checkpoint_assemble")
            with reg.span(name, step=step, stored_world=stored_world,
                          world=_world()[1]):
                leaves = [
                    _assemble_leaf(path, a, stored[path], step, reader)
                    for path, a in pairs
                ]
        return (jax.tree_util.tree_unflatten(treedef, leaves), step,
                dict(meta or {}))

    def stored_world(self, step: int) -> Optional[int]:
        """Process count of the world that WROTE ``step`` (local engine;
        None for remote backends) — the resume journal's evidence that a
        re-sharding restore crossed world sizes."""
        if self._remote is not None or \
                _no_checkpoint_possible(self.directory):
            return None
        try:
            _meta, manifest = self._store.read_manifest(step)
        except CorruptCheckpointError:
            return None
        return manifest.get("nprocs")

    # ---- clear ------------------------------------------------------
    def clear(self) -> int:
        """Delete every committed step; returns how many were removed.

        Called after a run *succeeds*: the burn-in is validated, resume
        state is no longer needed, and leaving it behind would make the
        next fresh Job silently continue a finished run's step count.

        Multi-host discipline (local engine): every process snapshots
        the step list, then rendezvouses through token files so all
        snapshots happen *before* process 0 mutates the directory;
        process 0 deletes, the rest wait (bounded) for the steps to be
        gone. No collective runs through the fabric. Quarantined steps
        are kept — they are post-mortem evidence, not resume state.
        """
        # an uncommitted async save racing the delete could re-land its
        # step AFTER the directory sweep — commit everything first
        self.flush()
        if _no_checkpoint_possible(self.directory):
            return 0
        if self._remote is not None:
            return self._remote.clear()
        store = self._store
        steps = store.committed_steps()
        pid, nprocs = _world()
        if nprocs == 1:
            for s in steps:
                store._remove_step(s)
            store.sweep_stale_tmp(min_age_s=0.0)
            return len(steps)
        sync_dir = os.path.join(store.root, f"{_TMP_PREFIX}clear")
        os.makedirs(sync_dir, exist_ok=True)
        _atomic_write_json(
            os.path.join(sync_dir, f"clear_p{pid:05d}.json"),
            {"process": pid, "steps": steps})
        if pid == 0:
            _wait_for(
                lambda: all(
                    os.path.isfile(os.path.join(
                        sync_dir, f"clear_p{k:05d}.json"))
                    for k in range(nprocs)),
                store.sync_timeout_s, "every process's clear snapshot")
            for s in steps:
                store._remove_step(s)
            shutil.rmtree(sync_dir, ignore_errors=True)
            store.sweep_stale_tmp(min_age_s=0.0)
        else:
            _wait_for(
                lambda: not any(
                    os.path.isdir(os.path.join(
                        store.root, _step_dirname(s)))
                    for s in steps) and not os.path.isdir(sync_dir),
                store.sync_timeout_s, "process 0 to finish clearing")
        return len(steps)


# ------------------------------------------------------- remote passthrough


class _RemoteOrbax:
    """Remote-URI backend: the previous orbax/tensorstore path, kept for
    ``gs://…`` prefixes where the local engine cannot reach. Atomicity
    and retention are orbax's contract; the manifest/quarantine layer
    does not apply here."""

    def __init__(self, directory: str, max_to_keep: int):
        self.directory = directory
        self._max_to_keep = max_to_keep
        self._mgr = None

    def _manager(self):
        if self._mgr is None:
            import orbax.checkpoint as ocp

            self._mgr = ocp.CheckpointManager(
                _root(self.directory),
                options=ocp.CheckpointManagerOptions(
                    max_to_keep=self._max_to_keep, create=True),
            )
        return self._mgr

    def close(self) -> None:
        if self._mgr is not None:
            self._mgr.wait_until_finished()
            self._mgr.close()
            self._mgr = None

    def flush(self) -> None:
        if self._mgr is not None:
            self._mgr.wait_until_finished()

    def latest_step(self) -> Optional[int]:
        return self._manager().latest_step()

    def all_steps(self) -> list[int]:
        return sorted(self._manager().all_steps())

    def save(self, step: int, params, meta, wait: bool) -> None:
        import orbax.checkpoint as ocp

        mgr = self._manager()
        mgr.save(step, args=ocp.args.Composite(
            params=ocp.args.StandardSave(params),
            meta=ocp.args.JsonSave(meta or {}),
        ))
        if wait:
            mgr.wait_until_finished()

    def restore_tree(self, abstract, step):
        import orbax.checkpoint as ocp

        mgr = self._manager()
        if step is None:
            step = mgr.latest_step()
        if step is None:
            return None
        restored = mgr.restore(step, args=ocp.args.Composite(
            params=ocp.args.StandardRestore(abstract),
            meta=ocp.args.JsonRestore(),
        ))
        return restored["params"], step, dict(restored["meta"] or {})

    def clear(self) -> int:
        mgr = self._manager()
        steps = list(mgr.all_steps())
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices("checkpointer_clear_snapshot")
        for s in steps:
            mgr.delete(s)
        return len(steps)


# One-shot convenience wrappers (tests, ad-hoc use). Run loops should hold
# a Checkpointer instead of paying directory scans per call.

def latest_step(directory: str) -> Optional[int]:
    """Highest committed step in ``directory``, or None if no checkpoint."""
    with Checkpointer(directory) as c:
        return c.latest_step()


def save_checkpoint(directory: str, step: int, params: Any,
                    meta: Optional[dict[str, Any]] = None,
                    max_to_keep: int = 2) -> None:
    with Checkpointer(directory, max_to_keep) as c:
        c.save(step, params, meta)


def restore_checkpoint(
    directory: str,
    cfg: BurnInConfig,
    rules=None,
    step: Optional[int] = None,
) -> Optional[tuple[Any, int, dict[str, Any]]]:
    with Checkpointer(directory) as c:
        return c.restore(cfg, rules, step)


def clear_checkpoints(directory: str) -> int:
    with Checkpointer(directory) as c:
        return c.clear()
