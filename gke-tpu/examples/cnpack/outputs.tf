# Values the operator pastes into the platform installer config — the same
# handoff shape as the reference's CNPack flow
# (/root/reference/eks/examples/cnpack/Readme.md:49-94), plus the TPU metric
# names GKE exports for the provisioned slice.

output "cluster_name" {
  description = "Name of the TPU cluster."
  value       = module.tpu_cluster.cluster_name
}

output "prometheus_service_account_email" {
  description = "GSA the monitoring KSA impersonates (annotate the KSA with this)."
  value       = google_service_account.prometheus.email
}

output "prometheus_ksa_annotation" {
  description = "Ready-to-paste Workload Identity annotation for the monitoring KSA."
  value       = "iam.gke.io/gcp-service-account: ${google_service_account.prometheus.email}"
}

output "monitoring_namespace" {
  description = "Namespace the monitoring stack must be installed into."
  value       = local.monitoring_namespace
}

output "tpu_slices" {
  description = "Slice facts (selectors, hosts, chips) for scrape-config targeting."
  value       = module.tpu_cluster.tpu_slices
}

output "tpu_metric_types" {
  description = "GKE system metrics exported for TPU nodes; use in dashboards/alerts."
  value = [
    "kubernetes.io/node/accelerator/duty_cycle",
    "kubernetes.io/node/accelerator/memory_used",
    "kubernetes.io/node/accelerator/memory_total",
    "kubernetes.io/container/accelerator/tensorcore_utilization",
  ]
}
