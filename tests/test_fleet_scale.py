# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""The elastic-fleet gate: deterministic autoscaling, warm bring-up,
and churn-composed chaos — every non-shed request bit-exact.

PR 13 proved the fleet survives UNPLANNED replica death; this suite
pins the PLANNED side (ISSUE 15): replicas joining and draining at
runtime under a deterministic load-driven policy, with the PR 14 host
tier repurposed as the warm-state migration transport. The invariants:

- **Defaults-off, twice over.** A fleet with no ``autoscale=`` is the
  PR 13 fleet (its whole suite still passes), and an ARMED policy
  whose thresholds never fire reproduces the fixed-size fleet's
  outputs, placements and shed set exactly — the elastic plane is a
  seam, never a behaviour change.
- **Bit-exact scaling.** An autoscaled run serves every request with
  tokens equal to its undisturbed solo greedy decode — scale-up
  joiners and scale-down drains move WORK, never bits (tokens are
  schedule-invariant, PR 10's contract).
- **Deterministic schedule.** (seed, policy, trace) ⇒ identical scale
  events: the policy is evaluated on the routing plan's virtual clock,
  so two runs of the same trace scale identically, like
  ``FleetFaultProfile`` kills.
- **Warm join beats cold start.** A joiner whose keyspace share is in
  the fleet's ``WarmChainStore`` seeds its HOST tier at bring-up and
  the first matching admissions swap those chains in crc-verified —
  billed in ``last_stats`` so a cold join is visible, never silent.
- **Faults compose with scaling.** Kill-during-bring-up (a fault
  aimed at a joiner id), drain-racing-kill, and join/leave churn all
  complete every non-shed request bit-exactly; a spawn that fails
  every retry is CLASSIFIED dead and its planned requests redrive.

One seeded scale-up case and one seeded churn-with-faults case are
tier-1; the matrix and the failure-injection legs are slow-marked
(the chaos-suite convention since PR 5; tier-1 budget audit, ISSUE 15).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nvidia_terraform_modules_tpu.models import (
    AutoscalePolicy,
    BurnInConfig,
    MultiProcTransport,
    WarmChainStore,
    greedy_decode,
    init_params,
    make_fleet,
)
from nvidia_terraform_modules_tpu.models.fleet import (
    FleetFault,
    FleetFaultProfile,
    HashRing,
    affinity_key,
)
from nvidia_terraform_modules_tpu.models.paging import chain_chunks, chain_key

CFG = dict(vocab=64, d_model=32, n_heads=4, d_ff=64, n_layers=2,
           seq_len=16, batch=2, dtype=jnp.float32)


@functools.lru_cache(maxsize=None)
def _setup(n=12, templates=4):
    """A multi-template workload: distinct first-block keys spread the
    keyspace across ring targets, so scale events move real shares and
    a joiner's warm take is non-trivially owned."""
    cfg = BurnInConfig(**CFG)
    params = init_params(jax.random.PRNGKey(0), cfg)
    tmpls = [jax.random.randint(jax.random.PRNGKey(3 + t), (4,), 0,
                                cfg.vocab) for t in range(templates)]
    prompts = tuple(jnp.concatenate(
        [tmpls[i % templates],
         jax.random.randint(jax.random.PRNGKey(40 + i), (1 + i % 3,), 0,
                            cfg.vocab)])
        for i in range(n))
    return cfg, params, prompts


@functools.lru_cache(maxsize=None)
def _want(n=12, templates=4, n_new=6):
    cfg, params, prompts = _setup(n, templates)
    return [greedy_decode(params, p[None, :], n_new, cfg,
                          max_len=16)[0] for p in prompts]


def _assert_all_equal(outs, want, label=""):
    for i, (g, w) in enumerate(zip(outs, want)):
        assert g is not None, f"{label} request {i} unserved"
        assert jnp.array_equal(g, w), f"{label} request {i} diverged"


# --------------------------------------------------------- policy plane


def test_autoscale_policy_validation():
    """The policy rejects shapes that cannot express a sane schedule:
    inverted bounds, oscillating thresholds, negative knobs — loudly
    at construction, like every config object in this repo."""
    AutoscalePolicy()                            # defaults are valid
    with pytest.raises(ValueError, match="min_replicas"):
        AutoscalePolicy(min_replicas=0)
    with pytest.raises(ValueError, match="max_replicas"):
        AutoscalePolicy(min_replicas=3, max_replicas=2)
    with pytest.raises(ValueError, match="oscillate"):
        AutoscalePolicy(up_backlog=1.0, down_backlog=1.0)
    with pytest.raises(ValueError, match="down_backlog"):
        AutoscalePolicy(down_backlog=-0.5, up_backlog=1.0)
    with pytest.raises(ValueError, match="cooldown"):
        AutoscalePolicy(cooldown_s=-1.0)


def test_make_fleet_autoscale_validation():
    """The fleet-level contract: a policy needs ``est_token_s`` (its
    virtual clock), refuses disaggregation (the elastic ring is the
    decode ring), and the initial size must sit inside the bounds."""
    cfg, params, _ = _setup()
    pol = AutoscalePolicy(min_replicas=2, max_replicas=4)
    with pytest.raises(ValueError, match="est_token_s"):
        make_fleet(params, cfg, max_len=16, replicas=2, autoscale=pol)
    with pytest.raises(ValueError, match="bounds"):
        make_fleet(params, cfg, max_len=16, replicas=1,
                   est_token_s=0.01, autoscale=pol)
    with pytest.raises(ValueError, match="AutoscalePolicy"):
        make_fleet(params, cfg, max_len=16, replicas=2,
                   est_token_s=0.01, autoscale="yes")
    with pytest.raises(ValueError, match="colocated"):
        make_fleet(params, cfg, max_len=16, replicas=3,
                   est_token_s=0.01, disaggregate=True,
                   prefill_workers=1,
                   autoscale=AutoscalePolicy(min_replicas=1,
                                             max_replicas=4))
    with pytest.raises(ValueError, match="warm_blocks"):
        make_fleet(params, cfg, max_len=16, replicas=2,
                   est_token_s=0.01,
                   autoscale=AutoscalePolicy(min_replicas=1,
                                             max_replicas=4),
                   warm_blocks=0)


def test_hash_ring_add_after_remove_restores_assignment():
    """The flapping-joiner pin (ISSUE 15 satellite): remove a replica
    and re-ADD it, and every key routes exactly as before the flap —
    the add-side twin of PR 13's removal-symmetry pin, the property
    that makes a rejoining replica inherit its OWN old keyspace (and
    therefore its own warm working set), not a reshuffled one."""
    ring = HashRing(4)
    keys = [affinity_key(np.arange(i, i + 6), 4) for i in range(64)]
    before = [ring.target(k) for k in keys]
    ring.remove(2)
    during = [ring.target(k) for k in keys]
    # only the removed target's keyspace moved
    for b, d in zip(before, during):
        assert b == d or b == 2
    ring.add(2)
    after = [ring.target(k) for k in keys]
    assert after == before


# ----------------------------------------------------- warm chain store


def _chain_payload(cfg, host, n_blocks, seed=0):
    """A wire-format payload of ``n_blocks`` random rows matching
    ``host``'s buffers — what ``export_block_rows`` would produce."""
    rng = np.random.default_rng(seed)
    out = {}
    for key, bufs in host.pool._bufs.items():
        out[key] = [rng.standard_normal(
            (n_blocks,) + buf.shape[1:]).astype(buf.dtype)
            for buf in bufs]
    return out


def test_warm_chain_store_publish_take_roundtrip():
    """The migration transport's core contract: published chains come
    back bitwise from ``take`` for the owner the ring assigns, takes
    COPY (two joiners can inherit the same head), and a re-publish of
    the same leaf key refreshes instead of burning rows."""
    cfg = BurnInConfig(**CFG)
    store = WarmChainStore(cfg, 8, block_size=4)
    chunks = tuple(tuple(c) for c in chain_chunks(list(range(8)), 4))
    payload = _chain_payload(cfg, store, 2, seed=1)
    assert store.publish([(chunks, payload)]) == 1
    assert store.publish([(chunks, payload)]) == 0     # refresh, no rows
    assert len(store) == 1 and store.pool.in_use == 2
    root = chain_key(chunks, 1)
    assert store.take(lambda r: r != root) == []       # not my share
    got = store.take(lambda r: r == root)
    got2 = store.take(lambda r: r == root)             # takes copy
    for out in (got, got2):
        assert len(out) == 1
        ch, pay = out[0]
        assert ch == chunks
        for key in payload:
            for a, b in zip(payload[key], pay[key]):
                assert np.array_equal(np.asarray(a), np.asarray(b))
    st = store.stats()
    assert st["taken_chains"] == 2 and st["published_chains"] == 1
    store.clear()
    assert store.pool.in_use == 0


def test_warm_chain_store_capacity_keeps_hot_head_drops_cold_tail():
    """Publishing is best-effort by design, and the squeeze keeps the
    POPULAR HEAD: a batch arrives hottest-first (export_chains' MRU
    order), so under capacity pressure the COLD TAIL is what evicts
    and drops — and a chain bigger than the whole pool is refused up
    front (billed), never allowed to evict everything and then fail
    anyway. The store never blocks or raises."""
    cfg = BurnInConfig(**CFG)
    store = WarmChainStore(cfg, 2, block_size=4)
    hot = tuple(tuple(c) for c in chain_chunks([1] * 4, 4))
    mid = tuple(tuple(c) for c in chain_chunks([2] * 4, 4))
    cold = tuple(tuple(c) for c in chain_chunks([3] * 4, 4))
    # 3 one-block chains, hottest first, into a 2-block pool: every
    # adopt lands (cold first by reverse insert, then mid, then hot
    # evicting cold) — but the SURVIVORS are the hot head
    assert store.publish(
        [(hot, _chain_payload(cfg, store, 1, 1)),
         (mid, _chain_payload(cfg, store, 1, 2)),
         (cold, _chain_payload(cfg, store, 1, 3))]) == 3
    from nvidia_terraform_modules_tpu.models.paging import chain_key
    with store._lock:
        kept = set(store._chains)
    assert kept == {chain_key(hot), chain_key(mid)}
    assert store.pool.in_use == 2
    # a chain bigger than the WHOLE pool: refused up front, billed,
    # and the stored head is untouched
    big = tuple(tuple(c) for c in chain_chunks(list(range(12)), 4))
    assert store.publish([(big, _chain_payload(cfg, store, 3, 4))]) == 0
    assert store.stats()["store_full_drops"] == 1
    assert len(store) == 2 and store.pool.in_use == 2
    store.clear()
    assert store.pool.in_use == 0


def test_warm_chain_store_dedups_shared_template_prefix():
    """The Zipf-head economics the store exists for: chains sharing a
    template prefix share its ROWS (per-node refcounts), so a popular
    template with L divergent suffixes costs ~B+L rows, never B×L —
    and dropping one leaf frees only the unshared suffix row while
    the shared head keeps serving the surviving chains."""
    cfg = BurnInConfig(**CFG)
    store = WarmChainStore(cfg, 8, block_size=4)
    tmpl = [7] * 4                                # 1 shared block
    chains = []
    for sfx in (1, 2, 3):
        chunks = tuple(tuple(c)
                       for c in chain_chunks(tmpl + [sfx] * 4, 4))
        chains.append((chunks, _chain_payload(cfg, store, 2, sfx)))
    assert store.publish(chains) == 3
    # 3 chains × 2 blocks each, but the template row is shared:
    # 1 shared head + 3 suffix rows
    assert len(store) == 3 and store.pool.in_use == 4
    got = store.take(lambda r: True)
    assert len(got) == 3
    for (chunks, pay), (chunks0, _p) in zip(sorted(got), sorted(chains)):
        assert np.asarray(pay["k"][0]).shape[0] == 2
    with store._lock:
        store._drop_chain_locked(next(iter(store._chains)))
    assert store.pool.in_use == 3                 # suffix row freed,
    store.clear()                                 # head row retained
    assert store.pool.in_use == 0


def test_warm_chain_store_corrupt_chain_never_migrates():
    """Host RAM is not trustworthy at fleet scale: a stored chain
    whose bytes moved under the crc is DROPPED at take (billed in
    ``corrupt_dropped``) — quarantine discipline, suspect bytes never
    reach a joiner's pool."""
    cfg = BurnInConfig(**CFG)
    store = WarmChainStore(cfg, 4, block_size=4)
    chunks = tuple(tuple(c) for c in chain_chunks(list(range(4)), 4))
    store.publish([(chunks, _chain_payload(cfg, store, 1, 4))])
    hid = next(iter(store._rows.values()))[0]
    store.pool._bufs["k"][0][hid, 0, 0, 0] += 1
    assert store.take(lambda r: True) == []
    st = store.stats()
    assert st["corrupt_dropped"] == 1 and st["chains"] == 0
    assert store.pool.in_use == 0                  # rows released


# ------------------------------------------------------ tier-1 gates


def test_fleet_no_scale_event_schedule_matches_fixed_fleet_tier1():
    """THE defaults-off acceptance gate (ISSUE 15): an armed policy
    whose thresholds never fire — and whose bounds pin the size —
    reproduces the PR 13 fixed fleet byte for byte: same tokens, same
    placements, same (empty) shed set, and an all-zero scale ledger."""
    cfg, params, prompts = _setup()
    want = _want()
    base = make_fleet(params, cfg, max_len=16, replicas=2, kv_block=4,
                      est_token_s=0.01, steal=False)
    got_base = base(prompts, 6, slots=2)
    _assert_all_equal(got_base, want, "fixed:")
    bst = base.last_stats["fleet"]
    assert bst["scale"] is None
    pol = AutoscalePolicy(min_replicas=2, max_replicas=4,
                          up_backlog=1e9, down_backlog=0.0, seed=3)
    elastic = make_fleet(params, cfg, max_len=16, replicas=2,
                         kv_block=4, est_token_s=0.01, steal=False,
                         autoscale=pol)
    got = elastic(prompts, 6, slots=2)
    _assert_all_equal(got, want, "no-event policy:")
    est = elastic.last_stats["fleet"]
    assert est["routed_to"] == bst["routed_to"]
    assert est["shed_requests"] == bst["shed_requests"] == []
    sc = est["scale"]
    assert sc["events"] == [] and sc["ups_planned"] == 0
    assert sc["downs"] == 0 and sc["final_live"] == sc["initial"] == 2
    assert sc["warm_joins"] == 0 and sc["spawn_failures"] == 0


def test_fleet_scale_up_warm_inherit_bit_exact_tier1():
    """THE seeded scale-up gate (ISSUE 15 acceptance): a 1-replica
    fleet under a backlog burst joins replicas up to ``max_replicas``
    at admission-poll boundaries, every request bit-matches its solo
    greedy decode (the fixed-size fleet's own gate — so autoscaled ==
    fixed per request, transitively), the scale schedule replays
    identically, and a SECOND run's joiners inherit the published
    working set warm: host-tier chains seeded at bring-up, swapped in
    through the crc-verified tiered path, billed as prefix hits."""
    cfg, params, prompts = _setup(n=18, templates=6)
    want = _want(n=18, templates=6)
    pol = AutoscalePolicy(min_replicas=1, max_replicas=3,
                          up_backlog=2.0, down_backlog=0.25,
                          cooldown_s=0.0, seed=0)
    fleet = make_fleet(params, cfg, max_len=16, replicas=1, kv_block=4,
                       est_token_s=0.01, autoscale=pol, steal=False,
                       share_prefix=True, host_spill=True,
                       host_blocks=64, prefix_keep_blocks=16)
    got = fleet(prompts, 6, slots=2)
    _assert_all_equal(got, want, "scale-up:")
    st = fleet.last_stats["fleet"]
    sc = st["scale"]
    assert st["served"] == len(prompts) and st["shed"] == 0
    assert sc["ups_executed"] == sc["ups_planned"] == 2
    assert sc["final_live"] == 3 and sc["spawn_failures"] == 0
    assert [e["trigger"] for e in sc["events"]
            if e["kind"] == "up"] == ["backlog", "backlog"]
    events1 = sc["events"]
    # every replica drained its pool (the leak invariant crosses the
    # elastic plane unchanged)
    for rs in fleet.last_stats["replica_stats"]:
        if rs is not None:
            assert rs["kv"]["in_use"] == 0
    # the run's close published the retained working set fleet-wide
    assert sc["warm_store"]["chains"] > 0
    # round 2: same trace ⇒ same schedule (determinism), and the
    # joiners now take their keyspace share WARM from the store
    got2 = fleet(prompts, 6, slots=2)
    _assert_all_equal(got2, want, "scale-up round 2:")
    sc2 = fleet.last_stats["fleet"]["scale"]
    assert sc2["events"] == events1
    assert sc2["warm_joins"] >= 1 and sc2["warm_chains_primed"] >= 1
    warm = [rs["prefix"]["warm"]
            for rs in fleet.last_stats["replica_stats"] if rs]
    assert sum(w["seeded_chains"] for w in warm) >= 1
    assert sum(w["seeded_blocks"] for w in warm) >= 1
    # the seeded chains were HIT through the tiered swap-in path —
    # warm bring-up converts to real prefix hits, not just bytes
    spill = fleet.last_stats["fleet"]["spill"]
    assert spill["host_hit_blocks"] >= 1


def test_fleet_scale_churn_with_faults_bit_exact_tier1():
    """THE seeded churn gate (ISSUE 15 acceptance): burst → idle →
    burst arrivals drive join/drain churn while a fault profile lands
    BOTH hard compositions — a kill aimed at a not-yet-joined replica
    (kill-during-bring-up) and a drain racing it on the base replica
    (drain-racing-kill) — and every request still completes bit-exact,
    with the whole (policy, profile, trace) triple replaying
    identically."""
    cfg, params, prompts = _setup(n=20)
    want = _want(n=20)
    # burst → sparse → burst → sparse: joins under both bursts, policy
    # drains in both gaps, while the profile drains base replica 0 and
    # kills joiner 2 during its bring-up window
    arrivals = tuple([0.0] * 6 + [0.6 + 0.05 * i for i in range(4)]
                     + [1.4 + 0.03 * i for i in range(5)]
                     + [2.2 + 0.2 * i for i in range(5)])
    pol = AutoscalePolicy(min_replicas=1, max_replicas=4,
                          up_backlog=2.0, down_backlog=0.4,
                          cooldown_s=0.05, seed=0)
    profile = FleetFaultProfile(
        [FleetFault("drain_replica", target=0, at_s=0.05),
         FleetFault("kill_replica", target=2, at_s=0.06)], seed=1)
    fleet = make_fleet(params, cfg, max_len=16, replicas=2, kv_block=4,
                       est_token_s=0.02, autoscale=pol, faults=profile,
                       steal=False)
    got = fleet(prompts, 6, slots=2, arrivals=arrivals)
    _assert_all_equal(got, want, "churn:")
    st = fleet.last_stats["fleet"]
    sc, fr = st["scale"], st["faults"]
    assert st["served"] == len(prompts) and st["shed"] == 0
    # replica 2 is a JOINER (base size 2): the kill could only land
    # during/after its bring-up — the composition the gate exists for
    assert fr["killed"] == ["replica-2"]
    assert fr["drained"] == ["replica-0"]
    assert sc["ups_executed"] >= 2 and sc["downs"] >= 1
    assert len(sc["scaled_down"]) == sc["downs"]
    assert fr["redriven"] >= 1
    # replay: the full composed schedule is deterministic
    got2 = fleet(prompts, 6, slots=2, arrivals=arrivals)
    _assert_all_equal(got2, want, "churn replay:")
    st2 = fleet.last_stats["fleet"]
    assert st2["scale"]["events"] == sc["events"]
    assert st2["faults"]["killed"] == fr["killed"]


def test_fleet_scale_up_proc_warm_inherit_bit_exact_tier1():
    """THE proc-autoscale acceptance gate (ISSUE 18): the elastic
    control loop runs UNCHANGED over real processes — a scale-up
    spawns a real child, the joiner's keyspace share of the warm store
    ships as crc-stamped chain frames over the pipe, and every request
    bit-matches solo greedy AND the in-proc elastic fleet (same
    events, same tokens). Round 2's joiner is WARM: chains seeded over
    the wire convert to real host-tier prefix hits."""
    cfg, params, prompts = _setup(n=18, templates=6)
    want = _want(n=18, templates=6)

    def _pol():
        # the SAME policy as the in-proc warm-inherit gate above, so
        # the two fleets' scale schedules are comparable event-for-
        # event (and the joiners' union keyspace share is known to
        # own stored roots)
        return AutoscalePolicy(min_replicas=1, max_replicas=3,
                               up_backlog=2.0, down_backlog=0.25,
                               cooldown_s=0.0, seed=0)

    kw = dict(max_len=16, replicas=1, kv_block=4, est_token_s=0.01,
              steal=False, share_prefix=True, host_spill=True,
              host_blocks=64, prefix_keep_blocks=16)
    fl_in = make_fleet(params, cfg, autoscale=_pol(), **kw)
    _assert_all_equal(fl_in(prompts, 6, slots=2), want, "inproc:")
    events_in = fl_in.last_stats["fleet"]["scale"]["events"]

    tr = MultiProcTransport()
    fleet = make_fleet(params, cfg, autoscale=_pol(), transport=tr,
                       join_timeout_s=240.0, **kw)
    try:
        got = fleet(prompts, 6, slots=2)
        _assert_all_equal(got, want, "proc scale-up:")
        st = fleet.last_stats["fleet"]
        sc = st["scale"]
        assert st["served"] == len(prompts) and st["shed"] == 0
        assert sc["ups_executed"] == sc["ups_planned"] == 2
        assert sc["spawn_failures"] == 0
        # the scale SCHEDULE is transport-invariant (pure function of
        # the trace), and every joiner is a real child process
        assert sc["events"] == events_in
        assert sorted(tr._children) == [0, 1, 2]
        # run close published the retained working set over the wire
        # (publish_chains RPC from each child)
        assert sc["warm_store"]["chains"] > 0

        # round 2: same trace ⇒ same schedule; the joiner now takes
        # its share WARM — chain frames over the pipe, seeded into the
        # child's host tier, swapped in as real prefix hits
        got2 = fleet(prompts, 6, slots=2)
        _assert_all_equal(got2, want, "proc scale-up round 2:")
        sc2 = fleet.last_stats["fleet"]["scale"]
        assert sc2["events"] == events_in
        assert sc2["warm_joins"] >= 1 and sc2["warm_chains_primed"] >= 1
        warm = [rs["prefix"]["warm"]
                for rs in fleet.last_stats["replica_stats"] if rs]
        assert sum(w["seeded_chains"] for w in warm) >= 1
        assert sum(w["seeded_blocks"] for w in warm) >= 1
        spill = fleet.last_stats["fleet"]["spill"]
        assert spill["host_hit_blocks"] >= 1
    finally:
        fleet.close()
    assert tr._children == {}


# ------------------------------------------------------- slow matrix


@pytest.mark.slow
def test_fleet_autoscaled_equals_fixed_fleet_per_request():
    """The direct form of the undisturbed-trace acceptance gate: the
    autoscaled fleet's per-request outputs equal the FIXED fleet's on
    the same trace (not just solo — the two fleets are compared to
    each other), shed sets included."""
    cfg, params, prompts = _setup()
    fixed = make_fleet(params, cfg, max_len=16, replicas=3, kv_block=4,
                       est_token_s=0.01, steal=False)
    got_fixed = fixed(prompts, 6, slots=2)
    pol = AutoscalePolicy(min_replicas=1, max_replicas=3,
                          up_backlog=2.0, down_backlog=0.25,
                          cooldown_s=0.0, seed=0)
    elastic = make_fleet(params, cfg, max_len=16, replicas=1,
                         kv_block=4, est_token_s=0.01, autoscale=pol,
                         steal=False)
    got = elastic(prompts, 6, slots=2)
    assert elastic.last_stats["fleet"]["scale"]["ups_executed"] >= 1
    for i, (g, w) in enumerate(zip(got, got_fixed)):
        assert (g is None) == (w is None), f"shed set diverged at {i}"
        if g is not None:
            assert jnp.array_equal(g, w), f"request {i} diverged"


@pytest.mark.slow
def test_fleet_spawn_failure_is_classified_and_redrives():
    """A joiner whose engine build fails EVERY retry is classified
    dead — its planned requests redrive to survivors (bit-exact), the
    failure and its retries are billed, and the run completes instead
    of hanging on a replica that never came up."""
    import nvidia_terraform_modules_tpu.models.fleet as fleet_mod

    cfg, params, prompts = _setup()
    want = _want()
    pol = AutoscalePolicy(min_replicas=1, max_replicas=2,
                          up_backlog=2.0, down_backlog=0.25,
                          cooldown_s=0.0, seed=0)
    fleet = make_fleet(params, cfg, max_len=16, replicas=1, kv_block=4,
                       est_token_s=0.01, autoscale=pol, steal=False)
    real = fleet_mod.make_serve_engine
    fleet_mod.make_serve_engine = _always_fails
    try:
        got = fleet(prompts, 6, slots=2)
    finally:
        fleet_mod.make_serve_engine = real
    _assert_all_equal(got, want, "spawn failure:")
    st = fleet.last_stats["fleet"]
    sc = st["scale"]
    assert sc["ups_planned"] >= 1 and sc["ups_executed"] == 0
    assert sc["spawn_failures"] >= 1 and sc["spawn_retries"] >= 1
    assert st["served"] == len(prompts)
    # the dead joiner is visible, its planned requests were redriven
    dead = [r for r in st["per_replica"] if r.get("spawned") is False]
    assert len(dead) >= 1 and all(r["dead"] for r in dead)


def _always_fails(*a, **k):
    raise RuntimeError("injected spawn failure")


@pytest.mark.slow
def test_fleet_spawn_transient_failure_retries_then_joins():
    """The retry half of the spawn contract: a build that fails once
    and then succeeds costs a billed retry, never the ring its joiner
    — the fleet still scales up and serves bit-exactly."""
    import nvidia_terraform_modules_tpu.models.fleet as fleet_mod

    cfg, params, prompts = _setup()
    want = _want()
    pol = AutoscalePolicy(min_replicas=1, max_replicas=2,
                          up_backlog=2.0, down_backlog=0.25,
                          cooldown_s=0.0, seed=0)
    fleet = make_fleet(params, cfg, max_len=16, replicas=1, kv_block=4,
                       est_token_s=0.01, autoscale=pol, steal=False)
    real = fleet_mod.make_serve_engine
    state = {"n": 0}

    def flaky(*a, **k):
        state["n"] += 1
        if state["n"] == 1:
            raise RuntimeError("transient build failure")
        return real(*a, **k)

    fleet_mod.make_serve_engine = flaky
    try:
        got = fleet(prompts, 6, slots=2)
    finally:
        fleet_mod.make_serve_engine = real
    _assert_all_equal(got, want, "flaky spawn:")
    sc = fleet.last_stats["fleet"]["scale"]
    assert sc["ups_executed"] >= 1
    assert sc["spawn_retries"] >= 1 and sc["spawn_failures"] == 0


@pytest.mark.slow
def test_fleet_scale_down_drains_and_publishes():
    """A scale-down is a PLANNED drain: the drained replica finishes
    its in-flight work (never marked dead), its queued work moves, the
    fleet-size ledger shrinks, and its retained chains land in the
    warm store for successors — billed in ``published_chains``."""
    cfg, params, prompts = _setup(n=16)
    want = _want(n=16)
    arrivals = tuple([0.0] * 6 + [0.8 + 0.1 * i for i in range(10)])
    pol = AutoscalePolicy(min_replicas=1, max_replicas=3,
                          up_backlog=2.0, down_backlog=0.5,
                          cooldown_s=0.05, seed=0)
    fleet = make_fleet(params, cfg, max_len=16, replicas=2, kv_block=4,
                       est_token_s=0.02, autoscale=pol, steal=False,
                       share_prefix=True, host_spill=True,
                       host_blocks=64, prefix_keep_blocks=16)
    got = fleet(prompts, 6, slots=2, arrivals=arrivals)
    _assert_all_equal(got, want, "scale-down:")
    st = fleet.last_stats["fleet"]
    sc = st["scale"]
    assert sc["downs"] >= 1 and len(sc["scaled_down"]) >= 1
    assert sc["final_live"] < sc["initial"] + sc["ups_executed"]
    # a scale-down is not degradation: no faults armed, so no fault
    # record at all — and the drained replica reports stats (alive)
    assert st["faults"] is None
    by_label = {r["replica"]: r for r in st["per_replica"]}
    for lbl in sc["scaled_down"]:
        assert by_label[lbl]["dead"] is False
    assert sc["warm_store"]["chains"] >= 1


@pytest.mark.slow
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_fleet_scale_churn_matrix(seed):
    """Preemption-churn storms across seeds: seeded join/leave churn
    from ``fault_times``-style bursty arrivals composes with a seeded
    kill, and every seed's every request stays bit-exact."""
    from nvidia_terraform_modules_tpu.utils.traffic import (
        fault_times,
        poisson_trace,
    )

    cfg, params, prompts = _setup(n=16)
    want = _want(n=16)
    arrivals = tuple(poisson_trace(30.0, len(prompts),
                                   seed=f"churn-{seed}"))
    kill_at = fault_times(arrivals, 1, seed=f"churn-kill-{seed}")[0]
    pol = AutoscalePolicy(min_replicas=1, max_replicas=3,
                          up_backlog=2.0, down_backlog=0.4,
                          cooldown_s=0.03, seed=seed)
    profile = FleetFaultProfile(
        [FleetFault("kill_replica", target=1, at_s=kill_at)],
        seed=seed)
    fleet = make_fleet(params, cfg, max_len=16, replicas=1, kv_block=4,
                       est_token_s=0.02, autoscale=pol, faults=profile,
                       steal=False)
    got = fleet(prompts, 6, slots=2, arrivals=arrivals)
    _assert_all_equal(got, want, f"churn seed {seed}:")
    st = fleet.last_stats["fleet"]
    assert st["served"] == len(prompts) and st["shed"] == 0


@pytest.mark.slow
def test_fleet_elastic_fault_target_beyond_realised_fleet_raises():
    """Per-call validation (the elastic twin of resolve-time shape
    checks): a fault aimed at a replica id the realised fleet never
    reaches — the policy joined fewer than the target needs — is a
    loud error naming the realised size, never a silently unfired
    fault."""
    cfg, params, prompts = _setup()
    pol = AutoscalePolicy(min_replicas=2, max_replicas=4,
                          up_backlog=1e9, down_backlog=0.0, seed=0)
    profile = FleetFaultProfile(
        [FleetFault("kill_replica", target=3, at_s=0.05)], seed=0)
    fleet = make_fleet(params, cfg, max_len=16, replicas=2, kv_block=4,
                       est_token_s=0.01, autoscale=pol, faults=profile,
                       steal=False)
    with pytest.raises(ValueError, match="realises only 2"):
        fleet(prompts, 6, slots=2)


# -------------------------------------------- slow matrix over processes


@pytest.mark.slow
def test_fleet_proc_kill_during_warm_join_discards_partial_seed_slow():
    """SIGKILL-during-warm-join over real processes (ISSUE 18
    acceptance): the joiner dies — for real — while (or right after)
    its warm chains cross the pipe. The partial seed dies with the
    child (the store's ``take`` copies, so fleet state is untouched),
    its requests redrive to the survivor, zero strand / zero double
    (served == submitted; the fleet's duplicate check makes
    double-serving a hard error), and outputs bit-match undisturbed
    solo decode. Round 1 arms the same kill cold (empty store), round
    2 is the warm-join composition proper."""
    cfg, params, prompts = _setup(n=18, templates=6)
    want = _want(n=18, templates=6)
    # the warm-inherit gate's policy: three members, and joiner 2's
    # keyspace share is the one that owns stored roots — so target=2
    # kills the WARM joiner specifically
    pol = AutoscalePolicy(min_replicas=1, max_replicas=3,
                          up_backlog=2.0, down_backlog=0.25,
                          cooldown_s=0.0, seed=0)
    profile = FleetFaultProfile(
        [FleetFault("kill_replica", target=2, at_s=0.05)], seed=0)
    tr = MultiProcTransport()
    fleet = make_fleet(params, cfg, max_len=16, replicas=1, kv_block=4,
                       est_token_s=0.01, autoscale=pol, faults=profile,
                       steal=False, share_prefix=True, host_spill=True,
                       host_blocks=64, prefix_keep_blocks=16,
                       transport=tr, join_timeout_s=240.0)
    try:
        got = fleet(prompts, 6, slots=2)
        _assert_all_equal(got, want, "cold kill-join:")
        st = fleet.last_stats["fleet"]
        assert st["served"] == len(prompts) and st["shed"] == 0
        assert st["faults"]["killed"] == ["replica-2"]
        # the survivors' closes still published the working set
        assert st["scale"]["warm_store"]["chains"] > 0

        got2 = fleet(prompts, 6, slots=2)
        _assert_all_equal(got2, want, "warm kill-join:")
        st2 = fleet.last_stats["fleet"]
        sc2 = st2["scale"]
        assert st2["served"] == len(prompts) and st2["shed"] == 0
        assert st2["faults"]["killed"] == ["replica-2"]
        assert st2["faults"]["redriven"] >= 1
        # the join WAS warm when the kill landed: chains were primed
        # for the joiner, and losing it stranded nothing
        assert sc2["warm_joins"] >= 1
        assert sc2["warm_store"]["chains"] > 0
    finally:
        tr.close()


@pytest.fixture(scope="module")
def shared_proc_transport():
    tr = MultiProcTransport()
    yield tr
    tr.close()


@pytest.mark.slow
@pytest.mark.parametrize("seed", [0, 1])
def test_fleet_proc_churn_drain_racing_kill_matrix_slow(
        seed, shared_proc_transport):
    """The drain-racing-kill + kill-during-bring-up composition of the
    tier-1 churn gate, rerun over REAL processes per profile seed: the
    base replica drains while a joiner is killed during its bring-up
    window — a real SIGKILL of a real child — and every request still
    completes bit-exact. One shared transport amortises spawns across
    seeds."""
    cfg, params, prompts = _setup(n=20)
    want = _want(n=20)
    arrivals = tuple([0.0] * 6 + [0.6 + 0.05 * i for i in range(4)]
                     + [1.4 + 0.03 * i for i in range(5)]
                     + [2.2 + 0.2 * i for i in range(5)])
    pol = AutoscalePolicy(min_replicas=1, max_replicas=4,
                          up_backlog=2.0, down_backlog=0.4,
                          cooldown_s=0.05, seed=0)
    profile = FleetFaultProfile(
        [FleetFault("drain_replica", target=0, at_s=0.05),
         FleetFault("kill_replica", target=2, at_s=0.06)], seed=seed)
    tr = shared_proc_transport
    fleet = make_fleet(params, cfg, max_len=16, replicas=2, kv_block=4,
                       est_token_s=0.02, autoscale=pol, faults=profile,
                       steal=False, transport=tr, join_timeout_s=240.0)
    label = f"proc churn seed {seed}:"
    got = fleet(prompts, 6, slots=2, arrivals=arrivals)
    _assert_all_equal(got, want, label)
    st = fleet.last_stats["fleet"]
    assert st["served"] == len(prompts) and st["shed"] == 0, label
    assert st["faults"]["killed"] == ["replica-2"], label
    assert st["faults"]["drained"] == ["replica-0"], label
    assert st["scale"]["ups_executed"] >= 2, label


@pytest.mark.slow
def test_fleet_proc_spawn_retry_exhaustion_classified_slow():
    """Spawn-retry-exhaustion-during-churn over processes (ISSUE 18
    acceptance): a joiner whose process spawn fails EVERY attempt is
    classified dead — never a hang — its planned requests redrive to
    the live children, the failure is billed, and outputs bit-match
    solo. The base replica's child is brought up FIRST so only the
    joiner's spawn path is poisoned."""
    from nvidia_terraform_modules_tpu.models.transport import TransportDead

    cfg, params, prompts = _setup()
    want = _want()
    pol = AutoscalePolicy(min_replicas=1, max_replicas=2,
                          up_backlog=2.0, down_backlog=0.25,
                          cooldown_s=0.0, seed=0)
    tr = MultiProcTransport()
    fleet = make_fleet(params, cfg, max_len=16, replicas=1, kv_block=4,
                       est_token_s=0.01, autoscale=pol, steal=False,
                       transport=tr, join_timeout_s=240.0)
    try:
        tr.ensure_engine(0)              # base child up before poisoning
        real_spawn = tr._spawn

        def fail_spawn(i):
            raise TransportDead(f"injected spawn failure replica-{i}")

        tr._spawn = fail_spawn
        try:
            got = fleet(prompts, 6, slots=2)
        finally:
            tr._spawn = real_spawn
        _assert_all_equal(got, want, "proc spawn exhaustion:")
        st = fleet.last_stats["fleet"]
        sc = st["scale"]
        assert sc["ups_planned"] >= 1 and sc["ups_executed"] == 0
        assert sc["spawn_failures"] >= 1
        assert st["served"] == len(prompts) and st["shed"] == 0
        dead = [r for r in st["per_replica"]
                if r.get("spawned") is False]
        assert len(dead) >= 1 and all(r["dead"] for r in dead)
        assert sorted(tr._children) == [0]   # no half-spawned child
    finally:
        tr.close()


@pytest.mark.slow
def test_fleet_proc_churn_storm_bit_exact_slow():
    """A poisson churn storm over real processes: seeded bursty
    arrivals drive join/leave churn while a seeded kill lands on a
    joiner — the full (policy, profile, trace) composition over the
    multiproc wire stays bit-exact."""
    from nvidia_terraform_modules_tpu.utils.traffic import (
        fault_times,
        poisson_trace,
    )

    cfg, params, prompts = _setup(n=16)
    want = _want(n=16)
    arrivals = tuple(poisson_trace(30.0, len(prompts), seed="churn-0"))
    kill_at = fault_times(arrivals, 1, seed="churn-kill-0")[0]
    pol = AutoscalePolicy(min_replicas=1, max_replicas=3,
                          up_backlog=2.0, down_backlog=0.4,
                          cooldown_s=0.03, seed=0)
    profile = FleetFaultProfile(
        [FleetFault("kill_replica", target=1, at_s=kill_at)], seed=0)
    tr = MultiProcTransport()
    fleet = make_fleet(params, cfg, max_len=16, replicas=1, kv_block=4,
                       est_token_s=0.02, autoscale=pol, faults=profile,
                       steal=False, transport=tr, join_timeout_s=240.0)
    try:
        got = fleet(prompts, 6, slots=2, arrivals=arrivals)
        _assert_all_equal(got, want, "proc churn storm:")
        st = fleet.last_stats["fleet"]
        assert st["served"] == len(prompts) and st["shed"] == 0
    finally:
        tr.close()
