# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""graftlint — the runtime-aware static-analysis pass (tier-1 gate).

Four layers:

1. Per-rule contracts: every ``graft-*`` rule has a positive test (the
   violation idiom is caught) and a negative test (the clean idiom the
   runtime actually uses passes). All pure-AST — no jax needed.
2. The shared engine through the Python front-end: severity overrides,
   ``off``, suppression comment semantics (trailing / standalone /
   wildcard), CLI exit codes, and the bad ``-severity`` diagnostic.
3. The concurrency layer: static lock-order graph (cycles, Condition
   aliasing, cross-file method resolution) and the runtime lock-order
   watchdog (edge recording, cycle verdicts, lock-held sleeps, clean
   factory restore).
4. The package gate: ``run_graftlint`` over the real package must be
   CLEAN, with every inline suppression counted, capped at 10, and
   carrying a reason string — plus the combined HCL+Python golden that
   pins the unified Finding schema across both rule packs.
"""

import json
import os
import textwrap
import threading
import time

import pytest

from nvidia_terraform_modules_tpu.analysis import (
    Finding,
    PyContext,
    exit_code,
    list_rules,
    run_graftlint,
)
from nvidia_terraform_modules_tpu.analysis import lockwatch
from nvidia_terraform_modules_tpu.analysis.__main__ import main as graft_main
from nvidia_terraform_modules_tpu.analysis.core import (
    findings_json,
    sarif_report,
)
from nvidia_terraform_modules_tpu.analysis.graftlint import RULES
from nvidia_terraform_modules_tpu.analysis.lockgraph import build_lock_graph

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GOLDEN = os.path.join(os.path.dirname(os.path.abspath(__file__)), "golden")
PKG = os.path.join(ROOT, "nvidia_terraform_modules_tpu")

# the two CLIs' suffix bindings, combined for the unified-schema golden
_SUFFIXES = (".py", ".tf", ".tfvars", ".hcl", ".example")


def lint(tmp_path, files, overrides=None):
    """Write a synthetic tree under tmp and graftlint it; findings carry
    tmp-relative wheres like ``src/mod.py:3``."""
    root = tmp_path / "src"
    for rel, body in files.items():
        p = root / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(body))
    return run_graftlint(str(root), rel_to=str(tmp_path),
                         overrides=overrides)


def hit(findings, rule):
    return [f for f in findings if f.rule == rule]


# ===================================================== rule: unseeded-rng

def test_unseeded_rng_positive(tmp_path):
    fs = lint(tmp_path, {"rng.py": """\
        import random
        import numpy as np

        def draw():
            r = random.Random()
            g = np.random.default_rng(42)
            h = random.Random(hash("salt"))
            random.seed(0)
            x = np.random.normal()
            return r.random() + g.random() + h.random() + x
        """})
    msgs = [f.message for f in hit(fs, "graft-unseeded-rng")]
    assert any("seedless random.Random()" in m for m in msgs)
    assert any("integer-literal seed" in m for m in msgs)
    assert any("PYTHONHASHSEED" in m for m in msgs)
    assert any("reseeds the shared global RNG" in m for m in msgs)
    assert any("draws from the shared global RNG" in m for m in msgs)
    assert all(f.severity == "error" for f in hit(fs, "graft-unseeded-rng"))


def test_unseeded_rng_negative(tmp_path):
    # the string-seeded convention the runtime uses everywhere
    fs = lint(tmp_path, {"rng.py": """\
        import random
        import numpy as np

        def draw(salt, seed):
            r = random.Random(f"{salt}-{seed}")
            g = np.random.default_rng(derive(salt))
            return r.random() + g.random()

        def derive(salt):
            return len(salt)
        """})
    assert hit(fs, "graft-unseeded-rng") == []


def test_unseeded_rng_resolves_import_aliases(tmp_path):
    fs = lint(tmp_path, {"rng.py": """\
        from random import Random

        R = Random()
        """})
    assert len(hit(fs, "graft-unseeded-rng")) == 1
    assert fs[0].where == "src/rng.py:3"


# ============================================== rule: host-sync-in-loop

def test_host_sync_in_traced_body_positive(tmp_path):
    fs = lint(tmp_path, {"step.py": """\
        import jax

        @jax.jit
        def bad(x):
            return x.item()

        def scan_bad(xs):
            def body(c, x):
                return c, float(x)
            return jax.lax.scan(body, 0, xs)
        """})
    found = hit(fs, "graft-host-sync-in-loop")
    assert any(".item()" in f.message and "traced" in f.message
               for f in found)
    assert any("float()" in f.message and "traced" in f.message
               for f in found)


def test_host_sync_in_wave_loop_positive(tmp_path):
    fs = lint(tmp_path, {"wave.py": """\
        import jax
        import numpy as np

        @jax.jit
        def step(s):
            return s

        def run(xs):
            out = []
            for x in xs:
                s = step(x)
                out.append(np.asarray(s))
            return out
        """})
    found = hit(fs, "graft-host-sync-in-loop")
    assert len(found) == 1
    assert "wave loop driving a jitted step" in found[0].message


def test_host_sync_negative(tmp_path):
    # sync AFTER the loop, float() casts on host, loops with no jitted
    # step — all clean
    fs = lint(tmp_path, {"wave.py": """\
        import jax
        import numpy as np

        @jax.jit
        def step(s):
            return s

        def run(xs):
            acc = None
            for x in xs:
                acc = step(x)
                loss = float(len(xs))
            return np.asarray(acc)

        def plain(items):
            return [i.item() for i in items]
        """})
    assert hit(fs, "graft-host-sync-in-loop") == []


# ===================================================== rule: wallclock

def test_wallclock_positive(tmp_path):
    fs = lint(tmp_path, {"engine.py": """\
        import time

        def stamp():
            return time.time()

        def tick():
            return time.monotonic()
        """})
    found = hit(fs, "graft-wallclock-nondeterminism")
    assert len(found) == 2
    assert all("allowlist" in f.message for f in found)
    assert all(f.severity == "warning" for f in found)


def test_wallclock_allowlists(tmp_path):
    # telemetry/ owns the clock; models/fleet.py may use INTERVAL clocks
    # (real poll deadlines) but never epoch clocks
    fs = lint(tmp_path, {
        "telemetry/clock.py": """\
            import time

            def now():
                return time.time()
            """,
        "models/fleet.py": """\
            import time

            def deadline():
                return time.monotonic() + 1.0

            def stamp():
                return time.time()
            """})
    found = hit(fs, "graft-wallclock-nondeterminism")
    assert len(found) == 1
    assert found[0].where == "src/models/fleet.py:7"
    assert "time.time" in found[0].message


def test_wallclock_default_arg_and_traced_flagged_everywhere(tmp_path):
    # even inside the telemetry allowlist: a default-arg clock is frozen
    # at import, a traced clock is baked into the jaxpr
    fs = lint(tmp_path, {"telemetry/clock.py": """\
        import time
        import jax

        def log(t=time.time()):
            return t

        @jax.jit
        def traced(x):
            return x + time.time()
        """})
    msgs = [f.message for f in hit(fs, "graft-wallclock-nondeterminism")]
    assert len(msgs) == 2
    assert any("default-argument" in m for m in msgs)
    assert any("trace-time constant" in m for m in msgs)


def test_wallclock_reference_not_call_is_clean(tmp_path):
    # clock INJECTION (`clock=time.time` as a default callable) is the
    # fixed idiom — passing the function is not reading the clock
    fs = lint(tmp_path, {"hb.py": """\
        import time

        class Heartbeat:
            def __init__(self, clock=time.time):
                self._clock = clock
        """})
    assert hit(fs, "graft-wallclock-nondeterminism") == []


# ================================================== rule: silent-except

def test_silent_except_positive(tmp_path):
    fs = lint(tmp_path, {"errs.py": """\
        def a():
            try:
                work()
            except:
                pass

        def b():
            try:
                work()
            except Exception:
                pass

        def c():
            try:
                work()
            except (ValueError, Exception) as e:
                pass
        """})
    found = hit(fs, "graft-silent-except")
    assert len(found) == 3
    assert any("bare except" in f.message for f in found)
    assert sum("swallows the error" in f.message for f in found) == 2


def test_silent_except_negative(tmp_path):
    fs = lint(tmp_path, {"errs.py": """\
        class Classified(RuntimeError):
            pass

        def a():
            try:
                work()
            except ValueError:
                pass

        def b():
            try:
                work()
            except Exception as e:
                raise Classified(str(e)) from e

        def c(log):
            try:
                work()
            except Exception as e:
                log.warning("probe failed: %s", e)

        def d():
            try:
                work()
            except Exception:  # noqa: BLE001
                pass
        """})
    assert hit(fs, "graft-silent-except") == []


# ========================================== rule: unlocked-shared-state

def test_unlocked_shared_state_positive(tmp_path):
    fs = lint(tmp_path, {"box.py": """\
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self.items = []

            def add(self, x):
                with self._lock:
                    self.items.append(x)

            def drop(self):
                self.items = []
        """})
    found = hit(fs, "graft-unlocked-shared-state")
    assert len(found) == 1
    assert found[0].where == "src/box.py:13"
    assert "races" in found[0].message


def test_unlocked_shared_state_negative(tmp_path):
    # __init__ writes, *_locked helpers, attrs never locked anywhere,
    # and fully locked classes are all clean
    fs = lint(tmp_path, {"box.py": """\
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self.items = []
                self.stats = 0

            def add(self, x):
                with self._lock:
                    self.items.append(x)

            def _drop_chain_locked(self):
                self.items = []

            def bump(self):
                self.stats += 1
        """})
    assert hit(fs, "graft-unlocked-shared-state") == []


# ================================================= rule: donated-reuse

def test_donated_reuse_positive(tmp_path):
    fs = lint(tmp_path, {"don.py": """\
        import functools
        import jax

        @functools.partial(jax.jit, donate_argnums=(0,))
        def step(buf, x):
            return buf + x

        def bad(buf, xs):
            out = step(buf, xs)
            return out + buf.sum()
        """})
    found = hit(fs, "graft-donated-reuse")
    assert len(found) == 1
    assert "donated to step()" in found[0].message
    assert found[0].where == "src/don.py:10"


def test_donated_reuse_loop_carry_positive(tmp_path):
    # donated on iteration N, read again at the top of iteration N+1 —
    # the back-edge pass catches what a straight-line scan misses
    fs = lint(tmp_path, {"don.py": """\
        import jax

        def step_impl(buf, x):
            return buf + x

        step = jax.jit(step_impl, donate_argnums=0)

        def worker(buf, xs):
            acc = None
            for x in xs:
                acc = step(buf, x)
            return acc
        """})
    found = hit(fs, "graft-donated-reuse")
    assert len(found) == 1


def test_donated_reuse_negative(tmp_path):
    # the rebind idiom — `buf = step(buf, x)` — is exactly what
    # donate_argnums is for
    fs = lint(tmp_path, {"don.py": """\
        import functools
        import jax

        @functools.partial(jax.jit, donate_argnums=(0,))
        def step(buf, x):
            return buf + x

        def ok(buf, xs):
            for x in xs:
                buf = step(buf, x)
            return buf

        def also_ok(buf, x):
            out = step(buf, x)
            return out
        """})
    assert hit(fs, "graft-donated-reuse") == []


# ==================================================== rule: lock-cycle

def test_lock_cycle_positive(tmp_path):
    fs = lint(tmp_path, {"locks.py": """\
        import threading

        A = threading.Lock()
        B = threading.Lock()

        def ab():
            with A:
                with B:
                    pass

        def ba():
            with B:
                with A:
                    pass
        """})
    found = hit(fs, "graft-lock-cycle")
    assert len(found) == 1
    assert "lock-order cycle" in found[0].message
    assert "global" in found[0].message


def test_lock_cycle_cross_file_interprocedural(tmp_path):
    # P holds its lock and calls into Q (another file), which takes its
    # own lock — and vice versa: the may-acquire fixpoint closes the loop
    fs = lint(tmp_path, {
        "p.py": """\
            import threading

            class P:
                def __init__(self):
                    self._lock = threading.Lock()

                def call_q(self, q):
                    with self._lock:
                        q.q_work()

                def p_work(self):
                    with self._lock:
                        pass
            """,
        "q.py": """\
            import threading

            class Q:
                def __init__(self):
                    self._lock = threading.Lock()

                def call_p(self, p):
                    with self._lock:
                        p.p_work()

                def q_work(self):
                    with self._lock:
                        pass
            """})
    found = hit(fs, "graft-lock-cycle")
    assert len(found) == 1
    assert "P._lock" in found[0].message and "Q._lock" in found[0].message


def test_lock_cycle_negative_consistent_order(tmp_path):
    fs = lint(tmp_path, {"locks.py": """\
        import threading

        A = threading.Lock()
        B = threading.Lock()

        def ab():
            with A:
                with B:
                    pass

        def ab2():
            with A:
                with B:
                    pass
        """})
    assert hit(fs, "graft-lock-cycle") == []


def test_lockgraph_condition_aliases_its_lock(tmp_path):
    # Condition(self._lock) IS that lock: re-entering through the cv
    # while holding the lock must not fabricate a two-node cycle
    root = tmp_path / "src"
    root.mkdir()
    (root / "cv.py").write_text(textwrap.dedent("""\
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._cv = threading.Condition(self._lock)

            def put(self, x):
                with self._cv:
                    self._notify()

            def _notify(self):
                with self._lock:
                    pass
        """))
    g = build_lock_graph(PyContext(str(root), rel_to=str(tmp_path)))
    assert g.nodes == {"src/cv.py::C._lock"}
    assert g.cycles() == []


# ======================================================= rule: load

def test_graft_load_surfaces_syntax_errors(tmp_path):
    fs = lint(tmp_path, {
        "broken.py": "def f(:\n",
        "ok.py": "import random\nR = random.Random()\n",
    })
    assert len(hit(fs, "graft-load")) == 1
    assert hit(fs, "graft-load")[0].severity == "error"
    # the parse failure must not drop the other file's findings
    assert len(hit(fs, "graft-unseeded-rng")) == 1


# ======================================== engine: suppressions/overrides

def test_suppression_trailing_and_standalone(tmp_path):
    fs = lint(tmp_path, {"s.py": """\
        import random

        A = random.Random()  # graftlint: ignore[graft-unseeded-rng] — why
        # graftlint: ignore[graft-unseeded-rng] — reason above the line
        B = random.Random()
        C = random.Random()
        """})
    found = hit(fs, "graft-unseeded-rng")
    assert len(found) == 1
    assert found[0].where == "src/s.py:6"


def test_suppression_standalone_covers_next_line_only(tmp_path):
    # a reason comment BETWEEN the marker and the code breaks coverage —
    # the marker must sit directly above the flagged line
    fs = lint(tmp_path, {"s.py": """\
        import random

        # graftlint: ignore[graft-unseeded-rng] — detached
        # ... marker no longer adjacent ...
        A = random.Random()
        """})
    assert len(hit(fs, "graft-unseeded-rng")) == 1


def test_suppression_wildcard(tmp_path):
    fs = lint(tmp_path, {"s.py": """\
        import random
        import time

        def f():
            return random.Random(), time.time()  # graftlint: ignore[*]
        """})
    assert fs == []


# ================================================== rule: unbounded-recv

def test_unbounded_recv_positive(tmp_path):
    """Timeout-less receives and zero-arg joins in the serving runtime
    are latent hangs — each named, each at its line."""
    fs = lint(tmp_path, {"models/transport.py": """\
        def pump(conn, q, worker):
            frame = conn.recv_bytes()
            item = q.get()
            worker.join()
            return frame, item
    """})
    found = hit(fs, "graft-unbounded-recv")
    assert len(found) == 3
    assert all(f.severity == "error" for f in found)
    wheres = sorted(f.where for f in found)
    assert wheres == ["src/models/transport.py:2",
                      "src/models/transport.py:3",
                      "src/models/transport.py:4"]
    msgs = " ".join(f.message for f in found)
    assert ".recv_bytes()" in msgs and ".get()" in msgs \
        and ".join()" in msgs


def test_unbounded_recv_negative_bounded_and_guarded(tmp_path):
    """The bounded idioms pass: explicit timeouts, the
    poll-then-recv_bytes guard (FrameChannel.recv's shape), joins with
    a budget, argful ``str.join``, and receives outside the
    serving-runtime scope."""
    fs = lint(tmp_path, {"models/fleet.py": """\
        def bounded(conn, q, worker, parts):
            item = q.get(timeout=1.0)
            worker.join(5.0)
            label = ",".join(parts)
            return item, label

        def guarded(conn, budget):
            if not conn.poll(budget):
                raise TimeoutError
            return conn.recv_bytes()
    """, "models/checkpoint.py": """\
        def out_of_scope(q):
            return q.get()
    """})
    assert hit(fs, "graft-unbounded-recv") == []


def test_unbounded_recv_guard_is_per_function(tmp_path):
    """A poll elsewhere in the file does not bless a different
    function's unbounded receive — the guard is scope-local."""
    fs = lint(tmp_path, {"models/serving.py": """\
        def guarded(conn):
            conn.poll(0.1)
            return conn.recv_bytes()

        def naked(other):
            return other.recv_bytes()
    """})
    found = hit(fs, "graft-unbounded-recv")
    assert [f.where for f in found] == ["src/models/serving.py:6"]


# ========================================= rule: spawn-no-retry-classify

def test_spawn_no_retry_classify_positive(tmp_path):
    """A bare Process/Popen spawn in the serving runtime is flagged at
    its line: a transient bring-up failure must classify, not crash."""
    fs = lint(tmp_path, {"models/fleet.py": """\
        import multiprocessing as mp
        import subprocess

        def naked_spawn(target):
            proc = mp.Process(target=target)
            proc.start()
            return proc

        def naked_exec(cmd):
            return subprocess.Popen(cmd)
    """})
    found = hit(fs, "graft-spawn-no-retry-classify")
    assert len(found) == 2
    assert all(f.severity == "error" for f in found)
    assert sorted(f.where for f in found) == \
        ["src/models/fleet.py:10", "src/models/fleet.py:5"]
    msgs = " ".join(f.message for f in found)
    assert "Process()" in msgs and "Popen()" in msgs \
        and "retry_call" in msgs


def test_spawn_no_retry_classify_negative_guarded_and_scoped(tmp_path):
    """The blessed idioms pass: a spawn under ``retry_call`` in the
    SAME function, the transport shape — a nested ``bring_up`` closure
    handed to ``retry_call`` one level up — and spawns outside the
    serving-runtime scope."""
    fs = lint(tmp_path, {"models/transport.py": """\
        import multiprocessing as mp

        from ..utils.retry import retry_call

        def direct(target, policy):
            return retry_call(lambda: mp.Process(target=target),
                              policy=policy)

        def nested(self, target, policy):
            ctx = mp.get_context("spawn")

            def bring_up():
                proc = ctx.Process(target=target)
                proc.start()
                return proc

            return retry_call(bring_up, policy=policy,
                              retryable=(OSError,))
    """, "smoketest/runner.py": """\
        import subprocess

        def out_of_scope(cmd):
            return subprocess.Popen(cmd)
    """})
    assert hit(fs, "graft-spawn-no-retry-classify") == []


def test_spawn_no_retry_classify_guard_is_chain_local(tmp_path):
    """A ``retry_call`` in a SIBLING function does not bless another
    function's bare spawn — the guard search walks enclosing
    functions, never the whole file."""
    fs = lint(tmp_path, {"models/serving.py": """\
        import multiprocessing as mp

        from ..utils.retry import retry_call

        def guarded(target, policy):
            return retry_call(lambda: mp.Process(target=target),
                              policy=policy)

        def naked(target):
            return mp.Process(target=target)
    """})
    found = hit(fs, "graft-spawn-no-retry-classify")
    assert [f.where for f in found] == ["src/models/serving.py:10"]


def test_severity_overrides_and_off(tmp_path):
    files = {"s.py": "import random\nR = random.Random()\n"}
    assert lint(tmp_path, files,
                overrides={"graft-unseeded-rng": "info"}
                )[0].severity == "info"
    assert lint(tmp_path, files,
                overrides={"graft-unseeded-rng": "off"}) == []
    with pytest.raises(ValueError, match="unknown rule id"):
        lint(tmp_path, files, overrides={"nope": "error"})
    with pytest.raises(ValueError, match="level must be one of"):
        lint(tmp_path, files, overrides={"graft-unseeded-rng": "loud"})


def test_rule_catalog(tmp_path):
    ids = {r.id for r in list_rules()}
    assert ids == {
        "graft-load", "graft-unseeded-rng", "graft-host-sync-in-loop",
        "graft-wallclock-nondeterminism", "graft-silent-except",
        "graft-unlocked-shared-state", "graft-donated-reuse",
        "graft-lock-cycle", "graft-unbounded-recv",
        "graft-spawn-no-retry-classify", "graft-durable-write-no-atomic",
    }
    # disjoint from the HCL pack: one engine, two registries
    from nvidia_terraform_modules_tpu.tfsim.lint import engine as hcl
    hcl_ids = {r.id for r in hcl.list_rules()}
    assert ids.isdisjoint(hcl_ids)
    assert hcl.Finding is Finding  # the unified schema IS one class


# ============================================================= the CLI

def _cli(tmp_path, files, argv_tail=()):
    root = tmp_path / "cli"
    root.mkdir(exist_ok=True)
    for rel, body in files.items():
        (root / rel).write_text(textwrap.dedent(body))
    return graft_main([str(root), *argv_tail])


def test_cli_exit_codes(tmp_path, capsys):
    assert _cli(tmp_path, {"a.py": "import random\nR = random.Random()\n"
                           }) == 2
    assert _cli(tmp_path, {"a.py": "import time\nT = time.time()\n"}) == 1
    assert _cli(tmp_path, {"a.py": "X = 1\n"}) == 0
    out = capsys.readouterr().out
    assert "Success! 0 finding(s)" in out


def test_cli_json_and_sarif(tmp_path, capsys):
    rc = _cli(tmp_path, {"a.py": "import random\nR = random.Random()\n"},
              ["-json"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 2
    assert doc["clean"] is False and doc["error_count"] == 1
    assert doc["findings"][0]["rule"] == "graft-unseeded-rng"
    assert doc["findings"][0]["file"] == "cli/a.py"
    rc = _cli(tmp_path, {"a.py": "import random\nR = random.Random()\n"},
              ["-sarif"])
    sarif = json.loads(capsys.readouterr().out)
    assert sarif["version"] == "2.1.0"
    results = sarif["runs"][0]["results"]
    assert results[0]["ruleId"] == "graft-unseeded-rng"
    assert results[0]["level"] == "error"


def test_cli_bad_severity_is_a_diagnostic(tmp_path, capsys):
    rc = _cli(tmp_path, {"a.py": "X = 1\n"}, ["-severity", "nope=error"])
    out = capsys.readouterr().out
    assert rc == 2
    assert "unknown rule id" in out and "-rules" in out


def test_cli_rules_listing(capsys):
    assert graft_main(["-rules"]) == 0
    out = capsys.readouterr().out
    for rid in RULES:
        assert rid in out


# ===================================================== lockwatch (runtime)

def test_lockwatch_records_edges_and_cycles():
    with lockwatch.armed() as watch:
        a = threading.Lock()
        b = threading.Lock()
    # the watch keeps observing after the window closes
    with a:
        with b:
            pass
    with b:
        with a:
            pass
    assert watch.acquisitions == 4
    assert len(watch.lock_names) == 2
    cycles = watch.cycles()
    assert cycles, "opposite-order acquisition must report a cycle"
    assert watch.report()["cycles"]


def test_lockwatch_clean_order_no_cycle():
    with lockwatch.armed() as watch:
        a = threading.Lock()
        b = threading.Lock()
        with a:
            with b:
                pass
        with a:
            with b:
                pass
    assert watch.cycles() == []
    assert list(watch.edges) == [tuple(sorted(watch.lock_names))] or \
        len(watch.edges) == 1


def test_lockwatch_flags_sleep_under_lock():
    with lockwatch.armed() as watch:
        lk = threading.Lock()
        with lk:
            time.sleep(0)
        time.sleep(0)  # not held — must not be flagged
    held = watch.held_sleeps
    assert len(held) == 1
    lock_name, sleep_site, count = held[0]
    assert count == 1
    assert "test_analysis.py" in lock_name
    assert "test_analysis.py" in sleep_site


def test_lockwatch_out_of_order_release():
    # handoff patterns release out of LIFO order; the held-stack must
    # not drift and poison later edges
    with lockwatch.armed() as watch:
        a = threading.Lock()
        b = threading.Lock()
        a.acquire()
        b.acquire()
        a.release()
        b.release()
        with a:
            with b:
                pass
    assert watch.cycles() == []


def test_lockwatch_restores_factories_and_sleep():
    orig_lock, orig_rlock, orig_sleep = \
        threading.Lock, threading.RLock, time.sleep
    with lockwatch.armed():
        assert threading.Lock is not orig_lock
        assert time.sleep is not orig_sleep
    assert threading.Lock is orig_lock
    assert threading.RLock is orig_rlock
    assert time.sleep is orig_sleep


def test_lockwatch_condition_compat():
    # Condition borrows _release_save/_acquire_restore/_is_owned from
    # the wrapped lock via __getattr__; Event.wait must work while armed
    with lockwatch.armed() as watch:
        ev = threading.Event()
        cv = threading.Condition()
        with cv:
            cv.notify_all()
    ev.set()
    assert ev.wait(timeout=1.0)
    assert watch.cycles() == []


# ================================================== the package gate

def test_package_is_graftlint_clean():
    """THE gate: the shipped package scans clean — zero findings, every
    violation either fixed or suppressed with an inline reason."""
    assert run_graftlint(PKG) == []


def test_suppression_budget_and_reasons():
    subs = PyContext(PKG).count_suppressions()
    assert 0 < len(subs) <= 10, \
        f"{len(subs)} inline suppressions (cap is 10): {subs}"
    for fname, line, reason in subs:
        assert reason, (f"{fname}:{line}: suppression carries no reason "
                        f"string after the bracket")


def test_exit_code_shared_semantics():
    mk = lambda sev: Finding(sev, "x.py:1", "m", rule="r")
    assert exit_code([mk("error"), mk("warning")]) == 2
    assert exit_code([mk("warning"), mk("info")]) == 1
    assert exit_code([mk("info")]) == 0
    assert exit_code([]) == 0


# =============================================== combined-schema golden

def _check_golden(name, text):
    path = os.path.join(GOLDEN, name)
    if os.environ.get("GOLDEN_UPDATE"):
        with open(path, "w") as fh:
            fh.write(text)
    with open(path) as fh:
        assert fh.read() == text, \
            f"{name} drifted — regenerate intentionally with GOLDEN_UPDATE=1"


def test_combined_hcl_python_golden(tmp_path):
    """One run, both rule packs, one document: an HCL finding and a
    Python finding render through the SAME json/sarif serializers —
    the unified Finding schema is the contract CI parses."""
    from nvidia_terraform_modules_tpu.tfsim.lint import engine as hcl

    mod = tmp_path / "hclmod"
    mod.mkdir()
    (mod / "main.tf").write_text(
        'terraform {\n'
        '  required_version = ">= 1.5.0"\n'
        '  required_providers {\n'
        '    google = { source = "hashicorp/google", version = "~> 5.0" }\n'
        '  }\n'
        '}\n'
        '\n'
        'variable "unused_thing" {\n'
        '  description = "never wired in"\n'
        '  type        = number\n'
        '  default     = 1\n'
        '}\n')
    pyroot = tmp_path / "graftpkg"
    pyroot.mkdir()
    (pyroot / "rng.py").write_text(
        "import random\n\nR = random.Random()\n")

    hcl_findings = hcl.run_lint(str(mod))
    py_findings = run_graftlint(str(pyroot), rel_to=str(tmp_path))
    assert [f.rule for f in hcl_findings] == ["unused-variable"]
    assert [f.rule for f in py_findings] == ["graft-unseeded-rng"]

    combined = sorted(hcl_findings + py_findings,
                      key=lambda f: (f.file, f.line, f.rule, f.message))
    doc = findings_json(combined, _SUFFIXES)
    sarif = sarif_report(combined, hcl.list_rules() + list_rules(),
                         "unified-lint", _SUFFIXES)
    assert doc["error_count"] == 1 and doc["warning_count"] == 1
    _check_golden("combined_lint.json",
                  json.dumps(doc, indent=2, sort_keys=True) + "\n")
    _check_golden("combined_lint.sarif",
                  json.dumps(sarif, indent=2, sort_keys=True) + "\n")


# ======================================= rule: durable-write-no-atomic

def test_durable_write_no_atomic_positive(tmp_path):
    fs = lint(tmp_path, {"models/store.py": """\
        import json

        def save(path, record):
            with open(path, "w") as fh:
                json.dump(record, fh)
        """})
    (f,) = hit(fs, "graft-durable-write-no-atomic")
    assert f.severity == "error"
    assert "src/models/store.py:4" in f.where
    assert "os.replace" in f.message


def test_durable_write_path_oneshot_positive(tmp_path):
    # pathlib's one-shot writers have no handle to fsync and no
    # tmp+rename — never atomic, always flagged in durable scope
    fs = lint(tmp_path, {"models/cachefile.py": """\
        def save(path, blob):
            path.write_bytes(blob)

        def note(path, text):
            path.write_text(text)
        """})
    assert len(hit(fs, "graft-durable-write-no-atomic")) == 2


def test_durable_write_tmp_replace_negative(tmp_path):
    # the blessed idiom: write the tmp name, fsync, os.replace — the
    # scope guard (os.replace) and the path marker both exempt it
    fs = lint(tmp_path, {"models/store.py": """\
        import os

        def save(path, blob):
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "wb") as fh:
                fh.write(blob)
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, path)
        """})
    assert hit(fs, "graft-durable-write-no-atomic") == []


def test_durable_write_tmp_path_split_scope_negative(tmp_path):
    # tmp-marked path alone is enough: the os.replace that publishes
    # it may live in a helper or the caller
    fs = lint(tmp_path, {"models/store.py": """\
        def stage(tmp_path, blob):
            with open(tmp_path, "wb") as fh:
                fh.write(blob)
        """})
    assert hit(fs, "graft-durable-write-no-atomic") == []


def test_durable_write_reads_and_dynamic_modes_negative(tmp_path):
    fs = lint(tmp_path, {"models/store.py": """\
        def load(path, mode):
            with open(path) as fh:          # default "r"
                a = fh.read()
            with open(path, "rb") as fh:    # explicit read
                b = fh.read()
            with open(path, mode) as fh:    # dynamic: best-effort skip
                c = fh.read()
            return a, b, c
        """})
    assert hit(fs, "graft-durable-write-no-atomic") == []


def test_durable_write_out_of_scope_negative(tmp_path):
    # tfsim's emitters and CLI report writers are outside the durable
    # serving-runtime scope (they have their own discipline)
    fs = lint(tmp_path, {"tfsim/emit.py": """\
        def emit(path, text):
            with open(path, "w") as fh:
                fh.write(text)
        """})
    assert hit(fs, "graft-durable-write-no-atomic") == []


def test_durable_write_suppression(tmp_path):
    fs = lint(tmp_path, {"models/store.py": """\
        def save(path, text):
            with open(path, "w") as fh:  # graftlint: ignore[graft-durable-write-no-atomic] scratch file, never reread
                fh.write(text)
        """})
    assert hit(fs, "graft-durable-write-no-atomic") == []
