# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""``tfsim test`` — offline analogue of terraform's native test framework.

The reference repo has **no automated tests at all** (SURVEY §4:
``/root/reference/CONTRIBUTING.md:56`` — "no CI/CD process in place yet …
adequate testing … manually"). Modern terraform's answer is the ``.tftest.hcl``
framework (``terraform test``): run blocks that plan/apply the module with
fixture variables and assert on the planned values. tfsim ships the same
surface so module test suites live next to the HCL they cover and run in CI
with no cloud and no terraform binary:

    tests/*.tftest.hcl              # discovered under the module dir
    variables { ... }               # file-level fixture values
    run "name" {
      command = plan                # or apply (default)
      variables { ... }             # run-level overrides
      assert {
        condition     = <expr over resources / data / output.* / var.*>
        error_message = "..."
      }
      expect_failures = [var.x, check.y]   # the negative-path form
    }

Semantics mirrored from terraform: variable precedence is run block >
file block > CLI ``-var``/``-var-file``; runs execute in file order and an
``apply`` run's outputs are visible to later runs as ``run.<name>.<output>``;
``check`` block failures fail a run unless listed in ``expect_failures``;
a failed run does not stop the file (remaining runs still execute).
"""

from __future__ import annotations

import dataclasses
import os
import re
from typing import Any

from . import ast as A
from .eval import COMPUTED, EvalError, Scope, evaluate
from .module import Module, load_module
from .parser import HclParseError, parse_hcl
from .plan import Plan, PlanError, plan_eval_scope, simulate_plan
from .state import State, apply_plan


@dataclasses.dataclass
class RunResult:
    name: str
    command: str                       # "plan" | "apply"
    status: str                        # "pass" | "fail" | "error"
    failures: list[str] = dataclasses.field(default_factory=list)

    @property
    def ok(self) -> bool:
        return self.status == "pass"


@dataclasses.dataclass
class FileResult:
    path: str
    runs: list[RunResult] = dataclasses.field(default_factory=list)
    error: str | None = None           # file-level parse/shape error

    @property
    def ok(self) -> bool:
        return self.error is None and all(r.ok for r in self.runs)


def discover_test_files(module_dir: str) -> list[str]:
    """``*.tftest.hcl`` directly in the module dir or its ``tests/`` subdir."""
    out = []
    for sub in ("", "tests"):
        d = os.path.join(module_dir, sub) if sub else module_dir
        if not os.path.isdir(d):
            continue
        out.extend(sorted(
            os.path.join(d, f) for f in os.listdir(d)
            if f.endswith(".tftest.hcl")))
    return out


def run_tests(module_dir: str, cli_vars: dict[str, Any] | None = None,
              filter_paths: list[str] | None = None) -> list[FileResult]:
    module = load_module(module_dir)
    files = discover_test_files(module_dir)
    if filter_paths:
        wanted = {os.path.normpath(p) for p in filter_paths}
        files = [f for f in files
                 if os.path.normpath(f) in wanted or
                 os.path.basename(f) in {os.path.basename(w) for w in wanted}]
    return [run_test_file(module, f, cli_vars or {}) for f in files]


def run_test_file(module: Module, path: str,
                  cli_vars: dict[str, Any]) -> FileResult:
    result = FileResult(path=path)
    try:
        with open(path) as fh:
            body = parse_hcl(fh.read(), filename=path)
    except (HclParseError, OSError) as ex:
        result.error = str(ex)
        return result

    # CLI vars feed every run but only where the module declares the name —
    # terraform's own behaviour (undeclared CLI vars warn, they don't error)
    cli_vars = {k: v for k, v in cli_vars.items() if k in module.variables}

    file_vars: dict[str, Any] = {}
    run_outputs: dict[str, dict[str, Any]] = {}  # run name → plan outputs
    state: State | None = None                   # rolls forward across applies
    base = Scope()

    for attr in body.attributes:
        result.error = (f"{path}:{attr.line}: top-level attribute "
                        f"{attr.name!r} not allowed in a test file")
        return result

    # file-level variables apply to EVERY run, wherever the block sits in
    # the file (terraform semantics) — collect them before executing any run
    for blk in body.blocks_of("variables"):
        for attr in blk.body.attributes:
            file_vars[attr.name] = evaluate(attr.expr, base)

    runs_seen: set[str] = set()
    for blk in body.blocks:
        if blk.type == "variables":
            continue
        if blk.type == "provider":
            continue                   # accepted and ignored: no real providers
        if blk.type != "run":
            result.error = (f"{path}:{blk.line}: unsupported block "
                            f"{blk.type!r} in a test file")
            return result
        name = blk.labels[0] if blk.labels else f"<line {blk.line}>"
        if name in runs_seen:
            result.error = f"{path}:{blk.line}: duplicate run {name!r}"
            return result
        runs_seen.add(name)
        rr, state = _execute_run(module, path, blk, name, cli_vars,
                                 file_vars, run_outputs, state)
        result.runs.append(rr)
    return result


def _execute_run(module: Module, path: str, blk: A.Block, name: str,
                 cli_vars: dict, file_vars: dict,
                 run_outputs: dict[str, dict[str, Any]],
                 state: State | None) -> tuple[RunResult, State | None]:
    # ---- run-level config ------------------------------------------------
    command = "apply"
    cmd_attr = blk.body.attr("command")
    if cmd_attr is not None:
        command = _bare_word(cmd_attr.expr)
        if command not in ("plan", "apply"):
            return RunResult(name, str(command), "error", [
                f"{path}:{cmd_attr.line}: command must be plan or apply"]), \
                state
    rr = RunResult(name, command, "pass")

    if blk.body.blocks_of("module"):
        rr.status = "error"
        rr.failures.append(
            f"{path}:{blk.line}: run-level module {{ source = … }} blocks "
            f"are not supported by tfsim (test the module directly)")
        return rr, state

    # run-level variables may read earlier runs' outputs (run.<name>.<out>)
    # and the vars below them in the precedence chain (CLI < file)
    var_scope = Scope(variables={**cli_vars, **file_vars})
    var_scope.bindings["run"] = run_outputs
    run_vars: dict[str, Any] = {}
    for vblk in blk.body.blocks_of("variables"):
        for attr in vblk.body.attributes:
            try:
                run_vars[attr.name] = evaluate(attr.expr, var_scope)
            except EvalError as ex:
                rr.status = "error"
                rr.failures.append(f"{path}:{attr.line}: variables: {ex}")
                return rr, state
    merged = {**cli_vars, **file_vars, **run_vars}

    expected = _expect_failures(blk)

    # ---- plan ------------------------------------------------------------
    try:
        plan = simulate_plan(module, merged)
    except (PlanError, EvalError) as ex:
        matched = _match_expected_failure(str(ex), expected)
        if matched:
            expected.discard(matched)
            if expected:
                rr.status = "fail"
                rr.failures.append(
                    f"expected failures did not all occur: "
                    f"{sorted(expected)} (plan stopped at: {ex})")
            return rr, state
        rr.status = "error" if not expected else "fail"
        rr.failures.append(f"plan failed: {ex}")
        return rr, state

    # check-block failures fail the run unless expected (terraform test
    # treats checks as assertions inside the module under test)
    for failure in plan.check_failures:
        m = re.match(r"check '([^']+)'", failure)
        addr = f"check.{m.group(1)}" if m else None
        if addr in expected:
            expected.discard(addr)
        else:
            rr.status = "fail"
            rr.failures.append(failure)
    if expected:
        rr.status = "fail"
        rr.failures.append(
            f"expected failures did not occur: {sorted(expected)}")

    # ---- asserts ---------------------------------------------------------
    # plan.variables carries the EFFECTIVE values (declaration defaults and
    # optional() fills included), so `var.x == 2` holds for a default too
    scope = plan_eval_scope(plan, plan.variables, run_outputs)
    for ab in blk.body.blocks_of("assert"):
        cond = ab.body.attr("condition")
        if cond is None:
            rr.status = "error"
            rr.failures.append(
                f"{path}:{ab.line}: assert without condition")
            continue
        try:
            ok = evaluate(cond.expr, scope)
        except EvalError as ex:
            rr.status = "fail"
            rr.failures.append(f"{path}:{cond.line}: condition error: {ex}")
            continue
        if ok is COMPUTED:
            rr.status = "fail"
            rr.failures.append(
                f"{path}:{cond.line}: condition depends on a value only "
                f"known after a real apply")
            continue
        if not ok:
            msg_attr = ab.body.attr("error_message")
            msg = ""
            if msg_attr is not None:
                try:
                    msg = evaluate(msg_attr.expr, scope)
                except EvalError:
                    msg = "<error_message failed to evaluate>"
            rr.status = "fail"
            rr.failures.append(f"{path}:{ab.line}: {msg or 'assert failed'}")

    # ---- apply: advance the rolling state, expose outputs to later runs --
    if rr.ok:
        if command == "apply":
            try:
                state = apply_plan(plan, state)
            except ValueError as ex:       # defensive: diff/apply edge cases
                rr.status = "error"
                rr.failures.append(f"apply failed: {ex}")
                return rr, state
        run_outputs[name] = dict(plan.outputs)
    return rr, state


def _bare_word(expr: A.Expr) -> str:
    """``command = plan`` parses as a bare traversal; unwrap to its word."""
    if isinstance(expr, A.Traversal) and not expr.ops:
        return expr.root
    if isinstance(expr, A.Literal) and isinstance(expr.value, str):
        return expr.value
    return "<invalid>"


def _expect_failures(blk: A.Block) -> set[str]:
    attr = blk.body.attr("expect_failures")
    if attr is None or not isinstance(attr.expr, A.TupleExpr):
        return set()
    out = set()
    for item in attr.expr.items:
        if isinstance(item, A.Traversal):
            out.add(item.path_str())
    return out


def _match_expected_failure(message: str, expected: set[str]) -> str | None:
    """The expect_failures entry a PlanError corresponds to, if any.

    Variable validation failures carry the variable name
    (``variable 'x' validation failed: …`` — plan.py); that is the one
    checkable object whose failure aborts a plan.
    """
    m = re.search(r"variable '([^']+)' validation failed", message)
    if m and f"var.{m.group(1)}" in expected:
        return f"var.{m.group(1)}"
    return None


def format_results(results: list[FileResult]) -> str:
    """terraform-test-shaped report; one line per run, summary at the end."""
    lines: list[str] = []
    passed = failed = 0
    for fr in results:
        lines.append(f"{fr.path}... {'pass' if fr.ok else 'fail'}")
        if fr.error:
            failed += 1
            lines.append(f"  error: {fr.error}")
            continue
        for rr in fr.runs:
            lines.append(f'  run "{rr.name}"... {rr.status}')
            if rr.ok:
                passed += 1
            else:
                failed += 1
            for f in rr.failures:
                lines.append(f"    {f}")
    verdict = "Success!" if failed == 0 else "Failure!"
    lines.append(f"{verdict} {passed} passed, {failed} failed.")
    return "\n".join(lines)
