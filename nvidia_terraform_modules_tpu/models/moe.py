# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""Switch-style top-1 Mixture-of-Experts layer, expert-parallel over ``ep``.

The reference provisions the fabric and never runs a workload on it
(SURVEY §2.6); our validation workload exists to prove the fabric carries
real parallelism. The dense burn-in transformer already exercises dp
(gradient psum), tp (all-gather / reduce-scatter), and sp (ring
collectives); this layer adds the remaining first-class axis: **ep**,
whose signature collective is the all-to-all token shuffle between
data-sharded activations and expert-sharded FFN weights.

TPU-first design (GShard/Switch dispatch, not a CUDA-style scatter):

- **static shapes**: every token picks its top-1 expert, but routing is
  materialised as dense one-hot dispatch/combine tensors of fixed shape
  ``[tokens, experts, capacity]`` — no data-dependent shapes, so the whole
  layer jits into one XLA program and tiles onto the MXU;
- **capacity factor**: each expert processes at most
  ``ceil(tokens/experts · capacity_factor)`` tokens; overflow tokens are
  dropped (their residual path carries them) — the standard Switch
  trade that keeps the einsums static;
- **sharding does the communication**: expert weights shard over
  ``ep`` (and their FFN dim over ``tp``); constraining the dispatched
  activations to ``P("ep", …)`` makes XLA lower the dispatch/combine
  einsums to all-to-alls over ICI — no hand-written collective;
- **load-balance auxiliary loss** (Switch eq. 4): mean expert load ×
  mean router probability × E, differentiable pressure toward uniform
  routing, returned for the train loss to add.
"""

from __future__ import annotations

import math
from typing import Any

import jax
import jax.numpy as jnp

from ..utils.layers import dense_init


def expert_capacity(tokens: int, n_experts: int,
                    capacity_factor: float) -> int:
    """Per-expert token slots; multiple of 8 so the [E, C, D] expert batch
    tiles cleanly onto TPU sublanes."""
    cap = math.ceil(tokens / n_experts * capacity_factor)
    return max(8, math.ceil(cap / 8) * 8)


def drop_free_capacity(assignments: int) -> int:
    """Capacity at which NO assignment can overflow (worst case: every
    token routes to one expert). The SERVING capacity: capacity drops are
    a training-time load-balancing trade, but a dropped token at decode
    silently changes the model — and drop behaviour depends on the total
    token count, which would break the cached-decode ==
    full-re-forward exactness contract (capacity grows with sequence
    length, so a prefill-dropped token could fit in the longer full
    forward)."""
    return max(8, math.ceil(assignments / 8) * 8)


def init_moe_params(rng, cfg) -> dict[str, Any]:
    """Router + stacked expert FFN weights ([E, ...] leading expert dim)."""
    kr, ku, kd = jax.random.split(rng, 3)

    def dense(key, shape):
        return dense_init(key, shape, cfg.dtype)

    return {
        # router stays f32: tiny, and routing decisions are
        # precision-sensitive (bf16 logit ties flip expert choice)
        "router": jax.random.normal(
            kr, (cfg.d_model, cfg.n_experts), dtype=jnp.float32) * 0.02,
        "experts_up": dense(ku, (cfg.n_experts, cfg.d_model, cfg.d_ff)),
        "experts_down": dense(kd, (cfg.n_experts, cfg.d_ff, cfg.d_model)),
    }


def moe_layer(x, params, cfg, rules=None, *, capacity: int | None = None):
    """Top-k MoE FFN (k = ``cfg.router_top_k``); returns ([B,S,D], aux).

    Dispatch/combine follow GShard: a dense [T, E, C] one-hot tensor
    routes tokens into per-expert batches and back. With ``rules`` on an
    ``ep`` mesh, the expert batch is constrained to ``P("ep", …)`` so XLA
    inserts the all-to-all; unsharded it is a plain pair of einsums.

    k=1 is Switch routing (gate = raw top probability — numerically
    identical to the original top-1 layer); k>1 is GShard routing: gates
    renormalised over the selected experts, and rank-r assignments claim
    capacity slots AFTER every rank<r assignment (each expert's counter
    is offset by the lower ranks' totals), so a full expert drops its
    second-choice tokens first — the standard GShard priority.

    ``capacity`` overrides the factor-derived per-expert slot count —
    the serving path passes :func:`drop_free_capacity` so routing never
    depends on how many tokens happen to share the batch.
    """
    B, S, D = x.shape
    E = cfg.n_experts
    K = cfg.router_top_k
    T = B * S
    # top-k makes K·T assignments, so capacity provisions K·T/E slots per
    # expert (GShard's k-scaled capacity) — without the K factor, top-2
    # under the default factor would drop ~37% of assignments at uniform
    # load and quietly degrade toward top-1
    C = capacity if capacity is not None else \
        expert_capacity(T * K, E, cfg.capacity_factor)

    tokens = x.reshape(T, D)
    logits = tokens.astype(jnp.float32) @ params["router"]     # [T, E]
    probs = jax.nn.softmax(logits, axis=-1)
    top_p, top_e = jax.lax.top_k(probs, K)                     # [T, K]
    if K > 1:
        top_p = top_p / jnp.sum(top_p, axis=-1, keepdims=True)

    onehot = jax.nn.one_hot(top_e[:, 0], E, dtype=jnp.float32)  # rank-0 [T,E]
    dispatch = jnp.zeros((T, E, C), jnp.float32)
    combine = jnp.zeros((T, E, C), jnp.float32)
    # int32 cumsum: f32 would lose integer exactness past 2^24 tokens and
    # silently collapse distinct tokens into one capacity slot
    used = jnp.zeros((E,), jnp.int32)    # slots claimed by lower ranks
    for r in range(K):
        oh_i = jax.nn.one_hot(top_e[:, r], E, dtype=jnp.int32)  # [T, E]
        # position within the expert batch: exclusive cumsum along the
        # token dim (deterministic first-come-first-served), offset by the
        # lower ranks' per-expert totals
        pos = jnp.cumsum(oh_i, axis=0) * oh_i - oh_i + used[None] * oh_i
        within = ((pos < C) & (oh_i == 1)).astype(jnp.float32)
        pos_oh = jax.nn.one_hot(pos, C, dtype=jnp.float32)     # [T, E, C]
        d_r = pos_oh * within[..., None]
        dispatch = dispatch + d_r
        combine = combine + d_r * top_p[:, r][:, None, None]
        used = used + jnp.sum(oh_i, axis=0)

    def ep(t, spec):
        if rules is None:
            return t
        return jax.lax.with_sharding_constraint(t, rules.shard(spec))

    # dispatch: token-sharded [T, D] → expert-sharded [E, C, D]
    # (all-to-all over ep when experts are sharded there)
    xin = jnp.einsum("tec,td->ecd", dispatch.astype(cfg.dtype),
                     tokens)
    xin = ep(xin, rules.moe_act if rules else None)
    h = jnp.einsum("ecd,edf->ecf", xin, params["experts_up"])
    h = jax.nn.gelu(h.astype(jnp.float32)).astype(cfg.dtype)
    h = ep(h, rules.moe_hidden if rules else None)
    xout = jnp.einsum("ecf,efd->ecd", h, params["experts_down"])
    xout = ep(xout, rules.moe_act if rules else None)
    # combine: back to token-sharded [T, D]
    out = jnp.einsum("tec,ecd->td", combine.astype(cfg.dtype), xout)

    # Switch load-balance loss: E · Σ_e load_e · prob_e (minimised at
    # uniform routing). Computed over ALL tokens, including dropped ones.
    load = jnp.mean(onehot, axis=0)                            # [E]
    prob = jnp.mean(probs, axis=0)                             # [E]
    aux = E * jnp.sum(load * prob)

    return out.reshape(B, S, D), aux
