# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""Paged KV cache: allocator invariants and the paged forward path.

The allocator (models/paging.py) is host-side bookkeeping the whole
engine's correctness leans on: a double-granted block would let two
requests scribble over each other's cache rows. These tests pin the
free-list invariants (no double alloc, all-or-nothing grants, LIFO
recycling, the fragmentation bound) and the paged forward's equivalence
against the dense cache layout (``forward_paged`` vs ``forward_cached``
on the same tokens — the layer-level version of the engine-level
bit-match contract in test_serving.py).
"""

import jax
import jax.numpy as jnp
import pytest

from nvidia_terraform_modules_tpu.models import BurnInConfig, init_params
from nvidia_terraform_modules_tpu.models.paging import (
    BlockAllocator,
    blocks_for_rows,
    init_paged_cache,
    paged_pool_spec,
)

CFG = dict(vocab=64, d_model=32, n_heads=4, d_ff=64, n_layers=2,
           seq_len=16, batch=2, dtype=jnp.float32)


# ------------------------------------------------------------- allocator


def test_alloc_is_all_or_nothing_and_exhaustion_returns_none():
    a = BlockAllocator(6)                       # 1 reserved + 5 usable
    got = a.alloc(3)
    assert got is not None and len(got) == 3
    assert a.in_use == 3 and a.free_blocks == 2
    # a grant larger than the remaining free list is REFUSED whole —
    # a partial grant would admit a request that cannot finish
    assert a.alloc(3) is None
    assert a.in_use == 3 and a.free_blocks == 2   # nothing leaked
    assert a.alloc(2) is not None
    assert a.free_blocks == 0


def test_block_zero_is_never_granted():
    """Block 0 is the garbage block dead slots write into — handing it
    out would let an idle slot corrupt a live request."""
    a = BlockAllocator(5)
    got = a.alloc(4)
    assert got is not None and 0 not in got
    assert a.alloc(1) is None                   # pool exhausted at 4


def test_free_recycles_and_double_free_is_loud():
    a = BlockAllocator(4)
    got = a.alloc(3)
    a.free(got[:2])
    assert a.free_blocks == 2 and a.in_use == 1
    again = a.alloc(2)
    assert sorted(again) == sorted(got[:2])     # recycled, not leaked
    with pytest.raises(ValueError, match="not allocated"):
        a.free(got[:1] + got[:1])               # second free of same id
    with pytest.raises(ValueError, match="not allocated"):
        a.free([0])                             # the reserved block


def test_high_water_tracks_peak_not_current():
    a = BlockAllocator(8)
    g1 = a.alloc(5)
    a.free(g1[:4])
    a.alloc(2)
    assert a.in_use == 3
    assert a.high_water == 5
    assert a.stats()["high_water"] == 5


def test_fragmentation_bound_blocks_for_rows():
    """Internal fragmentation is bounded by block_size - 1 rows per
    request: the block count never over-allocates by a whole block."""
    for bs in (1, 4, 16):
        for rows in (0, 1, bs - 1, bs, bs + 1, 5 * bs + 3):
            n = blocks_for_rows(rows, bs)
            assert n * bs >= rows
            assert n * bs - rows < bs or rows == 0
    with pytest.raises(ValueError, match="rows"):
        blocks_for_rows(-1, 4)


def test_allocator_validates_construction():
    with pytest.raises(ValueError, match="exceed"):
        BlockAllocator(1)                       # nothing beyond reserved
    with pytest.raises(ValueError, match="allocate"):
        BlockAllocator(4).alloc(-1)


# ---------------------------------------------------------- pool + spec


def test_paged_pool_spec_matches_cache_rows():
    from nvidia_terraform_modules_tpu.models.decode import cache_rows

    cfg = BurnInConfig(**CFG)
    spec = paged_pool_spec(cfg, 20, 8)
    assert spec["rows"] == 20
    assert spec["tables"] == 3                  # ceil(20 / 8)
    assert spec["logical_rows"] == 24
    # int8 keeps the 256-row kernel grain through the paged geometry
    spec8 = paged_pool_spec(cfg, 20, 8, "int8")
    assert spec8["rows"] == cache_rows(20, "int8") == 256
    assert spec8["tables"] * 8 >= 256
    with pytest.raises(ValueError, match="block_size"):
        paged_pool_spec(cfg, 20, 0)


def test_init_paged_cache_layout():
    cfg = BurnInConfig(**CFG)
    pool = init_paged_cache(cfg, 3, 20, block_size=8, num_blocks=7)
    assert len(pool["k"]) == cfg.n_layers
    assert pool["k"][0].shape == (7, 8, cfg.kv_heads, cfg.head_dim)
    assert pool["block_tables"].shape == (3, 3)
    assert pool["pos"].shape == (3,)
    q = init_paged_cache(cfg, 2, 16, block_size=8, num_blocks=5,
                         cache_dtype="int8")
    assert q["k"][0].dtype == jnp.int8
    assert q["k_scale"][0].shape == (5, 8, cfg.kv_heads)
    with pytest.raises(ValueError, match="cache_dtype"):
        init_paged_cache(cfg, 2, 16, block_size=8, num_blocks=5,
                         cache_dtype="fp8")


# ------------------------------------------------- paged forward parity


def _paged_setup(cache_dtype="bf16", bs=4, **over):
    from nvidia_terraform_modules_tpu.models.decode import forward_cached
    from nvidia_terraform_modules_tpu.models import init_cache

    cfg = BurnInConfig(**{**CFG, **over})
    params = init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params, forward_cached, init_cache


def test_forward_paged_matches_forward_cached_prefill_and_steps():
    """The layer-level contract under the engine: a prefill + decode
    steps through scattered, non-contiguous physical blocks produce
    logits identical to the dense cache buffer."""
    from nvidia_terraform_modules_tpu.models.decode import forward_paged

    cfg, params, forward_cached, init_cache = _paged_setup()
    max_len, bs = 16, 4
    prompt = jax.random.randint(jax.random.PRNGKey(1), (1, 6), 0,
                                cfg.vocab)
    dense = init_cache(cfg, 1, max_len)
    d_logits, dense = forward_cached(params, prompt, dense, cfg)

    pool = init_paged_cache(cfg, 1, max_len, block_size=bs, num_blocks=9)
    # deliberately NON-CONTIGUOUS, out-of-order physical blocks: the
    # table, not adjacency, must carry the logical order
    pool["block_tables"] = jnp.asarray([[7, 2, 5, 3]], jnp.int32)
    p_logits, pool = forward_paged(params, prompt, pool, cfg,
                                   prefill_impl="dense")
    assert jnp.allclose(d_logits, p_logits, atol=0, rtol=0)

    tok = jnp.argmax(d_logits[:, -1], axis=-1)
    for _ in range(4):
        d_logits, dense = forward_cached(params, tok[:, None], dense, cfg)
        p_logits, pool = forward_paged(params, tok[:, None], pool, cfg)
        assert jnp.array_equal(d_logits, p_logits)
        tok = jnp.argmax(d_logits[:, -1], axis=-1)
    assert int(pool["pos"][0]) == int(dense["pos"])


def test_forward_paged_rope_per_row_positions():
    """Two rows at DIFFERENT depths in one batched step: per-row pos
    feeds rope and the mask, and each row matches its own solo run."""
    from nvidia_terraform_modules_tpu.models.decode import (
        forward_cached,
        forward_paged,
    )
    from nvidia_terraform_modules_tpu.models import init_cache

    cfg = BurnInConfig(**{**CFG, "rope": True})
    params = init_params(jax.random.PRNGKey(0), cfg)
    bs, max_len = 4, 12
    lens = (3, 7)
    solo_caches, solo_toks = [], []
    for i, L in enumerate(lens):
        prompt = jax.random.randint(jax.random.PRNGKey(i), (1, L), 0,
                                    cfg.vocab)
        c = init_cache(cfg, 1, max_len)
        lg, c = forward_cached(params, prompt, c, cfg)
        solo_caches.append(c)
        solo_toks.append(jnp.argmax(lg[:, -1], axis=-1))

    pool = init_paged_cache(cfg, 2, max_len, block_size=bs, num_blocks=9)
    pool["block_tables"] = jnp.asarray([[1, 2, 3], [4, 5, 6]], jnp.int32)
    for i, L in enumerate(lens):
        prompt = jax.random.randint(jax.random.PRNGKey(i), (1, L), 0,
                                    cfg.vocab)
        sub = dict(pool, block_tables=pool["block_tables"][i][None],
                   pos=jnp.zeros((1,), jnp.int32))
        _lg, sub = forward_paged(params, prompt, sub, cfg,
                                 prefill_impl="dense")
        pool = dict(pool, k=sub["k"], v=sub["v"])
    pool["pos"] = jnp.asarray(lens, jnp.int32)

    toks = jnp.concatenate(solo_toks)
    for _ in range(3):
        lg, pool = forward_paged(params, toks[:, None], pool, cfg)
        nxt = jnp.argmax(lg[:, -1], axis=-1)
        for i in range(2):
            s_lg, solo_caches[i] = forward_cached(
                params, solo_toks[i][:, None], solo_caches[i], cfg)
            solo_toks[i] = jnp.argmax(s_lg[:, -1], axis=-1)
            assert jnp.array_equal(nxt[i], solo_toks[i][0]), \
                "batched per-row decode diverged from solo"
        toks = nxt


def test_forward_paged_active_mask_fences_writes_to_garbage():
    """A dead slot's writes must land in block 0 and its pos freeze —
    the fence that keeps a retired slot from corrupting blocks already
    recycled to another request."""
    from nvidia_terraform_modules_tpu.models.decode import forward_paged

    cfg = BurnInConfig(**CFG)
    params = init_params(jax.random.PRNGKey(0), cfg)
    pool = init_paged_cache(cfg, 2, 8, block_size=4, num_blocks=4)
    # slot 1 (dead) points at the SAME blocks as slot 0 (live): without
    # the fence its write would corrupt slot 0's rows
    pool["block_tables"] = jnp.asarray([[1, 2], [1, 2]], jnp.int32)
    pool["pos"] = jnp.asarray([3, 3], jnp.int32)
    before_k = pool["k"][0]
    toks = jnp.asarray([5, 9], jnp.int32)
    active = jnp.asarray([True, False])
    _lg, pool = forward_paged(params, toks[:, None], pool, cfg,
                              active=active)
    assert int(pool["pos"][0]) == 4 and int(pool["pos"][1]) == 3
    # block 0 (garbage) took the dead slot's row; blocks 1/2 changed
    # only at the live slot's write row
    assert not jnp.array_equal(pool["k"][0][0], before_k[0])
    live_row_changed = not jnp.array_equal(pool["k"][0][1], before_k[1])
    assert live_row_changed


def test_forward_paged_int8_scales_ride_the_tables():
    """Int8 paged storage: quantised rows and their scale sidecars
    gather through the same tables; results equal the dense int8
    cache's bit for bit."""
    from nvidia_terraform_modules_tpu.models.decode import (
        forward_cached,
        forward_paged,
    )
    from nvidia_terraform_modules_tpu.models import init_cache

    cfg = BurnInConfig(**CFG)
    params = init_params(jax.random.PRNGKey(0), cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(2), (1, 5), 0,
                                cfg.vocab)
    dense = init_cache(cfg, 1, 12, cache_dtype="int8")
    d_lg, dense = forward_cached(params, prompt, dense, cfg)
    pool = init_paged_cache(cfg, 1, 12, block_size=4, num_blocks=70,
                            cache_dtype="int8")
    nt = pool["block_tables"].shape[1]
    # scattered tables across the (256-row-grained) int8 pool
    pool["block_tables"] = (jnp.arange(nt, dtype=jnp.int32)[None] * 2
                            + 1)
    p_lg, pool = forward_paged(params, prompt, pool, cfg,
                               prefill_impl="dense")
    assert jnp.array_equal(d_lg, p_lg)
    tok = jnp.argmax(d_lg[:, -1], axis=-1)
    for _ in range(3):
        d_lg, dense = forward_cached(params, tok[:, None], dense, cfg)
        p_lg, pool = forward_paged(params, tok[:, None], pool, cfg)
        assert jnp.array_equal(d_lg, p_lg)
        tok = jnp.argmax(d_lg[:, -1], axis=-1)
