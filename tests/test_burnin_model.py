# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""Burn-in transformer: forward, sharded train step, loss decreases."""

import jax
import jax.numpy as jnp

from nvidia_terraform_modules_tpu.models import (
    BurnInConfig,
    forward,
    init_params,
    loss_fn,
    make_train_step,
    synthetic_batch,
)
from nvidia_terraform_modules_tpu.parallel import build_mesh, make_rules, plan_mesh

CFG = BurnInConfig(vocab=64, d_model=32, n_heads=2, d_ff=64, n_layers=2, seq_len=16, batch=4)


def test_forward_shapes_unsharded():
    params = init_params(jax.random.PRNGKey(0), CFG)
    tokens, _ = synthetic_batch(jax.random.PRNGKey(1), CFG)
    logits = forward(params, tokens, CFG)
    assert logits.shape == (CFG.batch, CFG.seq_len, CFG.vocab)
    assert jnp.isfinite(logits.astype(jnp.float32)).all()


def test_loss_finite_unsharded():
    params = init_params(jax.random.PRNGKey(0), CFG)
    batch = synthetic_batch(jax.random.PRNGKey(1), CFG)
    loss = loss_fn(params, batch, CFG)
    assert jnp.isfinite(loss)


def test_sharded_train_step_decreases_loss(jax8):
    mesh = build_mesh(plan_mesh(8, tp=2, sp=2))
    rules = make_rules(mesh)
    cfg = BurnInConfig(vocab=64, d_model=32, n_heads=2, d_ff=64, n_layers=2,
                       seq_len=16, batch=8)
    params = init_params(jax.random.PRNGKey(0), cfg, rules)
    step = make_train_step(cfg, rules, lr=5e-2)
    batch = synthetic_batch(jax.random.PRNGKey(1), cfg, rules)
    losses = []
    for _ in range(8):
        params, loss = step(params, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses


def test_sharded_matches_unsharded_forward(jax8):
    """Sharding annotations must not change numerics (same program, laid out)."""
    mesh = build_mesh(plan_mesh(8, tp=2, sp=2))
    rules = make_rules(mesh)
    cfg = BurnInConfig(vocab=64, d_model=32, n_heads=2, d_ff=64, n_layers=1,
                       seq_len=16, batch=8, dtype=jnp.float32)
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens, _ = synthetic_batch(jax.random.PRNGKey(1), cfg)
    ref = forward(params, tokens, cfg)
    sharded_params = init_params(jax.random.PRNGKey(0), cfg, rules)
    got = forward(sharded_params, jax.device_put(tokens, rules.shard(
        jax.sharding.PartitionSpec("dp", None))), cfg, rules)
    assert jnp.allclose(ref, got, atol=1e-5)


def test_remat_is_gradient_exact():
    """remat=True must change memory, never math: loss AND grads identical."""
    import jax.numpy as jnp

    from nvidia_terraform_modules_tpu.models import loss_fn

    base = dict(vocab=64, d_model=32, n_heads=2, d_ff=64, n_layers=2,
                seq_len=16, batch=4, dtype=jnp.float32)
    cfg = BurnInConfig(**base)
    cfg_r = BurnInConfig(**base, remat=True)
    params = init_params(jax.random.PRNGKey(0), cfg)
    batch = synthetic_batch(jax.random.PRNGKey(1), cfg)
    l, g = jax.value_and_grad(loss_fn)(params, batch, cfg)
    lr_, gr = jax.value_and_grad(loss_fn)(params, batch, cfg_r)
    assert float(l) == float(lr_)
    for a, b in zip(jax.tree.leaves(g), jax.tree.leaves(gr)):
        # ulp-tight rather than bitwise: some XLA CPU versions reassociate
        # the rematerialised forward's fusions, shifting grads by ~1e-8 —
        # a compiler scheduling artifact, not a remat math change
        assert jnp.allclose(a, b, rtol=1e-6, atol=1e-7)


def test_remat_trains_sharded(jax8):
    from nvidia_terraform_modules_tpu.parallel import (
        build_mesh,
        make_rules,
        plan_mesh,
    )

    mesh = build_mesh(plan_mesh(8, tp=2, sp=2))
    rules = make_rules(mesh)
    cfg = BurnInConfig(vocab=64, d_model=32, n_heads=4, d_ff=64, n_layers=2,
                       seq_len=16, batch=8, remat=True, attn="ulysses")
    params = init_params(jax.random.PRNGKey(0), cfg, rules)
    step = make_train_step(cfg, rules, lr=5e-2)
    batch = synthetic_batch(jax.random.PRNGKey(1), cfg, rules)
    losses = []
    for _ in range(6):
        params, loss = step(params, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses


def test_grad_accum_matches_full_batch():
    """Averaged microbatch grads equal full-batch grads (loss is a mean)."""
    import jax.numpy as jnp

    cfg = BurnInConfig(vocab=64, d_model=32, n_heads=2, d_ff=64, n_layers=2,
                       seq_len=16, batch=8, dtype=jnp.float32)
    params = init_params(jax.random.PRNGKey(0), cfg)
    batch = synthetic_batch(jax.random.PRNGKey(1), cfg)
    full = make_train_step(cfg, lr=1e-2)
    accum = make_train_step(cfg, lr=1e-2, accum_steps=4)
    p_full, l_full = full(params, batch)
    p_acc, l_acc = accum(params, batch)
    assert abs(float(l_full) - float(l_acc)) < 1e-6
    for a, b in zip(jax.tree.leaves(p_full), jax.tree.leaves(p_acc)):
        assert float(jnp.max(jnp.abs(a - b))) < 1e-6


def test_grad_accum_sharded_and_adamw(jax8):
    import jax.numpy as jnp

    from nvidia_terraform_modules_tpu.models import (
        AdamWConfig,
        make_adamw_train_step,
    )
    from nvidia_terraform_modules_tpu.parallel import (
        build_mesh,
        make_rules,
        plan_mesh,
    )

    # dp=4 regression: per-device microbatch of 1 once stressed the SPMD
    # partitioner before the explicit microbatch sharding pin
    mesh = build_mesh(plan_mesh(8, tp=2, sp=1))
    rules = make_rules(mesh)
    cfg = BurnInConfig(vocab=64, d_model=32, n_heads=4, d_ff=64, n_layers=2,
                       seq_len=16, batch=8, remat=True)
    params = init_params(jax.random.PRNGKey(0), cfg, rules)
    init_state, step = make_adamw_train_step(cfg, rules, AdamWConfig(lr=1e-2),
                                             accum_steps=2)
    state = init_state(params)
    batch = synthetic_batch(jax.random.PRNGKey(1), cfg, rules)
    losses = []
    for _ in range(6):
        params, state, loss = step(params, state, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses


def test_grad_accum_rejects_bad_split():
    import pytest

    cfg = BurnInConfig(vocab=64, d_model=32, n_heads=2, d_ff=64, n_layers=1,
                       seq_len=16, batch=6)
    params = init_params(jax.random.PRNGKey(0), cfg)
    batch = synthetic_batch(jax.random.PRNGKey(1), cfg)
    step = make_train_step(cfg, accum_steps=4)
    with pytest.raises(ValueError, match="not divisible"):
        step(params, batch)


def test_gqa_forward_and_training(jax8):
    """GQA is a projection change, not a different attention: kv_heads ==
    n_heads reproduces MHA shapes, smaller kv_heads trains sharded."""
    import jax.numpy as jnp
    import pytest

    from nvidia_terraform_modules_tpu.parallel import (
        build_mesh,
        make_rules,
        plan_mesh,
    )

    base = dict(vocab=64, d_model=32, n_heads=4, d_ff=64, n_layers=2,
                seq_len=16, batch=8, dtype=jnp.float32)
    # explicit n_kv_heads == n_heads must equal the default exactly
    p1 = init_params(jax.random.PRNGKey(0), BurnInConfig(**base))
    p2 = init_params(jax.random.PRNGKey(0),
                     BurnInConfig(**base, n_kv_heads=4))
    for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(p2)):
        assert jnp.array_equal(a, b)

    cfg = BurnInConfig(**base, n_kv_heads=2)
    assert cfg.kv_heads == 2
    # K/V projections shrink with the KV head count
    params = init_params(jax.random.PRNGKey(0), cfg)
    assert params["layers"][0]["wk"].shape == (32, 2 * cfg.head_dim)

    mesh = build_mesh(plan_mesh(8, tp=2, sp=2))
    rules = make_rules(mesh)
    sp_params = init_params(jax.random.PRNGKey(0), cfg, rules)
    step = make_train_step(cfg, rules, lr=5e-2)
    batch = synthetic_batch(jax.random.PRNGKey(1), cfg, rules)
    losses = []
    for _ in range(6):
        sp_params, loss = step(sp_params, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses

    with pytest.raises(ValueError, match="n_kv_heads"):
        BurnInConfig(**base, n_kv_heads=3)   # 3 does not divide 4


def test_mqa_cache_replicates_heads_when_tp_does_not_divide(jax8):
    """MQA (kv_heads=1) on a tp=2 mesh: in-jit constraints pad unevenly,
    but device_put refuses — the cache falls back to a replicated head
    axis and sharded decode still works."""
    from nvidia_terraform_modules_tpu.models import init_cache
    from nvidia_terraform_modules_tpu.parallel import (
        build_mesh,
        make_rules,
        plan_mesh,
    )

    mesh = build_mesh(plan_mesh(8, tp=2, sp=1))
    rules = make_rules(mesh)
    cfg = BurnInConfig(vocab=64, d_model=32, n_heads=4, n_kv_heads=1,
                       d_ff=64, n_layers=1, seq_len=16, batch=8,
                       dtype=jnp.float32)
    cache = init_cache(cfg, 8, 32, rules)
    assert cache["k"][0].sharding.spec[2] is None      # heads replicated
    from nvidia_terraform_modules_tpu.models import greedy_decode

    params = init_params(jax.random.PRNGKey(0), cfg, rules)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (8, 6), 0, cfg.vocab)
    toks = jax.jit(
        lambda p, t: greedy_decode(p, t, 4, cfg, rules))(params, prompt)
    assert toks.shape == (8, 4)
    # the sharded TRAINING path with non-dividing KV heads (uneven
    # in-jit constraint, GSPMD pads) must keep working too
    step = make_train_step(cfg, rules, lr=5e-2)
    batch = synthetic_batch(jax.random.PRNGKey(1), cfg, rules)
    p2, l0 = step(params, batch)
    for _ in range(4):
        p2, loss = step(p2, batch)
    assert float(loss) < float(l0)


def test_gqa_flops_accounting():
    from nvidia_terraform_modules_tpu.models import train_step_flops

    base = dict(vocab=64, d_model=32, n_heads=4, d_ff=64, n_layers=1,
                seq_len=16, batch=2)
    mha = train_step_flops(BurnInConfig(**base))
    gqa = train_step_flops(BurnInConfig(**base, n_kv_heads=1))
    assert gqa < mha          # narrower K/V projections bill fewer FLOPs


def test_rope_position_sensitivity_and_training(jax8):
    """RoPE makes the model order-aware beyond the causal mask, trains
    sharded, and stays exact across attention layouts."""
    import jax.numpy as jnp
    import pytest

    from nvidia_terraform_modules_tpu.parallel import (
        build_mesh,
        make_rules,
        plan_mesh,
    )

    base = dict(vocab=64, d_model=32, n_heads=4, d_ff=64, n_layers=2,
                seq_len=16, batch=4, dtype=jnp.float32)
    cfg = BurnInConfig(**base, rope=True)
    params = init_params(jax.random.PRNGKey(0), cfg)

    # discriminating position test, single layer: keep the LAST query
    # token fixed and permute only the history. A 1-layer causal NoPE
    # model's last-position output is a content-weighted set function of
    # the history (permutation-INVARIANT); RoPE must break the invariance
    one = dict(base, n_layers=1)
    t = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 64)
    hist, last = t[:, :15], t[:, 15:]
    t_a = jnp.concatenate([hist, last], axis=1)
    t_b = jnp.concatenate([jnp.roll(hist, 5, axis=1), last], axis=1)
    nope_cfg = BurnInConfig(**one)
    nope_params = init_params(jax.random.PRNGKey(0), nope_cfg)
    na = forward(nope_params, t_a, nope_cfg)[:, -1]
    nb = forward(nope_params, t_b, nope_cfg)[:, -1]
    assert float(jnp.max(jnp.abs(na - nb))) < 1e-5     # NoPE: invariant
    rope_cfg = BurnInConfig(**one, rope=True)
    rope_params = init_params(jax.random.PRNGKey(0), rope_cfg)
    ra = forward(rope_params, t_a, rope_cfg)[:, -1]
    rb = forward(rope_params, t_b, rope_cfg)[:, -1]
    assert float(jnp.max(jnp.abs(ra - rb))) > 1e-4     # RoPE: sensitive

    # rope + ring attention on the mesh matches unsharded dense exactly
    mesh = build_mesh(plan_mesh(8, tp=2, sp=2))
    rules = make_rules(mesh)
    sp = init_params(jax.random.PRNGKey(0), cfg, rules)
    ref = forward(params, t, cfg)
    ring_cfg = BurnInConfig(**base, rope=True, attn="ring")
    got = jax.jit(lambda p, x: forward(p, x, ring_cfg, rules))(sp, t)
    assert float(jnp.max(jnp.abs(ref - got))) < 2e-5

    step = make_train_step(ring_cfg, rules, lr=5e-2)
    batch = synthetic_batch(jax.random.PRNGKey(1), ring_cfg, rules)
    p2, l0 = step(sp, batch)
    for _ in range(5):
        p2, loss = step(p2, batch)
    assert float(loss) < float(l0)

    with pytest.raises(ValueError, match="even head_dim"):
        BurnInConfig(vocab=64, d_model=12, n_heads=4, rope=True)
