"""MXU and HBM micro-probes.

The reference's only hardware validation is "wait ~5 minutes, then kubectl get
pods" (``/root/reference/gke/README.md:50``). These probes turn cluster burn-in
into numbers: achieved bf16 matmul TFLOP/s (MXU health) and f32 streaming
bandwidth (HBM health), reported as roofline fractions by ``bench.py``.

Shapes are static, large, and bf16 so XLA tiles them straight onto the
128×128 systolic array.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from ..utils.device import device_spec
from ..utils.timing import delta_time


def matmul_probe(n: int = 4096, dtype=jnp.bfloat16, iters: int = 8) -> dict[str, Any]:
    """Chained square matmuls; returns achieved TFLOP/s and roofline fraction.

    A `lax.scan` of dependent matmuls keeps the MXU busy across a single
    dispatch; the two-point ``delta_time`` measurement (``iters`` vs
    ``8*iters``) cancels fixed dispatch/readback latency, which otherwise
    dominates on tunnelled backends.
    """
    key = jax.random.PRNGKey(0)
    a = jax.random.normal(key, (n, n), dtype=dtype)
    b = jax.random.normal(jax.random.PRNGKey(1), (n, n), dtype=dtype)

    def make_chain(length):
        @jax.jit
        def chain(a, b):
            def step(acc, _):
                return jnp.dot(acc, b, preferred_element_type=jnp.float32).astype(dtype), None

            out, _ = jax.lax.scan(step, a, None, length=length)
            return out

        return chain

    secs_per_iter = delta_time(make_chain, a, b, iters_lo=iters, iters_hi=8 * iters)
    secs = secs_per_iter * iters
    flops = 2.0 * n * n * n * iters
    tflops = flops / secs / 1e12
    spec = device_spec()
    return {
        "n": n,
        "seconds": secs,
        "tflops": tflops,
        "roofline_fraction": tflops / spec.bf16_tflops,
        "device": spec.kind,
    }


def hbm_probe(mib: int = 256, iters: int = 8) -> dict[str, Any]:
    """Streaming triad (read 2, write 1 array); returns achieved GiB/s."""
    n = mib * (1 << 20) // 4  # f32 elements
    x = jnp.ones((n,), dtype=jnp.float32)
    y = jnp.full((n,), 2.0, dtype=jnp.float32)

    def make_triad(length):
        @jax.jit
        def triad(x, y):
            def step(acc, _):
                return acc * 1.0001 + y, None

            out, _ = jax.lax.scan(step, x, None, length=length)
            return out

        return triad

    secs_per_iter = delta_time(make_triad, x, y, iters_lo=iters, iters_hi=8 * iters)
    secs = secs_per_iter * iters
    moved = 3.0 * x.nbytes * iters  # read acc, read y, write acc
    gibps = moved / secs / (1 << 30)
    spec = device_spec()
    return {
        "mib": mib,
        "seconds": secs,
        "gibps": gibps,
        "roofline_fraction": gibps / (spec.hbm_gbps * 1e9 / (1 << 30)),
        "device": spec.kind,
    }
