# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
# Private certificate authority for in-cluster TLS.
#
# Capability parity with the reference's AWS Private CA composition
# (/root/reference/eks/examples/cnpack/aws-pca.tf:9-105): a ROOT CA the
# platform's cert-manager issuer chains from, plus the IAM that lets the
# issuer request certificates. GCP-native shape: Certificate Authority
# Service (CAS) pool + self-signed root, consumed by cert-manager's
# google-cas-issuer via Workload Identity — no node-role policy attachments
# (the reference grants issuing rights to node IAM roles; Workload Identity
# scopes it to the issuer's KSA instead).

variable "private_ca_enabled" {
  description = "Provision a Certificate Authority Service root CA (reference: pca_enabled)."
  type        = bool
  default     = true
}

variable "common_name" {
  description = "Common Name of the root CA certificate."
  type        = string
  default     = "cluster.local"
}

resource "google_privateca_ca_pool" "cnpack" {
  count = var.private_ca_enabled ? 1 : 0

  project  = var.project_id
  name     = "${var.cluster_name}-ca-pool"
  location = var.region
  tier     = "ENTERPRISE"

  publishing_options {
    publish_ca_cert = true
    publish_crl     = true
  }
}

# Self-signed ROOT authority. The reference uses RSA-4096/SHA-512
# (aws-pca.tf:13-14); CAS's strongest RSA PKCS1 signing spec is 4096/SHA-256.
resource "google_privateca_certificate_authority" "cnpack" {
  count = var.private_ca_enabled ? 1 : 0

  project                  = var.project_id
  pool                     = google_privateca_ca_pool.cnpack[count.index].name
  location                 = var.region
  certificate_authority_id = "${var.cluster_name}-root-ca"
  type                     = "SELF_SIGNED"

  # reference root cert validity: 1 year (aws-pca.tf:36-39)
  lifetime = "31536000s"

  key_spec {
    algorithm = "RSA_PKCS1_4096_SHA256"
  }

  config {
    subject_config {
      subject {
        common_name  = var.common_name
        organization = "tpu-platform"
      }
    }
    x509_config {
      ca_options {
        is_ca = true
      }
      key_usage {
        base_key_usage {
          cert_sign = true
          crl_sign  = true
        }
        extended_key_usage {
          server_auth = true
          client_auth = true
        }
      }
    }
  }

  # parity with permanent_deletion_time_in_days = 7 (aws-pca.tf:22): allow
  # terraform destroy to actually remove the CA instead of wedging the pool
  deletion_protection                    = false
  skip_grace_period                      = true
  ignore_active_certificates_on_deletion = true
}

# Identity for cert-manager's google-cas-issuer controller.
resource "google_service_account" "cas_issuer" {
  count = var.private_ca_enabled ? 1 : 0

  project      = var.project_id
  account_id   = "tpu-cas-issuer-${random_id.sa_suffix.hex}"
  display_name = "cert-manager CAS issuer for ${var.cluster_name}"
}

resource "google_service_account_iam_member" "cas_issuer_wi" {
  count = var.private_ca_enabled ? 1 : 0

  service_account_id = google_service_account.cas_issuer[count.index].name
  role               = "roles/iam.workloadIdentityUser"
  member             = "serviceAccount:${var.project_id}.svc.id.goog[cert-manager/google-cas-issuer]"
}

# Issuing rights scoped to the pool, not the project (least privilege vs the
# reference's node-role-wide policy, aws-pca.tf:74-105).
resource "google_privateca_ca_pool_iam_member" "cas_issuer_requester" {
  count = var.private_ca_enabled ? 1 : 0

  ca_pool = google_privateca_ca_pool.cnpack[count.index].id
  role    = "roles/privateca.certificateRequester"
  member  = "serviceAccount:${google_service_account.cas_issuer[count.index].email}"
}
