# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
# Values the operator pastes into the platform installer config — the same
# handoff shape as the reference's CNPack flow
# (/root/reference/eks/examples/cnpack/Readme.md:49-94), plus the TPU metric
# names GKE exports for the provisioned slice.

locals {
  # single source for values that appear both as standalone outputs and
  # inside the rendered platform_config — one edit point, no desync
  prometheus_ksa_annotation = "iam.gke.io/gcp-service-account: ${google_service_account.prometheus.email}"
  tpu_metric_types = [
    "kubernetes.io/node/accelerator/duty_cycle",
    "kubernetes.io/node/accelerator/memory_used",
    "kubernetes.io/node/accelerator/memory_total",
    "kubernetes.io/container/accelerator/tensorcore_utilization",
  ]
}

output "cluster_name" {
  description = "Name of the TPU cluster."
  value       = module.tpu_cluster.cluster_name
}

output "prometheus_service_account_email" {
  description = "GSA the monitoring KSA impersonates (annotate the KSA with this)."
  value       = google_service_account.prometheus.email
}

output "prometheus_ksa_annotation" {
  description = "Ready-to-paste Workload Identity annotation for the monitoring KSA."
  value       = local.prometheus_ksa_annotation
}

output "monitoring_namespace" {
  description = "Namespace the monitoring stack must be installed into."
  value       = local.monitoring_namespace
}

output "tpu_slices" {
  description = "Slice facts (selectors, hosts, chips) for scrape-config targeting."
  value       = module.tpu_cluster.tpu_slices
}

output "tpu_metric_types" {
  description = "GKE system metrics exported for TPU nodes; use in dashboards/alerts."
  value       = local.tpu_metric_types
}

output "ca_pool" {
  description = "CAS pool the GoogleCASClusterIssuer must reference (null when private_ca_enabled = false)."
  value       = var.private_ca_enabled ? google_privateca_ca_pool.cnpack[0].name : null
}

output "ca_resource_name" {
  description = "Fully-qualified root CA resource (paste into the issuer spec)."
  value       = var.private_ca_enabled ? google_privateca_certificate_authority.cnpack[0].id : null
}

output "cas_issuer_service_account_email" {
  description = "GSA the cert-manager google-cas-issuer KSA impersonates."
  value       = var.private_ca_enabled ? google_service_account.cas_issuer[0].email : null
}

output "fluentbit_service_account_email" {
  description = "GSA the Fluent Bit DaemonSet KSA impersonates."
  value       = var.fluentbit_enabled ? google_service_account.fluentbit[0].email : null
}

output "log_bucket" {
  description = "Dedicated Cloud Logging bucket receiving cluster logs."
  value       = var.fluentbit_enabled ? google_logging_project_bucket_config.cnpack[0].bucket_id : null
}

# ------------------------------------------------------------------ handoff
# The reference ends with a HUMAN step: copy ~10 terraform outputs into an
# NvidiaPlatform YAML and feed it to the external `cnpack` binary
# (/root/reference/eks/examples/cnpack/Readme.md:49-105). Render the whole
# installer config instead — `terraform output -raw platform_config_yaml`
# is the entire handoff, no transcription errors possible.

locals {
  platform_config = {
    apiVersion = "tpu.nvidia-terraform-modules/v1"
    kind       = "TpuPlatform"
    metadata = {
      name = module.tpu_cluster.cluster_name
    }
    spec = {
      cluster = {
        name     = module.tpu_cluster.cluster_name
        location = module.tpu_cluster.cluster_location
        project  = var.project_id
      }
      monitoring = {
        namespace           = local.monitoring_namespace
        serviceAccountEmail = google_service_account.prometheus.email
        ksaAnnotation       = local.prometheus_ksa_annotation
        tpuMetricTypes      = local.tpu_metric_types
      }
      certManager = var.private_ca_enabled ? {
        casIssuer = {
          caPool              = google_privateca_ca_pool.cnpack[0].name
          caResourceName      = google_privateca_certificate_authority.cnpack[0].id
          serviceAccountEmail = google_service_account.cas_issuer[0].email
        }
      } : null
      logging = var.fluentbit_enabled ? {
        fluentbit = {
          serviceAccountEmail = google_service_account.fluentbit[0].email
          logBucket           = google_logging_project_bucket_config.cnpack[0].bucket_id
        }
      } : null
      slices = module.tpu_cluster.tpu_slices
    }
  }
}

output "platform_config" {
  description = "Structured platform installer config (the automated NvidiaPlatform handoff)."
  value       = local.platform_config
}

output "platform_config_yaml" {
  description = "Same config rendered for the installer: terraform output -raw platform_config_yaml > platform.yaml"
  value       = yamlencode(local.platform_config)
}
