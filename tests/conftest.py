"""Test rig: force an 8-device virtual CPU platform BEFORE jax initialises.

This mirrors the SURVEY §4 implication: the reference tests nothing without a
live cloud; we exercise every collective/sharding path on a virtual mesh
(XLA host-platform device count), so `pytest` needs no TPU and no cloud.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # force: the session env may point at a TPU
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# Some rigs pre-import jax (sitecustomize) with a TPU platform already chosen;
# the backend is lazy, so a config update before first use still wins.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def jax8():
    import jax

    assert len(jax.devices()) == 8, "virtual 8-device CPU platform not active"
    return jax


@pytest.fixture(scope="session")
def repo_root():
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
