# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""Mesh planning + collective probes on the 8-device virtual mesh."""

import pytest

from nvidia_terraform_modules_tpu.parallel import (
    build_mesh,
    plan_mesh,
)
from nvidia_terraform_modules_tpu.parallel.collectives import (
    all_gather_probe,
    psum_probe,
    reduce_scatter_probe,
    all_to_all_probe,
    ring_permute_probe,
)


def test_plan_mesh_default_factorisation():
    plan = plan_mesh(8)
    assert plan.shape == (2, 1, 4)
    assert plan.axis_names == ("dp", "sp", "tp")
    assert plan.n_devices == 8


def test_plan_mesh_explicit_tp_sp():
    plan = plan_mesh(8, tp=2, sp=2)
    assert plan.shape == (2, 2, 2)


def test_plan_mesh_rejects_nondividing():
    with pytest.raises(ValueError):
        plan_mesh(8, tp=3)


def test_build_mesh_shape(jax8):
    mesh = build_mesh(plan_mesh(8))
    assert dict(mesh.shape) == {"dp": 2, "sp": 1, "tp": 4}


def test_psum_probe_all_devices(jax8):
    mesh = build_mesh(plan_mesh(8, tp=1, sp=1))
    r = psum_probe(mesh, axis="dp", n_elems=1 << 10)
    assert r["ok"]
    assert r["participants"] == 8


def test_all_gather_probe(jax8):
    mesh = build_mesh(plan_mesh(8))
    r = all_gather_probe(mesh, axis="tp", n_elems=64)
    assert r["ok"]


def test_reduce_scatter_probe(jax8):
    mesh = build_mesh(plan_mesh(8))
    r = reduce_scatter_probe(mesh, axis="tp", n_elems=64)
    assert r["ok"]


def test_ring_permute_probe(jax8):
    mesh = build_mesh(plan_mesh(8, tp=1, sp=1))
    r = ring_permute_probe(mesh, axis="dp", n_elems=64)
    assert r["ok"]


def test_all_to_all_probe_on_ep_axis(jax8):
    """The MoE dispatch collective, over a real expert axis."""
    mesh = build_mesh(plan_mesh(8, ep=2, tp=2))
    r = all_to_all_probe(mesh, axis="ep", n_elems=64)
    assert r["ok"]
    assert r["participants"] == 2


def test_all_to_all_probe_all_devices(jax8):
    mesh = build_mesh(plan_mesh(8, tp=1, sp=1))
    r = all_to_all_probe(mesh, axis="dp", n_elems=64)
    assert r["ok"]
    assert r["participants"] == 8
