# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""Device-mesh planning.

A slice's physical topology comes from the Terraform layer
(``gke-tpu`` variable ``tpu_topology``, e.g. ``"2x4"``); at runtime we fold the
visible devices into a logical mesh with named axes:

- ``dp``  — data parallel (gradient psum rides ICI)
- ``tp``  — tensor/model parallel (activations all-gather / reduce-scatter)
- ``sp``  — sequence/context parallel (ring collectives for long context)
- ``ep``  — expert parallel (MoE dispatch/combine all-to-alls), present
  only when requested (``ep > 1``) so dense workloads keep 3-axis meshes

The planner keeps ``tp`` innermost so tensor-parallel collectives map onto the
fastest ICI dimension, mirroring the scaling-book recipe: pick a mesh, annotate
shardings, let XLA insert the collectives.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Sequence


@dataclasses.dataclass(frozen=True)
class MeshPlan:
    """A named logical mesh shape over ``n_devices`` chips."""

    axis_names: tuple[str, ...]
    shape: tuple[int, ...]

    @property
    def n_devices(self) -> int:
        return math.prod(self.shape)

    def describe(self) -> str:
        return " × ".join(f"{n}:{s}" for n, s in zip(self.axis_names, self.shape))


def plan_mesh(
    n_devices: int,
    *,
    tp: int | None = None,
    sp: int = 1,
    ep: int = 1,
    axis_names: Sequence[str] | None = None,
) -> MeshPlan:
    """Choose a (dp[, ep], sp, tp) factorisation of ``n_devices``.

    ``tp`` defaults to the largest power of two ≤ 4 dividing the device count —
    small enough that a v5e-8 slice still has a data axis, large enough to
    exercise tensor-parallel collectives. ``ep > 1`` inserts an expert
    axis between dp and sp (axes ``("dp", "ep", "sp", "tp")``) — MoE
    dispatch all-to-alls then ride the same ICI ring the data axis uses,
    while dense workloads keep the 3-axis mesh unchanged.
    """
    if n_devices < 1:
        raise ValueError(f"n_devices must be >= 1, got {n_devices}")
    if ep < 1 or n_devices % (sp * ep) != 0:
        raise ValueError(
            f"ep*sp = {ep}*{sp} does not divide device count {n_devices}")
    if tp is None:
        tp = 1
        while tp < 4 and n_devices % (tp * 2 * sp * ep) == 0:
            tp *= 2
    if n_devices % (tp * sp * ep) != 0:
        raise ValueError(
            f"tp*sp*ep = {tp}*{sp}*{ep} does not divide device count "
            f"{n_devices}"
        )
    dp = n_devices // (tp * sp * ep)
    shape = (dp, ep, sp, tp) if ep > 1 else (dp, sp, tp)
    names = tuple(axis_names) if axis_names is not None else (
        ("dp", "ep", "sp", "tp") if ep > 1 else ("dp", "sp", "tp"))
    if len(names) != len(shape):
        raise ValueError(
            f"axis_names {names} has {len(names)} names for a "
            f"{len(shape)}-axis mesh {shape} (ep > 1 adds an axis)")
    return MeshPlan(names, shape)


def build_mesh(plan: MeshPlan | None = None, *, devices=None):
    """Materialise a ``jax.sharding.Mesh`` for ``plan`` over ``devices``.

    Uses ``mesh_utils.create_device_mesh`` when the full process-global device
    set is used, so physical ICI neighbours land adjacent in the logical mesh;
    falls back to a plain reshape for explicit device subsets.
    """
    import jax
    from jax.experimental import mesh_utils
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    if plan is None:
        plan = plan_mesh(len(devices))
    if plan.n_devices != len(devices):
        raise ValueError(
            f"plan wants {plan.n_devices} devices, got {len(devices)}"
        )
    import numpy as np

    if len(devices) == len(jax.devices()) and all(
        a is b for a, b in zip(devices, jax.devices())
    ):
        dev_array = mesh_utils.create_device_mesh(plan.shape, devices=devices)
    else:
        dev_array = np.asarray(devices).reshape(plan.shape)
    return Mesh(dev_array, plan.axis_names)
