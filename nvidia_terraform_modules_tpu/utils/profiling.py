# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""Device trace capture: the profiling tier above ``utils/timing``.

``timing`` answers "how long" (wall-clock medians with tunnel-safe
sync); this module answers "WHY" — it captures XLA device traces
(per-kernel timelines, HLO op names, memory allocations) through
``jax.profiler``, viewable in TensorBoard's profile plugin or Perfetto.
On TPU the trace includes per-core step breakdowns — the tool for
finding whether a slow step is MXU-bound, HBM-bound, or host-stalled,
which a scalar seconds number cannot say.

Reference analogue: none — SURVEY §5 records the reference has no
tracing/profiling beyond resource timeouts; this is build-side depth
the TPU workload tier needs (BASELINE targets are roofline fractions,
and roofline claims should be checkable against a real trace).

Usage::

    from nvidia_terraform_modules_tpu.utils import device_trace, annotate

    with device_trace("/tmp/trace"):            # one capture window
        with annotate("train_step"):            # named timeline region
            out = step(params, batch)
        sync(out)                               # capture real execution

The capture window must contain the device SYNC, not just the dispatch
— an async dispatch that outlives the window records as a host stub
with no device activity (the same pitfall ``timing.sync`` exists for).
"""

from __future__ import annotations

import os
from contextlib import contextmanager
from typing import Any, Callable, Iterator

from .timing import sync


@contextmanager
def device_trace(log_dir: str, *, host_tracer_level: int = 2,
                 python_tracer_level: int = 0) -> Iterator[str]:
    """Capture a ``jax.profiler`` trace of the enclosed block.

    Writes a TensorBoard-profile/Perfetto trace under ``log_dir``
    (created if needed) and yields that path. ``host_tracer_level``
    controls host-side instrumentation detail (0 disables);
    ``python_tracer_level`` > 0 additionally records the Python stack
    (costly — leave off for kernel work). Nesting is refused by jax
    itself (one active trace per process).
    """
    import jax

    os.makedirs(log_dir, exist_ok=True)
    if hasattr(jax.profiler, "ProfileOptions"):
        opts = jax.profiler.ProfileOptions()
        opts.host_tracer_level = host_tracer_level
        opts.python_tracer_level = python_tracer_level
        jax.profiler.start_trace(
            log_dir,
            create_perfetto_link=False,
            create_perfetto_trace=True,
            profiler_options=opts)
    else:
        # older jax has no ProfileOptions; trace with its defaults rather
        # than refusing to trace at all
        jax.profiler.start_trace(
            log_dir,
            create_perfetto_link=False,
            create_perfetto_trace=True)
    try:
        yield log_dir
    finally:
        jax.profiler.stop_trace()


@contextmanager
def annotate(name: str, telemetry=None) -> Iterator[None]:
    """Named region on the trace timeline (``jax.profiler``'s
    ``TraceAnnotation``): dispatches issued inside the block — and
    their device kernels — group under ``name`` in the viewer. Cheap
    enough to leave in production code; a no-op when no trace is
    active.

    When the telemetry plane is active (``TPU_TELEMETRY_DIR`` or an
    injected registry), the same ``name`` is ALSO emitted as a host-side
    telemetry span — so an XLA device trace and the telemetry timeline
    correlate region-for-region by name (the ``device_trace`` capture
    shows the kernels, the telemetry span shows where that region sits
    among checkpoints, restarts, and serve requests).
    """
    import jax

    from ..telemetry import get_registry

    reg = telemetry if telemetry is not None else get_registry()
    if reg.enabled:
        with reg.span(name), jax.profiler.TraceAnnotation(name):
            yield
    else:
        with jax.profiler.TraceAnnotation(name):
            yield


def trace_once(fn: Callable[..., Any], *args: Any, log_dir: str,
               warmup: int = 1, **kwargs: Any) -> tuple[Any, str]:
    """Capture one SYNCED call of ``fn`` → ``(out, trace_dir)``.

    ``warmup`` untimed calls first keep XLA compilation out of the
    capture (a first-call trace is 99% compiler, which hides the
    steady-state kernels being diagnosed). The traced call is synced
    inside the window via ``timing.sync`` so device execution — not
    just dispatch — lands in the capture.
    """
    for _ in range(warmup):
        sync(fn(*args, **kwargs))
    with device_trace(log_dir) as path:
        with annotate(getattr(fn, "__name__", "traced_fn")):
            out = fn(*args, **kwargs)
        sync(out)
    return out, path


def trace_artifacts(log_dir: str) -> list[str]:
    """Paths of trace files produced under ``log_dir`` (the
    ``plugins/profile/<run>/`` layout TensorBoard expects). Empty means
    the capture recorded nothing — usually a window that missed the
    sync.

    Deterministically sorted by path *components*, independent of
    ``os.walk``'s directory enumeration order: callers golden-test and
    diff these lists, and a flat string sort is separator-dependent
    (``a-b/`` vs ``a/b`` order flips with the platform separator).
    """
    found: list[str] = []
    for root, dirs, files in os.walk(log_dir):
        dirs.sort()   # deterministic descent, platform-independent
        found.extend(os.path.join(root, f) for f in sorted(files)
                     if f.endswith((".xplane.pb", ".perfetto-trace",
                                    ".json.gz")))
    return sorted(found, key=lambda p: p.split(os.sep))
