# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
# Cluster-auth wiring (L5): point the kubernetes and helm providers at the
# cluster created in this same apply.
#
# Capability parity with /root/reference/gke/providers.tf:4-20 — the one
# bootstrap approach of the reference's three that needs no local-exec and no
# kubeconfig mutation (survey §3.3 discusses why the AKS local-exec variant is
# worse); adopted here per SURVEY.md §7.

data "google_client_config" "current" {}

locals {
  cluster_endpoint = "https://${google_container_cluster.this.endpoint}"
  cluster_ca       = base64decode(google_container_cluster.this.master_auth[0].cluster_ca_certificate)
}

provider "kubernetes" {
  host                   = local.cluster_endpoint
  token                  = data.google_client_config.current.access_token
  cluster_ca_certificate = local.cluster_ca
}

provider "helm" {
  kubernetes {
    host                   = local.cluster_endpoint
    token                  = data.google_client_config.current.access_token
    cluster_ca_certificate = local.cluster_ca
  }
}
