# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""Kill-and-resume chaos gate: the training-stack mirror of the
``tfsim chaos`` convergence gate in tests/test_tfsim_faults.py, layered
the same way — ONE seeded kill-and-resume case, ONE seeded *elastic*
(shrink/continue/grow-back) case, plus the checkpoint-corruption path
stay tier-1; the full seeds × signal × kill-step × world matrix
(including the 2-process gloo worlds, the dead-peer classification, and
the elastic shrink/grow matrix) is slow-marked.

Every case asserts the exact-resume invariants inside
``smoketest.chaos.run_case``: final params/opt-state bit-match an
uninterrupted run (comfortably inside the ulp-tolerance bar), the step
count is exact, no quarantined checkpoint is ever restored, and repeated
kill-at-step-k replays are deterministic.
"""

import dataclasses
import glob
import os

import pytest

from nvidia_terraform_modules_tpu.smoketest.chaos import (
    ChaosCase,
    ChaosInvariantError,
    Supervisor,
    run_case,
)


def test_chaos_case_validation():
    with pytest.raises(ValueError):
        ChaosCase(seed=0, kill_signal="SIGSTOP")
    with pytest.raises(ValueError):
        ChaosCase(seed=0, kill_signal="SIGKILL", kill_scope="one", nprocs=1)
    with pytest.raises(ValueError):
        ChaosCase(seed=0, kill_signal="SIGKILL", kill_scope="some")


def test_elastic_case_validation():
    # elastic needs an armed ONE-peer kill (a whole-world kill leaves no
    # survivors) and room to pause before the configured total
    with pytest.raises(ValueError, match="one-peer"):
        ChaosCase(seed=0, kill_signal="SIGKILL", kill_step=3, nprocs=2,
                  total_steps=6, elastic=True)
    with pytest.raises(ValueError, match="one-peer"):
        ChaosCase(seed=0, kill_signal="", nprocs=2, kill_scope="one",
                  elastic=True)
    with pytest.raises(ValueError, match="total_steps"):
        ChaosCase(seed=0, kill_signal="SIGKILL", kill_step=4, nprocs=2,
                  total_steps=5, kill_scope="one", elastic=True)
    # a kill before the first commit would leave nothing to re-shard —
    # reject the config up front, not as a misleading invariant failure
    with pytest.raises(ValueError, match="save_every"):
        ChaosCase(seed=0, kill_signal="SIGKILL", kill_step=1, nprocs=2,
                  total_steps=6, kill_scope="one", elastic=True)
    with pytest.raises(ValueError, match="save_every"):
        ChaosCase(seed=0, kill_signal="SIGKILL", kill_step=2, nprocs=2,
                  total_steps=6, save_every=2, kill_scope="one",
                  elastic=True)
    ok = ChaosCase(seed=0, kill_signal="SIGKILL", kill_step=3, nprocs=2,
                   total_steps=6, kill_scope="one", elastic=True)
    assert ok.pause_step == 4


def test_elastic_restart_schedule_is_evidence_driven(tmp_path):
    """The shrink decision needs evidence a peer is GONE — the
    survivor's classified EXIT_PEER_DEAD or a signal death. Transient
    failures with every peer alive (positive exit codes: a corruption
    retry, an init timeout) keep the current shape; the classified
    pause grows back."""
    from nvidia_terraform_modules_tpu.models.resilience import (
        EXIT_ELASTIC_PAUSE,
        EXIT_PEER_DEAD,
    )

    case = ChaosCase(seed=0, kill_signal="SIGKILL", kill_step=3, nprocs=2,
                     total_steps=6, kill_scope="one", elastic=True)
    sup = Supervisor(case, str(tmp_path))
    assert sup._plan_attempt(None, 2) == (2, 0)            # attempt 0
    assert sup._plan_attempt([-9, EXIT_PEER_DEAD], 2) == (1, 4)  # kill
    assert sup._plan_attempt([EXIT_PEER_DEAD], 2) == (1, 4)
    assert sup._plan_attempt([1, 1], 2) == (2, 0)          # transient
    assert sup._plan_attempt([1], 1) == (1, 4)             # stay reduced
    assert sup._plan_attempt([EXIT_ELASTIC_PAUSE], 1) == (2, 0)  # grow
    # non-elastic: always the configured shape
    plain = Supervisor(dataclasses.replace(
        case, elastic=False, kill_scope="world"), str(tmp_path))
    assert plain._plan_attempt([-9], 1) == (2, 0)


def test_elastic_one_peer_kill_shrinks_then_grows_back_tier1(tmp_path):
    """THE elastic acceptance gate, tier-1: a seeded one-peer SIGKILL in
    a 2-process gloo world. The survivor classifies the dead peer, the
    supervisor re-forms a 1-process world that elastic-restores the
    2-process checkpoint and CONTINUES (its pause-step params bit-match
    a fresh 1-process restore from the same checkpoint — asserted inside
    run_elastic_case), then grows back to 2 processes with the exact
    step count, no quarantined checkpoint restored, and a deterministic
    seed replay of the whole elastic leg."""
    report = run_case(
        ChaosCase(seed=0, kill_signal="SIGKILL", kill_step=3, nprocs=2,
                  total_steps=6, kill_scope="one", elastic=True),
        str(tmp_path))
    assert report["converged"] is True
    # the world sequence: full → survivors (paused at kill+1) → full
    assert [(w, s) for _, w, s in report["worlds"]] == \
        [(2, 0), (1, 4), (2, 0)]
    assert report["quarantined"] == []   # clean kill: no bad bytes


def test_seeded_sigkill_resume_exact_tier1(tmp_path):
    """THE acceptance gate, tier-1: a seeded SIGKILL at step 3 of 6, the
    supervisor restarts, and the resumed run reaches the uninterrupted
    run's final params/opt-state exactly, with exact step count and a
    deterministic replay."""
    report = run_case(
        ChaosCase(seed=0, kill_signal="SIGKILL", kill_step=3,
                  nprocs=1, total_steps=6),
        str(tmp_path))
    assert report["converged"] is True
    assert report["attempts"]["killed"] == 2   # death + one resume
    assert report["attempts"]["baseline"] == 1
    assert report["quarantined"] == []         # clean kill: no bad bytes


def test_corrupted_newest_checkpoint_quarantined_on_resume_tier1(tmp_path):
    """Tier-1 corruption leg of the gate: the checkpoint that would be
    resumed is truncated between death and restart. The engine must
    quarantine it, resume from the step before, and STILL reach the
    uninterrupted run's final state — and the journal must prove the
    quarantined step was never restored."""
    case = ChaosCase(seed=1, kill_signal="SIGKILL", kill_step=4,
                     nprocs=1, total_steps=6)
    baseline_dir = tmp_path / "baseline"
    killed_dir = tmp_path / "killed"
    baseline = Supervisor(
        ChaosCase(seed=1, kill_signal="", nprocs=1, total_steps=6),
        str(baseline_dir)).run_to_completion()

    def corrupt_newest(attempt):
        if attempt != 1:
            return
        shards = sorted(glob.glob(
            str(killed_dir / "step_*" / "shards_p*.bin")))
        newest = shards[-1]
        with open(newest, "r+b") as fh:
            fh.truncate(8)

    killed = Supervisor(case, str(killed_dir),
                        on_restart=corrupt_newest).run_to_completion()

    # exact final state despite losing the newest checkpoint to rot
    assert {v["digest"] for v in killed["verdicts"]} == \
        {v["digest"] for v in baseline["verdicts"]}
    assert {v["step"] for v in killed["verdicts"]} == {6}
    # step 3 (the newest commit at death) was quarantined, resume came
    # from step 2, and no journal entry ever restored a quarantined step
    assert any(q.startswith("step_00000003") for q in killed["quarantined"])
    resumes = [e["resumed_from"] for e in killed["journal"]
               if e["attempt"] == 1]
    assert resumes == [2]
    for entry in killed["journal"]:
        r = entry.get("resumed_from")
        if r is not None:
            assert not any(
                q.startswith(f"step_{r:08d}")
                for q in entry.get("quarantined", []))


def test_invariant_violation_is_loud(tmp_path):
    """The gate must FAIL when the invariant fails: a case whose killed
    run cannot complete inside the restart budget raises, it does not
    return a green report."""
    case = ChaosCase(seed=0, kill_signal="SIGKILL", kill_step=1,
                     nprocs=1, total_steps=3)
    sup = Supervisor(case, str(tmp_path), max_restarts=0)
    with pytest.raises(ChaosInvariantError):
        sup.run_to_completion()


# ----------------------------------------------------------- slow matrix

_MATRIX = [
    ChaosCase(seed=s, kill_signal=sig, kill_step=k, nprocs=1,
              total_steps=6)
    for s in (0, 1)
    for sig in ("SIGTERM", "SIGKILL")
    for k in (2, 5)
]


@pytest.mark.slow
@pytest.mark.parametrize(
    "case", _MATRIX,
    ids=[f"seed{c.seed}-{c.kill_signal}@{c.kill_step}" for c in _MATRIX])
def test_kill_matrix_single_process(case, tmp_path):
    report = run_case(case, str(tmp_path))
    assert report["converged"] is True


@pytest.mark.slow
def test_sigterm_drain_with_sparse_saves(tmp_path):
    """save_every=3 + SIGTERM at a non-multiple step: the drain's
    emergency checkpoint carries the killed step, so the resume loses
    nothing even between cadence saves."""
    report = run_case(
        ChaosCase(seed=4, kill_signal="SIGTERM", kill_step=4,
                  nprocs=1, total_steps=6, save_every=3),
        str(tmp_path))
    assert report["converged"] is True
    assert report["attempts"]["killed"] == 2


@pytest.mark.slow
def test_two_process_world_sigterm(tmp_path):
    """The 2-process gloo world: a whole-slice preemption (both workers
    SIGTERMed at the same step — exactly how GKE reclaims a spot slice)
    drains, emergency-saves collectively, and resumes exactly."""
    report = run_case(
        ChaosCase(seed=2, kill_signal="SIGTERM", kill_step=3,
                  nprocs=2, total_steps=6),
        str(tmp_path))
    assert report["converged"] is True


@pytest.mark.slow
def test_two_process_sigkill_one_peer_dead_classified(tmp_path):
    """Kill ONE worker of two with SIGKILL: the survivor's heartbeat
    monitor must convert its collective hang into the classified
    EXIT_PEER_DEAD (never an indefinite gloo wait), and the restarted
    world must still resume exactly."""
    report = run_case(
        ChaosCase(seed=3, kill_signal="SIGKILL", kill_step=3,
                  nprocs=2, total_steps=6, kill_scope="one"),
        str(tmp_path))
    assert report["converged"] is True


@pytest.mark.slow
def test_chaos_cli_smoke(tmp_path):
    """The CLI sweep drives the same gate (1 seed × 1 signal × 1 step
    to keep the smoke cheap)."""
    from nvidia_terraform_modules_tpu.smoketest.chaos import main

    assert main(["-seeds", "1", "-steps", "5", "-kill-steps", "2",
                 "-signals", "SIGKILL"]) == 0


# ------------------------------------------- slow elastic shrink/grow matrix

_ELASTIC_MATRIX = [
    ChaosCase(seed=s, kill_signal=sig, kill_step=k, nprocs=2,
              total_steps=7, kill_scope="one", elastic=True)
    for s, sig, k in (
        (0, "SIGTERM", 2),
        (0, "SIGTERM", 4),
        (0, "SIGKILL", 2),
        (0, "SIGKILL", 4),
        (1, "SIGKILL", 3),
    )
]


@pytest.mark.slow
@pytest.mark.parametrize(
    "case", _ELASTIC_MATRIX,
    ids=[f"seed{c.seed}-{c.kill_signal}@{c.kill_step}"
         for c in _ELASTIC_MATRIX])
def test_elastic_matrix_two_process(case, tmp_path):
    """The full shrink/continue/grow-back matrix: both signals (SIGTERM
    drains the killed step, SIGKILL loses it), early and late kills,
    a second seed — every case must shrink to 1, bit-match the fresh
    shrink reference, grow back to 2, and replay deterministically."""
    report = run_case(case, str(tmp_path))
    assert report["converged"] is True
    assert [w for _, w, _ in report["worlds"]] == [2, 1, 2]


@pytest.mark.slow
def test_elastic_min_world_floor_escalates(tmp_path):
    """TPU_ELASTIC_MIN_WORLD above the survivor count must refuse to
    re-form a too-small world — the supervisor escalates loudly instead
    of limping below the floor."""
    import os

    from nvidia_terraform_modules_tpu.models.resilience import (
        ElasticWorldError,
    )

    case = ChaosCase(seed=0, kill_signal="SIGKILL", kill_step=3, nprocs=2,
                     total_steps=6, kill_scope="one", elastic=True)
    os.environ["TPU_ELASTIC_MIN_WORLD"] = "2"
    try:
        with pytest.raises(ElasticWorldError):
            Supervisor(case, str(tmp_path)).run_to_completion()
    finally:
        del os.environ["TPU_ELASTIC_MIN_WORLD"]


@pytest.mark.slow
def test_chaos_cli_elastic_smoke(tmp_path):
    """-elastic drives the shrink/grow gate through the CLI."""
    from nvidia_terraform_modules_tpu.smoketest.chaos import main

    assert main(["-seeds", "1", "-steps", "6", "-kill-steps", "3",
                 "-signals", "SIGKILL", "-elastic"]) == 0
