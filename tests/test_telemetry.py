# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""The telemetry plane's contracts (telemetry/ + its instrumentation).

Pins the properties the observability PR promises:

- histogram bucket counts and p50/p90/p99 quantiles are EXACT against a
  reference sort of the recorded values;
- spans nest (per-thread depth) and the clock is injectable — a real
  and a simulated clock produce the same event schema;
- the three exports (JSONL events, Chrome trace, Prometheus text) match
  goldens from a deterministic fake clock;
- counters are thread-safe, including under the async checkpoint
  writer's background commits;
- the DISABLED path (the default) emits zero events and allocates no
  per-call objects: null instruments/spans are shared singletons and
  ``instrument_step`` returns the original function unchanged;
- the instrumented burn-in step costs < 2% over bare on the CPU burn-in
  config (the ``section_telemetry`` CI gate).
"""

import json
import math
import os
import threading
import time

import pytest

from nvidia_terraform_modules_tpu.telemetry import (
    NULL,
    EventLog,
    Registry,
    chrome_trace,
    get_registry,
    prometheus_text,
    read_events,
    set_registry,
    summary_table,
)


class FakeClock:
    """Deterministic injectable clock: advances a fixed tick per read."""

    def __init__(self, start=100.0, tick=0.5):
        self.now = start
        self.tick = tick

    def __call__(self):
        v = self.now
        self.now += self.tick
        return v


# ================================================================ histogram


def test_histogram_quantiles_exact_against_reference_sort():
    import random

    rng = random.Random(7)
    values = [rng.uniform(0.01, 5000.0) for _ in range(2311)]
    reg = Registry()
    h = reg.histogram("lat_ms")
    for v in values:
        h.record(v)
    ref = sorted(values)
    for q in (0.5, 0.9, 0.99, 0.0, 1.0):
        want = ref[max(0, math.ceil(q * len(ref)) - 1)]
        assert h.quantile(q) == want, q
    assert h.count == len(values)
    assert h.sum == pytest.approx(sum(values))


def test_histogram_bucket_counts_exact():
    reg = Registry()
    h = reg.histogram("b", buckets=(1.0, 10.0, 100.0))
    for v in (0.5, 1.0, 5.0, 50.0, 500.0):
        h.record(v)
    # cumulative counts, le semantics (1.0 lands in the le=1 bucket)
    assert h.bucket_counts() == [
        (1.0, 2), (10.0, 3), (100.0, 4), (math.inf, 5)]


def test_histogram_rejects_bad_quantile_and_empty():
    h = Registry().histogram("x")
    assert h.quantile(0.5) is None
    with pytest.raises(ValueError):
        h.quantile(1.5)


# ============================================================ spans / clock


def test_span_nesting_depth_and_containment_real_clock(tmp_path):
    reg = Registry(str(tmp_path))
    with reg.span("outer", phase="a"):
        with reg.span("inner") as sp:
            sp.args["found"] = 42
    spans = {e["name"]: e for e in reg.events if e["kind"] == "span"}
    assert spans["outer"]["depth"] == 0
    assert spans["inner"]["depth"] == 1
    assert spans["inner"]["args"]["found"] == 42
    # inner lies within outer on the timeline
    o, i = spans["outer"], spans["inner"]
    assert o["ts"] <= i["ts"]
    assert i["ts"] + i["dur"] <= o["ts"] + o["dur"] + 1e-9
    assert all(e["clock"] == "real" for e in spans.values())


def test_clock_injection_real_and_simulated_share_schema():
    clk = FakeClock(start=10.0, tick=1.0)
    reg = Registry(clock=clk, clock_id="sim", process="simproc")
    with reg.span("op"):
        pass
    reg.emit_span("manual", 3.0, 7.5, lane=2, clock="sim", status="ok")
    reg.event("mark", ts=4.0)
    span, manual, mark = reg.events
    assert span["ts"] == 10.0 and span["dur"] == pytest.approx(1.0)
    assert span["clock"] == "sim" and span["pid"] == "simproc"
    assert manual["tid"] == 2 and manual["dur"] == pytest.approx(4.5)
    assert mark["kind"] == "event" and mark["ts"] == 4.0
    # identical envelope keys for both clock domains
    real = Registry()
    with real.span("op"):
        pass
    assert set(real.events[0]) == set(span)


def test_span_records_error_classification():
    reg = Registry()
    with pytest.raises(RuntimeError):
        with reg.span("boom"):
            raise RuntimeError("x")
    assert reg.events[0]["args"]["error"] == "RuntimeError"


# ================================================================= exports


def _golden_registry():
    clk = FakeClock(start=100.0, tick=0.25)
    reg = Registry(clock=clk, process="p0")
    reg.counter("train_steps").inc(3)
    reg.gauge("train_mfu").set(0.7)
    h = reg.histogram("train_step_ms", buckets=(1.0, 10.0))
    for v in (0.5, 2.0, 20.0):
        h.record(v)
    with reg.span("train_step", step_ms=250.0):
        pass
    reg.emit_span("op create", 1.0, 3.0, lane=1, pid="sim0",
                  clock="sim", status="ok")
    return reg


def test_prometheus_export_golden():
    assert prometheus_text(_golden_registry()) == (
        "# TYPE train_steps counter\n"
        "train_steps 3\n"
        "# TYPE train_mfu gauge\n"
        "train_mfu 0.7\n"
        "# TYPE train_step_ms histogram\n"
        'train_step_ms_bucket{le="1"} 1\n'
        'train_step_ms_bucket{le="10"} 2\n'
        'train_step_ms_bucket{le="+Inf"} 3\n'
        "train_step_ms_sum 22.5\n"
        "train_step_ms_count 3\n"
        "# TYPE train_step_ms_p50 gauge\n"
        "train_step_ms_p50 2\n"
        "# TYPE train_step_ms_p90 gauge\n"
        "train_step_ms_p90 20\n"
        "# TYPE train_step_ms_p99 gauge\n"
        "train_step_ms_p99 20\n")


def test_summary_table_golden():
    assert summary_table(_golden_registry()) == (
        "train_steps    counter    3\n"
        "train_mfu      gauge      0.7\n"
        "train_step_ms  histogram  n=3 p50=2 p90=20 p99=20\n")


def test_chrome_trace_golden_structure():
    reg = _golden_registry()
    trace = chrome_trace(reg.events)["traceEvents"]
    xs = {e["name"]: e for e in trace if e["ph"] == "X"}
    # the real span re-bases to the earliest real event; sim keeps its
    # absolute (near-zero) clock — both in microseconds
    assert xs["train_step"]["ts"] == 0.0
    assert xs["train_step"]["dur"] == pytest.approx(0.25e6)
    assert xs["op create"]["ts"] == pytest.approx(1.0e6)
    assert xs["op create"]["dur"] == pytest.approx(2.0e6)
    assert xs["op create"]["args"]["clock"] == "sim"
    # process metadata names both lanes
    names = {e["args"]["name"] for e in trace
             if e["ph"] == "M" and e["name"] == "process_name"}
    assert names == {"p0", "sim0"}


def test_jsonl_roundtrip_and_kill_resilience(tmp_path):
    reg = Registry(str(tmp_path), process="w1")
    reg.event("chaos.resume", attempt=0, process=1, resumed_from=None)
    with reg.span("step"):
        pass
    # every record is already on disk (flushed per line) — no close needed,
    # exactly what a SIGKILL'd worker leaves behind
    events = read_events(str(tmp_path))
    assert [e["name"] for e in events] == ["chaos.resume", "step"]
    assert events[0]["args"]["attempt"] == 0
    # a half-written trailing line (the kill race) is skipped, not fatal
    files = [f for f in os.listdir(tmp_path) if f.startswith("events-")]
    with open(tmp_path / files[0], "a") as fh:
        fh.write('{"ts": 1, "kind": "span", "na')
    assert len(read_events(str(tmp_path))) == 2


def test_export_all_writes_three_artifacts(tmp_path):
    reg = Registry(str(tmp_path))
    reg.counter("c").inc()
    with reg.span("s"):
        pass
    paths = reg.export()
    assert sorted(os.path.basename(p) for p in paths.values()) == [
        "metrics.prom", "summary.txt", "trace.json"]
    trace = json.load(open(paths["trace"]))
    assert any(e.get("name") == "s" for e in trace["traceEvents"])
    assert "# TYPE c counter" in open(paths["prometheus"]).read()


# ============================================================ thread safety


def test_counter_thread_safety_exact_total():
    reg = Registry()
    c = reg.counter("n")
    h = reg.histogram("h")

    def work():
        for _ in range(5000):
            c.inc()
            h.record(1.0)

    threads = [threading.Thread(target=work) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == 40000
    assert h.count == 40000


def test_counters_and_spans_under_async_checkpoint_writer(tmp_path, jax8):
    """The async writer commits from a background thread: its
    checkpoint_commit spans and save counters must interleave safely
    with the caller's save spans."""
    import jax.numpy as jnp

    from nvidia_terraform_modules_tpu.models import Checkpointer

    reg = Registry(str(tmp_path / "telemetry"))
    tree = {"w": jnp.arange(64, dtype=jnp.float32)}
    with Checkpointer(str(tmp_path / "ckpt"), max_to_keep=10,
                      async_save=True, telemetry=reg) as ck:
        for s in range(6):
            ck.save(s, tree)
        ck.flush()
    assert reg.counter("checkpoint_saves").value == 6
    names = [e["name"] for e in reg.events if e["kind"] == "span"]
    assert names.count("checkpoint_save") == 6
    assert names.count("checkpoint_commit") == 6
    # every record written by either thread parses back off disk
    disk = read_events(str(tmp_path / "telemetry"))
    assert len(disk) == len(reg.events)


# ============================================================ disabled path


def test_disabled_path_is_shared_singletons_and_zero_events(tmp_path):
    assert NULL.enabled is False
    assert NULL.counter("a") is NULL.counter("b")
    assert NULL.counter("a") is NULL.histogram("h") is NULL.gauge("g")
    assert NULL.span("x") is NULL.span("y")
    with NULL.span("x"):
        NULL.counter("a").inc()
        NULL.event("e", k=1)
    assert NULL.events == []
    assert list(tmp_path.iterdir()) == []


def test_get_registry_defaults_to_null_and_env_enables(tmp_path,
                                                       monkeypatch):
    prev = set_registry(None)
    try:
        monkeypatch.delenv("TPU_TELEMETRY_DIR", raising=False)
        assert get_registry() is NULL
        set_registry(None)
        monkeypatch.setenv("TPU_TELEMETRY_DIR", str(tmp_path))
        reg = get_registry()
        assert reg.enabled and reg.directory == str(tmp_path)
        assert get_registry() is reg    # cached
    finally:
        set_registry(prev)


def test_instrument_step_disabled_returns_original_function():
    from nvidia_terraform_modules_tpu.models import BurnInConfig
    from nvidia_terraform_modules_tpu.models.burnin import instrument_step

    def step(p, b):
        return p, 0.0

    assert instrument_step(step, BurnInConfig(), NULL) is step


def test_checkpointer_disabled_emits_nothing(tmp_path, jax8):
    import jax.numpy as jnp

    from nvidia_terraform_modules_tpu.models import Checkpointer

    prev = set_registry(NULL)
    try:
        with Checkpointer(str(tmp_path / "ck")) as ck:
            ck.save(0, {"w": jnp.ones((4,))})
            ck.restore_tree(
                {"w": __import__("jax").ShapeDtypeStruct((4,),
                                                         jnp.float32)})
        # no telemetry artifacts anywhere near the checkpoint
        assert not [f for f in os.listdir(tmp_path / "ck")
                    if f.endswith(".jsonl")]
    finally:
        set_registry(prev)


# ===================================================== instrumented layers


def test_instrument_step_records_hist_gauges_and_spans(tmp_path, jax8):
    import jax

    from nvidia_terraform_modules_tpu.models import (
        BurnInConfig,
        init_params,
        instrument_step,
        make_train_step,
        synthetic_batch,
    )

    cfg = BurnInConfig(vocab=64, d_model=32, n_heads=2, d_ff=64,
                       n_layers=1, seq_len=16, batch=4)
    params = init_params(jax.random.PRNGKey(0), cfg)
    step = instrument_step(make_train_step(cfg), cfg,
                           Registry(str(tmp_path)))
    for _ in range(3):
        params, _loss = step(params, synthetic_batch(
            jax.random.PRNGKey(1), cfg))
    events = read_events(str(tmp_path))
    assert sum(e["name"] == "train_step" for e in events) == 3


def test_checkpoint_restore_spans_name_reshard(tmp_path, jax8):
    """A restore that crosses world sizes names its assembly span
    checkpoint_reshard; a same-world one says checkpoint_assemble."""
    import jax
    import jax.numpy as jnp

    from nvidia_terraform_modules_tpu.models import Checkpointer

    reg = Registry(str(tmp_path / "t"))
    tree = {"w": jnp.arange(32, dtype=jnp.float32)}
    with Checkpointer(str(tmp_path / "ck"), telemetry=reg) as ck:
        ck.save(3, tree)
        out = ck.restore_tree(
            {"w": jax.ShapeDtypeStruct((32,), jnp.float32)})
    assert out is not None and out[1] == 3
    spans = [e["name"] for e in reg.events if e["kind"] == "span"]
    assert "checkpoint_save" in spans
    assert "checkpoint_restore" in spans
    assert "checkpoint_assemble" in spans       # single-process world
    restore = [e for e in reg.events if e["name"] == "checkpoint_restore"]
    assert restore[0]["args"]["step"] == 3


def test_serve_engine_emits_request_spans(jax8, tmp_path):
    import jax

    from nvidia_terraform_modules_tpu.models import (
        BurnInConfig,
        init_params,
    )
    from nvidia_terraform_modules_tpu.models.serving import (
        make_serve_engine,
    )

    cfg = BurnInConfig(vocab=64, d_model=32, n_heads=2, d_ff=64,
                       n_layers=1, seq_len=16, batch=2)
    params = init_params(jax.random.PRNGKey(0), cfg)
    reg = Registry(str(tmp_path))
    engine = make_serve_engine(params, cfg, max_len=12, telemetry=reg)
    prompts = [jax.random.randint(jax.random.PRNGKey(i), (4,), 0, 64)
               for i in range(3)]
    outs = engine(prompts, 4, slots=2)
    assert len(outs) == 3
    names = [e["name"] for e in reg.events if e["kind"] == "span"]
    assert names.count("serve_prefill") == 3
    assert names.count("serve_request") == 3
    assert reg.counter("serve_generated_tokens").value == 12
    assert reg.histogram("serve_request_ms").count == 3


def test_serve_engine_gauges_and_span_args_export(jax8, tmp_path):
    """The serve telemetry satellite: queue-depth / slot-occupancy /
    kv-blocks gauges land in the Prometheus exposition, and every
    ``serve_request`` span carries the latency breakdown
    (queue_wait_ms, prefill_ms, decode_steps) into the trace args."""
    import jax

    from nvidia_terraform_modules_tpu.models import (
        BurnInConfig,
        init_params,
    )
    from nvidia_terraform_modules_tpu.models.serving import (
        make_serve_engine,
    )
    from nvidia_terraform_modules_tpu.telemetry.export import chrome_trace

    cfg = BurnInConfig(vocab=64, d_model=32, n_heads=2, d_ff=64,
                       n_layers=1, seq_len=16, batch=2)
    params = init_params(jax.random.PRNGKey(0), cfg)
    reg = Registry(str(tmp_path))
    engine = make_serve_engine(params, cfg, max_len=12, kv_block=4,
                               telemetry=reg)
    prompts = [jax.random.randint(jax.random.PRNGKey(i), (4,), 0, 64)
               for i in range(4)]
    engine(prompts, 4, slots=2)

    # gauges exist, carry sane final values, and export through the
    # standard Prometheus path (no serve-special exposition code)
    assert reg.gauge("serve_queue_depth").value == 0     # drained
    assert reg.gauge("serve_slot_occupancy").value == 0.0
    assert reg.gauge("kv_blocks_in_use").value == 0.0    # all freed
    # the per-wave decode-time gauge (PR 11's paged-kernel signal):
    # set every wave from the host clock, so the final value is the
    # last wave's — positive on any schedule that stepped
    assert reg.gauge("paged_decode_ms").value > 0
    prom = reg.prometheus_text()
    for line in ("# TYPE serve_queue_depth gauge",
                 "# TYPE serve_slot_occupancy gauge",
                 "# TYPE kv_blocks_in_use gauge",
                 "# TYPE paged_decode_ms gauge",
                 "# TYPE serve_request_ms histogram"):
        assert line in prom, line

    spans = [e for e in reg.events
             if e["kind"] == "span" and e["name"] == "serve_request"]
    assert len(spans) == 4
    for s in spans:
        args = s["args"]
        assert set(args) >= {"request", "tokens", "queue_wait_ms",
                             "prefill_ms", "decode_steps"}
        assert args["tokens"] == 4
        assert args["decode_steps"] == 3         # first token + 3 waves
        assert args["prefill_ms"] > 0
        assert args["queue_wait_ms"] >= 0
        # the span duration covers the prefill it reports
        assert s["dur"] * 1e3 >= args["prefill_ms"]
    # spans survive the Chrome-trace export with args intact
    xs = [e for e in chrome_trace(reg.events)["traceEvents"]
          if e["ph"] == "X" and e["name"] == "serve_request"]
    assert len(xs) == 4 and all("decode_steps" in e["args"] for e in xs)


def test_serve_scheduler_lever_gauges_export(jax8, tmp_path):
    """PR 10's scheduler-lever gauges: ``prefix_hit_blocks`` /
    ``prefix_hit_frac`` / ``blocks_grown_lazy`` carry the run's
    cumulative values and land in the Prometheus exposition through
    the standard path — golden-covered like the PR 8 serve gauges."""
    import jax

    from nvidia_terraform_modules_tpu.models import (
        BurnInConfig,
        init_params,
    )
    from nvidia_terraform_modules_tpu.models.serving import (
        make_serve_engine,
    )

    cfg = BurnInConfig(vocab=64, d_model=32, n_heads=2, d_ff=64,
                       n_layers=1, seq_len=16, batch=2)
    params = init_params(jax.random.PRNGKey(0), cfg)
    reg = Registry(str(tmp_path))
    # two 8-token templates over kv_block=4 → two shareable full
    # blocks per prompt; lazy growth on a generous pool still grows
    # (admission grants prompt + 1 only)
    tmpl = [jax.random.randint(jax.random.PRNGKey(80 + i), (8,), 0, 64)
            for i in range(2)]
    prompts = [jax.numpy.concatenate(
        [tmpl[i % 2],
         jax.random.randint(jax.random.PRNGKey(40 + i), (1 + i % 2,),
                            0, 64)]) for i in range(4)]
    engine = make_serve_engine(params, cfg, max_len=16, kv_block=4,
                               share_prefix=True, lazy_growth=True,
                               telemetry=reg)
    engine(prompts, 5, slots=2)
    st = engine.last_stats
    assert reg.gauge("prefix_hit_blocks").value \
        == st["prefix"]["hit_blocks"] > 0
    assert reg.gauge("prefix_hit_frac").value \
        == st["prefix"]["hit_frac"] > 0
    assert reg.gauge("blocks_grown_lazy").value \
        == st["kv"]["blocks_grown_lazy"] > 0
    prom = reg.prometheus_text()
    for line in ("# TYPE prefix_hit_blocks gauge",
                 "# TYPE prefix_hit_frac gauge",
                 "# TYPE blocks_grown_lazy gauge"):
        assert line in prom, line


def test_spec_engine_decode_steps_are_per_request(jax8, tmp_path):
    """The speculative engine attributes verification slot-steps to the
    REQUEST that ran them: each retirement's ``decode_steps`` is its
    own count (not the engine-wide counter), and the per-request
    counts partition the ``serve_verify_slot_steps`` total exactly."""
    import jax

    from nvidia_terraform_modules_tpu.models import (
        BurnInConfig,
        init_params,
    )
    from nvidia_terraform_modules_tpu.models.serving import (
        make_serve_engine,
    )

    cfg = BurnInConfig(vocab=64, d_model=32, n_heads=2, d_ff=64,
                       n_layers=1, seq_len=16, batch=2)
    params = init_params(jax.random.PRNGKey(0), cfg)
    reg = Registry(str(tmp_path))
    engine = make_serve_engine(params, cfg, max_len=24, spec_k=2,
                               telemetry=reg)
    prompts = [jax.random.randint(jax.random.PRNGKey(i), (4,), 0, 64)
               for i in range(4)]
    engine(prompts, 6, slots=2)
    spans = [e for e in reg.events
             if e["kind"] == "span" and e["name"] == "serve_request"]
    assert len(spans) == 4
    per_req = [s["args"]["decode_steps"] for s in spans]
    total = reg.counter("serve_verify_slot_steps").value
    assert sum(per_req) == total > 0
    assert all(0 < d < total for d in per_req) or len(per_req) == 1


def test_tfsim_apply_spans_on_sim_clock_one_lane_per_slot(tmp_path):
    """A replayed graph-parallel apply renders one lane per worker slot,
    on the simulated clock, and never more lanes than -parallelism."""
    from nvidia_terraform_modules_tpu.tfsim.faults.apply import (
        OpTrace,
        emit_apply_telemetry,
    )

    class Outcome:
        trace = [
            OpTrace("a", "create", 0.0, 5.0, "ok"),
            OpTrace("b", "create", 0.0, 3.0, "ok"),
            OpTrace("c", "create", 3.0, 6.0, "ok"),   # reuses b's lane
            OpTrace("d", "create", 1.0, 2.0, "failed"),
            OpTrace("e", "create", 2.0, 2.0, "skipped", blamed="d"),
        ]

    reg = Registry(str(tmp_path), clock_id="real")
    emit_apply_telemetry(Outcome(), reg, run="seed0x3")
    spans = [e for e in reg.events if e["kind"] == "span"]
    assert all(e["clock"] == "sim" for e in spans)
    assert all(e["pid"] == "seed0x3" for e in spans)
    lanes = {e["name"].split()[0]: e["tid"] for e in spans}
    assert len(set(lanes.values())) <= 3         # never exceeds the cap
    assert lanes["b"] == lanes["c"]              # slot recycled
    assert lanes["a"] != lanes["b"]              # concurrent ops split
    skipped = [e for e in reg.events if e["kind"] == "event"]
    assert skipped[0]["args"]["blamed"] == "d"
    assert reg.histogram("tfsim_apply_op_s").count == 4


# =============================================================== tier-1 gate


def test_instrumented_burnin_step_overhead_under_2pct(tmp_path, jax8):
    """The section_telemetry CI gate: on the CPU burn-in config (the
    default shapes the smoke test trains), instrumenting the step must
    cost < 2% wall-clock.

    Differencing two ~equal full-step timings is noise-bound on a
    shared CI box (scheduler jitter alone swings several percent of a
    tens-of-ms step), so the fraction is decomposed instead: the
    telemetry machinery's per-call cost is measured DIRECTLY by driving
    the same wrapper around a no-op step (clock reads, histogram
    record, gauge sets, flushed span write — everything the real
    wrapper adds), and compared against the real bare step's median.
    Both terms are stable, so the ratio is too.
    """
    import jax

    from nvidia_terraform_modules_tpu.models import (
        BurnInConfig,
        init_params,
        instrument_step,
        make_train_step,
        synthetic_batch,
    )
    from nvidia_terraform_modules_tpu.utils.timing import sync

    cfg = BurnInConfig()                         # the CPU burn-in config
    params = init_params(jax.random.PRNGKey(0), cfg)
    step = make_train_step(cfg)
    batch = synthetic_batch(jax.random.PRNGKey(1), cfg)

    def median_step(fn, iters=8):
        ts = []
        p = params
        for _ in range(iters):
            t0 = time.perf_counter()
            p, loss = fn(p, batch)
            sync(loss)
            ts.append(time.perf_counter() - t0)
        return sorted(ts)[len(ts) // 2]

    median_step(step, 3)                         # compile + warm
    bare_s = min(median_step(step) for _ in range(3))

    done = jax.block_until_ready(batch[0])       # committed array

    def noop(p, b):                              # the wrapper's payload
        return p, done

    inst_noop = instrument_step(noop, cfg, Registry(str(tmp_path)),
                                sync=False)
    n = 300
    for _ in range(50):                          # warm file/instruments
        inst_noop(params, batch)
        noop(params, batch)

    def per_call(fn):
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(n):
                fn(params, batch)
            best = min(best, (time.perf_counter() - t0) / n)
        return best

    overhead_s = max(0.0, per_call(inst_noop) - per_call(noop))
    frac = overhead_s / bare_s
    assert frac < 0.02, (
        f"telemetry adds {overhead_s*1e6:.0f} µs/step against a "
        f"{bare_s*1e3:.2f} ms bare burn-in step = {frac:.2%} overhead")


def test_instrument_step_flash_kernel_probe(tmp_path, jax8):
    """The per-kernel satellite: a flash config's FIRST instrumented step
    triggers the one-shot in-jit lax.scan probe — flash_fwd_ms /
    flash_bwd_ms histograms get exactly ONE sample (never re-probed on
    later steps) and the MXU-fraction gauges land in the Prometheus
    exposition; non-flash configs never pay for it."""
    import jax
    import jax.numpy as jnp

    from nvidia_terraform_modules_tpu.models import (
        BurnInConfig,
        init_params,
        instrument_step,
        make_train_step,
        synthetic_batch,
    )

    cfg = BurnInConfig(vocab=64, d_model=32, n_heads=2, d_ff=64,
                       n_layers=1, seq_len=16, batch=4,
                       dtype=jnp.float32, attn="flash")
    params = init_params(jax.random.PRNGKey(0), cfg)
    reg = Registry(str(tmp_path))
    step = instrument_step(make_train_step(cfg), cfg, reg)
    batch = synthetic_batch(jax.random.PRNGKey(1), cfg)
    for _ in range(3):
        params, _loss = step(params, batch)
    assert reg.histogram("flash_fwd_ms").count == 1
    assert reg.histogram("flash_bwd_ms").count == 1
    assert reg.gauge("flash_fwd_mxu_frac").value > 0
    assert reg.gauge("flash_bwd_mxu_frac").value > 0
    text = prometheus_text(reg)
    assert "flash_fwd_mxu_frac" in text and "flash_bwd_ms" in text
    # the probe must not have polluted the step clock's sample count
    assert reg.histogram("train_step_ms").count == 3

    # a dense config records NO flash instruments (and kernel_probe=True
    # on one is a loud error, not a silent skip)
    reg2 = Registry(str(tmp_path / "dense"))
    dcfg = BurnInConfig(vocab=64, d_model=32, n_heads=2, d_ff=64,
                        n_layers=1, seq_len=16, batch=4)
    dstep = instrument_step(make_train_step(dcfg), dcfg, reg2)
    dstep(init_params(jax.random.PRNGKey(0), dcfg),
          synthetic_batch(jax.random.PRNGKey(1), dcfg))
    assert reg2.histogram("flash_fwd_ms").count == 0
    with pytest.raises(ValueError, match="kernel_probe"):
        instrument_step(make_train_step(dcfg), dcfg, reg2,
                        kernel_probe=True)


def test_fleet_route_spans_gauges_and_engine_stitch(jax8, tmp_path):
    """PR 12's fleet telemetry: one ``fleet_route`` span per request
    whose args carry the chosen replica, the queue-depth/affinity
    gauges and shed/steal counters land in the Prometheus exposition,
    and — because the router shares its registry with every engine —
    router spans and the engines' ``serve_request`` spans stitch onto
    ONE Chrome-trace timeline."""
    import jax

    from nvidia_terraform_modules_tpu.models import (
        BurnInConfig,
        init_params,
        make_fleet,
    )

    cfg = BurnInConfig(vocab=64, d_model=32, n_heads=2, d_ff=64,
                       n_layers=1, seq_len=16, batch=2)
    params = init_params(jax.random.PRNGKey(0), cfg)
    reg = Registry(str(tmp_path))
    fleet = make_fleet(params, cfg, max_len=12, replicas=2, kv_block=4,
                       telemetry=reg, steal=False)
    prompts = [jax.random.randint(jax.random.PRNGKey(i), (4,), 0, 64)
               for i in range(4)]
    outs = fleet(prompts, 4, slots=2)
    assert all(o is not None for o in outs)

    routes = [e for e in reg.events
              if e["kind"] == "span" and e["name"] == "fleet_route"]
    assert len(routes) == 4
    for s in routes:
        assert s["args"]["replica"] in ("replica-0", "replica-1")
        assert s["args"]["shed"] is False
        assert "affinity" in s["args"]
    # the routed replica matches where the engine actually served it
    routed = fleet.last_stats["fleet"]["routed_to"]
    assert {s["args"]["request"]: s["args"]["replica"]
            for s in routes} == routed

    # engine spans share the registry: the stitch the timeline needs
    serve_spans = [e for e in reg.events
                   if e["kind"] == "span"
                   and e["name"] == "serve_request"]
    assert len(serve_spans) == 4
    prom = reg.prometheus_text()
    for line in ("# TYPE fleet_queue_depth gauge",
                 "# TYPE fleet_affinity_hit_frac gauge"):
        assert line in prom, line
    assert reg.gauge("fleet_queue_depth").value == 0     # drained
    xs = chrome_trace(reg.events)["traceEvents"]
    names = {e["name"] for e in xs if e["ph"] == "X"}
    assert {"fleet_route", "serve_prefill", "serve_request"} <= names


def test_fleet_shed_and_steal_counters_export(jax8, tmp_path):
    """The shed counter bills the SLO admission's drops; the steal
    counter bills cross-replica moves — both through the standard
    counter exposition, with shed routes marked in span args."""
    import jax

    from nvidia_terraform_modules_tpu.models import (
        BurnInConfig,
        init_params,
        make_fleet,
    )
    from nvidia_terraform_modules_tpu.utils.traffic import (
        poisson_trace,
        slo_deadlines,
    )

    cfg = BurnInConfig(vocab=64, d_model=32, n_heads=2, d_ff=64,
                       n_layers=1, seq_len=16, batch=2)
    params = init_params(jax.random.PRNGKey(0), cfg)
    reg = Registry(str(tmp_path))
    fleet = make_fleet(params, cfg, max_len=12, replicas=1, kv_block=4,
                       telemetry=reg, est_token_s=0.02)
    prompts = [jax.random.randint(jax.random.PRNGKey(i), (4,), 0, 64)
               for i in range(6)]
    budgets = [6] * 6
    arrivals = poisson_trace(500.0, 6, seed=4)
    deadlines = slo_deadlines(budgets, seed=5, base_s=0.08,
                              per_token_s=0.01, jitter=0.2)
    fleet(prompts, budgets, slots=2, arrivals=arrivals,
          deadlines=deadlines)
    st = fleet.last_stats["fleet"]
    assert st["shed"] > 0
    assert reg.counter("fleet_shed_total").value == st["shed"]
    shed_spans = [e for e in reg.events
                  if e["kind"] == "span" and e["name"] == "fleet_route"
                  and e["args"]["shed"]]
    assert len(shed_spans) == st["shed"]
    assert all(s["args"]["replica"] is None for s in shed_spans)
    prom = reg.prometheus_text()
    assert "# TYPE fleet_shed_total counter" in prom
    assert f"fleet_shed_total {st['shed']}" in prom


def test_fleet_fault_counters_degraded_span_and_redrive_marks(
        jax8, tmp_path):
    """PR 13's fault-plane telemetry, golden-tested on one registry:
    a seeded replica kill bills ``fleet_replica_down`` and
    ``fleet_redrive_total`` through the standard counter exposition,
    the redriven requests' ``fleet_route`` spans carry
    ``redrive=True``, and ONE ``fleet_degraded`` span covers the
    below-nominal-capacity interval — stitched on the SAME timeline as
    the router and engine spans so the dashboard's degraded bar lines
    up with the serve spans it explains."""
    import jax
    import jax.numpy as jnp

    from nvidia_terraform_modules_tpu.models import (
        BurnInConfig,
        init_params,
        make_fleet,
    )
    from nvidia_terraform_modules_tpu.models.fleet import (
        FleetFault,
        FleetFaultProfile,
        HashRing,
        affinity_key,
    )

    cfg = BurnInConfig(vocab=64, d_model=32, n_heads=2, d_ff=64,
                       n_layers=1, seq_len=16, batch=2)
    params = init_params(jax.random.PRNGKey(0), cfg)
    # one shared template → the ring target owns every request, so the
    # seeded kill of that target is guaranteed to redrive
    tmpl = jax.random.randint(jax.random.PRNGKey(3), (4,), 0, 64)
    prompts = [jnp.concatenate(
        [tmpl, jax.random.randint(jax.random.PRNGKey(50 + i),
                                  (1 + i % 2,), 0, 64)])
        for i in range(6)]
    victim = HashRing(3).target(affinity_key(prompts[0], 4))
    reg = Registry(str(tmp_path))
    fleet = make_fleet(
        params, cfg, max_len=12, replicas=3, kv_block=4, telemetry=reg,
        steal=False,
        faults=FleetFaultProfile(
            [FleetFault("kill_replica", target=victim, at_s=0.05)],
            seed=0))
    outs = fleet(prompts, 5, slots=2)
    assert all(o is not None for o in outs)
    fr = fleet.last_stats["fleet"]["faults"]
    assert fr["replica_down"] == 1 and fr["redriven"] >= 1

    # counters: billed once per event, exported in prometheus text
    assert reg.counter("fleet_replica_down").value == 1
    assert reg.counter("fleet_redrive_total").value == fr["redriven"]
    prom = reg.prometheus_text()
    for line in ("# TYPE fleet_replica_down counter",
                 "fleet_replica_down 1",
                 "# TYPE fleet_redrive_total counter",
                 "# TYPE fleet_circuit_open_total counter"):
        assert line in prom, line

    # redriven requests are re-routed with redrive=True span marks
    redrives = [e for e in reg.events
                if e["kind"] == "span" and e["name"] == "fleet_route"
                and e["args"].get("redrive")]
    assert len(redrives) == fr["redriven"]
    assert all(s["args"]["replica"] != f"replica-{victim}"
               for s in redrives)

    # ONE degraded span covering the kill→completion interval, on the
    # same timeline as the route/serve spans
    degraded = [e for e in reg.events
                if e["kind"] == "span" and e["name"] == "fleet_degraded"]
    assert len(degraded) == 1
    d = degraded[0]
    assert d["args"] == {"nominal": 3, "replicas_down": 1, "drained": 0}
    assert d["dur"] > 0
    xs = chrome_trace(reg.events)["traceEvents"]
    names = {e["name"] for e in xs if e["ph"] == "X"}
    assert {"fleet_degraded", "fleet_route", "serve_request"} <= names

    # a fault-free fleet on a fresh registry keeps the fault
    # instruments at zero and emits NO degraded span
    reg2 = Registry(str(tmp_path / "clean"))
    quiet = make_fleet(params, cfg, max_len=12, replicas=2, kv_block=4,
                       telemetry=reg2, steal=False)
    quiet(prompts, 4, slots=2)
    assert reg2.counter("fleet_replica_down").value == 0
    assert reg2.counter("fleet_redrive_total").value == 0
    assert not [e for e in reg2.events
                if e["kind"] == "span" and e["name"] == "fleet_degraded"]


def test_tiered_kv_spill_gauges_export(jax8, tmp_path):
    """ISSUE 14's tiered-KV gauges: ``prefix_spilled_blocks`` /
    ``prefix_swapin_ms`` / ``prefix_host_hit_frac`` carry the run's
    cumulative spill traffic, agree with ``last_stats``'s spill
    record, and land in the Prometheus exposition through the
    standard path — golden-covered like the PR 10 lever gauges."""
    import jax

    from nvidia_terraform_modules_tpu.models import (
        BurnInConfig,
        init_params,
    )
    from nvidia_terraform_modules_tpu.models.serving import (
        make_serve_engine,
    )

    cfg = BurnInConfig(vocab=64, d_model=32, n_heads=2, d_ff=64,
                       n_layers=1, seq_len=16, batch=2)
    params = init_params(jax.random.PRNGKey(0), cfg)
    reg = Registry(str(tmp_path))
    # two 8-token templates over kv_block=4, slots=1 + keep=0: every
    # retirement spills, every repeat swaps back in through the host
    # tier — real traffic on every gauge
    tmpl = [jax.random.randint(jax.random.PRNGKey(80 + i), (8,), 0, 64)
            for i in range(2)]
    prompts = [jax.numpy.concatenate(
        [tmpl[i % 2],
         jax.random.randint(jax.random.PRNGKey(40 + i), (1 + i % 2,),
                            0, 64)]) for i in range(6)]
    engine = make_serve_engine(params, cfg, max_len=16, kv_block=4,
                               share_prefix=True, prefix_keep_blocks=0,
                               host_spill=True, telemetry=reg)
    engine(prompts, 4, slots=1)
    sp = engine.last_stats["prefix"]["spill"]
    assert sp["spilled_blocks"] > 0 and sp["swapins"] > 0
    assert reg.gauge("prefix_spilled_blocks").value \
        == sp["spilled_blocks"]
    assert reg.gauge("prefix_swapin_ms").value == sp["swap_ms"] >= 0
    assert reg.gauge("prefix_host_hit_frac").value \
        == sp["host_hit_frac"] > 0
    prom = reg.prometheus_text()
    for line in ("# TYPE prefix_spilled_blocks gauge",
                 "# TYPE prefix_swapin_ms gauge",
                 "# TYPE prefix_host_hit_frac gauge"):
        assert line in prom, line


def test_prefix_cdn_disk_instruments_export(jax8, tmp_path):
    """ISSUE 20's prefix-CDN disk telemetry on one registry: a
    disk-warm admission sets the ``prefix_disk_hit_frac`` /
    ``prefix_disk_swapin_ms`` gauges (agreeing with ``last_stats``'s
    cdn record) and emits one ``prefix_disk_swap`` span per swap-in;
    ``DiskChainStore`` bills ``prefix_disk_quarantine_total`` (a
    corrupt frame moved aside, with a reason) and
    ``prefix_disk_degraded_total`` (an unusable tier) at event time;
    everything lands in the Prometheus exposition."""
    import glob
    import os

    import jax

    from nvidia_terraform_modules_tpu.models import (
        BurnInConfig,
        init_params,
    )
    from nvidia_terraform_modules_tpu.models.hostkv import (
        DiskChainStore,
        WarmChainStore,
    )
    from nvidia_terraform_modules_tpu.models.serving import (
        make_serve_engine,
    )

    cfg = BurnInConfig(vocab=64, d_model=32, n_heads=2, d_ff=64,
                       n_layers=1, seq_len=16, batch=2)
    params = init_params(jax.random.PRNGKey(0), cfg)
    reg = Registry(str(tmp_path / "t"))
    spill = str(tmp_path / "cdn")
    tmpl = [jax.random.randint(jax.random.PRNGKey(80 + i), (8,), 0, 64)
            for i in range(2)]
    prompts = [jax.numpy.concatenate(
        [tmpl[i % 2],
         jax.random.randint(jax.random.PRNGKey(40 + i), (1 + i % 2,),
                            0, 64)]) for i in range(6)]
    store = WarmChainStore(cfg, 16, block_size=4,
                           disk=DiskChainStore(spill, telemetry=reg))
    engine = make_serve_engine(params, cfg, max_len=16, kv_block=4,
                               share_prefix=True, prefix_keep_blocks=0,
                               shared_store=store, telemetry=reg)
    engine(prompts, 4, slots=1)
    assert store.disk.stored_chains > 0

    # the restart: a fresh store over the same dir, RAM tier cleared so
    # the next admission MUST come from the verified disk frame
    store2 = WarmChainStore(cfg, 16, block_size=4,
                            disk=DiskChainStore(spill, telemetry=reg))
    store2.clear()
    engine2 = make_serve_engine(params, cfg, max_len=16, kv_block=4,
                                share_prefix=True, prefix_keep_blocks=0,
                                shared_store=store2, telemetry=reg)
    engine2(prompts, 4, slots=1)
    cdn = engine2.last_stats["prefix"]["cdn"]
    assert cdn["disk_hit_blocks"] > 0
    assert reg.gauge("prefix_disk_hit_frac").value \
        == cdn["disk_hit_frac"] > 0
    assert reg.gauge("prefix_disk_swapin_ms").value \
        == cdn["disk_swap_ms"] >= 0
    spans = [e for e in reg.events
             if e["kind"] == "span" and e["name"] == "prefix_disk_swap"]
    assert spans and all(s["args"]["blocks"] > 0 for s in spans)

    # corruption: one bit flipped in one frame → the next scan
    # quarantines it with a reason and bills the counter
    before = reg.counter("prefix_disk_quarantine_total").value
    victim = sorted(glob.glob(
        os.path.join(spill, "objects", "*", "*.pcd")))[0]
    raw = bytearray(open(victim, "rb").read())
    raw[len(raw) // 2] ^= 0x40
    open(victim, "wb").write(bytes(raw))
    d3 = DiskChainStore(spill, telemetry=reg)
    assert d3.quarantined == 1 and d3.quarantine_reasons
    assert reg.counter("prefix_disk_quarantine_total").value \
        == before + 1

    # degradation: a tier whose root cannot even be a directory is
    # dead — billed, never raised
    hostile = tmp_path / "not-a-dir"
    hostile.write_text("x")
    dead = DiskChainStore(str(hostile), telemetry=reg)
    assert dead.dead
    assert reg.counter("prefix_disk_degraded_total").value > 0

    prom = reg.prometheus_text()
    for line in ("# TYPE prefix_disk_hit_frac gauge",
                 "# TYPE prefix_disk_swapin_ms gauge",
                 "# TYPE prefix_disk_quarantine_total counter",
                 "# TYPE prefix_disk_degraded_total counter"):
        assert line in prom, line


def test_fleet_scale_gauge_counters_and_span_export(jax8, tmp_path):
    """ISSUE 15's elastic-fleet telemetry, golden-tested on one
    registry: the ``fleet_size`` gauge tracks the live replica count
    through a scale-up → scale-down run, every executed event bills
    ``fleet_scale_up_total``/``fleet_scale_down_total`` exactly once,
    and each event emits a ``fleet_scale`` span whose args carry the
    trigger and the replica id — stitched on the SAME timeline as the
    route/serve spans. A fixed-size fleet on a fresh registry keeps
    every scale instrument silent."""
    import jax
    import jax.numpy as jnp

    from nvidia_terraform_modules_tpu.models import (
        AutoscalePolicy,
        BurnInConfig,
        init_params,
        make_fleet,
    )

    cfg = BurnInConfig(vocab=64, d_model=32, n_heads=2, d_ff=64,
                       n_layers=1, seq_len=16, batch=2)
    params = init_params(jax.random.PRNGKey(0), cfg)
    tmpls = [jax.random.randint(jax.random.PRNGKey(3 + t), (4,), 0, 64)
             for t in range(3)]
    prompts = [jnp.concatenate(
        [tmpls[i % 3], jax.random.randint(jax.random.PRNGKey(50 + i),
                                          (1 + i % 2,), 0, 64)])
        for i in range(12)]
    # burst then sparse tail: joins under the burst, a policy drain in
    # the tail — both sides of the ledger exercised in one run
    arrivals = [0.0] * 8 + [0.8 + 0.2 * i for i in range(4)]
    reg = Registry(str(tmp_path))
    fleet = make_fleet(
        params, cfg, max_len=12, replicas=2, kv_block=4, telemetry=reg,
        steal=False, est_token_s=0.02,
        autoscale=AutoscalePolicy(min_replicas=1, max_replicas=4,
                                  up_backlog=2.0, down_backlog=0.4,
                                  cooldown_s=0.05, seed=0))
    outs = fleet(prompts, 5, slots=2, arrivals=arrivals)
    assert all(o is not None for o in outs)
    sc = fleet.last_stats["fleet"]["scale"]
    assert sc["ups_executed"] >= 1 and sc["downs"] >= 1

    # the gauge ends at the final live size; counters bill per event
    assert reg.gauge("fleet_size").value == sc["final_live"]
    assert reg.counter("fleet_scale_up_total").value \
        == sc["ups_executed"]
    assert reg.counter("fleet_scale_down_total").value == sc["downs"]
    prom = reg.prometheus_text()
    for line in ("# TYPE fleet_size gauge",
                 f"fleet_size {sc['final_live']}",
                 "# TYPE fleet_scale_up_total counter",
                 f"fleet_scale_up_total {sc['ups_executed']}",
                 "# TYPE fleet_scale_down_total counter",
                 f"fleet_scale_down_total {sc['downs']}"):
        assert line in prom, line

    # one fleet_scale span per executed event, args = trigger + id
    spans = [e for e in reg.events
             if e["kind"] == "span" and e["name"] == "fleet_scale"]
    ups = [s for s in spans if s["args"]["kind"] == "up"]
    downs = [s for s in spans if s["args"]["kind"] == "down"]
    assert len(ups) == sc["ups_executed"]
    assert len(downs) == sc["downs"]
    for s in ups:
        assert s["args"]["trigger"] in ("backlog", "deadline_slack")
        assert s["args"]["replica"].startswith("replica-")
        assert "warm" in s["args"]
        # ISSUE 19: every up-span records whether the joiner AOT-
        # warmed its step family (False here — no aot_cache lever)
        assert s["args"]["warm_compile"] is False
        # a capture distinguishes thread joins from process spawns
        assert s["args"]["transport"] == "inproc"
    for s in downs:
        assert s["args"]["trigger"] == "low_load"
        assert s["args"]["replica"] in sc["scaled_down"]
        assert s["args"]["transport"] == "inproc"
    xs = chrome_trace(reg.events)["traceEvents"]
    names = {e["name"] for e in xs if e["ph"] == "X"}
    assert {"fleet_scale", "fleet_route", "serve_request"} <= names

    # a fixed fleet on a fresh registry: every scale instrument silent
    reg2 = Registry(str(tmp_path / "fixed"))
    quiet = make_fleet(params, cfg, max_len=12, replicas=2, kv_block=4,
                       telemetry=reg2, steal=False)
    quiet(prompts, 4, slots=2)
    assert reg2.counter("fleet_scale_up_total").value == 0
    assert reg2.counter("fleet_scale_down_total").value == 0
    assert not [e for e in reg2.events
                if e["kind"] == "span" and e["name"] == "fleet_scale"]


def test_aot_warm_instruments_export(jax8, tmp_path):
    """ISSUE 19's cold-start telemetry, golden-tested on one registry:
    the populating bring-up bills ``aot_cache_miss_total`` per
    registration and sets ``engine_warmup_ms``; priming (the engine's
    first run) sets ``join_first_token_ms``; a second bring-up against
    the same cache dir bills ``aot_cache_hit_total``; and all four
    instruments land in the prometheus export. An engine without the
    lever keeps every aot instrument silent on a fresh registry."""
    import jax
    import jax.numpy as jnp

    from nvidia_terraform_modules_tpu.models import (
        BurnInConfig,
        init_params,
        make_serve_engine,
    )

    cfg = BurnInConfig(vocab=64, d_model=32, n_heads=2, d_ff=64,
                       n_layers=1, seq_len=16, batch=2)
    params = init_params(jax.random.PRNGKey(0), cfg)
    cache_dir = str(tmp_path / "gac")
    reg = Registry(str(tmp_path / "t"))

    eng = make_serve_engine(params, cfg, max_len=12, kv_block=4,
                            aot_cache=cache_dir, telemetry=reg)
    w1 = eng.warm(slots=2, prompt_lens=(4, 6), n_new=3)
    assert w1["enabled"] and w1["registered"] >= 1
    assert w1["misses"] == w1["registered"] and w1["hits"] == 0
    assert reg.counter("aot_cache_miss_total").value == w1["misses"]
    assert reg.counter("aot_cache_hit_total").value == 0
    assert reg.gauge("engine_warmup_ms").value == w1["warm_ms"] > 0
    # priming drove the engine's first run → the joiner's clock is set
    assert reg.gauge("join_first_token_ms").value > 0

    eng2 = make_serve_engine(params, cfg, max_len=12, kv_block=4,
                             aot_cache=cache_dir, telemetry=reg)
    w2 = eng2.warm(slots=2, prompt_lens=(4, 6), n_new=3)
    assert w2["hits"] >= 1 and not w2["errors"]
    assert reg.counter("aot_cache_hit_total").value == w2["hits"]

    prom = reg.prometheus_text()
    for line in ("# TYPE aot_cache_hit_total counter",
                 f"aot_cache_hit_total {w2['hits']}",
                 "# TYPE aot_cache_miss_total counter",
                 "# TYPE engine_warmup_ms gauge",
                 "# TYPE join_first_token_ms gauge"):
        assert line in prom, line

    # unwind the sticky cache activation so later tests compile
    # against the default jax config
    eng2.aot_cache.deactivate()
    eng.aot_cache.deactivate()

    # defaults off: no lever → every aot instrument stays silent
    reg2 = Registry(str(tmp_path / "quiet"))
    plain = make_serve_engine(params, cfg, max_len=12, kv_block=4,
                              telemetry=reg2)
    plain([jnp.arange(1, 5, dtype=jnp.int32)], 3, slots=2)
    assert reg2.counter("aot_cache_hit_total").value == 0
    assert reg2.counter("aot_cache_miss_total").value == 0
    ws = plain.warm(slots=2, prompt_lens=(4,), n_new=2)
    assert ws == {"enabled": False, "registered": 0, "hits": 0,
                  "misses": 0, "serialized": 0, "traceonly": 0,
                  "demoted": 0, "quarantined": 0, "primed": 0,
                  "errors": []}


def test_transport_frame_and_rtt_instruments_export(tmp_path):
    """The transport seam's six instruments, golden-tested at the
    frame layer: ``transport_frames_total``/``transport_bytes_total``
    count every frame through the metered (router) side of a channel —
    both directions, bytes EXACT against a recomputation of the same
    frames — ``transport_rtt_ms`` records the replica-measured poll
    round-trips, ``transport_retries_total`` the classified reply
    retries, ``transport_child_respawn_total`` each dead child
    replaced by a fresh spawn and ``warm_chains_bytes_total`` the
    warm-chain payload bytes shipped over the pipes. A disabled
    registry costs nothing (no-op instruments)."""
    import multiprocessing as mp
    import pickle as _pickle

    from nvidia_terraform_modules_tpu.models.transport import (
        FrameChannel,
        TransportMetrics,
        pack_frame,
    )

    reg = Registry(str(tmp_path))
    metrics = TransportMetrics(reg)
    a, b = mp.Pipe(duplex=True)
    router = FrameChannel(a, metrics=metrics, label="router")
    replica = FrameChannel(b, label="replica")  # peer side unmetered
    try:
        sent = [("REQ", "candidate", ()), ("REQ", "pop", (3,)),
                ("REQ", "retired", (3, 6))]
        got_back = [("REP", ("OK", None)), ("REP", ("OK", True))]
        for msg in sent:
            router.send(msg)
        for _ in sent:
            assert replica.recv(1.0) in sent
        for msg in got_back:
            replica.send(msg)
        for _ in got_back:
            router.recv(1.0)

        # bytes golden: the metered side saw exactly these frames
        want_bytes = sum(
            len(pack_frame(seq, _pickle.dumps(m, _pickle.HIGHEST_PROTOCOL)))
            for seq, m in enumerate(sent))
        want_bytes += sum(
            len(pack_frame(seq, _pickle.dumps(m, _pickle.HIGHEST_PROTOCOL)))
            for seq, m in enumerate(got_back))
        assert reg.counter("transport_frames_total").value == 5
        assert reg.counter("transport_bytes_total").value == want_bytes

        metrics.rtt_ms([0.5, 1.25, 40.0])
        metrics.retries(2)
        metrics.retries(0)                   # zero retries: no count
        hist = reg.histogram("transport_rtt_ms")
        assert hist.count == 3
        assert math.isclose(hist.sum, 41.75)
        assert reg.counter("transport_retries_total").value == 2

        metrics.respawn()
        metrics.respawn()
        metrics.warm_bytes(4096)
        metrics.warm_bytes(0)                # empty prime: no count
        assert reg.counter(
            "transport_child_respawn_total").value == 2
        assert reg.counter("warm_chains_bytes_total").value == 4096

        prom = reg.prometheus_text()
        assert "# TYPE transport_frames_total counter" in prom
        assert "# TYPE transport_bytes_total counter" in prom
        assert "# TYPE transport_retries_total counter" in prom
        assert "# TYPE transport_child_respawn_total counter" in prom
        assert "# TYPE warm_chains_bytes_total counter" in prom
        assert "transport_rtt_ms" in prom
    finally:
        router.close()
        replica.close()

    # disabled registry: the metrics object is inert end to end
    off = TransportMetrics(None)
    assert off.enabled is False
    off.frame(128)
    off.retries(5)
    off.rtt_ms([1.0])
    off.respawn()
    off.warm_bytes(1024)
