# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""The Terraform function stdlib subset tfsim evaluates.

Only functions actually used by modules in this repo (plus close neighbours)
are implemented; anything else raises, which keeps module authors inside the
simulatable subset.
"""

from __future__ import annotations

import base64
import ipaddress
import json
import math
import os.path
import re
from typing import Any


class FunctionError(ValueError):
    pass


def _fn_cidrsubnet(prefix: str, newbits: int, netnum: int) -> str:
    net = ipaddress.ip_network(prefix)
    new_prefix = net.prefixlen + int(newbits)
    subnets = list(net.subnets(new_prefix=new_prefix))
    if netnum >= len(subnets):
        raise FunctionError(f"cidrsubnet: netnum {netnum} out of range for {prefix}")
    return str(subnets[int(netnum)])


def _join(sep: str, items: list) -> Any:
    """join() with terraform's unknown propagation: a computed element
    anywhere makes the whole string computed — otherwise the _Computed
    repr would be baked into a "known" plan value."""
    from .eval import COMPUTED, is_computed  # lazy: eval imports functions

    if is_computed(items):
        return COMPUTED
    return sep.join(_to_string(x) for x in items)


def _encode_json(v: Any):
    """jsonencode/yamlencode with terraform's unknown propagation: a
    computed value ANYWHERE in the structure makes the whole encoding
    computed at plan time (the encoder can't leave a hole mid-string).
    ``_eval_Call`` only short-circuits top-level COMPUTED args, so the
    deep check lives here."""
    from .eval import COMPUTED, is_computed  # lazy: eval imports functions

    if is_computed(v):
        return COMPUTED
    return json.dumps(v, separators=(",", ":"))


def _fn_format(fmt: str, *args: Any) -> Any:
    from .eval import COMPUTED, is_computed  # lazy: eval imports functions

    if any(is_computed(a) for a in args):
        # a computed value nested in a container arg (%v of a list) would
        # otherwise bake the _Computed repr into a "known" string;
        # top-level COMPUTED args are short-circuited by _eval_Call
        return COMPUTED
    out, ai = [], 0
    i = 0
    while i < len(fmt):
        if fmt[i] == "%" and i + 1 < len(fmt):
            c = fmt[i + 1]
            if c == "%":
                out.append("%")
            elif c in "sdvq":
                v = args[ai]
                ai += 1
                if c == "d":
                    out.append(str(int(v)))
                elif c == "q":
                    out.append(json.dumps(str(v)))
                else:
                    out.append(_to_string(v))
            else:
                raise FunctionError(f"format: unsupported verb %{c}")
            i += 2
        else:
            out.append(fmt[i])
            i += 1
    return "".join(out)


def _to_string(v: Any) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    if v is None:
        return ""
    return str(v)


def _fn_lookup(m: dict, key: str, *default: Any) -> Any:
    if key in m:
        return m[key]
    if default:
        return default[0]
    raise FunctionError(f"lookup: key {key!r} not found and no default")


def _fn_one(coll) -> Any:
    items = list(coll.values()) if isinstance(coll, dict) else list(coll)
    if len(items) == 0:
        return None
    if len(items) == 1:
        return items[0]
    raise FunctionError(f"one: collection has {len(items)} elements")


def _fn_coalesce(*args: Any) -> Any:
    for a in args:
        if a is not None and a != "":
            return a
    raise FunctionError("coalesce: all arguments are null/empty")


def _fn_try(*args: Any) -> Any:
    # evaluation errors are handled by the evaluator (lazy); here just pick
    # the first non-sentinel
    from .eval import _TryError

    for a in args:
        if not isinstance(a, _TryError):
            return a
    raise FunctionError("try: all expressions failed")


def _fn_merge(*maps: dict) -> dict:
    out: dict = {}
    for m in maps:
        if m is None:
            continue
        if not isinstance(m, dict):
            raise FunctionError(f"merge: expected map, got {type(m).__name__}")
        out.update(m)
    return out


def _fn_concat(*lists) -> list:
    out: list = []
    for l in lists:
        if l is None:
            continue
        out.extend(l)
    return out


def _fn_regex(pattern: str, s: str):
    m = re.search(pattern, s)
    if not m:
        raise FunctionError(f"regex: pattern {pattern!r} did not match")
    if m.groupdict():
        return m.groupdict()
    if m.groups():
        g = m.groups()
        return list(g) if len(g) > 1 else g[0]
    return m.group(0)


FUNCTIONS: dict[str, Any] = {
    "abs": abs,
    "alltrue": lambda l: all(bool(x) for x in l),
    "anytrue": lambda l: any(bool(x) for x in l),
    "abspath": os.path.abspath,
    "basename": os.path.basename,
    "dirname": os.path.dirname,
    "file": lambda p: open(p).read(),
    "fileexists": os.path.isfile,
    "filebase64": lambda p: base64.b64encode(open(p, "rb").read()).decode(),
    "base64decode": lambda s: base64.b64decode(s).decode(),
    "base64encode": lambda s: base64.b64encode(str(s).encode()).decode(),
    "can": lambda v: True,          # refined by evaluator (lazy)
    "ceil": math.ceil,
    "floor": math.floor,
    "cidrsubnet": _fn_cidrsubnet,
    "coalesce": _fn_coalesce,
    "coalescelist": lambda *ls: next((l for l in ls if l), []),
    "compact": lambda l: [x for x in l if x not in (None, "")],
    "concat": _fn_concat,
    "contains": lambda coll, v: v in coll,
    "distinct": lambda l: list(dict.fromkeys(l)),
    "element": lambda l, i: l[int(i) % len(l)],
    "endswith": lambda s, suf: str(s).endswith(suf),
    "flatten": lambda l: _flatten(l),
    "format": _fn_format,
    "join": lambda sep, l: _join(sep, l),
    "jsondecode": json.loads,
    "jsonencode": lambda v: _encode_json(v),
    "keys": lambda m: sorted(m.keys()),
    "length": len,
    "lower": lambda s: str(s).lower(),
    "lookup": _fn_lookup,
    "max": max,
    "merge": _fn_merge,
    "min": min,
    "one": _fn_one,
    "range": lambda *a: list(range(*(int(x) for x in a))),
    "regex": _fn_regex,
    "replace": lambda s, old, new: re.sub(old[1:-1], new, s)
    if len(old) > 1 and old.startswith("/") and old.endswith("/")
    else str(s).replace(old, new),
    "reverse": lambda l: list(reversed(l)),
    "sort": sorted,
    "split": lambda sep, s: str(s).split(sep),
    "startswith": lambda s, pre: str(s).startswith(pre),
    "substr": lambda s, off, length: str(s)[int(off):] if length < 0
    else str(s)[int(off): int(off) + int(length)],
    "sum": sum,
    "title": lambda s: str(s).title(),
    "tobool": lambda v: v if isinstance(v, bool) else {"true": True, "false": False}[str(v)],
    "tolist": list,
    "tomap": dict,
    "tonumber": lambda v: v if isinstance(v, (int, float)) else float(v)
    if "." in str(v) else int(v),
    "toset": lambda l: sorted(set(l)),
    "tostring": _to_string,
    "trim": lambda s, cut: str(s).strip(cut),
    "trimprefix": lambda s, p: s[len(p):] if str(s).startswith(p) else s,
    "trimspace": lambda s: str(s).strip(),
    "trimsuffix": lambda s, p: s[: -len(p)] if p and str(s).endswith(p) else s,
    "try": _fn_try,
    "upper": lambda s: str(s).upper(),
    "values": lambda m: [m[k] for k in sorted(m.keys())],
    # JSON is a subset of YAML; emitting it keeps tfsim dependency-free and
    # Helm/K8s consumers parse it identically
    "yamlencode": lambda v: _encode_json(v),  # JSON ⊂ YAML: valid either way
    "yamldecode": json.loads,
    "zipmap": lambda ks, vs: dict(zip(ks, vs)),
}


def _flatten(l):
    out = []
    for x in l:
        if isinstance(x, (list, tuple)):
            out.extend(_flatten(x))
        else:
            out.append(x)
    return out
