# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""AdamW + ZeRO-1 state sharding: math vs optax, partitioning, training.

The burn-in's SGD step is state-free by design; this is the stateful path a
real workload uses. The math is cross-checked leaf-by-leaf against
``optax.adamw`` (baked into the image), and the ZeRO-1 claim — moments
partitioned over the data axes while params stay replicated across dp — is
asserted on the actual committed shardings of a live 8-device train step.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nvidia_terraform_modules_tpu.models import (
    AdamWConfig,
    BurnInConfig,
    adamw_update,
    init_opt_state,
    init_params,
    make_adamw_train_step,
    opt_state_shardings,
    synthetic_batch,
)
from nvidia_terraform_modules_tpu.models.burnin import param_shardings
from nvidia_terraform_modules_tpu.parallel import build_mesh, make_rules, plan_mesh


def _tiny_tree(key, dtype=jnp.float32):
    k1, k2, k3 = jax.random.split(key, 3)
    return {
        "w": jax.random.normal(k1, (8, 4), dtype),
        "b": jax.random.normal(k2, (4,), dtype),
        "nested": {"u": jax.random.normal(k3, (2, 2), dtype)},
    }


def test_adamw_matches_optax():
    import optax

    opt = AdamWConfig(lr=3e-2, b1=0.9, b2=0.95, eps=1e-8, weight_decay=0.1)
    params = _tiny_tree(jax.random.PRNGKey(0))
    ref = optax.adamw(learning_rate=opt.lr, b1=opt.b1, b2=opt.b2,
                      eps=opt.eps, weight_decay=opt.weight_decay)
    ref_state = ref.init(params)
    state = init_opt_state(params)
    ours, theirs = params, params
    for i in range(5):
        grads = jax.tree.map(
            lambda p: jnp.sin(p + i), ours)  # deterministic pseudo-grads
        ours, state = adamw_update(ours, grads, state, opt)
        ref_grads = jax.tree.map(lambda p: jnp.sin(p + i), theirs)
        updates, ref_state = ref.update(ref_grads, ref_state, theirs)
        theirs = optax.apply_updates(theirs, updates)
    for a, b in zip(jax.tree.leaves(ours), jax.tree.leaves(theirs)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_moments_stay_f32_for_bf16_params():
    params = _tiny_tree(jax.random.PRNGKey(0), dtype=jnp.bfloat16)
    state = init_opt_state(params)
    grads = jax.tree.map(jnp.ones_like, params)
    params2, state = adamw_update(params, grads, state, AdamWConfig())
    assert all(m.dtype == jnp.float32 for m in jax.tree.leaves(state["mu"]))
    assert all(v.dtype == jnp.float32 for v in jax.tree.leaves(state["nu"]))
    assert all(p.dtype == jnp.bfloat16 for p in jax.tree.leaves(params2))


def test_zero1_shardings_partition_over_dp(jax8):
    mesh = build_mesh(plan_mesh(8, tp=2, sp=1))   # dp=4 × tp=2
    rules = make_rules(mesh)
    cfg = BurnInConfig(vocab=64, d_model=32, n_heads=4, d_ff=64, n_layers=1,
                       seq_len=16, batch=8)
    abstract = jax.eval_shape(lambda: init_params(jax.random.PRNGKey(0), cfg))
    ps = param_shardings(abstract, rules)
    ss = opt_state_shardings(abstract, rules)
    # embed [vocab=64, d] is P(None, "tp") for the param; its moments gain
    # dp on dim 0 (64 % 4 == 0)
    assert ps["embed"].spec == jax.sharding.PartitionSpec(None, "tp")
    assert ss["mu"]["embed"].spec[0] == "dp"
    # per-layer qkv [d, d]: dim0 replicated in param, dp-sharded in moments
    assert ss["mu"]["layers"][0]["wq"].spec[0] == "dp"
    # norm scales [d_model=32]: 32 % 4 == 0 → sharded too
    assert ss["nu"]["layers"][0]["attn_norm"].spec[0] == "dp"
    # step counter replicated
    assert ss["step"].spec == jax.sharding.PartitionSpec()


def test_zero1_falls_back_to_param_sharding_when_indivisible(jax8):
    mesh = build_mesh(plan_mesh(8, tp=1, sp=1))   # dp=8
    rules = make_rules(mesh)
    leaf = jax.ShapeDtypeStruct((6, 4), jnp.float32)   # 6 % 8 != 0, 4 % 8 != 0
    from nvidia_terraform_modules_tpu.models.optimizer import _zero1_sharding
    from jax.sharding import NamedSharding, PartitionSpec as P
    ns = _zero1_sharding(leaf, NamedSharding(mesh, P()), rules)
    assert all(ax is None for ax in ns.spec)


def test_zero1_skips_data_axes_already_used_by_param(jax8):
    """ep meshes set data=("dp","ep") AND shard expert params over ep; the
    moments must partition over the remaining ("dp",) only — a mesh axis may
    appear once per spec (regression: DuplicateSpecError on MoE meshes)."""
    mesh = build_mesh(plan_mesh(8, ep=2, tp=2))
    rules = make_rules(mesh)
    cfg = BurnInConfig(vocab=64, d_model=32, n_heads=4, d_ff=64, n_layers=1,
                       seq_len=16, batch=8, n_experts=4)
    abstract = jax.eval_shape(lambda: init_params(jax.random.PRNGKey(0), cfg))
    ss = opt_state_shardings(abstract, rules)   # must not raise
    down = ss["mu"]["layers"][0]["moe"]["experts_down"].spec
    assert down[0] == "ep"            # the param's own expert sharding kept
    assert down[2] == "dp"            # moments partition over dp only


def test_sharded_adamw_trains_moe_on_ep_mesh(jax8):
    mesh = build_mesh(plan_mesh(8, ep=2, tp=2))
    rules = make_rules(mesh)
    cfg = BurnInConfig(vocab=64, d_model=32, n_heads=4, d_ff=64, n_layers=2,
                       seq_len=16, batch=8, n_experts=4)
    params = init_params(jax.random.PRNGKey(0), cfg, rules)
    init_state, step = make_adamw_train_step(cfg, rules, AdamWConfig(lr=1e-2))
    state = init_state(params)
    batch = synthetic_batch(jax.random.PRNGKey(1), cfg, rules)
    losses = []
    for _ in range(6):
        params, state, loss = step(params, state, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses


@pytest.mark.parametrize("attn", ["dense", "ulysses"])
def test_sharded_adamw_trains(jax8, attn):
    mesh = build_mesh(plan_mesh(8, tp=2, sp=2))
    rules = make_rules(mesh)
    cfg = BurnInConfig(vocab=64, d_model=32, n_heads=4, d_ff=64, n_layers=2,
                       seq_len=16, batch=8, attn=attn)
    params = init_params(jax.random.PRNGKey(0), cfg, rules)
    init_state, step = make_adamw_train_step(cfg, rules,
                                             AdamWConfig(lr=1e-2))
    state = init_state(params)
    batch = synthetic_batch(jax.random.PRNGKey(1), cfg, rules)
    losses = []
    for _ in range(8):
        params, state, loss = step(params, state, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses
    # the live moment arrays really are dp-partitioned on device
    mu_embed = state["mu"]["embed"]
    assert mu_embed.sharding.spec[0] == "dp"
    # ZeRO-1 footprint: each device holds 1/(dp) of the moment rows
    shard_rows = {s.data.shape[0] for s in mu_embed.addressable_shards}
    assert shard_rows == {cfg.vocab // 2}   # dp=2 on this mesh


def test_unsharded_adamw_trains():
    cfg = BurnInConfig(vocab=64, d_model=32, n_heads=2, d_ff=64, n_layers=1,
                       seq_len=16, batch=4)
    params = init_params(jax.random.PRNGKey(0), cfg)
    init_state, step = make_adamw_train_step(cfg)
    state = init_state(params)
    batch = synthetic_batch(jax.random.PRNGKey(1), cfg)
    losses = []
    for _ in range(6):
        params, state, loss = step(params, state, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses


def test_lr_schedule_shape():
    """Warmup ramps to peak, cosine decays to the floor, then holds."""
    from nvidia_terraform_modules_tpu.models.optimizer import lr_at

    opt = AdamWConfig(lr=1e-2, warmup_steps=10, decay_steps=100,
                      min_lr_ratio=0.1)
    lrs = [float(lr_at(opt, jnp.int32(t))) for t in range(1, 131)]
    # monotone ramp over warmup, peak at the boundary
    assert all(lrs[i] < lrs[i + 1] for i in range(8))
    assert lrs[9] == pytest.approx(1e-2)
    # strictly decaying through the cosine phase
    assert all(lrs[i] > lrs[i + 1] for i in range(10, 109))
    # floor reached at warmup+decay and held afterwards
    assert lrs[109] == pytest.approx(1e-3, rel=1e-4)
    assert lrs[129] == pytest.approx(1e-3, rel=1e-4)


def test_lr_schedule_matches_optax():
    """Cross-check against optax's warmup_cosine_decay_schedule (its
    decay_steps counts FROM ZERO INCLUDING warmup; ours counts the decay
    phase alone)."""
    import optax

    from nvidia_terraform_modules_tpu.models.optimizer import lr_at

    opt = AdamWConfig(lr=3e-3, warmup_steps=5, decay_steps=50,
                      min_lr_ratio=0.2)
    sched = optax.warmup_cosine_decay_schedule(
        init_value=0.0, peak_value=opt.lr, warmup_steps=opt.warmup_steps,
        decay_steps=opt.warmup_steps + opt.decay_steps,
        end_value=opt.lr * opt.min_lr_ratio)
    for t in range(1, 60):
        ours = float(lr_at(opt, jnp.int32(t)))
        theirs = float(sched(t))
        assert ours == pytest.approx(theirs, rel=1e-4, abs=1e-8), t


def test_scheduled_adamw_trains():
    cfg = BurnInConfig(vocab=64, d_model=32, n_heads=2, d_ff=64, n_layers=1,
                       seq_len=16, batch=4)
    params = init_params(jax.random.PRNGKey(0), cfg)
    init_state, step = make_adamw_train_step(
        cfg, opt=AdamWConfig(lr=1e-2, warmup_steps=3, decay_steps=20))
    state = init_state(params)
    batch = synthetic_batch(jax.random.PRNGKey(1), cfg)
    losses = []
    for _ in range(8):
        params, state, loss = step(params, state, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses
