# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""KV-cache autoregressive decoding for the burn-in transformer.

The serve-side counterpart of the training burn-in: the ``gke-tpu``
examples name slice pools "serve" next to "train", and a framework that
validates a fresh slice should exercise the inference shape too — small
batched matmuls against a growing context, the regime where HBM bandwidth
(reading the weights and the cache every step), not MXU FLOPs, bounds
throughput. ``bench.py`` reports ``decode_tokens_per_s`` from this path.

TPU-first design:
- **static shapes**: the cache is a fixed ``[B, S_max, H, D]`` buffer per
  layer; each step writes one position with ``lax.dynamic_update_slice``
  and attends over the full buffer under a position mask — no dynamic
  shapes, so the whole generate loop compiles to one XLA program;
- **one program**: prefill (full-prompt causal forward that fills the
  cache) plus a ``lax.scan`` over decode steps, all under one ``jit``;
- **sharded**: the cache shards like activations — batch over the data
  axes, heads over ``tp`` (each device holds its heads' cache, matching
  the Megatron-style projection sharding), so decode runs on the same
  mesh the train step used with zero resharding.

Exactness contract: with the dense prefill (the default for
dense-trained configs), greedy tokens from this path EQUAL greedy tokens
from repeatedly running the full ``burnin.forward`` on the growing
sequence (``tests/test_decode.py``) — the cache is an optimisation, never
a different model. The flash prefill (default for long-context configs)
matches within kernel float tolerance instead, the same numerics the
config trained with. MoE configs serve through training's routed layer at
drop-free capacity (``models/moe.py``), so the exactness contract extends
to them whenever the training-side capacity factor also avoids drops.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp

from ..parallel.sharding import ShardingRules
from ..utils.layers import rmsnorm as _rmsnorm
from .burnin import BurnInConfig, apply_rope


def _check_cfg(cfg: BurnInConfig) -> None:
    # any cfg.attn is servable: the config's attn names the TRAINING
    # layout; decode uses its own cached attention, with the pallas flash
    # kernel doing the prompt prefill whenever the length tiles (so the
    # long-context configs don't hit a dense [B,H,T,S_max] score OOM).
    # MoE configs serve through the same routed layer as training, at
    # DROP-FREE capacity (models/moe.py: capacity drops are a training
    # trade; at serve time they would make routing depend on batch size
    # and break the cached == full-re-forward exactness contract)
    del cfg


_MOE_PREFILL_CHUNK = 128   # tokens per routed chunk along the seq dim


def _moe_ffn(h, layer, cfg: BurnInConfig, rules):
    """Routed FFN for the serve path: training's moe_layer at drop-free
    capacity. Routing is per-token and position-independent, so cached
    decode and full re-forward route identically. The ep constraint only
    applies when the serving mesh actually has an expert axis.

    Long prompts are routed in fixed chunks along the sequence: the
    GShard dispatch tensor is ``[T, E, C]`` and drop-free C grows with T,
    so one-shot prefill routing would be O(T²) HBM — the dense blow-up
    the flash prefill exists to avoid. With drop-free capacity, routing
    is independent per token, so chunking changes memory, never results
    (padding tokens get slots of their own and are sliced away)."""
    from .moe import drop_free_capacity, moe_layer

    b, t, d = h.shape
    moe_rules = rules if (rules is not None
                          and rules.mesh.shape.get("ep", 1) > 1) else None

    def routed(x):
        bb, tt, _ = x.shape
        # worst-case per-EXPERT load is the token count: a token's top-k
        # experts are distinct, so it contributes at most one assignment
        # to any single expert — scaling by k would only widen [T, E, C]
        out, _aux = moe_layer(
            x, layer["moe"], cfg, moe_rules,
            capacity=drop_free_capacity(bb * tt))
        return out

    if t <= _MOE_PREFILL_CHUNK:
        return routed(h)
    n = -(-t // _MOE_PREFILL_CHUNK)
    pad = n * _MOE_PREFILL_CHUNK - t
    hp = jnp.pad(h, ((0, 0), (0, pad), (0, 0))) if pad else h
    chunks = hp.reshape(b, n, _MOE_PREFILL_CHUNK, d).swapaxes(0, 1)

    def body(_, xc):
        return None, routed(xc)

    _, outs = jax.lax.scan(body, None, chunks)
    out = outs.swapaxes(0, 1).reshape(b, n * _MOE_PREFILL_CHUNK, d)
    return out[:, :t]


def init_cache(cfg: BurnInConfig, batch: int, max_len: int,
               rules: ShardingRules | None = None, *,
               cache_dtype: str = "bf16") -> dict[str, Any]:
    """Zeroed KV cache: per layer ``[B, S_max, H, D]`` k/v buffers.

    ``pos`` is the number of valid positions (python-int 0 at init,
    traced i32 afterwards).

    ``cache_dtype="int8"`` stores K/V rows as symmetric per-vector int8
    with an f32 scale per cached vector (``k_scale``/``v_scale``
    ``[B, S_max, H]``) — the cache is the OTHER per-step HBM read next to
    the weights in the decode loop, and int8 halves its bytes (the scale
    sidecar adds 4/head_dim). Rows are quantised at write time and
    dequantised on read; XLA fuses the dequant into the attention
    contraction's read stream. Lossy by construction: the decode ==
    full-re-forward exactness contract holds only for the default bf16
    cache (tests pin the int8 path's agreement instead).
    """
    _check_cfg(cfg)
    if cache_dtype not in ("bf16", "int8"):
        raise ValueError(
            f"unknown cache_dtype {cache_dtype!r}: use bf16|int8")
    quant = cache_dtype == "int8"
    max_len = cache_rows(max_len, cache_dtype)
    # GQA: only KV heads are cached — the cache shrinks by
    # n_heads/kv_heads, the point of grouped-query attention at serve time
    shape = (batch, max_len, cfg.kv_heads, cfg.head_dim)
    buf_dtype = jnp.int8 if quant else cfg.dtype
    kv = {
        "k": [jnp.zeros(shape, buf_dtype) for _ in range(cfg.n_layers)],
        "v": [jnp.zeros(shape, buf_dtype) for _ in range(cfg.n_layers)],
        "pos": jnp.zeros((), jnp.int32),
    }
    if quant:
        kv["k_scale"] = [jnp.zeros(shape[:3], jnp.float32)
                         for _ in range(cfg.n_layers)]
        kv["v_scale"] = [jnp.zeros(shape[:3], jnp.float32)
                         for _ in range(cfg.n_layers)]
    if rules is not None:
        # KV heads shard over tp when they divide it; otherwise (GQA/MQA
        # with few KV heads) the head axis replicates — device_put, unlike
        # in-jit constraints, refuses uneven sharding, and replicating a
        # small KV cache across tp is the natural MQA layout anyway
        tp = rules.mesh.shape.get("tp", 1)
        head_axis = "tp" if cfg.kv_heads % tp == 0 else None
        s = rules.shard(rules.act(None, head_axis, None))
        kv["k"] = [jax.device_put(x, s) for x in kv["k"]]
        kv["v"] = [jax.device_put(x, s) for x in kv["v"]]
        if quant:
            # scales ride the cache's own sharding minus the head dim
            s3 = rules.shard(rules.act(None, head_axis))
            kv["k_scale"] = [jax.device_put(x, s3) for x in kv["k_scale"]]
            kv["v_scale"] = [jax.device_put(x, s3) for x in kv["v_scale"]]
    return kv


def cache_rows(max_len: int, cache_dtype: str) -> int:
    """Buffer row count for a cache of logical length ``max_len``.

    int8 caches round up to the pallas decode kernel's 256-row block
    grain: the kernel tiles S exactly (a ragged tail block would CLAMP
    its start and silently read earlier rows under the mask), and rows
    past the caller's ``max_len`` sit above ``pos`` forever —
    position-masked, never written, a few MB next to the bandwidth they
    unlock. Every cache constructor (``init_cache``, the serving pool)
    must agree on this number, which is why it is one function.
    """
    if cache_dtype == "int8":
        return -(-max_len // 256) * 256
    return max_len


def quantize_kv(x):
    """Per-vector symmetric int8 for cache rows: ``[..., D]`` →
    ``(q int8, scale f32 [...])`` with ``|dequant - x| <= scale/2``."""
    x32 = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(x32), axis=-1)
    scale = jnp.maximum(amax, 1e-12) / 127.0
    q = jnp.clip(jnp.round(x32 / scale[..., None]), -127, 127)
    return q.astype(jnp.int8), scale


# Test hooks: the kernel branches below are gated on real TPU, so their
# call-site wiring (q slicing, pos broadcast, output reshape) would
# otherwise be unreachable in CPU CI. Tests flip these to route through
# the kernels in interpret mode (tests/test_decode_attention.py).
_FORCE_DECODE_KERNEL = False          # the contiguous int8 T=1 kernel
_FORCE_PAGED_KERNEL = False           # forward_paged's "auto" resolution


def _cached_attention(q, k_cache, v_cache, q_pos, scale,
                      k_scale=None, v_scale=None, int8_kernel=True):
    """Attention of ``q`` ``[B, T, H, D]`` over the full cache buffer.

    ``q_pos`` ``[T]`` (shared across the batch) or ``[B, T]`` (per-row —
    the paged serving pool's gathered caches, where every slot sits at
    its own depth) are the global positions of the query tokens; cache
    slots at positions > q_pos are masked (causal over the cache, which
    also hides the not-yet-written zero slots — they sit at positions
    above ``pos`` by construction).

    GQA: the cache carries ``kv`` heads while ``q`` carries ``H = kv·rep``.
    Queries are RESHAPED into their KV groups and contracted against the
    un-repeated cache — the repeated-cache tensor the serving win exists
    to avoid is never materialised.

    With ``k_scale``/``v_scale`` the buffers are int8, and the scales are
    applied AFTER the contractions, never to the cache operand itself:
    ``q·(k_q·s_k) = (q·k_q)·s_k`` per cached vector, and
    ``Σ_s p_s·(v_q·s_v)_s = Σ_s (p_s·s_v,s)·v_q_s`` — the scale folds
    into the scores / probabilities, which are [.., S] and tiny next to
    the [.., S, D] cache. Scaling the cache before the dot (the naive
    form) hands XLA an elementwise-times-int8 operand it materialises as
    a full compute-dtype copy of the cache — read 1 byte, write 2, read
    2: WORSE than a bf16 cache (measured 1534 vs 2135 tok/s at
    [8, 3584+] rows). After the restructure only int8 cache bytes cross
    HBM; the convert-in-dot is XLA operand fusion's easy case.
    """
    b, t, h, d = q.shape
    # kernel gate: ``int8_kernel=False`` when the cache operands may be
    # mesh-sharded (a pallas_call on sharded inputs inside jit without
    # shard_map can fail to lower or silently gather the pool — the
    # caller that knows the sharding owns the flag); the 8-multiple
    # check falls hand-built odd buffers (S=12) through to the jnp path
    # the kernel's block tiling would refuse at trace time
    if (k_scale is not None and t == 1 and d % 128 == 0
            and int8_kernel and k_cache.shape[1] % 8 == 0
            and (_FORCE_DECODE_KERNEL
                 or jax.devices()[0].platform == "tpu")):
        # the T=1 int8 step is the long-context hot path: the pallas
        # flash-decode kernel guarantees int8 cache bytes per step (XLA
        # materialises converted operands at long S even with the
        # scale-after-dot form below — measured parity instead of the
        # ~1.7× byte win). Positions are batch-uniform here (q_pos[0]);
        # the per-row generality lives in the kernel's pos argument.
        from ..ops.decode_attention import int8_kv_decode_attention

        pos_b = (jnp.broadcast_to(q_pos[0], (b,)) if q_pos.ndim == 1
                 else q_pos[:, 0])
        out = int8_kv_decode_attention(
            q[:, 0], k_cache, k_scale, v_cache, v_scale,
            pos_b, scale=scale)
        return out[:, None]
    kv = k_cache.shape[2]
    rep = h // kv
    qg = q.reshape(b, t, kv, rep, d)
    if k_scale is not None:
        k_op = k_cache.astype(q.dtype)                   # fuses into dot
    else:
        k_op = k_cache
    s = jnp.einsum("btkgd,bskd->bkgts", qg, k_op,
                   preferred_element_type=jnp.float32) * scale
    if k_scale is not None:
        # [B, S, KV] → [B, KV, 1, 1, S]: one multiply on the score tensor
        s = s * k_scale.transpose(0, 2, 1)[:, :, None, None, :]
    k_pos = jnp.arange(k_cache.shape[1])
    if q_pos.ndim == 1:
        mask = q_pos[:, None] >= k_pos[None, :]          # [T, S_max]
        mask = mask[None, None, None]
    else:
        mask = q_pos[:, :, None] >= k_pos[None, None, :]  # [B, T, S_max]
        mask = mask[:, None, None]
    s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    if v_scale is not None:
        p = p * v_scale.transpose(0, 2, 1)[:, :, None, None, :]
        v_op = v_cache.astype(q.dtype)
    else:
        v_op = v_cache
    out = jnp.einsum("bkgts,bskd->btkgd", p.astype(q.dtype), v_op,
                     preferred_element_type=jnp.float32)
    return out.reshape(b, t, h, d).astype(q.dtype)


def _transformer_body(params, tokens, cfg: BurnInConfig, q_pos, store,
                      attend, rules: ShardingRules | None = None):
    """The cached-transformer trunk shared by every KV storage layout.

    ``forward_cached`` (dense ``[B, S_max]`` buffers) and
    ``forward_paged`` (block/paged physical pool) differ ONLY in how
    fresh K/V rows are written and how the attention context is read —
    everything else (projections, rope at ``q_pos``, residuals, MoE/MLP,
    the final norm + tied unembedding) is this one function, so the two
    layouts can never drift numerically. Per layer: ``store(li, k, v) →
    handle`` writes the fresh rows into the layout's storage;
    ``attend(li, q, k, v, handle) → [B, T, H, D]`` computes attention
    (from the local rows during a pure prefill, from the stored context
    otherwise). ``q_pos`` is ``[T]`` or ``[B, T]`` and feeds rope
    directly, so per-row positions cost nothing extra.
    """
    def act(x, *rest):
        if rules is None:
            return x
        return jax.lax.with_sharding_constraint(x, rules.shard(rules.act(*rest)))

    b, t = tokens.shape
    x = params["embed"][tokens]                           # [B, T, D]
    x = act(x, None, None)
    for li, layer in enumerate(params["layers"]):
        h = _rmsnorm(x, layer["attn_norm"])
        q = h @ layer["wq"]
        k = h @ layer["wk"]
        v = h @ layer["wv"]

        def split(tns, heads=cfg.n_heads):
            tns = tns.reshape(b, t, heads, cfg.head_dim)
            return act(tns, None, "tp", None)

        q = split(q)
        k, v = split(k, cfg.kv_heads), split(v, cfg.kv_heads)
        if cfg.rope:
            # rotate at GLOBAL positions (traced is fine); K is rotated
            # before the cache write, so cached rows never need
            # re-rotation at later steps
            q = apply_rope(q, q_pos, cfg.rope_theta)
            k = apply_rope(k, q_pos, cfg.rope_theta)
        handle = store(li, k, v)
        attn = attend(li, q, k, v, handle)
        attn = attn.reshape(b, t, cfg.d_model)
        x = x + act(attn @ layer["wo"], None, None)

        h = _rmsnorm(x, layer["mlp_norm"])
        if cfg.n_experts > 0:
            x = x + act(_moe_ffn(h, layer, cfg, rules), None, None)
        else:
            h = jax.nn.gelu((h @ layer["up"]).astype(jnp.float32)).astype(cfg.dtype)
            h = act(h, None, "tp")
            x = x + act(h @ layer["down"], None, None)

    x = _rmsnorm(x, params["out_norm"])
    logits = x @ params["embed"].T
    return act(logits, None, None)


def _prompt_attention(q, k, v, q_pos, scale, cfg: BurnInConfig,
                      prefill_impl: str, quant: bool):
    """The pos==0 PROMPT attention branches shared by both cache
    layouts' attend adapters (``None`` → the caller attends over its
    stored context instead):

    - ``"flash"`` (t>1): prompt-only causal attention, fused tiles
      (the cache holds nothing the prompt shouldn't already see). The
      pallas kernel is MHA-shaped, so prefill broadcasts K/V once
      (prompt-sized, one-time); the per-STEP cached path contracts
      grouped queries against the un-repeated cache instead.
      Unquantised k/v on purpose: the prompt's own attention pays no
      cache read, so prefill numerics stay full-precision even under
      an int8 cache.
    - ``"dense"`` + int8 cache (t>1): pure prefill attends the
      just-computed FULL-PRECISION k/v (causally masked) so prefill
      numerics match the flash branch — only later steps read the
      quantised rows. Same pos==0 precondition; mid-stream t>1
      forwards (speculative verification) pass ``"cached"`` instead.

    One definition so the dense-buffer and paged layouts can never
    drift on the prompt path — the same no-drift goal
    :func:`_transformer_body` serves for the trunk.
    """
    t = q.shape[1]
    rep = cfg.n_heads // cfg.kv_heads

    def grow(tns):
        """KV-group broadcast for the MHA-shaped flash kernel."""
        return jnp.repeat(tns, rep, axis=2) if rep > 1 else tns

    if t > 1 and prefill_impl == "flash":
        from ..ops.flash_attention import flash_attention

        return flash_attention(q, grow(k), grow(v), causal=True,
                               scale=scale)
    if t > 1 and prefill_impl == "dense" and quant:
        return _cached_attention(q, k, v, q_pos, scale)
    return None


def forward_cached(params, tokens, cache, cfg: BurnInConfig,
                   rules: ShardingRules | None = None, *,
                   prefill_impl: str = "dense", int8_kernel: bool = True):
    """Forward ``tokens`` ``[B, T]`` starting at ``cache["pos"]``.

    Writes the new K/V rows into the cache and returns
    ``(logits [B, T, vocab], cache)``. ``T`` is the prompt length during
    prefill and 1 during decode — same code path, so prefill and step
    cannot diverge.

    Precondition: ``cache["pos"] + T <= S_max``. The caller owns this
    bound (``greedy_decode`` enforces it up front); past it,
    ``dynamic_update_slice`` would clamp the start index and silently
    overwrite the last cache rows — XLA has no traced-shape way to raise
    here, which is why the guard must live at the Python level.

    ``int8_kernel=False`` keeps the T=1 int8-cache step on the jnp path
    even on TPU — required when the CACHE operands are mesh-sharded by a
    caller this function cannot see (the serving pool: ``rules`` here is
    None while the stacked cache is sharded). With ``rules`` set the
    kernel is disabled automatically: the sharded solo-decode cache is
    the same hazard.

    ``prefill_impl="flash"`` runs the T>1 prompt attention through the
    fused pallas kernel instead of masked scores over the full cache
    buffer — the [T, S_max] score matrix never materialises. Valid ONLY
    when ``cache["pos"] == 0`` (the prompt attends to nothing before
    itself); ``pos`` is traced so this precondition is the caller's —
    ``greedy_decode`` selects it exactly there.
    """
    _check_cfg(cfg)
    b, t = tokens.shape
    pos0 = cache["pos"]
    q_pos = pos0 + jnp.arange(t)
    scale = 1.0 / (cfg.head_dim ** 0.5)
    quant = "k_scale" in cache
    new_k, new_v = [], []
    new_ks, new_vs = [], []

    def store(li, k, v):
        k_scale = v_scale = None
        if quant:
            # write path: quantise the fresh rows; the cache never holds
            # bf16 — int8 bytes are what cross HBM on every later step
            k_w, k_s = quantize_kv(k)
            v_w, v_s = quantize_kv(v)
            k_scale = jax.lax.dynamic_update_slice(
                cache["k_scale"][li], k_s, (0, pos0, 0))
            v_scale = jax.lax.dynamic_update_slice(
                cache["v_scale"][li], v_s, (0, pos0, 0))
            new_ks.append(k_scale)
            new_vs.append(v_scale)
        else:
            k_w, v_w = k, v
        k_cache = jax.lax.dynamic_update_slice(
            cache["k"][li], k_w, (0, pos0, 0, 0))
        v_cache = jax.lax.dynamic_update_slice(
            cache["v"][li], v_w, (0, pos0, 0, 0))
        new_k.append(k_cache)
        new_v.append(v_cache)
        return k_cache, v_cache, k_scale, v_scale

    def attend(li, q, k, v, handle):
        k_cache, v_cache, k_scale, v_scale = handle
        attn = _prompt_attention(q, k, v, q_pos, scale, cfg,
                                 prefill_impl, quant)
        if attn is not None:
            return attn
        return _cached_attention(q, k_cache, v_cache, q_pos, scale,
                                 k_scale, v_scale,
                                 int8_kernel=int8_kernel
                                 and rules is None)

    logits = _transformer_body(params, tokens, cfg, q_pos, store, attend,
                               rules)
    new_cache: dict[str, Any] = {"k": new_k, "v": new_v, "pos": pos0 + t}
    if quant:
        new_cache["k_scale"] = new_ks
        new_cache["v_scale"] = new_vs
    return logits, new_cache


def _paged_kernel_on(paged_kernel: str, t: int, bs: int, d: int,
                     rules) -> bool:
    """Resolve ``forward_paged``'s read-path dispatch for a T-token
    step. ``"auto"`` takes the pallas paged kernel exactly when it is
    the proven win: the T=1 decode step (prefill and ``[B, k+1]``
    verification keep the jnp path — their q width amortises the
    gather), an UNSHARDED pool (a pallas_call on mesh-sharded operands
    inside jit is not a supported lowering — the same hazard
    ``int8_kernel`` guards), lane-aligned geometry (``D % 128``,
    ``block_size % 8`` — Mosaic's tiling grain), on real TPU (the
    interpreter would be slower than the gather it replaces).
    ``"on"`` forces the kernel for the T=1 step wherever it can trace
    (tests run it in interpret mode on CPU); ``"off"`` keeps the
    gather path — the bit-for-bit reference the kernel is gated
    against."""
    if paged_kernel not in ("auto", "on", "off"):
        raise ValueError(f"unknown paged_kernel {paged_kernel!r}: "
                         f"use auto|on|off")
    if paged_kernel == "off" or t != 1:
        return False
    if paged_kernel == "on":
        return True
    return (rules is None and d % 128 == 0 and bs % 8 == 0
            and (_FORCE_PAGED_KERNEL
                 or jax.devices()[0].platform == "tpu"))


def _gather_logical(buf, tables, rows: int):
    """The logical-view gather — ``buf[tables]`` flattened to ``rows``
    logical rows — shared by every fallback read of the paged cache
    (K, V and both scale sidecars ride the same tables). This is the
    REFERENCE path the paged kernel supersedes: one expression so the
    four reads cannot drift, and so the lowering pin in
    ``tests/test_decode_attention.py`` has exactly one shape to
    assert absent."""
    shp = (tables.shape[0], rows) + buf.shape[2:]
    return buf[tables].reshape(shp)


def forward_paged(params, tokens, cache, cfg: BurnInConfig,
                  rules: ShardingRules | None = None, *,
                  prefill_impl: str = "cached", active=None,
                  int8_kernel: bool = True, paged_kernel: str = "auto"):
    """Forward ``tokens`` ``[B, T]`` through a BLOCK/PAGED KV cache.

    The paged twin of :func:`forward_cached` (same
    :func:`_transformer_body` trunk, so the math cannot drift): the
    physical store is one ``[num_blocks, block_size, kv, D]`` buffer per
    layer shared by every row, ``cache["block_tables"]`` ``[B, NT]``
    maps each row's logical block index to a physical block, and
    ``cache["pos"]`` is PER-ROW ``[B]`` — every slot sits at its own
    depth, which is what lets one compiled step advance a whole
    continuous-batching pool (``models/serving.py``).

    Write path: the fresh rows scatter to ``(table[pos // bs], pos %
    bs)`` — one scatter per layer, disjoint across live rows because
    the allocator (``models/paging.py``) never shares a block.

    Read path, T=1 decode (the serve engine's wave step): the pallas
    PAGED kernel (``ops/decode_attention.paged_decode_attention``)
    attends straight through the block tables — the table is a
    scalar-prefetch SMEM input and each live block is DMA'd from the
    physical pool inside the grid, so per-wave cache traffic scales
    with LIVE tokens, not pool size. Dead blocks (past a row's ``pos``
    — recycled garbage included) are skipped; int8 scale sidecars ride
    the same tables with in-kernel dequant. ``paged_kernel=
    "auto"|"on"|"off"`` picks the dispatch (see
    :func:`_paged_kernel_on`; ``"auto"`` = kernel on TPU for the T=1
    unsharded lane-aligned step).

    Read path, reference (``paged_kernel="off"``, prefill, multi-token
    verification, sharded pools): the logical view gathers
    ``k_phys[block_tables]`` → ``[B, NT·bs, kv, D]``
    (:func:`_gather_logical`) and runs the SAME masked
    :func:`_cached_attention` the dense buffer uses (rows past each
    row's ``pos`` are position-masked, so recycled-block garbage is
    unreachable); the scale sidecars gather alongside and keep the
    scale-after-dot contraction — and, gathered into a contiguous
    buffer, the T=1 int8 decode-kernel gate still applies on TPU. The
    kernel path is bit-match gated against this gather path
    (``tests/test_decode_attention.py``, smoketest ``paged_decode_ok``)
    — the gather is the semantics, the kernel is the bandwidth.

    ``active`` ``[B]`` bool (default all-true) fences DEAD rows: an
    idle or retired slot's writes are rerouted to reserved physical
    block 0 (the garbage block) and its ``pos`` freezes — without the
    reroute, a retired slot still computing in the static batch would
    scribble over blocks the allocator already recycled to another
    request. Reads need no fence on either path: a frozen row's
    position mask (kernel liveness ≡ gather mask) already hides
    everything past its ``pos``, and its output is never consumed.
    ``prefill_impl`` resolves as in :func:`forward_cached`
    (``"flash"``/``"dense"`` are pos==0 prompt paths; mid-stream t>1
    forwards pass ``"cached"``).

    ``rules`` applies the trunk's activation sharding constraints
    (batch = the slot pool over the data axes, heads over ``tp``) —
    the serving engine passes it for the all-slots decode/verification
    steps on a mesh, where the batch dim is the validated
    slots-divide-data-shards pool; the one-row admission forwards run
    unconstrained (a size-1 batch has nothing to shard) exactly as the
    dense engine's admission always did. Callers passing ``rules``
    should also pass ``int8_kernel=False`` (pallas on sharded operands
    — same hazard as :func:`forward_cached`).

    Precondition (the caller's, as ever): each active row's
    ``pos + T`` stays within its ALLOCATED rows. Under the serving
    engine's eager grants that is sized at admission for prompt +
    generation; under LAZY growth the engine grows the slot's table
    row (one ``.at[slot, idx].set(block)`` dispatch per crossing)
    BEFORE any wave whose write position enters an ungranted entry —
    an ungranted entry still holds the init-time 0 and a write through
    it would land in the garbage block, silently losing the row, which
    is why the growth check stalls the slot rather than stepping it.
    """
    _check_cfg(cfg)
    b, t = tokens.shape
    tables = cache["block_tables"]                        # [B, NT]
    nt = tables.shape[1]
    bs = cache["k"][0].shape[1]
    pos0 = cache["pos"]                                   # [B]
    q_pos = pos0[:, None] + jnp.arange(t)[None, :]        # [B, T]
    scale = 1.0 / (cfg.head_dim ** 0.5)
    quant = "k_scale" in cache
    kernel_on = _paged_kernel_on(paged_kernel, t, bs, cfg.head_dim,
                                 rules)
    if active is None:
        active = jnp.ones((b,), bool)
    blk = jnp.clip(q_pos // bs, 0, nt - 1)
    pb = jnp.take_along_axis(tables, blk, axis=1)         # [B, T] physical
    pb = jnp.where(active[:, None], pb, 0)                # dead → garbage
    pr = q_pos % bs
    new_k, new_v = [], []
    new_ks, new_vs = [], []

    def store(li, k, v):
        if quant:
            k_w, k_s = quantize_kv(k)
            v_w, v_s = quantize_kv(v)
            new_ks.append(cache["k_scale"][li].at[pb, pr].set(k_s))
            new_vs.append(cache["v_scale"][li].at[pb, pr].set(v_s))
        else:
            k_w, v_w = k, v
        new_k.append(cache["k"][li].at[pb, pr].set(k_w))
        new_v.append(cache["v"][li].at[pb, pr].set(v_w))
        return li

    def attend(li, q, k, v, handle):
        del handle
        attn = _prompt_attention(q, k, v, q_pos, scale, cfg,
                                 prefill_impl, quant)
        if attn is not None:
            return attn
        if kernel_on:
            # block-table-native read: no logical view, no gather —
            # the kernel fetches live blocks straight from the
            # (post-store) pool through the tables; a frozen row's
            # reads are identical to the gather path's (same tables,
            # same frozen pos — only WRITES are fenced, above)
            from ..ops.decode_attention import paged_decode_attention

            out = paged_decode_attention(
                q[:, 0], new_k[li], new_v[li], tables, pos0,
                scale=scale,
                k_scale=new_ks[li] if quant else None,
                v_scale=new_vs[li] if quant else None)
            return out[:, None]
        rows = nt * bs
        k_log = _gather_logical(new_k[li], tables, rows)
        v_log = _gather_logical(new_v[li], tables, rows)
        ks_log = vs_log = None
        if quant:
            ks_log = _gather_logical(new_ks[li], tables, rows)
            vs_log = _gather_logical(new_vs[li], tables, rows)
        # same guard depth as forward_cached: a mesh-sharded pool keeps
        # the jnp path whatever the caller's kernel flag says
        return _cached_attention(q, k_log, v_log, q_pos, scale,
                                 ks_log, vs_log,
                                 int8_kernel=int8_kernel
                                 and rules is None)

    logits = _transformer_body(params, tokens, cfg, q_pos, store, attend,
                               rules)
    new_cache = dict(cache)
    new_cache.update(k=new_k, v=new_v,
                     pos=jnp.where(active, pos0 + t, pos0))
    if quant:
        new_cache["k_scale"] = new_ks
        new_cache["v_scale"] = new_vs
    return logits, new_cache


def _select_prefill_impl(cfg: BurnInConfig, t: int, prefill: str) -> str:
    """Resolve the prefill attention impl.

    ``"auto"`` matches the config's training layout: dense-trained models
    prefill with the exact masked-cache path (preserving the bit-exactness
    contract vs full re-forward), long-context models (flash/ring/ulysses)
    prefill through the fused pallas kernel — dense scores would not fit
    the prompt lengths those configs exist for, so a prompt that does NOT
    tile into 8-multiple blocks is a loud error, not a silent dense
    fallback into an OOM.
    """
    from ..ops.flash_attention import pick_impl

    if prefill not in ("auto", "dense", "flash"):
        raise ValueError(f"unknown prefill {prefill!r}; use auto|dense|flash")
    requested = prefill
    if prefill == "auto":
        prefill = "dense" if cfg.attn == "dense" else "flash"
    if prefill == "flash" and pick_impl(None, t, "prefill") != "flash":
        # auto-resolved flash on a SHORT non-tiling prompt (t=1 especially
        # — the flash branch never even fires below t=2) falls back to the
        # memory-safe dense path; an EXPLICIT prefill="flash" request, and
        # any large prompt, errors loudly — never silently measure/serve a
        # different kernel than the caller asked for
        if requested == "auto" and t <= 512:
            return "dense"
        raise ValueError(
            f"prompt length {t} has no 8-multiple block divisor for the "
            f"flash prefill — pad the prompt (dense prefill at this "
            f"length would materialise the full [T, S_max] score matrix)")
    return prefill


def _generate(params, prompt, n_new, cfg, rules, max_len, pick_next,
              prefill, cache_dtype="bf16"):
    """Shared prefill + scan loop; ``pick_next(logits, rng) → token``."""
    b, t = prompt.shape
    if max_len is None:
        max_len = t + n_new
    if t + n_new > max_len:
        raise ValueError(f"prompt ({t}) + n_new ({n_new}) exceeds "
                         f"max_len ({max_len})")
    cache = init_cache(cfg, b, max_len, rules, cache_dtype=cache_dtype)
    logits, cache = forward_cached(
        params, prompt, cache, cfg, rules,
        prefill_impl=_select_prefill_impl(cfg, t, prefill))
    if pick_next is None:
        first = jnp.argmax(logits[:, -1], axis=-1)
        keys = jnp.zeros((n_new - 1,), jnp.uint32)        # unused by step
    else:
        rng, pick = pick_next
        all_keys = jax.random.split(rng, n_new)           # one per token
        first = pick(logits[:, -1], all_keys[0])
        keys = all_keys[1:]

    def step(carry, key):
        cache, tok = carry
        logits, cache = forward_cached(params, tok[:, None], cache, cfg,
                                       rules)
        nxt = jnp.argmax(logits[:, -1], axis=-1) if pick_next is None \
            else pick_next[1](logits[:, -1], key)
        return (cache, nxt), nxt

    # n_new - 1 scan steps: token 1 comes from prefill's logits, each step
    # consumes the previous token and emits the next — no forward whose
    # output would be thrown away
    (_, _), toks = jax.lax.scan(step, (cache, first), keys)
    toks = jnp.concatenate([first[None], toks], axis=0)   # [n_new, B]
    return jnp.swapaxes(toks, 0, 1)                       # [B, n_new]


def greedy_decode(params, prompt, n_new: int, cfg: BurnInConfig,
                  rules: ShardingRules | None = None,
                  max_len: int | None = None, prefill: str = "auto",
                  cache_dtype: str = "bf16"):
    """Greedy generation: prefill the prompt, then ``n_new`` cached steps.

    Returns generated tokens ``[B, n_new]``. Jittable end-to-end (the
    decode loop is a ``lax.scan``); wrap in ``jax.jit`` with ``n_new`` and
    shapes static for the compiled serving path. ``prefill`` picks the
    prompt attention impl (see ``_select_prefill_impl``): dense-trained
    configs keep the bit-exact dense path, long-context configs prefill
    through the flash kernel (matching their training numerics).
    """
    return _generate(params, prompt, n_new, cfg, rules, max_len, None,
                     prefill, cache_dtype)


def sample_decode(params, prompt, n_new: int, cfg: BurnInConfig, rng,
                  rules: ShardingRules | None = None,
                  max_len: int | None = None,
                  temperature: float = 1.0, top_k: int | None = None,
                  top_p: float | None = None,
                  prefill: str = "auto", cache_dtype: str = "bf16"):
    """Temperature / top-k / nucleus (top-p) sampling over the cached loop.

    ``temperature`` scales logits (→0 recovers greedy); ``top_k`` keeps
    only the k highest logits per position (``top_k=1`` IS greedy,
    exactly); ``top_p`` keeps the smallest prefix of the
    probability-sorted vocab whose mass reaches p (nucleus sampling —
    the standard lever when the tail, not the rank cutoff, is what
    should adapt per step). Filters compose in the mainstream
    (HF/vLLM) order: temperature FIRST, then top-k, then top-p over the
    tempered distribution — so ported sampling settings mean what they
    meant elsewhere. One PRNG key per generated token, split from
    ``rng`` — same key, same tokens, reproducible serving.
    """
    pick = make_sampler(temperature=temperature, top_k=top_k, top_p=top_p)
    return _generate(params, prompt, n_new, cfg, rules, max_len, (rng, pick),
                     prefill, cache_dtype)


def make_sampler(temperature: float = 1.0, top_k: int | None = None,
                 top_p: float | None = None):
    """Build the ``pick(logits [B, V], key) → [B]`` sampling function.

    The shared sampling core for :func:`sample_decode` and the serving
    engine (``models/serving.py``): temperature → top-k → top-p in the
    mainstream order, ``top_k=1`` recovering greedy exactly.
    """
    if top_k is not None and top_k < 1:
        raise ValueError(f"top_k must be >= 1, got {top_k}")
    if top_p is not None and not 0.0 < top_p <= 1.0:
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")
    temperature = max(float(temperature), 1e-6)

    def pick(logits, key):                                # [B, vocab] → [B]
        logits = logits.astype(jnp.float32) / temperature
        if top_k == 1:
            return jnp.argmax(logits, axis=-1)            # no tie-break draw
        if top_k is not None and top_k < logits.shape[-1]:
            # O(V log k) per step (this runs inside the decode scan) —
            # a full jnp.sort would be O(V log V) and copy the vocab
            kth = jax.lax.top_k(logits, top_k)[0][:, -1][:, None]
            logits = jnp.where(logits < kth, -jnp.inf, logits)
        if top_p is not None and top_p < 1.0:
            # nucleus over the tempered post-top-k distribution: keep
            # ranks whose EXCLUSIVE prefix mass is < p (the first token
            # always survives; the one crossing p is included, matching
            # the standard formulation). RANK-based, not value-based: a
            # logit tied with the boundary but ranked past it must NOT
            # survive — admitting it would grow the nucleus and shift
            # every kept token's renormalised probability. One argsort +
            # one O(V) scatter (put_along_axis) restores original
            # positions; this runs inside the decode scan, where a
            # second argsort would double the per-token vocab traffic.
            order = jnp.argsort(-logits, axis=-1)
            sorted_logits = jnp.take_along_axis(logits, order, axis=-1)
            probs = jax.nn.softmax(sorted_logits, axis=-1)
            prefix = jnp.cumsum(probs, axis=-1) - probs   # exclusive
            keep_sorted = prefix < top_p                  # [B, V] by rank
            keep = jnp.put_along_axis(
                jnp.zeros(logits.shape, bool), order, keep_sorted,
                axis=-1, inplace=False)
            logits = jnp.where(keep, logits, -jnp.inf)
        return jax.random.categorical(key, logits, axis=-1)

    return pick


def make_decoder(cfg: BurnInConfig, rules: ShardingRules | None = None,
                 n_new: int = 32, max_len: int | None = None,
                 cache_dtype: str = "bf16"):
    """Compiled greedy decoder: ``decoder(params, prompt) → [B, n_new]``."""
    fn = functools.partial(greedy_decode, n_new=n_new, cfg=cfg, rules=rules,
                           max_len=max_len, cache_dtype=cache_dtype)
    return jax.jit(fn)
