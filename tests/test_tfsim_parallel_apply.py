# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""Graph-parallel apply: deterministic concurrent scheduler (ISSUE 3).

The tentpole invariants, at unit and CLI level:

- instance-level dependency edges (transitive node closure, module
  internals resolved against child plans);
- `instance_apply_order` is a dependency-true topological order with
  the historical (rank, address) tie-break, and state-only addresses
  take a stable rank (satellite: regression);
- terraform failure isolation: independent branches finish, exactly
  the transitive dependents skip (with the root failure blamed);
- deletes schedule in reverse-edge direction;
- a replace's create waits for its own delete;
- concurrency charges each operation only its OWN elapsed time
  against its `timeouts {}` budget (satellite: deadline fairness);
- a crash abandons in-flight work: neither completed nor tainted;
- determinism per (seed, parallelism) and final-state equivalence
  across parallelism levels;
- `tfsim graph -cycles` renders the full cycle path as a DOT
  subgraph highlight (satellite).
"""

import io
import json
import os

import pytest

from nvidia_terraform_modules_tpu.tfsim.__main__ import main
from nvidia_terraform_modules_tpu.tfsim.faults import (
    ControlPlane,
    FaultProfile,
    FaultSpec,
    SimulatedCrash,
    run_apply,
)
from nvidia_terraform_modules_tpu.tfsim.plan import (
    instance_apply_order,
    instance_dependencies,
    simulate_plan,
)
from nvidia_terraform_modules_tpu.tfsim.state import State, apply_plan

# a diamond with an independent branch: vpc → cluster → {a, b} pools,
# and a KMS chain (ring → key) that shares nothing with the cluster
DIAMOND_HCL = """
resource "google_compute_network" "vpc" {
  name = "net"
}

resource "google_container_cluster" "this" {
  name    = "c"
  network = google_compute_network.vpc.name
}

resource "google_container_node_pool" "a" {
  name    = "a"
  cluster = google_container_cluster.this.name
}

resource "google_container_node_pool" "b" {
  name    = "b"
  cluster = google_container_cluster.this.name
}

resource "google_kms_key_ring" "ring" {
  name = "r"
}

resource "google_kms_crypto_key" "key" {
  key_ring = google_kms_key_ring.ring.id
}
"""


@pytest.fixture
def diamond(tmp_path):
    d = tmp_path / "diamond"
    d.mkdir()
    (d / "main.tf").write_text(DIAMOND_HCL)
    return str(d)


def profile_file(tmp_path, *specs) -> str:
    p = tmp_path / "faults.json"
    p.write_text(json.dumps({"faults": list(specs)}))
    return str(p)


def load_state(path) -> State:
    with open(path) as fh:
        return State.from_json(fh.read())


def run_cli(argv):
    import contextlib

    out, err = io.StringIO(), io.StringIO()
    with contextlib.redirect_stdout(out), contextlib.redirect_stderr(err):
        rc = main(argv)
    return rc, out.getvalue(), err.getvalue()


def engine_apply(module_dir, specs=(), seed=0, parallelism=10,
                 prior=None, tfvars=None):
    """Run the engine directly; returns (outcome, control_plane) —
    crashes are caught and their partial outcome returned."""
    plan = simulate_plan(module_dir, tfvars or {})
    cp = ControlPlane(FaultProfile(specs=[FaultSpec(**s) for s in specs]),
                      seed=seed)
    try:
        return run_apply(plan, prior, cp, parallelism=parallelism), cp
    except SimulatedCrash as ex:
        return ex.outcome, cp


def trace_by_key(outcome):
    return {(t.address, t.op): t for t in outcome.trace}


# ------------------------------------------------- instance-level edges

def test_instance_dependencies_transitive_gating(diamond):
    """A no-op intermediate (cluster omitted from the operation set)
    must still gate its endpoints: the pool depends on the vpc."""
    plan = simulate_plan(diamond, {})
    deps = instance_dependencies(plan, [
        "google_container_node_pool.a", "google_compute_network.vpc"])
    assert deps["google_container_node_pool.a"] == {
        "google_compute_network.vpc"}
    assert deps["google_compute_network.vpc"] == set()


def test_instance_dependencies_independent_branches(diamond):
    plan = simulate_plan(diamond, {})
    deps = instance_dependencies(plan, list(plan.instances))
    assert deps["google_kms_crypto_key.key"] == {
        "google_kms_key_ring.ring"}
    # nothing in the KMS chain depends on the cluster branch or back
    cluster_branch = {"google_compute_network.vpc",
                      "google_container_cluster.this",
                      "google_container_node_pool.a",
                      "google_container_node_pool.b"}
    assert not deps["google_kms_crypto_key.key"] & cluster_branch
    assert not deps["google_container_cluster.this"] & {
        "google_kms_key_ring.ring", "google_kms_crypto_key.key"}


def test_instance_dependencies_module_internal_edges(tmp_path):
    """Node-level edges collapse a child module to one node; the
    instance edges must come from the child plan so module internals
    are not read as mutually independent."""
    child = tmp_path / "child"
    child.mkdir()
    (child / "main.tf").write_text("""
variable "name" {
  type = string
}

resource "google_compute_network" "z_net" {
  name = var.name
}

resource "google_container_cluster" "a_cluster" {
  name    = var.name
  network = google_compute_network.z_net.name
}

output "cluster" {
  value = google_container_cluster.a_cluster.name
}
""")
    parent = tmp_path / "parent"
    parent.mkdir()
    (parent / "main.tf").write_text("""
module "env" {
  source = "./child"
  name   = "x"
}
""")
    os.rename(str(child), str(parent / "child"))
    plan = simulate_plan(str(parent), {})
    addrs = list(plan.instances)
    deps = instance_dependencies(plan, addrs)
    assert deps["module.env.google_container_cluster.a_cluster"] == {
        "module.env.google_compute_network.z_net"}
    # ...and the order honours it even though the address sort alone
    # would put a_cluster first
    order = instance_apply_order(plan, addrs)
    assert order.index("module.env.google_compute_network.z_net") < \
        order.index("module.env.google_container_cluster.a_cluster")


# ------------------------------------- stable state-only rank (satellite)

def test_state_only_addresses_stable_rank(diamond):
    """Addresses present only in state (node gone from config) take a
    stable rank: strictly after every planned node, ordered by bare
    address — delete ordering can never drift between runs."""
    plan = simulate_plan(diamond, {})
    addrs = ["zzz_gone.a", "aaa_gone.b[0]",
             "google_container_cluster.this", "google_compute_network.vpc"]
    order = instance_apply_order(plan, addrs)
    assert order == ["google_compute_network.vpc",
                     "google_container_cluster.this",
                     "aaa_gone.b[0]", "zzz_gone.a"]
    # input permutation must not change the result
    assert instance_apply_order(plan, list(reversed(addrs))) == order


def test_flat_module_order_matches_historical_sort(diamond):
    """For a flat module the topological linearisation reproduces the
    historical (node rank, address) sort exactly — the serial fault
    stream depends on it."""
    plan = simulate_plan(diamond, {})
    addrs = [a for a in plan.instances]
    rank = {n: i for i, n in enumerate(plan.order)}
    legacy = sorted(addrs, key=lambda a: (
        rank.get(a.split("[")[0], len(rank)), a))
    assert instance_apply_order(plan, addrs) == legacy


# ------------------------------------------------- failure isolation

def test_independent_branches_finish_and_closure_skips(tmp_path, diamond):
    """Terminal fault on the cluster: the KMS branch runs to completion
    and is persisted; exactly the cluster's transitive dependents skip,
    each blaming the errored address."""
    pfile = profile_file(tmp_path, {
        "fault": "tpu-stockout", "resource": "google_container_cluster.*",
        "op": "create"})
    spath = tmp_path / "s.json"
    rc, out, err = run_cli(["apply", diamond, "-state", str(spath),
                            "-fault-profile", pfile, "-fault-seed", "0",
                            "-parallelism", "4"])
    assert rc == 1
    assert ("google_container_node_pool.a: skipped — dependency "
            "google_container_cluster.this errored") in err
    assert ("google_container_node_pool.b: skipped — dependency "
            "google_container_cluster.this errored") in err
    assert "2 dependent operation(s) skipped" in err
    st = load_state(spath)
    assert set(st.resources) == {"google_compute_network.vpc",
                                 "google_kms_key_ring.ring",
                                 "google_kms_crypto_key.key"}
    # resume: only the failed node and its dependents are left
    rc, out, err = run_cli(["apply", diamond, "-state", str(spath)])
    assert rc == 0
    assert "Apply complete: 3 added, 0 changed, 0 destroyed." in out


def test_skip_blames_the_root_failure_through_intermediates(tmp_path,
                                                            diamond):
    """Fail the DEEPEST dependency (vpc): the pools skip through the
    skipped cluster, still blaming the address that actually errored."""
    outcome, _cp = engine_apply(diamond, specs=[
        {"kind": "quota-exceeded",
         "resource": "google_compute_network.vpc", "op": "create"}])
    assert [f.address for f in outcome.failures] == [
        "google_compute_network.vpc"]
    skips = {s.address: s.blamed for s in outcome.skipped}
    assert skips == {
        "google_container_cluster.this": "google_compute_network.vpc",
        "google_container_node_pool.a": "google_compute_network.vpc",
        "google_container_node_pool.b": "google_compute_network.vpc",
    }
    # the independent branch completed regardless
    done = {a for a, _op in outcome.completed}
    assert {"google_kms_key_ring.ring",
            "google_kms_crypto_key.key"} <= done


def test_multiple_independent_failures_are_all_reported(tmp_path, diamond):
    """One terminal fault per branch: both failures surface, both
    persist what completed before them."""
    outcome, _cp = engine_apply(diamond, specs=[
        {"kind": "tpu-stockout",
         "resource": "google_container_cluster.*", "op": "create"},
        {"kind": "quota-exceeded",
         "resource": "google_kms_crypto_key.*", "op": "create"}])
    assert {f.address for f in outcome.failures} == {
        "google_container_cluster.this", "google_kms_crypto_key.key"}
    assert {s.address for s in outcome.skipped} == {
        "google_container_node_pool.a", "google_container_node_pool.b"}
    assert {a for a, _op in outcome.completed} == {
        "google_compute_network.vpc", "google_kms_key_ring.ring"}


# --------------------------------------------------- schedule shape

def test_no_op_starts_before_dependency_completes(diamond):
    outcome, _cp = engine_apply(diamond, parallelism=10)
    t = trace_by_key(outcome)
    for before, after in [
        (("google_compute_network.vpc", "create"),
         ("google_container_cluster.this", "create")),
        (("google_container_cluster.this", "create"),
         ("google_container_node_pool.a", "create")),
        (("google_kms_key_ring.ring", "create"),
         ("google_kms_crypto_key.key", "create")),
    ]:
        assert t[before].finish_s <= t[after].start_s + 1e-9
    # genuinely parallel: both roots started at t=0
    assert t[("google_compute_network.vpc", "create")].start_s == 0.0
    assert t[("google_kms_key_ring.ring", "create")].start_s == 0.0


def test_deletes_run_in_reverse_edge_direction(tmp_path):
    """Shrinking count on a dependent pair: the pool instance's delete
    must FINISH before its cluster instance's delete starts, even at
    full parallelism."""
    d = tmp_path / "countmod"
    d.mkdir()
    (d / "main.tf").write_text("""
variable "n" {
  type    = number
  default = 2
}

resource "google_container_cluster" "c" {
  count = var.n
  name  = "c${count.index}"
}

resource "google_container_node_pool" "p" {
  count   = var.n
  name    = "p${count.index}"
  cluster = google_container_cluster.c[0].name
}
""")
    plan2 = simulate_plan(str(d), {"n": 2})
    prior = apply_plan(plan2, None)
    plan1 = simulate_plan(str(d), {"n": 1})
    cp = ControlPlane(FaultProfile(specs=[]), seed=0)
    outcome = run_apply(plan1, prior, cp, parallelism=10)
    assert outcome.ok
    t = trace_by_key(outcome)
    pool = t[("google_container_node_pool.p[1]", "delete")]
    cluster = t[("google_container_cluster.c[1]", "delete")]
    assert pool.finish_s <= cluster.start_s + 1e-9


def test_replace_delete_waits_for_dependent_deletes(tmp_path):
    """Review regression: a replaced resource must not be destroyed
    while a dependent instance's delete is still pending — the
    replace's destroy half takes reverse edges like any other
    delete."""
    d = tmp_path / "repmod"
    d.mkdir()
    (d / "main.tf").write_text("""
variable "n" {
  type    = number
  default = 2
}

resource "google_compute_network" "r" {
  name = "net"
}

resource "google_container_cluster" "x" {
  count   = var.n
  name    = "x${count.index}"
  network = google_compute_network.r.name
}
""")
    prior = apply_plan(simulate_plan(str(d), {"n": 2}), None)
    prior.tainted.add("google_compute_network.r")      # replace r …
    plan = simulate_plan(str(d), {"n": 1})             # … and shrink x
    cp = ControlPlane(FaultProfile(specs=[]), seed=0)
    outcome = run_apply(plan, prior, cp, parallelism=10)
    assert outcome.ok
    t = trace_by_key(outcome)
    dep_delete = t[("google_container_cluster.x[1]", "delete")]
    r_delete = t[("google_compute_network.r", "delete")]
    r_create = t[("google_compute_network.r", "create")]
    assert dep_delete.finish_s <= r_delete.start_s + 1e-9
    assert r_delete.finish_s <= r_create.start_s + 1e-9


def test_replace_create_waits_for_its_delete(diamond):
    plan = simulate_plan(diamond, {})
    prior = apply_plan(plan, None)
    prior.tainted.add("google_container_cluster.this")
    cp = ControlPlane(FaultProfile(specs=[]), seed=0)
    outcome = run_apply(plan, prior, cp, parallelism=10)
    assert outcome.ok
    t = trace_by_key(outcome)
    dele = t[("google_container_cluster.this", "delete")]
    crea = t[("google_container_cluster.this", "create")]
    assert dele.finish_s <= crea.start_s + 1e-9


# ---------------------------------- concurrency & budgets (satellite)

TWO_SLOW_HCL = """
resource "google_compute_network" "a" {
  name = "a"

  timeouts {
    create = "70s"
  }
}

resource "google_compute_network" "b" {
  name = "b"

  timeouts {
    create = "70s"
  }
}
"""

RETRY_BOTH = [
    {"kind": "api-429", "resource": "google_compute_network.a",
     "op": "create", "max": 1},
    {"kind": "api-429", "resource": "google_compute_network.b",
     "op": "create", "max": 1},
]


@pytest.fixture
def two_slow(tmp_path):
    d = tmp_path / "twoslow"
    d.mkdir()
    (d / "main.tf").write_text(TWO_SLOW_HCL)
    return str(d)


def test_concurrent_ops_charge_only_their_own_elapsed_time(two_slow):
    """Two slow creates (30s attempt + 1s backoff + 30s retry = 61s
    each, budget 70s) on the shared simulated clock: concurrently each
    stays inside its own budget and the pair takes 61s of wall clock —
    charging either one the pair's combined time would blow its
    deadline."""
    outcome, cp = engine_apply(two_slow, specs=RETRY_BOTH, parallelism=2)
    assert outcome.ok, [f.message for f in outcome.failures]
    assert cp.clock.now == pytest.approx(61.0)
    t = trace_by_key(outcome)
    assert t[("google_compute_network.a", "create")].start_s == 0.0
    assert t[("google_compute_network.b", "create")].start_s == 0.0
    # serially the SAME budgets still hold per-operation (wall clock is
    # the sum, each op's charge is unchanged)
    outcome, cp = engine_apply(two_slow, specs=RETRY_BOTH, parallelism=1)
    assert outcome.ok
    assert cp.clock.now == pytest.approx(122.0)


def test_start_operation_budget_ignores_global_clock():
    cp = ControlPlane(FaultProfile(specs=[
        FaultSpec(kind="api-429", max=1)]), seed=0)
    cp.clock.advance(10_000.0)   # someone else's elapsed time
    run = cp.start_operation("google_compute_network.a", "create", 70.0)
    assert run.error is None
    assert run.duration_s == pytest.approx(61.0)


# ------------------------------------------------ crash semantics

def test_crash_abandons_in_flight_operations(two_slow, tmp_path):
    """A crash kills the process at its event time: the op still in
    flight reports nothing — neither completed nor tainted."""
    outcome, _cp = engine_apply(two_slow, specs=[
        {"kind": "crash", "resource": "google_compute_network.a",
         "op": "create"}], parallelism=2)
    assert outcome.crashed
    assert outcome.completed == []
    statuses = {(t.address, t.op): t.status for t in outcome.trace}
    assert statuses[("google_compute_network.a", "create")] == "crashed"
    assert statuses[("google_compute_network.b", "create")] == "abandoned"
    assert outcome.state.resources == {}
    assert outcome.state.tainted == set()


def test_crash_reports_earlier_branch_failures(tmp_path, diamond):
    """Review regression: a crash that lands AFTER a terminal failure
    on another branch must not swallow that failure's (or its skips')
    diagnostics — impossible serially, routine in a parallel walk."""
    pfile = profile_file(
        tmp_path,
        {"fault": "tpu-stockout", "resource": "google_kms_key_ring.*",
         "op": "create"},
        {"fault": "crash", "resource": "google_container_cluster.*",
         "op": "create"})
    spath = tmp_path / "s.json"
    rc, _out, err = run_cli(["apply", diamond, "-state", str(spath),
                             "-fault-profile", pfile, "-fault-seed", "0",
                             "-parallelism", "4"])
    assert rc == 1
    assert "simulated crash" in err
    assert "tpu-stockout" in err and "apply interrupted" in err
    assert ("google_kms_crypto_key.key: skipped — dependency "
            "google_kms_key_ring.ring errored") in err
    # completed work was still persisted before the "process died"
    assert "google_compute_network.vpc" in load_state(spath).resources


# ------------------------------------- determinism & equivalence

def test_same_seed_same_parallelism_same_everything(tmp_path, diamond):
    pfile = profile_file(
        tmp_path,
        {"fault": "api-500", "op": "any", "prob": 0.3, "max": 2},
        {"fault": "quota-exceeded", "op": "create", "prob": 0.4})
    outs = []
    for run in ("x", "y"):
        spath = tmp_path / f"{run}.json"
        rc, out, err = run_cli(["apply", diamond, "-state", str(spath),
                                "-fault-profile", pfile,
                                "-fault-seed", "5", "-parallelism", "4"])
        outs.append((rc, out, err,
                     load_state(spath).resources
                     if spath.exists() else None))
    assert outs[0] == outs[1]


def test_fault_free_state_equivalent_across_parallelism(tmp_path,
                                                        diamond):
    """Serial and parallel runs land the same final state (the empty
    profile also proves -parallelism adds zero drift to the happy
    path's output)."""
    pfile = profile_file(tmp_path)     # {"faults": []}
    rc, plain_out, _ = run_cli(["apply", diamond, "-state",
                                str(tmp_path / "plain.json")])
    assert rc == 0
    states, outputs = [], []
    for p in (1, 4, 10):
        spath = tmp_path / f"p{p}.json"
        rc, out, _err = run_cli(["apply", diamond, "-state", str(spath),
                                 "-fault-profile", pfile,
                                 "-parallelism", str(p)])
        assert rc == 0
        outputs.append(out)
        states.append(load_state(spath))
    assert outputs[0] == plain_out       # byte-for-byte at parallelism 1
    assert outputs[0] == outputs[1] == outputs[2]
    base = load_state(tmp_path / "plain.json")
    for st in states:
        assert st.resources == base.resources
        assert st.outputs == base.outputs
        assert st.tainted == base.tainted
        assert st.serial == base.serial


def test_parallelism_flag_validation(diamond, tmp_path, capsys):
    rc, _out, err = run_cli(["apply", diamond, "-state",
                             str(tmp_path / "s.json"),
                             "-parallelism", "0"])
    assert rc == 2
    assert "-parallelism must be at least 1" in err


# --------------------------------------- graph -cycles (satellite)

CYCLE_HCL = """
resource "google_compute_network" "x" {
  name = google_compute_subnetwork.y.name
}

resource "google_compute_subnetwork" "y" {
  name = google_compute_network.x.name
}
"""


def test_graph_cycles_renders_dot_subgraph(tmp_path):
    d = tmp_path / "cyclic"
    d.mkdir()
    (d / "main.tf").write_text(CYCLE_HCL)
    rc, out, err = run_cli(["graph", str(d)])
    assert rc == 1
    assert "dependency cycle" in err and out == ""
    rc, out, err = run_cli(["graph", str(d), "-cycles"])
    assert rc == 1
    assert "dependency cycle" in err
    assert "subgraph cluster_cycle" in out
    assert '"google_compute_network.x" [color = "red"];' in out
    assert '"google_compute_subnetwork.y" [color = "red"];' in out
    # the loop closes: both directed edges appear
    assert ('"google_compute_network.x" -> "google_compute_subnetwork.y"'
            in out)
    assert ('"google_compute_subnetwork.y" -> "google_compute_network.x"'
            in out)


def test_graph_without_cycle_unaffected_by_flag(diamond):
    rc, out, err = run_cli(["graph", diamond, "-cycles"])
    assert rc == 0
    assert out.startswith("digraph {")
    assert "cluster_cycle" not in out
