# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""Ring attention: exactness vs dense reference, grads, burn-in integration.

The reference has no long-context story at all (SURVEY §5); ours is ring
attention over the sp mesh axis. These tests prove the ring produces the SAME
numbers as dense attention — forward and backward — on every mesh
factorisation a v5e-8 slice supports, so the smoke-test Job can trust it.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from nvidia_terraform_modules_tpu.models import (
    BurnInConfig,
    forward,
    init_params,
    make_train_step,
    synthetic_batch,
)
from nvidia_terraform_modules_tpu.ops import (
    dense_reference_attention,
    ring_self_attention,
)
from nvidia_terraform_modules_tpu.parallel import build_mesh, make_rules, plan_mesh


def _mesh(jax, dp, sp, tp):
    devs = np.array(jax.devices()[: dp * sp * tp]).reshape(dp, sp, tp)
    return Mesh(devs, ("dp", "sp", "tp"))


def _qkv(b=4, s=16, h=2, d=8, dtype=jnp.float32):
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    return tuple(jax.random.normal(k, (b, s, h, d), dtype) for k in ks)


@pytest.mark.parametrize("dp,sp,tp", [(1, 1, 1), (1, 2, 1), (1, 8, 1),
                                      (2, 2, 2), (1, 2, 2), (4, 2, 1)])
@pytest.mark.parametrize("causal", [True, False])
def test_ring_matches_dense(jax8, dp, sp, tp, causal):
    q, k, v = _qkv()
    ref = dense_reference_attention(q, k, v, causal=causal)
    out = ring_self_attention(q, k, v, _mesh(jax8, dp, sp, tp), causal=causal)
    assert jnp.max(jnp.abs(out - ref)) < 1e-5


@pytest.mark.parametrize("impl", ["dense", "flash"])
@pytest.mark.parametrize("causal", [True, False])
def test_ring_impls_match_dense_at_tile_scale(jax8, impl, causal):
    """Both per-block tile paths, at shapes where the flash path actually
    tiles (s_local = 64 → 8-multiple blocks): VERDICT round-1 item 8 —
    ring composed with the pallas flash kernel must stay exact."""
    q, k, v = _qkv(b=2, s=256, h=2, d=16)
    mesh = _mesh(jax8, 1, 4, 2)
    ref = dense_reference_attention(q, k, v, causal=causal)
    out = ring_self_attention(q, k, v, mesh, causal=causal, impl=impl)
    assert jnp.max(jnp.abs(out - ref)) < 2e-5


@pytest.mark.parametrize("impl", ["dense", "flash"])
def test_ring_impl_gradients_match_dense(jax8, impl):
    q, k, v = _qkv(b=2, s=128, h=2, d=16)
    mesh = _mesh(jax8, 1, 4, 1)

    def f_ring(q, k, v):
        return jnp.sum(jnp.square(
            ring_self_attention(q, k, v, mesh, impl=impl)))

    def f_ref(q, k, v):
        return jnp.sum(jnp.square(dense_reference_attention(q, k, v)))

    g_ring = jax.grad(f_ring, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        assert jnp.max(jnp.abs(a - b)) < 1e-3


def test_ring_invalid_impl_rejected(jax8):
    with pytest.raises(ValueError, match="unknown ring impl"):
        ring_self_attention(*_qkv(), _mesh(jax8, 1, 2, 1), impl="cuda")


def test_ring_auto_impl_falls_back_to_dense_on_untileable_shards(jax8):
    """s=100 over sp=4 → s_loc=25, no 8-multiple divisor: the default impl
    must fall back to the dense ring (round-1 behavior) instead of raising,
    while explicit impl='flash' still raises the actionable error."""
    q, k, v = _qkv(s=100)
    mesh = _mesh(jax8, 1, 4, 1)
    ref = dense_reference_attention(q, k, v)
    out = ring_self_attention(q, k, v, mesh)
    assert jnp.max(jnp.abs(out - ref)) < 1e-5
    with pytest.raises(ValueError, match="pad the sequence"):
        ring_self_attention(q, k, v, mesh, impl="flash")


def test_ring_gradients_match_dense(jax8):
    q, k, v = _qkv()
    mesh = _mesh(jax8, 2, 2, 2)

    def f_ring(q, k, v):
        return jnp.sum(jnp.square(ring_self_attention(q, k, v, mesh)))

    def f_ref(q, k, v):
        return jnp.sum(jnp.square(dense_reference_attention(q, k, v)))

    g_ring = jax.grad(f_ring, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        assert jnp.max(jnp.abs(a - b)) < 1e-4


def test_ring_jit_under_sharded_inputs(jax8):
    """jit(shard_map) with committed sharded inputs — the production shape."""
    mesh = _mesh(jax8, 1, 4, 2)
    q, k, v = _qkv(s=32)
    spec = jax.sharding.NamedSharding(mesh, P("dp", "sp", "tp", None))
    q, k, v = (jax.device_put(t, spec) for t in (q, k, v))
    out = jax.jit(lambda q, k, v: ring_self_attention(q, k, v, mesh))(q, k, v)
    ref = dense_reference_attention(
        jax.device_get(q), jax.device_get(k), jax.device_get(v))
    assert jnp.max(jnp.abs(jax.device_get(out) - ref)) < 1e-5


def test_burnin_ring_matches_dense_forward(jax8):
    """attn="ring" must be a pure layout change: identical numbers (f32)."""
    mesh = build_mesh(plan_mesh(8, tp=2, sp=2))
    rules = make_rules(mesh)
    base = dict(vocab=64, d_model=32, n_heads=2, d_ff=64, n_layers=2,
                seq_len=16, batch=8, dtype=jnp.float32)
    cfg_d = BurnInConfig(**base, attn="dense")
    cfg_r = BurnInConfig(**base, attn="ring")
    params = init_params(jax.random.PRNGKey(0), cfg_d, rules)
    tokens, _ = synthetic_batch(jax.random.PRNGKey(1), cfg_d, rules)
    dense = forward(params, tokens, cfg_d, rules)
    ring = forward(params, tokens, cfg_r, rules)
    assert jnp.max(jnp.abs(dense - ring)) < 1e-5


def test_burnin_ring_train_step_decreases_loss(jax8):
    mesh = build_mesh(plan_mesh(8, tp=2, sp=2))
    rules = make_rules(mesh)
    cfg = BurnInConfig(vocab=64, d_model=32, n_heads=2, d_ff=64, n_layers=2,
                       seq_len=16, batch=8, attn="ring")
    params = init_params(jax.random.PRNGKey(0), cfg, rules)
    step = make_train_step(cfg, rules, lr=5e-2)
    batch = synthetic_batch(jax.random.PRNGKey(1), cfg, rules)
    losses = []
    for _ in range(8):
        params, loss = step(params, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses


def test_ring_unsharded_config_falls_back_to_dense():
    """attn="ring" without rules (single chip) must still run — dense path."""
    cfg = BurnInConfig(vocab=64, d_model=32, n_heads=2, d_ff=64, n_layers=1,
                       seq_len=16, batch=4, attn="ring")
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens, _ = synthetic_batch(jax.random.PRNGKey(1), cfg)
    logits = forward(params, tokens, cfg)
    assert logits.shape == (4, 16, 64)
    assert jnp.isfinite(logits.astype(jnp.float32)).all()


def test_invalid_attn_impl_rejected():
    with pytest.raises(ValueError, match="unknown attn impl"):
        BurnInConfig(attn="flashh")


def test_long_sequence_ring_memory_shape(jax8):
    """S=512 over sp=8: each shard only ever holds S/8 of the sequence."""
    mesh = _mesh(jax8, 1, 8, 1)
    q, k, v = _qkv(b=1, s=512, h=2, d=8)
    out = ring_self_attention(q, k, v, mesh, causal=True)
    ref = dense_reference_attention(q, k, v, causal=True)
    assert jnp.max(jnp.abs(out - ref)) < 1e-5


# ------------------------------------------- pipelined ring sweep (PR 9)

def test_ring_pipelined_bitmatches_unpipelined(jax8):
    """The ring's per-visiting-block flash sweeps under pipeline='on' must
    BIT-match pipeline='off' at equal blocks — the same scheduling-only
    contract as the monolithic kernel, here through shard_map, the
    lax.scan ring rotation, and the per-block custom_vjp."""
    q, k, v = _qkv(b=2, s=256, h=2, d=16)
    mesh = _mesh(jax8, 1, 4, 1)

    def run(pipeline):
        return ring_self_attention(q, k, v, mesh, impl="flash",
                                   pipeline=pipeline, block_q=16,
                                   block_k=16)

    assert jnp.array_equal(run("on"), run("off"))

    def g(pipeline):
        return jax.grad(
            lambda q_, k_, v_: jnp.sum(jnp.square(ring_self_attention(
                q_, k_, v_, mesh, impl="flash", pipeline=pipeline,
                block_q=16, block_k=16))),
            argnums=(0, 1, 2))(q, k, v)

    for a, b in zip(g("on"), g("off")):
        assert jnp.array_equal(a, b)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_pipelined_fused_matches_dense_at_sharded_s(jax8, causal):
    """The flagship composition the ISSUE names: ring attention at a
    sharded S with the PIPELINED fused backward per visiting K/V block —
    forward and gradients against the dense reference."""
    q, k, v = _qkv(b=2, s=256, h=2, d=16)
    mesh = _mesh(jax8, 1, 4, 2)
    ref = dense_reference_attention(q, k, v, causal=causal)
    out = ring_self_attention(q, k, v, mesh, causal=causal, impl="flash",
                              pipeline="on", block_q=16, block_k=16)
    assert jnp.max(jnp.abs(out - ref)) < 2e-5

    def f_ring(q_, k_, v_):
        return jnp.sum(jnp.square(ring_self_attention(
            q_, k_, v_, mesh, causal=causal, impl="flash", pipeline="on",
            block_q=16, block_k=16)))

    def f_ref(q_, k_, v_):
        return jnp.sum(jnp.square(dense_reference_attention(
            q_, k_, v_, causal=causal)))

    g_ring = jax.grad(f_ring, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        assert jnp.max(jnp.abs(a - b)) < 1e-3


def test_ring_auto_pipeline_shrinks_default_k_block(jax8):
    """The ring's default K block spans the whole shard (nk = 1); under
    pipeline='auto' the default must walk down to an even tiling so the
    flagship actually runs pipelined — and stay exact doing it."""
    q, k, v = _qkv(b=1, s=256, h=2, d=8)
    mesh = _mesh(jax8, 1, 4, 1)
    out = ring_self_attention(q, k, v, mesh, impl="flash")
    ref = dense_reference_attention(q, k, v)
    assert jnp.max(jnp.abs(out - ref)) < 1e-5


def test_ring_pipeline_knob_validated(jax8):
    with pytest.raises(ValueError, match="auto|on|off"):
        ring_self_attention(*_qkv(s=64), _mesh(jax8, 1, 2, 1),
                            impl="flash", pipeline="bogus")
