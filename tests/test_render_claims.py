# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""The claims pipeline guards the docs; this guards the pipeline.

tools/render_claims.py is a CI gate (README's Measured-performance
table must re-render byte-identically from the newest committed
capture) — a regression here silently un-gates every published number.
"""

import importlib.util
import json
import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOL = os.path.join(ROOT, "tools", "render_claims.py")


def _mod():
    spec = importlib.util.spec_from_file_location("render_claims", TOOL)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_check_passes_against_committed_artifact():
    """The committed README block must match a fresh render — the exact
    assertion CI makes."""
    proc = subprocess.run([sys.executable, TOOL, "--check"], cwd=ROOT,
                          capture_output=True, text=True)
    assert proc.returncode == 0, proc.stderr


def test_newest_artifact_picks_highest_round():
    mod = _mod()
    newest = os.path.basename(mod.newest_artifact())
    rounds = [int(f.split("_r")[-1].split(".")[0])
              for f in os.listdir(ROOT)
              if f.startswith("BENCH_tpu_capture_r")]
    assert newest == f"BENCH_tpu_capture_r{max(rounds):02d}.json" or \
        newest == f"BENCH_tpu_capture_r{max(rounds)}.json"


def test_render_skips_absent_fields_and_formats_minmax(tmp_path):
    mod = _mod()
    art = tmp_path / "BENCH_tpu_capture_r99.json"
    art.write_text(json.dumps({
        "device_kind": "TPU v5 lite", "bench_platform": "tpu",
        "burnin_mfu": 0.7, "burnin_mfu_minmax": [0.69, 0.71],
    }))
    block = mod.render(str(art))
    assert "0.700" in block and "0.690 – 0.710" in block
    # absent metrics leave no row behind
    assert "Decode, bf16" not in block
    assert block.startswith(mod.BEGIN) and block.endswith(mod.END)


def test_splice_requires_markers():
    mod = _mod()
    with pytest.raises(SystemExit, match="markers"):
        mod.splice("no markers here", "block")
    out = mod.splice(f"head\n{mod.BEGIN}\nold\n{mod.END}\ntail",
                     f"{mod.BEGIN}\nnew\n{mod.END}")
    assert "new" in out and "old" not in out
    assert out.startswith("head") and out.endswith("tail")
