# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""The graph-parallel apply engine: terraform's walk, fault-aware.

``apply_plan`` (:mod:`..state`) realises a diff atomically — correct,
but it cannot fail halfway and it cannot race. Real ``terraform apply``
walks the resource graph with up to ``-parallelism N`` (default 10)
concurrent operations, and when one fails terminally it does NOT abort
the world: independent branches run to completion, only the failed
node's transitive dependents are *skipped*. This engine reproduces that
walk deterministically:

- the diff becomes a per-instance operation DAG
  (:func:`operation_schedule`): creates/updates in dependency order,
  deletes in reverse-edge order, a replace expanding to its
  delete → create pair (destroy-before-create default);
- up to ``parallelism`` ready operations run concurrently on the
  :class:`..control_plane.ControlPlane`'s **simulated clock**. Dispatch
  order is the serial priority order, completions are arbitrated on an
  event heap with a deterministic tie-break — identical
  ``(-fault-seed, -parallelism)`` ⇒ identical interleaving, and
  ``-parallelism 1`` reproduces the historical serial engine exactly
  (same RNG stream, same operation order, same output);
- terraform's failure isolation: a terminal fault marks the operation
  failed, its transitive dependents become **skipped** (reported as
  ``"<addr>: skipped — dependency <failed addr> errored"``), every
  completed operation is **persisted** to the returned state, and a
  half-created resource (preemption/timeout mid-create) is recorded
  **tainted** so the next apply replaces it instead of duplicating it;
- a ``crash`` kills the process at its event time: operations still in
  flight report nothing (neither completed nor tainted), exactly like
  the crashing operation itself.

When every operation succeeds the engine returns ``apply_plan``'s own
result, so a profile that injects nothing is bit-identical to the
atomic path.
"""

from __future__ import annotations

import dataclasses
import heapq

from ..plan import Plan, instance_apply_order, instance_dependencies
from ..state import Diff, State, apply_plan, diff, rendered_instances
from .control_plane import (
    DEFAULT_TIMEOUT_S,
    ControlPlane,
    CrashSignal,
    FaultError,
    TerminalFault,
    parse_duration,
)
from .profile import PARTIAL_CREATE

# terraform's own default for `-parallelism`
DEFAULT_PARALLELISM = 10


class SimulatedCrash(FaultError):
    """The profile killed the apply process. Carries the partial
    :class:`ApplyOutcome` so the CLI can persist completed work before
    "dying" — and, unlike every other failure, the state **lock is left
    behind** (a crashed process releases nothing), so the recovery
    playbook's ``force-unlock`` step is exercised too."""

    def __init__(self, outcome: "ApplyOutcome"):
        super().__init__(
            "simulated crash: apply died mid-run (the state lock, if "
            "held, was left behind — break it with `tfsim force-unlock`)")
        self.outcome = outcome


@dataclasses.dataclass
class OpFailure:
    """One terminal failure in an apply (there can now be several: a
    fault on each independent branch)."""

    address: str
    op: str            # create | update | delete
    kind: str          # fault kind ("timeout" for an exhausted budget)
    message: str
    attempts: int


@dataclasses.dataclass
class SkippedOp:
    """An operation never attempted because a dependency errored."""

    address: str
    op: str
    blamed: str     # the failed address whose error cascaded here

    def describe(self) -> str:
        return (f"{self.address}: skipped — dependency {self.blamed} "
                f"errored")


@dataclasses.dataclass
class OpTrace:
    """One operation's scheduled execution, for invariant checking
    (the chaos harness asserts dependency-order safety, skipped-closure
    exactness, and the concurrency cap from this record)."""

    address: str
    op: str
    start_s: float
    finish_s: float
    status: str            # ok | failed | skipped | crashed | abandoned
    blamed: str | None = None    # for skipped: the errored address


@dataclasses.dataclass
class ApplyOutcome:
    state: State
    crashed: bool = False
    completed: list = dataclasses.field(default_factory=list)  # (addr, op)
    mutated: bool = False    # state differs from prior → worth persisting
    failures: list = dataclasses.field(default_factory=list)   # [OpFailure]
    skipped: list = dataclasses.field(default_factory=list)    # [SkippedOp]
    trace: list = dataclasses.field(default_factory=list)      # [OpTrace]

    @property
    def failure(self) -> OpFailure | None:
        """The first terminal failure — the serial engine's single
        slot, kept for callers that predate graph-parallel apply."""
        return self.failures[0] if self.failures else None

    @property
    def ok(self) -> bool:
        return not self.failures and not self.crashed


def _timeouts_of(attrs) -> dict:
    """The resource's rendered ``timeouts {}`` block, if any. Blocks
    evaluate to a list of one object; tolerate both shapes."""
    t = (attrs or {}).get("timeouts")
    if isinstance(t, list) and t and isinstance(t[0], dict):
        return t[0]
    return t if isinstance(t, dict) else {}


def operation_timeout_s(op: str, planned_attrs, prior_attrs=None) -> float:
    """The ``timeouts {}`` budget for one operation, in simulated
    seconds. Deletes of resources gone from config take the budget the
    *applied* attributes carry (the config block that created them);
    anything undeclared gets the provider default."""
    spec = _timeouts_of(planned_attrs) or _timeouts_of(prior_attrs)
    raw = spec.get(op)
    if isinstance(raw, str) and raw.strip():
        budget = parse_duration(raw, what=f"timeouts.{op}")
        if budget <= 0:
            raise ValueError(
                f"invalid timeouts.{op} duration {raw!r}: an operation "
                f"budget must be positive")
        return budget
    return DEFAULT_TIMEOUT_S


def operation_schedule(plan: Plan, d: Diff
                       ) -> tuple[list[tuple[str, str]], list[set[int]]]:
    """The apply schedule for a diff: ``(ops, deps)``.

    ``ops`` is the serial priority order — ``-parallelism 1`` executes
    exactly this sequence, higher parallelism dispatches ready
    operations in this order: EVERY delete first (plain deletes and
    the destroy half of each replace) in reverse dependency order
    (terraform tears down leaves before roots), then creates/updates
    in dependency order, a replace's create where the serial engine
    ran it.

    ``deps[i]`` is the set of op indices that must complete before
    ``ops[i]`` may start:

    - a create/update waits for the realising operation of every
      address it transitively depends on in the plan graph;
    - a replace's create waits for its own delete (destroy-before-
      create default);
    - a delete — plain or replace — waits for the deletes of the
      addresses that *depend on* it: reverse-edge direction, so a
      replaced resource is never destroyed while a dependent's delete
      is still pending;
    - addresses only in state (node gone from config) carry no edges —
      the simulated statefile records no dependency information, so
      they schedule freely (and deterministically: see
      :func:`..plan.instance_apply_order`'s stable state-only rank).

    Every edge points to a lower index (``ops`` is a linearisation of
    this DAG), which downstream closure walks rely on. Public so the
    chaos harness can assert the scheduler's dependency-order safety
    and skipped-closure exactness against the same ground truth the
    engine runs on.
    """
    delete_addrs = d.by_action("delete") + d.by_action("replace")
    change_addrs = (d.by_action("create") + d.by_action("update") +
                    d.by_action("replace"))
    rev = instance_dependencies(plan, delete_addrs)
    fwd = instance_dependencies(plan, change_addrs)
    ops: list[tuple[str, str]] = []
    for addr in reversed(instance_apply_order(plan, delete_addrs,
                                              deps=rev)):
        ops.append((addr, "delete"))
    for addr in instance_apply_order(plan, change_addrs, deps=fwd):
        act = d.actions[addr]
        ops.append((addr, "create" if act == "replace" else act))
    delete_idx = {a: i for i, (a, op) in enumerate(ops)
                  if op == "delete"}
    final_idx = {a: i for i, (a, op) in enumerate(ops)
                 if op != "delete"}    # the op that realises an address
    deps: list[set[int]] = [set() for _ in ops]
    for addr, wants in fwd.items():
        deps[final_idx[addr]] |= {final_idx[b] for b in wants}
        if addr in delete_idx:    # replace: destroy-before-create
            deps[final_idx[addr]].add(delete_idx[addr])
    for addr, wants in rev.items():
        for b in wants:     # addr depends on b ⇒ delete addr BEFORE b
            deps[delete_idx[b]].add(delete_idx[addr])
    return ops, deps


def _partial_state(prior: State | None, planned: dict,
                   completed: list[tuple[str, str]],
                   taints=()) -> tuple[State, bool]:
    """The state an interrupted apply persists: prior advanced by every
    completed operation, plus the tainted half-created resources.
    Returns ``(state, mutated)``."""
    resources = dict(prior.resources) if prior else {}
    tainted = set(prior.tainted) if prior else set()
    for addr, op in completed:
        if op == "delete":
            resources.pop(addr, None)
            tainted.discard(addr)
        else:
            resources[addr] = planned[addr]
            tainted.discard(addr)   # a completed replace consumed the taint
    for addr in taints:
        resources[addr] = planned[addr]
        tainted.add(addr)
    mutated = (resources != (dict(prior.resources) if prior else {}) or
               tainted != (set(prior.tainted) if prior else set()))
    serial = (prior.serial if prior else 0) + (1 if mutated else 0)
    # outputs are NOT refreshed: the plan did not complete, and claiming
    # its outputs would hand the operator values the infrastructure
    # doesn't have (the converging re-apply refreshes them)
    return State(resources=resources, serial=serial,
                 outputs=dict(prior.outputs) if prior else {},
                 tainted=tainted,
                 lineage=prior.lineage if prior else ""), mutated


def run_apply(plan: Plan, prior: State | None, cp: ControlPlane,
              targets: list[str] | None = None,
              d: Diff | None = None, log=None,
              parallelism: int = DEFAULT_PARALLELISM) -> ApplyOutcome:
    """Apply ``plan`` over ``prior``, up to ``parallelism`` operations
    at a time on the simulated clock.

    Returns an :class:`ApplyOutcome`; raises :class:`SimulatedCrash`
    (carrying the partial outcome) when the profile kills the process.
    On full success the returned state comes from :func:`..state.apply_plan`
    — the fault layer adds no drift to the happy path.

    Determinism: ready operations dispatch in serial priority order
    (consuming the profile's RNG stream at dispatch), completions pop
    off an event heap keyed ``(finish time, dispatch sequence)`` — so
    the whole interleaving is a pure function of
    ``(profile, seed, parallelism)``.
    """
    if parallelism < 1:
        raise ValueError("parallelism must be >= 1")
    if d is None:
        d = diff(plan, prior, targets)
    planned = rendered_instances(plan)
    prior_res = prior.resources if prior else {}
    ops, deps = operation_schedule(plan, d)
    # validate EVERY timeouts{} budget before the first operation runs:
    # a malformed duration must fail the apply up front (state untouched),
    # never halfway through — that would orphan the completed work
    timeouts: dict[tuple[str, str], float] = {}
    for addr, op in ops:
        try:
            timeouts[(addr, op)] = operation_timeout_s(
                op, planned.get(addr), prior_res.get(addr))
        except ValueError as ex:
            raise ValueError(f"{addr}: {ex}") from None

    completed: list[tuple[str, str]] = []
    failures: list[OpFailure] = []
    skipped: list[SkippedOp] = []
    trace: list[OpTrace] = []
    taints: set[str] = set()
    state_of = ["pending"] * len(ops)
    waiting = [set(s) for s in deps]
    dependents: list[list[int]] = [[] for _ in ops]
    for i, s in enumerate(deps):
        for j in s:
            dependents[j].append(i)

    ready = [i for i in range(len(ops)) if not waiting[i]]
    heapq.heapify(ready)
    # in-flight completions: (finish time, dispatch seq, op index, OpRun)
    events: list = []
    started: dict[int, float] = {}
    now = cp.clock.now
    seq = 0

    def skip_dependents(root: int, blamed: str) -> None:
        hit: list[int] = []
        stack = [root]
        while stack:
            for dep in dependents[stack.pop()]:
                if state_of[dep] == "pending":
                    state_of[dep] = "skipped"
                    hit.append(dep)
                    stack.append(dep)
        for k in sorted(hit):
            a, o = ops[k]
            skipped.append(SkippedOp(a, o, blamed))
            trace.append(OpTrace(a, o, now, now, "skipped", blamed))

    while True:
        # dispatch every ready op the worker pool can hold, in serial
        # priority order — THE deterministic arbitration point: the
        # profile's RNG draws happen here, in dispatch order
        while ready and len(events) < parallelism:
            i = heapq.heappop(ready)
            if state_of[i] != "pending":
                continue    # skipped while queued (defensive: a skip
                            # can only cascade through dependency
                            # edges, which a ready op has none left of)
            addr, op = ops[i]
            run = cp.start_operation(addr, op, timeouts[addr, op], log=log)
            state_of[i] = "running"
            started[i] = now
            heapq.heappush(events, (now + run.duration_s, seq, i, run))
            seq += 1
        if not events:
            break
        finish, _, i, run = heapq.heappop(events)
        now = max(now, finish)
        cp.clock.now = max(cp.clock.now, finish)
        cp.retries += run.retried
        addr, op = ops[i]
        if run.crashed:
            # the process dies HERE: operations still in flight never
            # report back — neither completed nor tainted, exactly like
            # the crashing operation itself
            trace.append(OpTrace(addr, op, started[i], finish, "crashed"))
            for _t, _s, j, _r in sorted(events):
                a2, o2 = ops[j]
                trace.append(OpTrace(a2, o2, started[j], now, "abandoned"))
            state, mutated = _partial_state(prior, planned, completed,
                                            taints)
            raise SimulatedCrash(ApplyOutcome(
                state=state, crashed=True, completed=completed,
                mutated=mutated, failures=failures, skipped=skipped,
                trace=trace)) from None
        if run.error is not None:
            ex = run.error
            state_of[i] = "failed"
            if op == "create" and ex.kind in PARTIAL_CREATE:
                taints.add(addr)
            failures.append(OpFailure(
                address=addr, op=op, kind=ex.kind, message=str(ex),
                attempts=ex.attempts))
            trace.append(OpTrace(addr, op, started[i], finish, "failed"))
            skip_dependents(i, addr)
            continue
        state_of[i] = "done"
        completed.append((addr, op))
        trace.append(OpTrace(addr, op, started[i], finish, "ok"))
        for dep in dependents[i]:
            if state_of[dep] != "pending":
                continue
            pending = waiting[dep]
            pending.discard(i)
            if not pending:
                heapq.heappush(ready, dep)

    if failures:
        state, mutated = _partial_state(prior, planned, completed, taints)
        return ApplyOutcome(state=state, failures=failures,
                            completed=completed, mutated=mutated,
                            skipped=skipped, trace=trace)
    return ApplyOutcome(state=apply_plan(plan, prior, targets, d=d),
                        completed=completed, mutated=not d.is_noop,
                        trace=trace)


def assign_lanes(trace: list[OpTrace]) -> dict[int, int]:
    """Greedy interval partitioning of the executed operations onto the
    smallest number of lanes — the rendering of ``-parallelism``: with
    the engine's concurrency cap intact, lane count never exceeds the
    parallelism level, so each lane IS one worker slot of the schedule.
    Returns ``{id(op_trace): lane}``; deterministic for a given trace
    (sorted by start, finish, address — the same total order for every
    replay of a (seed, parallelism) pair).
    """
    import heapq as _hq

    ran = [t for t in trace
           if t.status in ("ok", "failed", "crashed", "abandoned")]
    busy: list[tuple[float, int]] = []      # (finish, lane)
    free: list[int] = []
    lanes: dict[int, int] = {}
    n = 0
    for t in sorted(ran, key=lambda t: (t.start_s, t.finish_s, t.address)):
        while busy and busy[0][0] <= t.start_s + 1e-9:
            _, lane = _hq.heappop(busy)
            _hq.heappush(free, lane)
        if free:
            lane = _hq.heappop(free)
        else:
            lane = n
            n += 1
        lanes[id(t)] = lane
        _hq.heappush(busy, (t.finish_s, lane))
    return lanes


def emit_apply_telemetry(outcome: ApplyOutcome, telemetry=None, *,
                         run: str | None = None) -> None:
    """Emit an apply's operation trace as telemetry spans on the
    **simulated clock** (``clock: "sim"``), one lane per parallelism
    slot, so a seeded ``tfsim chaos`` run renders in Perfetto exactly
    like a real training timeline — the fleet end of the one-timeline
    contract. Skipped operations (never started: their dependency
    errored) land as instant events at their decision time. ``run``
    labels the trace's process lane group (e.g. ``"seed3x4"``) so
    sweeps don't interleave. No-op when telemetry is disabled.
    """
    from ...telemetry import get_registry

    reg = telemetry if telemetry is not None else get_registry()
    if not reg.enabled:
        return
    lanes = assign_lanes(outcome.trace)
    pid = run if run is not None else "tfsim-apply"
    op_s = reg.histogram("tfsim_apply_op_s")
    for t in outcome.trace:
        if t.status == "skipped":
            reg.event(f"{t.address} {t.op} skipped", ts=t.start_s,
                      pid=pid, clock="sim", blamed=t.blamed)
            continue
        op_s.record(t.finish_s - t.start_s)
        reg.emit_span(f"{t.address} {t.op}", t.start_s, t.finish_s,
                      lane=lanes[id(t)], pid=pid, clock="sim",
                      status=t.status)
