# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""Seeded arrival-trace generation: the shared demand model.

ONE load model drives both sides of the serving story: the REAL engine
(``models/serving.py`` admits requests at these arrival times;
``bench.py section_serve_engine`` reports sustained tokens/s and
p50/p99 latency under them) and the SIMULATED fleet (ROADMAP item 4's
tfsim capacity digital twin resizes node pools against the same
traces). That is why this module is stdlib-only and deterministic: no
jax import (tfsim and the bench orchestrator must be able to load it
for free), and one ``(kind, seed, params)`` tuple always yields one
byte-identical trace (``tests/test_traffic.py`` property-tests the
determinism), so a simulator run and a bench capture labelled with the
same seed saw the SAME users.

Processes:

- :func:`poisson_trace` — homogeneous Poisson arrivals (exponential
  inter-arrival gaps), the memoryless baseline of serving load.
- :func:`diurnal_trace` — inhomogeneous Poisson via Lewis-Shedler
  thinning against a sinusoidal day curve: rate swings between
  ``base_rate·(1−amplitude)`` and ``base_rate·(1+amplitude)`` over
  ``period`` seconds — the millions-of-users daily tide.
- :func:`spike_trace` — a baseline process plus seeded burst windows at
  ``spike_rate`` (launch moments, retry storms) — the stockout-shaped
  traffic tfsim's fault profiles care about.
- :func:`make_trace` — the string-keyed front door the CLI-ish callers
  (bench sections, future ``tfsim chaos`` demand flags) use.

Traces are plain ``list[float]`` of arrival offsets in seconds,
ascending from 0. :func:`ragged_lengths` rides along for the matching
per-request prompt/output-length draws — ragged lengths are the whole
reason the paged KV cache exists, so the workload generator owns them —
and :func:`shared_prefix_prompts` for Zipf-popularity template
workloads, the shared-leading-span shape the serving engine's
cross-request prefix sharing exists for. :func:`slo_deadlines` closes
the loop on the demand side: per-request latency deadlines
(work-proportional, seeded slack) that the fleet router's SLO-aware
admission sheds against and bills attainment with.
:func:`fault_times` is the SUPPLY-side twin: seeded mid-trace instants
where the serving fault plane (``models/fleet.py``) schedules replica
kills, so a chaos bench and its undisturbed baseline are labelled by
the same seeds end to end.
"""

from __future__ import annotations

import math
import random
from typing import Sequence


def _rng(seed, salt: str = "traffic") -> random.Random:
    # a dedicated Random per trace (the global PRNG would couple traces
    # to call order), seeded by STRING — random's version-2 str seeding
    # is sha512-based and cross-process deterministic, where hash(tuple)
    # would be PYTHONHASHSEED-salted and break the one-seed-one-trace
    # contract between a bench child process and a tfsim run
    return random.Random(f"{salt}-{seed}")


def poisson_trace(rate: float, n: int, seed: int = 0) -> list[float]:
    """``n`` homogeneous Poisson arrivals at ``rate`` requests/second.

    Exponential gaps drawn from a seed-local PRNG; same ``(rate, n,
    seed)`` → same trace, independent of call order or platform.
    """
    if rate <= 0:
        raise ValueError(f"rate must be > 0, got {rate}")
    if n < 0:
        raise ValueError(f"n must be >= 0, got {n}")
    r = _rng(seed)
    t = 0.0
    out = []
    for _ in range(n):
        t += r.expovariate(rate)
        out.append(t)
    return out


def diurnal_rate(t: float, base_rate: float, amplitude: float,
                 period: float, phase: float = 0.0) -> float:
    """Instantaneous rate of the diurnal curve at time ``t`` (seconds):
    ``base·(1 + amplitude·sin(2π(t/period + phase)))``, floored at 0."""
    return max(0.0, base_rate * (
        1.0 + amplitude * math.sin(2.0 * math.pi * (t / period + phase))))


def diurnal_trace(base_rate: float, n: int, seed: int = 0, *,
                  amplitude: float = 0.5, period: float = 86400.0,
                  phase: float = 0.0) -> list[float]:
    """``n`` arrivals from an inhomogeneous Poisson process whose rate
    follows :func:`diurnal_rate` — Lewis-Shedler thinning against the
    peak rate, so the trace is exact for the curve, not a step
    approximation. ``amplitude`` in [0, 1): 0 degrades to
    :func:`poisson_trace`'s homogeneous process (different draws, same
    law)."""
    if not 0.0 <= amplitude < 1.0:
        raise ValueError(f"amplitude must be in [0, 1), got {amplitude}")
    if period <= 0:
        raise ValueError(f"period must be > 0, got {period}")
    if base_rate <= 0:
        raise ValueError(f"base_rate must be > 0, got {base_rate}")
    r = _rng(seed)
    peak = base_rate * (1.0 + amplitude)
    t = 0.0
    out: list[float] = []
    while len(out) < n:
        t += r.expovariate(peak)
        if r.random() * peak <= diurnal_rate(t, base_rate, amplitude,
                                             period, phase):
            out.append(t)
    return out


def spike_trace(base_rate: float, n: int, seed: int = 0, *,
                spike_rate: float | None = None,
                spike_every: float = 60.0,
                spike_duration: float = 5.0) -> list[float]:
    """Baseline Poisson arrivals plus periodic burst windows: every
    ``spike_every`` seconds the rate jumps to ``spike_rate`` (default
    ``10·base_rate``) for ``spike_duration`` seconds — thinning again,
    so bursts are exact. The launch-day / retry-storm shape."""
    if spike_rate is None:
        spike_rate = 10.0 * base_rate
    if base_rate <= 0 or spike_rate <= 0:
        raise ValueError("rates must be > 0")
    if spike_every <= 0 or spike_duration <= 0:
        raise ValueError("spike_every and spike_duration must be > 0")
    r = _rng(seed)
    peak = max(base_rate, spike_rate)
    t = 0.0
    out: list[float] = []
    while len(out) < n:
        t += r.expovariate(peak)
        in_spike = (t % spike_every) < spike_duration
        rate = spike_rate if in_spike else base_rate
        if r.random() * peak <= rate:
            out.append(t)
    return out


_KINDS = {
    "poisson": lambda rate, n, seed, kw: poisson_trace(rate, n, seed),
    "diurnal": lambda rate, n, seed, kw: diurnal_trace(rate, n, seed,
                                                       **kw),
    "spike": lambda rate, n, seed, kw: spike_trace(rate, n, seed, **kw),
}


def make_trace(kind: str, rate: float, n: int, seed: int = 0,
               **kw) -> list[float]:
    """String-keyed trace constructor: ``kind`` ∈ ``poisson | diurnal |
    spike``; extra keywords go to the process (``amplitude``/``period``
    for diurnal, ``spike_rate``/``spike_every``/``spike_duration`` for
    spike). The one entry point bench sections and tfsim share."""
    if kind not in _KINDS:
        raise ValueError(
            f"unknown trace kind {kind!r}: use {' | '.join(_KINDS)}")
    return _KINDS[kind](rate, n, seed, kw)


def ragged_lengths(n: int, seed: int = 0, *, lo: int = 1, hi: int = 64,
                   mean: float | None = None) -> list[int]:
    """``n`` seeded request lengths in ``[lo, hi]`` — long-tailed
    (``lo`` + exponential, clamped at ``hi``), the shape real
    prompt/output lengths have, with the pre-clamp distribution mean at
    ``mean`` (default the range midpoint) — the exponential's own mean
    is ``mean - lo``, so the parameter names the realised label, not an
    offset. The deterministic source of the RAGGEDNESS the paged KV
    cache and per-request retirement exist for: bench and tfsim draw
    the same lengths for the same seed."""
    if not 1 <= lo <= hi:
        raise ValueError(f"need 1 <= lo <= hi, got lo={lo} hi={hi}")
    if hi == lo:
        return [lo] * n                 # constant-length workload
    if mean is None:
        mean = (lo + hi) / 2.0
    if mean <= lo:
        raise ValueError(f"mean must exceed lo ({lo}), got {mean}")
    r = _rng(seed, salt="lengths")
    scale = mean - lo
    return [max(lo, min(hi, lo + int(r.expovariate(1.0 / scale))))
            for _ in range(n)]


def shared_prefix_prompts(n: int, seed: int = 0, *,
                          n_templates: int = 4, zipf_s: float = 1.2,
                          template_len: int = 32, suffix_lo: int = 1,
                          suffix_hi: int = 16, vocab: int = 256,
                          working_set_blocks: int | None = None,
                          block_size: int = 16,
                          ) -> list[tuple[int, list[int]]]:
    """``n`` seeded ``(template_id, prompt)`` pairs for prefix-reuse
    workloads: a pool of ``n_templates`` fixed token templates with
    ZIPF popularity (template rank ``r`` drawn ∝ ``1 / r**zipf_s`` —
    the few-hot-prompts shape real serving traffic has: system prompts,
    few-shot preambles, popular documents), each request appending a
    seeded ragged suffix of ``suffix_lo..suffix_hi`` fresh tokens.

    The shared span is the whole reason the serving engine's
    cross-request prefix sharing exists, so the workload generator owns
    it the way :func:`ragged_lengths` owns raggedness: stdlib-only,
    STRING-seeded (cross-process deterministic — same seed, same
    templates, same draws, whatever PYTHONHASHSEED says), one
    ``(n, seed, params)`` tuple → one byte-identical workload for
    bench, tests and the tfsim fleet simulator alike.

    ``working_set_blocks`` sizes the pool IN KV BLOCKS instead of
    template count: ``n_templates`` is derived as the smallest pool
    whose full-block footprint (``n_templates · (template_len //
    block_size)`` blocks of ``block_size`` tokens — the spans the
    engine's prefix index can actually chain) reaches it. The tiered-KV
    bench drives this knob to a value ABOVE the engine's
    ``prefix_keep_blocks`` so the device cap provably cannot retain the
    template working set and the host spill tier has real work —
    ``template_len`` must then hold at least one full block
    (``template_len >= block_size``), or no template would ever enter
    the index. Derivation is part of the seeded parameter tuple like
    everything else here: one ``(working_set_blocks, block_size)``
    pair → one pool, byte-identical across processes.
    """
    if n < 0:
        raise ValueError(f"n must be >= 0, got {n}")
    if working_set_blocks is not None:
        if working_set_blocks < 1:
            raise ValueError(
                f"working_set_blocks must be >= 1, got "
                f"{working_set_blocks}")
        if block_size < 1:
            raise ValueError(
                f"block_size must be >= 1, got {block_size}")
        if template_len < block_size:
            raise ValueError(
                f"working_set_blocks sizes the pool in FULL kv blocks "
                f"— template_len ({template_len}) must hold at least "
                f"one block_size ({block_size}) span, or no template "
                f"ever enters the prefix index")
        per_template = template_len // block_size
        n_templates = -(-working_set_blocks // per_template)
    if n_templates < 1:
        raise ValueError(f"n_templates must be >= 1, got {n_templates}")
    if template_len < 1:
        raise ValueError(f"template_len must be >= 1, got {template_len}")
    if not 1 <= suffix_lo <= suffix_hi:
        raise ValueError(
            f"need 1 <= suffix_lo <= suffix_hi, got "
            f"lo={suffix_lo} hi={suffix_hi}")
    if vocab < 2:
        raise ValueError(f"vocab must be >= 2, got {vocab}")
    if zipf_s <= 0:
        raise ValueError(f"zipf_s must be > 0, got {zipf_s}")
    r = _rng(seed, salt="prefix")
    templates = [[r.randrange(vocab) for _ in range(template_len)]
                 for _ in range(n_templates)]
    weights = [1.0 / (rank + 1) ** zipf_s for rank in range(n_templates)]
    total = sum(weights)
    cum = []
    acc = 0.0
    for w in weights:
        acc += w / total
        cum.append(acc)
    # float rounding can leave cum[-1] a hair under 1.0 while random()
    # reaches 1 - 2**-53 — pin the last boundary so the draw can never
    # fall off the end of the table
    cum[-1] = 1.0
    out: list[tuple[int, list[int]]] = []
    for _ in range(n):
        u = r.random()
        tid = next(i for i, c in enumerate(cum) if u <= c)
        suffix = [r.randrange(vocab)
                  for _ in range(r.randint(suffix_lo, suffix_hi))]
        out.append((tid, templates[tid] + suffix))
    return out


def slo_deadlines(budgets: Sequence[int], seed: int = 0, *,
                  base_s: float = 0.05, per_token_s: float = 0.01,
                  jitter: float = 0.25) -> list[float]:
    """Per-request SLO deadlines (seconds from each request's ARRIVAL)
    for a trace whose generation budgets are ``budgets``: deadline_i =
    ``(base_s + per_token_s * budgets[i]) * u_i`` with ``u_i`` drawn
    uniformly from ``[1 - jitter, 1 + jitter]`` — work-proportional
    (a 200-token answer is allowed longer than a 5-token one, the shape
    real latency SLOs have) with seeded per-request slack so identical
    budgets still exercise distinct deadlines.

    The fleet router's admission control (``models/fleet.py``) sheds a
    request when its predicted queue wait would blow this bound, and
    ``last_stats["fleet"]["deadline_attainment"]`` bills the realised
    outcome against the same numbers — so the deadline generator lives
    here with the arrival/length generators: stdlib-only, STRING-seeded
    (cross-process deterministic whatever PYTHONHASHSEED says), one
    ``(budgets, seed, params)`` tuple → one byte-identical deadline
    vector for bench, tests and the tfsim fleet twin alike.
    """
    if base_s <= 0 or per_token_s < 0:
        raise ValueError(
            f"need base_s > 0 and per_token_s >= 0, got "
            f"base_s={base_s} per_token_s={per_token_s}")
    if not 0.0 <= jitter < 1.0:
        raise ValueError(f"jitter must be in [0, 1), got {jitter}")
    for b in budgets:
        if b < 1:
            raise ValueError(f"budgets must be >= 1, got {b}")
    r = _rng(seed, salt="slo")
    return [(base_s + per_token_s * int(b))
            * (1.0 + jitter * (2.0 * r.random() - 1.0))
            for b in budgets]


def fault_times(times: Sequence[float], n: int = 1, seed: int = 0, *,
                lo: float = 0.25, hi: float = 0.75) -> list[float]:
    """``n`` seeded fault instants strictly INSIDE an arrival trace —
    uniform draws over the ``[lo, hi]`` fraction of the trace's horizon,
    sorted ascending. The mid-trace kill schedule for the serving fault
    plane (``models/fleet.py``'s :class:`FleetFaultProfile`): bench's
    redrive leg, the smoketest's ``fleet_chaos_ok`` burn-in and the
    chaos-gate matrix all need kills that land while requests are still
    in flight — not before the first arrival (a trivial re-route) and
    not after the last retirement (a no-op) — from the SAME one-seed-
    one-schedule contract as every generator here: stdlib-only,
    STRING-seeded, byte-identical across processes whatever
    PYTHONHASHSEED says.
    """
    if n < 0:
        raise ValueError(f"n must be >= 0, got {n}")
    if not times:
        raise ValueError("fault_times needs a non-empty arrival trace")
    if not 0.0 <= lo <= hi <= 1.0:
        raise ValueError(
            f"need 0 <= lo <= hi <= 1, got lo={lo} hi={hi}")
    horizon = max(times)
    r = _rng(seed, salt="fault")
    return sorted(horizon * (lo + (hi - lo) * r.random())
                  for _ in range(n))


def trace_summary(times: Sequence[float]) -> dict[str, float]:
    """Host-side sanity stats for a trace (bench provenance fields):
    count, horizon, realised mean rate, max burst in any 1 s window."""
    times = sorted(times)
    n = len(times)
    horizon = times[-1] if times else 0.0
    burst = 0
    j = 0
    for i in range(n):
        while times[i] - times[j] > 1.0:
            j += 1
        burst = max(burst, i - j + 1)
    return {
        "count": n,
        "horizon_s": round(horizon, 3),
        "mean_rate": round(n / horizon, 3) if horizon > 0 else float(n),
        "max_burst_1s": burst,
    }
