# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""Saved plan files: ``plan -out=FILE`` → ``show FILE`` → ``apply FILE``.

The reference's documented operator flow is review-then-apply
(``/root/reference/gke/README.md:45-49``: run ``terraform plan``, inspect,
then ``terraform apply``). Real terraform makes that safe with plan files:
what you apply is byte-for-byte what you reviewed, and a plan computed
against stale state is refused ("saved plan is stale") instead of silently
re-planning. tfsim implements the same contract offline:

- the file records the fully-resolved plan (rendered instances, outputs,
  apply order), the diff it showed the reviewer, the effective variables,
  and the **serial of the state it was computed against**;
- ``apply FILE`` re-loads the current state and refuses on serial drift —
  terraform's stale-plan error — so the review can never be bypassed by a
  concurrent apply;
- ``show FILE`` renders the saved diff (or the raw JSON with ``-json``)
  without touching state.

The format is versioned JSON (``tfsim-plan/1``); forward-incompatible
files are a clean error, not a KeyError.
"""

from __future__ import annotations

import json
import os
from typing import Any

from .plan import Plan, PlannedInstance, ResourceAttrs, render
from .state import Diff, State

PLAN_FORMAT = "tfsim-plan/1"

# every key apply/show dereferences: absence is a clean PlanFileError at
# load time (the documented contract), never a KeyError mid-apply
_REQUIRED_KEYS = frozenset({
    "module_dir", "workspace", "state_path", "targets", "variables",
    "state_serial", "instances", "outputs", "sensitive_outputs", "order",
    "check_failures", "actions", "changed_keys",
})


class PlanFileError(ValueError):
    pass


def plan_file_payload(plan: Plan, d: Diff, disk_serial: int | None, *,
                      module_dir: str, workspace: str,
                      state_path: str | None,
                      targets: list[str] | None,
                      replace: list[str] | None = None,
                      imports: list | None = None) -> dict[str, Any]:
    """The serializable record of a reviewed plan.

    Instances are stored RENDERED (computed markers as strings) — the same
    shape ``apply`` writes to state, so reconstruction round-trips.
    ``disk_serial`` is the ON-DISK state serial (pre-``moved{}``
    migration, which is in-memory and bumps nothing): both ends of the
    stale check read the disk state before migrating, so the comparison
    is like-for-like.
    """
    return {
        "format": PLAN_FORMAT,
        "module_dir": module_dir,
        "workspace": workspace,
        # the RESOLVED statefile the plan was computed against (absolute;
        # None = stateless legacy mode). apply FILE uses this verbatim —
        # re-resolving through the currently-selected workspace could
        # silently retarget the reviewed plan at a different statefile
        "state_path": (os.path.abspath(state_path)
                       if state_path is not None else None),
        "targets": targets or [],
        # forced recreations (-replace): the apply-file re-diff must force
        # the same instances or the saved "replace" actions read as drift
        "replace": replace or [],
        # config-driven imports ADOPTED at plan time: the apply-file
        # re-diff replays exactly these (never re-derives from module
        # imports — a destroy-mode plan adopts nothing, and replay keeps
        # the reviewed actions byte-identical)
        "imports": imports or [],
        "variables": render(plan.variables),
        # the stale-plan guard: what the diff was computed against
        "state_serial": disk_serial,
        "instances": {addr: render(dict(inst.attrs))
                      for addr, inst in plan.instances.items()},
        "outputs": render(plan.outputs),
        "sensitive_outputs": sorted(plan.sensitive_outputs),
        "order": plan.order,
        "check_failures": plan.check_failures,
        "actions": d.actions,
        "changed_keys": d.changed_keys,
    }


def save_plan_file(path: str, payload: dict[str, Any]) -> None:
    with open(path, "w") as fh:
        json.dump(payload, fh, indent=2, sort_keys=True)
        fh.write("\n")


def load_plan_file(path: str) -> dict[str, Any]:
    try:
        with open(path) as fh:
            raw = json.load(fh)
    except (OSError, json.JSONDecodeError) as ex:
        raise PlanFileError(f"cannot read plan file {path!r}: {ex}") from ex
    if not isinstance(raw, dict) or raw.get("format") != PLAN_FORMAT:
        raise PlanFileError(
            f"{path!r} is not a tfsim plan file (expected format "
            f"{PLAN_FORMAT!r}, got {raw.get('format')!r})"
        )
    missing = _REQUIRED_KEYS - set(raw)
    if missing:
        raise PlanFileError(
            f"{path!r} is missing plan-file keys {sorted(missing)} — "
            f"written by an older tfsim? re-run plan -out")
    return raw


def is_plan_file(path: str) -> bool:
    """Sniff for apply's file-vs-module-dir positional.

    Parses the whole file: plan files are small, and a prefix sniff is
    wrong under ``sort_keys`` (the ``format`` key sorts after the
    arbitrarily-large ``actions`` map)."""
    try:
        with open(path) as fh:
            raw = json.load(fh)
    except (OSError, json.JSONDecodeError):
        return False
    return isinstance(raw, dict) and raw.get("format") == PLAN_FORMAT


def plan_from_payload(payload: dict[str, Any]) -> Plan:
    """Reconstruct a :class:`Plan` good enough for ``apply_plan``/``diff``.

    Rendered attrs are what apply writes to state anyway (``render`` is
    idempotent), so the reconstructed plan applies to the same state the
    live plan would have produced.
    """
    return Plan(
        module_path=payload["module_dir"],
        instances={addr: PlannedInstance(addr, ResourceAttrs(attrs))
                   for addr, attrs in payload["instances"].items()},
        outputs=payload["outputs"],
        edges=[],
        order=payload["order"],
        check_failures=payload["check_failures"],
        sensitive_outputs=set(payload["sensitive_outputs"]),
        variables=payload["variables"],
    )


def check_not_stale(payload: dict[str, Any], prior: State | None) -> None:
    """Terraform's stale-plan contract: the state the plan was computed
    against must be the state being applied to."""
    saved = payload["state_serial"]
    current = prior.serial if prior is not None else None
    if saved != current:
        raise PlanFileError(
            f"saved plan is stale: it was computed against state serial "
            f"{saved}, but the current state is serial {current} — "
            f"run plan again and re-review (an interrupted or partially "
            f"failed apply advances the serial too: re-plan against the "
            f"recovered state, never re-apply the old file)"
        )
