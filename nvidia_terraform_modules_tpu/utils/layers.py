# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""Shared layer primitives for the validation workloads.

One definition of RMSNorm and the init scale, imported by both the
burn-in transformer (``models/burnin.py``) and the pipeline model
(``parallel/pipeline.py``) — the pipeline mirrors the burn-in block, and
a norm/init tweak must not silently diverge the two. Lives in ``utils``
(a leaf package) so neither side imports the other.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rmsnorm(x, scale):
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x * jax.lax.rsqrt(var + 1e-6)).astype(x.dtype) * scale


def dense_init(key, shape, dtype, scale: float = 0.02):
    return (jax.random.normal(key, shape, dtype=jnp.float32)
            * scale).astype(dtype)
