# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""tfsim CLI: the terraform-shaped operator surface (SURVEY L7), offline.

Each verb is exercised through main(argv) — same code path as
``python -m nvidia_terraform_modules_tpu.tfsim`` — against the shipped
modules, including a full plan → apply → re-plan statefile round-trip.
"""

import json
import os

import pytest

from nvidia_terraform_modules_tpu.tfsim.__main__ import main

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GKE_TPU = os.path.join(ROOT, "gke-tpu")
VARS = ["-var", "project_id=p", "-var", "cluster_name=c"]


def test_validate_ok(capsys):
    assert main(["validate", GKE_TPU]) == 0
    assert "0 error(s)" in capsys.readouterr().out


def test_validate_catches_errors(tmp_path, capsys):
    (tmp_path / "main.tf").write_text(
        'resource "google_compute_network" "n" {\n  name = var.missing\n}\n')
    assert main(["validate", str(tmp_path)]) == 1
    assert "missing" in capsys.readouterr().out


def test_plan_fresh_shows_creates(capsys):
    assert main(["plan", GKE_TPU] + VARS) == 0
    out = capsys.readouterr().out
    assert '  + google_container_cluster.this' in out
    assert "Plan: 10 to add, 0 to change, 0 to destroy." in out


def test_plan_json(capsys):
    assert main(["plan", GKE_TPU, "-json"] + VARS) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["actions"]["google_container_cluster.this"] == "create"
    assert payload["outputs"]["cluster_name"] == "c"


def test_plan_missing_var_fails(capsys):
    assert main(["plan", GKE_TPU]) == 1
    assert "project_id" in capsys.readouterr().err


def test_apply_plan_roundtrip_via_statefile(tmp_path, capsys):
    state = str(tmp_path / "terraform.tfstate.json")
    assert main(["apply", GKE_TPU, "-state", state] + VARS) == 0
    assert "Apply complete: 10 added" in capsys.readouterr().out
    # unchanged re-plan against the saved state: all no-op
    assert main(["plan", GKE_TPU, "-state", state] + VARS) == 0
    assert "Plan: 0 to add, 0 to change, 0 to destroy." in capsys.readouterr().out
    # a drifted variable surfaces as exactly one update
    assert main(["plan", GKE_TPU, "-state", state, "-var",
                 'cpu_pool={"machine_type": "n2-standard-16"}'] + VARS) == 0
    out = capsys.readouterr().out
    assert "~ google_container_node_pool.cpu  (node_config)" in out
    assert "Plan: 0 to add, 1 to change, 0 to destroy." in out


def test_destroy_reports_order_and_exit(capsys):
    assert main(["destroy", GKE_TPU] + VARS) == 0
    out = capsys.readouterr().out
    assert "Destroy: 14 to destroy, 0 hazard(s), 0 refusal(s)." in out
    assert out.strip().splitlines()[-2].strip() == "- google_compute_network.vpc"


def test_destroy_hazard_exit_code(tmp_path, capsys):
    (tmp_path / "main.tf").write_text("""
resource "google_container_cluster" "c" {
  name = "x"
}

provider "kubernetes" {
  host = google_container_cluster.c.endpoint
}

resource "kubernetes_namespace_v1" "ns" {
  metadata {
    name = "op"
  }
}
""")
    assert main(["destroy", str(tmp_path)]) == 1
    assert "HAZARD" in capsys.readouterr().err


def test_fmt_check_clean_tree():
    assert main(["fmt", "-check", os.path.join(ROOT, "gke"), GKE_TPU]) == 0


def test_fmt_check_flags_dirty(tmp_path, capsys):
    (tmp_path / "main.tf").write_text(
        'resource "google_compute_network" "n" {\nname="x"\n}\n')
    assert main(["fmt", "-check", str(tmp_path)]) == 1
    assert "main.tf" in capsys.readouterr().out


def test_fmt_rewrites_in_place(tmp_path):
    f = tmp_path / "main.tf"
    f.write_text('resource "google_compute_network" "n" {\nname="x"\n}\n')
    assert main(["fmt", str(tmp_path)]) == 0
    assert main(["fmt", "-check", str(tmp_path)]) == 0
    assert 'name = "x"' in f.read_text()


def test_docs_check_and_render(capsys):
    assert main(["docs", "-check", GKE_TPU]) == 0
    capsys.readouterr()
    assert main(["docs", GKE_TPU]) == 0
    assert "tpu_slices" in capsys.readouterr().out


def test_plan_json_stays_parseable_with_moved_blocks(tmp_path, capsys):
    """moved diagnostics go to stderr; -json stdout must json.loads clean."""
    state = str(tmp_path / "s.json")
    (tmp_path / "main.tf").write_text(
        'resource "google_compute_network" "old" {\n  name = "x"\n}\n')
    assert main(["apply", str(tmp_path), "-state", state]) == 0
    (tmp_path / "main.tf").write_text(
        'resource "google_compute_network" "new" {\n  name = "x"\n}\n\n'
        'moved {\n  from = google_compute_network.old\n'
        '  to   = google_compute_network.new\n}\n')
    capsys.readouterr()
    assert main(["plan", str(tmp_path), "-state", state, "-json"]) == 0
    cap = capsys.readouterr()
    payload = json.loads(cap.out)
    assert payload["actions"]["google_compute_network.new"] == "no-op"
    assert "moved:" in cap.err


def test_check_failures_in_json_and_apply(tmp_path, capsys):
    (tmp_path / "main.tf").write_text("""
resource "google_compute_network" "n" {
  name = "x"
}

check "quota" {
  assert {
    condition     = 1 == 2
    error_message = "over quota"
  }
}
""")
    assert main(["plan", str(tmp_path), "-json"]) == 0
    cap = capsys.readouterr()
    assert json.loads(cap.out)["check_failures"] == ["check 'quota': over quota"]
    assert main(["apply", str(tmp_path)]) == 0
    assert "over quota" in capsys.readouterr().err


def test_var_file(tmp_path, capsys):
    vf = tmp_path / "fixture.tfvars"
    vf.write_text('project_id = "p"\ncluster_name = "c"\n')
    assert main(["plan", GKE_TPU, "-var-file", str(vf)]) == 0
    assert "Plan: 10 to add" in capsys.readouterr().out


def test_output_list_masks_sensitive(tmp_path, capsys):
    state = str(tmp_path / "s.json")
    assert main(["apply", GKE_TPU, "-state", state] + VARS) == 0
    capsys.readouterr()
    assert main(["output", "-state", state]) == 0
    out = capsys.readouterr().out
    assert 'cluster_name = "c"' in out
    assert "cluster_ca_certificate = <sensitive>" in out


def test_output_by_name_reveals_and_json(tmp_path, capsys):
    state = str(tmp_path / "s.json")
    assert main(["apply", GKE_TPU, "-state", state] + VARS) == 0
    capsys.readouterr()
    # naming an output reveals it (terraform semantics)
    assert main(["output", "-state", state, "cluster_ca_certificate"]) == 0
    assert "<sensitive>" not in capsys.readouterr().out
    assert main(["output", "-state", state, "-json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["cluster_name"] == {"value": "c", "sensitive": False}


def test_output_errors(tmp_path, capsys):
    state = str(tmp_path / "s.json")
    assert main(["output", "-state", state]) == 1
    assert "apply first" in capsys.readouterr().err
    assert main(["apply", GKE_TPU, "-state", state] + VARS) == 0
    capsys.readouterr()
    assert main(["output", "-state", state, "nope"]) == 1
    assert "not found" in capsys.readouterr().err


def test_state_list_show_rm_mv(tmp_path, capsys):
    state = str(tmp_path / "s.json")
    assert main(["apply", GKE_TPU, "-state", state] + VARS) == 0
    capsys.readouterr()

    assert main(["state", "list", "-state", state]) == 0
    listing = capsys.readouterr().out.splitlines()
    assert "google_container_cluster.this" in listing

    assert main(["state", "show", "google_container_cluster.this",
                 "-state", state]) == 0
    shown = json.loads(capsys.readouterr().out)
    assert shown["name"] == "c"

    assert main(["state", "mv",
                 'google_container_node_pool.tpu_slice["default"]',
                 'google_container_node_pool.tpu_slice["primary"]',
                 "-state", state]) == 0
    assert "Successfully moved 1 object(s)." in capsys.readouterr().out

    assert main(["state", "rm", "google_container_node_pool.tpu_slice",
                 "-state", state]) == 0
    assert "Successfully removed 1 resource" in capsys.readouterr().out
    # the file itself advanced: list no longer shows the pool
    assert main(["state", "list", "-state", state]) == 0
    assert "tpu_slice" not in capsys.readouterr().out


def test_state_rm_then_plan_recreates(tmp_path, capsys):
    """The runbook flow end-to-end through the CLI: rm → plan shows create."""
    state = str(tmp_path / "s.json")
    assert main(["apply", GKE_TPU, "-state", state] + VARS) == 0
    assert main(["state", "rm", "kubernetes_namespace_v1.tpu_runtime",
                 "-state", state]) == 0
    capsys.readouterr()
    assert main(["plan", GKE_TPU, "-state", state] + VARS) == 0
    out = capsys.readouterr().out
    assert "+ kubernetes_namespace_v1.tpu_runtime" in out
    assert "Plan: 1 to add, 0 to change, 0 to destroy." in out


def test_state_errors(tmp_path, capsys):
    state = str(tmp_path / "s.json")
    assert main(["state", "list", "-state", state]) == 1
    capsys.readouterr()
    assert main(["apply", GKE_TPU, "-state", state] + VARS) == 0
    capsys.readouterr()
    assert main(["state", "rm", "nope.nope", "-state", state]) == 1
    assert "no resource in state" in capsys.readouterr().err
    assert main(["state", "show", "nope.nope", "-state", state]) == 1
    assert "not in state" in capsys.readouterr().err


def test_graph_dot(capsys):
    assert main(["graph", GKE_TPU] + VARS) == 0
    dot = capsys.readouterr().out
    assert dot.startswith("digraph {")
    assert dot.rstrip().endswith("}")
    # the runtime helm release depends on the namespace it installs into
    assert '"helm_release.tpu_runtime" -> ' \
        '"kubernetes_namespace_v1.tpu_runtime";' in dot
    # every planned node appears, even leaves
    assert '"google_compute_network.vpc";' in dot


def test_graph_error_exit(tmp_path, capsys):
    assert main(["graph", GKE_TPU]) == 1
    assert "project_id" in capsys.readouterr().err


def test_state_usage_errors(tmp_path, capsys):
    state = str(tmp_path / "s.json")
    assert main(["state", "show", "-state", state]) == 2
    assert main(["state", "mv", "a.b", "-state", state]) == 2
    assert main(["state", "rm", "-state", state]) == 2
    assert "address argument" in capsys.readouterr().err


def test_output_raw(tmp_path, capsys):
    """-raw prints the bare string for piping (the platform.yaml handoff)
    and refuses structured values, terraform-style."""
    state = str(tmp_path / "s.json")
    assert main(["apply", GKE_TPU, "-state", state] + VARS) == 0
    capsys.readouterr()
    assert main(["output", "-state", state, "-raw", "cluster_name"]) == 0
    assert capsys.readouterr().out == "c"   # bare, no newline (terraform -raw)
    assert main(["output", "-state", state, "-raw", "tpu_slices"]) == 1
    assert "-raw requires" in capsys.readouterr().err
    assert main(["output", "-state", state, "-raw"]) == 1
    assert "requires an output NAME" in capsys.readouterr().err


def test_output_raw_refuses_computed(tmp_path, capsys):
    """Piping '<computed>' into platform.yaml would be silent garbage."""
    state = str(tmp_path / "s.json")
    assert main(["apply", GKE_TPU, "-state", state] + VARS) == 0
    capsys.readouterr()
    assert main(["output", "-state", state, "-raw",
                 "latest_version_per_channel"]) == 1
    assert "known after a real apply" in capsys.readouterr().err


def test_cli_survives_broken_pipe(tmp_path):
    """`tfsim output | head` must exit 141 (SIGPIPE convention), never
    traceback — the handoff pipeline pipes these commands routinely.
    PYTHONUNBUFFERED forces write-through stdout so the EPIPE
    deterministically fires (block-buffered small output would fit the
    pipe buffer and never trip); PIPESTATUS reads tfsim's own exit code
    rather than head's."""
    import subprocess
    import sys as _sys

    # an output larger than the 64 KiB pipe buffer makes the EPIPE
    # deterministic: the writer MUST block after head exits, whatever the
    # process scheduling — a small output could fit the buffer whole and
    # race to rc 0 under load
    (tmp_path / "main.tf").write_text(
        'output "big" {\n'
        '  value = join("", [for i in range(30000) : "xxxx"])\n'
        '}\n')
    state = str(tmp_path / "s.json")
    assert main(["apply", str(tmp_path), "-state", state]) == 0
    p = subprocess.run(
        ["bash", "-c",
         f"{_sys.executable} -m nvidia_terraform_modules_tpu.tfsim output "
         f"-state {state} big | head -c 5; exit ${{PIPESTATUS[0]}}"],
        capture_output=True, text=True,
        env={**os.environ, "PYTHONUNBUFFERED": "1"},
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert "Traceback" not in p.stderr, p.stderr
    assert p.returncode == 141, (p.returncode, p.stderr)


def test_plan_and_apply_target(tmp_path, capsys):
    """-target scopes plan/apply to the target's dependency closure; a
    follow-up full apply picks up the rest."""
    state = str(tmp_path / "s.json")
    assert main(["plan", GKE_TPU, "-target", "google_compute_network.vpc"]
                + VARS) == 0
    out = capsys.readouterr().out
    assert "Plan: 1 to add" in out
    assert main(["apply", GKE_TPU, "-state", state, "-target",
                 "google_compute_network.vpc"] + VARS) == 0
    assert "Apply complete: 1 added" in capsys.readouterr().out
    assert main(["plan", GKE_TPU, "-state", state] + VARS) == 0
    assert "Plan: 9 to add, 0 to change, 0 to destroy." in \
        capsys.readouterr().out
    assert main(["plan", GKE_TPU, "-target", "nope.nope"] + VARS) == 1
    assert "matches no resource" in capsys.readouterr().err


def test_import_cli(tmp_path, capsys):
    state = str(tmp_path / "s.json")
    assert main(["import", GKE_TPU, "google_compute_network.vpc[0]",
                 "projects/p/global/networks/c-net", "-state", state]
                + VARS) == 0
    assert "Import prepared" in capsys.readouterr().out
    assert main(["plan", GKE_TPU, "-state", state] + VARS) == 0
    out = capsys.readouterr().out
    assert "Plan: 9 to add, 0 to change, 0 to destroy." in out
    # re-import of a managed address refuses
    assert main(["import", GKE_TPU, "google_compute_network.vpc[0]", "x",
                 "-state", state] + VARS) == 1
    assert "already managed" in capsys.readouterr().err


def test_import_respects_moved_blocks(tmp_path, capsys):
    """import must migrate moved{} first or the statefile wedges at the
    next plan (destination already exists)."""
    state = str(tmp_path / "s.json")
    (tmp_path / "main.tf").write_text(
        'resource "google_compute_network" "old" {\n  name = "x"\n}\n')
    assert main(["apply", str(tmp_path), "-state", state]) == 0
    (tmp_path / "main.tf").write_text(
        'resource "google_compute_network" "new" {\n  name = "x"\n}\n\n'
        'moved {\n  from = google_compute_network.old\n'
        '  to   = google_compute_network.new\n}\n')
    capsys.readouterr()
    # importing the rename destination: migration happens first, so the
    # address is already managed — refused instead of wedging the file
    assert main(["import", str(tmp_path), "google_compute_network.new",
                 "some-id", "-state", state]) == 1
    assert "already managed" in capsys.readouterr().err
    assert main(["plan", str(tmp_path), "-state", state]) == 0


def test_auto_tfvars_loaded_in_terraform_order(tmp_path, capsys):
    """terraform.tfvars then *.auto.tfvars auto-load from the module dir,
    with -var-file and -var overriding in terraform's precedence order."""
    (tmp_path / "main.tf").write_text(
        'variable "a" {\n  type = string\n}\n'
        'variable "b" {\n  type    = string\n  default = "unset"\n}\n'
        'output "ab" {\n  value = "${var.a}/${var.b}"\n}\n')
    (tmp_path / "terraform.tfvars").write_text('a = "base"\nb = "base"\n')
    (tmp_path / "zz.auto.tfvars").write_text('b = "auto"\n')
    assert main(["plan", str(tmp_path), "-json"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["outputs"]["ab"] == "base/auto"
    # explicit -var still wins over every file tier
    assert main(["plan", str(tmp_path), "-json", "-var", "b=cli"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["outputs"]["ab"] == "base/cli"
    # -var-file beats auto files, loses to -var
    (tmp_path / "extra.tfvars").write_text('b = "file"\n')
    assert main(["plan", str(tmp_path), "-json",
                 "-var-file", str(tmp_path / "extra.tfvars")]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["outputs"]["ab"] == "base/file"


def test_broken_auto_tfvars_is_clean_error(tmp_path, capsys):
    """A malformed or mis-referencing terraform.tfvars now reaches every
    verb via auto-loading — it must print the documented Error line,
    never a traceback."""
    (tmp_path / "main.tf").write_text('locals {\n  a = 1\n}\n')
    (tmp_path / "terraform.tfvars").write_text("a = = broken\n")
    assert main(["plan", str(tmp_path)]) == 1
    assert "Error:" in capsys.readouterr().err
    (tmp_path / "terraform.tfvars").write_text("a = var.missing\n")
    assert main(["destroy", str(tmp_path)]) == 1
    assert "Error:" in capsys.readouterr().err


def test_providers_lists_requirement_tree(capsys):
    assert main(["providers", os.path.join(ROOT, "gke-tpu", "examples",
                                           "multislice")]) == 0
    out = capsys.readouterr().out
    assert "provider[hashicorp/google] ~> 6.8" in out
    assert "module.tpu_fleet (../..):" in out
    assert "provider[hashicorp/helm]" in out


def test_providers_missing_dir_errors(capsys):
    assert main(["providers", "/nonexistent-dir-xyz"]) == 1
    assert "Error:" in capsys.readouterr().err


def test_providers_broken_child_is_loud_error(tmp_path, capsys):
    (tmp_path / "main.tf").write_text(
        'module "child" {\n  source = "./missing"\n}\n')
    assert main(["providers", str(tmp_path)]) == 1
    assert "Error:" in capsys.readouterr().err


def test_providers_prints_sibling_calls_sharing_a_source(tmp_path, capsys):
    (tmp_path / "child").mkdir()
    (tmp_path / "main.tf").write_text(
        'module "a" {\n  source = "./child"\n}\n'
        'module "b" {\n  source = "./child"\n}\n')
    (tmp_path / "child" / "main.tf").write_text(
        'terraform {\n  required_providers {\n    google = {\n'
        '      source  = "hashicorp/google"\n      version = "~> 6.8"\n'
        '    }\n  }\n}\n')
    assert main(["providers", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "module.a (child):" in out
    assert "module.b (child):" in out


def test_init_check_on_shipped_example(capsys):
    assert main(["init", os.path.join(ROOT, "gke-tpu", "examples",
                                      "multislice"), "-check"]) == 0
    out = capsys.readouterr().out
    assert "- tpu_fleet in" in out
    assert "Lock file is up to date." in out


def test_init_writes_lockfile_and_checks_version(tmp_path, capsys):
    (tmp_path / "main.tf").write_text(
        'terraform {\n  required_version = ">= 1.5.0"\n'
        '  required_providers {\n    google = {\n'
        '      source  = "hashicorp/google"\n      version = "~> 6.8"\n'
        '    }\n  }\n}\n')
    assert main(["init", str(tmp_path)]) == 0
    assert (tmp_path / ".terraform.lock.hcl").exists()
    capsys.readouterr()
    assert main(["init", str(tmp_path), "-check"]) == 0
    # a floor above the simulated CLI version refuses to init
    (tmp_path / "main.tf").write_text(
        'terraform {\n  required_version = ">= 99.0"\n}\n')
    assert main(["init", str(tmp_path)]) == 1
    assert "excludes the simulated terraform" in capsys.readouterr().err


def test_init_prints_sibling_calls_and_detects_cycles(tmp_path, capsys):
    (tmp_path / "child").mkdir()
    (tmp_path / "main.tf").write_text(
        'module "a" {\n  source = "./child"\n}\n'
        'module "b" {\n  source = "./child"\n}\n')
    (tmp_path / "child" / "main.tf").write_text('locals {\n  x = 1\n}\n')
    assert main(["init", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "- a in child" in out and "- b in child" in out
    # a real source cycle errors exactly, at any depth
    (tmp_path / "child" / "main.tf").write_text(
        'module "up" {\n  source = "../"\n}\n')
    assert main(["init", str(tmp_path)]) == 1
    assert "cycle" in capsys.readouterr().err
    capsys.readouterr()
    assert main(["providers", str(tmp_path)]) == 1
    assert "cycle" in capsys.readouterr().err


def test_state_pull_push_with_serial_guard(tmp_path, capsys, monkeypatch):
    import io

    state = str(tmp_path / "s.json")
    assert main(["apply", GKE_TPU, "-state", state] + VARS) == 0
    capsys.readouterr()
    # pull: the raw statefile JSON on stdout
    assert main(["state", "pull", "-state", state]) == 0
    pulled = capsys.readouterr().out
    assert json.loads(pulled)["serial"] >= 1
    # push the same state back: same serial, accepted
    monkeypatch.setattr("sys.stdin", io.StringIO(pulled))
    assert main(["state", "push", "-state", state]) == 0
    # a stale serial is refused without -force (lineage guard)
    stale = json.loads(pulled)
    stale["serial"] = 0
    monkeypatch.setattr("sys.stdin", io.StringIO(json.dumps(stale)))
    assert main(["state", "push", "-state", state]) == 1
    assert "does not advance the current serial" in capsys.readouterr().err
    # same-serial push with DIFFERENT content: the lost-update race, refused
    racy = json.loads(pulled)
    racy["resources"] = {}
    monkeypatch.setattr("sys.stdin", io.StringIO(json.dumps(racy)))
    assert main(["state", "push", "-state", state]) == 1
    capsys.readouterr()
    monkeypatch.setattr("sys.stdin", io.StringIO(json.dumps(stale)))
    assert main(["state", "push", "-state", state, "-force"]) == 0
    capsys.readouterr()
    assert main(["state", "pull", "-state", state]) == 0
    assert json.loads(capsys.readouterr().out)["serial"] == 0
    # garbage on stdin is a clean error
    monkeypatch.setattr("sys.stdin", io.StringIO("not json"))
    assert main(["state", "push", "-state", state]) == 1
    assert "invalid state" in capsys.readouterr().err


def test_state_push_rejects_malformed_payloads(tmp_path, capsys, monkeypatch):
    import io

    state = str(tmp_path / "s.json")
    assert main(["apply", GKE_TPU, "-state", state] + VARS) == 0
    capsys.readouterr()
    for payload in ("123", '["x"]',
                    '{"serial": "0", "resources": {}, "outputs": {}}',
                    '{"serial": null, "resources": {}, "outputs": {}}'):
        monkeypatch.setattr("sys.stdin", io.StringIO(payload))
        assert main(["state", "push", "-state", state]) == 1, payload
        assert "invalid state" in capsys.readouterr().err


def test_taint_untaint_replace_cycle(tmp_path, capsys):
    state = str(tmp_path / "s.json")
    (tmp_path / "main.tf").write_text(
        'resource "google_compute_network" "n" {\n  name = "x"\n}\n')
    assert main(["apply", str(tmp_path), "-state", state]) == 0
    capsys.readouterr()
    # untainted: no-op plan
    assert main(["plan", str(tmp_path), "-state", state]) == 0
    assert "0 to add, 0 to change, 0 to destroy" in capsys.readouterr().out
    # taint → plan shows -/+ replace, counted add+destroy
    assert main(["taint", "google_compute_network.n", "-state", state]) == 0
    capsys.readouterr()
    assert main(["plan", str(tmp_path), "-state", state]) == 0
    out = capsys.readouterr().out
    assert "-/+ google_compute_network.n" in out
    assert "1 to add, 0 to change, 1 to destroy" in out
    # apply recreates and clears the taint
    assert main(["apply", str(tmp_path), "-state", state]) == 0
    capsys.readouterr()
    assert main(["plan", str(tmp_path), "-state", state]) == 0
    assert "0 to add, 0 to change, 0 to destroy" in capsys.readouterr().out
    # untaint flow + error paths
    assert main(["taint", "google_compute_network.n", "-state", state]) == 0
    assert main(["untaint", "google_compute_network.n",
                 "-state", state]) == 0
    capsys.readouterr()
    assert main(["plan", str(tmp_path), "-state", state]) == 0
    assert "0 to add" in capsys.readouterr().out
    assert main(["untaint", "google_compute_network.n",
                 "-state", state]) == 1
    assert "not tainted" in capsys.readouterr().err
    assert main(["taint", "google_compute_network.zzz",
                 "-state", state]) == 1
    assert "not in state" in capsys.readouterr().err


def test_replace_flag_forces_recreation(tmp_path, capsys):
    """terraform's -replace=ADDR: the stateless successor to taint —
    plan shows -/+, apply recreates, no taint mark survives."""
    state = str(tmp_path / "s.json")
    (tmp_path / "main.tf").write_text(
        'resource "google_compute_network" "n" {\n  name = "x"\n}\n')
    assert main(["apply", str(tmp_path), "-state", state]) == 0
    capsys.readouterr()
    assert main(["plan", str(tmp_path), "-state", state,
                 "-replace", "google_compute_network.n"]) == 0
    out = capsys.readouterr().out
    assert "-/+ google_compute_network.n" in out
    assert "1 to add, 0 to change, 1 to destroy" in out
    # apply -replace recreates (serial bumps) and leaves no sticky mark
    serial0 = json.load(open(state))["serial"]
    assert main(["apply", str(tmp_path), "-state", state,
                 "-replace", "google_compute_network.n"]) == 0
    assert json.load(open(state))["serial"] == serial0 + 1
    capsys.readouterr()
    assert main(["plan", str(tmp_path), "-state", state]) == 0
    assert "0 to add, 0 to change, 0 to destroy" in capsys.readouterr().out
    # unknown address: terraform refuses
    assert main(["plan", str(tmp_path), "-state", state,
                 "-replace", "google_compute_network.zzz"]) == 1
    assert "no resource instance" in capsys.readouterr().err
    # -destroy -replace is a usage error like -destroy -target
    assert main(["plan", str(tmp_path), "-state", state, "-destroy",
                 "-replace", "google_compute_network.n"]) == 2
    capsys.readouterr()


def test_replace_flag_rides_saved_plans(tmp_path, capsys):
    """-replace recorded in plan -out must survive the apply-FILE
    re-diff (otherwise the saved replace actions read as drift)."""
    state = str(tmp_path / "s.json")
    pfile = str(tmp_path / "p.tfplan")
    (tmp_path / "main.tf").write_text(
        'resource "google_compute_network" "n" {\n  name = "x"\n}\n')
    assert main(["apply", str(tmp_path), "-state", state]) == 0
    capsys.readouterr()
    assert main(["plan", str(tmp_path), "-state", state, "-out", pfile,
                 "-replace", "google_compute_network.n"]) == 0
    capsys.readouterr()
    assert main(["apply", pfile]) == 0
    out = capsys.readouterr().out
    assert "1 added, 0 changed, 1 destroyed" in out


def test_replace_flag_interactions_rejected(tmp_path, capsys):
    """-replace must be rejected (never silently dropped) wherever it
    cannot be honoured: saved-plan apply, -refresh-only, and a -target
    scope that excludes the replaced address (review findings)."""
    state = str(tmp_path / "s.json")
    pfile = str(tmp_path / "p.tfplan")
    (tmp_path / "main.tf").write_text(
        'resource "google_compute_network" "a" {\n  name = "x"\n}\n'
        'resource "google_compute_subnetwork" "b" {\n  name = "y"\n}\n')
    assert main(["apply", str(tmp_path), "-state", state]) == 0
    assert main(["plan", str(tmp_path), "-state", state,
                 "-out", pfile]) == 0
    capsys.readouterr()
    assert main(["apply", pfile, "-replace",
                 "google_compute_network.a"]) == 2
    assert "-replace" in capsys.readouterr().err
    assert main(["plan", str(tmp_path), "-state", state, "-refresh-only",
                 "-replace", "google_compute_network.a"]) == 2
    assert "-refresh-only" in capsys.readouterr().err
    assert main(["apply", str(tmp_path), "-state", state, "-refresh-only",
                 "-replace", "google_compute_network.a"]) == 2
    assert "-refresh-only" in capsys.readouterr().err
    assert main(["plan", str(tmp_path), "-state", state,
                 "-target", "google_compute_subnetwork.b",
                 "-replace", "google_compute_network.a"]) == 1
    assert "not covered by the given -target" in capsys.readouterr().err


def test_config_driven_import_block(tmp_path, capsys):
    """terraform 1.5+ `import {}` blocks: adoption is part of the plan —
    plan reports the import and no create, apply persists it with the
    operator-supplied id, and the block is idempotent on re-apply."""
    state = str(tmp_path / "s.json")
    (tmp_path / "main.tf").write_text(
        'import {\n  to = google_compute_network.n\n  id = "proj/net-1"\n}\n'
        'resource "google_compute_network" "n" {\n  name = "x"\n}\n')
    assert main(["plan", str(tmp_path), "-state", state]) == 0
    out = capsys.readouterr()
    assert "import: google_compute_network.n (id=proj/net-1)" in out.err
    assert "0 to add, 0 to change, 0 to destroy" in out.out
    assert main(["apply", str(tmp_path), "-state", state]) == 0
    capsys.readouterr()
    st = json.load(open(state))
    assert st["resources"]["google_compute_network.n"]["id"] == "proj/net-1"
    # idempotent: the block stays in config, the next apply is a no-op
    assert main(["apply", str(tmp_path), "-state", state]) == 0
    assert "0 added, 0 changed, 0 destroyed" in capsys.readouterr().out
    assert json.load(open(state))["serial"] == st["serial"]


def test_config_driven_import_rides_saved_plans(tmp_path, capsys):
    state = str(tmp_path / "s.json")
    pfile = str(tmp_path / "p.tfplan")
    (tmp_path / "main.tf").write_text(
        'import {\n  to = google_compute_network.n\n  id = "net-9"\n}\n'
        'resource "google_compute_network" "n" {\n  name = "x"\n}\n')
    assert main(["plan", str(tmp_path), "-state", state,
                 "-out", pfile]) == 0
    capsys.readouterr()
    assert main(["apply", pfile, "-state", state]) == 0
    capsys.readouterr()
    assert json.load(open(state))["resources"][
        "google_compute_network.n"]["id"] == "net-9"


def test_import_blocks_ignored_in_refresh_and_destroy(tmp_path, capsys):
    """terraform ignores import{} in refresh-only/destroy modes: refresh
    must still say 'nothing to refresh' on empty state, destroy-mode
    plans must not conjure never-managed resources, and
    -detailed-exitcode must report an import-only plan as changes
    (review findings)."""
    state = str(tmp_path / "s.json")
    (tmp_path / "main.tf").write_text(
        'import {\n  to = google_compute_network.n\n  id = "net-1"\n}\n'
        'resource "google_compute_network" "n" {\n  name = "x"\n}\n')
    # refresh on empty state: the import must not manufacture a prior
    assert main(["refresh", str(tmp_path), "-state", state]) == 1
    assert "nothing to refresh" in capsys.readouterr().err
    assert not os.path.exists(state)
    # destroy-mode plan on empty state: likewise nothing to destroy
    assert main(["plan", str(tmp_path), "-state", state, "-destroy"]) == 1
    assert "nothing to destroy" in capsys.readouterr().err
    # an import-only plan IS a pending change for -detailed-exitcode
    assert main(["plan", str(tmp_path), "-state", state,
                 "-detailed-exitcode"]) == 2
    capsys.readouterr()
    assert main(["apply", str(tmp_path), "-state", state]) == 0
    capsys.readouterr()
    assert main(["plan", str(tmp_path), "-state", state,
                 "-detailed-exitcode"]) == 0
    capsys.readouterr()
    # a destroy-mode SAVED plan must replay cleanly (no adoption at
    # either end), and -refresh-only drift honours -detailed-exitcode
    pfile = str(tmp_path / "d.tfplan")
    assert main(["plan", str(tmp_path), "-state", state, "-destroy",
                 "-out", pfile]) == 0
    capsys.readouterr()
    assert main(["apply", pfile, "-state", state]) == 0
    capsys.readouterr()


def test_generate_config_out_for_unconfigured_import(tmp_path, capsys):
    """plan -generate-config-out (terraform 1.5): an import target with
    no configuration gets a schema-derived skeleton instead of an error;
    moving the file into the module makes the next plan stage the
    import for real."""
    state = str(tmp_path / "s.json")
    gen = str(tmp_path / "generated.tf")
    (tmp_path / "main.tf").write_text(
        'import {\n  to = google_compute_network.n\n  id = "net-1"\n}\n')
    assert main(["plan", str(tmp_path), "-state", state,
                 "-generate-config-out", gen]) == 0
    err = capsys.readouterr().err
    assert "skeleton block(s) written" in err
    text = open(gen).read()
    assert 'resource "google_compute_network" "n"' in text
    assert "__generated__" in text and "name = null" in text
    # the operator fills the TODOs and drops the file into the module:
    # the very next plan stages (adopts) the import
    (tmp_path / "generated.tf").write_text(
        text.replace("name = null # TODO: value of the imported "
                     "resource's name", 'name = "imported-net"'))
    assert main(["apply", str(tmp_path), "-state", state]) == 0
    capsys.readouterr()
    st = json.load(open(state))
    assert st["resources"]["google_compute_network.n"]["id"] == "net-1"


def test_plan_json_reports_imports(tmp_path, capsys):
    state = str(tmp_path / "s.json")
    (tmp_path / "main.tf").write_text(
        'import {\n  to = google_compute_network.n\n  id = "net-1"\n}\n'
        'resource "google_compute_network" "n" {\n  name = "x"\n}\n')
    assert main(["plan", str(tmp_path), "-state", state, "-json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["imports"] == [
        {"to": "google_compute_network.n", "id": "net-1"}]


def test_generate_config_out_guards(tmp_path, capsys):
    """Review findings: an existing out-file refuses (never clobber
    hand-filled TODOs), pending generation is a change for
    -detailed-exitcode, and data/indexed targets error in both modes."""
    state = str(tmp_path / "s.json")
    # the out-file lives OUTSIDE the module dir (the operator hasn't
    # moved it in yet), so re-plans keep seeing the target unconfigured
    mod = tmp_path / "mod"
    mod.mkdir()
    gen = str(tmp_path / "generated.tf")
    (mod / "main.tf").write_text(
        'import {\n  to = google_compute_network.n\n  id = "net-1"\n}\n')
    assert main(["plan", str(mod), "-state", state,
                 "-generate-config-out", gen, "-detailed-exitcode"]) == 2
    capsys.readouterr()
    assert main(["plan", str(mod), "-state", state,
                 "-generate-config-out", gen]) == 1
    assert "already exists" in capsys.readouterr().err
    (mod / "main.tf").write_text(
        'import {\n  to = data.google_client_config.c\n  id = "x"\n}\n')
    assert main(["plan", str(mod), "-state", state,
                 "-generate-config-out", str(tmp_path / "g2.tf")]) == 1
    assert "data source" in capsys.readouterr().err
    (mod / "main.tf").write_text(
        'import {\n  to = google_compute_network.n[0]\n  id = "x"\n}\n')
    assert main(["plan", str(mod), "-state", state,
                 "-generate-config-out", str(tmp_path / "g3.tf")]) == 1
    assert "count/for_each" in capsys.readouterr().err


def test_duplicate_import_blocks_rejected(tmp_path, capsys):
    state = str(tmp_path / "s.json")
    (tmp_path / "main.tf").write_text(
        'import {\n  to = google_compute_network.n\n  id = "net-1"\n}\n'
        'import {\n  to = google_compute_network.n\n  id = "net-OTHER"\n}\n'
        'resource "google_compute_network" "n" {\n  name = "x"\n}\n')
    assert main(["plan", str(tmp_path), "-state", state]) == 1
    assert "duplicate import block" in capsys.readouterr().err


def test_config_driven_import_errors(tmp_path, capsys):
    state = str(tmp_path / "s.json")
    # no matching configuration block
    (tmp_path / "main.tf").write_text(
        'import {\n  to = google_compute_network.n\n  id = "net-9"\n}\n')
    assert main(["plan", str(tmp_path), "-state", state]) == 1
    assert "no configuration block" in capsys.readouterr().err
    # non-literal id
    (tmp_path / "main.tf").write_text(
        'variable "i" {\n  type = string\n  default = "z"\n}\n'
        'import {\n  to = google_compute_network.n\n  id = var.i\n}\n'
        'resource "google_compute_network" "n" {\n  name = "x"\n}\n')
    assert main(["plan", str(tmp_path), "-state", state]) == 1
    assert "literal string" in capsys.readouterr().err


def test_apply_destroy_tears_down_state(tmp_path, capsys):
    """terraform's `apply -destroy` (the real teardown path, distinct
    from the config-level `destroy` hazard dry-run): deletes everything
    from state, honours prevent_destroy, rejects -target/-replace and
    saved-plan combination."""
    state = str(tmp_path / "s.json")
    (tmp_path / "main.tf").write_text(
        'resource "google_compute_network" "n" {\n  name = "x"\n}\n'
        'resource "google_compute_subnetwork" "s" {\n  name = "y"\n}\n')
    assert main(["apply", str(tmp_path), "-state", state]) == 0
    capsys.readouterr()
    assert main(["apply", str(tmp_path), "-state", state, "-destroy",
                 "-target", "google_compute_network.n"]) == 2
    capsys.readouterr()
    assert main(["apply", str(tmp_path), "-state", state, "-destroy",
                 "-refresh-only"]) == 2
    assert "-refresh-only" in capsys.readouterr().err
    assert main(["apply", str(tmp_path), "-state", state, "-destroy"]) == 0
    out = capsys.readouterr().out
    assert "2 destroyed" in out
    assert json.load(open(state))["resources"] == {}
    # empty state: nothing to destroy is an error, like plan -destroy
    assert main(["apply", str(tmp_path), "-state", state, "-destroy"]) == 1
    assert "nothing to destroy" in capsys.readouterr().err
    # prevent_destroy refuses the teardown outright
    (tmp_path / "main.tf").write_text(
        'resource "google_compute_network" "n" {\n  name = "x"\n'
        '  lifecycle {\n    prevent_destroy = true\n  }\n}\n')
    assert main(["apply", str(tmp_path), "-state", state]) == 0
    capsys.readouterr()
    assert main(["apply", str(tmp_path), "-state", state, "-destroy"]) == 1
    assert "prevent_destroy" in capsys.readouterr().err
    assert "google_compute_network.n" in json.load(
        open(state))["resources"]
    # saved plan + -destroy is a usage error
    pfile = str(tmp_path / "p.tfplan")
    (tmp_path / "main.tf").write_text(
        'resource "google_compute_network" "n" {\n  name = "x"\n}\n')
    assert main(["plan", str(tmp_path), "-state", state,
                 "-out", pfile]) == 0
    capsys.readouterr()
    assert main(["apply", pfile, "-destroy"]) == 2
    capsys.readouterr()


def test_version_verb(capsys):
    assert main(["version"]) == 0
    out = capsys.readouterr().out
    assert "tfsim v" in out and "Terraform v" in out
    assert "registry.terraform.io/hashicorp/google v" in out


# ---------------------------------------------------------------- saved plans


def test_plan_out_show_apply_roundtrip(tmp_path, capsys):
    """The review-then-apply contract: plan -out → show → apply FILE
    performs exactly the reviewed actions (round-2 VERDICT item 5)."""
    state = str(tmp_path / "s.json")
    pfile = str(tmp_path / "p.tfplan")
    assert main(["plan", GKE_TPU, "-state", state, "-out", pfile] + VARS) == 0
    err = capsys.readouterr().err
    assert f"Saved the plan to: {pfile}" in err

    assert main(["show", pfile]) == 0
    out = capsys.readouterr().out
    assert "+ google_container_cluster.this" in out
    assert "against state serial None" in out

    assert main(["show", pfile, "-json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["format"] == "tfsim-plan/1"
    assert payload["actions"]["google_container_cluster.this"] == "create"
    assert payload["variables"]["project_id"] == "p"

    assert main(["apply", pfile, "-state", state]) == 0
    assert "Apply complete: 10 added" in capsys.readouterr().out
    assert json.load(open(state))["serial"] == 1


def test_apply_saved_plan_refuses_stale_state(tmp_path, capsys):
    """Terraform's stale-plan contract: a concurrent apply between review
    and apply invalidates the file instead of silently re-planning."""
    state = str(tmp_path / "s.json")
    pfile = str(tmp_path / "p.tfplan")
    assert main(["plan", GKE_TPU, "-state", state, "-out", pfile] + VARS) == 0
    assert main(["apply", GKE_TPU, "-state", state] + VARS) == 0  # concurrent
    capsys.readouterr()
    assert main(["apply", pfile, "-state", state]) == 1
    assert "saved plan is stale" in capsys.readouterr().err


def test_apply_saved_plan_refuses_var_overrides(tmp_path, capsys):
    state = str(tmp_path / "s.json")
    pfile = str(tmp_path / "p.tfplan")
    assert main(["plan", GKE_TPU, "-state", state, "-out", pfile] + VARS) == 0
    capsys.readouterr()
    assert main(["apply", pfile, "-state", state, "-var", "x=1"]) == 2
    assert "cannot be combined" in capsys.readouterr().err


def test_apply_rejects_non_plan_file(tmp_path, capsys):
    bogus = tmp_path / "notaplan.json"
    bogus.write_text("{}")
    assert main(["apply", str(bogus)]) == 2
    assert "not a tfsim plan" in capsys.readouterr().err


def test_show_statefile(tmp_path, capsys):
    state = str(tmp_path / "s.json")
    assert main(["apply", GKE_TPU, "-state", state] + VARS) == 0
    capsys.readouterr()
    assert main(["show", state]) == 0
    out = capsys.readouterr().out
    assert "State serial 1" in out
    assert "google_container_cluster.this" in out


# ------------------------------------------------------------------- refresh


def test_refresh_updates_drifted_outputs(tmp_path, capsys):
    """An outputs-block edit after apply is provider-readable drift:
    refresh accepts it into state without touching resources."""
    mod = tmp_path / "mod"
    mod.mkdir()
    (mod / "main.tf").write_text(
        'variable "name" {\n'
        '  description = "n"\n'
        '  type        = string\n'
        '}\n\n'
        'resource "google_compute_network" "vpc" {\n'
        '  name = var.name\n'
        '}\n\n'
        'output "vpc_name" {\n'
        '  description = "o"\n'
        '  value       = google_compute_network.vpc.name\n'
        '}\n')
    state = str(tmp_path / "s.json")
    assert main(["apply", str(mod), "-state", state, "-var", "name=demo"]) == 0
    # outputs block changes meaning; resources do not
    txt = (mod / "main.tf").read_text()
    (mod / "main.tf").write_text(
        txt.replace("google_compute_network.vpc.name",
                    "upper(google_compute_network.vpc.name)"))
    capsys.readouterr()
    assert main(["plan", str(mod), "-state", state, "-var", "name=demo",
                 "-refresh-only"]) == 0
    out = capsys.readouterr().out
    assert "~ output.vpc_name" in out
    assert "No resource changes" in out
    before = json.load(open(state))
    assert main(["refresh", str(mod), "-state", state,
                 "-var", "name=demo"]) == 0
    after = json.load(open(state))
    assert after["outputs"]["vpc_name"]["value"] == "DEMO"
    assert after["serial"] == before["serial"] + 1
    assert after["resources"] == before["resources"]


def test_refresh_reports_orphans_without_removing(tmp_path, capsys):
    state = str(tmp_path / "s.json")
    assert main(["apply", GKE_TPU, "-state", state] + VARS) == 0
    raw = json.load(open(state))
    raw["resources"]["google_compute_network.gone"] = {"name": "old"}
    json.dump(raw, open(state, "w"))
    capsys.readouterr()
    assert main(["refresh", GKE_TPU, "-state", state] + VARS) == 0
    out = capsys.readouterr().out
    assert "google_compute_network.gone" in out and "orphaned" in out
    # reported, never removed: refresh accepts reality, apply destroys
    assert "google_compute_network.gone" in json.load(open(state))["resources"]


def test_refresh_without_state_errors(capsys):
    assert main(["refresh", GKE_TPU, "-state", "/nonexistent/s.json"]
                + VARS) == 1
    assert "nothing to refresh" in capsys.readouterr().err


def test_saved_plan_applies_across_moved_blocks(tmp_path, capsys):
    """moved{} migration is in-memory: the plan file records the ON-DISK
    serial, so a saved plan over a refactored module applies instead of
    always reading as stale (review finding, round 3)."""
    import textwrap

    mod = tmp_path / "mod"
    mod.mkdir()

    def write(body):
        (mod / "main.tf").write_text(textwrap.dedent(body))

    state = str(tmp_path / "s.json")
    write("""
        resource "google_compute_network" "old" {
          name = "net"
        }
    """)
    assert main(["apply", str(mod), "-state", state]) == 0
    write("""
        resource "google_compute_network" "new" {
          name = "net"
        }

        moved {
          from = google_compute_network.old
          to   = google_compute_network.new
        }
    """)
    pfile = str(tmp_path / "p.tfplan")
    assert main(["plan", str(mod), "-state", state, "-out", pfile]) == 0
    capsys.readouterr()
    assert main(["apply", pfile, "-state", state]) == 0
    out = capsys.readouterr().out
    assert "Apply complete: 0 added, 0 changed, 0 destroyed." in out
    assert "google_compute_network.new" in json.load(open(state))["resources"]


def test_show_rejects_unrecognised_json(tmp_path, capsys):
    bogus = tmp_path / "other.json"
    bogus.write_text("{}")
    assert main(["show", str(bogus)]) == 1
    assert "neither" in capsys.readouterr().err


def test_refresh_only_json_is_machine_readable(tmp_path, capsys):
    state = str(tmp_path / "s.json")
    assert main(["apply", GKE_TPU, "-state", state] + VARS) == 0
    capsys.readouterr()
    assert main(["plan", GKE_TPU, "-state", state, "-refresh-only",
                 "-json"] + VARS) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload == {"refresh_only": True, "changed_outputs": [],
                       "orphans": []}


def test_refresh_only_refuses_out(tmp_path, capsys):
    state = str(tmp_path / "s.json")
    assert main(["plan", GKE_TPU, "-state", state, "-refresh-only",
                 "-out", str(tmp_path / "p")] + VARS) == 2
    assert "-refresh-only" in capsys.readouterr().err


def test_apply_saved_plan_module_dir_gone_is_clean_error(tmp_path, capsys):
    import shutil
    import textwrap

    mod = tmp_path / "mod"
    mod.mkdir()
    (mod / "main.tf").write_text(textwrap.dedent("""
        resource "google_compute_network" "vpc" {
          name = "n"
        }
    """))
    state = str(tmp_path / "s.json")
    pfile = str(tmp_path / "p.tfplan")
    assert main(["apply", str(mod), "-state", state]) == 0
    assert main(["plan", str(mod), "-state", state, "-out", pfile]) == 0
    shutil.rmtree(mod)
    capsys.readouterr()
    assert main(["apply", pfile, "-state", state]) == 1
    assert "Error:" in capsys.readouterr().err


def test_destroy_refuses_prevent_destroy_instances(tmp_path, capsys):
    """Real terraform hard-refuses destroying a prevent_destroy resource;
    the simulator must report the refusal, not '0 hazard(s)' (review
    finding, round 3 — first prevent_destroy entered the modules)."""
    import textwrap

    mod = tmp_path / "mod"
    mod.mkdir()
    (mod / "main.tf").write_text(textwrap.dedent("""
        resource "google_compute_network" "keep" {
          name = "n"
          lifecycle {
            prevent_destroy = true
          }
        }
    """))
    assert main(["destroy", str(mod)]) == 1
    captured = capsys.readouterr()
    assert "REFUSED" in captured.err and "prevent_destroy" in captured.err
    assert "1 refusal(s)" in captured.out


def test_destroy_ignores_prevent_destroy_on_uninstantiated(capsys):
    """The gke modules declare a prevent_destroy KMS key behind
    count = encryption.enabled; with encryption off it has no instances
    and must not block destroy."""
    assert main(["destroy", GKE_TPU] + VARS) == 0
    assert "0 refusal(s)" in capsys.readouterr().out


def test_gke_destroy_refuses_when_encryption_enabled(capsys):
    assert main(["destroy", GKE_TPU, "-var",
                 'database_encryption={"enabled": true}'] + VARS) == 1
    captured = capsys.readouterr()
    assert "google_kms_crypto_key.secrets" in captured.err


def test_plan_out_unwritable_path_clean_error(tmp_path, capsys):
    assert main(["plan", GKE_TPU, "-state", str(tmp_path / "s.json"),
                 "-out", "/nonexistent-dir/p.tfplan"] + VARS) == 1
    assert "Error:" in capsys.readouterr().err


def test_apply_saved_plan_rejects_refresh_only_and_workspace(tmp_path, capsys):
    state = str(tmp_path / "s.json")
    pfile = str(tmp_path / "p.tfplan")
    assert main(["plan", GKE_TPU, "-state", state, "-out", pfile] + VARS) == 0
    capsys.readouterr()
    assert main(["apply", pfile, "-refresh-only"]) == 2
    assert "cannot be combined" in capsys.readouterr().err


def test_saved_plan_pins_resolved_statefile(tmp_path, capsys):
    """apply FILE targets the statefile the plan resolved, not whatever
    workspace is selected at apply time (review finding, round 3)."""
    import textwrap

    mod = tmp_path / "mod"
    mod.mkdir()
    (mod / "main.tf").write_text(textwrap.dedent("""
        resource "google_compute_network" "vpc" {
          name = "n"
        }
    """))
    pfile = str(tmp_path / "p.tfplan")
    # workspaces on; review the plan while STAGING is selected
    assert main(["workspace", "new", str(mod), "staging"]) == 0
    assert main(["plan", str(mod), "-out", pfile]) == 0
    payload = json.loads(open(pfile).read())
    assert payload["state_path"] and "staging" in payload["state_path"]
    # an operator switches workspace between review and apply
    assert main(["workspace", "select", str(mod), "default"]) == 0
    capsys.readouterr()
    assert main(["apply", pfile]) == 0
    # STAGING's statefile (the reviewed one) got the resources
    assert os.path.exists(payload["state_path"])
    assert "google_compute_network.vpc" in \
        json.load(open(payload["state_path"]))["resources"]


def test_plan_destroy_to_saved_file_roundtrip(tmp_path, capsys):
    """terraform's state-driven teardown flow: plan -destroy -out FILE →
    apply FILE empties the state through the same reviewed-plan contract."""
    state = str(tmp_path / "s.json")
    pfile = str(tmp_path / "d.tfplan")
    assert main(["apply", GKE_TPU, "-state", state] + VARS) == 0
    capsys.readouterr()
    assert main(["plan", GKE_TPU, "-state", state, "-destroy",
                 "-out", pfile] + VARS) == 0
    out = capsys.readouterr().out
    assert "- google_container_cluster.this" in out
    assert "10 to destroy." in out
    assert main(["apply", pfile, "-state", state]) == 0
    assert "10 destroyed" in capsys.readouterr().out
    assert json.load(open(state))["resources"] == {}


def test_plan_destroy_empty_state_errors(tmp_path, capsys):
    state = str(tmp_path / "s.json")
    assert main(["plan", GKE_TPU, "-state", state, "-destroy"] + VARS) == 1
    assert "nothing to destroy" in capsys.readouterr().err


def test_plan_destroy_refuses_prevent_destroy(tmp_path, capsys):
    import textwrap

    mod = tmp_path / "mod"
    mod.mkdir()
    (mod / "main.tf").write_text(textwrap.dedent("""
        resource "google_compute_network" "keep" {
          name = "n"
          lifecycle {
            prevent_destroy = true
          }
        }
    """))
    state = str(tmp_path / "s.json")
    assert main(["apply", str(mod), "-state", state]) == 0
    capsys.readouterr()
    assert main(["plan", str(mod), "-state", state, "-destroy"]) == 1
    assert "prevent_destroy" in capsys.readouterr().err


def test_plan_destroy_refuses_child_module_prevent_destroy(tmp_path, capsys):
    import textwrap

    child = tmp_path / "child"
    child.mkdir()
    (child / "main.tf").write_text(textwrap.dedent("""
        resource "google_compute_network" "keep" {
          name = "n"
          lifecycle {
            prevent_destroy = true
          }
        }
    """))
    mod = tmp_path / "mod"
    mod.mkdir()
    (mod / "main.tf").write_text(textwrap.dedent("""
        module "sec" {
          source = "../child"
        }
    """))
    state = str(tmp_path / "s.json")
    assert main(["apply", str(mod), "-state", state]) == 0
    capsys.readouterr()
    assert main(["plan", str(mod), "-state", state, "-destroy"]) == 1
    err = capsys.readouterr().err
    assert "prevent_destroy" in err and "module.sec" in err


def test_resource_block_for_broken_child_raises(tmp_path):
    """A local child that fails to load must surface a PlanError, not
    silently disable its resources' prevent_destroy refusals (advisor
    finding, round 3: a safety check may not degrade to 'allow' on
    error). Registry-source children stay None — they are plan stubs
    with no local config to read refusals from."""
    import textwrap

    import pytest

    from nvidia_terraform_modules_tpu.tfsim.__main__ import (
        _resource_block_for,
    )
    from nvidia_terraform_modules_tpu.tfsim.module import load_module
    from nvidia_terraform_modules_tpu.tfsim.plan import PlanError

    child = tmp_path / "child"
    child.mkdir()
    (child / "main.tf").write_text('resource "null_resource" {{{ broken')
    mod_dir = tmp_path / "mod"
    mod_dir.mkdir()
    (mod_dir / "main.tf").write_text(textwrap.dedent("""
        module "sec" {
          source = "../child"
        }
        module "reg" {
          source = "registry/vpc/google"
        }
    """))
    mod = load_module(str(mod_dir))
    with pytest.raises(PlanError, match="prevent_destroy"):
        _resource_block_for(
            mod, "module.sec.google_compute_network.keep", {})
    assert _resource_block_for(
        mod, "module.reg.google_compute_network.keep", {}) is None


def test_plan_destroy_rejects_target(capsys):
    assert main(["plan", GKE_TPU, "-destroy", "-target",
                 "google_compute_network.vpc"] + VARS) == 2
    assert "-destroy cannot combine with -target" in capsys.readouterr().err


def test_old_plan_file_missing_keys_clean_error(tmp_path, capsys):
    old = tmp_path / "old.tfplan"
    old.write_text(json.dumps({"format": "tfsim-plan/1",
                               "module_dir": "/x"}))
    assert main(["apply", str(old)]) == 1
    err = capsys.readouterr().err
    assert "missing plan-file keys" in err


def test_validate_json_clean_and_dirty(tmp_path, capsys):
    """terraform's `validate -json` diagnostics shape: valid flag, counts,
    per-diagnostic severity/summary/range."""
    assert main(["validate", GKE_TPU, "-json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["valid"] is True and payload["error_count"] == 0

    (tmp_path / "main.tf").write_text(
        'resource "google_compute_network" "n" {\n  name = var.missing\n}\n')
    assert main(["validate", str(tmp_path), "-json"]) == 1
    payload = json.loads(capsys.readouterr().out)
    assert payload["valid"] is False and payload["error_count"] >= 1
    errors = [d for d in payload["diagnostics"] if d["severity"] == "error"]
    assert errors and any("missing" in d["summary"] for d in errors)
    diag = errors[0]
    assert diag["range"]["filename"].endswith("main.tf")
    assert diag["range"]["start"]["line"] >= 1


def test_validate_json_omits_zero_line_ranges(tmp_path, capsys):
    """Synthetic module-level findings (versions.tf:0) must not emit a
    0 line — 1-based consumers (GitHub annotations) reject it."""
    (tmp_path / "main.tf").write_text(
        'resource "google_compute_network" "n" {\n  name = "x"\n}\n')
    # no versions.tf: validate emits module-level pin warnings at line 0
    main(["validate", str(tmp_path), "-json"])
    payload = json.loads(capsys.readouterr().out)
    for d in payload["diagnostics"]:
        start = d.get("range", {}).get("start")
        if start is not None:
            assert start["line"] >= 1, d


def test_validate_json_drops_pseudo_filename_ranges(tmp_path, capsys):
    """Synthetic locations like 'locals' (not a source file) must carry
    no range at all — an annotator would misplace them."""
    (tmp_path / "main.tf").write_text(
        'locals {\n  derived = var.nope\n}\n\n'
        'resource "google_compute_network" "n" {\n  name = local.derived\n}\n')
    main(["validate", str(tmp_path), "-json"])
    payload = json.loads(capsys.readouterr().out)
    assert payload["valid"] is False
    for d in payload["diagnostics"]:
        rng = d.get("range")
        if rng is not None:
            assert rng["filename"].endswith((".tf", ".tfvars", ".hcl")), d
