# SPDX-FileCopyrightText: Copyright (c) 2026 tpu-terraform-modules authors. All rights reserved.
# SPDX-License-Identifier: Apache-2.0
"""Capped exponential backoff with jitter — the workload-side retry policy.

The *infrastructure* simulator already models retries precisely
(``tfsim/faults/control_plane.py``: 1s → ×2 → cap 30s, the google
provider's shape, on a simulated clock). This module is the same policy
shape for the *workload* layer — distributed init on a half-scheduled
slice, restore-time reads racing a PVC remount — where time is real and
many workers retry at once, so a deterministic schedule would
synchronise every peer's retry into the exact thundering herd the
backoff exists to avoid. Hence the one deliberate difference from the
simulator: **full jitter** (each delay drawn uniformly from
``[0, capped_backoff]``), seedable for tests.

Kept in ``utils`` (not ``models`` or ``parallel``) on purpose: both
``parallel/multihost.py`` and ``models/resilience.py`` consume it, and
``models`` already imports ``parallel`` — a policy living in either
would cycle.
"""

from __future__ import annotations

import dataclasses
import random
import time
from typing import Callable, Iterator, Optional


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Capped exponential backoff (the ``tfsim`` control-plane shape)
    plus full jitter and an attempt bound.

    ``max_attempts`` counts *attempts*, not retries: 3 means the first
    try and up to two more. ``jitter=False`` pins each delay to the
    deterministic cap (the simulator's behaviour) for tests that assert
    exact schedules.
    """

    initial_s: float = 1.0
    multiplier: float = 2.0
    cap_s: float = 30.0
    max_attempts: int = 3
    jitter: bool = True

    def delays(self, rng: Optional[random.Random] = None) -> Iterator[float]:
        """The backoff delay before each retry (``max_attempts - 1`` of
        them). Deterministic under a seeded ``rng``."""
        # decorrelated full-jitter default is the point here;
        # replay-sensitive callers pass a seeded rng
        # graftlint: ignore[graft-unseeded-rng] — entropy jitter by design
        rng = rng or random.Random()
        backoff = self.initial_s
        for _ in range(max(0, self.max_attempts - 1)):
            capped = min(backoff, self.cap_s)
            yield rng.uniform(0.0, capped) if self.jitter else capped
            backoff *= self.multiplier


class RetriesExhausted(Exception):
    """All attempts failed; ``last`` carries the final attempt's error."""

    def __init__(self, what: str, attempts: int, elapsed_s: float,
                 last: BaseException):
        super().__init__(
            f"{what}: failed after {attempts} attempt(s) over "
            f"{elapsed_s:.1f}s — last error: {type(last).__name__}: {last}")
        self.attempts = attempts
        self.elapsed_s = elapsed_s
        self.last = last


def retry_call(fn: Callable, *, policy: RetryPolicy,
               what: str = "operation",
               retryable: tuple = (Exception,),
               giveup: Optional[Callable[[BaseException], bool]] = None,
               rng: Optional[random.Random] = None,
               sleep: Callable[[float], None] = time.sleep,
               log: Optional[Callable[[str], None]] = None):
    """Run ``fn()`` under ``policy``.

    Only ``retryable`` exceptions are retried; anything else propagates
    immediately (terminal faults must fail fast, exactly like the
    simulator's retryable-vs-terminal split). ``giveup`` refines the
    split *within* a retryable type: an exception it returns True for
    propagates untouched — the lever for exception hierarchies where a
    subtype is terminal (a corrupt checkpoint inside the transient
    checkpoint-error family). When the budget runs out the last error
    is wrapped in :class:`RetriesExhausted` so callers can report a
    *classified*, attempt-counted failure instead of the bare final
    exception.
    """
    t0 = time.monotonic()
    delays = policy.delays(rng)
    attempt = 0
    while True:
        attempt += 1
        try:
            return fn()
        except retryable as exc:  # noqa: PERF203 — retry loop by design
            if giveup is not None and giveup(exc):
                raise
            delay = next(delays, None)
            if delay is None:
                raise RetriesExhausted(
                    what, attempt, time.monotonic() - t0, exc) from exc
            if log:
                log(f"{what}: attempt {attempt} failed "
                    f"({type(exc).__name__}: {exc}); retrying in "
                    f"{delay:.1f}s")
            sleep(delay)
